"""Serving metrics: counters + a crash-safe JSONL journal.

Reuses the measurement harness's journal (`harness.journal.Journal` —
fsynced append-only JSONL, torn-tail tolerant) so a served incident
leaves the same class of evidence a measurement run does: every request
admission, shed, batch execution and response is one journal record, and
`replay_serve` folds a journal back into the incident summary
("the metrics journal replays the full incident" — the backpressure
acceptance criterion).

Record schema (all lines also carry the journal's v/seq/ts):

  {"event": "serve_request",  "id": ..., "spec": {...}, "scale": ...,
                              "queue_depth": N}
  {"event": "serve_shed",     "id": ..., "failure_class": "transient",
                              "queue_depth": N}
  {"event": "serve_admit",    "id": ..., "lane": L, "iter": K,
                              "midsolve": bool, "live": N}
  {"event": "serve_retire",   "id": ..., "lane": L, "iter": K,
                              "iters_run": R, "live": N}
  {"event": "serve_batch",    "spec": {...}, "nrhs_live": N,
                              "nrhs_bucket": B, "cache": "hit"|"miss",
                              "wall_s": ..., "gdof_per_second": ...,
                              "padded_lanes": P, "midsolve": M,
                              "boundaries": Q, "mean_live_lanes": ...,
                              "continuous": bool}
  {"event": "serve_response", "id": ..., "ok": bool, "latency_s": ...,
                              "cache": "hit"|"miss" (when known),
                              "failure_class": ... (failures only),
                              "retriable": bool (failures only)}
  {"event": "serve_retry",    "spec": {...}, "failure_class": ...,
                              "attempt": N, "wait_s": ..., "resumed": bool}
  {"event": "serve_recover",  "outstanding": N, "replayed": N,
                              "skipped": N, "corrupt_lines": N}

Overload-resilience records (ISSUE 18 — journaled ONLY when the hedging
/ brownout controllers are armed; the tracing-off vocabulary pin stays
byte-identical, and deadline refusals reuse the EXISTING serve_shed /
serve_response kinds with additive fields):

  {"event": "serve_hedge_fired",     "id": ..., "src": D1, "dst": D2,
                                     "wait_s": ..., "inputs": {...}}
  {"event": "serve_hedge_won",       "id": ..., "dst": D2}
  {"event": "serve_hedge_cancelled", "id": ..., "lane": L, "iter": K}
  {"event": "fleet_brownout",        "action": "step"|"recover",
                                     "level": N, "from": "f32",
                                     "to": "bf16", "inputs": {...}}

Every ``inputs`` dict carries the controller decision's full evidence
(prediction fold, burn rates, thresholds, budget state) so the decision
REPLAYS deterministically from the journal alone — the reqtrace
route-cause discipline applied to control decisions.

serve_request is the broker's WRITE-AHEAD admitted-request record
(fsynced before the client gets its future back; `scale` makes it
replayable), serve_response its visibility fence (fsynced before
``done.set()``): recovery (serve.recovery) folds the two into the
admitted-but-unresponded set after a crash. serve_retry is a
broker-internal bounded retry of a retriable-failed batch
(resumed=true = the continuous solve resumed from its iter-chunk
boundary checkpoint instead of restarting); serve_recover is one
``Broker.recover`` replay.

serve_admit/serve_retire are the continuous-batching boundary events:
`iter` is the batch's iteration-boundary index at the event and `live`
the live-lane count right after it — together they ARE the
lane-occupancy-over-time record (occupancy only changes at these
events), replayable from the journal alone.

Cache hit-rate is REQUEST-weighted (requests served from an
already-compiled executable / requests batched): a warm cache serving
64 requests in 10 batches is a 100% hit-rate story, not a 10-lookup
one. The raw cache counters ride along unweighted in `snapshot()`.
Response latency percentiles split by cache warmth (the `cache` field
on responses): `latency_warm_*` is the steady-state serving latency
story, uncontaminated by compile stalls.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..harness.journal import Journal, read_records
from ..obs.reqtrace import ExemplarRing, summarize_phases

# Bounded latency window: serving metrics must not grow without bound.
_LATENCY_WINDOW = 4096

# Per-(spec, bucket) latency split (ISSUE 15 satellite): bounded key
# count so the /metrics JSON and the Prometheus label cardinality can
# never grow with the spec space — keys beyond the cap pool into
# "_other" (still bounded, still honest about existing).
_SPEC_KEYS_MAX = 16

# Minimum per-spec latency samples before the completion-time predictor
# speaks (ISSUE 18): below this the admission controller treats the
# distribution as unknown and never sheds predictively.
_PREDICT_MIN_SAMPLES = 4


def spec_latency_key(spec_dict: dict, bucket) -> str:
    """The per-(spec, bucket) latency-split key: compact, deterministic,
    label-safe. Rides as an ADDITIVE field on serve_response records."""
    return (f"d{spec_dict.get('degree')}"
            f":n{spec_dict.get('ndofs')}"
            f":r{spec_dict.get('nreps')}"
            f":{spec_dict.get('precision', 'f32')}"
            f":b{int(bucket or 0)}")


class Metrics:
    """Thread-safe counters + optional journal. Every mutator journals
    first (evidence before bookkeeping — a crash mid-increment still
    leaves the record).

    ``slo_objective_s`` (ISSUE 10) arms SLO tracking: every response
    becomes a timestamped sample, and `snapshot()` folds the samples
    into latency-objective burn rates over the fast/slow windows
    (obs.regress.burn_rates — the SAME fold `python -m bench_tpu_fem.obs
    trend` runs offline over the journal's serve_response lifecycles, so
    the live /metrics story and the journal replay cannot diverge).
    None (the default) leaves the snapshot exactly as before."""

    def __init__(self, journal_path: str | None = None,
                 slo_objective_s: float | None = None,
                 slo_target: float = 0.99,
                 device: str | None = None):
        self.journal = Journal(journal_path) if journal_path else None
        self.slo_objective_s = slo_objective_s
        self.slo_target = slo_target
        # fleet lanes stamp their device label on every journal record
        # (ISSUE 13): per-device occupancy/affinity stories replay from
        # the one shared journal file
        self.device = device
        # (wall ts, latency, ok) samples for the burn-rate windows;
        # bounded like every other metrics series
        self._slo_samples: deque = deque(maxlen=_LATENCY_WINDOW)
        self._lock = threading.Lock()
        self.requests_total = 0
        self.shed_total = 0
        self.completed = 0
        self.failed = 0
        self.failed_by_class: dict[str, int] = {}
        self.batches = 0
        self.lanes_total = 0  # live lanes across batches (occupancy sum)
        self.cache_hit_requests = 0
        self.cache_miss_requests = 0
        self.gdof_samples: deque = deque(maxlen=_LATENCY_WINDOW)
        self.latencies: deque = deque(maxlen=_LATENCY_WINDOW)
        self.latencies_warm: deque = deque(maxlen=_LATENCY_WINDOW)
        self.queue_depth = 0
        # continuous-batching accounting
        self.midsolve_admissions = 0
        self.padded_lanes_total = 0  # dead/padded lane-slots across batches
        self.lane_slots_total = 0  # bucket-sized slots across batches
        self.live_lane_boundaries = 0  # sum of live counts per boundary
        self.boundaries_total = 0
        # fault-tolerance accounting (ISSUE 9)
        self.broker_retries = 0  # bounded internal retries of failed batches
        self.batch_resumes = 0  # retries that resumed a boundary checkpoint
        self.recovery_runs = 0  # Broker.recover invocations
        self.recovered_requests = 0  # admitted-unresponded requests replayed
        # Overload resilience (ISSUE 18): deadline + hedge accounting.
        # early = answered/shed `deadline_exceeded` WITHOUT burning a
        # solve (the budget was gone, or the predictor said it would
        # be); late = a real response delivered PAST its deadline — the
        # count the whole subsystem exists to hold at zero.
        self.deadline_exceeded_early = 0
        self.deadline_exceeded_late = 0
        self.hedge_wins = 0  # hedged requests rescued by the copy
        self.hedge_cancels = 0  # loser copies retired without response
        # SDC defense accounting (ISSUE 14): retire-time audit verdicts
        self.sdc_detected = 0  # audit exceedances (finite-but-wrong lanes)
        self.sdc_rollbacks = 0  # detections answered by a lane re-run
        self.sdc_terminal = 0  # detected AGAIN on the re-run: deterministic
        # detection timestamps for the fleet's windowed quarantine trip
        self._sdc_times: deque = deque(maxlen=_LATENCY_WINDOW)
        # request-scoped tracing (ISSUE 15): per-phase bounded window of
        # (latency, phase decomposition) samples, trace-completeness
        # counters and the exemplar ring (K slowest + every anomalous +
        # deterministic head-sampled normals). All empty/zero until the
        # first traced response arrives — with tracing off the snapshot
        # never grows a reqtrace block. (The per-(spec, bucket) latency
        # split below is DELIBERATELY reqtrace-independent: spec_key is
        # an additive field on records the broker writes anyway.)
        self._trace_samples: deque = deque(maxlen=_LATENCY_WINDOW)
        self.trace_complete = 0
        self.trace_incomplete = 0
        self.exemplars = ExemplarRing()
        # per-(spec, bucket) latency windows (bounded key count)
        self._lat_by_key: dict[str, deque] = {}

    def _journal(self, rec: dict) -> None:
        if self.journal is not None:
            if self.device is not None:
                rec = {**rec, "device": self.device}
            self.journal.append(rec)

    # -- events ------------------------------------------------------------

    def request(self, req_id: str, spec_dict: dict, queue_depth: int,
                scale: float = 1.0,
                warm_scale: float | None = None) -> None:
        """The write-ahead admitted-request record: journaled (fsynced)
        before the submitting client gets its future back, carrying
        everything a recovery replay needs (spec + scale). A non-zero
        ``warm_scale`` (ISSUE 20, heat workload) rides as an ADDITIVE
        field — cold requests keep their pre-zoo record bytes."""
        rec = {"event": "serve_request", "id": req_id,
               "spec": spec_dict, "scale": float(scale),
               "queue_depth": queue_depth}
        if warm_scale:
            rec["warm_scale"] = float(warm_scale)
        self._journal(rec)
        with self._lock:
            self.requests_total += 1
            self.queue_depth = queue_depth

    def shed(self, req_id: str, queue_depth: int,
             failure_class: str = "transient",
             controller: dict | None = None,
             retry_after_s: float | None = None) -> None:
        """``controller`` (ISSUE 18, ADDITIVE) journals the admission
        controller's decision inputs — the prediction fold, the deadline
        budget — so an early deadline shed replays deterministically
        from this one record. ``retry_after_s`` is the
        predicted-queue-time hint handed back to the shed client."""
        rec = {"event": "serve_shed", "id": req_id,
               "failure_class": failure_class,
               "queue_depth": queue_depth}
        if controller is not None:
            rec["controller"] = controller
        if retry_after_s is not None:
            rec["retry_after_s"] = round(float(retry_after_s), 3)
        self._journal(rec)
        with self._lock:
            self.shed_total += 1
            if failure_class == "deadline_exceeded":
                self.deadline_exceeded_early += 1

    def admit(self, req_id: str, lane: int, boundary: int,
              midsolve: bool, live: int) -> None:
        """A request entered a batch lane at iteration boundary
        `boundary` (0 = batch formation; midsolve=True = continuous
        admission into an in-flight solve)."""
        self._journal({"event": "serve_admit", "id": req_id,
                       "lane": int(lane), "iter": int(boundary),
                       "midsolve": bool(midsolve), "live": int(live)})
        if midsolve:
            with self._lock:
                self.midsolve_admissions += 1

    def retire(self, req_id: str, lane: int, boundary: int,
               iters_run: int, live: int) -> None:
        """A lane finished its iteration budget and was freed at
        boundary `boundary` (`live` = live lanes remaining)."""
        self._journal({"event": "serve_retire", "id": req_id,
                       "lane": int(lane), "iter": int(boundary),
                       "iters_run": int(iters_run), "live": int(live)})

    def batch(self, spec_dict: dict, nrhs_live: int, nrhs_bucket: int,
              cache_hit: bool, wall_s: float,
              gdof_per_second: float, *,
              padded_lanes: int | None = None, midsolve: int = 0,
              boundaries: int = 0, live_lane_boundaries: int = 0,
              continuous: bool = False) -> None:
        """One executed batch. `padded_lanes` defaults to the one-shot
        padding (bucket - live); continuous batches pass their true
        dead-slot integral (bucket * boundaries - live-lane boundaries,
        in boundary units normalised to lanes)."""
        if padded_lanes is None:
            padded_lanes = max(nrhs_bucket - nrhs_live, 0)
        mean_live = (live_lane_boundaries / boundaries
                     if boundaries else float(nrhs_live))
        self._journal({"event": "serve_batch", "spec": spec_dict,
                       "nrhs_live": nrhs_live, "nrhs_bucket": nrhs_bucket,
                       "cache": "hit" if cache_hit else "miss",
                       "wall_s": round(wall_s, 6),
                       "gdof_per_second": round(gdof_per_second, 6),
                       "padded_lanes": int(padded_lanes),
                       "midsolve": int(midsolve),
                       "boundaries": int(boundaries),
                       "mean_live_lanes": round(mean_live, 4),
                       "continuous": bool(continuous)})
        with self._lock:
            self.batches += 1
            self.lanes_total += nrhs_live
            self.padded_lanes_total += int(padded_lanes)
            self.lane_slots_total += int(nrhs_bucket)
            self.live_lane_boundaries += int(live_lane_boundaries)
            self.boundaries_total += int(boundaries)
            if cache_hit:
                self.cache_hit_requests += nrhs_live
            else:
                self.cache_miss_requests += nrhs_live
            self.gdof_samples.append(gdof_per_second)

    def response(self, req_id: str, ok: bool, latency_s: float,
                 failure_class: str | None = None,
                 retriable: bool | None = None,
                 cache: str | None = None,
                 lifecycle: dict | None = None,
                 phase_s: dict | None = None,
                 trace: dict | None = None,
                 spec_key: str | None = None,
                 deadline_late: bool = False,
                 controller: dict | None = None,
                 degraded: dict | None = None) -> None:
        rec = {"event": "serve_response", "id": req_id, "ok": ok,
               "latency_s": round(latency_s, 6)}
        if cache is not None:
            rec["cache"] = cache
        if deadline_late:
            # ISSUE 18 (ADDITIVE): this response went out PAST its
            # declared deadline — the late counter the perfgate pins
            # at zero
            rec["deadline_late"] = True
        if controller is not None:
            # controller decision inputs (early deadline refusals at
            # batch formation / admission): replayable evidence
            rec["controller"] = controller
        if degraded is not None:
            # brownout provenance stamp (ISSUE 18): the answer was
            # computed on a stepped-down precision rung
            rec["degraded"] = degraded
        if lifecycle:
            # the request's lifecycle breakdown (enqueue->admit->solve->
            # respond deltas, obs.trace.Lifecycle) — queue wait vs solve
            # time attribution per response, replayable from the journal
            rec["lifecycle_s"] = lifecycle
        if spec_key is not None:
            # per-(spec, bucket) latency split key (ADDITIVE — old
            # readers ignore it; replay folds stay exactly-once-safe)
            rec["spec_key"] = spec_key
        tags: list[str] = []
        if phase_s is not None:
            # the phase decomposition (ISSUE 15): additive fields on the
            # EXISTING serve_response WAL record — fold_reqtrace rebuilds
            # the live per-phase percentiles from exactly these
            rec["phase_s"] = phase_s
            tags = self._anomaly_tags(ok, latency_s, failure_class,
                                      phase_s, trace)
            if tags:
                rec["anomalies"] = tags
            if ok and trace is not None:
                rec["trace_complete"] = bool(trace.get("complete"))
        if not ok:
            rec["failure_class"] = failure_class or "transient"
            rec["retriable"] = bool(retriable)
        self._journal(rec)
        with self._lock:
            if ok:
                self.completed += 1
            else:
                self.failed += 1
                fc = failure_class or "transient"
                self.failed_by_class[fc] = (
                    self.failed_by_class.get(fc, 0) + 1)
                if fc == "deadline_exceeded":
                    self.deadline_exceeded_early += 1
            if deadline_late:
                self.deadline_exceeded_late += 1
            self.latencies.append(latency_s)
            self._slo_samples.append((time.time(), latency_s, ok))
            if cache == "hit":
                self.latencies_warm.append(latency_s)
            if spec_key is not None:
                win = self._lat_by_key.get(spec_key)
                if win is None:
                    if len(self._lat_by_key) >= _SPEC_KEYS_MAX:
                        spec_key = "_other"  # bounded cardinality
                    win = self._lat_by_key.setdefault(
                        spec_key, deque(maxlen=_LATENCY_WINDOW))
                win.append(latency_s)
            if phase_s is not None:
                # the window stores the journal's rounded values so the
                # live fold and fold_reqtrace see identical samples
                self._trace_samples.append((round(latency_s, 6), phase_s))
                if ok and trace is not None:
                    if trace.get("complete"):
                        self.trace_complete += 1
                    else:
                        self.trace_incomplete += 1
        if trace is not None:
            ex = dict(trace)
            ex["latency_s"] = round(latency_s, 6)
            ex["ok"] = ok
            if failure_class:
                ex["failure_class"] = failure_class
            ex["anomalies"] = tags
            if self.device is not None:
                ex["device"] = self.device
            self.exemplars.offer(ex)

    def _anomaly_tags(self, ok: bool, latency_s: float,
                      failure_class: str | None, phase_s: dict,
                      trace: dict | None) -> list[str]:
        """The tail-based sampling predicate (ISSUE 15): a response is
        anomalous when it violated the SLO, retried, hit sdc/breakdown,
        was steal-moved or quarantine-drained, or failed outright —
        every such trace is kept in full, never sampled away."""
        tags: list[str] = []
        if self.slo_objective_s is not None \
                and latency_s > self.slo_objective_s:
            tags.append("slo_violation")
        events = {e.get("name") for e in (trace or {}).get("events", [])}
        if phase_s.get("retry_s", 0.0) > 0.0 or "retry" in events \
                or (trace or {}).get("retries", 0):
            tags.append("retry")
        if failure_class == "sdc" or "sdc_rollback" in events:
            tags.append("sdc")
        if failure_class == "breakdown":
            tags.append("breakdown")
        if "steal_moved" in events:
            tags.append("steal_moved")
        if "quarantine_drained" in events:
            tags.append("quarantine_drained")
        if not ok and failure_class not in ("sdc", "breakdown"):
            tags.append("failed")
        return tags

    def phase_event(self, ids: list, phase: str, **fields) -> None:
        """One ``serve_phase`` journal record (ISSUE 15): phase
        boundaries that have NO existing WAL record today (batch
        execution start with its cache-resolution source). Carries
        ``ids`` (plural — deliberately NOT ``id``, so the exactly-once
        ledger folds never see it). Only the reqtrace-armed broker
        calls this: tracing off journals no serve_phase records (and no
        phase fields — the off path's only schema delta is the
        reqtrace-independent spec_key field on serve_response)."""
        self._journal({"event": "serve_phase", "phase": phase,
                       "ids": [str(i) for i in ids][:64], **fields})

    def sdc(self, req_id: str, lane: int, drift: float, envelope: float,
            action: str) -> None:
        """One retire-time SDC audit exceedance (ISSUE 14): the lane's
        carried rnorm and its recomputed true residual disagree past
        the per-precision envelope. ``action`` is the adjudication step
        taken — "rollback" (first detection: the lane re-runs from its
        write-ahead record, the serve layer's durable checkpoint) or
        "terminal" (detected again on the re-run: deterministic fault,
        the request answers `failure_class: "sdc"`). The timestamps
        feed the fleet's windowed lane-quarantine trip."""
        self._journal({"event": "serve_sdc", "id": req_id,
                       "lane": int(lane), "drift": float(drift),
                       "envelope": float(envelope), "action": action})
        with self._lock:
            self.sdc_detected += 1
            if action == "rollback":
                self.sdc_rollbacks += 1
            elif action == "terminal":
                self.sdc_terminal += 1
            self._sdc_times.append(time.time())

    def sdc_recent(self, window_s: float, now: float | None = None) -> int:
        """Detections inside the trailing window — the fleet's
        quarantine-trip input (serve.fleet.quarantine_scan)."""
        if now is None:
            now = time.time()
        with self._lock:
            return sum(1 for t in self._sdc_times if t >= now - window_s)

    def sdc_reset_window(self) -> None:
        """Clear the windowed detection timestamps. The fleet calls
        this at READMISSION: a lane that just passed its self-test must
        start with a clean window — otherwise the balancer's next scan
        re-trips it on the pre-quarantine detections still inside the
        window, silently undoing the readmit. The monotone counters
        (sdc_detected et al.) are untouched — history is evidence, the
        window is a control signal."""
        with self._lock:
            self._sdc_times.clear()

    def hedge_won(self, req_id: str, dst: str) -> None:
        """The speculative hedge copy answered first (ISSUE 18): the
        straggler's victim was rescued by the lane the hedge landed on.
        Journaled AFTER the winning serve_response — the ledger sees
        exactly one response; this record is the attribution."""
        self._journal({"event": "serve_hedge_won", "id": req_id,
                       "dst": dst})
        with self._lock:
            self.hedge_wins += 1

    def hedge_cancel(self, req_id: str, lane: int, boundary: int) -> None:
        """The losing copy of a hedge pair was dropped at its next
        boundary WITHOUT a response (the claim CAS was already won by
        the other lane)."""
        self._journal({"event": "serve_hedge_cancelled", "id": req_id,
                       "lane": int(lane), "iter": int(boundary)})
        with self._lock:
            self.hedge_cancels += 1

    def retry(self, spec_dict: dict, failure_class: str, attempt: int,
              wait_s: float, resumed: bool) -> None:
        """One broker-internal retry of a retriable-failed batch
        (resumed=True: the continuous solve resumed from its iter-chunk
        boundary checkpoint instead of restarting at iteration 0)."""
        self._journal({"event": "serve_retry", "spec": spec_dict,
                       "failure_class": failure_class,
                       "attempt": int(attempt),
                       "wait_s": round(float(wait_s), 6),
                       "resumed": bool(resumed)})
        with self._lock:
            self.broker_retries += 1
            if resumed:
                self.batch_resumes += 1

    def recovery(self, outstanding: int, replayed: int, skipped: int,
                 corrupt: int) -> None:
        """One Broker.recover replay of a crashed generation's journal."""
        self._journal({"event": "serve_recover",
                       "outstanding": int(outstanding),
                       "replayed": int(replayed),
                       "skipped": int(skipped),
                       "corrupt_lines": int(corrupt)})
        with self._lock:
            self.recovery_runs += 1
            self.recovered_requests += int(replayed)

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    def latency_samples(self) -> list:
        """Copy of the bounded response-latency window (the fleet
        snapshot merges lanes' samples for fleet-wide percentiles)."""
        with self._lock:
            return list(self.latencies)

    def trace_samples(self) -> list:
        """Copy of the bounded (latency, phase decomposition) window —
        the fleet snapshot merges lanes' samples through the SAME
        summarize_phases fold the single-broker snapshot runs."""
        with self._lock:
            return list(self._trace_samples)

    def latency_key_samples(self) -> dict:
        """Per-(spec, bucket) latency windows as plain lists (fleet
        merge input)."""
        with self._lock:
            return {k: list(v) for k, v in self._lat_by_key.items()}

    def slo_samples(self) -> list:
        """Copy of the (wall ts, latency, ok) SLO sample window — the
        fleet's brownout controller pools lanes' samples through the
        SAME burn_rates fold the per-lane snapshot runs."""
        with self._lock:
            return list(self._slo_samples)

    def predict_completion(self, spec_dict: dict) -> dict | None:
        """Per-spec completion-time estimate (ISSUE 18): fold the
        per-(spec, bucket) latency windows — the SAME windows the
        latency_by_spec snapshot split reads — merged across buckets
        (the bucket is unknown at admission time). Returns
        ``{"samples", "p50_s", "p95_s"}`` or None below
        ``_PREDICT_MIN_SAMPLES`` (unknown distribution: the admission
        controller never sheds predictively on thin evidence). The
        returned dict IS the controller's journaled decision input."""
        prefix = (f"d{spec_dict.get('degree')}"
                  f":n{spec_dict.get('ndofs')}"
                  f":r{spec_dict.get('nreps')}"
                  f":{spec_dict.get('precision', 'f32')}:b")
        with self._lock:
            merged = [v for k, win in self._lat_by_key.items()
                      if k.startswith(prefix) for v in win]
        if len(merged) < _PREDICT_MIN_SAMPLES:
            return None
        s = sorted(merged)
        return {"samples": len(s), "p50_s": round(_pct(s, 0.50), 6),
                "p95_s": round(_pct(s, 0.95), 6)}

    def fast_burn_rate(self) -> float:
        """Fast-window SLO burn rate as a CONTROL SIGNAL (ISSUE 13): the
        fleet dispatcher spills arrivals away from a lane whose
        fast-window burn exceeds 1 (the PR 10 alert input becomes a
        routing input). 0.0 when SLO tracking is unarmed. Cached for
        250 ms so the per-submit routing cost stays negligible."""
        if self.slo_objective_s is None:
            return 0.0
        now = time.time()
        with self._lock:
            cached = getattr(self, "_burn_cache", None)
            if cached is not None and now - cached[0] < 0.25:
                return cached[1]
            samples = list(self._slo_samples)
        from ..obs.regress import burn_rates

        burn = burn_rates(samples, objective_s=self.slo_objective_s,
                          target=self.slo_target,
                          now=now)["fast_burn_rate"]
        with self._lock:
            self._burn_cache = (now, burn)
        return burn

    # -- snapshot ----------------------------------------------------------

    def snapshot(self, cache_stats: dict | None = None,
                 memory: dict | None = None) -> dict:
        with self._lock:
            lat = sorted(self.latencies)
            warm = sorted(self.latencies_warm)
            batched = self.cache_hit_requests + self.cache_miss_requests
            out = {
                "requests_total": self.requests_total,
                "shed_total": self.shed_total,
                "completed": self.completed,
                "failed": self.failed,
                "failed_by_class": dict(self.failed_by_class),
                "batches": self.batches,
                "queue_depth": self.queue_depth,
                "mean_batch_occupancy": (
                    self.lanes_total / self.batches if self.batches else 0.0
                ),
                "cache_hit_rate_requests": (
                    self.cache_hit_requests / batched if batched else 0.0
                ),
                "latency_p50_s": _pct(lat, 0.50),
                "latency_p95_s": _pct(lat, 0.95),
                "latency_p99_s": _pct(lat, 0.99),
                # cache-warm percentiles: the steady-state serving story
                # (cold responses carry compile stalls)
                "latency_warm_p50_s": _pct(warm, 0.50),
                "latency_warm_p95_s": _pct(warm, 0.95),
                "latency_warm_p99_s": _pct(warm, 0.99),
                # padding waste: dead/padded lane-slots over all slots
                # the executed buckets provided
                "padded_lanes_total": self.padded_lanes_total,
                "padding_waste": (
                    self.padded_lanes_total / self.lane_slots_total
                    if self.lane_slots_total else 0.0
                ),
                # continuous batching: admissions into in-flight solves
                # and the boundary-weighted live-lane occupancy
                "midsolve_admissions": self.midsolve_admissions,
                "mean_live_lanes": (
                    self.live_lane_boundaries / self.boundaries_total
                    if self.boundaries_total else 0.0
                ),
                "gdof_per_second_mean": (
                    sum(self.gdof_samples) / len(self.gdof_samples)
                    if self.gdof_samples else 0.0
                ),
                # fault tolerance: internal retries, boundary-checkpoint
                # resumes and journal-replay recovery (ISSUE 9)
                "broker_retries": self.broker_retries,
                "batch_resumes": self.batch_resumes,
                "recovery_runs": self.recovery_runs,
                "recovered_requests": self.recovered_requests,
                # SDC defense (ISSUE 14): audit exceedances + how each
                # was adjudicated (rollback re-run vs terminal)
                "sdc_detected": self.sdc_detected,
                "sdc_rollbacks": self.sdc_rollbacks,
                "sdc_terminal": self.sdc_terminal,
                # overload resilience (ISSUE 18): the early/late deadline
                # split and the hedge win/cancel ledger
                "deadline_exceeded_early": self.deadline_exceeded_early,
                "deadline_exceeded_late": self.deadline_exceeded_late,
                "hedge_wins": self.hedge_wins,
                "hedge_cancels": self.hedge_cancels,
            }
        if cache_stats is not None:
            out["cache"] = cache_stats
        if memory is not None:
            # device-memory telemetry (obs.memory): allocator stats on
            # hardware, labelled process-RSS proxy on CPU
            out["memory"] = memory
        with self._lock:
            by_key = {k: sorted(v) for k, v in self._lat_by_key.items()}
            trace_samples = list(self._trace_samples)
            trace_complete = self.trace_complete
            trace_incomplete = self.trace_incomplete
        if by_key:
            # per-(spec, bucket) split (ISSUE 15 satellite): one slow
            # degree-7 spec can no longer hide a degree-1 tail
            # regression inside the pooled latency_* windows. Bounded
            # keys (the _other pool), flattened to LABELLED Prometheus
            # series by prometheus_text.
            out["latency_by_spec"] = {
                k: {"n": len(v), "p50_s": _pct(v, 0.50),
                    "p95_s": _pct(v, 0.95), "p99_s": _pct(v, 0.99)}
                for k, v in sorted(by_key.items())}
        if trace_samples or trace_complete or trace_incomplete:
            # request-scoped tracing (ISSUE 15): per-phase percentiles
            # via the SAME fold fold_reqtrace runs over the journal —
            # live and replay cannot diverge. Absent entirely until the
            # first traced response (tracing-off snapshot unchanged).
            rq = summarize_phases(trace_samples)
            judged = trace_complete + trace_incomplete
            rq["trace_complete"] = trace_complete
            rq["trace_incomplete"] = trace_incomplete
            rq["trace_complete_rate"] = (
                round(trace_complete / judged, 6) if judged else None)
            rq["anomalies"] = dict(self.exemplars.counts)
            rq["exemplars"] = self.exemplars.snapshot()
            out["reqtrace"] = rq
        if self.slo_objective_s is not None:
            # SLO burn-rate state (ISSUE 10): a flat numeric sub-dict,
            # so the Prometheus flattener exposes every field as its
            # own benchfem_serve_slo_* series
            from ..obs.regress import burn_rates

            with self._lock:
                samples = list(self._slo_samples)
            out["slo"] = burn_rates(samples,
                                    objective_s=self.slo_objective_s,
                                    target=self.slo_target,
                                    now=time.time())
        return out


class FleetMetrics:
    """Fleet-level counters + journal events (ISSUE 13): routing
    decisions, steals, spills and standby adoptions, on the SAME shared
    journal file as the lanes' serve records (harness.journal appends
    are O_APPEND-atomic across writers, the chaos-proven multi-writer
    discipline).

    Record schema (all lines also carry the journal's v/seq/ts):

      {"event": "fleet_route", "id": ..., "device": D,
                "affinity": bool, "spill": bool, "queue_depth": N}
      {"event": "fleet_steal", "src": D1, "dst": D2, "count": K}
      {"event": "fleet_spill", "id": ..., "src": D1, "dst": D2,
                "fast_burn": ...}
      {"event": "fleet_adopt", "outstanding": N, "routed": N,
                "skipped": N, "corrupt_lines": N}

    Affinity hit-rate is ROUTING-decision-weighted: hits / routed, a hit
    being a request sent to a device whose cache (or warm source)
    already held its (spec, bucket) executable at decision time."""

    def __init__(self, journal_path: str | None = None):
        self.journal = Journal(journal_path) if journal_path else None
        self._lock = threading.Lock()
        self.routed = 0
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.steals = 0  # requests moved between device queues
        self.steal_events = 0  # balancer passes that moved anything
        self.spills = 0  # burn-rate-driven reroutes away from hot lanes
        self.sheds = 0  # fleet-level sheds (every lane at capacity)
        self.adoptions = 0  # standby journal adoptions
        self.adopted_requests = 0
        # lane quarantine (ISSUE 14): corruption-tripped isolation
        self.quarantines = 0  # lanes tripped into quarantine
        self.quarantine_drained = 0  # queued requests drained to peers
        self.readmits = 0  # lanes readmitted after a passing self-test
        self.selftests = 0  # known-answer self-tests run
        self.selftests_failed = 0  # self-tests that kept the lane out
        # overload resilience (ISSUE 18): hedge + brownout controllers
        self.hedges_fired = 0  # speculative copies enqueued
        self.brownout_steps = 0  # precision-ladder step-downs
        self.brownout_recoveries = 0  # hysteresis-gated step-ups

    def _journal(self, rec: dict) -> None:
        if self.journal is not None:
            self.journal.append(rec)

    def route(self, req_id: str, device: str, affinity: bool,
              spill: bool, queue_depth: int,
              cause: str | None = None) -> None:
        rec = {"event": "fleet_route", "id": req_id,
               "device": device, "affinity": bool(affinity),
               "spill": bool(spill), "queue_depth": int(queue_depth)}
        if cause is not None:
            # routing-decision cause (ISSUE 15, ADDITIVE): affinity-hit
            # / cold-home / spill — the per-request "why did it land
            # here" the reqtrace timeline renders
            rec["cause"] = cause
        self._journal(rec)
        with self._lock:
            self.routed += 1
            if affinity:
                self.affinity_hits += 1
            else:
                self.affinity_misses += 1
            if spill:
                self.spills += 1

    def steal(self, src: str, dst: str, count: int,
              ids: list | None = None) -> None:
        rec = {"event": "fleet_steal", "src": src, "dst": dst,
               "count": int(count)}
        if ids:
            # moved request ids (ISSUE 15, ADDITIVE, bounded): lets the
            # reqtrace render pin steal instants to the right requests.
            # Deliberately "ids", never "id": the exactly-once ledger
            # folds key on "id" and must not see queue moves.
            rec["ids"] = [str(i) for i in ids][:64]
        self._journal(rec)
        with self._lock:
            self.steals += int(count)
            self.steal_events += 1

    def spill(self, req_id: str, src: str, dst: str,
              fast_burn: float) -> None:
        self._journal({"event": "fleet_spill", "id": req_id, "src": src,
                       "dst": dst, "fast_burn": round(float(fast_burn),
                                                      4)})

    def shed(self, req_id: str, queue_depth: int,
             failure_class: str = "transient",
             retry_after_s: float | None = None,
             controller: dict | None = None) -> None:
        """Fleet-level shed (every lane at capacity): journaled BEFORE
        any write-ahead record exists for the id, COUNTED so /metrics
        shed_total and the perfgate shed gate see fleet-mode sheds —
        a journal-only record would hide a shedding regression from
        every live counter. ``retry_after_s`` (ISSUE 18, ADDITIVE) is
        the predicted-queue-time hint handed to the shed client;
        ``controller`` journals the prediction inputs behind it."""
        rec = {"event": "serve_shed", "id": req_id,
               "failure_class": failure_class, "device": "fleet",
               "queue_depth": int(queue_depth)}
        if retry_after_s is not None:
            rec["retry_after_s"] = round(float(retry_after_s), 3)
        if controller is not None:
            rec["controller"] = controller
        self._journal(rec)
        with self._lock:
            self.sheds += 1

    def hedge_fired(self, req_id: str, src: str, dst: str,
                    wait_s: float, inputs: dict) -> None:
        """One speculative hedge copy enqueued on a second healthy lane
        (ISSUE 18). ``inputs`` carries the controller's full decision
        evidence — observed queue wait, the per-spec hedge delay and
        where it came from (p95 fold or override), the hedge-budget
        state — so the fire decision replays from this record alone."""
        self._journal({"event": "serve_hedge_fired", "id": req_id,
                       "src": src, "dst": dst,
                       "wait_s": round(float(wait_s), 6),
                       "inputs": inputs})
        with self._lock:
            self.hedges_fired += 1

    def hedge_budget_state(self) -> tuple[int, int]:
        """(routed, hedges_fired) read under the lock — the hedge
        controller's budget inputs, taken as one consistent snapshot so
        the balancer thread never sees a routed/fired pair torn across
        a concurrent route() or hedge_fired()."""
        with self._lock:
            return self.routed, self.hedges_fired

    def brownout(self, action: str, level: int, from_precision: str,
                 to_precision: str, inputs: dict) -> None:
        """One brownout-ladder transition (ISSUE 18): ``action`` is
        "step" (sustained fast+slow burn stepped the fleet DOWN a
        registry precision rung) or "recover" (hysteresis cleared and
        the fleet stepped back UP). ``inputs`` journals the burn rates
        and thresholds that drove the decision."""
        self._journal({"event": "fleet_brownout", "action": action,
                       "level": int(level), "from": from_precision,
                       "to": to_precision, "inputs": inputs})
        with self._lock:
            if action == "step":
                self.brownout_steps += 1
            elif action == "recover":
                self.brownout_recoveries += 1

    def quarantine(self, device: str, drained: int,
                   window_events: int) -> None:
        """One lane tripped into quarantine (ISSUE 14): its windowed
        SDC-detection counter crossed the threshold; `drained` queued
        requests moved to healthy lanes through the steal/adopt
        machinery (pure queue moves — the exactly-once ledger never
        sees them)."""
        self._journal({"event": "fleet_quarantine", "device": device,
                       "drained": int(drained),
                       "window_events": int(window_events)})
        with self._lock:
            self.quarantines += 1
            self.quarantine_drained += int(drained)

    def selftest(self, device: str, req_id: str, ok: bool) -> None:
        """One known-answer self-test on a quarantined lane (the test
        request itself rides the normal WAL/response ledger)."""
        self._journal({"event": "fleet_selftest", "device": device,
                       "id": req_id, "ok": bool(ok)})
        with self._lock:
            self.selftests += 1
            if not ok:
                self.selftests_failed += 1

    def readmit(self, device: str, req_id: str) -> None:
        """A quarantined lane passed its self-test and rejoined the
        routing pool."""
        self._journal({"event": "fleet_readmit", "device": device,
                       "id": req_id})
        with self._lock:
            self.readmits += 1

    def adopt(self, outstanding: int, routed: int, skipped: int,
              corrupt: int) -> None:
        self._journal({"event": "fleet_adopt",
                       "outstanding": int(outstanding),
                       "routed": int(routed), "skipped": int(skipped),
                       "corrupt_lines": int(corrupt)})
        with self._lock:
            self.adoptions += 1
            self.adopted_requests += int(routed)

    def snapshot(self) -> dict:
        with self._lock:
            routed = self.routed
            return {
                "routed": routed,
                "affinity_hits": self.affinity_hits,
                "affinity_misses": self.affinity_misses,
                "affinity_hit_rate": (
                    self.affinity_hits / routed if routed else 0.0),
                "steals": self.steals,
                "steal_events": self.steal_events,
                "spills": self.spills,
                "sheds": self.sheds,
                "adoptions": self.adoptions,
                "adopted_requests": self.adopted_requests,
                "quarantines": self.quarantines,
                "quarantine_drained": self.quarantine_drained,
                "readmits": self.readmits,
                "selftests": self.selftests,
                "selftests_failed": self.selftests_failed,
                "hedges_fired": self.hedges_fired,
                "brownout_steps": self.brownout_steps,
                "brownout_recoveries": self.brownout_recoveries,
            }


# --------------------------------------------------------------------------
# Prometheus text exposition (GET /metrics content negotiation).

_PROM_PREFIX = "benchfem_serve_"
# snapshot keys that are monotone counters (TYPE counter); everything
# else numeric is a gauge
_PROM_COUNTERS = frozenset({
    "requests_total", "shed_total", "completed", "failed", "batches",
    "padded_lanes_total", "midsolve_admissions",
    "broker_retries", "batch_resumes", "recovery_runs",
    "recovered_requests",
    # SDC defense (ISSUE 14): detection + adjudication counters
    "sdc_detected", "sdc_rollbacks", "sdc_terminal",
    # overload resilience (ISSUE 18): deadline split + hedge ledger
    "deadline_exceeded_early", "deadline_exceeded_late",
    "hedge_wins", "hedge_cancels",
    "fleet_hedges_fired", "fleet_brownout_steps",
    "fleet_brownout_recoveries",
    # request tracing (ISSUE 15): completeness counters
    "reqtrace_trace_complete", "reqtrace_trace_incomplete",
    # fleet block leaves (flattened as fleet_<leaf>): monotone counters
    "fleet_routed", "fleet_affinity_hits", "fleet_affinity_misses",
    "fleet_steals", "fleet_steal_events", "fleet_spills", "fleet_sheds",
    "fleet_adoptions", "fleet_adopted_requests",
    "fleet_quarantines", "fleet_quarantine_drained", "fleet_readmits",
    "fleet_selftests", "fleet_selftests_failed",
})

#: flattened-name prefixes that are monotone counters (dynamic leaves:
#: the anomaly tag set is small and fixed, but spelled per tag)
_PROM_COUNTER_PREFIXES = ("reqtrace_anomalies_",)

#: how deep the flattener follows nested dicts (reqtrace -> phases ->
#: queue -> p50_s is depth 4; anything deeper is a schema smell)
_PROM_MAX_DEPTH = 4


def _prom_name(key: str) -> str:
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in key)
    if out and out[0].isdigit():
        out = "_" + out
    return _PROM_PREFIX + out


def _prom_escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def prometheus_text(snapshot: dict) -> str:
    """Render a metrics snapshot as Prometheus text exposition format
    (version 0.0.4 — what a standard scrape expects): one
    ``# HELP``/``# TYPE`` header per metric, ``benchfem_serve_``-prefixed
    names, labelled series for the per-class failure counts and the
    per-(spec, bucket) latency split, and nested sub-dicts (cache,
    memory, fleet, reqtrace — including reqtrace.phases.<phase>.<q>)
    flattened recursively into underscore-joined gauge names.

    Cardinality is bounded by construction: the phase set is fixed, the
    anomaly tag set is fixed, spec keys are capped (_SPEC_KEYS_MAX +
    "_other") and ride as LABELS of a fixed series family, lists (the
    exemplar ring, the per-lane array) are never emitted, and
    non-numeric leaves collapse into one ``_info`` labelled gauge."""
    lines: list[str] = []

    def emit(key: str, value) -> None:
        name = _prom_name(key)
        kind = ("counter" if key in _PROM_COUNTERS
                or key.startswith(_PROM_COUNTER_PREFIXES) else "gauge")
        lines.append(f"# HELP {name} serve metrics snapshot field "
                     f"{key!r}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {float(value):g}")

    def emit_labelled(key: str, label: str, rows: dict, help_text: str,
                      kind: str = "gauge") -> None:
        name = _prom_name(key)
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        for lv, v in sorted(rows.items()):
            lines.append(f'{name}{{{label}="{_prom_escape(lv)}"}} '
                         f"{float(v):g}")

    def emit_tree(key: str, value, depth: int) -> None:
        if isinstance(value, bool):
            emit(key, int(value))
        elif isinstance(value, (int, float)):
            emit(key, value)
        elif isinstance(value, dict) and depth < _PROM_MAX_DEPTH:
            info = {}
            for leaf, lv in value.items():
                if isinstance(lv, (bool, int, float, dict)):
                    emit_tree(f"{key}_{leaf}", lv, depth + 1)
                elif isinstance(lv, str):
                    info[leaf] = lv
                # lists / None: JSON-only (exemplars, quarantined_lanes)
            if info:
                name = _prom_name(f"{key}_info")
                lab = ",".join(f'{k}="{_prom_escape(v)}"'
                               for k, v in sorted(info.items()))
                lines.append(f"# HELP {name} non-numeric {key} fields")
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name}{{{lab}}} 1")

    for key, value in snapshot.items():
        if key == "failed_by_class" and isinstance(value, dict):
            emit_labelled("failed_by_class", "failure_class", value,
                          "failed responses by harness failure class",
                          kind="counter")
        elif key == "latency_by_spec" and isinstance(value, dict):
            # per-(spec, bucket) percentiles as LABELLED series: the
            # spec key is a label value, never a metric name, so the
            # metric-name space stays fixed and the label cardinality
            # is bounded by the window's key cap
            for q in ("n", "p50_s", "p95_s", "p99_s"):
                emit_labelled(
                    f"latency_by_spec_{q}", "spec",
                    {k: row.get(q, 0.0) for k, row in value.items()},
                    f"per-(spec,bucket) response latency {q} "
                    "(bounded key set; overflow pools into _other)")
        elif key == "reqtrace" and isinstance(value, dict):
            emit_tree("reqtrace",
                      {k: v for k, v in value.items()
                       if k != "exemplars"}, 0)
        else:
            emit_tree(key, value, 0)
    return "\n".join(lines) + "\n"


def _pct(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return float(sorted_vals[i])


def replay_serve(journal_path: str) -> dict:
    """Fold a serve journal back into the incident summary: per-event
    counts, per-class failure counts, occupancy, hit-rate, padding
    waste, mid-solve admissions and cache-warm latency percentiles —
    enough to reconstruct "what happened" from the file alone (the
    journal IS the incident record; this is its reader)."""
    records, corrupt = read_records(journal_path)
    out = {
        "requests": 0, "shed": 0, "batches": 0, "responses_ok": 0,
        "responses_failed": 0, "failed_by_class": {}, "lanes_total": 0,
        "cache_hits": 0, "cache_misses": 0, "corrupt_lines": len(corrupt),
        "admits": 0, "midsolve_admissions": 0, "retires": 0,
        "padded_lanes_total": 0, "lane_slots_total": 0,
        "live_lane_boundaries": 0, "boundaries_total": 0,
        "broker_retries": 0, "batch_resumes": 0, "recovery_runs": 0,
        "recovered_requests": 0,
        # SDC defense (ISSUE 14): detections + adjudications + lane
        # quarantine/readmission evidence
        "sdc_detected": 0, "sdc_rollbacks": 0, "sdc_terminal": 0,
        "fleet_quarantines": 0, "fleet_quarantine_drained": 0,
        "fleet_readmits": 0, "fleet_selftests": 0,
        # fleet events (ISSUE 13): routing/steal/spill/adoption evidence
        "fleet_routed": 0, "fleet_affinity_hits": 0, "fleet_steals": 0,
        "fleet_steal_events": 0, "fleet_spills": 0, "fleet_adoptions": 0,
        "requests_by_device": {},
        # request tracing (ISSUE 15): serve_phase records + responses
        # carrying a phase decomposition (fold_reqtrace owns the full
        # percentile fold; these are the incident-summary counts)
        "phase_events": 0, "traced_responses": 0,
        # overload resilience (ISSUE 18): early/late deadline split,
        # hedge pair lifecycle and brownout transitions
        "deadline_exceeded_early": 0, "deadline_exceeded_late": 0,
        "hedges_fired": 0, "hedge_wins": 0, "hedge_cancels": 0,
        "brownout_steps": 0, "brownout_recoveries": 0,
    }
    warm_lat: list[float] = []
    occupancy: list[dict] = []  # (seq, iter, live) — occupancy over time
    for rec in records:
        ev = rec.get("event")
        if ev == "serve_request":
            out["requests"] += 1
            dev = rec.get("device")
            if dev is not None:
                out["requests_by_device"][dev] = (
                    out["requests_by_device"].get(dev, 0) + 1)
        elif ev == "serve_shed":
            out["shed"] += 1
            fc = rec.get("failure_class", "transient")
            out["failed_by_class"][fc] = (
                out["failed_by_class"].get(fc, 0) + 1)
            if fc == "deadline_exceeded":
                out["deadline_exceeded_early"] += 1
        elif ev == "serve_admit":
            out["admits"] += 1
            if rec.get("midsolve"):
                out["midsolve_admissions"] += 1
            occupancy.append({"seq": rec.get("seq"),
                              "iter": rec.get("iter"),
                              "live": rec.get("live")})
        elif ev == "serve_retire":
            out["retires"] += 1
            occupancy.append({"seq": rec.get("seq"),
                              "iter": rec.get("iter"),
                              "live": rec.get("live")})
        elif ev == "serve_batch":
            out["batches"] += 1
            out["lanes_total"] += int(rec.get("nrhs_live", 0))
            out["padded_lanes_total"] += int(rec.get("padded_lanes", 0))
            out["lane_slots_total"] += int(rec.get("nrhs_bucket", 0))
            out["live_lane_boundaries"] += int(
                rec.get("boundaries", 0)
                and round(rec.get("mean_live_lanes", 0.0)
                          * rec.get("boundaries", 0)))
            out["boundaries_total"] += int(rec.get("boundaries", 0))
            if rec.get("cache") == "hit":
                out["cache_hits"] += int(rec.get("nrhs_live", 0))
            else:
                out["cache_misses"] += int(rec.get("nrhs_live", 0))
        elif ev == "serve_retry":
            out["broker_retries"] += 1
            if rec.get("resumed"):
                out["batch_resumes"] += 1
        elif ev == "serve_recover":
            out["recovery_runs"] += 1
            out["recovered_requests"] += int(rec.get("replayed", 0))
        elif ev == "fleet_route":
            out["fleet_routed"] += 1
            if rec.get("affinity"):
                out["fleet_affinity_hits"] += 1
            if rec.get("spill"):
                out["fleet_spills"] += 1
        elif ev == "fleet_steal":
            out["fleet_steal_events"] += 1
            out["fleet_steals"] += int(rec.get("count", 0))
        elif ev == "fleet_adopt":
            out["fleet_adoptions"] += 1
        elif ev == "serve_sdc":
            out["sdc_detected"] += 1
            if rec.get("action") == "rollback":
                out["sdc_rollbacks"] += 1
            elif rec.get("action") == "terminal":
                out["sdc_terminal"] += 1
        elif ev == "fleet_quarantine":
            out["fleet_quarantines"] += 1
            out["fleet_quarantine_drained"] += int(rec.get("drained", 0))
        elif ev == "fleet_readmit":
            out["fleet_readmits"] += 1
        elif ev == "fleet_selftest":
            out["fleet_selftests"] += 1
        elif ev == "serve_phase":
            out["phase_events"] += 1
        elif ev == "serve_hedge_fired":
            out["hedges_fired"] += 1
        elif ev == "serve_hedge_won":
            out["hedge_wins"] += 1
        elif ev == "serve_hedge_cancelled":
            out["hedge_cancels"] += 1
        elif ev == "fleet_brownout":
            if rec.get("action") == "step":
                out["brownout_steps"] += 1
            elif rec.get("action") == "recover":
                out["brownout_recoveries"] += 1
        elif ev == "serve_response":
            if isinstance(rec.get("phase_s"), dict):
                out["traced_responses"] += 1
            if rec.get("deadline_late"):
                out["deadline_exceeded_late"] += 1
            if rec.get("ok"):
                out["responses_ok"] += 1
                if rec.get("cache") == "hit":
                    warm_lat.append(float(rec.get("latency_s", 0.0)))
            else:
                out["responses_failed"] += 1
                fc = rec.get("failure_class", "transient")
                out["failed_by_class"][fc] = (
                    out["failed_by_class"].get(fc, 0) + 1)
                if fc == "deadline_exceeded":
                    out["deadline_exceeded_early"] += 1
    out["mean_batch_occupancy"] = (
        out["lanes_total"] / out["batches"] if out["batches"] else 0.0)
    batched = out["cache_hits"] + out["cache_misses"]
    out["cache_hit_rate_requests"] = (
        out["cache_hits"] / batched if batched else 0.0)
    out["padding_waste"] = (
        out["padded_lanes_total"] / out["lane_slots_total"]
        if out["lane_slots_total"] else 0.0)
    out["mean_live_lanes"] = (
        out["live_lane_boundaries"] / out["boundaries_total"]
        if out["boundaries_total"] else 0.0)
    out["fleet_affinity_hit_rate"] = (
        out["fleet_affinity_hits"] / out["fleet_routed"]
        if out["fleet_routed"] else 0.0)
    warm = sorted(warm_lat)
    out["latency_warm_p50_s"] = _pct(warm, 0.50)
    out["latency_warm_p95_s"] = _pct(warm, 0.95)
    out["latency_warm_p99_s"] = _pct(warm, 0.99)
    out["occupancy_timeline"] = occupancy
    return out
