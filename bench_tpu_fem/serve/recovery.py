"""Broker crash recovery: fold a (possibly torn) serve journal back into
the set of admitted-but-unresponded requests, and verify the
exactly-once contract over any number of broker generations.

The write-ahead record is the existing ``serve_request`` journal line:
``Broker.submit`` fsyncs it (id, spec, scale) BEFORE the client gets its
``PendingRequest`` back, so every request a client may be waiting on is
durable. The matching visibility rule lives in ``Broker._respond``: the
``serve_response`` record is fsynced BEFORE ``done.set()`` releases the
client. Together they make recovery exactly-once by construction:

* a request with a ``serve_request`` record and no ``serve_response``
  record was never answered — the crash ate it mid-flight; replaying it
  answers it for the first time;
* a request whose response record is the TORN final line was never
  released to the client either (the fsync did not return, so
  ``done.set()`` never ran) — ``read_records`` drops the torn line and
  the request correctly replays;
* a request with a COMPLETE response record may have been seen by the
  client — it is never replayed.

``fold_outstanding`` is the reader half of that contract;
``Broker.recover`` is the writer half (re-admit each outstanding request
under its ORIGINAL id, so the journal reads as one continuous incident
across restarts). ``verify_exactly_once`` is the chaos-soak invariant:
over the whole journal — all broker generations appended to one file —
every requested id has exactly one response, no losses, no duplicates.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..harness.journal import read_records

_NUMERIC_ID = re.compile(r"^r(\d+)$")


@dataclass
class RecoveryPlan:
    """The fold of a serve journal a recovering broker consumes."""

    outstanding: list[dict] = field(default_factory=list)
    requests: int = 0
    responses: int = 0
    shed: int = 0
    corrupt: int = 0
    #: highest numeric rN id seen — the recovering broker resumes its id
    #: counter past it so fresh admissions never collide with replayed
    #: ids (the journal must stay one id-space per incident)
    max_numeric_id: int = 0


def fold_outstanding(path_or_records) -> RecoveryPlan:
    """Fold journal records into the recovery plan. Outstanding =
    requested, never responded, never shed — in admission order (the
    order the original clients were promised)."""
    if isinstance(path_or_records, str):
        records, corrupt = read_records(path_or_records)
    else:
        records, corrupt = list(path_or_records), []
    plan = RecoveryPlan(corrupt=len(corrupt))
    requested: dict[str, dict] = {}
    answered: set[str] = set()
    shed: set[str] = set()
    for rec in records:
        ev = rec.get("event")
        rid = rec.get("id")
        if ev == "serve_request" and rid:
            plan.requests += 1
            requested[rid] = {"id": rid, "spec": rec.get("spec") or {},
                              "scale": rec.get("scale", 1.0)}
            m = _NUMERIC_ID.match(str(rid))
            if m:
                plan.max_numeric_id = max(plan.max_numeric_id,
                                          int(m.group(1)))
        elif ev == "serve_response" and rid:
            plan.responses += 1
            answered.add(rid)
        elif ev == "serve_shed" and rid:
            plan.shed += 1
            shed.add(rid)
            # shed ids advance the id-space handoff too: a fleet-level
            # shed journals a fleet-minted id with NO serve_request
            # record, and a standby that re-minted it would journal a
            # serve_request whose id sits in the shed set — a later
            # crash would then read that admitted request as shed (not
            # outstanding, not lost): a silent, ledger-clean loss
            m = _NUMERIC_ID.match(str(rid))
            if m:
                plan.max_numeric_id = max(plan.max_numeric_id,
                                          int(m.group(1)))
    plan.outstanding = [req for rid, req in requested.items()
                       if rid not in answered and rid not in shed]
    return plan


def verify_exactly_once(path_or_records) -> dict:
    """The chaos-soak invariant over a whole incident journal (any
    number of broker generations appended to one file): every requested
    id has EXACTLY one response. Returns a verdict dict with ``ok`` and
    the offending id lists (bounded) — losses (requested, never
    answered) and duplicates (answered more than once) are both
    contract violations."""
    if isinstance(path_or_records, str):
        records, _ = read_records(path_or_records)
    else:
        records = list(path_or_records)
    requested: list[str] = []
    responses: dict[str, int] = {}
    shed: set[str] = set()
    for rec in records:
        ev, rid = rec.get("event"), rec.get("id")
        if not rid:
            continue
        if ev == "serve_request":
            requested.append(rid)
        elif ev == "serve_response":
            responses[rid] = responses.get(rid, 0) + 1
        elif ev == "serve_shed":
            shed.add(rid)
    lost = [r for r in requested if r not in responses and r not in shed]
    dup = sorted(r for r, n in responses.items() if n > 1)
    return {
        "ok": not lost and not dup,
        "requested": len(requested),
        "responded": sum(responses.values()),
        "shed": len(shed),
        "lost": lost[:32],
        "duplicates": dup[:32],
    }
