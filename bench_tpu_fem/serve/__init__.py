"""Solver-as-a-service: batched multi-RHS CG behind an
admission-controlled request broker with an AOT-executable cache.

The one-shot benchmark amortises compile/launch cost by problem size
(>= 10M dofs/device, README.md:160-163 in the reference); this package
amortises it ACROSS REQUESTS — the production-serving shape the ROADMAP
north star names:

  engine.py   SolveSpec -> compiled batched solver with an iteration-
              boundary checkpoint API (la.cg.BatchedCGState machinery:
              the fused nrhs-native kron ring on f32 uniform specs,
              the unfused vmapped composition elsewhere; vmapped
              cg_solve_df for df32 pairs, continuous-gated)
  cache.py    AOT executables keyed by (degree, cell shape, precision,
              geometry class, PLANNED engine form, nrhs bucket, device
              mesh), LRU + hit/miss/evict/compile counters + warmup
  broker.py   bounded-queue admission control, continuous batching
              (mid-solve lane admissions + early retires at iteration
              boundaries; fixed-window fallback for gated solvers),
              per-batch hard deadline, harness-taxonomy fault classes
  server.py   localhost HTTP/JSON front end (POST /solve, GET /metrics,
              GET /healthz) — `python -m bench_tpu_fem.serve`
  metrics.py  counters + crash-safe JSONL journal (harness.journal),
              with `replay_serve` folding a journal back into the
              incident summary
  fleet.py    multi-device dispatch (ISSUE 13): per-device queues with
              spec-aware affinity routing, work stealing, SLO-burn
              spill, and standby journal adoption
  artifacts.py  shared AOT executable-artifact store: serialized
              compiled solvers keyed like cache.ExecutableKey, so
              replicas warm from peers with zero recompiles

Everything is stdlib + the existing jax stack: no new dependencies.
"""

from .artifacts import ArtifactStore, ArtifactWarmCache
from .broker import Broker, QueueFull, RETRIABLE_CLASSES
from .fleet import FleetDispatcher
from .cache import (
    NRHS_BUCKETS,
    ExecutableCache,
    ExecutableKey,
    default_cache,
    nrhs_bucket,
)
from .engine import (
    ArtifactIncompatible,
    BatchResult,
    CompiledSolver,
    SolveSpec,
    UnsupportedSpec,
    build_solver,
    planned_engine_form,
    spec_cache_key,
)
from .metrics import FleetMetrics, Metrics, prometheus_text, replay_serve
from .recovery import RecoveryPlan, fold_outstanding, verify_exactly_once
from .server import make_server

__all__ = [
    "ArtifactIncompatible",
    "ArtifactStore",
    "ArtifactWarmCache",
    "BatchResult",
    "Broker",
    "CompiledSolver",
    "ExecutableCache",
    "ExecutableKey",
    "FleetDispatcher",
    "FleetMetrics",
    "Metrics",
    "NRHS_BUCKETS",
    "QueueFull",
    "RETRIABLE_CLASSES",
    "RecoveryPlan",
    "SolveSpec",
    "UnsupportedSpec",
    "build_solver",
    "default_cache",
    "fold_outstanding",
    "make_server",
    "nrhs_bucket",
    "planned_engine_form",
    "prometheus_text",
    "replay_serve",
    "spec_cache_key",
    "verify_exactly_once",
]
