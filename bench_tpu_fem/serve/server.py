"""Localhost HTTP/JSON front end over the broker (stdlib http.server).

The same handler fronts a single Broker or a FleetDispatcher
(serve.fleet) — both expose submit/wait/metrics_snapshot/shutdown; the
fleet's /metrics additionally carries the `fleet` block (routing/steal/
spill counters, artifact-store stats) and the per-lane `lanes` list.

Endpoints:

  POST /solve    {"degree": 3, "ndofs": 50000, "nreps": 30,
                  "precision": "f32", "geom_perturb_fact": 0.0,
                  "scale": 1.0}
                 -> 200 {"ok": true, "xnorm": ..., "nrhs_live": ...,
                         "nrhs_bucket": ..., "cache": "hit", ...}
                 -> 503 + Retry-After on shed / retriable failure
                    (failure_class in transient/timeout/oom/tunnel_wedge)
                 -> 422 on deterministic failure (mosaic_reject/
                    accuracy_fail/unsupported) — retrying cannot help
                 -> 400 on malformed requests
  GET  /metrics  metrics snapshot + cache counters + device-memory
                 telemetry. Content negotiation: JSON by default;
                 Prometheus text exposition (0.0.4) when the Accept
                 header asks for text/plain or openmetrics (what
                 standard scrapers send), or with ?format=prometheus.
  GET  /healthz  {"ok": true}

ThreadingHTTPServer gives one handler thread per connection; every
handler immediately parks on its broker future, so concurrency is
bounded by the BROKER's queue, not by threads — admission control stays
the single backpressure point.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .broker import Broker, QueueFull
from .engine import SolveSpec

RETRY_AFTER_S = 1


def make_handler(broker: Broker, request_timeout_s: float = 300.0,
                 quiet: bool = True):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: N802
            if not quiet:
                super().log_message(fmt, *args)

        def _send(self, code: int, payload: dict,
                  headers: dict | None = None) -> None:
            body = (json.dumps(payload) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, code: int, body: str,
                       content_type: str) -> None:
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            from urllib.parse import parse_qs, urlparse

            url = urlparse(self.path)
            if url.path == "/healthz":
                self._send(200, {"ok": True})
            elif url.path == "/metrics":
                from ..obs.memory import memory_summary
                from .metrics import prometheus_text

                snap = broker.metrics_snapshot(memory=memory_summary())
                accept = (self.headers.get("Accept", "") or "").lower()
                fmt = (parse_qs(url.query).get("format", [""])[0]
                       or "").lower()
                # standard scrapers ask for text/plain (0.0.4) or
                # openmetrics and never for application/json; JSON wins
                # whenever the client lists it (e.g. the common
                # composite default "application/json, text/plain, */*"
                # must keep getting JSON — existing consumers)
                want_prom = (fmt == "prometheus"
                             or (("openmetrics" in accept
                                  or "text/plain" in accept)
                                 and "application/json" not in accept))
                if want_prom:
                    self._send_text(
                        200, prometheus_text(snap),
                        "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._send(200, snap)
            else:
                self._send(404, {"ok": False, "error": "not found"})

        def do_POST(self):  # noqa: N802
            if self.path != "/solve":
                self._send(404, {"ok": False, "error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(length) or b"{}")
                if not isinstance(req, dict):
                    raise ValueError(
                        f"request body must be a JSON object, got "
                        f"{type(req).__name__}")
                # client deadline propagation (ISSUE 18): wire-level
                # milliseconds (the gRPC idiom), seconds inside
                deadline_ms = req.get("deadline_ms")
                spec = SolveSpec(
                    degree=int(req.get("degree", 3)),
                    ndofs=int(req.get("ndofs", 50_000)),
                    nreps=int(req.get("nreps", 30)),
                    precision=str(req.get("precision", "f32")),
                    geom_perturb_fact=float(
                        req.get("geom_perturb_fact", 0.0)),
                    deadline_s=(float(deadline_ms) / 1000.0
                                if deadline_ms is not None else None),
                    form=str(req.get("form", "poisson")),
                )
                scale = float(req.get("scale", 1.0))
                # warm-start hint (ISSUE 20): the heat workload's
                # previous-step scale; 0.0 (absent) is the cold path
                warm_scale = float(req.get("warm_scale", 0.0))
            except (ValueError, TypeError, json.JSONDecodeError) as exc:
                self._send(400, {"ok": False,
                                 "error": f"malformed request: {exc}",
                                 "failure_class": "unsupported",
                                 "retriable": False})
                return
            try:
                pending = broker.submit(spec, scale,
                                        warm_scale=warm_scale)
            except QueueFull as exc:
                # the shed carries its own class + retry hint when the
                # admission controller computed one (ISSUE 18): a
                # deadline refusal reads deadline_exceeded, and the
                # Retry-After header is the predicted-queue-time fold
                # instead of the blind constant
                retry_after = getattr(exc, "retry_after_s", None)
                body = {"ok": False, "error": str(exc),
                        "failure_class": getattr(exc, "failure_class",
                                                 "transient"),
                        "retriable": True}
                if retry_after is not None:
                    body["retry_after_s"] = retry_after
                self._send(503, body,
                           {"Retry-After": (retry_after
                                            if retry_after is not None
                                            else RETRY_AFTER_S)})
                return
            result = broker.wait(pending, request_timeout_s)
            if result.get("ok"):
                self._send(200, result)
            elif result.get("retriable"):
                self._send(503, result, {"Retry-After": RETRY_AFTER_S})
            else:
                self._send(422, result)

    return Handler


def make_server(broker: Broker, host: str = "127.0.0.1", port: int = 0,
                request_timeout_s: float = 300.0,
                quiet: bool = True) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral; the bound port is
    `server.server_address[1]`). The caller owns serve_forever/shutdown
    — tests run it on a thread, the CLI blocks on it."""
    handler = make_handler(broker, request_timeout_s, quiet)

    class _Server(ThreadingHTTPServer):
        daemon_threads = True
        # stdlib default backlog is 5: a fleet loadgen's 32-connection
        # burst overflows it and reads as connection resets at the
        # client — raise it to the broker's own admission scale (the
        # QUEUE stays the single backpressure point, not the socket)
        request_queue_size = 128

    return _Server((host, port), handler)
