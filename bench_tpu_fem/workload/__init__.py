"""Workload generators (ISSUE 20): temporally-correlated traffic for
the serve layer and the implicit-Euler heat stepper that produces it.

`traffic` owns the deterministic-seeded streams (scale random walks,
mixed-spec request sequences) — same seed, same stream, byte for byte,
so a load test replays exactly. `heat` owns the physics: the backward-
Euler time stepper whose per-step CG solves are the workload's requests,
and whose step-to-step solution continuity is WHY warm starts save
iterations (the measured contract the perfgate pins).
"""

from .heat import HeatResult, run_heat, warm_start_savings
from .traffic import heat_scale_stream, spec_mixture, warm_pairs

__all__ = [
    "HeatResult",
    "run_heat",
    "warm_start_savings",
    "heat_scale_stream",
    "spec_mixture",
    "warm_pairs",
]
