"""Deterministic temporally-correlated traffic streams (ISSUE 20).

A serving benchmark is only as honest as its traffic: the warm-start
savings the perfgate pins exist BECAUSE consecutive heat steps are
correlated, so the generator must produce correlation that is (a)
controlled — one drift knob, not an accident of the RNG — and (b)
replayable — the same seed yields the same stream byte for byte, so a
failed run re-executes on identical input (the same discipline as the
chaos fault plans).

Everything here is host-side numpy with an explicitly-seeded Generator;
nothing touches jax or the wall clock.
"""

from __future__ import annotations

import numpy as np

# Bounds on the scale random walk: the RHS-as-scale serve protocol is
# linear in the scale, but a walk wandering to 1e6 (or 1e-6) would stop
# resembling a physical time series and quietly change the xnorm
# magnitudes every latency/SDC envelope was calibrated against.
SCALE_MIN = 0.5
SCALE_MAX = 2.0


def heat_scale_stream(nsteps: int, seed: int = 0,
                      drift: float = 0.01) -> np.ndarray:
    """A bounded multiplicative random walk of RHS scales — the
    temporally-correlated request stream of a heat time series under
    the RHS-as-scale protocol: step k's RHS is scales[k] * b for the
    canonical RHS b, and consecutive scales differ by O(drift).

    Deterministic in (nsteps, seed, drift): numpy's PCG64 stream is
    versioned and platform-stable, so a replay regenerates the exact
    array (tests/test_workload.py pins this).
    """
    if nsteps < 1:
        raise ValueError(f"nsteps must be >= 1, got {nsteps}")
    rng = np.random.default_rng(seed)
    scales = np.empty(nsteps, np.float64)
    s = 1.0
    for k in range(nsteps):
        scales[k] = s
        s = float(np.clip(s * (1.0 + drift * rng.standard_normal()),
                          SCALE_MIN, SCALE_MAX))
    return scales


def warm_pairs(scales) -> list:
    """Fold a scale stream into (scale, warm_scale) request pairs: the
    warm hint for step k is step k-1's scale (the previous solution
    under the RHS-as-scale protocol, x_{k-1} = scales[k-1] * xbase),
    and step 0 is cold (warm 0.0 — bitwise the cold admit)."""
    scales = np.asarray(scales, np.float64)
    return [(float(s), float(scales[k - 1]) if k else 0.0)
            for k, s in enumerate(scales)]


def spec_mixture(nreq: int, seed: int = 0,
                 forms=("poisson", "mass", "varkappa", "heat"),
                 degrees=(1, 3), ndofs: int = 4096,
                 nreps: int = 30) -> list[dict]:
    """A deterministic mixed-spec request sequence: each entry is a
    kwargs dict for serve.engine.SolveSpec (plus a "scale" key), drawn
    form-and-degree uniform from the given sets. The mixture exercises
    the executable cache's form axis (every (form, degree) pair is its
    own ExecutableKey) and the broker's compatible-batch gathering
    under heterogeneous traffic."""
    if nreq < 1:
        raise ValueError(f"nreq must be >= 1, got {nreq}")
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(nreq):
        out.append({
            "form": str(rng.choice(list(forms))),
            "degree": int(rng.choice(list(degrees))),
            "ndofs": int(ndofs),
            "nreps": int(nreps),
            "scale": float(rng.uniform(0.8, 1.2)),
        })
    return out
