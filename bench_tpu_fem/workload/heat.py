"""Backward-Euler heat stepper (ISSUE 20): the physics behind the
temporally-correlated serve workload.

Each time step of u_t = div(grad u) + f with homogeneous Dirichlet
walls solves

    (M + dt K) u^{n+1} = M u^n + dt b

— exactly the registry's "heat" form row (grad_coeff = HEAT_DT,
mass_coeff = 1) on the left, one mass-form apply on the right. The
solve runs the SAME batched checkpointable CG the serve layer compiles
(la.cg: one lane, rtol-frozen), so the per-step iteration counts
measured here are the counts a served heat stream produces: warm runs
seed each step's CG with the previous step's solution, cold runs start
from zero, and the difference IS the warm-start savings the perfgate
pins (scripts/perfgate.py `forms` leg, `heat_warm_start_iters_saved`).

Everything is in-process and journal-free — the serve-side stream
(workload.traffic + scripts/serve_loadgen.py --workload heat:N) is the
end-to-end variant of the same physics under the RHS-as-scale protocol.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..forms.registry import HEAT_DT, HEAT_RTOL


@dataclass
class HeatResult:
    """Per-step CG iteration accounting for one heat run."""

    nsteps: int
    warm: bool
    rtol: float
    dt: float
    iters: list[int] = field(default_factory=list)
    xnorms: list[float] = field(default_factory=list)

    @property
    def iters_total(self) -> int:
        return int(sum(self.iters))

    @property
    def iters_after_first(self) -> list[int]:
        """Steps 1..N-1 — the steps a warm start can help (step 0 has
        no previous solution; warm and cold are identical there)."""
        return self.iters[1:]


def _build_problem(ndofs: int, degree: int, perturb: float, dtype):
    """Mesh + heat/mass operators + assembled source RHS (host f64,
    the oracle-precision convention every driver shares)."""
    import jax.numpy as jnp

    from ..elements.tables import build_operator_tables
    from ..fem.assemble import assemble_rhs
    from ..fem.geometry import geometry_factors
    from ..fem.source import default_source
    from ..forms.operators import build_form_operator
    from ..forms.registry import form_spec
    from ..mesh.box import create_box_mesh
    from ..mesh.dofmap import (
        boundary_dof_marker,
        cell_dofmap,
        dof_coordinates,
        dof_grid_shape,
    )
    from ..mesh.sizing import compute_mesh_size

    n = compute_mesh_size(ndofs, degree)
    t = build_operator_tables(degree, 1, "gll")
    mesh = create_box_mesh(n, geom_perturb_fact=perturb)
    op_heat = build_form_operator(mesh, form_spec("heat"), degree, 1,
                                  "gll", dtype=dtype, tables=t)
    op_mass = build_form_operator(mesh, form_spec("mass"), degree, 1,
                                  "gll", dtype=dtype, tables=t)
    grid_shape = dof_grid_shape(n, degree)
    bc_grid = boundary_dof_marker(n, degree)
    coords = dof_coordinates(mesh.vertices, degree, t.nodes1d)
    f = default_source(coords).ravel()
    dm = cell_dofmap(n, degree)
    corners = mesh.cell_corners.reshape(-1, 2, 2, 2, 3)
    _, wdetJ = geometry_factors(corners, t.pts1d, t.wts1d,
                                compute_G=False)
    b = assemble_rhs(t, wdetJ, dm, f,
                     bc_grid.ravel()).reshape(grid_shape)
    return op_heat, op_mass, jnp.asarray(b, dtype)


def run_heat(nsteps: int, ndofs: int = 4096, degree: int = 3,
             perturb: float = 0.0, warm: bool = True,
             rtol: float = HEAT_RTOL, max_iter: int = 200,
             dtype=None) -> HeatResult:
    """Run `nsteps` backward-Euler steps from u0 = 0 and return the
    per-step CG iteration counts. `warm=True` seeds each step's CG
    with the previous step's solution (x0 = u^n); `warm=False` starts
    every step cold (x0 = 0) — same operators, same RHS sequence, same
    rtol, so the iteration difference isolates the warm start.

    Deterministic: no RNG anywhere (the forcing is the fixed benchmark
    source), so two runs with the same arguments produce identical
    iteration sequences.
    """
    import jax
    import jax.numpy as jnp

    from ..la.cg import (
        batched_cg_init_warm,
        batched_cg_run,
        make_batched_cg_step,
        unfused_batch_engine,
    )

    if dtype is None:
        dtype = (jnp.float64 if jax.config.jax_enable_x64
                 else jnp.float32)
    if nsteps < 1:
        raise ValueError(f"nsteps must be >= 1, got {nsteps}")
    op_heat, op_mass, b = _build_problem(ndofs, degree, perturb, dtype)
    dt = HEAT_DT

    def _step(Ah, Am, u_n, x0, bb):
        rhs = Am.apply(u_n) + dt * bb
        st = batched_cg_init_warm(rhs[None], x0[None],
                                  jax.vmap(Ah.apply), rtol=rtol)
        step = make_batched_cg_step(
            unfused_batch_engine(jax.vmap(Ah.apply)), max_iter,
            rtol=rtol)
        st = batched_cg_run(st, step, max_iter)
        return st.X[0], st.iters[0]

    step_fn = jax.jit(_step)
    res = HeatResult(nsteps=nsteps, warm=warm, rtol=rtol, dt=dt)
    u = jnp.zeros_like(b)
    for _ in range(nsteps):
        x0 = u if warm else jnp.zeros_like(b)
        u, iters = step_fn(op_heat, op_mass, u, x0, b)
        res.iters.append(int(np.asarray(iters)))
        res.xnorms.append(float(jnp.sqrt(jnp.vdot(u, u).real)))
    return res


def warm_start_savings(nsteps: int, **kwargs) -> dict:
    """Run the SAME heat time series warm and cold and fold the
    iteration ledger the perfgate `forms` leg counters come from.
    `heat_warm_start_iters_saved` is total cold minus total warm
    iterations over the steps a warm start can influence (step 0
    excluded: both runs are cold there by construction)."""
    warm = run_heat(nsteps, warm=True, **kwargs)
    cold = run_heat(nsteps, warm=False, **kwargs)
    saved = sum(cold.iters_after_first) - sum(warm.iters_after_first)
    return {
        "nsteps": nsteps,
        "iters_warm": warm.iters,
        "iters_cold": cold.iters,
        "iters_saved": int(saved),
        "xnorm_final_warm": warm.xnorms[-1],
        "xnorm_final_cold": cold.xnorms[-1],
    }
