"""Operator constant tables: interpolation matrix phi0 and the 1D
collocation derivative matrix dphi1.

Mirrors the table construction in the reference operator constructors
(/root/reference/src/laplacian.hpp:123-212):

- element0: 1D Lagrange of degree P with nodes at the GLL points (the
  "gll_warped" variant) -- always GLL-noded, for both quadrature types.
- quadrature: nq = P + qmode + 1 points (GLL or Gauss rule).
- element1: 1D Lagrange of degree nq-1 whose nodes *are* the quadrature
  points, so its dofs collocate with quadrature ("discontinuous" in the
  reference; node placement is all that matters here).
- phi0[q, i]  = element0 basis i evaluated at quadrature point q, i.e. the
  interpolation matrix element0 -> element1. Identity iff qmode == 0 with
  GLL quadrature (enforced, as in laplacian.hpp:197-198).
- dphi1[q, i] = element1 basis i derivative at quadrature point q (a square
  spectral differentiation matrix).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .lagrange import gll_nodes, lagrange_eval, lagrange_eval_deriv
from .quadrature import make_quadrature_1d


@dataclass(frozen=True)
class OperatorTables:
    degree: int
    qmode: int
    rule: str  # "gll" or "gauss"
    nd: int  # dofs per direction = degree + 1
    nq: int  # quadrature points per direction = degree + qmode + 1
    pts1d: np.ndarray  # (nq,) quadrature points on [0, 1]
    wts1d: np.ndarray  # (nq,) quadrature weights
    nodes1d: np.ndarray  # (nd,) element0 nodes (sorted GLL points)
    phi0: np.ndarray  # (nq, nd) interpolation matrix element0 -> quadrature
    dphi1: np.ndarray  # (nq, nq) collocation derivative matrix
    is_identity: bool  # phi0 is the identity (qmode=0, GLL)


def _snap_small(mat: np.ndarray) -> np.ndarray:
    """Zero entries below 5 eps, as the reference does before the identity
    check (/root/reference/src/laplacian.hpp:188-193)."""
    out = mat.copy()
    out[np.abs(out) < 5 * np.finfo(np.float64).eps] = 0.0
    return out


def _matrix_is_identity(mat: np.ndarray) -> bool:
    if mat.shape[0] != mat.shape[1]:
        return False
    eps = np.finfo(np.float64).eps
    return bool(np.all(np.abs(mat - np.eye(mat.shape[0])) <= 5 * eps))


def build_operator_tables(degree: int, qmode: int, rule: str = "gll") -> OperatorTables:
    if not 1 <= degree <= 8:
        raise ValueError(f"unsupported degree {degree} (expected 1..8)")
    if qmode not in (0, 1):
        raise ValueError("Invalid qmode.")
    if rule not in ("gll", "gauss"):
        raise ValueError(f"unknown quadrature rule '{rule}'")

    pts, wts = make_quadrature_1d(rule, degree, qmode)
    nodes = gll_nodes(degree)

    phi0 = _snap_small(lagrange_eval(nodes, pts))
    is_identity = _matrix_is_identity(phi0)
    if qmode == 0 and not is_identity:
        # Same constraint as laplacian.hpp:197-198: qmode=0 requires the
        # quadrature points to collocate with the element nodes (GLL only).
        raise ValueError("Expecting identity interpolation matrix for qmode=0")

    dphi1 = lagrange_eval_deriv(pts, pts)

    return OperatorTables(
        degree=degree,
        qmode=qmode,
        rule=rule,
        nd=degree + 1,
        nq=len(pts),
        pts1d=pts,
        wts1d=wts,
        nodes1d=nodes,
        phi0=phi0,
        dphi1=dphi1,
        is_identity=is_identity,
    )
