"""1D Gauss-Legendre and Gauss-Lobatto-Legendre quadrature on [0, 1].

Capability parity with `basix::quadrature::make_quadrature` as used by the
reference operator setup (/root/reference/src/laplacian.hpp:125-146,166-175):
the reference requests a rule by *polynomial exactness degree* via
    GLL:   qdeg(p) = 2p-2 for p > 2 else 2p-1
    Gauss: qdeg(p) = 2p
with p = element_degree + qmode, and Basix returns the minimal-point rule.
Both maps resolve to nq = p + 1 points in 1D, which is also how the reference
dispatches its kernels (Q = P+1 for qmode=0, Q = P+2 for qmode=1,
/root/reference/src/laplacian.hpp:361-398).
"""

from __future__ import annotations

import numpy as np
from numpy.polynomial import legendre as npleg


def gauss_points_weights(n: int) -> tuple[np.ndarray, np.ndarray]:
    """n-point Gauss-Legendre rule on [0, 1] (exact for degree 2n-1)."""
    if n < 1:
        raise ValueError("need n >= 1 quadrature points")
    x, w = npleg.leggauss(n)
    return (x + 1.0) / 2.0, w / 2.0


def gll_points_weights(n: int) -> tuple[np.ndarray, np.ndarray]:
    """n-point Gauss-Lobatto-Legendre rule on [0, 1] (exact for degree 2n-3).

    Points are the endpoints plus the roots of L'_{n-1}; weights are
    w_i = 2 / (n (n-1) L_{n-1}(x_i)^2) on [-1, 1], halved for [0, 1].
    """
    if n < 2:
        raise ValueError("GLL rule needs n >= 2 points")
    if n == 2:
        x = np.array([-1.0, 1.0])
    else:
        # Roots of the derivative of the (n-1)-th Legendre polynomial.
        c = np.zeros(n)
        c[n - 1] = 1.0
        dc = npleg.legder(c)
        interior = np.sort(npleg.legroots(dc).real)
        # Polish with Newton iterations on L'_{n-1} for full f64 accuracy.
        d2c = npleg.legder(dc)
        for _ in range(3):
            interior = interior - npleg.legval(interior, dc) / npleg.legval(interior, d2c)
        x = np.concatenate(([-1.0], interior, [1.0]))
    Ln = npleg.legval(x, np.eye(n)[n - 1])
    w = 2.0 / (n * (n - 1) * Ln**2)
    return (x + 1.0) / 2.0, w / 2.0


def quadrature_degree(rule: str, p: int) -> int:
    """Polynomial exactness degree requested by the reference for parameter p.

    Mirrors the q_map lambdas in /root/reference/src/laplacian.hpp:128-133 and
    the form tables in /root/reference/src/poisson64.py:19-20.
    """
    if rule == "gauss":
        return 2 * p
    if rule == "gll":
        return 2 * p - 2 if p > 2 else 2 * p - 1
    raise ValueError(f"unknown quadrature rule '{rule}'")


def num_points_for_degree(rule: str, qdeg: int) -> int:
    """Minimal number of 1D points whose rule is exact to degree `qdeg`."""
    if rule == "gauss":
        # n points exact to 2n-1
        return (qdeg + 2) // 2
    if rule == "gll":
        # n points exact to 2n-3
        return max(2, (qdeg + 4) // 2)
    raise ValueError(f"unknown quadrature rule '{rule}'")


def make_quadrature_1d(rule: str, degree: int, qmode: int) -> tuple[np.ndarray, np.ndarray]:
    """1D rule for an operator of element degree `degree` and quadrature mode
    `qmode` (0 or 1). Resolves to degree + qmode + 1 points for both rules."""
    nq = num_points_for_degree(rule, quadrature_degree(rule, degree + qmode))
    assert nq == degree + qmode + 1, (rule, degree, qmode, nq)
    if rule == "gauss":
        return gauss_points_weights(nq)
    return gll_points_weights(nq)
