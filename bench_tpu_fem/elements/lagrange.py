"""Lagrange basis tabulation on arbitrary 1D node sets.

Replaces `basix::create_element(...).tabulate` and
`basix::compute_interpolation_operator` (/root/reference/src/laplacian.hpp:
161-212). The reference's "gll_warped" Lagrange variant is, on an interval,
simply the Lagrange basis through the GLL points; we use the sorted point set
directly since this framework owns its dof numbering (grid-lexicographic,
see bench_tpu_fem.mesh.dofmap) rather than Basix's vertex-first ordering.
"""

from __future__ import annotations

import numpy as np

from .quadrature import gauss_points_weights, gll_points_weights


def gll_nodes(degree: int) -> np.ndarray:
    """Nodes of the degree-P GLL-warped Lagrange element on [0, 1], sorted."""
    pts, _ = gll_points_weights(degree + 1)
    return pts


def gl_nodes(degree: int) -> np.ndarray:
    """Nodes of the degree-P Gauss-point (gl_warped) element on [0, 1]."""
    pts, _ = gauss_points_weights(degree + 1)
    return pts


def lagrange_eval(nodes: np.ndarray, x: np.ndarray) -> np.ndarray:
    """Tabulate phi[q, i] = L_i(x_q) for the Lagrange basis through `nodes`.

    Uses the direct product form; node counts here are <= 10, where this is
    accurate to a few ulp in float64.
    """
    nodes = np.asarray(nodes, dtype=np.float64)
    x = np.asarray(x, dtype=np.float64)
    n = len(nodes)
    phi = np.ones((len(x), n))
    for i in range(n):
        for j in range(n):
            if j != i:
                phi[:, i] *= (x - nodes[j]) / (nodes[i] - nodes[j])
    return phi


def lagrange_eval_deriv(nodes: np.ndarray, x: np.ndarray) -> np.ndarray:
    """Tabulate dphi[q, i] = L_i'(x_q).

    L_i'(x) = sum_{m != i} 1/(x_i - x_m) * prod_{j != i,m} (x - x_j)/(x_i - x_j).
    """
    nodes = np.asarray(nodes, dtype=np.float64)
    x = np.asarray(x, dtype=np.float64)
    n = len(nodes)
    dphi = np.zeros((len(x), n))
    for i in range(n):
        for m in range(n):
            if m == i:
                continue
            term = np.full(len(x), 1.0 / (nodes[i] - nodes[m]))
            for j in range(n):
                if j != i and j != m:
                    term *= (x - nodes[j]) / (nodes[i] - nodes[j])
            dphi[:, i] += term
    return dphi
