"""1D quadrature rules and Lagrange element tabulation (layer L0).

Replaces the reference's use of Basix (`basix::quadrature::make_quadrature`,
`basix::create_element`, `basix::compute_interpolation_operator`; see
/root/reference/src/laplacian.hpp:123-212) with a pure-numpy implementation.
All tables are computed host-side in float64 and shipped to the device as
compile-time constants of the jitted operator.
"""

from .quadrature import (
    gauss_points_weights,
    gll_points_weights,
    make_quadrature_1d,
    num_points_for_degree,
    quadrature_degree,
)
from .lagrange import gll_nodes, lagrange_eval, lagrange_eval_deriv
from .tables import OperatorTables, build_operator_tables

__all__ = [
    "gauss_points_weights",
    "gll_points_weights",
    "make_quadrature_1d",
    "num_points_for_degree",
    "quadrature_degree",
    "gll_nodes",
    "lagrange_eval",
    "lagrange_eval_deriv",
    "OperatorTables",
    "build_operator_tables",
]
