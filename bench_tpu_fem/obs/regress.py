"""Regression sentinel (ISSUE 10): watch the benchmark's own history.

PR 8 gave every number *attribution* (roofline placement, phase spans,
memory telemetry); this module watches numbers *over time* — three
layers, stdlib + numpy only:

**Round trend loader** (`load_trend`): schema-tolerant folding of the
committed round artifacts — ``BENCH_r*.json`` (five generations of
schema: r01's bare metric line, r02/r03's enriched parse, r04's
error-stamped zero, r05's ``parsed: null`` tunnel wedge, plus the
``*_measured`` provenance sidecars), ``MULTICHIP_r*.json`` and the
``MEASURE_r*.jsonl`` harness journals — into one per-round trend table.
The honesty rule (satellite): a wedged round is a **labelled gap**
(``status: "gap"`` with its ``failure_class`` from the tail, via
`harness.classify`), NEVER a zero-throughput data point — averaging a
wedge into a trajectory would manufacture a regression out of an
infrastructure failure.

**Statistical comparison** (`classify_timing`): current vs pinned
baseline over the ``--timing-reps`` per-rep wall distributions
(``timing.walls_s``, stamped by BenchObserver). Mann-Whitney U (rank
sum, tie-corrected normal approximation) for significance + bootstrap
CI on the median + a relative effect-size threshold, classifying
``improved`` / ``neutral`` / ``regressed`` (``insufficient-data`` below
3 reps a side). Wall-clock on shared CI hosts is noisy, so this
classification is **advisory** — it prints, it never gates.

**Deterministic-counter gating** (`gate_counters`): the counters that
are noise-free on CPU for a pinned workload — trace-level
``collectives_per_iter``, executable-cache compile counts and
request-weighted hit-rate, shed/failed/lost request counts, journal
corruption, record-contract booleans — gate HARD (any violation is the
CI perfgate lane's rc 1). A collective that sneaks back into the
overlapped iteration or a recompile that reappears in a warm serve run
is a real regression no matter what the clock says.

Serve SLO tracking lives in the shared `burn_rates` fold here (consumed
live by `serve.metrics.Metrics.snapshot` and offline by
`python -m bench_tpu_fem.obs trend` over a serve journal's request
lifecycles).
"""

from __future__ import annotations

import glob
import json
import math
import os
import re

import numpy as np

# --------------------------------------------------------------------------
# Round trend loader.

_ROUND_BENCH = re.compile(r"BENCH_r(\d+)\.json$")
_ROUND_SIDE = re.compile(r"BENCH_r(\d+)_([a-z_]+)\.json$")
_ROUND_MULTI = re.compile(r"MULTICHIP_r(\d+)\.json$")
_ROUND_JOURNAL = re.compile(r"MEASURE_r(\d+)\.jsonl$")


def _read_json(path: str):
    try:
        with open(path) as fh:
            return json.load(fh), None
    except (OSError, json.JSONDecodeError) as exc:
        return None, str(exc)


def _classify(text: str, rc=None) -> str:
    from ..harness.classify import classify_text

    # rc 124/-9 are the harness/timeout(1) kill signatures: the tail
    # decides wedge-vs-timeout exactly as the runner's adjudication does
    return classify_text(text or "", timed_out=rc in (124, -9))


def _bench_row(path: str, rnd: int) -> dict:
    """One BENCH_rNN.json -> one trend row. The loader must accept every
    schema generation committed to date AND refuse to fabricate data:
    no parse / an error-stamped parse is a labelled gap."""
    row = {"round": rnd, "source": os.path.basename(path), "kind": "bench"}
    d, err = _read_json(path)
    if d is None:
        row.update(status="gap", failure_class="transient",
                   detail=f"unreadable artifact: {err}")
        return row
    parsed = d.get("parsed") if isinstance(d, dict) else None
    rc = d.get("rc") if isinstance(d, dict) else None
    tail = d.get("tail", "") if isinstance(d, dict) else ""
    if not isinstance(parsed, dict):
        # r05 shape: rc=124, parsed null — the tunnel wedged and the
        # round produced NO number. A labelled gap, never a zero.
        tail_lines = (tail or "").strip().splitlines()
        row.update(status="gap", failure_class=_classify(tail, rc),
                   detail=tail_lines[-1][:200] if tail_lines
                   else f"no parsed payload (rc={rc})")
        return row
    if parsed.get("error") or (parsed.get("value", 0.0) == 0.0
                               and "error" in parsed):
        # r04 shape: the end-of-round bench.py saw a wedged tunnel and
        # stamped an error line (value 0.0) — also a labelled gap
        row.update(status="gap",
                   failure_class=parsed.get(
                       "failure_class", _classify(parsed.get("error", ""),
                                                 rc)),
                   detail=str(parsed.get("error", ""))[:200])
        return row
    if not isinstance(parsed.get("value"), (int, float)):
        # a parse with no usable number is a gap too — "measured" rows
        # must always carry a real value (the renderer formats it)
        row.update(status="gap", failure_class=_classify(tail, rc),
                   detail="parsed payload carries no numeric value")
        return row
    row.update(status="measured",
               metric=parsed.get("metric"),
               value=parsed.get("value"),
               unit=parsed.get("unit"),
               vs_baseline=parsed.get("vs_baseline"))
    for key in ("backend", "ndofs_global", "nreps", "cg_wall_s",
                "precond", "s_step"):
        # precond/s_step (ISSUE 11) label the row so two rounds with
        # different preconditioners never render as one trend series
        if key in parsed:
            row[key] = parsed[key]
    return row


def _side_row(path: str, rnd: int, tag: str) -> dict | None:
    """Provenance sidecars (BENCH_r04_measured.json et al.): mid-round
    measurements kept because the end-of-round capture may only see a
    wedged tunnel. A `flagship` dict loads as a measured row labelled
    with its provenance; anything else is skipped (prewedge notes are
    narrative, not trend points)."""
    d, _ = _read_json(path)
    if not isinstance(d, dict) or not isinstance(d.get("flagship"), dict):
        return None
    f = d["flagship"]
    if not isinstance(f.get("value"), (int, float)) or f.get("value") == 0:
        return None
    return {"round": rnd, "source": os.path.basename(path),
            "kind": "bench", "status": "measured",
            "metric": f.get("metric"), "value": f.get("value"),
            "unit": f.get("unit"), "vs_baseline": f.get("vs_baseline"),
            "provenance": (d.get("provenance") or "")[:200] or
            f"mid-round sidecar ({tag})"}


def _multichip_row(path: str, rnd: int) -> dict:
    row = {"round": rnd, "source": os.path.basename(path),
           "kind": "multichip"}
    d, err = _read_json(path)
    if d is None:
        row.update(status="gap", failure_class="transient",
                   detail=f"unreadable artifact: {err}")
        return row
    if d.get("skipped"):
        row.update(status="skipped",
                   detail=str(d.get("tail", ""))[:120])
    elif d.get("ok"):
        row.update(status="measured", n_devices=d.get("n_devices"))
    else:
        row.update(status="gap",
                   failure_class=_classify(str(d.get("tail", "")),
                                           d.get("rc")),
                   detail=str(d.get("tail", ""))[:200])
    return row


def _journal_row(path: str, rnd: int) -> dict:
    """Fold a round's harness journal into stage completion counts +
    per-stage failure classes (the round's execution story next to its
    numbers)."""
    from ..harness.journal import replay

    row = {"round": rnd, "source": os.path.basename(path),
           "kind": "journal"}
    try:
        st = replay(path)
    except Exception as exc:
        row.update(status="gap", failure_class="transient",
                   detail=f"journal replay failed: {exc}")
        return row
    failed_classes = sorted({
        str(rec.get("failure_class", "transient"))
        for rec in st.failed.values()})
    row.update(status="measured",
               stages_completed=len(st.completed),
               stages_failed=len(st.failed),
               failed_classes=failed_classes,
               corrupt_lines=len(st.corrupt))
    return row


def load_trend(root: str = ".") -> dict:
    """Fold every round artifact under `root` into the trend table:
    ``{"rows": [...], "gaps": N, "measured": N}`` with rows sorted by
    (round, kind, source). Wedge rounds appear as labelled gaps."""
    rows: list[dict] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        name = os.path.basename(path)
        m = _ROUND_BENCH.match(name)
        if m:
            rows.append(_bench_row(path, int(m.group(1))))
            continue
        m = _ROUND_SIDE.match(name)
        if m:
            side = _side_row(path, int(m.group(1)), m.group(2))
            if side is not None:
                rows.append(side)
    for path in sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json"))):
        m = _ROUND_MULTI.match(os.path.basename(path))
        if m:
            rows.append(_multichip_row(path, int(m.group(1))))
    for path in sorted(glob.glob(os.path.join(root, "MEASURE_r*.jsonl"))):
        m = _ROUND_JOURNAL.match(os.path.basename(path))
        if m:
            rows.append(_journal_row(path, int(m.group(1))))
    rows.sort(key=lambda r: (r.get("round", 0), r.get("kind", ""),
                             r.get("source", "")))
    return {
        "rows": rows,
        "measured": sum(1 for r in rows if r.get("status") == "measured"),
        "gaps": sum(1 for r in rows if r.get("status") == "gap"),
    }


# --------------------------------------------------------------------------
# Statistical comparison: Mann-Whitney U + bootstrap CI on the median.


def _rankdata(values: np.ndarray) -> np.ndarray:
    """Average ranks (1-based) with ties sharing their mean rank — the
    standard Mann-Whitney treatment."""
    order = np.argsort(values, kind="mergesort")
    ranks = np.empty(len(values), dtype=np.float64)
    sv = values[order]
    i = 0
    while i < len(sv):
        j = i
        while j + 1 < len(sv) and sv[j + 1] == sv[i]:
            j += 1
        ranks[order[i:j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    return ranks


def mann_whitney_u(a, b) -> tuple[float, float]:
    """Two-sided Mann-Whitney U via the tie-corrected normal
    approximation (with continuity correction). Returns ``(U, p)`` with
    U the statistic of sample ``a``. Exactness is not needed here: the
    classifier pairs the p-value with an effect-size threshold and a
    bootstrap CI, and the known-answer tests pin this implementation
    against hand-computed values."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    n1, n2 = len(a), len(b)
    if n1 == 0 or n2 == 0:
        return 0.0, 1.0
    combined = np.concatenate([a, b])
    ranks = _rankdata(combined)
    r1 = float(ranks[:n1].sum())
    u1 = r1 - n1 * (n1 + 1) / 2.0
    mean = n1 * n2 / 2.0
    n = n1 + n2
    # tie correction on the variance
    _, counts = np.unique(combined, return_counts=True)
    tie_term = float(((counts ** 3 - counts).sum()))
    var = n1 * n2 / 12.0 * ((n + 1) - tie_term / (n * (n - 1))) \
        if n > 1 else 0.0
    if var <= 0:
        # all values identical: no evidence of a shift
        return u1, 1.0
    z = (u1 - mean - math.copysign(0.5, u1 - mean)) / math.sqrt(var) \
        if u1 != mean else 0.0
    p = 2.0 * (1.0 - 0.5 * (1.0 + math.erf(abs(z) / math.sqrt(2.0))))
    return u1, min(max(p, 0.0), 1.0)


def bootstrap_median_ci(values, n_boot: int = 2000, alpha: float = 0.05,
                        seed: int = 0) -> tuple[float, float]:
    """Percentile bootstrap CI on the median (deterministic seed — the
    sentinel must produce the same verdict on the same input)."""
    v = np.asarray(values, dtype=np.float64)
    if v.size == 0:
        return 0.0, 0.0
    rng = np.random.default_rng(seed)
    meds = np.median(
        v[rng.integers(0, v.size, size=(n_boot, v.size))], axis=1)
    return (float(np.quantile(meds, alpha / 2.0)),
            float(np.quantile(meds, 1.0 - alpha / 2.0)))


def classify_timing(current, baseline, *, alpha: float = 0.05,
                    effect_threshold: float = 0.05, n_boot: int = 2000,
                    lower_is_better: bool = True,
                    min_reps: int = 3) -> dict:
    """Classify current vs baseline per-rep wall distributions:
    ``improved`` / ``neutral`` / ``regressed`` / ``insufficient-data``.

    A shift must clear BOTH bars to leave neutral: Mann-Whitney p <
    alpha (the distributions genuinely differ) AND the relative median
    shift beyond `effect_threshold` (a statistically-real 1% wobble is
    not a regression worth a red build). Bootstrap CIs on both medians
    ride along as evidence. Advisory by design — wall-clock gates would
    flake on shared CI hosts; the deterministic counters are the hard
    gate (`gate_counters`)."""
    cur = np.asarray(current, dtype=np.float64)
    base = np.asarray(baseline, dtype=np.float64)
    out: dict = {
        "n_current": int(cur.size), "n_baseline": int(base.size),
        "alpha": alpha, "effect_threshold": effect_threshold,
    }
    if cur.size < min_reps or base.size < min_reps:
        out.update(classification="insufficient-data", p_value=None,
                   detail=f"need >= {min_reps} reps a side "
                          f"(have {cur.size} vs {base.size})")
        return out
    med_c, med_b = float(np.median(cur)), float(np.median(base))
    _, p = mann_whitney_u(cur, base)
    shift = (med_c - med_b) / med_b if med_b else 0.0
    out.update(
        median_current=med_c, median_baseline=med_b,
        rel_median_shift=round(shift, 6), p_value=round(p, 6),
        ci_current=[round(x, 6) for x in
                    bootstrap_median_ci(cur, n_boot=n_boot, seed=1)],
        ci_baseline=[round(x, 6) for x in
                     bootstrap_median_ci(base, n_boot=n_boot, seed=2)],
    )
    significant = p < alpha and abs(shift) > effect_threshold
    if not significant:
        out["classification"] = "neutral"
    else:
        worse = shift > 0 if lower_is_better else shift < 0
        out["classification"] = "regressed" if worse else "improved"
    return out


# --------------------------------------------------------------------------
# Deterministic-counter gating (the CI perfgate's hard contract).

#: snapshot keys where an INCREASE over baseline is a regression
#: (noise-free on CPU for a pinned workload)
LOWER_IS_BETTER_COUNTERS = (
    "compiles", "recompiles", "shed_total", "responses_failed",
    "failed", "corrupt_lines", "lost",
    # ISSUE 11: reductions per CG iteration of the sharded s-step loop
    # (trace-level, noise-free; an increase = a collective crept back)
    "sstep_reductions_per_iter",
    # ISSUE 13 fleet counters: a standby replica that COMPILES instead
    # of warming from the shared artifact store, or a lost/duplicated
    # response in the fleet's exactly-once ledger, is a regression
    "fleet_warm_replica_recompiles", "fleet_lost", "fleet_duplicates",
    # ISSUE 14 SDC counters on the deterministic injected schedule: a
    # missed injection (injected - detected) or a false positive on the
    # clean fixed-seed solves is a detector regression — both pin at 0
    "sdc_missed", "sdc_false_positives",
    # ISSUE 15 request-trace counters on the pinned serve schedule: an
    # incomplete trace is a lost phase stamp (the CI probe injects
    # exactly that), and an anomalous request on the CLEAN pinned
    # schedule (no injection, no SLO breach) is a serving regression
    "reqtrace_incomplete", "reqtrace_anomalous",
    # ISSUE 16 autotuner counters on the pinned CPU sweep: a fallback on
    # a key the sweep just tuned means the DB round-trip broke (torn
    # write, key drift, corrupt load) — pinned at 0 on the perfgate leg
    "tuning_fallbacks",
    # ISSUE 17 mixed-precision refinement on the fixed-seed perfgate
    # problem: the outer/inner iteration counts are DETERMINISTIC on
    # CPU (fixed seed, fixed ladder) — an increase means the bf16 inner
    # solve got weaker or the outer correction regressed (the exact
    # failure the CI refinement-regression probe injects)
    "refine_outer_iters", "refine_inner_iters_total",
    # ISSUE 18 overload counters on the pinned perfgate schedule: a
    # LATE deadline response (the early-refusal machinery failed) or a
    # duplicate response across a hedge pair (the claim CAS failed) is
    # the overload subsystem's worst regression — both pin at 0
    "deadline_exceeded_late", "hedge_duplicates",
)
#: snapshot keys where a DECREASE below baseline is a regression
HIGHER_IS_BETTER_COUNTERS = (
    "cache_hit_rate_requests", "responses_ok", "completed",
    # ISSUE 13: the pinned imbalance schedule must keep stealing, the
    # affinity router must keep hitting, and artifact warm loads must
    # keep happening — a drop on any of these is the fleet logic
    # silently degrading to single-device behaviour
    "fleet_steals", "fleet_affinity_hit_rate", "fleet_warm_loads",
    # ISSUE 14: every injection on the pinned schedule must keep being
    # detected — a drop here is a SUPPRESSED detector (the regression
    # probe the CI perfgate lane injects), the worst failure mode this
    # subsystem can have
    "sdc_detected",
    # ISSUE 15: every OK response on the pinned schedule must carry a
    # complete phase decomposition — a rate below the pinned 1.0 means
    # a stamp went missing somewhere in the request path
    "reqtrace_complete_rate",
    # ISSUE 16: every build on the pinned autotune leg must keep finding
    # its swept entry — a drop means lookups silently stopped consulting
    # the tuning DB (the exact regression the injected probe simulates)
    "tuning_db_hits",
    # ISSUE 17 bf16 speed ladder: the refinement solve must keep
    # reaching f64-class rtol with every hot-loop apply at bf16
    # (bf16_parity_ok = 1), and the calibrated bf16 envelopes must keep
    # their measured headroom multiple over the clean-solve floor — a
    # drop means the envelope drifted toward false positives
    "bf16_parity_ok", "bf16_envelope_headroom",
    # ISSUE 18: the pinned overload schedule must keep shedding EARLY
    # (before burning a solve), the forced straggler must keep being
    # rescued by its hedge, and the forced burn must keep engaging the
    # brownout ladder — a drop on any of these is a silently disarmed
    # overload controller (the suppressed-brownout CI probe injects
    # exactly that)
    "deadline_exceeded_early", "hedge_wins", "brownout_steps",
    # ... and, once browned out, the hysteresis band must keep stepping
    # the fleet back UP when the burn clears — a recovery count of zero
    # on the pinned schedule is a ladder stuck at reduced precision
    "brownout_recoveries",
    # ISSUE 20: warm starts on the pinned 200-step heat stream must
    # keep saving CG iterations over the cold twin — a shrink means the
    # warm-start path silently degraded to cold solves (the exact state
    # the CI BENCH_SUPPRESS_WARMSTART probe injects)
    "heat_warm_start_iters_saved",
)
#: contract booleans: baseline True -> current must stay True
CONTRACT_FLAGS = ("record_contract_ok", "trace_valid",
                  # ISSUE 16: every tuning-DB entry must carry a
                  # registered provenance label (cpu-measured /
                  # design-estimate / hardware) — an unlabeled entry is
                  # evidence without provenance
                  "tuning_labels_ok",
                  # ISSUE 20: every zoo form's device action must keep
                  # matching the CSR oracle at f64 on the fixed-seed
                  # perturbed problem — arithmetic, not timing
                  "form_parity_ok_mass", "form_parity_ok_helmholtz",
                  "form_parity_ok_varkappa", "form_parity_ok_heat")

#: counters whose VALUE is timing-derived (advisory — phase-share drift
#: never gates, per the ISSUE 15 contract) but whose PRESENCE is the
#: contract: a baseline that measured them and a current that reads
#: None means tracing silently turned off, which DOES gate.
MEASURED_ONLY_COUNTERS = ("reqtrace_queue_share_p99",)

#: counters collected as experiment INPUTS, not outcomes — they ride in
#: the snapshot as evidence (how many SDC faults the probe injected) but
#: no table direction makes sense for them. benchfem-lint's BF-CNTR002
#: cross-check consumes this registry: a counter perfgate collects must
#: be gated by a table above, specially gated (collectives_per_iter,
#: iters_to_*), a configuration label, or registered here — anything
#: else is silent drift.
ADVISORY_COUNTERS = ("sdc_injected",)


def comparable_labels(current: dict, baseline: dict) -> bool:
    """Whether two counter dicts measured the SAME solver configuration
    (precond kind + s-step factor + heat-stream shape). Absent labels
    compare as matching — a pre-ISSUE-11 baseline that never stamped a
    label cannot mismatch."""
    for key in ("precond_label", "s_step_label", "heat_warm_start_label"):
        cb, cc = baseline.get(key), current.get(key)
        if cb is not None and cc is not None and cb != cc:
            return False
    return True


def gate_counters(current: dict, baseline: dict) -> list[str]:
    """Compare two perf-snapshot counter dicts; returns the violation
    list (empty = gate passes). Only keys PRESENT IN THE BASELINE gate —
    a baseline that never measured a counter cannot fail it — and every
    violation names the counter, both values and the direction, so the
    rc-1 line is actionable on its own."""
    violations: list[str] = []
    cc = current.get("collectives_per_iter")
    cb = baseline.get("collectives_per_iter")
    if isinstance(cb, dict):
        if not isinstance(cc, dict):
            violations.append(
                "collectives_per_iter: baseline has trace-level counts "
                "but current measured none (tracer off or stamp lost)")
        else:
            for op, n in sorted(cb.items()):
                got = cc.get(op, 0)
                if got > n:
                    violations.append(
                        f"collectives_per_iter[{op}]: {got} > baseline "
                        f"{n} — a collective crept into the iteration")
            for op in sorted(set(cc) - set(cb)):
                violations.append(
                    f"collectives_per_iter[{op}]: {cc[op]} new "
                    "collective absent from baseline")
    # iterations-to-rtol counters (ISSUE 11): deterministic on CPU for a
    # fixed-seed problem, so an increase gates hard — but ONLY under
    # matching precond/s_step labels. A label mismatch is an
    # apples-to-oranges comparison (a Jacobi run "regressing" against a
    # Chebyshev baseline is a measurement-design change, not a solver
    # regression): those keys are skipped here and surfaced as a
    # labelled mismatch by gate_snapshots, never as a violation.
    labels_match = comparable_labels(current, baseline)
    for key in sorted(baseline):
        if key.startswith("iters_to_") and key in current and labels_match:
            cur_v, base_v = current[key], baseline[key]
            if cur_v is None and base_v is not None:
                violations.append(
                    f"{key}: baseline converged in {base_v} iterations "
                    "but current never crossed the rtol")
            elif (cur_v is not None and base_v is not None
                    and float(cur_v) > float(base_v)):
                violations.append(
                    f"{key}: {cur_v} > baseline {base_v} iterations — "
                    "convergence regressed on the fixed-seed problem")
    for key in LOWER_IS_BETTER_COUNTERS:
        if key in baseline and key in current:
            if baseline[key] is None:
                continue  # a baseline that measured nothing cannot gate
            if key == "sstep_reductions_per_iter" and not labels_match:
                # label-dependent counter (reductions/s): a mismatch is
                # the same apples-to-oranges gap as the iters_to_* rows
                continue
            if current[key] is None:
                violations.append(
                    f"{key}: baseline measured {baseline[key]} but "
                    "current measured nothing (tracer off or stamp "
                    "lost)")
            elif float(current[key]) > float(baseline[key]):
                violations.append(
                    f"{key}: {current[key]} > baseline {baseline[key]}")
    for key in HIGHER_IS_BETTER_COUNTERS:
        if key in baseline and key in current:
            if baseline[key] is None:
                continue  # a baseline that measured nothing cannot gate
            if current[key] is None:
                violations.append(
                    f"{key}: baseline measured {baseline[key]} but "
                    "current measured nothing (stamp lost)")
            elif float(current[key]) < float(baseline[key]) - 1e-12:
                violations.append(
                    f"{key}: {current[key]} < baseline {baseline[key]}")
    for key in CONTRACT_FLAGS:
        if baseline.get(key) is True and current.get(key) is not True:
            violations.append(f"{key}: baseline held the contract, "
                              f"current reads {current.get(key)!r}")
    for key in MEASURED_ONLY_COUNTERS:
        if key in baseline and baseline[key] is not None \
                and key in current and current[key] is None:
            violations.append(
                f"{key}: baseline measured {baseline[key]} but current "
                "measured nothing (request tracing off or stamp lost) "
                "— the value is advisory, its presence is the contract")
    return violations


#: the bench-record fields the perfgate requires on every stamped record
#: (the PR-8 attribution contract + the PR-10 convergence contract)
RECORD_REQUIRED = ("roofline", "phase_share", "timing",
                   "peak_memory_bytes")


def check_record_contract(output: dict,
                          require_convergence: bool = False) -> list[str]:
    """Schema check of one bench record's observability stamps (the
    `results_json` output dict or a journal `bench_record`)."""
    errs: list[str] = []
    for key in RECORD_REQUIRED:
        if output.get(key) is None:
            errs.append(f"bench record missing {key!r}")
    rl = output.get("roofline")
    if isinstance(rl, dict) and not rl.get("intensity_flop_per_byte", 0) > 0:
        errs.append("roofline.intensity_flop_per_byte must be > 0")
    timing = output.get("timing")
    if isinstance(timing, dict):
        if not timing.get("reps", 0) >= 1:
            errs.append("timing.reps must be >= 1")
        walls = timing.get("walls_s")
        if not (isinstance(walls, list)
                and len(walls) == timing.get("reps")):
            errs.append("timing.walls_s must carry the full per-rep "
                        "distribution (len == reps)")
    if require_convergence:
        conv = output.get("convergence")
        if not isinstance(conv, dict):
            errs.append("bench record missing the convergence block "
                        "(run with convergence capture on)")
        else:
            for key in ("iters_to_rtol", "time_to_rtol_s", "iters_run",
                        "evidence"):
                if key not in conv:
                    errs.append(f"convergence block missing {key!r}")
    return errs


# --------------------------------------------------------------------------
# Serve SLO: latency objective + multi-window burn rates.

#: (window seconds, label) — the standard fast/slow burn pair: the fast
#: window catches a fire, the slow window confirms it is not a blip
SLO_WINDOWS = ((300.0, "fast"), (3600.0, "slow"))


def burn_rates(samples, *, objective_s: float, target: float = 0.99,
               windows=SLO_WINDOWS, now: float | None = None) -> dict:
    """Fold ``(ts, latency_s, ok)`` samples into per-window error-budget
    burn rates. A sample violates the SLO when it failed OR overran the
    latency objective; burn rate = violation_rate / (1 - target) (1.0 =
    burning budget exactly as fast as the SLO allows; >1 on BOTH
    windows = alert). Flat keys so the Prometheus flattener exposes
    every value as its own series."""
    samples = [(float(t), float(lat), bool(ok)) for t, lat, ok in samples]
    if now is None:
        now = max((t for t, _, _ in samples), default=0.0)
    budget = max(1.0 - target, 1e-9)
    out: dict = {
        "objective_s": float(objective_s),
        "target": float(target),
        "samples": len(samples),
    }
    alert = bool(samples)
    for win, label in windows:
        in_win = [(t, lat, ok) for t, lat, ok in samples
                  if t >= now - win]
        n = len(in_win)
        viol = sum(1 for _, lat, ok in in_win
                   if not ok or lat > objective_s)
        rate = viol / n if n else 0.0
        burn = rate / budget
        out[f"{label}_window_s"] = float(win)
        out[f"{label}_requests"] = n
        out[f"{label}_violations"] = viol
        out[f"{label}_burn_rate"] = round(burn, 4)
        alert = alert and burn > 1.0
    out["alert"] = alert
    return out


def fold_slo(records, *, objective_s: float, target: float = 0.99,
             windows=SLO_WINDOWS, now: float | None = None) -> dict:
    """SLO state from journaled request lifecycles: every
    ``serve_response`` record is one sample (its journal ``ts`` is the
    response wall-clock instant, ``latency_s`` the enqueue->respond
    latency, ``ok`` the outcome). The offline twin of the live
    `serve.metrics.Metrics` SLO snapshot — both fold through
    `burn_rates`, so the journal replays the exact /metrics story."""
    samples = [(rec.get("ts", 0.0), rec.get("latency_s", 0.0),
                bool(rec.get("ok")))
               for rec in records if rec.get("event") == "serve_response"]
    return burn_rates(samples, objective_s=objective_s, target=target,
                      windows=windows, now=now)


# --------------------------------------------------------------------------
# Perf-snapshot gating (the obs CLI `gate` subcommand's engine).


def gate_snapshots(current: dict, baseline: dict, *,
                   alpha: float = 0.05,
                   effect_threshold: float = 0.05) -> dict:
    """Compare two perfgate snapshots (scripts/perfgate.py output):
    hard-gate the deterministic counters, advisory-classify the timing
    distributions. ``{"violations": [...], "timing": {...}, "ok": bool}``
    — ok is the COUNTER verdict only (timing never gates)."""
    violations = gate_counters(current.get("counters", {}),
                               baseline.get("counters", {}))
    violations += check_record_contract(
        current.get("bench", {}),
        require_convergence=bool(
            (baseline.get("bench") or {}).get("convergence")))
    timing: dict = {}
    for name in ("bench", "dist"):
        cur_t = ((current.get(name) or {}).get("timing") or {})
        base_t = ((baseline.get(name) or {}).get("timing") or {})
        if cur_t.get("walls_s") and base_t.get("walls_s"):
            timing[name] = classify_timing(
                cur_t["walls_s"], base_t["walls_s"], alpha=alpha,
                effect_threshold=effect_threshold)
    out = {"violations": violations, "timing": timing,
           "ok": not violations}
    # ISSUE 11: a precond/s-step label mismatch between the snapshots
    # is a LABELLED apples-to-oranges gap — the iters_to_* counters were
    # skipped by gate_counters, and the reason is surfaced here so the
    # gate output says why those rows did not compare
    if not comparable_labels(current.get("counters", {}),
                             baseline.get("counters", {})):
        out["label_mismatch"] = (
            "precond/s_step labels differ between current and baseline "
            f"(current {current.get('counters', {}).get('precond_label')!r}"
            f"/{current.get('counters', {}).get('s_step_label')!r} vs "
            f"baseline "
            f"{baseline.get('counters', {}).get('precond_label')!r}"
            f"/{baseline.get('counters', {}).get('s_step_label')!r}): "
            "iterations-to-rtol rows are an apples-to-oranges gap, not "
            "a regression, and were not gated")
    return out
