"""Hierarchical span tracer: context manager + decorator, thread-safe,
near-no-op when disabled.

Design constraints, in order:

1. **Disabled is free.** The global ``span()`` helper is the form the
   hot paths call; when tracing is off it returns a shared no-op context
   manager after ONE attribute load — no allocation, no lock, no clock
   read. A test bounds the overhead (tests/test_obs.py).
2. **Stdlib only.** The harness (which must run when jax is wedged) and
   the serve broker both import this module; jax is touched only when it
   is ALREADY imported by the process (``sys.modules`` probe), in which
   case every span also enters a ``jax.profiler.TraceAnnotation`` so
   spans line up with TPU profiler timelines (hardware-armed: on CPU the
   annotation is a cheap no-op; under an active on-device profiler
   session it labels the device timeline).
3. **Spans are evidence.** A tracer can sink every closed span into the
   harness JSONL journal (``harness.journal.Journal`` — fsynced,
   torn-tail tolerant) as ``{"event": "span", ...}`` records, and/or
   export the whole run as Chrome trace-event JSON
   (``export_chrome_trace`` — loads in Perfetto / chrome://tracing).
   ``validate_chrome_trace`` is the schema checker the obs CLI and CI
   lane run (rc 1 on violation).

Span record schema (journal + ``SpanTracer.spans()``):

    {"event": "span", "span_id": N, "parent": N|null, "name": ...,
     "thread": tid, "depth": D, "t_start_s": ..., "dur_s": ...,
     "attrs": {...}}

``t_start_s`` is seconds since the tracer's epoch (``perf_counter``
based — monotonic, immune to NTP steps).
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
from functools import wraps

__all__ = [
    "SpanTracer", "span", "traced", "tracer", "enable", "disable",
    "enabled", "export_chrome_trace", "validate_chrome_trace",
    "Lifecycle", "BenchObserver",
]


def _jax_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` when jax is already imported
    (never import jax from here — the harness must stay stdlib-only),
    else None. Failures are swallowed: profiler plumbing must never
    sink the traced computation."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return None


class _SpanCtx:
    """One open span: context manager handed out by SpanTracer.span()."""

    __slots__ = ("_tracer", "name", "attrs", "span_id", "parent", "depth",
                 "_t0", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = None
        self.parent = None
        self.depth = 0
        self._t0 = 0.0
        self._ann = None

    def __enter__(self):
        self.span_id, self.parent, self.depth, self._t0 = (
            self._tracer._open(self))
        if self._tracer.annotate:
            self._ann = _jax_annotation(self.name)
            if self._ann is not None:
                try:
                    self._ann.__enter__()
                except Exception:
                    self._ann = None
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        if exc_type is not None:
            # a span that died carries the exception class: a trace with
            # a hole in it should say why
            self.attrs = dict(self.attrs)
            self.attrs["error"] = exc_type.__name__
        self._tracer._close(self)
        return False


class _Noop:
    """The disabled-mode context manager: one shared instance, nothing
    but two empty methods. ``as s`` still works (s is the singleton)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


class SpanTracer:
    """Thread-safe hierarchical span recorder.

    Per-thread nesting via ``threading.local`` stacks (a span's parent
    is the innermost open span ON ITS OWN THREAD — the broker's
    disposable solve threads each get an independent tree); closed spans
    append to one locked list and optionally to a journal sink."""

    def __init__(self, journal=None, annotate: bool = True,
                 clock=time.perf_counter):
        self.journal = journal
        self.annotate = annotate
        self._clock = clock
        self._epoch = clock()
        self._lock = threading.Lock()
        self._spans: list[dict] = []
        self._ids = itertools.count(1)
        self._local = threading.local()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **attrs) -> _SpanCtx:
        return _SpanCtx(self, name, attrs)

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _open(self, ctx: _SpanCtx):
        st = self._stack()
        parent = st[-1].span_id if st else None
        depth = len(st)
        st.append(ctx)
        return next(self._ids), parent, depth, self._clock()

    def _close(self, ctx: _SpanCtx) -> None:
        t1 = self._clock()
        st = self._stack()
        # tolerate out-of-order exits (a generator-held span closing
        # late): pop ctx wherever it is, not blindly the top
        if ctx in st:
            st.remove(ctx)
        rec = {
            "event": "span",
            "span_id": ctx.span_id,
            "parent": ctx.parent,
            "name": ctx.name,
            "thread": threading.get_ident(),
            "depth": ctx.depth,
            "t_start_s": round(ctx._t0 - self._epoch, 9),
            "dur_s": round(t1 - ctx._t0, 9),
        }
        if ctx.attrs:
            rec["attrs"] = ctx.attrs
        with self._lock:
            self._spans.append(rec)
        if self.journal is not None:
            try:
                self.journal.append(rec)
            except Exception:
                pass  # evidence sink failure must not sink the work

    # -- reading / export --------------------------------------------------

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
        self._epoch = self._clock()

    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON object (complete 'X' events,
        microsecond timestamps). Loads in Perfetto / chrome://tracing;
        span_id/parent ride along in args so the obs CLI can rebuild
        the tree from the file alone."""
        pid = os.getpid()
        events = []
        for s in self.spans():
            args = {"span_id": s["span_id"], "parent": s["parent"],
                    "depth": s["depth"]}
            args.update(s.get("attrs", {}))
            events.append({
                "name": s["name"],
                "cat": "bench_tpu_fem",
                "ph": "X",
                "ts": round(s["t_start_s"] * 1e6, 3),
                "dur": round(s["dur_s"] * 1e6, 3),
                "pid": pid,
                "tid": s["thread"],
                "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> dict:
        obj = self.chrome_trace()
        with open(path, "w") as fh:
            json.dump(obj, fh)
        return obj


# --------------------------------------------------------------------------
# Global tracer + the near-no-op disabled fast path.

_tracer = SpanTracer()
_enabled = False


def tracer() -> SpanTracer:
    return _tracer


def enabled() -> bool:
    return _enabled


def enable(journal=None, annotate: bool = True,
           fresh: bool = False) -> SpanTracer:
    """Turn the global tracer on (optionally sinking spans into a
    harness Journal). ``fresh=True`` replaces the tracer (new epoch,
    empty span list) — what the CLI does per run."""
    global _tracer, _enabled
    if fresh:
        _tracer = SpanTracer(journal=journal, annotate=annotate)
    else:
        if journal is not None:
            _tracer.journal = journal
        _tracer.annotate = annotate
    _enabled = True
    return _tracer


def disable() -> None:
    global _enabled
    _enabled = False


def span(name: str, **attrs):
    """The form hot paths call: a real span when tracing is enabled,
    the shared no-op context manager otherwise (no allocation, no
    clock read — the disabled-overhead test bounds this)."""
    if not _enabled:
        return _NOOP
    return _tracer.span(name, **attrs)


def traced(name: str | None = None, **attrs):
    """Decorator twin of ``span``: ``@traced()`` uses the function's
    qualname."""

    def deco(fn):
        label = name or fn.__qualname__

        @wraps(fn)
        def wrapper(*a, **kw):
            if not _enabled:
                return fn(*a, **kw)
            with _tracer.span(label, **attrs):
                return fn(*a, **kw)

        return wrapper

    return deco


def export_chrome_trace(path: str) -> dict:
    return _tracer.export_chrome_trace(path)


# --------------------------------------------------------------------------
# Chrome trace-event schema validation (the obs CLI / CI lane checker).

_PHASES = frozenset("BEXibnsftPNODMVvRcCSp")


def validate_chrome_trace(obj) -> list[str]:
    """Schema check of a Chrome trace-event JSON object. Returns the
    violation list (empty = valid). Checks the shape Perfetto's legacy
    importer requires: a ``traceEvents`` array of event objects, each
    with a string ``name``, a known single-char ``ph``, numeric
    non-negative ``ts``, int ``pid``/``tid``, numeric non-negative
    ``dur`` on complete ('X') events, and object ``args`` when
    present."""
    errs: list[str] = []
    if not isinstance(obj, dict):
        return [f"top level must be an object, got {type(obj).__name__}"]
    events = obj.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents must be an array"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errs.append(f"{where}: event must be an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            errs.append(f"{where}: missing/empty string 'name'")
        ph = ev.get("ph")
        if not (isinstance(ph, str) and len(ph) == 1 and ph in _PHASES):
            errs.append(f"{where}: 'ph' must be a known phase char, "
                        f"got {ph!r}")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or isinstance(ts, bool) \
                or ts < 0:
            errs.append(f"{where}: 'ts' must be a non-negative number, "
                        f"got {ts!r}")
        for key in ("pid", "tid"):
            v = ev.get(key)
            if not isinstance(v, int) or isinstance(v, bool):
                errs.append(f"{where}: '{key}' must be an int, got {v!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or isinstance(dur, bool) \
                    or dur < 0:
                errs.append(f"{where}: complete event needs non-negative "
                            f"numeric 'dur', got {dur!r}")
        if "args" in ev and not isinstance(ev["args"], dict):
            errs.append(f"{where}: 'args' must be an object")
    return errs


# --------------------------------------------------------------------------
# Request lifecycle marks (the serve broker's enqueue→admit→solve→respond
# arithmetic, replacing ad-hoc time.monotonic() subtraction).


class Lifecycle:
    """Monotonic lifecycle marks for one request. ``mark`` records the
    FIRST occurrence of each named event (a retire/timeout race must not
    rewrite history); ``breakdown`` folds the marks into the per-stage
    deltas the response/journal carry."""

    __slots__ = ("_clock", "marks")

    #: canonical serve order; breakdown() reports deltas between the
    #: present consecutive marks
    ORDER = ("enqueue", "admit", "solve", "respond")

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self.marks: dict[str, float] = {}

    def mark(self, name: str) -> float:
        t = self._clock()
        self.marks.setdefault(name, t)
        return t

    def t(self, name: str) -> float | None:
        return self.marks.get(name)

    def since(self, name: str) -> float:
        t0 = self.marks.get(name)
        return 0.0 if t0 is None else self._clock() - t0

    def breakdown(self) -> dict[str, float]:
        """Per-stage deltas over the canonical order, e.g.
        {"queue_wait_s": admit-enqueue, "solve_s": respond-solve,
        "total_s": respond-enqueue}. Missing marks collapse their stage
        to the next present one (a shed request has only
        enqueue→respond = total)."""
        present = [(n, self.marks[n]) for n in self.ORDER
                   if n in self.marks]
        out: dict[str, float] = {}
        names = {("enqueue", "admit"): "queue_wait_s",
                 ("admit", "solve"): "batch_form_s",
                 ("solve", "respond"): "solve_s"}
        for (a, ta), (b, tb) in zip(present, present[1:]):
            out[names.get((a, b), f"{a}_to_{b}_s")] = round(tb - ta, 6)
        if len(present) >= 2:
            out["total_s"] = round(present[-1][1] - present[0][1], 6)
        return out


# --------------------------------------------------------------------------
# The benchmark drivers' integration facade: phase spans + per-rep timing
# distribution + device-memory watch, stamped into one results dict.


class BenchObserver:
    """One per benchmark run. Wraps the driver's three phases —
    ``compile`` (AOT lowering+compilation), ``transfer`` (the warm-up
    execution, which pays the one-time transfer/init costs), ``solve``
    (the timed region) — in spans that always accumulate locally (phase
    attribution is part of the record contract, tracer on or off) and
    mirror into the global tracer when it is enabled.

    ``solve_region`` additionally opens ``jax.profiler.trace`` when the
    config carries a profile_dir — the five ad-hoc profiler sites the
    drivers used to hand-roll — so device timelines and spans share one
    entry point.

    ``rep``/``elapsed`` implement the per-rep timing distribution: the
    driver may execute the timed computation ``timing_reps`` times
    (default 1 — byte-identical to the historical single measurement)
    and the stamp carries min/median/max to expose warmup and jitter;
    ``elapsed()`` (the number GDoF/s divides by) is the MEDIAN."""

    def __init__(self, cfg=None, run: str = "bench"):
        self.run = run
        self.profile_dir = getattr(cfg, "profile_dir", "") if cfg else ""
        self.timing_reps = max(int(getattr(cfg, "timing_reps", 1) or 1), 1)
        self.phase_s: dict[str, float] = {}
        self.walls: list[float] = []
        self.warmup_s: float | None = None
        from .memory import MemoryWatch

        self._mem = MemoryWatch()
        self._mem.start()

    # -- phases ------------------------------------------------------------

    class _Phase:
        __slots__ = ("obs", "name", "inner", "extra_cms", "_t0")

        def __init__(self, obs, name, extra_cms=()):
            self.obs = obs
            self.name = name
            self.inner = None
            self.extra_cms = list(extra_cms)
            self._t0 = 0.0

        def __enter__(self):
            self.inner = span(f"{self.obs.run}:{self.name}")
            self.inner.__enter__()
            for cm in self.extra_cms:
                cm.__enter__()
            if not _enabled:
                # the enabled tracer's span already annotates; with the
                # tracer off the phase still labels the device timeline
                ann = _jax_annotation(f"{self.obs.run}:{self.name}")
                if ann is not None:
                    try:
                        ann.__enter__()
                        self.extra_cms.append(ann)
                    except Exception:
                        pass
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            dt = time.perf_counter() - self._t0
            for cm in reversed(self.extra_cms):
                try:
                    cm.__exit__(*exc)
                except Exception:
                    pass
            self.inner.__exit__(*exc)
            self.obs.phase_s[self.name] = (
                self.obs.phase_s.get(self.name, 0.0) + dt)
            return False

    def phase(self, name: str) -> "_Phase":
        return self._Phase(self, name)

    def solve_region(self):
        """The timed region: span + (when cfg.profile_dir is set)
        ``jax.profiler.trace`` writing device timelines there — the
        drivers' historical profiler hook, now the same entry point as
        the span."""
        extra = []
        if self.profile_dir:
            jax = sys.modules.get("jax")
            if jax is not None:
                try:
                    extra.append(jax.profiler.trace(self.profile_dir))
                except Exception:
                    pass
        return self._Phase(self, "solve", extra_cms=extra)

    # -- per-rep timing ----------------------------------------------------

    def timed_reps(self, call):
        """THE timed region, shared by every bench/dist driver path:
        run ``call`` ``timing_reps`` times inside ``solve_region()``,
        each rep walled around call -> ``jax.block_until_ready`` ->
        a scalar fetch of the result (under the axon PJRT tunnel
        block_until_ready can return before the device work drains;
        fetching one scalar is a hard fence — 4-byte transfer, one
        slice kernel, negligible vs the timed work). Double-float
        results fence through their ``hi`` component; tuple results
        (e.g. a convergence-captured solve returning ``(x, info)``)
        fence through their first element. Returns the last rep's
        result; ``elapsed()`` is the median wall."""
        jax = sys.modules["jax"]  # the drivers imported it long ago
        out = None
        with self.solve_region():
            for _ in range(self.timing_reps):
                t0 = time.perf_counter()
                out = call()
                jax.block_until_ready(out)
                arr = out[0] if isinstance(out, (tuple, list)) else out
                arr = arr.hi if hasattr(arr, "hi") else arr
                float(arr[(0,) * arr.ndim])
                self.rep(time.perf_counter() - t0)
        return out

    def rep(self, wall_s: float) -> None:
        self.walls.append(float(wall_s))

    def elapsed(self) -> float:
        """Median of the recorded rep walls (== the single wall when
        timing_reps is 1, the default)."""
        if not self.walls:
            return 0.0
        s = sorted(self.walls)
        return s[len(s) // 2]

    # -- the stamp ---------------------------------------------------------

    def stamp(self, extra: dict) -> None:
        """Fold everything into the bench record: ``phase_s`` (absolute
        seconds), ``phase_share`` (normalised over the attributed
        phases), ``timing`` (per-rep distribution) and the memory
        telemetry (``peak_memory_bytes`` + ``memory``)."""
        total = sum(self.phase_s.values())
        extra["phase_s"] = {k: round(v, 6) for k, v in self.phase_s.items()}
        extra["phase_share"] = {
            k: round(v / total, 4) if total > 0 else 0.0
            for k, v in self.phase_s.items()
        }
        if self.warmup_s is None and "transfer" in self.phase_s:
            # the transfer phase IS the warm-up execution (it pays the
            # one-time transfer/init costs)
            self.warmup_s = self.phase_s["transfer"]
        timing = {
            "reps": len(self.walls),
            "min_s": round(min(self.walls), 6) if self.walls else 0.0,
            "median_s": round(self.elapsed(), 6),
            "max_s": round(max(self.walls), 6) if self.walls else 0.0,
            # the raw per-rep distribution (ISSUE 10): the regression
            # sentinel's Mann-Whitney/bootstrap comparison consumes the
            # full sample, not the 3-point summary
            "walls_s": [round(w, 6) for w in self.walls],
        }
        if self.warmup_s is not None:
            timing["warmup_s"] = round(self.warmup_s, 6)
        extra["timing"] = timing
        self._mem.stop()
        self._mem.stamp(extra)
