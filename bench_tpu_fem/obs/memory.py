"""Device-memory telemetry: peak / bytes-in-use around timed regions.

On hardware backends every JAX device exposes ``memory_stats()``
(PJRT allocator counters: ``bytes_in_use``, ``peak_bytes_in_use``,
``bytes_limit``); the host-CPU backend returns ``None``, so CPU runs
fall back to process RSS from ``/proc/self/status`` (``VmRSS`` current,
``VmHWM`` peak) — a HOST proxy, labelled as such, never presented as
HBM telemetry (the evidence-hygiene rule).

Stamp contract (``MemoryWatch.stamp`` / bench records):

    "peak_memory_bytes": N,          # the one headline number
    "memory": {
        "source":  "device" | "process_rss",
        "measured": "hardware" | "cpu-host",
        "bytes_in_use": N,           # at stop()
        "peak_bytes": N,             # max over devices (device source)
        "baseline_bytes": N,         # at start()
        "devices": K,                # device source only
        "bytes_limit": N,            # device source, when reported
    }

``memory_summary()`` is the serve ``/metrics`` form of the same sample
(no start/stop pair — a point-in-time reading).
"""

from __future__ import annotations

import sys

__all__ = ["device_memory_stats", "process_rss", "sample",
           "memory_summary", "MemoryWatch"]


def device_memory_stats() -> dict | None:
    """Aggregate ``memory_stats()`` over the visible devices (sum of
    bytes_in_use, MAX of per-device peaks — the binding constraint is
    the fullest chip, not the fleet total). None when jax is not
    imported or no device reports stats (host CPU)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        devs = jax.devices()
    except Exception:
        return None
    per = []
    for d in devs:
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        if st:
            per.append(st)
    if not per:
        return None
    out = {
        "bytes_in_use": sum(int(s.get("bytes_in_use", 0)) for s in per),
        "peak_bytes": max(int(s.get("peak_bytes_in_use",
                                    s.get("bytes_in_use", 0)))
                          for s in per),
        "devices": len(per),
    }
    limits = [int(s["bytes_limit"]) for s in per if "bytes_limit" in s]
    if limits:
        out["bytes_limit"] = min(limits)
    return out


def process_rss() -> tuple[int, int]:
    """(current RSS, peak RSS) in bytes. /proc on Linux; the resource
    module's ru_maxrss (KiB on Linux) as the portable peak fallback."""
    rss = hwm = 0
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    rss = int(line.split()[1]) * 1024
                elif line.startswith("VmHWM:"):
                    hwm = int(line.split()[1]) * 1024
    except OSError:
        pass
    if hwm == 0:
        try:
            import resource

            hwm = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            pass
    return rss, max(hwm, rss)


def sample() -> dict:
    """One labelled memory sample: device allocator stats when any
    device reports them, else the process-RSS host proxy."""
    dev = device_memory_stats()
    if dev is not None:
        return {"source": "device", "measured": "hardware", **dev}
    rss, hwm = process_rss()
    return {"source": "process_rss", "measured": "cpu-host",
            "bytes_in_use": rss, "peak_bytes": hwm}


def memory_summary() -> dict:
    """The serve /metrics form: a point-in-time sample (same labels)."""
    return sample()


class MemoryWatch:
    """Before/after sampling around a timed region. ``stamp`` folds the
    pair into the bench-record contract (peak_memory_bytes + the
    labelled detail dict)."""

    def __init__(self):
        self.baseline: dict | None = None
        self.final: dict | None = None

    def start(self) -> "MemoryWatch":
        self.baseline = sample()
        return self

    def stop(self) -> dict:
        self.final = sample()
        return self.final

    def stamp(self, extra: dict) -> None:
        if self.final is None:
            self.stop()
        fin = dict(self.final)
        if self.baseline is not None:
            fin["baseline_bytes"] = self.baseline.get("bytes_in_use", 0)
        extra["peak_memory_bytes"] = int(fin.get("peak_bytes", 0))
        extra["memory"] = fin
