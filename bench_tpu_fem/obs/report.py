"""The obs CLI: render a journal + exported Chrome trace into a report.

    python -m bench_tpu_fem.obs [--journal MEASURE_r06.jsonl]
                                [--trace trace.json]
                                [--json] [--validate-only]

Sections (text mode):

  * trace validation — schema check of the Chrome trace-event JSON
    (``obs.trace.validate_chrome_trace``); ANY violation exits rc 1
    (the CI obs lane's contract);
  * span tree — the hierarchical spans from the journal's ``span``
    records and/or the trace file (parent links ride in ``args``);
  * timer table — spans aggregated by name (count / total / max), the
    count/total/max shape derived from spans (also the renderer for
    the legacy `%`-phase Timer registry — utils.timing);
  * roofline table — every journal record carrying a ``roofline`` stamp
    (``bench_record`` events, weak-scaling rows), one line per record
    with intensity / fraction / bound / evidence.

``--json`` emits the folded report as one JSON object instead.
"""

from __future__ import annotations

import argparse
import json
import sys

from .trace import validate_chrome_trace

_TREE_MAX = 400  # spans rendered in the tree before truncation


def load_trace(path: str) -> tuple[dict | None, list[str]]:
    """(trace object, violations). An unreadable/unparseable file is a
    violation, not an exception — the CLI must exit 1, not crash."""
    try:
        with open(path) as fh:
            obj = json.load(fh)
    except OSError as exc:
        return None, [f"cannot read trace file: {exc}"]
    except json.JSONDecodeError as exc:
        return None, [f"trace file is not valid JSON: {exc}"]
    return obj, validate_chrome_trace(obj)


def spans_from_trace(obj: dict) -> list[dict]:
    """Span records recovered from an exported Chrome trace (our export
    carries span_id/parent/depth in args; foreign traces fall back to
    flat spans)."""
    out = []
    for ev in obj.get("traceEvents", []):
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        out.append({
            "event": "span",
            "span_id": args.get("span_id"),
            "parent": args.get("parent"),
            "name": ev.get("name", "?"),
            "thread": ev.get("tid", 0),
            "depth": args.get("depth", 0),
            "t_start_s": float(ev.get("ts", 0)) / 1e6,
            "dur_s": float(ev.get("dur", 0)) / 1e6,
            "attrs": {k: v for k, v in args.items()
                      if k not in ("span_id", "parent", "depth")},
        })
    return out


def spans_from_journal(records: list[dict]) -> list[dict]:
    return [r for r in records if r.get("event") == "span"]


def render_span_tree(spans: list[dict]) -> str:
    """Indent spans by their parent links, per thread, children in
    start order. Spans without a resolvable parent root their thread's
    tree."""
    if not spans:
        return "(no spans)"
    by_id = {s.get("span_id"): s for s in spans
             if s.get("span_id") is not None}
    children: dict = {}
    roots: list[dict] = []
    for s in sorted(spans, key=lambda r: r.get("t_start_s", 0.0)):
        pid = s.get("parent")
        if pid is not None and pid in by_id:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    lines: list[str] = []

    def emit(s, indent):
        if len(lines) >= _TREE_MAX:
            return
        attrs = s.get("attrs") or {}
        extra = (" " + json.dumps(attrs, sort_keys=True)) if attrs else ""
        lines.append(f"{'  ' * indent}{s.get('name', '?'):<{max(44 - 2 * indent, 8)}s}"
                     f" {s.get('dur_s', 0.0) * 1e3:10.3f} ms{extra}")
        for c in children.get(s.get("span_id"), []):
            emit(c, indent + 1)

    threads = sorted({s.get("thread", 0) for s in roots})
    for tid in threads:
        lines.append(f"-- thread {tid}")
        for s in roots:
            if s.get("thread", 0) == tid:
                emit(s, 1)
    if len(lines) >= _TREE_MAX:
        lines.append(f"... truncated at {_TREE_MAX} lines "
                     f"({len(spans)} spans)")
    return "\n".join(lines)


def timer_table(spans: list[dict]) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for s in spans:
        t = out.setdefault(s.get("name", "?"),
                           {"count": 0, "total": 0.0, "max": 0.0})
        t["count"] += 1
        t["total"] += float(s.get("dur_s", 0.0))
        t["max"] = max(t["max"], float(s.get("dur_s", 0.0)))
    return out


def render_timer_rows(timers: dict[str, dict]) -> str:
    """Render a {name: {count, total, max}} aggregate as the timer
    table. ONE renderer for both sources: span-derived aggregates
    (``timer_table``) and the legacy `%`-phase Timer registry
    (``utils.timing.aggregated_timings`` — the CLI's reference-parity
    banner, whose deprecated ``timer_report`` shim this replaced)."""
    rows = [f"{'Timer':<44s} {'count':>6s} {'total (s)':>12s} {'max (s)':>12s}"]
    for name, t in sorted(timers.items()):
        rows.append(f"{name:<44s} {t['count']:>6d} {t['total']:>12.4f} "
                    f"{t['max']:>12.4f}")
    return "\n".join(rows)


def render_timer_table(spans: list[dict]) -> str:
    return render_timer_rows(timer_table(spans))


def roofline_rows(records: list[dict]) -> list[dict]:
    rows = []
    for r in records:
        rl = r.get("roofline") or (r.get("result") or {}).get("roofline")
        if not isinstance(rl, dict):
            continue
        rows.append({
            "event": r.get("event", "?"),
            "form": rl.get("form"),
            "precision": rl.get("precision"),
            "degree": rl.get("degree"),
            "gdof_s": rl.get("achieved_gdof_s"),
            "intensity": rl.get("intensity_flop_per_byte"),
            "fraction": rl.get("fraction_of_ceiling"),
            "bound": rl.get("bound"),
            "evidence": rl.get("evidence"),
        })
    return rows


def render_roofline_table(rows: list[dict]) -> str:
    if not rows:
        return "(no roofline-stamped records)"
    out = [f"{'event':<16s} {'form':<18s} {'prec':<5s} {'deg':>3s} "
           f"{'GDoF/s':>10s} {'flop/B':>8s} {'frac':>6s} {'bound':<9s} evidence"]
    for r in rows:
        out.append(
            f"{str(r['event']):<16s} {str(r['form']):<18s} "
            f"{str(r['precision']):<5s} {str(r['degree']):>3s} "
            f"{(r['gdof_s'] if r['gdof_s'] is not None else 0):>10.4f} "
            f"{(r['intensity'] or 0):>8.2f} {(r['fraction'] or 0):>6.3f} "
            f"{str(r['bound']):<9s} {str(r['evidence'])[:48]}")
    return "\n".join(out)


def build_report(journal_path: str | None, trace_path: str | None) -> dict:
    """Fold journal + trace into one report dict (the --json payload):
    violations, spans (deduped: journal wins over trace replicas of the
    same span_id), timer table, roofline rows, serve/bench counts."""
    violations: list[str] = []
    spans: list[dict] = []
    records: list[dict] = []
    if trace_path:
        obj, violations = load_trace(trace_path)
        if obj is not None and not violations:
            spans.extend(spans_from_trace(obj))
    if journal_path:
        from ..harness.journal import read_records

        records, corrupt = read_records(journal_path)
        jspans = spans_from_journal(records)
        if jspans:
            seen = {s.get("span_id") for s in jspans
                    if s.get("span_id") is not None}
            spans = [s for s in spans
                     if s.get("span_id") not in seen] + jspans
        if corrupt:
            violations.append(
                f"journal: {len(corrupt)} corrupt line(s) (torn tail "
                "excluded) — retained for audit")
    return {
        "violations": violations,
        "valid": not violations,
        "n_spans": len(spans),
        "spans": spans,
        "timers": timer_table(spans),
        "roofline": roofline_rows(records),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.obs",
        description="Render a journal + Chrome trace into a report "
                    "(span tree, timer table, roofline table); "
                    "validates the trace JSON (rc 1 on violations).")
    p.add_argument("--journal", default="",
                   help="harness JSONL journal (span/bench records)")
    p.add_argument("--trace", default="",
                   help="exported Chrome trace-event JSON")
    p.add_argument("--json", action="store_true",
                   help="emit the folded report as one JSON object")
    p.add_argument("--validate-only", action="store_true",
                   help="only run the trace schema check")
    args = p.parse_args(argv)
    if not args.journal and not args.trace:
        p.error("need --journal and/or --trace")
    rep = build_report(args.journal or None, args.trace or None)
    if args.json:
        print(json.dumps(rep))
    else:
        if args.trace:
            status = ("OK" if rep["valid"]
                      else f"INVALID ({len(rep['violations'])})")
            print(f"== trace validation: {status}")
            for v in rep["violations"][:20]:
                print(f"   {v}")
        if not args.validate_only:
            print("== span tree")
            print(render_span_tree(rep["spans"]))
            print("== timer table (from spans)")
            print(render_timer_table(rep["spans"]))
            print("== roofline table")
            print(render_roofline_table(rep["roofline"]))
    return 0 if rep["valid"] else 1


if __name__ == "__main__":
    sys.exit(main())
