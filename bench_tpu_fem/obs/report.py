"""The obs CLI: render a journal + exported Chrome trace into a report.

    python -m bench_tpu_fem.obs [--journal MEASURE_r06.jsonl]
                                [--trace trace.json]
                                [--json] [--validate-only]
    python -m bench_tpu_fem.obs trend [--root .] [--journal X.jsonl]
                                [--slo-objective S] [--json]
    python -m bench_tpu_fem.obs gate --current cur.json
                                --baseline base.json [--json]
    python -m bench_tpu_fem.obs reqtrace --journal serve.jsonl
                                [--out trace.json] [--json]

Sections (text mode, default command):

  * trace validation — schema check of the Chrome trace-event JSON
    (``obs.trace.validate_chrome_trace``); ANY violation exits rc 1
    (the CI obs lane's contract);
  * span tree — the hierarchical spans from the journal's ``span``
    records and/or the trace file (parent links ride in ``args``);
  * timer table — spans aggregated by name (count / total / max), the
    count/total/max shape derived from spans (also the renderer for
    the legacy `%`-phase Timer registry — utils.timing);
  * roofline table — every journal record carrying a ``roofline`` stamp
    (``bench_record`` events, weak-scaling rows), one line per record
    with intensity / fraction / bound / evidence.

``trend`` (ISSUE 10) renders the regression sentinel's view: the
per-round trajectory from the committed BENCH_r*/MULTICHIP_r*/
MEASURE_r* artifacts (wedge rounds as LABELLED GAPS, never zeros),
convergence curves + time-to-rtol ladders from a journal's
``bench_record`` events, and SLO burn-rate state from a serve journal's
request lifecycles. rc 0 — the trend is a report, not a gate.

``gate`` compares two perfgate snapshots (scripts/perfgate.py):
deterministic counters gate HARD (rc 1 on any violation), the
Mann-Whitney/bootstrap timing classification prints as advisory.

``--json`` emits the folded report as one JSON object instead.
"""

from __future__ import annotations

import argparse
import json
import sys

from .trace import validate_chrome_trace

_TREE_MAX = 400  # spans rendered in the tree before truncation


def load_trace(path: str) -> tuple[dict | None, list[str]]:
    """(trace object, violations). An unreadable/unparseable file is a
    violation, not an exception — the CLI must exit 1, not crash."""
    try:
        with open(path) as fh:
            obj = json.load(fh)
    except OSError as exc:
        return None, [f"cannot read trace file: {exc}"]
    except json.JSONDecodeError as exc:
        return None, [f"trace file is not valid JSON: {exc}"]
    return obj, validate_chrome_trace(obj)


def spans_from_trace(obj: dict) -> list[dict]:
    """Span records recovered from an exported Chrome trace (our export
    carries span_id/parent/depth in args; foreign traces fall back to
    flat spans)."""
    out = []
    for ev in obj.get("traceEvents", []):
        if not isinstance(ev, dict) or ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        out.append({
            "event": "span",
            "span_id": args.get("span_id"),
            "parent": args.get("parent"),
            "name": ev.get("name", "?"),
            "thread": ev.get("tid", 0),
            "depth": args.get("depth", 0),
            "t_start_s": float(ev.get("ts", 0)) / 1e6,
            "dur_s": float(ev.get("dur", 0)) / 1e6,
            "attrs": {k: v for k, v in args.items()
                      if k not in ("span_id", "parent", "depth")},
        })
    return out


def spans_from_journal(records: list[dict]) -> list[dict]:
    return [r for r in records if r.get("event") == "span"]


def render_span_tree(spans: list[dict]) -> str:
    """Indent spans by their parent links, per thread, children in
    start order. Spans without a resolvable parent root their thread's
    tree."""
    if not spans:
        return "(no spans)"
    by_id = {s.get("span_id"): s for s in spans
             if s.get("span_id") is not None}
    children: dict = {}
    roots: list[dict] = []
    for s in sorted(spans, key=lambda r: r.get("t_start_s", 0.0)):
        pid = s.get("parent")
        if pid is not None and pid in by_id:
            children.setdefault(pid, []).append(s)
        else:
            roots.append(s)
    lines: list[str] = []

    def emit(s, indent):
        if len(lines) >= _TREE_MAX:
            return
        attrs = s.get("attrs") or {}
        extra = (" " + json.dumps(attrs, sort_keys=True)) if attrs else ""
        lines.append(f"{'  ' * indent}{s.get('name', '?'):<{max(44 - 2 * indent, 8)}s}"
                     f" {s.get('dur_s', 0.0) * 1e3:10.3f} ms{extra}")
        for c in children.get(s.get("span_id"), []):
            emit(c, indent + 1)

    threads = sorted({s.get("thread", 0) for s in roots})
    for tid in threads:
        lines.append(f"-- thread {tid}")
        for s in roots:
            if s.get("thread", 0) == tid:
                emit(s, 1)
    if len(lines) >= _TREE_MAX:
        lines.append(f"... truncated at {_TREE_MAX} lines "
                     f"({len(spans)} spans)")
    return "\n".join(lines)


def timer_table(spans: list[dict]) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for s in spans:
        t = out.setdefault(s.get("name", "?"),
                           {"count": 0, "total": 0.0, "max": 0.0})
        t["count"] += 1
        t["total"] += float(s.get("dur_s", 0.0))
        t["max"] = max(t["max"], float(s.get("dur_s", 0.0)))
    return out


def render_timer_rows(timers: dict[str, dict]) -> str:
    """Render a {name: {count, total, max}} aggregate as the timer
    table. ONE renderer for both sources: span-derived aggregates
    (``timer_table``) and the legacy `%`-phase Timer registry
    (``utils.timing.aggregated_timings`` — the CLI's reference-parity
    banner, whose deprecated ``timer_report`` shim this replaced)."""
    rows = [f"{'Timer':<44s} {'count':>6s} {'total (s)':>12s} {'max (s)':>12s}"]
    for name, t in sorted(timers.items()):
        rows.append(f"{name:<44s} {t['count']:>6d} {t['total']:>12.4f} "
                    f"{t['max']:>12.4f}")
    return "\n".join(rows)


def render_timer_table(spans: list[dict]) -> str:
    return render_timer_rows(timer_table(spans))


def roofline_rows(records: list[dict]) -> list[dict]:
    rows = []
    for r in records:
        rl = r.get("roofline") or (r.get("result") or {}).get("roofline")
        if not isinstance(rl, dict):
            continue
        rows.append({
            "event": r.get("event", "?"),
            "form": rl.get("form"),
            "precision": rl.get("precision"),
            "degree": rl.get("degree"),
            "gdof_s": rl.get("achieved_gdof_s"),
            "intensity": rl.get("intensity_flop_per_byte"),
            "fraction": rl.get("fraction_of_ceiling"),
            "bound": rl.get("bound"),
            "evidence": rl.get("evidence"),
        })
    return rows


def render_roofline_table(rows: list[dict]) -> str:
    if not rows:
        return "(no roofline-stamped records)"
    out = [f"{'event':<16s} {'form':<18s} {'prec':<5s} {'deg':>3s} "
           f"{'GDoF/s':>10s} {'flop/B':>8s} {'frac':>6s} {'bound':<9s} evidence"]
    for r in rows:
        out.append(
            f"{str(r['event']):<16s} {str(r['form']):<18s} "
            f"{str(r['precision']):<5s} {str(r['degree']):>3s} "
            f"{(r['gdof_s'] if r['gdof_s'] is not None else 0):>10.4f} "
            f"{(r['intensity'] or 0):>8.2f} {(r['fraction'] or 0):>6.3f} "
            f"{str(r['bound']):<9s} {str(r['evidence'])[:48]}")
    return "\n".join(out)


def build_report(journal_path: str | None, trace_path: str | None) -> dict:
    """Fold journal + trace into one report dict (the --json payload):
    violations, spans (deduped: journal wins over trace replicas of the
    same span_id), timer table, roofline rows, serve/bench counts."""
    violations: list[str] = []
    spans: list[dict] = []
    records: list[dict] = []
    if trace_path:
        obj, violations = load_trace(trace_path)
        if obj is not None and not violations:
            spans.extend(spans_from_trace(obj))
    if journal_path:
        from ..harness.journal import read_records

        records, corrupt = read_records(journal_path)
        jspans = spans_from_journal(records)
        if jspans:
            seen = {s.get("span_id") for s in jspans
                    if s.get("span_id") is not None}
            spans = [s for s in spans
                     if s.get("span_id") not in seen] + jspans
        if corrupt:
            violations.append(
                f"journal: {len(corrupt)} corrupt line(s) (torn tail "
                "excluded) — retained for audit")
    return {
        "violations": violations,
        "valid": not violations,
        "n_spans": len(spans),
        "spans": spans,
        "timers": timer_table(spans),
        "roofline": roofline_rows(records),
    }


# --------------------------------------------------------------------------
# `trend`: the regression sentinel's rendered view (ISSUE 10).


def render_trend_rows(rows: list[dict]) -> str:
    """Round trajectory, one line per artifact. Gaps render as
    `GAP [failure_class]` — never as zeros (the satellite contract)."""
    if not rows:
        return "(no round artifacts found)"
    out = [f"{'round':<6s} {'kind':<10s} {'status':<9s} "
           f"{'value':>10s}  detail"]
    for r in rows:
        rnd = f"r{r.get('round', 0):02d}"
        if r.get("status") == "measured":
            if r.get("kind") == "bench":
                # the loader guarantees a numeric value on measured
                # bench rows; `or 0.0` defends the renderer against
                # hand-built rows anyway (a crash here would take the
                # whole trend down for one odd artifact)
                val = f"{r.get('value') or 0.0:10.4f}"
                detail = (f"{r.get('unit', '')}"
                          f" vs_baseline {r.get('vs_baseline')}")
                if r.get("provenance"):
                    detail += f" [{r['source']}]"
            elif r.get("kind") == "journal":
                val = f"{r.get('stages_completed', 0):>10d}"
                detail = (f"stages ok, {r.get('stages_failed', 0)} failed"
                          + (f" {r.get('failed_classes')}"
                             if r.get("failed_classes") else ""))
            else:
                val = f"{'ok':>10s}"
                detail = f"n_devices {r.get('n_devices')}"
        elif r.get("status") == "gap":
            val = f"{'GAP':>10s}"
            detail = (f"[{r.get('failure_class', '?')}] "
                      f"{r.get('detail', '')}")
        else:
            val = f"{r.get('status', '?'):>10s}"
            detail = r.get("detail", "")
        out.append(f"{rnd:<6s} {r.get('kind', '?'):<10s} "
                   f"{r.get('status', '?'):<9s} {val}  {detail[:90]}")
    return "\n".join(out)


_SPARK = " .:-=+*#%@"


def render_convergence(records: list[dict]) -> str:
    """Convergence curves from a journal's `bench_record` events: the
    decimated rel-residual curve as a -log10 sparkline plus the
    iters/time-to-rtol ladder."""
    lines: list[str] = []
    for rec in records:
        conv = rec.get("convergence") or (
            (rec.get("result") or {}).get("convergence"))
        if not isinstance(conv, dict):
            continue
        curve = conv.get("curve") or []
        spark = ""
        for _, rel in curve:
            if rel <= 0:
                depth = 8.0
            else:
                import math as _math

                depth = min(max(-_math.log10(max(rel, 1e-16)), 0.0), 8.0)
            spark += _SPARK[min(int(depth / 8.0 * (len(_SPARK) - 1)),
                                len(_SPARK) - 1)]
        # ISSUE 11: the precond/s-step labels render ON the row —
        # preconditioned and bare curves must never read as one series
        label = ""
        if conv.get("precond", "none") != "none":
            label += f" precond={conv['precond']}"
        if int(conv.get("s_step", 1) or 1) > 1:
            label += f" s_step={conv['s_step']}"
        lines.append(
            f"{rec.get('event', '?')}: iters_run="
            f"{conv.get('iters_run')} final_rel="
            f"{conv.get('final_rel_residual') or 0.0:.3e} "
            f"stag_max={conv.get('stagnation_max_run')} "
            f"restarts={conv.get('restarts')}{label} "
            f"[{conv.get('evidence')}]")
        lines.append(f"  |{spark}|  (depth: ' '=1e0 .. '@'=1e-8)")
        iters = conv.get("iters_to_rtol") or {}
        times = conv.get("time_to_rtol_s") or {}
        lines.append("  " + "  ".join(
            f"{k}:{iters[k]} it/"
            + (f"{times.get(k):.3g}s" if times.get(k) is not None
               else "-")
            if iters[k] is not None else f"{k}:-"
            for k in sorted(iters)))
    return "\n".join(lines) if lines else "(no convergence-stamped records)"


def fold_tuning(records: list[dict]) -> dict:
    """Tuning-evidence rollup for the trend view: every journaled
    `tuning` stamp (engines.autotune — drivers and serve builds write
    one per executable-key lookup), counted by source and provenance
    label. A journal with no stamps folds to a LABELLED GAP, never a
    zero row (the wedge-honesty rule)."""
    stamps: list[dict] = []
    for r in records:
        for holder in (r, r.get("result") or {}, r.get("extra") or {},
                       (r.get("result") or {}).get("extra") or {}):
            t = holder.get("tuning") if isinstance(holder, dict) else None
            if isinstance(t, dict) and t.get("source"):
                stamps.append(t)
                break
    if not stamps:
        return {"status": "gap", "reason": "no-tuning-stamps"}
    by_label: dict[str, int] = {}
    by_reason: dict[str, int] = {}
    hits = 0
    for t in stamps:
        by_label[t.get("label") or "?"] = (
            by_label.get(t.get("label") or "?", 0) + 1)
        if t.get("source") == "db":
            hits += 1
        else:
            reason = t.get("fallback_reason") or "?"
            by_reason[reason] = by_reason.get(reason, 0) + 1
    return {"status": "ok", "stamps": len(stamps), "db_hits": hits,
            "fallbacks": len(stamps) - hits, "labels": by_label,
            "fallback_reasons": by_reason}


def render_tuning(fold: dict) -> str:
    """The trend's tuning table: db-hit/fallback split, provenance
    labels, and the registered fallback reasons with counts."""
    lines = [f"stamps {fold['stamps']}: {fold['db_hits']} tuned (db), "
             f"{fold['fallbacks']} defaults (reason recorded)"]
    lines.append("  labels: " + ", ".join(
        f"{k}={v}" for k, v in sorted(fold["labels"].items())))
    for reason, n in sorted(fold["fallback_reasons"].items()):
        lines.append(f"  fallback x{n}: {reason[:80]}")
    return "\n".join(lines)


def render_slo(slo: dict) -> str:
    lines = [f"objective {slo.get('objective_s')}s @ target "
             f"{slo.get('target')} over {slo.get('samples')} responses"]
    for label in ("fast", "slow"):
        if f"{label}_burn_rate" in slo:
            lines.append(
                f"  {label:<5s} window {slo[f'{label}_window_s']:>7.0f}s: "
                f"{slo[f'{label}_violations']}/{slo[f'{label}_requests']} "
                f"violations, burn rate {slo[f'{label}_burn_rate']}")
    lines.append(f"  alert: {slo.get('alert')}")
    return "\n".join(lines)


def trend_main(argv=None) -> int:
    from .regress import fold_slo, load_trend

    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.obs trend",
        description="Regression-sentinel trend view: round trajectory "
                    "(wedge rounds as labelled gaps), convergence "
                    "curves, serve SLO state.")
    p.add_argument("--root", default=".",
                   help="directory holding BENCH_r*/MULTICHIP_r*/"
                        "MEASURE_r* artifacts")
    p.add_argument("--journal", default="",
                   help="journal with bench_record convergence stamps "
                        "and/or serve_response lifecycles")
    p.add_argument("--slo-objective", type=float, default=1.0,
                   help="latency objective (seconds) for the SLO fold")
    p.add_argument("--slo-target", type=float, default=0.99,
                   help="SLO availability target (fraction)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    trend = load_trend(args.root)
    records: list[dict] = []
    slo = None
    if args.journal:
        from ..harness.journal import read_records

        records, corrupt = read_records(args.journal)
        if corrupt:
            trend["corrupt_journal_lines"] = len(corrupt)
        if any(r.get("event") == "serve_response" for r in records):
            slo = fold_slo(records, objective_s=args.slo_objective,
                           target=args.slo_target)
    reqtrace = None
    if records and any(r.get("event") == "serve_response"
                       for r in records):
        from .reqtrace import fold_reqtrace

        reqtrace = fold_reqtrace(records)
    tuning = fold_tuning(records) if records else None
    if args.json:
        out = dict(trend)
        out["slo"] = slo
        out["reqtrace"] = reqtrace
        out["tuning"] = tuning
        # same lookup as render_convergence: the block may ride at top
        # level or nested under `result` (weak-scaling-style records)
        out["convergence_records"] = [
            r for r in records
            if isinstance(r.get("convergence"), dict)
            or isinstance((r.get("result") or {}).get("convergence"),
                          dict)]
        print(json.dumps(out))
        return 0
    print("== round trajectory")
    print(render_trend_rows(trend["rows"]))
    print(f"   ({trend['measured']} measured, {trend['gaps']} labelled "
          "gaps — a wedged round is a gap, never a zero)")
    if args.journal:
        print("== convergence")
        print(render_convergence(records))
        if slo is not None:
            print("== serve SLO")
            print(render_slo(slo))
        if reqtrace is not None:
            # serve phase shares next to the SLO block (ISSUE 15): a
            # journal that predates phase stamps renders as a LABELLED
            # GAP, never as a zero row (the PR 10 wedge-honesty rule)
            from .reqtrace import render_phases

            print("== serve phases")
            if reqtrace.get("status") == "ok":
                print(render_phases(reqtrace))
            else:
                print(f"   GAP [{reqtrace.get('reason', '?')}] — "
                      "phase shares unavailable for this journal; a "
                      "missing stamp is a gap, never a zero")
        # autotuner evidence (ISSUE 16): tuned-vs-default split with
        # provenance labels; a journal that never stamped tuning
        # renders as a LABELLED GAP, never a zero table
        print("== tuning")
        if tuning and tuning.get("status") == "ok":
            print(render_tuning(tuning))
        else:
            reason = (tuning or {}).get("reason", "no-tuning-stamps")
            print(f"   GAP [{reason}] — no tuning stamps in this "
                  "journal; a missing stamp is a gap, never a zero")
    return 0


def gate_main(argv=None) -> int:
    from .regress import gate_snapshots

    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.obs gate",
        description="Perfgate: deterministic counters gate hard (rc 1 "
                    "on violation); Mann-Whitney/bootstrap timing "
                    "classification is advisory.")
    p.add_argument("--current", required=True,
                   help="perfgate snapshot JSON (scripts/perfgate.py)")
    p.add_argument("--baseline", required=True,
                   help="pinned baseline snapshot JSON")
    p.add_argument("--alpha", type=float, default=0.05)
    p.add_argument("--effect-threshold", type=float, default=0.05)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    with open(args.current) as fh:
        current = json.load(fh)
    with open(args.baseline) as fh:
        baseline = json.load(fh)
    verdict = gate_snapshots(current, baseline, alpha=args.alpha,
                             effect_threshold=args.effect_threshold)
    if args.json:
        print(json.dumps(verdict))
    else:
        status = "OK" if verdict["ok"] else (
            f"REGRESSED ({len(verdict['violations'])} violations)")
        print(f"== perfgate: {status}")
        for v in verdict["violations"]:
            print(f"   GATE {v}")
        if verdict.get("label_mismatch"):
            # ISSUE 11: an apples-to-oranges precond/s-step comparison
            # is a LABELLED gap, never a silent pass or a violation
            print(f"   LABEL GAP {verdict['label_mismatch']}")
        for name, t in sorted(verdict["timing"].items()):
            print(f"   timing[{name}] (advisory): "
                  f"{t.get('classification')} "
                  f"(p={t.get('p_value')}, shift="
                  f"{t.get('rel_median_shift')})")
    return 0 if verdict["ok"] else 1


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # subcommand dispatch (trend/gate); everything else is the original
    # render/validate CLI
    if argv and argv[0] == "trend":
        return trend_main(argv[1:])
    if argv and argv[0] == "gate":
        return gate_main(argv[1:])
    if argv and argv[0] == "reqtrace":
        from .reqtrace import reqtrace_main

        return reqtrace_main(argv[1:])
    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.obs",
        description="Render a journal + Chrome trace into a report "
                    "(span tree, timer table, roofline table); "
                    "validates the trace JSON (rc 1 on violations).")
    p.add_argument("--journal", default="",
                   help="harness JSONL journal (span/bench records)")
    p.add_argument("--trace", default="",
                   help="exported Chrome trace-event JSON")
    p.add_argument("--json", action="store_true",
                   help="emit the folded report as one JSON object")
    p.add_argument("--validate-only", action="store_true",
                   help="only run the trace schema check")
    args = p.parse_args(argv)
    if not args.journal and not args.trace:
        p.error("need --journal and/or --trace")
    rep = build_report(args.journal or None, args.trace or None)
    if args.json:
        print(json.dumps(rep))
    else:
        if args.trace:
            status = ("OK" if rep["valid"]
                      else f"INVALID ({len(rep['violations'])})")
            print(f"== trace validation: {status}")
            for v in rep["violations"][:20]:
                print(f"   {v}")
        if not args.validate_only:
            print("== span tree")
            print(render_span_tree(rep["spans"]))
            print("== timer table (from spans)")
            print(render_timer_table(rep["spans"]))
            print("== roofline table")
            print(render_roofline_table(rep["roofline"]))
    return 0 if rep["valid"] else 1


if __name__ == "__main__":
    sys.exit(main())
