import sys

from .report import main

sys.exit(main())
