"""Analytic FLOP / HBM-byte cost model per engine form — the roofline
stamp every bench record carries.

The models are DESIGN ESTIMATES derived from the kernel structure (the
same discipline as the VMEM plans in ``analysis.budgets``): counted from
the shipped kernels' dataflow, never fitted to a measurement. Two
anchors keep them honest, cross-checked by tests/test_obs.py on degrees
{1, 3, 6}:

* the df32 kron model REPLICATES ``scripts/roofline_df.py`` exactly
  (``df_flops_per_dof`` / ``DF_BYTES_PER_DOF`` — the committed round-5
  roofline analysis); a drift between the two is a test failure, not a
  silent fork;
* the folded G-stream traffic model ties to
  ``ops.pallas_laplacian.stream_cell_bytes``'s VMEM accounting: the
  kernel double-buffers the stream, so its VMEM term must equal exactly
  2x the per-cell HBM bytes modelled here.

Machine peaks: measured on-chip numbers from the newest
``ROOFLINE_DF_r*.json`` at the repo root when one exists (the armed
``scripts/roofline_df.py`` writes it), else labelled design estimates —
a roofline *fraction* stamped from estimated peaks says so in its
``evidence`` field (ROADMAP item 8: numbers carry provenance).
"""

from __future__ import annotations

import glob
import json
import os

__all__ = [
    "df_flops_per_dof", "DF_BYTES_PER_DOF", "folded_cell_flops",
    "folded_g_stream_bytes_per_cell", "cost_model", "machine_peaks",
    "roofline_stamp", "refine_byte_model",
]

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# --- machine peaks ---------------------------------------------------------

# Design-estimate peaks (v5e class) used until scripts/roofline_df.py has
# measured the chip: HBM stream bandwidth from the datasheet ballpark,
# VPU f32 rate from the kernel family's arithmetic port (the CG engines
# are VPU elementwise/banded work, not MXU matmuls).
DESIGN_PEAKS = {"hbm_gbps": 819.0, "vpu_f32_gflops": 4000.0}


def machine_peaks(root: str = _ROOT) -> dict:
    """{"hbm_gbps", "vpu_f32_gflops", "evidence"} — measured numbers
    from the newest ROOFLINE_DF_r*.json when present (evidence names the
    file), else the design table (evidence: "design-estimate")."""
    candidates = sorted(glob.glob(os.path.join(root, "ROOFLINE_DF_r*.json")))
    for path in reversed(candidates):
        try:
            with open(path) as fh:
                data = json.load(fh)
            hbm = float(data["hbm_gbps"])
            vpu = float(data["vpu_f32_gflops"])
        except (OSError, KeyError, TypeError, ValueError,
                json.JSONDecodeError):
            continue
        return {"hbm_gbps": hbm, "vpu_f32_gflops": vpu,
                "evidence": f"measured:{os.path.basename(path)}"}
    return {**DESIGN_PEAKS, "evidence": "design-estimate"}


# --- df32 kron model (MUST equal scripts/roofline_df.py) -------------------


def df_flops_per_dof(P: int) -> int:
    """Analytic VPU flop count per dof of one fused df CG iteration
    (ops.kron_cg_df kernel + the XLA update pass) — the committed
    round-5 model, replicated verbatim from scripts/roofline_df.py
    (tests cross-check the two stay equal): per banded term ~28 flops
    (_eft_term 13 + renorm 6 + accumulation 9); z stage 2 contractions,
    y stage 3, x stage 2, each (2P+1) terms; + per-stage splits/renorms,
    p-update, Dirichlet/dot, and the XLA-side x/r update + <r,r>."""
    nb = 2 * P + 1
    per_term = 28
    contractions = (2 + 3 + 2) * nb * per_term
    stage_overhead = 3 * 10 + 2 * 12
    p_update = 40
    emit = 6 + 4 + 30
    xla_update = 30 + 30 + 35
    return contractions + stage_overhead + p_update + emit + xla_update


# kernel: r,p_prev in + p,y out, hi+lo each = 8 streams; XLA update:
# read x,p,r,y + write x,r (hi+lo) = 12 streams + ~2 effective re-reads
# for the <r,r> tree (scripts/roofline_df.py's DF_BYTES_PER_DOF).
DF_BYTES_PER_DOF = 8 * 4 + 14 * 4

# --- f32 kron model --------------------------------------------------------

# One fused f32 CG iteration: the same 7 banded contraction passes
# (z 2 + y 3 + x 2) at 2 flops per (2P+1)-term, plus the in-kernel
# p-update/emit (~6) and the XLA-side x/r axpys + <r,r> (~8).
_KRON_F32_STAGE_PASSES = 7
_KRON_F32_TAIL = 14


def kron_f32_flops_per_dof(P: int, use_cg: bool = True) -> int:
    nb = 2 * P + 1
    apply_f = _KRON_F32_STAGE_PASSES * nb * 2
    return apply_f + (_KRON_F32_TAIL if use_cg else 2)


# f32 CG streams per dof: kernel r,p_prev in + p,y out (4) + XLA update
# read x,p,r,y write x,r + ~1 dot re-read (7) = 11 f32 streams.
KRON_F32_CG_STREAMS = 11
# action: x in, y out through the ring (+ the barriered carry) = 3.
KRON_F32_ACTION_STREAMS = 3
# the unfused 3-stage composition materialises two stage temporaries
# per apply (in+out each): +4 streams over the fused ring.
UNFUSED_EXTRA_STREAMS = 4

# --- folded (general-geometry) model ---------------------------------------

# G·grad contraction at each quadrature point: 6 symmetric G entries
# against 3 gradient components -> 9 multiplies + 6 adds.
_G_DOT_GRAD_FLOPS = 15
# corner mode recomputes the geometry chain (Jacobian, adjugate,
# det/div) in-kernel per quadrature point instead of streaming G.
GEOM_CHAIN_FLOPS_PER_QP = 85


def folded_cell_flops(P: int, nq: int, geom: str = "g") -> int:
    """Per-cell flops of one sum-factorized general-geometry apply:
    three gradient components forward (dofs -> quad) and three transpose
    components back, each a 3-stage 1D tensor contraction chain, plus
    the per-quadrature-point G·grad (and, in corner mode, the in-kernel
    geometry chain)."""
    p1 = P + 1
    chain = 2 * (nq * p1**3 + nq**2 * p1**2 + nq**3 * p1)
    qp = _G_DOT_GRAD_FLOPS + (GEOM_CHAIN_FLOPS_PER_QP
                              if geom == "corner" else 0)
    return 6 * chain + qp * nq**3


def folded_g_stream_bytes_per_cell(nq: int, itemsize: int = 4) -> int:
    """Per-cell HBM traffic of the streamed geometry tensor: 6 symmetric
    G entries per quadrature point, read once per apply. The kernel
    double-buffers this stream, so ops.pallas_laplacian's VMEM model
    carries exactly 2x this value (12*nq^3 of its 19*nq^3 term) — the
    cross-check tests/test_obs.py pins."""
    return 6 * nq**3 * itemsize


# corner mode streams 24 corner coordinates + ~1 mask value per cell
# instead of G.
FOLDED_CORNER_VALUES_PER_CELL = 25


# --- the per-form cost model -----------------------------------------------

# double-float pairs double every stream; emulated f64 doubles width and
# multiplies VPU work (software f64 on a chip without f64 units — the
# measured ~70x throughput ratio proxied as a flop multiplier, a crude
# but labelled estimate).
_EMULATED_F64_FLOP_MULT = 70


def cost_model(*, family: str, degree: int, qmode: int = 1,
               precision: str = "f32", geom: str = "uniform",
               form: str = "unfused", use_cg: bool = True) -> dict:
    """FLOPs and HBM bytes per dof per CG iteration (or per apply when
    ``use_cg`` is false) for one engine family:

    ``kron``   uniform-mesh Kronecker/banded engines (ops.kron_cg[,_df])
    ``folded`` general-geometry folded Pallas kernels (ops.folded*)
    ``xla``    the einsum fallback (folded dataflow + gather/scatter
               overhead — the crudest model here, labelled so)

    Returns {"flops_per_dof", "hbm_bytes_per_dof",
    "intensity_flop_per_byte", "model"}.
    """
    P = max(int(degree), 1)
    nq = P + 1 + int(qmode)
    fused = form not in ("unfused", "unknown")
    note = "analytic-design-estimate"

    if family == "kron":
        if precision == "df32":
            flops = df_flops_per_dof(P)
            hbm = DF_BYTES_PER_DOF
            if not use_cg:
                flops = int(flops * 0.6)  # no XLA x/r update tail
                hbm = 8 * 4
        else:
            # bf16 (ISSUE 17): IDENTICAL stream counts to f32 at
            # itemsize 2 — exactly half the f32 HBM bytes (the pinned
            # cross-check in tests/test_bf16.py); f32-accumulate keeps
            # the flop count unchanged.
            itemsize = (8 if precision == "f64"
                        else (2 if precision == "bf16" else 4))
            flops = kron_f32_flops_per_dof(P, use_cg)
            if precision == "f64":
                flops *= _EMULATED_F64_FLOP_MULT
                note = ("analytic-design-estimate (emulated-f64 flop "
                        "multiplier is a measured-ratio proxy)")
            streams = (KRON_F32_CG_STREAMS if use_cg
                       else KRON_F32_ACTION_STREAMS)
            if use_cg and not fused:
                streams += UNFUSED_EXTRA_STREAMS
            hbm = streams * itemsize
    else:  # folded / xla: general geometry
        itemsize = (8 if precision == "f64"
                    else (2 if precision == "bf16" else 4))
        dof_per_cell = P**3  # interior share: (nP+1)^3 / n^3 -> P^3
        gmode = "corner" if geom == "corner" else "g"
        cell_f = folded_cell_flops(P, nq, gmode)
        if precision == "df32":
            cell_f *= 13  # per-op EFT cost (la.df64 _eft_term)
            itemsize = 8  # hi+lo pair per value
        elif precision == "f64":
            cell_f *= _EMULATED_F64_FLOP_MULT
            note = ("analytic-design-estimate (emulated-f64 flop "
                    "multiplier is a measured-ratio proxy)")
        # bf16 streams the geometry factors at half width too; every
        # other precision keeps the committed 4-byte G stream.
        g_item = 2 if precision == "bf16" else 4
        geom_stream = (FOLDED_CORNER_VALUES_PER_CELL * g_item
                       if gmode == "corner"
                       else folded_g_stream_bytes_per_cell(
                           nq, itemsize=g_item))
        vec_streams = (KRON_F32_CG_STREAMS if use_cg
                       else KRON_F32_ACTION_STREAMS)
        if use_cg and not fused:
            vec_streams += UNFUSED_EXTRA_STREAMS
        flops = cell_f // dof_per_cell + (_KRON_F32_TAIL if use_cg else 0)
        hbm = geom_stream // dof_per_cell + vec_streams * itemsize
        if family == "xla":
            # einsum path adds dofmap gather/scatter traffic per apply
            hbm += 2 * 4 + 2 * itemsize
            note = ("analytic-design-estimate (xla einsum path: folded "
                    "dataflow + gather/scatter overhead, crudest model)")
    if precision == "bf16":
        note += ("; bf16-stream operands at itemsize 2 (half the f32 "
                 "bytes), f32-accumulate flops unchanged, int32 "
                 "gather traffic stays 4-byte")
    flops = int(flops)
    hbm = int(hbm)
    return {
        "flops_per_dof": flops,
        "hbm_bytes_per_dof": hbm,
        "intensity_flop_per_byte": round(flops / hbm, 4) if hbm else 0.0,
        "model": note,
    }


def refine_byte_model(*, family: str, degree: int, qmode: int = 1,
                      geom: str = "uniform", inner_iters_total: int,
                      outer_iters: int,
                      outer_precision: str = "f64") -> dict:
    """Combined HBM byte model of ONE mixed-precision refinement solve
    (ISSUE 17): ``inner_iters_total`` bf16 CG iterations plus one
    hi-precision residual apply per outer check. Per-dof bytes split by
    precision so the evidence stamp shows where the bandwidth bill
    lands (the bf16 fraction is the ladder's whole point); labelled
    design-estimate like every cost_model number."""
    inner = cost_model(family=family, degree=degree, qmode=qmode,
                       precision="bf16", geom=geom, use_cg=True)
    outer = cost_model(family=family, degree=degree, qmode=qmode,
                       precision=outer_precision, geom=geom,
                       use_cg=False)
    inner_b = inner["hbm_bytes_per_dof"] * int(inner_iters_total)
    outer_b = outer["hbm_bytes_per_dof"] * int(outer_iters)
    total = inner_b + outer_b
    return {
        "inner_precision": "bf16",
        "outer_precision": outer_precision,
        "inner_iters_total": int(inner_iters_total),
        "outer_applies": int(outer_iters),
        "inner_hbm_bytes_per_dof": int(inner_b),
        "outer_hbm_bytes_per_dof": int(outer_b),
        "total_hbm_bytes_per_dof": int(total),
        "bf16_byte_fraction": round(inner_b / total, 4) if total else 0.0,
        "model": "analytic-design-estimate (refinement inner+outer split)",
    }


_FAMILY_BY_BACKEND = {"kron": "kron", "pallas": "folded", "xla": "xla"}


def roofline_stamp(extra: dict, *, degree: int, qmode: int,
                   precision: str, backend: str, geom: str,
                   use_cg: bool, gdof_s: float,
                   platform: str | None = None,
                   root: str = _ROOT) -> dict:
    """Stamp ``extra["roofline"]`` from a finished benchmark: the cost
    model for the form that RAN (``cg_engine_form``), achieved GB/s and
    GFLOP/s at the measured GDoF/s, both roofline ceilings and the
    achieved-vs-ceiling fraction, with the peaks' provenance. A CPU run
    stamps its fraction against the TPU peaks with an explicit evidence
    label (the fraction then reads "where this config would sit on the
    chip's roofline at this rate" — a design aid, never a hardware
    claim)."""
    family = _FAMILY_BY_BACKEND.get(backend or "", "xla")
    form = (extra.get("cg_engine_form")
            or extra.get("engine_form", "unfused"))
    model = cost_model(family=family, degree=degree, qmode=qmode,
                       precision=precision, geom=geom, form=form,
                       use_cg=use_cg)
    peaks = machine_peaks(root)
    hbm_pd = model["hbm_bytes_per_dof"]
    flops_pd = model["flops_per_dof"]
    ceil_bw = peaks["hbm_gbps"] / hbm_pd if hbm_pd else 0.0
    ceil_fl = (peaks["vpu_f32_gflops"] / flops_pd) if flops_pd else 0.0
    ceiling = min(ceil_bw, ceil_fl) if ceil_bw and ceil_fl else (
        ceil_bw or ceil_fl)
    on_tpu = (platform or "") == "tpu"
    rl = {
        "family": family,
        "form": form,
        "precision": precision,
        "degree": int(degree),
        **model,
        "achieved_gdof_s": round(float(gdof_s), 4),
        "achieved_gbps": round(float(gdof_s) * hbm_pd, 2),
        "achieved_gflops": round(float(gdof_s) * flops_pd, 2),
        "ceiling_bandwidth_gdof_s": round(ceil_bw, 3),
        "ceiling_compute_gdof_s": round(ceil_fl, 3),
        "ceiling_gdof_s": round(ceiling, 3),
        "fraction_of_ceiling": (round(float(gdof_s) / ceiling, 4)
                                if ceiling else 0.0),
        "bound": "bandwidth" if ceil_bw <= ceil_fl else "compute",
        "peaks": peaks,
        "evidence": ("hardware" if on_tpu else
                     "cpu-measured (vs chip design peaks — placement on "
                     "the roofline, not a throughput claim)"),
    }
    pc = precond_cost(extra, model, precision)
    if pc is not None:
        rl["precond_cost"] = pc
    extra["roofline"] = rl
    return rl


def precond_cost(extra: dict, model: dict,
                 precision: str = "f32") -> dict | None:
    """ISSUE 11: fold the preconditioner's per-iteration cost into the
    roofline stamp, from the driver's own `precond` block. The model is
    analytic and honest about what it counts: `applies_per_iter` extra
    operator applies (each at the running form's per-dof cost — an
    upper bound for p-MG, whose coarse-level applies are cheaper) plus
    one diagonal stream read + one vector write per precond apply
    (Jacobi's whole cost; also the Chebyshev/pmg smoother scaling
    streams), so `iter_cost_multiplier` says how much more HBM traffic
    one PCG iteration moves than a bare iteration — the number
    time-to-rtol must beat via iteration count."""
    pre = extra.get("precond")
    if not isinstance(pre, dict) or pre.get("kind", "none") == "none":
        return None
    applies = int(pre.get("applies_per_iter", 0))
    itemsize = 4 if precision == "f32" else 8  # df32 pairs / f64
    base_pd = float(model.get("hbm_bytes_per_dof", 0.0)) or 1.0
    # per precond apply: read dinv + read r + write z
    stream_pd = 3.0 * itemsize * max(applies, 1)
    extra_pd = applies * base_pd + stream_pd
    return {
        "kind": pre.get("kind"),
        "setup_applies": int(pre.get("setup_applies", 0)),
        "setup_s": pre.get("setup_s"),
        "applies_per_iter": applies,
        "extra_hbm_bytes_per_dof": round(extra_pd, 2),
        "iter_cost_multiplier": round(1.0 + extra_pd / base_pd, 3),
        "evidence": "analytic-design-estimate (time_to_rtol_s "
                    "adjudicates the measured trade)",
    }
