"""Request-scoped tracing for the serve fleet (ISSUE 15).

PRs 8/10/12/13 made the *solver* and the *history* observable; a served
request still answered with one opaque ``latency_s``. This module is the
per-request lifecycle tracer the broker/fleet thread through every
request when ``reqtrace`` is armed:

``ReqTrace``
    Monotonic-clock phase accounting for ONE request. The whole request
    lifetime is partitioned into consecutive half-open intervals by
    ``cut(phase)`` calls — each cut attributes the time since the
    previous cut to a named phase — so the phase decomposition sums to
    the total BY CONSTRUCTION (``queue_s + compile_s + solve_s +
    audit_s + retry_s + respond_s ≈ latency_s``; the only slack is
    per-phase rounding). Instant events (steal-moved, SDC rollback,
    quarantine drain) and routing/occupancy metadata ride along for the
    exemplar/timeline render.

``ExemplarRing``
    Bounded tail-based sampling: full traces are kept for the K slowest
    requests plus EVERY anomalous one (SLO violation, retry, sdc,
    breakdown, steal-moved, quarantine-drained); normal traffic is
    head-sampled by a deterministic id hash (``head_sampled`` — crc32,
    never RNG: the same incident samples the same requests on every
    replay).

``fold_reqtrace``
    The offline twin of the live ``/metrics`` ``reqtrace`` block: folds
    a serve journal's ``serve_response`` phase stamps back into the
    same per-phase percentiles through the SAME ``summarize_phases``
    fold, so live and replay cannot diverge (the PR 10 ``fold_slo``
    discipline). A journal whose responses predate phase stamps is a
    LABELLED GAP (``status: "gap"``), never a zero row.

``python -m bench_tpu_fem.obs reqtrace``
    Renders a serve journal as a Perfetto-loadable Chrome trace: one
    process per device, one track per lane, request slices with their
    phase children laid end to end, and steal / spill / quarantine /
    rollback / retry as instant events. The emitted JSON passes
    ``obs.trace.validate_chrome_trace`` (rc 1 otherwise).

Tracing OFF is the pre-PR code path: no ``ReqTrace`` is allocated, no
``serve_phase`` record is journaled, no extra fsync or host sync runs.
Phase data on the wire is ADDITIVE fields on the existing WAL records,
so ``serve.recovery.fold_outstanding`` / ``verify_exactly_once`` replay
mixed old/new-schema journals unchanged (pinned by test).
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib

__all__ = [
    "PHASES", "REQUIRED_OK", "ReqTrace", "ExemplarRing", "head_sampled",
    "summarize_phases", "fold_reqtrace", "render_phases",
    "journal_to_chrome", "reqtrace_main", "merge_exemplars",
]

#: canonical phase order: every request's lifetime partitions into these
#: (phases that never happened simply carry no segment / read 0.0)
PHASES = ("queue", "compile", "solve", "audit", "retry", "respond")

#: phases every OK response must have cut at least once — the
#: trace-complete contract (audit/retry are conditional by design)
REQUIRED_OK = ("queue", "compile", "solve", "respond")

#: fault-injection seam for the CI incomplete-trace probe: a phase name
#: here makes every cut() of that phase silently vanish (the time is
#: lost, the segment unrecorded) — exactly the shape of a lost stamp.
#: Settable via the REQTRACE_DROP_PHASE env var (read at import) or by
#: tests monkeypatching the module attribute.
DROP_PHASE: str | None = os.environ.get("REQTRACE_DROP_PHASE") or None


class ReqTrace:
    """Phase accounting for one request. Thread-safe (cuts come from
    the submit thread, the batching worker and the disposable solve
    thread); ``cut`` is rare (~10 per request) so the lock is noise.

    ``t0`` should be the broker's enqueue instant so the trace total
    and the journaled ``latency_s`` share one origin."""

    __slots__ = ("req_id", "_clock", "t0", "_last", "phase_s",
                 "timeline", "events", "meta", "retries", "_lock")

    def __init__(self, req_id: str, t0: float | None = None,
                 clock=time.monotonic):
        self.req_id = req_id
        self._clock = clock
        self.t0 = clock() if t0 is None else float(t0)
        self._last = self.t0
        self.phase_s: dict[str, float] = {}
        self.timeline: list = []  # (phase, start_rel_s, dur_s)
        self.events: list[dict] = []
        self.meta: dict = {}
        self.retries = 0
        self._lock = threading.Lock()

    def cut(self, phase: str, now: float | None = None) -> float:
        """Close the open interval, attributing it to ``phase``.
        Returns the cut instant. Honors the DROP_PHASE probe seam."""
        if now is None:
            now = self._clock()
        with self._lock:
            dt = max(now - self._last, 0.0)
            if phase == DROP_PHASE:
                # the injected lost stamp: time vanishes, segment
                # unrecorded — breaks BOTH the phase sum and the
                # completeness contract, which is the point
                self._last = now
                return now
            self.phase_s[phase] = self.phase_s.get(phase, 0.0) + dt
            self.timeline.append((phase, round(self._last - self.t0, 6),
                                  round(dt, 6)))
            self._last = now
        return now

    def event(self, name: str, **attrs) -> None:
        """Instant event (steal_moved, sdc_rollback, quarantine_drained,
        retry ...) at now, relative to the trace origin."""
        rec = {"name": name, "t_s": round(self._clock() - self.t0, 6)}
        if attrs:
            rec.update(attrs)
        with self._lock:
            self.events.append(rec)

    def annotate(self, **attrs) -> None:
        """Merge metadata under the trace lock. Meta writers span
        threads (the fleet's submit thread stamps the route cause while
        the lane worker may already be answering), and ``export`` copies
        the dict under the same lock — unlocked writers could race that
        copy."""
        with self._lock:
            self.meta.update(attrs)

    def annotate_default(self, key: str, value) -> None:
        """setdefault twin of ``annotate`` (first writer wins)."""
        with self._lock:
            self.meta.setdefault(key, value)

    def total_s(self) -> float:
        with self._lock:
            return self._last - self.t0

    def decomposition(self) -> dict[str, float]:
        """``{"<phase>_s": seconds, ..., "total_s": seconds}`` over the
        phases that recorded at least one segment. Sums to total within
        per-phase rounding (6 decimals)."""
        with self._lock:
            out = {f"{p}_s": round(self.phase_s[p], 6)
                   for p in PHASES if p in self.phase_s}
            out["total_s"] = round(self._last - self.t0, 6)
        return out

    def complete(self) -> bool:
        """Every REQUIRED_OK phase recorded a segment — the contract an
        OK response's trace must meet (a dropped stamp fails it)."""
        with self._lock:
            return all(p in self.phase_s for p in REQUIRED_OK)

    def export(self) -> dict:
        """Full exemplar payload (bounded: the timeline is one entry
        per cut, the events one per instant)."""
        with self._lock:
            return {
                "id": self.req_id,
                "phase_s": {f"{p}_s": round(self.phase_s[p], 6)
                            for p in PHASES if p in self.phase_s},
                "timeline": [list(seg) for seg in self.timeline[:64]],
                "events": list(self.events[:64]),
                "meta": dict(self.meta),
                "retries": self.retries,
                "complete": all(p in self.phase_s for p in REQUIRED_OK),
            }


def head_sampled(req_id: str, every: int) -> bool:
    """Deterministic head-sampling verdict for NORMAL traffic: true for
    ~1/every of the id space, by crc32 — never RNG, so a replayed
    incident samples exactly the same requests."""
    if every <= 1:
        return True
    return zlib.crc32(str(req_id).encode()) % every == 0


class ExemplarRing:
    """Bounded full-trace retention: the K slowest requests (min-heap
    by latency), EVERY anomalous request (bounded deque — tail-based
    sampling), and a head-sampled slice of normal traffic. Anomaly
    counts are monotone (evidence); the ring is a window (control)."""

    def __init__(self, k_slowest: int = 8, max_anomalous: int = 64,
                 max_sampled: int = 32, head_every: int = 16):
        from collections import deque

        self.k_slowest = max(int(k_slowest), 1)
        self.head_every = max(int(head_every), 1)
        self._slow: list = []  # (latency, seq, exemplar) min-heap
        self._anom = deque(maxlen=max(int(max_anomalous), 1))
        self._sampled = deque(maxlen=max(int(max_sampled), 1))
        self._seq = 0
        self.counts: dict[str, int] = {}
        self._lock = threading.Lock()

    def offer(self, exemplar: dict) -> None:
        import heapq

        lat = float(exemplar.get("latency_s", 0.0))
        tags = list(exemplar.get("anomalies") or [])
        with self._lock:
            self._seq += 1
            item = (lat, self._seq, exemplar)
            if len(self._slow) < self.k_slowest:
                heapq.heappush(self._slow, item)
            elif lat > self._slow[0][0]:
                heapq.heapreplace(self._slow, item)
            if tags:
                for t in tags:
                    self.counts[t] = self.counts.get(t, 0) + 1
                self._anom.append(exemplar)
            elif head_sampled(exemplar.get("id", ""), self.head_every):
                self._sampled.append(exemplar)

    def anomalous_total(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def snapshot(self, limit: int = 16) -> dict:
        """Bounded JSON-able view for /metrics (the Prometheus
        flattener skips the lists; the counts ride separately)."""
        with self._lock:
            slowest = [e for _, _, e in
                       sorted(self._slow, reverse=True)][:limit]
            return {"slowest": slowest,
                    "anomalous": list(self._anom)[-limit:],
                    "sampled": list(self._sampled)[-limit:]}


def merge_exemplars(snapshots: list[dict], k_slowest: int = 8,
                    limit: int = 16) -> dict:
    """Fold per-lane ring snapshots into one fleet view (slowest
    re-ranked across lanes; anomalous/sampled concatenated, bounded)."""
    slowest: list[dict] = []
    anomalous: list[dict] = []
    sampled: list[dict] = []
    for snap in snapshots:
        slowest.extend(snap.get("slowest") or [])
        anomalous.extend(snap.get("anomalous") or [])
        sampled.extend(snap.get("sampled") or [])
    slowest.sort(key=lambda e: -float(
        (e.get("phase_s") or {}).get("total_s",
                                     e.get("latency_s", 0.0)) or 0.0))
    return {"slowest": slowest[:min(k_slowest, limit)],
            "anomalous": anomalous[-limit:],
            "sampled": sampled[-limit:]}


# --------------------------------------------------------------------------
# The shared phase fold: live /metrics and the journal replay both run
# EXACTLY this, which is what makes the parity test structural.


def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return float(sorted_vals[i])


def summarize_phases(samples) -> dict:
    """Fold ``(latency_s, {"<phase>_s": v, ...})`` samples into
    per-phase percentiles, aggregate shares and the queue-share of the
    p99 tail. A phase a response never entered contributes 0.0 to that
    response's column (the decomposition stays a partition)."""
    samples = [(float(lat), dict(ph or {})) for lat, ph in samples]
    n = len(samples)
    out: dict = {"n": n, "phases": {}, "queue_share_p99": None}
    if not n:
        return out
    lats = sorted(lat for lat, _ in samples)
    total = sum(lats)
    for p in PHASES:
        vals = sorted(ph.get(f"{p}_s", 0.0) for _, ph in samples)
        psum = sum(vals)
        out["phases"][p] = {
            "p50_s": round(_pct(vals, 0.50), 6),
            "p95_s": round(_pct(vals, 0.95), 6),
            "p99_s": round(_pct(vals, 0.99), 6),
            "share": round(psum / total, 4) if total > 0 else 0.0,
        }
    thr = _pct(lats, 0.99)
    tail = [(lat, ph) for lat, ph in samples if lat >= thr]
    tail_total = sum(lat for lat, _ in tail)
    if tail and tail_total > 0:
        out["queue_share_p99"] = round(
            sum(ph.get("queue_s", 0.0) for _, ph in tail) / tail_total, 4)
    return out


def fold_reqtrace(path_or_records) -> dict:
    """Fold a serve journal back into the live ``reqtrace`` block's
    story: per-phase percentiles (``summarize_phases`` — the same fold
    /metrics runs), trace-complete counts, anomaly counts and the
    queue-share of the p99 tail.

    Old journals (responses without ``phase_s``) return ``status:
    "gap"`` with a reason — a round that predates phase stamps is a
    labelled gap, never a zero row (the PR 10 wedge-honesty rule)."""
    if isinstance(path_or_records, str):
        from ..harness.journal import read_records

        records, _ = read_records(path_or_records)
    else:
        records = list(path_or_records)
    responses = [r for r in records if r.get("event") == "serve_response"]
    traced = [r for r in responses if isinstance(r.get("phase_s"), dict)]
    if not responses:
        return {"status": "empty", "responses": 0, "traced": 0,
                "reason": "journal carries no serve_response records"}
    if not traced:
        return {"status": "gap", "responses": len(responses), "traced": 0,
                "reason": "no phase stamps (reqtrace off or journal "
                          "predates request tracing)"}
    samples = [(float(r.get("latency_s", 0.0)), r["phase_s"])
               for r in traced]
    out = {"status": "ok", "responses": len(responses),
           "traced": len(traced)}
    out.update(summarize_phases(samples))
    complete = sum(1 for r in traced
                   if r.get("ok") and r.get("trace_complete") is True)
    incomplete = sum(1 for r in traced
                     if r.get("ok") and r.get("trace_complete") is False)
    out["trace_complete"] = complete
    out["trace_incomplete"] = incomplete
    judged = complete + incomplete
    out["trace_complete_rate"] = (round(complete / judged, 6)
                                  if judged else None)
    anomalies: dict[str, int] = {}
    for r in traced:
        for tag in r.get("anomalies") or []:
            anomalies[tag] = anomalies.get(tag, 0) + 1
    out["anomalies"] = anomalies
    return out


def render_phases(fold: dict) -> str:
    """Text table of a fold (or the live reqtrace block): p50/p95/p99
    + aggregate share per phase, completeness and anomaly tail."""
    phases = fold.get("phases") or {}
    if not phases:
        return "(no phase-stamped responses)"
    lines = [f"{'phase':<9s} {'p50 (s)':>10s} {'p95 (s)':>10s} "
             f"{'p99 (s)':>10s} {'share':>7s}"]
    for p in PHASES:
        row = phases.get(p)
        if row is None:
            continue
        lines.append(f"{p:<9s} {row['p50_s']:>10.4f} {row['p95_s']:>10.4f} "
                     f"{row['p99_s']:>10.4f} {row['share']:>7.3f}")
    comp = fold.get("trace_complete", 0)
    incomp = fold.get("trace_incomplete", 0)
    rate = fold.get("trace_complete_rate")
    qshare = fold.get("queue_share_p99")
    lines.append(
        f"trace-complete {comp}/{comp + incomp}"
        + (f" (rate {rate})" if rate is not None else "")
        + (f"  queue-share of p99 tail {qshare}" if qshare is not None
           else "")
        + f"  anomalies {fold.get('anomalies') or {}}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# Perfetto timeline render: the serve journal's WAL records already
# carry wall-clock `ts` per event, and every traced response carries its
# phase decomposition — enough to rebuild the whole incident as one
# Chrome trace without ever journaling full timelines per request.

#: instant-event names per journal record kind (src/device picks track)
_INSTANT_EVENTS = {
    "fleet_steal": "steal",
    "fleet_spill": "spill",
    "fleet_quarantine": "quarantine",
    "fleet_readmit": "readmit",
    "fleet_selftest": "selftest",
    "serve_sdc": "sdc",
    "serve_retry": "retry",
    # overload resilience (ISSUE 18): hedge-pair lifecycle + brownout
    # ladder transitions — journaled only when the controllers are
    # armed, rendered as control-plane instants like the rest
    "serve_hedge_fired": "hedge_fired",
    "serve_hedge_won": "hedge_won",
    "serve_hedge_cancelled": "hedge_cancelled",
    "fleet_brownout": "brownout_step",
}


def journal_to_chrome(records) -> dict:
    """Chrome trace-event JSON from a serve journal: one process per
    device label, one thread per lane, request slices ('X') with phase
    children laid end to end, control-plane records as instants."""
    records = [r for r in records if isinstance(r, dict)]
    responses = [r for r in records
                 if r.get("event") == "serve_response"
                 and isinstance(r.get("phase_s"), dict)
                 and isinstance(r.get("ts"), (int, float))]
    lane_of: dict[str, int] = {}
    dev_of: dict[str, str] = {}
    devices: list[str] = []

    def _dev(label) -> str:
        label = str(label or "serve")
        if label not in devices:
            devices.append(label)
        return label

    for r in records:
        if r.get("event") in ("serve_admit", "serve_retire") and r.get("id"):
            lane_of.setdefault(str(r["id"]), int(r.get("lane", 0)))
            dev_of.setdefault(str(r["id"]), _dev(r.get("device")))
    ts_floor = [float(r["ts"]) for r in records
                if isinstance(r.get("ts"), (int, float))]
    ts_floor += [float(r["ts"]) - float(r.get("latency_s", 0.0))
                 for r in responses]
    epoch = min(ts_floor) if ts_floor else 0.0
    events: list[dict] = []
    for r in responses:
        rid = str(r.get("id"))
        lat = float(r.get("latency_s", 0.0))
        dev = _dev(r.get("device") or dev_of.get(rid))
        pid = devices.index(dev) + 1
        tid = lane_of.get(rid, 0)
        t0 = float(r["ts"]) - epoch - lat
        args = {"id": rid, "ok": bool(r.get("ok")),
                "cache": r.get("cache"),
                "trace_complete": r.get("trace_complete")}
        if r.get("failure_class"):
            args["failure_class"] = r["failure_class"]
        if r.get("anomalies"):
            args["anomalies"] = r["anomalies"]
        if r.get("degraded"):
            # brownout provenance (ISSUE 18): the slice says which
            # precision rung actually computed the answer
            args["degraded"] = r["degraded"]
        events.append({"name": f"req {rid}", "cat": "reqtrace",
                       "ph": "X", "ts": round(max(t0, 0.0) * 1e6, 3),
                       "dur": round(lat * 1e6, 3), "pid": pid,
                       "tid": tid, "args": args})
        cursor = max(t0, 0.0)
        for p in PHASES:
            dur = float(r["phase_s"].get(f"{p}_s", 0.0))
            if dur <= 0.0:
                continue
            events.append({"name": p, "cat": "reqtrace.phase", "ph": "X",
                           "ts": round(cursor * 1e6, 3),
                           "dur": round(dur * 1e6, 3),
                           "pid": pid, "tid": tid,
                           "args": {"id": rid, "phase": p}})
            cursor += dur
    for r in records:
        name = _INSTANT_EVENTS.get(r.get("event"))
        if name is None or not isinstance(r.get("ts"), (int, float)):
            continue
        dev = _dev(r.get("src") or r.get("device"))
        args = {k: v for k, v in r.items()
                if k in ("id", "ids", "src", "dst", "count", "action",
                         "failure_class", "drained", "fast_burn",
                         "attempt", "resumed", "wait_s", "level",
                         "from", "to")}
        events.append({"name": name, "cat": "reqtrace.event", "ph": "i",
                       "ts": round(max(float(r["ts"]) - epoch, 0.0) * 1e6,
                                   3),
                       "pid": devices.index(dev) + 1, "tid": 0,
                       "s": "p", "args": args})
    meta = [{"name": "process_name", "ph": "M", "ts": 0, "pid": i + 1,
             "tid": 0, "args": {"name": f"device {dev}"}}
            for i, dev in enumerate(devices)]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def reqtrace_main(argv=None) -> int:
    """``python -m bench_tpu_fem.obs reqtrace``: fold + render a serve
    journal's request traces. rc 1 when the emitted Chrome trace would
    violate the Perfetto schema (the CI contract)."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.obs reqtrace",
        description="Render a serve journal's request-scoped traces: "
                    "phase-percentile table + Perfetto timeline (one "
                    "track per device lane, request slices with phase "
                    "children, control-plane instants).")
    p.add_argument("--journal", required=True,
                   help="serve journal (harness.journal JSONL)")
    p.add_argument("--out", default="",
                   help="write the Chrome trace-event JSON here "
                        "(loads in Perfetto / chrome://tracing)")
    p.add_argument("--json", action="store_true",
                   help="emit the fold as one JSON object")
    args = p.parse_args(argv)
    from ..harness.journal import read_records
    from .trace import validate_chrome_trace

    records, corrupt = read_records(args.journal)
    fold = fold_reqtrace(records)
    trace = journal_to_chrome(records)
    violations = validate_chrome_trace(trace)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(trace, fh)
    n_req = sum(1 for ev in trace["traceEvents"]
                if ev.get("cat") == "reqtrace")
    if args.json:
        out = dict(fold)
        out["trace_events"] = len(trace["traceEvents"])
        out["request_slices"] = n_req
        out["trace_violations"] = violations[:10]
        out["corrupt_lines"] = len(corrupt)
        print(json.dumps(out))
    else:
        print("== request phases")
        if fold.get("status") == "ok":
            print(render_phases(fold))
        else:
            print(f"   {fold.get('status', '?').upper()} "
                  f"[{fold.get('reason', '')}] — a journal without "
                  "phase stamps is a labelled gap, never zeros")
        print(f"== timeline: {n_req} request slices, "
              f"{len(trace['traceEvents'])} events"
              + (f" -> {args.out}" if args.out else ""))
        for v in violations[:10]:
            print(f"   TRACE VIOLATION {v}")
    return 1 if violations else 0
