"""Convergence telemetry (ISSUE 10): fold a CG residual history into
the `convergence` evidence block + the paired time-to-rtol metric.

The capture side lives in the solvers (`la.cg.cg_solve(capture=True)`,
`cg_solve_batched(capture=True)`, `ops.kron_df.cg_solve_df(capture=True)`
and the dist twins): a preallocated device buffer of the CARRIED squared
residual norms, written inside the fori_loop body — no host sync on the
hot path. This module is the host-side fold, run ONCE after the solve:

* **iterations-to-rtol** at the ladder 1e-2..1e-8: the first iteration k
  with ||r_k|| / ||r_0|| < rtol. The paper's framing (unpreconditioned
  CG, fixed `nreps`) makes iteration count — not per-iteration speed —
  the wall-clock driver at scale; ROADMAP item 4 asks for this paired
  with GDoF/s.
* **time-to-rtol**: iterations-to-rtol x the measured per-iteration
  wall (solve wall / iterations run). GDoF/s answers "how fast is one
  iteration"; time-to-rtol answers "how fast is a SOLVE" — both ride
  every CG bench record once capture is on.
* **stagnation / restart counts**: longest run of non-decreasing
  residual norms (a stall signature) and the count of iterations whose
  residual norm GREW (the graceful-restart / conjugacy-loss signature —
  the history-level view of the `sentinel=True` in-loop counters).
* a **decimated curve** (<= `CURVE_POINTS` `[iteration, rel_residual]`
  pairs) for rendering (`python -m bench_tpu_fem.obs trend`) — the full
  history is NOT stamped (a 1000-iteration record would bloat every
  journal line ~20 KB; the fold keeps the curve's shape and both
  endpoints).

Evidence discipline (ROADMAP item 8): iteration counts are measured
wherever the solve ran (they are a property of the arithmetic, not the
clock); the TIMES carry the platform label — `cpu-measured` off-TPU
(hardware-armed: the same capture runs on the chip the moment the
tunnel lives), `hardware` on it.
"""

from __future__ import annotations

import math

import numpy as np

#: the iterations-to-rtol ladder (relative RESIDUAL NORM, not its square)
RTOL_LADDER = (1e-2, 1e-3, 1e-4, 1e-5, 1e-6, 1e-7, 1e-8)

#: max [iteration, rel_residual] pairs stamped for curve rendering
CURVE_POINTS = 64


def rtol_key(rtol: float) -> str:
    """Ladder dict key: '1e-02' .. '1e-08' (stable, sortable)."""
    return f"{rtol:.0e}"


def rel_residuals(hist) -> np.ndarray:
    """||r_k|| / ||r_0|| from a squared-norm history (hist[0] = rnorm0).
    A zero rnorm0 (the batched padding-lane convention) folds to an
    all-zero curve — 'converged at iteration 0', never a div-by-zero."""
    h = np.asarray(hist, dtype=np.float64)
    if h.size == 0 or h[0] <= 0.0:
        return np.zeros_like(h)
    with np.errstate(invalid="ignore"):
        return np.sqrt(np.maximum(h, 0.0) / h[0])


def iters_to_rtol(hist, ladder=RTOL_LADDER) -> dict[str, int | None]:
    """First iteration k with rel residual < rtol, per ladder rung
    (None = not reached within the captured budget). Monotone by
    construction of the scan (first crossing wins; later stagnation or
    growth does not un-cross)."""
    rel = rel_residuals(hist)
    out: dict[str, int | None] = {}
    for rtol in ladder:
        below = np.nonzero(rel < rtol)[0]
        out[rtol_key(rtol)] = int(below[0]) if below.size else None
    return out


def stagnation_stats(hist) -> dict[str, int]:
    """History-level stall/restart signatures: `stagnation_max_run` is
    the longest consecutive run of iterations whose residual norm did
    not decrease; `restarts` counts iterations whose residual norm GREW
    (finite growth — the conjugacy-loss / graceful-restart signature;
    non-finite entries are counted separately as `nonfinite_iters`)."""
    h = np.asarray(hist, dtype=np.float64)
    stag_run = stag_max = restarts = nonfinite = 0
    for k in range(1, h.size):
        if not math.isfinite(h[k]):
            nonfinite += 1
            continue
        if h[k] >= h[k - 1]:
            stag_run += 1
            stag_max = max(stag_max, stag_run)
            if h[k] > h[k - 1]:
                restarts += 1
        else:
            stag_run = 0
    return {"stagnation_max_run": stag_max, "restarts": restarts,
            "nonfinite_iters": nonfinite}


def decimate_curve(hist, max_points: int = CURVE_POINTS) -> list:
    """<= max_points `[iteration, rel_residual]` pairs, endpoints always
    included (stride-sampled — convergence curves are smooth enough that
    uniform decimation keeps the story)."""
    rel = rel_residuals(hist)
    n = rel.size
    if n == 0:
        return []
    idx = np.unique(np.linspace(0, n - 1, min(max_points, n)).astype(int))
    return [[int(k), float(rel[k])] for k in idx]


def fold_history(hist, *, wall_s: float, iters_run: int,
                 evidence: str) -> dict:
    """One solve's residual history -> the `convergence` block (see
    `convergence_stamp` for the stamped shape). `wall_s` is the measured
    solve wall for `iters_run` iterations; time-to-rtol multiplies the
    iteration count by the per-iteration wall."""
    h = np.asarray(hist, dtype=np.float64)
    iters = iters_to_rtol(h)
    per_iter_s = wall_s / max(int(iters_run), 1)
    time_to = {k: (round(v * per_iter_s, 6) if v is not None else None)
               for k, v in iters.items()}
    rel = rel_residuals(h)
    block = {
        "iters_run": int(iters_run),
        "rnorm0": float(h[0]) if h.size else 0.0,
        "final_rel_residual": float(rel[-1]) if rel.size else 0.0,
        "iters_to_rtol": iters,
        "time_to_rtol_s": time_to,
        "per_iter_s": round(per_iter_s, 9),
        "curve": decimate_curve(h),
        "evidence": evidence,
    }
    block.update(stagnation_stats(h))
    return block


def _evidence() -> str:
    """Platform label for the TIME side of the block (iteration counts
    are platform-independent measurements; the clock is not)."""
    import sys

    jax = sys.modules.get("jax")
    try:
        backend = jax.default_backend() if jax is not None else "cpu"
    except Exception:
        backend = "cpu"
    return ("hardware" if backend == "tpu"
            else "cpu-measured (time-to-rtol hardware-armed: same capture "
                 "re-runs on chip)")


def convergence_stamp(extra: dict, hist, *, wall_s: float, iters_run: int,
                      nrhs: int = 1, lane: int | None = None,
                      evidence: str | None = None) -> None:
    """Stamp the `convergence` block + the top-level `time_to_rtol_s`
    paired metric (next to `gdof_per_second` on every record). For
    batched solves pass lane 0's history (`hist[:, 0]` — the scale-1.0
    one-shot problem) with `nrhs`/`lane` recording what was folded."""
    block = fold_history(hist, wall_s=wall_s, iters_run=iters_run,
                         evidence=evidence or _evidence())
    if nrhs > 1:
        block["nrhs"] = int(nrhs)
        block["lane"] = int(lane or 0)
    # ISSUE 11: label the block with the preconditioner / s-step that
    # PRODUCED this history (read from the record's own stamps, written
    # by the drivers before the fold) — preconditioned and bare curves
    # must never compare silently; consumers (obs.regress) treat a
    # label mismatch as an apples-to-oranges gap, not a regression
    pre = extra.get("precond")
    block["precond"] = (pre.get("kind", "none")
                        if isinstance(pre, dict) else "none")
    block["s_step"] = int(extra.get("s_step", 1) or 1)
    extra["convergence"] = block
    # the paired metric, surfaced at top level so GDoF/s and
    # time-to-rtol read off one record side by side (ROADMAP item 4)
    extra["time_to_rtol_s"] = block["time_to_rtol_s"]
