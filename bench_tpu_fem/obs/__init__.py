"""Unified tracing & telemetry (ISSUE 8).

Three small, stdlib-first pieces with one contract between them — every
number a benchmark, serve or harness run emits can be *attributed*:

``obs.trace``
    Hierarchical span tracer: context manager + decorator, thread-safe,
    provably near-no-op when disabled. Exports Chrome trace-event JSON
    (Perfetto-loadable), folds span records into the harness JSONL
    journal, and emits ``jax.profiler.TraceAnnotation`` around spans so
    they line up with TPU profiler timelines the moment a hardware
    profile is taken (hardware-armed; CPU runs exercise the same code).

``obs.roofline``
    Analytic FLOP / HBM-byte cost model per engine form (degree x cells
    x precision), cross-checked against the ``analysis.budgets`` VMEM
    models and ``scripts/roofline_df.py`` — stamps arithmetic intensity
    and achieved-vs-roofline fraction into every bench record.

``obs.memory``
    Device-memory telemetry: ``device.memory_stats()`` peak /
    bytes-in-use around timed regions on hardware, process-RSS fallback
    on CPU — stamped into bench records and the serve ``/metrics``.

``obs.convergence`` (ISSUE 10)
    Convergence telemetry: folds the solvers' jit-safe in-loop residual
    histories (``la.cg`` / ``ops.kron_df`` ``capture=True``) into the
    ``convergence`` evidence block — iterations/time-to-rtol at the
    1e-2..1e-8 ladder, stagnation/restart counts — and the paired
    ``time_to_rtol_s`` metric next to GDoF/s (ROADMAP item 4).

``obs.regress`` (ISSUE 10)
    Regression sentinel: schema-tolerant round-trend loader (wedge
    rounds as labelled gaps), Mann-Whitney/bootstrap baseline
    comparison (advisory), deterministic-counter hard gates (the CI
    ``perfgate`` lane), and the serve SLO burn-rate fold shared with
    ``serve.metrics``.

``obs.reqtrace`` (ISSUE 15)
    Request-scoped tracing for the serve fleet: per-request phase
    decomposition (queue/compile/solve/audit/retry/respond summing to
    ``latency_s``), bounded exemplar ring (K slowest + every anomalous
    request, head-sampled normals by deterministic id hash),
    ``fold_reqtrace`` journal replay with live parity, and the
    ``python -m bench_tpu_fem.obs reqtrace`` Perfetto timeline render
    (one track per device lane, phase children, control-plane
    instants).

``python -m bench_tpu_fem.obs`` renders a journal + exported trace into
a report (span tree, timer table, roofline table) and validates the
trace JSON (rc 1 on schema violations); ``... obs trend`` renders the
round trajectory / convergence curves / SLO state, and ``... obs gate``
compares two perfgate snapshots (rc 1 on a gated counter regression) —
see ``obs.report``.

Evidence discipline (ROADMAP item 8): every stamp carries its evidence
label — a CPU-measured share or an analytic design estimate is never
presented as a hardware measurement.
"""

from .reqtrace import (  # noqa: F401
    ExemplarRing,
    ReqTrace,
    fold_reqtrace,
    summarize_phases,
)
from .trace import (  # noqa: F401
    BenchObserver,
    Lifecycle,
    SpanTracer,
    enable,
    disable,
    enabled,
    export_chrome_trace,
    span,
    traced,
    tracer,
    validate_chrome_trace,
)
