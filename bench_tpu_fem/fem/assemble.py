"""Assembled-matrix oracle and RHS assembly (numpy/scipy).

Replaces the reference's `--mat_comp` path: DOLFINx CPU CSR assembly with
FFCx-generated element kernels plus Dirichlet handling
(/root/reference/src/laplacian_solver.cpp:151-227, csr.hpp) and the RHS
`b = L(f)` assembly (laplacian_solver.cpp:100-105). The element stiffness
matrices here are computed from *full 3D* basis-gradient tables — an
independent discretisation path from the sum-factorised operator in
bench_tpu_fem.ops, so agreement at machine precision is a real check.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from ..elements.lagrange import lagrange_eval, lagrange_eval_deriv
from ..elements.tables import OperatorTables


def _phi_table_3d(tables: OperatorTables) -> np.ndarray:
    """Phi[q, i]: 3D basis function i at 3D quadrature point q (both in
    row-major (x, y, z) order)."""
    phi = lagrange_eval(tables.nodes1d, tables.pts1d)  # (nq, nd)
    return np.einsum("qi,rj,sk->qrsijk", phi, phi, phi).reshape(
        tables.nq**3, tables.nd**3
    )


def _grad_tables_3d(tables: OperatorTables) -> np.ndarray:
    """D[a, q, i]: derivative along reference axis a of 3D basis function i at
    3D quadrature point q (q and i in row-major (x, y, z) order)."""
    phi = lagrange_eval(tables.nodes1d, tables.pts1d)  # (nq, nd)
    dphi = lagrange_eval_deriv(tables.nodes1d, tables.pts1d)  # (nq, nd)
    Dx = np.einsum("qi,rj,sk->qrsijk", dphi, phi, phi)
    Dy = np.einsum("qi,rj,sk->qrsijk", phi, dphi, phi)
    Dz = np.einsum("qi,rj,sk->qrsijk", phi, phi, dphi)
    nq3 = tables.nq**3
    nd3 = tables.nd**3
    return np.stack([D.reshape(nq3, nd3) for D in (Dx, Dy, Dz)])


def element_stiffness_matrices(
    tables: OperatorTables, G: np.ndarray, kappa: float
) -> np.ndarray:
    """A_e[c, i, j] = kappa * sum_q sum_ab G[c, ab, q] D[a, q, i] D[b, q, j].

    G is the packed 6-component geometry tensor from
    bench_tpu_fem.fem.geometry.geometry_factors, shape (ncells, 6, nq, nq, nq).
    """
    D = _grad_tables_3d(tables)  # (3, nq3, nd3)
    ncells = G.shape[0]
    nq3 = tables.nq**3
    Gp = G.reshape(ncells, 6, nq3)
    # Unpack symmetric 6 -> (3, 3)
    idx = np.array([[0, 1, 2], [1, 3, 4], [2, 4, 5]])
    Gfull = Gp[:, idx, :]  # (ncells, 3, 3, nq3)
    # flux[c, a, q, j] = sum_b G[c,a,b,q] D[b,q,j]
    flux = np.einsum("cabq,bqj->caqj", Gfull, D)
    A = kappa * np.einsum("aqi,caqj->cij", D, flux)
    return A


def element_mass_matrices(
    tables: OperatorTables, wdetJ: np.ndarray
) -> np.ndarray:
    """M_e[c, i, j] = sum_q w*detJ(c, q) Phi_i(q) Phi_j(q).

    The basis-squared counterpart of element_stiffness_matrices: the
    oracle for the mass form and for the mass term of the shifted forms
    (helmholtz, heat). wdetJ is the (ncells, nq, nq, nq) tensor from
    bench_tpu_fem.fem.geometry.geometry_factors.
    """
    Phi = _phi_table_3d(tables)
    w = np.asarray(wdetJ).reshape(np.shape(wdetJ)[0], -1)
    return np.einsum("qi,cq,qj->cij", Phi, w, Phi, optimize=True)


def element_form_matrices(
    tables: OperatorTables,
    G: np.ndarray | None,
    wdetJ: np.ndarray | None,
    grad_coeff: float,
    mass_coeff: float,
    kq: np.ndarray | None = None,
) -> np.ndarray:
    """Element matrices for a registry form (forms.registry.FormSpec):

        A_e = grad_coeff * K_e(G_kappa) + mass_coeff * M_e(wdetJ)

    with kappa(x_q) folded into G exactly as the device operator folds
    it (a pointwise scale of the packed tensor). Chains with a zero
    coefficient skip their tables entirely, mirroring the kernel's
    static with_grad/with_mass flags.
    """
    A = None
    if grad_coeff != 0.0:
        Gk = G if kq is None else G * np.asarray(kq)[:, None]
        A = grad_coeff * element_stiffness_matrices(tables, Gk, 1.0)
    if mass_coeff != 0.0:
        M = mass_coeff * element_mass_matrices(tables, wdetJ)
        A = M if A is None else A + M
    return A


def assemble_csr(
    element_matrices: np.ndarray, dofmap: np.ndarray, bc_marker_flat: np.ndarray
) -> sp.csr_matrix:
    """Assemble global CSR with Dirichlet rows/columns zeroed and unit
    diagonal on constrained dofs.

    Matches DOLFINx semantics used by the oracle: `assemble_matrix(..., {bc})`
    skips insertion on constrained rows/columns and `set_diagonal` then places
    1.0 there (/root/reference/src/laplacian_solver.cpp:182-184).
    """
    ncells, nd3, _ = element_matrices.shape
    rows = np.repeat(dofmap, nd3, axis=1).ravel()
    cols = np.tile(dofmap, (1, nd3)).ravel()
    vals = element_matrices.ravel().copy()
    keep = ~(bc_marker_flat[rows] | bc_marker_flat[cols])
    A = sp.coo_matrix(
        (vals[keep], (rows[keep], cols[keep])),
        shape=(len(bc_marker_flat), len(bc_marker_flat)),
    ).tocsr()
    bc_idx = np.flatnonzero(bc_marker_flat)
    A += sp.coo_matrix(
        (np.ones(len(bc_idx)), (bc_idx, bc_idx)), shape=A.shape
    ).tocsr()
    return A


def csr_spmv_T(A: sp.csr_matrix, x: np.ndarray) -> np.ndarray:
    """Transpose SpMV y = A^T x — parity with the reference's
    `spmvT_impl`/`apply_transpose` (/root/reference/src/csr.hpp:61-77),
    which its own CG never calls either; provided for operator-API
    completeness (the assembled Laplacian is symmetric, so this equals
    the forward SpMV up to assembly rounding — a property the oracle
    tests assert rather than assume)."""
    return A.T @ x


def csr_diag_inv(A: sp.csr_matrix) -> np.ndarray:
    """Inverse diagonal 1/diag(A) — the Jacobi preconditioner vector the
    reference's MatrixOperator computes at construction
    (/root/reference/src/csr.hpp:79-107,135) and never consumes in its
    unpreconditioned CG. Constrained (Dirichlet) rows carry a unit
    diagonal (assemble_csr), so the result is finite everywhere for any
    assembled Laplacian."""
    d = np.asarray(A.diagonal())
    return 1.0 / d


def csr_cg_reference(A: sp.csr_matrix, b: np.ndarray, niter: int) -> np.ndarray:
    """Fixed-iteration unpreconditioned CG through the assembled matrix — the
    oracle counterpart of the device CG, same recurrence as the reference
    `cg_solve` (/root/reference/src/cg.hpp:89-169) with rtol = 0."""
    x, r = np.zeros_like(b), b.copy()
    p = r.copy()
    rnorm = float(p @ r)
    for _ in range(niter):
        y = A @ p
        alpha = rnorm / float(p @ y)
        x = x + alpha * p
        r = r - alpha * y
        rnorm_new = float(r @ r)
        beta = rnorm_new / rnorm
        rnorm = rnorm_new
        p = beta * p + r
    return x


def assemble_rhs(
    tables: OperatorTables,
    wdetJ: np.ndarray,
    dofmap: np.ndarray,
    f_dofs_flat: np.ndarray,
    bc_marker_flat: np.ndarray,
) -> np.ndarray:
    """Assemble b_i = sum_cells sum_q w*detJ(q) * f_h(q) * Phi_i(q), then set
    b = 0 on Dirichlet dofs.

    f_h is the finite-element interpolant of f (dof values `f_dofs_flat`).
    Mirrors `assemble_vector(b, L)` + `bc.set(b)` in
    /root/reference/src/laplacian_solver.cpp:100-105 for the mass form
    L = inner(w0, v)*dx (/root/reference/src/poisson64.py:66).
    """
    Phi = _phi_table_3d(tables)
    fq = np.einsum("qi,ci->cq", Phi, f_dofs_flat[dofmap])
    be = np.einsum("cq,cq,qi->ci", wdetJ.reshape(len(dofmap), -1), fq, Phi)
    b = np.zeros(len(bc_marker_flat), dtype=be.dtype)
    np.add.at(b, dofmap.ravel(), be.ravel())
    b[bc_marker_flat] = 0.0
    return b
