"""Host-side reference FEM path (layer L2): geometry factors, assembled CSR
stiffness matrix, RHS vector — the correctness oracle.

Replaces the reference's FFCx-generated element kernels + DOLFINx CPU assembly
used by `--mat_comp` (/root/reference/src/laplacian_solver.cpp:151-227,
csr.hpp) and the RHS form assembly (forms.cpp, laplacian_solver.cpp:100-105).
Everything here is numpy/scipy and deliberately *independent* of the
sum-factorised device path in bench_tpu_fem.ops: the element matrices are
built from full 3D basis-gradient tables, never from the 1D factorised chain.
"""

from .geometry import geometry_factors
from .assemble import (
    assemble_csr,
    assemble_rhs,
    csr_cg_reference,
    element_form_matrices,
    element_mass_matrices,
    element_stiffness_matrices,
)
from .source import default_source, interpolate

__all__ = [
    "geometry_factors",
    "assemble_csr",
    "assemble_rhs",
    "csr_cg_reference",
    "element_form_matrices",
    "element_mass_matrices",
    "element_stiffness_matrices",
    "default_source",
    "interpolate",
]
