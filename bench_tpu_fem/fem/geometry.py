"""Geometry factors at quadrature points (numpy reference implementation).

Computes, per cell and quadrature point, the symmetric weighted geometry
tensor used by the weak Laplacian,

    G = w * adj(J) adj(J)^T / det(J),        J_ij = dx_i / dxi_j,

stored as its 6 upper-triangular entries, plus w*det(J) for mass/RHS forms.
Mirrors `geometry_computation_cpu` (/root/reference/src/geometry_cpu.hpp:
25-112): K = adj(J) has rows K[a, :] = cross(J[:, a+1], J[:, a+2]) (cyclic),
and G_ab = K[a, :] . K[b, :] * w / detJ. The trilinear coordinate map means
J at a quadrature point is a small contraction over the 8 cell corners.
"""

from __future__ import annotations

import numpy as np


def _shape1d(pts: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Linear 1D shape functions and derivatives at points: (nq, 2) each."""
    pts = np.asarray(pts)
    N = np.stack([1.0 - pts, pts], axis=1)
    D = np.broadcast_to(np.array([-1.0, 1.0]), (len(pts), 2)).copy()
    return N, D


def jacobians(corners: np.ndarray, pts1d: np.ndarray) -> np.ndarray:
    """J[cell, qx, qy, qz, i, a] = dx_i/dxi_a for trilinearly-mapped hexes.

    corners: (..., 2, 2, 2, 3) cell corner coordinates indexed (a, b, c)
    along the (x, y, z) reference axes.
    """
    N, D = _shape1d(pts1d)
    # For derivative along axis 0: D(q0) x N(q1) x N(q2) contracted with corners.
    tab = {0: (D, N, N), 1: (N, D, N), 2: (N, N, D)}
    Js = []
    for a in range(3):
        A, B, C = tab[a]
        Js.append(np.einsum("...abci,xa,yb,zc->...xyzi", corners, A, B, C))
    # Stack as J[..., i, a]
    return np.stack(Js, axis=-1)


def geometry_factors(
    corners: np.ndarray, pts1d: np.ndarray, wts1d: np.ndarray, compute_G: bool = True
) -> tuple[np.ndarray | None, np.ndarray]:
    """Return (G, wdetJ).

    G:     (ncells, 6, nq, nq, nq) with components ordered
           (G00, G01, G02, G11, G12, G22) — same packing as the reference
           (geometry_cpu.hpp:92-109); None when compute_G is False (the RHS
           mass form needs only wdetJ, and G is ~6x its size).
    wdetJ: (ncells, nq, nq, nq) = quadrature weight * det(J).
    """
    corners = np.asarray(corners).reshape(-1, 2, 2, 2, 3)
    J = jacobians(corners, pts1d)  # (ncells, nq, nq, nq, 3, 3)
    cols = [J[..., :, a] for a in range(3)]
    K = np.stack(
        [
            np.cross(cols[1], cols[2]),
            np.cross(cols[2], cols[0]),
            np.cross(cols[0], cols[1]),
        ],
        axis=-2,
    )  # K[..., a, i] = adj(J) rows
    detJ = np.einsum("...i,...i->...", cols[0], K[..., 0, :])
    w = np.asarray(wts1d)
    w3 = w[:, None, None] * w[None, :, None] * w[None, None, :]
    if not compute_G:
        return None, w3[None] * detJ
    scale = w3[None] / detJ
    pairs = [(0, 0), (0, 1), (0, 2), (1, 1), (1, 2), (2, 2)]
    G = np.stack(
        [np.einsum("...i,...i->...", K[..., a, :], K[..., b, :]) * scale for a, b in pairs],
        axis=1,
    )
    return G, w3[None] * detJ
