"""ctypes bindings for the native (C++) runtime library.

The reference's host runtime is C++ (mesh glue, DOLFINx CSR assembly, CPU
geometry kernels); `native/benchfem_native.cpp` provides the equivalent
pieces here, and this module exposes them behind the same signatures as the
numpy implementations in bench_tpu_fem.fem. If the shared library has not
been built (`make -C native`), callers fall back to numpy transparently via
`available()`.

Why native matters on the host path: the numpy oracle materialises the full
(ncells, nd^3, nd^3) element-matrix batch plus ~3x that again in COO index/
value arrays (~32 B per pre-merge entry); the C++ assembler computes element
matrices cell-by-cell and buffers one 16-byte (col, value) pair per entry in
a single build pass, roughly halving peak memory and skipping the big einsum
temporaries on the way to the reference's nnz < 2^31 oracle limit
(laplacian_solver.cpp:170-172).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np
import scipy.sparse as sp

_LIB = None
_SEARCHED = False


def _lib_path() -> str | None:
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for cand in (
        os.path.join(here, "native", "libbenchfem_native.so"),
        os.path.join(here, "native", "build", "libbenchfem_native.so"),
    ):
        if os.path.exists(cand):
            return cand
    return None


def _load():
    global _LIB, _SEARCHED
    if _SEARCHED:
        return _LIB
    _SEARCHED = True
    path = _lib_path()
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    f64p = ctypes.POINTER(ctypes.c_double)
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    u8p = ctypes.POINTER(ctypes.c_uint8)

    lib.geometry_factors_f64.argtypes = [
        f64p, f64p, f64p, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        f64p, f64p,
    ]
    lib.csr_build_f64.argtypes = [
        f64p, f64p, i32p, u8p, ctypes.c_double, ctypes.c_int64,
        ctypes.c_int, ctypes.c_int, ctypes.c_int64, i64p,
    ]
    lib.csr_build_f64.restype = ctypes.c_void_p
    lib.csr_fill_f64.argtypes = [ctypes.c_void_p, i64p, i32p, f64p]
    lib.csr_free_f64.argtypes = [ctypes.c_void_p]
    lib.assemble_rhs_f64.argtypes = [
        f64p, f64p, i32p, u8p, f64p, ctypes.c_int64, ctypes.c_int,
        ctypes.c_int, ctypes.c_int64, f64p,
    ]
    lib.csr_spmv_f64.argtypes = [i64p, i32p, f64p, f64p, ctypes.c_int64, f64p]
    lib.csr_cg_f64.argtypes = [
        i64p, i32p, f64p, f64p, ctypes.c_int64, ctypes.c_int, f64p,
    ]
    _LIB = lib
    return _LIB


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


def geometry_factors(corners, pts1d, wts1d, compute_G: bool = True):
    """Native twin of fem.geometry.geometry_factors (G is None when
    compute_G is False — it is ~6x the size of wdetJ)."""
    lib = _load()
    corners = np.ascontiguousarray(corners, dtype=np.float64).reshape(-1, 2, 2, 2, 3)
    pts = np.ascontiguousarray(pts1d, dtype=np.float64)
    wts = np.ascontiguousarray(wts1d, dtype=np.float64)
    ncells, nq = corners.shape[0], len(pts)
    G = np.empty((ncells, 6, nq, nq, nq)) if compute_G else None
    wdetj = np.empty((ncells, nq, nq, nq))
    lib.geometry_factors_f64(
        _ptr(corners, ctypes.c_double), _ptr(pts, ctypes.c_double),
        _ptr(wts, ctypes.c_double), ncells, nq, int(compute_G),
        _ptr(G, ctypes.c_double) if compute_G else None,
        _ptr(wdetj, ctypes.c_double),
    )
    return G, wdetj


def assemble_csr(tables, G, kappa, dofmap, bc_marker_flat) -> sp.csr_matrix:
    """Native twin of fem.assemble.assemble_csr (which takes precomputed
    element matrices; this one builds them cell-by-cell from the gradient
    tables — assembly runs exactly once, then the CSR arrays are filled from
    the build handle)."""
    from .assemble import _grad_tables_3d

    lib = _load()
    D = np.ascontiguousarray(_grad_tables_3d(tables))
    nq3, nd3 = tables.nq**3, tables.nd**3
    G = np.ascontiguousarray(G, dtype=np.float64).reshape(-1, 6, nq3)
    dofmap = np.ascontiguousarray(dofmap, dtype=np.int32)
    bc = np.ascontiguousarray(bc_marker_flat, dtype=np.uint8)
    ncells, nrows = dofmap.shape[0], len(bc)

    nnz = np.zeros(1, dtype=np.int64)
    handle = lib.csr_build_f64(
        _ptr(G, ctypes.c_double), _ptr(D, ctypes.c_double),
        _ptr(dofmap, ctypes.c_int32), _ptr(bc, ctypes.c_uint8),
        float(kappa), ncells, nq3, nd3, nrows, _ptr(nnz, ctypes.c_int64),
    )
    try:
        row_ptr = np.empty(nrows + 1, dtype=np.int64)
        cols = np.empty(int(nnz[0]), dtype=np.int32)
        vals = np.empty(int(nnz[0]), dtype=np.float64)
        lib.csr_fill_f64(
            handle, _ptr(row_ptr, ctypes.c_int64), _ptr(cols, ctypes.c_int32),
            _ptr(vals, ctypes.c_double),
        )
    except BaseException:
        # csr_fill_f64 frees the handle on success; on an allocation failure
        # here the handle (holding the whole pre-merged matrix) would leak —
        # exactly when memory is scarcest.
        lib.csr_free_f64(handle)
        raise
    return sp.csr_matrix((vals, cols, row_ptr), shape=(nrows, nrows))


def csr_spmv(A: sp.csr_matrix, x: np.ndarray) -> np.ndarray:
    """Native twin of the oracle SpMV (y = A x, cf. reference csr.hpp)."""
    lib = _load()
    row_ptr = np.ascontiguousarray(A.indptr, dtype=np.int64)
    cols = np.ascontiguousarray(A.indices, dtype=np.int32)
    vals = np.ascontiguousarray(A.data, dtype=np.float64)
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.empty(A.shape[0], dtype=np.float64)
    lib.csr_spmv_f64(
        _ptr(row_ptr, ctypes.c_int64), _ptr(cols, ctypes.c_int32),
        _ptr(vals, ctypes.c_double), _ptr(x, ctypes.c_double),
        A.shape[0], _ptr(y, ctypes.c_double),
    )
    return y


def assemble_rhs(tables, wdetJ, dofmap, f_dofs_flat, bc_marker_flat) -> np.ndarray:
    """Native streaming twin of fem.assemble.assemble_rhs."""
    from .assemble import _phi_table_3d

    lib = _load()
    Phi = np.ascontiguousarray(_phi_table_3d(tables))
    wdetj = np.ascontiguousarray(wdetJ, dtype=np.float64).reshape(
        -1, tables.nq**3
    )
    dofmap = np.ascontiguousarray(dofmap, dtype=np.int32)
    bc = np.ascontiguousarray(bc_marker_flat, dtype=np.uint8)
    f = np.ascontiguousarray(f_dofs_flat, dtype=np.float64)
    b = np.empty(len(bc), dtype=np.float64)
    lib.assemble_rhs_f64(
        _ptr(wdetj, ctypes.c_double), _ptr(Phi, ctypes.c_double),
        _ptr(dofmap, ctypes.c_int32), _ptr(bc, ctypes.c_uint8),
        _ptr(f, ctypes.c_double), dofmap.shape[0], tables.nq**3,
        tables.nd**3, len(bc), _ptr(b, ctypes.c_double),
    )
    return b


def csr_cg(A: sp.csr_matrix, b: np.ndarray, niter: int) -> np.ndarray:
    """Native twin of fem.assemble.csr_cg_reference."""
    lib = _load()
    row_ptr = np.ascontiguousarray(A.indptr, dtype=np.int64)
    cols = np.ascontiguousarray(A.indices, dtype=np.int32)
    vals = np.ascontiguousarray(A.data, dtype=np.float64)
    b = np.ascontiguousarray(b, dtype=np.float64)
    x = np.empty_like(b)
    lib.csr_cg_f64(
        _ptr(row_ptr, ctypes.c_int64), _ptr(cols, ctypes.c_int32),
        _ptr(vals, ctypes.c_double), _ptr(b, ctypes.c_double),
        len(b), niter, _ptr(x, ctypes.c_double),
    )
    return x
