"""Source term and nodal interpolation.

The benchmark's source is a Gaussian bump in (x, y),
f = 1000 * exp(-((x-0.5)^2 + (y-0.5)^2) / 0.02)
(/root/reference/src/main.cpp:81-92), interpolated into the FE space by
evaluation at the dof coordinates.
"""

from __future__ import annotations

import numpy as np


def default_source(x: np.ndarray) -> np.ndarray:
    """f(x) for coordinate array of shape (..., 3)."""
    dx = (x[..., 0] - 0.5) ** 2
    dy = (x[..., 1] - 0.5) ** 2
    return 1000.0 * np.exp(-(dx + dy) / 0.02)


def interpolate(fn, dof_coords: np.ndarray) -> np.ndarray:
    """Evaluate `fn` at every dof coordinate; returns the dof-grid array."""
    return np.asarray(fn(dof_coords))
