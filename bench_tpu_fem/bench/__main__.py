"""bench package CLI.

    python -m bench_tpu_fem.bench engines [--json]

``engines`` renders the declarative engine registry
(bench_tpu_fem.engines.registry): every routable engine slice with its
capability predicate, VMEM plan reference, gate-reason vocabulary and
tunable defaults — plus the tuned-vs-default state when a tuning DB is
armed ($BTF_TUNING_DB, engines.autotune). The benchmark CLI itself is
``python -m bench_tpu_fem.cli`` (single-chip) and
``python -m bench_tpu_fem`` (dist); this module is the registry's
inspection surface, not a runner.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.bench",
        description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    eng = sub.add_parser(
        "engines", help="render the declarative engine registry")
    eng.add_argument("--json", action="store_true",
                     help="machine-readable rows instead of the table")
    args = p.parse_args(argv)

    from ..engines.autotune import default_tuning_db
    from ..engines.registry import (
        ENGINE_SPECS,
        GATE_REASONS,
        render_registry,
    )

    db = default_tuning_db()
    if args.json:
        rows = []
        for s in ENGINE_SPECS:
            rows.append({
                "name": s.name, "forms": list(s.forms),
                "precision": s.precision, "geometry": s.geometry,
                "sharding": s.sharding, "backend": s.backend,
                "nrhs": s.nrhs, "enabler": s.enabler, "plan": s.plan,
                "gate_slugs": list(s.gate_slugs),
                "tunables": list(s.tunables),
                "defaults": dict(s.defaults),
            })
        print(json.dumps({
            "engines": rows,
            "gate_reasons": dict(sorted(GATE_REASONS.items())),
            "tuning_db": (db.stats() if db is not None else None),
        }, sort_keys=True))
    else:
        print(render_registry(tuning_db=db))
    return 0


if __name__ == "__main__":
    sys.exit(main())
