"""Benchmark driver: the equivalent of `laplace_action_gpu/cpu`
(/root/reference/src/laplacian_solver.cpp:65-230,265-391).

Protocol (identical to the reference):
1. assemble b = L(f) for the Gaussian-bump source, zero Dirichlet rows;
   u <- b  (laplacian_solver.cpp:100-109)
2. timed region: nreps x (y = A u)  or  cg_solve(A, y, u, nreps, rtol=0)
   (laplacian_solver.cpp:119-127)
3. report ||u||, ||y||, wall time, GDoF/s = ndofs_global*nreps/(1e9*t)
4. --mat_comp: same applies/CG through the assembled CSR oracle -> z,
   report ||z|| and ||y - z|| (laplacian_solver.cpp:151-227)

One deliberate deviation: the operator is compiled (jitted) *before* the
timed region. The reference's kernels are compiled at build time, so its
timed region also contains no compilation; including XLA compile time would
measure the toolchain, not the hardware.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from functools import partial

import numpy as np

from ..elements.tables import build_operator_tables
from ..fem.assemble import (
    assemble_csr,
    assemble_rhs,
    element_stiffness_matrices,
)
from ..fem.geometry import geometry_factors
from ..fem.source import default_source
from ..la.cg import cg_solve
from ..mesh.box import create_box_mesh
from ..mesh.dofmap import (
    boundary_dof_marker,
    cell_dofmap,
    dof_coordinates,
    dof_grid_shape,
    global_ndofs,
)
from ..ops.laplacian import build_laplacian
from ..utils.compilation import (  # noqa: F401  (TPU_COMPILER_OPTIONS re-exported for probes/tests, which must mutate it IN PLACE — rebinding the name here would not reach compile_lowered)
    TPU_COMPILER_OPTIONS,
    compile_lowered,
    exc_str,
    scoped_vmem_options,
)
from ..obs.trace import BenchObserver
from ..utils.timing import Timer


@dataclass(frozen=True)
class BenchConfig:
    """Mirrors the reference CLI flag set (/root/reference/src/main.cpp:145-183)."""

    ndofs_global: int = 1000
    degree: int = 3
    qmode: int = 1
    float_bits: int = 64
    nreps: int = 1000
    use_cg: bool = False
    mat_comp: bool = False
    use_gauss: bool = False
    geom_perturb_fact: float = 0.0
    platform: str = "auto"  # "auto" | "tpu" | "cpu": jax default device
    ndevices: int = 1  # chips to shard over (1 = single-chip path)
    # operator kernel: "auto" | "kron" | "xla" | "pallas" (auto resolves to
    # kron on uniform single-chip meshes; see resolve_backend)
    backend: str = "auto"
    # float_bits=64 strategy: "emulated" (XLA software f64 — exact f64
    # semantics, ~100x slower than f32 on TPUs, which have no f64 units)
    # or "df32" (double-float f32 pairs, ~1e-12 residual floors at a ~20x
    # flop multiplier — ops.kron_df single-chip, dist.kron_df sharded;
    # uniform meshes only)
    f64_impl: str = "emulated"
    # non-empty: wrap the timed region in jax.profiler.trace writing to this
    # directory (device timelines; view with TensorBoard / xprof)
    profile_dir: str = ""
    # batched multi-RHS: solve nrhs right-hand sides (distinct per-lane
    # scales of the benchmark RHS) in ONE batched CG/action — the
    # serving-layer shape. Single-chip uniform kron f32 CG runs the
    # fused nrhs-native delay ring (ops.kron_cg.kron_cg_solve_batched)
    # where the per-bucket VMEM plan admits it; other paths run the
    # UNFUSED vmapped operators with the fallback recorded
    # (cg_engine_form: "unfused"). GDoF/s accounts the whole batch:
    # ndofs * nreps * nrhs / t.
    nrhs: int = 1
    # route the final solver compile through the serve-layer executable
    # cache (serve.cache.default_cache) so repeated identical configs in
    # one process (bench.py's retry/ladder sweeps) stop recompiling.
    # Single-device paths only (the dist drivers compile fresh). Off by
    # default: tests that monkeypatch kernel internals rely on every
    # run_benchmark call compiling fresh.
    exec_cache: bool = False
    # execute the timed region this many times (each fully fenced) and
    # report the per-rep wall distribution (min/median/max) in
    # extra["timing"] — exposes warmup and jitter. mat_free_time (what
    # GDoF/s divides by) is the MEDIAN; the default 1 reproduces the
    # historical single measurement exactly.
    timing_reps: int = 1
    # communication/compute overlap for the SHARDED fused CG engines
    # (ISSUE 7): "auto" engages the double-buffered-halo single-psum
    # forms (`halo_overlap` / `ext2d_overlap`) wherever the family's
    # resolver supports them, "off" pins the synchronous engines, "on"
    # insists (unsupported configs still fall back with the gate reason
    # recorded in `overlap_gate_reason`). Single-chip paths have no
    # collectives and ignore this.
    overlap: str = "auto"
    # Durable CG checkpoints (ISSUE 9): > 0 runs the CG solve at
    # iteration boundaries (la.checkpoint — the body is cg_solve's
    # verbatim, so the chunked loop is bitwise the one-loop solve) and,
    # with checkpoint_dir set, snapshots the solve state crash-safely
    # every `checkpoint_every` iterations (harness.checkpoint): a killed
    # process restores from the last snapshot instead of iteration 0.
    # The fused whole-solve engines expose no boundary and are gated off
    # with `checkpoint_gate_reason` recorded. 0 (the default) leaves the
    # hot path untouched — same executables, same routing, bit-for-bit.
    # Env defaults (BENCH_CHECKPOINT_EVERY / BENCH_CHECKPOINT_DIR) let
    # harness stages opt in without payload changes.
    checkpoint_every: int = field(default_factory=lambda: int(
        os.environ.get("BENCH_CHECKPOINT_EVERY", "0") or 0))
    checkpoint_dir: str = field(default_factory=lambda: os.environ.get(
        "BENCH_CHECKPOINT_DIR", ""))
    # Convergence telemetry (ISSUE 10): capture the per-iteration
    # residual-norm history inside the CG loop (la.cg capture=True —
    # device-buffered, no host sync on the hot path) and stamp the
    # `convergence` evidence block + the paired time-to-rtol metric
    # next to GDoF/s (obs.convergence). Routes fused whole-solve
    # engines to the capture-able unfused loop with
    # `convergence_gate_reason` recorded (same discipline as durable
    # checkpointing). False (the default) leaves the hot path
    # untouched — bitwise the pre-capture solve. Env default:
    # BENCH_CONVERGENCE=1 (harness stages opt in without payload
    # changes).
    convergence: bool = field(default_factory=lambda: bool(int(
        os.environ.get("BENCH_CONVERGENCE", "0") or 0)))
    # Preconditioned CG (ISSUE 11): "none" (the default — bitwise the
    # pre-PR solve) | "jacobi" (matrix-free diagonal) | "chebyshev"
    # (fixed polynomial in D^-1 A, power-method interval) | "pmg"
    # (p-multigrid V-cycle across the degree family). PCG runs the
    # unfused <r, z> loop; fused whole-solve engines gate off with the
    # reason recorded (la.precond.PRECOND_GATE_REASONS), and paths
    # without a wired preconditioner (folded layout, action runs,
    # checkpointed loops) record theirs. Every preconditioned record
    # stamps the `precond` evidence block (kind, setup wall, setup
    # applies, per-iteration apply cost). Env default: BENCH_PRECOND.
    precond: str = field(default_factory=lambda: (
        os.environ.get("BENCH_PRECOND", "none") or "none"))
    # s-step (communication-avoiding) CG (ISSUE 11): batch the
    # reductions of `s_step` iterations into ONE stacked reduction
    # (la.sstep — sharded: one psum per outer step, i.e. < 1 collective
    # per iteration). 1 (the default) is the standard recurrence; on a
    # breakdown the drivers re-run the standard loop and record
    # `s_step_fallback_reason`. Env default: BENCH_S_STEP.
    s_step: int = field(default_factory=lambda: int(
        os.environ.get("BENCH_S_STEP", "1") or 1))
    # SDC boundary audit + corruption-aware rollback (ISSUE 14): rides
    # the iteration-boundary checkpointed loop (checkpoint_every > 0 —
    # the audit cadence IS the durable-snapshot cadence, so a detected
    # corruption always has an audited-clean snapshot to roll back to).
    # Every boundary recomputes the true residual
    # (la.checkpoint.true_residual_sq) and compares it to the carried
    # rnorm; exceedance journals/stamps an `sdc` event, rolls back to
    # the last durable snapshot and re-runs; a SECOND detection on the
    # re-run adjudicates deterministic (RuntimeError classified `sdc`,
    # never retried) vs transient (one rollback, stamped, run
    # completes). The deterministic seeded injector is the CHAOS_SDC
    # env seam (harness.faults.sdc_env_plan); audit off (the default)
    # and CHAOS_SDC unset are the pre-PR checkpointed loop bit-for-bit.
    # Env default: BENCH_SDC_AUDIT=1.
    sdc_audit: bool = field(default_factory=lambda: bool(int(
        os.environ.get("BENCH_SDC_AUDIT", "0") or 0)))
    # Mixed-precision speed ladder (ISSUE 17): "auto" (the default —
    # the precision float_bits/f64_impl already select, bit-for-bit the
    # pre-ladder dispatch) | "bf16" (bf16-stream / f32-accumulate
    # operator applies, ops.bf16 — bf16-class answers at half HBM
    # bytes) | "bf16-refine" (the same bf16 hot loop wrapped in the
    # iterative-refinement outer correction, la.refine — f64-class
    # answers, `refine` evidence stamp with the inner/outer iteration
    # split and time_to_rtol_s). bf16 modes require --float 32 (the
    # registered bf16-float-bits reason) and route through the
    # engines.registry bf16 rows; unsupported combinations (sharded,
    # checkpointed, batched refinement, ...) record their registry
    # gate reasons, never silently. Env default: BENCH_PRECISION.
    precision: str = field(default_factory=lambda: (
        os.environ.get("BENCH_PRECISION", "auto") or "auto"))
    # Operator zoo (ISSUE 20): which weak form the benchmark runs.
    # "poisson" (the default) is the flagship path, bit-for-bit the
    # pre-zoo dispatch. The registry rows (forms.registry.FORMS — mass,
    # helmholtz, varkappa, heat) run the general sum-factorised form
    # action (forms.operators) on the single-chip unfused XLA path;
    # every unsupported feature combination raises or records its
    # REGISTERED form-* gate reason, never a silent fallback.
    # Env default: BENCH_FORM.
    form: str = field(default_factory=lambda: (
        os.environ.get("BENCH_FORM", "poisson") or "poisson"))


@dataclass
class BenchmarkResults:
    """Same fields as benchdolfinx::BenchmarkResults
    (/root/reference/src/laplacian_solver.hpp:13-20) plus mesh metadata."""

    mat_free_time: float = 0.0
    unorm: float = 0.0
    ynorm: float = 0.0
    unorm_linf: float = 0.0
    ynorm_linf: float = 0.0
    znorm: float = 0.0
    enorm: float = 0.0
    ncells_global: int = 0
    ndofs_global: int = 0
    nreps: int = 0
    gdof_per_second: float = 0.0
    extra: dict = field(default_factory=dict)


def record_engine(extra: dict, engine: bool, form: str | None = None,
                  error=None) -> None:
    """Unified engine-routing record, stamped by EVERY branch (kron /
    folded / df, single-chip / dist): `cg_engine_form` is one of
    "one_kernel" (single-chip delay ring) | "halo" (distributed plane/
    block-halo ring) | "ext2d" (3D-sharded halo-extended cross-section
    ring) | "halo_overlap" / "ext2d_overlap" (the communication-
    overlapped double-buffered-halo single-psum variants of the two
    dist forms) | "chunked" (y-chunked two-kernel) | "one_kernel_batched"
    (nrhs-native batched ring) | "unfused", and any fallback carries the
    reason in `cg_engine_error` plus its harness taxonomy class in
    `failure_class` (tunnel_wedge/oom/mosaic_reject/accuracy_fail/
    timeout/unsupported/transient) — so fallback audits are ONE grep
    across BENCH/MULTICHIP/MEASURE artifacts."""
    from ..harness.classify import classify_exception, classify_text

    extra["cg_engine"] = engine
    extra["cg_engine_form"] = (form or "unfused") if engine else "unfused"
    if error is not None:
        if isinstance(error, str):
            extra["cg_engine_error"] = error
            extra["failure_class"] = classify_text(error)
        else:
            extra["cg_engine_error"] = exc_str(error)
            extra["failure_class"] = classify_exception(error)
        # A hardware run that fell back to unfused is exactly the event
        # static analysis exists to predict: stamp the analyzer's
        # per-rule verdict next to the failure_class so "did static
        # analysis predict this?" is one grep across artifacts.
        from ..analysis.verdict import stamp_static_analysis

        stamp_static_analysis(extra)


def config_precision(cfg: BenchConfig) -> str:
    """The unified precision label every obs/serve/cache consumer uses:
    f32 | df32 | f64 (emulated) | bf16 (ISSUE 17 — both bf16 modes
    execute their hot loop at bf16 stream width; the refinement
    variant is distinguished in the executable-key KIND slot, not
    here)."""
    if cfg.precision.startswith("bf16"):
        return "bf16"
    return ("f32" if cfg.float_bits == 32
            else ("df32" if cfg.f64_impl == "df32" else "f64"))


def stamp_observability(cfg: BenchConfig, res: BenchmarkResults,
                        obs: BenchObserver,
                        precision: str | None = None) -> None:
    """The ISSUE-8 record contract, stamped by EVERY driver branch:
    ``phase_s``/``phase_share`` (span-attributed compile/transfer/solve
    shares), ``timing`` (per-rep wall distribution),
    ``peak_memory_bytes`` + ``memory`` (device stats on hardware,
    process-RSS proxy on CPU), and ``roofline`` (analytic intensity +
    achieved-vs-ceiling fraction for the engine form that RAN).
    ``precision`` is the precision that actually EXECUTED (a df32
    config rerun through the emulated fallback stamps "f64")."""
    import jax

    from ..obs.roofline import roofline_stamp

    obs.stamp(res.extra)
    geom = res.extra.get("geom") or (
        "perturbed" if cfg.geom_perturb_fact != 0.0 else "uniform")
    try:
        roofline_stamp(
            res.extra, degree=cfg.degree, qmode=cfg.qmode,
            precision=precision or config_precision(cfg),
            backend=res.extra.get("backend", ""), geom=geom,
            use_cg=cfg.use_cg, gdof_s=res.gdof_per_second,
            platform=jax.default_backend())
    except Exception as exc:  # telemetry must never sink a benchmark
        res.extra["roofline_error"] = exc_str(exc)


# engine_plan/engine_plan_df form names -> the unified vocabulary
ENGINE_FORM_NAMES = {"one": "one_kernel", "chunked": "chunked",
                     "one_batched": "one_kernel_batched"}

from ..engines.registry import GATE_REASONS, gate_reason

# The recorded reason every nrhs>1 branch WITHOUT a fused batched form
# stamps (classified `unsupported` by the harness taxonomy). Since the
# nrhs-native kron engine (ops.kron_cg.kron_cg_solve_batched) landed,
# single-chip uniform f32 CG batches run fused where the per-bucket VMEM
# plan admits them; every other batched branch (action, folded, df,
# sharded, over-budget buckets) still runs the unfused vmapped apply and
# records this. Text owned by the registry vocabulary (engines.registry)
# — every stamped reason must be a registered constant.
BATCHED_UNFUSED_REASON = GATE_REASONS["batched-unfused"]

# The recorded reason every fused-engine branch stamps when durable
# checkpointing is requested (ISSUE 9): the whole-solve engines bake
# nreps into ONE executable and expose no iteration boundary to snapshot
# at, so the driver runs the unfused checkpointable loop instead.
CHECKPOINT_GATE_REASON = GATE_REASONS["checkpoint-engine"]

# The recorded reason every fused-engine CG branch stamps when
# convergence capture is requested (ISSUE 10): the whole-solve engines
# bake the recurrence into ONE kernel chain with no per-iteration
# residual to buffer, so the driver runs the capture-able unfused loop
# instead (same structure as the checkpoint gate above).
CONVERGENCE_GATE_REASON = GATE_REASONS["convergence-engine"]


def stamp_precond(extra: dict, cfg: BenchConfig, bundle=None,
                  gate_reason: str | None = None) -> None:
    """The ISSUE-11 precond/s-step evidence stamps, written by every
    driver branch that saw a `--precond`/`--s-step` request: the
    `precond` block records what RAN (kind "none" + the gate reason
    when the request could not be served — never silent), `s_step` the
    requested batching factor (its own gate/fallback reasons ride
    separate keys). Also folds the per-iteration precond cost into the
    roofline stamp when one exists (obs.roofline.precond_cost)."""
    if cfg.precond != "none" or gate_reason is not None:
        block = {"requested": cfg.precond,
                 "kind": bundle.kind if bundle is not None else "none"}
        if bundle is not None:
            block.update(bundle.stamp())
        if gate_reason:
            block["gate_reason"] = gate_reason
            extra["precond_gate_reason"] = gate_reason
        extra["precond"] = block
    if cfg.s_step > 1:
        extra["s_step"] = int(cfg.s_step)


def resolve_precond_bundle(cfg: BenchConfig, op, u, mesh=None):
    """Build the requested preconditioner for a single-chip grid-layout
    operator, or return the recorded gate reason: ``(bundle | None,
    gate_reason | None)``. Setup cost (diagonal assembly wall,
    power-method applies, pmg level builds) is measured into the bundle
    and stamped — a PCG record always answers what its setup cost."""
    import time as _time

    from ..la.precond import (
        PRECOND_GATE_REASONS,
        build_chebyshev_bundle,
        build_jacobi_bundle,
        op_jacobi_dinv,
    )

    kind = cfg.precond
    if kind not in ("jacobi", "chebyshev", "pmg"):
        raise ValueError(f"unknown precond {kind!r}: expected none | "
                         "jacobi | chebyshev | pmg")
    if kind == "pmg":
        if mesh is None or cfg.use_gauss:
            return None, GATE_REASONS["precond-pmg-family"]
        if cfg.degree < 2:
            return None, GATE_REASONS["precond-pmg-degree"]
        from ..la.pmg import build_pmg_bundle

        backend = "kron" if hasattr(op, "Kd") else "xla"
        return build_pmg_bundle(mesh, cfg.degree, cfg.qmode, 2.0,
                                u.dtype, backend), None
    t0 = _time.monotonic()
    dinv = op_jacobi_dinv(op)
    if dinv is None:
        return None, PRECOND_GATE_REASONS["folded"]
    import jax

    jax.block_until_ready(dinv)
    diag_s = _time.monotonic() - t0
    if kind == "jacobi":
        return build_jacobi_bundle(dinv, setup_s=diag_s), None
    return build_chebyshev_bundle(op.apply, dinv, dinv.shape, u.dtype,
                                  setup_s_diag=diag_s), None


def precond_compile_form(bundle, apply_fn):
    """How a bundle enters the solver COMPILE: ``(pargs, factory)``
    with `factory(A, *pargs) -> precond callable`. Jacobi/Chebyshev
    pass their O(N) diagonal as an executable ARGUMENT (the driver's
    no-HLO-constants rule); the pmg V-cycle closes over its level
    hierarchy (coarse-level state is a small fraction of the fine
    problem, and pmg is CPU-proof scale today — the hardware-sized
    plumbing is a recorded remainder)."""
    from ..la.precond import make_chebyshev

    if bundle.kind == "jacobi":
        return ((bundle.state["dinv"],),
                lambda A, d: (lambda rr: d * rr))
    if bundle.kind == "chebyshev":
        lmax = bundle.params["lmax"]
        lmin = bundle.params["lmin"]
        steps = bundle.params["steps"]
        return ((bundle.state["dinv"],),
                lambda A, d: make_chebyshev(apply_fn(A), d, lmax, lmin,
                                            steps))
    return (), lambda A: bundle.apply


def _fence_scalar(out) -> None:
    """The drivers' warm-up hard fence (one scalar fetch), tolerant of
    tuple results — a convergence-captured solve returns (x, info) or
    (x, hist). Plain tuples fence their first element; DF results (a
    NamedTuple, not a plain tuple) fence their hi channel."""
    if type(out) is tuple:
        out = out[0]
    arr = out.hi if hasattr(out, "hi") else out
    float(arr[(0,) * arr.ndim])


def stamp_convergence(extra: dict, info, *, wall_s: float,
                      iters_run: int, nrhs: int = 1) -> None:
    """Fold a captured residual history (the info dict the capture-mode
    solvers return) into the `convergence` + `time_to_rtol_s` stamps
    (obs.convergence). Batched histories fold lane 0 (scale 1.0 — the
    one-shot problem verbatim). Telemetry must never sink a benchmark:
    failures stamp `convergence_error` instead of raising."""
    from ..obs.convergence import convergence_stamp

    try:
        hist = np.asarray(info["rnorm_history"], dtype=np.float64)
        lane = None
        if hist.ndim == 2:
            lane = 0
            hist = hist[:, 0]
        convergence_stamp(extra, hist, wall_s=wall_s, iters_run=iters_run,
                          nrhs=nrhs, lane=lane)
    except Exception as exc:
        extra["convergence_error"] = exc_str(exc)


def checkpoint_fingerprint(cfg: BenchConfig, kind: str,
                           ndofs_global: int,
                           backend: str = "") -> str:
    """The solve identity a snapshot is keyed on: every field that
    changes the CG trajectory. An OOM-ladder rung (different
    ndofs_global), a precision change or an operator-backend flip
    (kron/xla/pallas produce distinct f32 trajectories) gets a fresh
    fingerprint — its snapshots can never restore into the wrong
    solve. ``backend`` is the RESOLVED backend (res.extra), not the
    raw --backend flag, so auto-resolution can't alias two operators
    under one key."""
    from ..harness.checkpoint import solve_fingerprint

    return solve_fingerprint(
        kind=kind, ndofs_global=int(ndofs_global), degree=cfg.degree,
        qmode=cfg.qmode, float_bits=cfg.float_bits, nreps=cfg.nreps,
        geom_perturb_fact=cfg.geom_perturb_fact,
        f64_impl=cfg.f64_impl, use_gauss=cfg.use_gauss,
        backend=backend or cfg.backend,
        every=int(cfg.checkpoint_every))


def stamp_checkpoint(extra: dict, cfg: BenchConfig, store,
                     restored_it: int, saves: int) -> None:
    """The checkpoint evidence stamp every checkpointed run carries:
    cadence, durable-or-not, snapshots written, the iteration restored
    from, and the evidence label (snapshot/restore on real HBM is
    hardware-armed; off-TPU numbers are CPU-measured — ROADMAP item 8)."""
    import jax

    extra["checkpoint"] = {
        "every": int(cfg.checkpoint_every),
        "durable": store is not None,
        "saves": int(saves),
        "restored_iteration": int(restored_it),
        "evidence": ("hardware" if jax.default_backend() == "tpu"
                     else "cpu-measured"),
    }


def stamp_sdc(extra: dict, stats: dict | None) -> None:
    """SDC audit evidence stamp (ISSUE 14): every audited checkpointed
    run records its boundary-check count, worst clean drift vs the
    envelope, injected/detected/rolled-back counts and the adjudication
    verdict — the stamp-label-gate contract (ROADMAP item 7; on-chip
    detection economics are hardware-armed)."""
    if stats is None:
        return
    import jax

    # a deterministic verdict never reaches the stamp (the loop raises
    # on the second same-attempt detection): any recorded detection
    # here was adjudicated transient by its completed rollback re-run
    verdict = ("transient" if stats.get("detections", 0) >= 1
               else "clean")
    extra["sdc"] = {
        **{k: stats[k] for k in ("audited", "envelope", "checks",
                                 "drift_max", "injected", "detections",
                                 "rollbacks", "restored_iteration")},
        "adjudication": verdict,
        "evidence": ("hardware" if jax.default_backend() == "tpu"
                     else "cpu-measured"),
    }


def stamp_breakdown(extra: dict, ynorm) -> None:
    """Breakdown sentinel stamp (ISSUE 9), shared by every driver: a
    NaN/Inf solution must carry a recorded failure class, never pose as
    a clean benchmark number."""
    if not np.isfinite(ynorm):
        extra["failure_class"] = "breakdown"
        extra["breakdown"] = ("non-finite solution norm "
                              f"({ynorm!r}): CG breakdown")


def open_checkpoint(cfg: BenchConfig, res: BenchmarkResults, state_s,
                    kind: str, nreps: int):
    """Open the solve's CheckpointStore and restore its newest usable
    snapshot (host-side pytree; sharded callers re-place it on device).
    Shared by the single-chip f32/df and dist checkpointed paths so the
    restore rules live in ONE place:

    * a snapshot at or past ``nreps`` is a COMPLETED solve — restoring
      it would replay zero iterations and journal a zero-work
      "measurement" (gdof_per_second 0.0) on any retry that reuses the
      stage's round-stable snapshot dir; a re-run measures fresh
      instead, with the reason recorded;
    * a mismatched snapshot (shape/dtype/field drift) restores NOTHING
      (reason recorded) — wrong state is worse than restart.

    Returns ``(store, host_state_or_None, restored_iteration)``."""
    from ..harness.checkpoint import CheckpointStore
    from ..la.checkpoint import state_from_host

    store = CheckpointStore(
        cfg.checkpoint_dir,
        checkpoint_fingerprint(cfg, kind, res.ndofs_global,
                               backend=res.extra.get("backend", "")))
    snap = store.latest()
    if snap is None:
        return store, None, 0
    it, arrays, _meta = snap
    if int(it) >= nreps:
        res.extra["checkpoint_restore_skipped"] = (
            f"snapshot at iteration {int(it)} covers the whole solve "
            f"(nreps {nreps}): completed run, measuring fresh")
        # clear the WHOLE store, not just skip: left in place, the
        # completed snapshot sorts newest-by-iteration forever and
        # would shadow the mid-solve snapshot a later preemption of
        # THIS retry leaves behind — re-disabling resume for good
        store.clear()
        return store, None, 0
    try:
        return store, state_from_host(state_s, arrays), int(it)
    except ValueError as exc:
        res.extra["checkpoint_restore_error"] = exc_str(exc)
        return store, None, 0


def checkpointed_loop(state, run_chunk, *, store, restored_it: int,
                      nreps: int, k: int, kind: str, saves: dict,
                      save: bool, audit=None, envelope: float = 0.0,
                      inject=None, reinit=None, template=None,
                      sdc: dict | None = None):
    """Advance a restored (or fresh) iteration-boundary CG state to
    ``nreps``, snapshotting at every boundary when a store is given —
    the one loop all three checkpointed paths run. ``state_to_host``
    fetches the carry (the boundary host sync the enabled path pays and
    the disabled path provably does not).

    With ``audit`` (ISSUE 14: SDC defense) every boundary is
    true-residual-audited BEFORE its snapshot is trusted enough to
    save: ``audit(state) -> drift`` recomputes ``‖b − A x‖`` and
    compares it to the carried rnorm; drift past ``envelope`` is
    corruption — the loop rolls back to the last durable snapshot
    (every saved snapshot passed its own audit, so the rollback target
    is audited-clean; no store/snapshot -> ``reinit()`` restarts at
    iteration 0) and re-runs. A SECOND detection adjudicates
    deterministic: RuntimeError carrying the `sdc` classifier
    signature, never retried at this level. ``inject`` is the
    CHAOS_SDC seam (harness.faults.sdc_env_plan): one seeded host-side
    bit flip of the solution iterate when the loop crosses the
    scripted iteration (``once`` controls whether a rollback re-run
    sees it again — the transient-vs-deterministic models). ``sdc``
    accumulates the evidence counters the caller stamps. audit=None
    and inject=None are the pre-PR loop exactly."""
    from ..la.checkpoint import state_to_host

    it = restored_it
    inj_fired = False
    # adjudication is PER SOLVE ATTEMPT (this call): "detected again on
    # the re-run" means twice within one rollback chain — a later,
    # independent run (a second timing rep) that hits its own transient
    # upset adjudicates fresh. The caller's `sdc` dict still
    # accumulates totals across calls for the evidence stamp.
    detections_here = 0
    while it < nreps:
        state = run_chunk(state)
        prev_it, it = it, min(it + k, nreps)
        if inject is not None and prev_it < inject["iteration"] <= it \
                and not (inject.get("once", True) and inj_fired):
            import jax.numpy as jnp

            from ..harness.faults import flip_host_bit

            host_x = flip_host_bit(np.asarray(state.x),
                                   inject.get("index", -1),
                                   inject.get("bit"))
            state = state._replace(x=jnp.asarray(host_x))
            inj_fired = True
            if sdc is not None:
                sdc["injected"] = sdc.get("injected", 0) + 1
        if audit is not None:
            drift = audit(state)
            if sdc is not None:
                sdc["checks"] = sdc.get("checks", 0) + 1
                sdc["drift_max"] = max(sdc.get("drift_max", 0.0), drift)
            if drift > envelope:
                detections_here += 1
                if sdc is not None:
                    sdc["detections"] = sdc.get("detections", 0) + 1
                if detections_here >= 2:
                    raise RuntimeError(
                        "silent data corruption detected again after "
                        f"checkpoint rollback (true-residual audit "
                        f"drift {drift:.3e} > envelope {envelope:.1e} "
                        f"at iteration {it}): deterministic fault, "
                        "failure_class sdc")
                snap = store.latest() if store is not None else None
                # only a snapshot strictly BEFORE the detection point
                # is a rollback target: a stale completed snapshot from
                # an earlier run of the same store (a prior timing rep)
                # would otherwise "roll" the solve FORWARD past nreps
                if (snap is not None and template is not None
                        and int(snap[0]) < it):
                    from ..la.checkpoint import state_from_host

                    s_it, arrays, _meta = snap
                    state = state_from_host(template, arrays)
                    it = int(s_it)
                else:
                    # nothing durable yet: iteration 0 IS the last
                    # trustworthy checkpoint
                    state = reinit()
                    it = 0
                if sdc is not None:
                    sdc["rollbacks"] = sdc.get("rollbacks", 0) + 1
                    sdc["restored_iteration"] = it
                continue
        if save and store is not None:
            store.save(it, state_to_host(state),
                       meta={"kind": kind, "nreps": nreps})
            saves["n"] += 1
    return state


def _make_checkpointed_cg(cfg: BenchConfig, res: BenchmarkResults, obs,
                          op, apply_fn, u, opts):
    """Compile the iteration-boundary CG loop (la.checkpoint — cg_solve's
    body verbatim, so the chunked loop is bitwise the one-loop solve) and
    return ``run(save=True) -> x`` plus the restore bookkeeping.

    With cfg.checkpoint_dir set, ``run`` snapshots the host-fetched state
    every ``checkpoint_every`` iterations through the crash-safe
    CheckpointStore, and a fresh process restores from the newest valid
    snapshot instead of iteration 0 (torn/mismatched snapshots are
    skipped by the store — a restore can never load another solve's
    state). Without a dir the chunked loop still runs (the
    measured-overhead A/B arm) but nothing is written."""
    import jax

    from ..la.checkpoint import cg_ckpt_init, cg_ckpt_run, make_cg_ckpt_step

    k = int(cfg.checkpoint_every)
    nreps = cfg.nreps

    def _init(A, b):
        return cg_ckpt_init(apply_fn(A), b)

    def _run_chunk(A, s):
        return cg_ckpt_run(s, make_cg_ckpt_step(apply_fn(A), nreps), k)

    with obs.phase("compile"):
        state_s = jax.eval_shape(_init, op, u)
        init_fn = compile_lowered(jax.jit(_init).lower(op, u), opts)
        run_fn = compile_lowered(jax.jit(_run_chunk).lower(op, state_s),
                                 opts)

    store = None
    start_state = None
    restored_it = 0
    if cfg.checkpoint_dir:
        store, start_state, restored_it = open_checkpoint(
            cfg, res, state_s, "bench_cg", nreps)
    saves = {"n": 0}

    # SDC boundary audit (ISSUE 14): true residual recomputed per
    # boundary, compared to the carried rnorm against the
    # per-precision envelope; CHAOS_SDC arms the seeded injector.
    audit_fn = None
    inject = None
    sdc_stats = None
    envelope = 0.0
    if cfg.sdc_audit:
        from ..harness.faults import sdc_env_plan
        from ..la.checkpoint import true_residual_sq
        from ..ops.abft import RESIDUAL_ENVELOPE

        envelope = RESIDUAL_ENVELOPE[
            "f32" if cfg.float_bits == 32 else "f64"]
        tr_fn = jax.jit(lambda A, s: true_residual_sq(apply_fn(A), u,
                                                      s.x))

        def audit_fn(s):
            tr = float(np.asarray(tr_fn(op, s)))
            rn = float(np.asarray(s.rnorm))
            rn0 = float(np.asarray(s.rnorm0))
            if rn0 <= 0.0 or not (np.isfinite(tr) and np.isfinite(rn)):
                # non-finite is the breakdown sentinel's class, not
                # sdc's (finite-but-inconsistent by construction)
                return 0.0
            return float(abs(np.sqrt(max(tr, 0.0))
                             - np.sqrt(max(rn, 0.0))) / np.sqrt(rn0))

        inject = sdc_env_plan()
        sdc_stats = {"audited": True, "envelope": envelope,
                     "checks": 0, "drift_max": 0.0, "injected": 0,
                     "detections": 0, "rollbacks": 0,
                     "restored_iteration": None}

    def run(save: bool = True):
        state = start_state if start_state is not None else init_fn(op, u)
        # audit/injection ride the REAL run only: the save=False
        # warm-up exists to pay compile/transfer, and a once-shot
        # injection consumed there would leave the measured run with
        # nothing to detect
        state = checkpointed_loop(
            state, lambda s: run_fn(op, s), store=store,
            restored_it=restored_it, nreps=nreps, k=k, kind="bench_cg",
            saves=saves, save=save,
            audit=audit_fn if save else None, envelope=envelope,
            inject=inject if save else None,
            reinit=lambda: init_fn(op, u), template=state_s,
            sdc=sdc_stats)
        jax.block_until_ready(state.x)
        return state.x

    return run, store, restored_it, saves, sdc_stats


def batch_scales(nrhs: int) -> np.ndarray:
    """Per-lane RHS scales for the batched benchmark/serving path:
    powers of two (exact in f32 AND as df pair scalings — scaling both
    df channels by a power of two loses no bits), lane 0 exactly 1.0 so
    the batch's first lane reproduces the one-shot problem verbatim."""
    return 2.0 ** (np.arange(nrhs) % 3).astype(np.float64)


def stamp_nrhs(extra: dict, nrhs: int, checkpoint_every: int = 0) -> None:
    """nrhs + its serving bucket, stamped into every batched artifact
    line (the serve cache pads batches to these buckets). A batched run
    that ASKED for durable checkpoints records why it got none: the
    bench batched paths run whole-batch executables with no iteration
    boundary (the serve broker's BatchedCGState checkpointing is a
    different machine) — without the reason a preempted batched ladder
    retry would silently restart at iteration 0."""
    from ..serve.cache import nrhs_bucket

    extra["nrhs"] = int(nrhs)
    extra["nrhs_bucket"] = nrhs_bucket(int(nrhs))
    if checkpoint_every > 0:
        extra["checkpoint_gate_reason"] = GATE_REASONS["checkpoint-batched"]


def _exec_cache_key(cfg: BenchConfig, n, form: str, kind: str):
    """serve.cache.ExecutableKey for a driver compile: keyed on the
    PLANNED engine form (deterministic per config, so a fallback chain's
    final executable is found again under the same key) plus everything
    else that shapes the lowered computation. The nrhs slot carries the
    EXACT batch width, not the serve bucket: the driver compiles
    unpadded (benchmark work must equal accounted work — padding lanes
    would burn unmeasured bandwidth), so executables of different
    widths within one bucket must not collide."""
    from ..engines.registry import EngineSpec, bench_engine_form

    precision = config_precision(cfg)
    return EngineSpec.cache_key(
        degree=cfg.degree,
        cell_shape=tuple(int(c) for c in n),
        precision=precision,
        geom="perturbed" if cfg.geom_perturb_fact != 0.0 else "uniform",
        engine_form=bench_engine_form(cfg.backend, form, kind, cfg.qmode,
                                      cfg.use_gauss),
        nrhs_bucket=int(cfg.nrhs),
        device_mesh=(cfg.ndevices,),
        nreps=cfg.nreps,
    )


def _stamp_tuning(key, res: BenchmarkResults):
    """Tuned build parameters for this executable key (engines.autotune).
    Stamps the tuning evidence block (source=db with the entry's label
    and round when tuned, source=default with a registered reason
    otherwise) into the results, and returns the tuned params dict or
    None — defaults run with the reason journaled, never silently."""
    from ..engines.autotune import tuning_stamp

    return tuning_stamp(res.extra, key)


def _exec_cache_get(cfg: BenchConfig, key, res: BenchmarkResults):
    """Cached executable for this config, replaying the engine stamps
    the original compile recorded (the executable and its routing
    record are one unit of evidence)."""
    if not cfg.exec_cache:
        return None
    from ..serve.cache import default_cache

    entry = default_cache().get(key)
    if entry is None:
        return None
    res.extra.update(entry.meta)
    res.extra["exec_cache"] = "hit"
    return entry.executable


def _exec_cache_put(cfg: BenchConfig, key, fn,
                    res: BenchmarkResults) -> None:
    if not cfg.exec_cache:
        return
    from ..serve.cache import default_cache

    # the paired `get` above already counted the miss; insert counts
    # the compile and replays the engine-routing stamps on future hits
    default_cache().insert(key, fn, meta={
        k: v for k, v in res.extra.items()
        if k.startswith("cg_engine") or k in
        ("failure_class", "static_analysis", "geom")})
    res.extra["exec_cache"] = "miss"


def _mesh_setup(cfg: BenchConfig, n: tuple[int, int, int] | None = None):
    """Sizing, tables and mesh — O(ncells) host work, no dof-sized arrays."""
    from ..mesh.sizing import compute_mesh_size

    if n is None:
        n = compute_mesh_size(cfg.ndofs_global, cfg.degree)
    rule = "gauss" if cfg.use_gauss else "gll"
    with Timer("% Element tables (quadrature+basis)"):
        t = build_operator_tables(cfg.degree, cfg.qmode, rule)
    with Timer("% Build box mesh"):
        mesh = create_box_mesh(n, geom_perturb_fact=cfg.geom_perturb_fact)
    return n, rule, t, mesh


def _setup_problem(cfg: BenchConfig, n: tuple[int, int, int] | None = None,
                   prebuilt=None):
    """Shared host-side setup: mesh, tables, RHS (the oracle-precision f64
    path, as the reference assembles its RHS on the CPU). The host geometry
    tensor G is only materialised when the mat_comp oracle needs it.
    `prebuilt` forwards an existing (n, rule, t, mesh) so callers that
    already ran _mesh_setup don't rebuild the mesh and tables."""
    n, rule, t, mesh = prebuilt if prebuilt is not None else _mesh_setup(cfg, n)
    grid_shape = dof_grid_shape(n, cfg.degree)
    bc_grid = boundary_dof_marker(n, cfg.degree)

    from ..fem import native

    with Timer("% Assemble RHS (host)"):
        coords = dof_coordinates(mesh.vertices, cfg.degree, t.nodes1d)
        f = default_source(coords).ravel()
        dm = cell_dofmap(n, cfg.degree)
        corners = mesh.cell_corners.reshape(-1, 2, 2, 2, 3)
        bc_flat = bc_grid.ravel()
        if native.available():
            # C++ host path (native/benchfem_native.cpp) — same results as
            # the numpy oracle (tests/test_native.py), without the large
            # einsum intermediates.
            G_host, wdetJ = native.geometry_factors(
                corners, t.pts1d, t.wts1d, compute_G=cfg.mat_comp
            )
            b = native.assemble_rhs(t, wdetJ, dm, f, bc_flat).reshape(grid_shape)
        else:
            G_host, wdetJ = geometry_factors(
                corners, t.pts1d, t.wts1d, compute_G=cfg.mat_comp
            )
            b = assemble_rhs(t, wdetJ, dm, f, bc_flat).reshape(grid_shape)

    return n, rule, t, mesh, grid_shape, bc_grid, dm, b, G_host


def resolve_backend(backend: str, float_bits: int, uniform: bool = False,
                    degree: int = 3, qmode: int = 1) -> str:
    """'auto' backend resolution:

    - uniform (unperturbed) mesh -> 'kron': the exact Kronecker-sum fast
      path (ops.kron), any dtype — no geometry tensor, ~2x the folded
      kernel's CG rate;
    - perturbed mesh, f32 on TPU, if the folded kernels fit full 128-lane
      blocks (G streaming through degree 3 qmode 1; corner mode extends
      that to degree 4, and its plane-streamed form to degree 5 qmode 1 —
      ops.folded.pallas_geom_constraint) -> 'pallas' (the folded general
      kernel);
    - otherwise 'xla' (einsum path; Mosaic has no f64, CPU runs use einsum,
      interpret-mode Pallas is for tests).

    The decision table lives in engines.registry (one source of truth
    for routing, serve capability checks, and the analysis matrix);
    this is a thin delegate kept for the existing call sites.
    """
    from ..engines.registry import resolve_backend as _resolve

    return _resolve(backend, float_bits, uniform, degree, qmode)


def run_benchmark(cfg: BenchConfig) -> BenchmarkResults:
    import jax

    if cfg.float_bits not in (32, 64):
        raise ValueError("Invalid float size. Must be 32 or 64.")
    # Set in BOTH directions: a prior f64 run in the same process (e.g.
    # bench.py's f64 side metric) must not leak x64 into an f32 run — under
    # x64, Python-int kernel parameters trace as int64 and Mosaic rejects
    # them (tpu.dynamic_rotate wants i32 shifts). Restored on exit so an f32
    # benchmark doesn't silently downgrade the caller's later f64 numerics
    # (all results leave this function as Python floats).
    if cfg.f64_impl not in ("emulated", "df32"):
        raise ValueError("f64_impl must be 'emulated' or 'df32'")
    if cfg.precision not in ("auto", "bf16", "bf16-refine"):
        raise ValueError("precision must be 'auto', 'bf16' or "
                         f"'bf16-refine' (got {cfg.precision!r})")
    if cfg.precision != "auto" and cfg.float_bits != 32:
        # bf16 streams the f32-assembled operator; the registered
        # reason (engines.registry) is the error text, never free text
        raise ValueError(gate_reason("bf16-float-bits",
                                     bits=cfg.float_bits))
    # df32 traces in pure f32 pairs — x64 stays off for it. bf16 runs
    # f32 outer state (x64 off); the refinement outer loop toggles x64
    # on around its f64 operator itself.
    want_x64 = cfg.float_bits == 64 and cfg.f64_impl == "emulated"
    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", want_x64)
    try:
        if cfg.form != "poisson":
            return _run_benchmark_form(cfg)
        if cfg.precision != "auto":
            return _run_benchmark_bf16(cfg)
        if cfg.float_bits == 64 and cfg.f64_impl == "df32":
            return _run_benchmark_df64(cfg)
        return _run_benchmark(cfg)
    finally:
        jax.config.update("jax_enable_x64", prev_x64)


def _df64_emulated_fallback(cfg: BenchConfig, reason: str) -> BenchmarkResults:
    """Recorded (never silent) XLA-emulation fallback for df32 configs the
    df pipelines cannot serve: rerun the config through the emulated f64
    path with x64 on, stamping the reason into the results. The backend is
    reset to 'auto' (an explicit --backend pallas request legitimately
    reached the df attempt, but Mosaic has no f64 — the emulated rerun must
    resolve to the XLA path). The caller chain's finally-restore keeps the
    caller's x64 setting intact."""
    import dataclasses

    import jax

    cfg = dataclasses.replace(cfg, backend="auto")
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        res = _run_benchmark(cfg)
    finally:
        jax.config.update("jax_enable_x64", prev)
    res.extra["f64_impl"] = "emulated-fallback"
    res.extra["f64_df32_fallback_reason"] = reason
    from ..analysis.verdict import stamp_static_analysis
    from ..harness.classify import classify_text

    res.extra["failure_class"] = classify_text(reason)
    stamp_static_analysis(res.extra)
    return res


def _run_benchmark_folded_df(cfg: BenchConfig) -> BenchmarkResults:
    """Perturbed (general-geometry) float_bits=64 via double-float pairs:
    the folded df pipeline (ops.folded_df — unfused v1 composition, df
    geometry end to end). The XLA-emulation fallback only engages with a
    recorded reason (a config outside the df VMEM plan, or a compile
    rejection) — never silently, so a benchmark number can always be
    attributed to the path that produced it."""
    import jax
    import jax.numpy as jnp

    from ..la.df64 import DF, df_dot, df_to_f64
    from ..ops.folded import fold_vector, unfold_vector
    from ..ops.folded_df import (
        build_folded_laplacian_df,
        folded_action_df,
        folded_cg_solve_df,
        folded_df_plan,
    )

    if cfg.backend not in ("auto", "pallas"):
        raise ValueError(
            gate_reason("df-backend-folded", backend=cfg.backend))
    if cfg.nrhs > 1:
        # the folded df pipeline has no batched form (its kernels are
        # not vmap-batchable today): recorded emulation fallback — the
        # emulated path batches through _finish_batched
        return _df64_emulated_fallback(
            cfg, gate_reason("df-batched-folded"))
    n, rule, t, mesh = _mesh_setup(cfg)
    supported, _, kib = folded_df_plan(cfg.degree, t.nq)
    if not supported:
        return _df64_emulated_fallback(
            cfg, gate_reason("df-plan-unsupported", degree=cfg.degree,
                             qmode=cfg.qmode))
    ndofs_global = global_ndofs(n, cfg.degree)
    res = BenchmarkResults(
        ncells_global=mesh.ncells, ndofs_global=ndofs_global, nreps=cfg.nreps
    )
    res.extra["backend"] = "pallas"
    res.extra["f64_impl"] = "df32"
    res.extra["f64_df32_path"] = "folded"
    # the folded df pipeline is the deliberately-unfused composition
    # (ops.folded_df v1) — no fused engine form exists for it yet
    record_engine(res.extra, False)
    if cfg.use_cg and cfg.checkpoint_every > 0:
        # no checkpointable boundary exists inside the folded df CG
        # composition yet (its seam-fold state rides the kernel chain):
        # recorded, runs the standard whole-solve executable
        res.extra["checkpoint_gate_reason"] = (
            GATE_REASONS["checkpoint-folded-df"])
    if cfg.convergence:
        # same seam: the folded df CG's residual rides the kernel chain
        # with no per-iteration buffer to capture into (recorded)
        res.extra["convergence_gate_reason"] = (
            GATE_REASONS["convergence-folded-df"])
    if cfg.sdc_audit:
        res.extra["sdc_gate_reason"] = GATE_REASONS["sdc-folded-df"]
    if cfg.precond != "none":
        from ..la.precond import PRECOND_GATE_REASONS

        stamp_precond(res.extra, cfg,
                      gate_reason=PRECOND_GATE_REASONS["folded"])
    if cfg.s_step > 1:
        res.extra["s_step"] = int(cfg.s_step)
        res.extra["s_step_gate_reason"] = GATE_REASONS["sstep-folded-df"]

    # Host-assembled f64 RHS (the reference assembles its RHS on the CPU
    # too), split into df channels and folded per channel. The oracle
    # state rides along when mat_comp asks for it.
    _, _, _, _, _, bc_grid, dm, b_host, G_host = _setup_problem(
        cfg, n, prebuilt=(n, rule, t, mesh)
    )

    obs = BenchObserver(cfg)
    with Timer("% Create matfree operator"):
        op = build_folded_laplacian_df(
            mesh, cfg.degree, cfg.qmode, rule, kappa=2.0, tables=t
        )
        res.extra["geom"] = "corner" if op.Gh is None else "g"
        b64 = np.asarray(b_host, np.float64)
        bh = np.asarray(b64, np.float32)
        bl = np.asarray(b64 - np.asarray(bh, np.float64), np.float32)
        u = DF(jnp.asarray(fold_vector(bh, op.layout)),
               jnp.asarray(fold_vector(bl, op.layout)))
        compile_opts = (scoped_vmem_options(kib)
                        if jax.default_backend() == "tpu" else None)
        if cfg.use_cg:
            fn_py = lambda A, b: folded_cg_solve_df(A, b, cfg.nreps)  # noqa: E731
        else:
            fn_py = lambda A, b: folded_action_df(A, b, cfg.nreps)  # noqa: E731
        try:
            with obs.phase("compile"):
                fn = compile_lowered(jax.jit(fn_py).lower(op, u),
                                     compile_opts)
        except Exception as exc:
            # a Mosaic/XLA rejection of the folded df kernels must not
            # sink the benchmark: recorded emulation fallback
            return _df64_emulated_fallback(
                cfg, gate_reason("df-compile-failed", error=exc_str(exc)))
        with obs.phase("transfer"):
            warm = fn(op, u)
            float(warm.hi[(0,) * warm.hi.ndim])
            del warm

    y = obs.timed_reps(lambda: fn(op, u))
    res.mat_free_time = obs.elapsed()

    dot_fn = jax.jit(df_dot)
    linf_fn = jax.jit(lambda a: jnp.max(jnp.abs(a.hi + a.lo)))

    def norms(v):
        l2 = float(np.sqrt(max(float(df_to_f64(dot_fn(v, v))), 0.0)))
        return l2, float(linf_fn(v))

    with Timer("% Norms (device reduce)"):
        res.unorm, res.unorm_linf = norms(u)
        res.ynorm, res.ynorm_linf = norms(y)
    res.gdof_per_second = ndofs_global * cfg.nreps / (
        1e9 * res.mat_free_time
    )
    stamp_observability(cfg, res, obs, "df32")

    if cfg.mat_comp:
        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        y64 = (unfold_vector(np.asarray(y.hi, np.float64), op.layout)
               + unfold_vector(np.asarray(y.lo, np.float64), op.layout))
        e = y64 - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def _make_checkpointed_cg_df(cfg: BenchConfig, res: BenchmarkResults,
                             obs, op, u, opts=None):
    """The df (double-float) twin of ``_make_checkpointed_cg``:
    ops.kron_df.cg_solve_df's body at iteration boundaries
    (la.checkpoint.make_df_cg_ckpt_step — including its residual-floor
    freeze), so the chunked loop is bitwise the uninterrupted df solve
    and a restore continues it bit-for-bit."""
    import jax

    from ..la.checkpoint import (
        cg_ckpt_run,
        df_cg_ckpt_init,
        make_df_cg_ckpt_step,
    )

    k = int(cfg.checkpoint_every)
    nreps = cfg.nreps

    def _init(b):
        return df_cg_ckpt_init(b)

    def _run_chunk(A, s):
        return cg_ckpt_run(s, make_df_cg_ckpt_step(A.apply, nreps), k)

    with obs.phase("compile"):
        state_s = jax.eval_shape(_init, u)
        init_fn = compile_lowered(jax.jit(_init).lower(u), None)
        run_fn = compile_lowered(jax.jit(_run_chunk).lower(op, state_s),
                                 opts)

    store = None
    start_state = None
    restored_it = 0
    if cfg.checkpoint_dir:
        store, start_state, restored_it = open_checkpoint(
            cfg, res, state_s, "bench_cg_df", nreps)
    saves = {"n": 0}

    def run(save: bool = True):
        state = start_state if start_state is not None else init_fn(u)
        state = checkpointed_loop(
            state, lambda s: run_fn(op, s), store=store,
            restored_it=restored_it, nreps=nreps, k=k, kind="bench_cg_df",
            saves=saves, save=save)
        jax.block_until_ready(state.x.hi)
        return state.x

    return run, store, restored_it, saves


def _run_benchmark_df64(cfg: BenchConfig) -> BenchmarkResults:
    """float_bits=64 via double-float f32 pairs: the kron path on uniform
    meshes (ops.kron_df), the folded path on perturbed/general geometry
    (ops.folded_df — _run_benchmark_folded_df); ndevices > 1 dispatches
    to the sharded dist drivers — the same protocol and reporting as
    _run_benchmark."""
    import jax
    import numpy as np

    from ..ops.kron_df import (
        action_df,
        build_kron_laplacian_df,
        cg_solve_df,
        device_rhs_uniform_df,
    )
    from ..la.df64 import df_to_f64

    if cfg.ndevices > 1:
        from ..dist.driver import run_distributed_df64

        res = BenchmarkResults(nreps=cfg.nreps)
        return run_distributed_df64(cfg, res)
    if cfg.geom_perturb_fact != 0.0:
        return _run_benchmark_folded_df(cfg)
    if cfg.backend not in ("auto", "kron"):
        raise ValueError(gate_reason("df-backend-kron", backend=cfg.backend))
    n, rule, t, mesh = _mesh_setup(cfg)
    if not mesh.is_uniform:
        raise ValueError("f64_impl='df32' requires a uniform (unperturbed) "
                         "mesh — the kron fast path")
    ndofs_global = global_ndofs(n, cfg.degree)
    res = BenchmarkResults(
        ncells_global=mesh.ncells, ndofs_global=ndofs_global, nreps=cfg.nreps
    )
    res.extra["backend"] = "kron"
    res.extra["f64_impl"] = "df32"

    b_host = bc_grid = dm = G_host = None
    if cfg.mat_comp:
        # oracle runs must solve the oracle's own RHS (the f32 path does
        # the same): u is the host-assembled b, not the separable device
        # RHS, so enorm measures solver error only
        _, _, _, _, _, bc_grid, dm, b_host, G_host = _setup_problem(
            cfg, n, prebuilt=(n, rule, t, mesh)
        )

    from ..la.df64 import df_from_f64

    obs = BenchObserver(cfg)
    with Timer("% Create matfree operator"):
        op = build_kron_laplacian_df(
            mesh, cfg.degree, cfg.qmode, rule, kappa=2.0, tables=t
        )
        u = (df_from_f64(np.asarray(b_host, np.float64))
             if cfg.mat_comp else device_rhs_uniform_df(t, mesh.n))

        if cfg.nrhs > 1:
            # batched df32: the whole per-lane df solve vmapped (the
            # fused df engine has no batched form — recorded fallback)
            oracle_args = ((t, dm, bc_grid, b_host, G_host)
                           if cfg.mat_comp else None)
            return _finish_batched_df(cfg, res, n, op, u, oracle_args)

        # Fused df delay-ring engine (ops.kron_cg_df) on TPU where the
        # one-kernel form fits a scoped-VMEM tier; Mosaic compile
        # rejections fall back to the unfused path with the reason
        # recorded (same hardening as the f32 engine above).
        from ..ops.kron_cg_df import (
            action_ring_df,
            engine_plan_df,
            kron_cg_df_solve,
        )

        form, kib = engine_plan_df(dof_grid_shape(n, cfg.degree),
                                   cfg.degree)
        engine = jax.default_backend() == "tpu"
        ckpt = cfg.use_cg and cfg.checkpoint_every > 0
        if ckpt and engine:
            # same gate as the f32 driver: the fused df ring is one
            # whole-solve executable with no boundary to snapshot at
            engine = False
            res.extra["checkpoint_gate_reason"] = CHECKPOINT_GATE_REASON
        if cfg.sdc_audit:
            # the df checkpointed loop carries (hi, lo) pairs the f32
            # boundary audit is not wired through (the serve layer's
            # df retire audit covers df32 detection); recorded, never
            # silent
            res.extra["sdc_gate_reason"] = GATE_REASONS["sdc-df"]
        # convergence capture (ISSUE 10): rides the unfused df loop
        # (cg_solve_df capture=True); the fused df ring gates off with
        # the reason recorded — same discipline as the f32 driver
        conv = cfg.convergence and cfg.use_cg and not ckpt
        if cfg.convergence and cfg.use_cg and ckpt:
            res.extra["convergence_gate_reason"] = (
                GATE_REASONS["convergence-checkpoint"])
        if cfg.convergence and not cfg.use_cg:
            res.extra["convergence_gate_reason"] = (
                GATE_REASONS["convergence-action"])
        if conv and engine:
            engine = False
            res.extra["convergence_gate_reason"] = CONVERGENCE_GATE_REASON
        # Preconditioning (ISSUE 11) on the df path: Jacobi only — the
        # f32 inverse diagonal scales both df channels (la.precond.
        # make_jacobi_df; a preconditioner's own rounding reshapes M,
        # never the answer). Apply-based preconditioners and s-step
        # have no df forms (recorded remainders).
        pre_df = None
        if cfg.s_step > 1:
            res.extra["s_step"] = int(cfg.s_step)
            res.extra["s_step_gate_reason"] = GATE_REASONS["sstep-df"]
        if cfg.precond != "none":
            from ..la.precond import (
                PRECOND_GATE_REASONS,
                build_jacobi_bundle,
                jacobi_dinv_uniform,
                make_jacobi_df,
            )

            gate = None
            bundle = None
            if not cfg.use_cg:
                gate = PRECOND_GATE_REASONS["action"]
            elif ckpt:
                gate = PRECOND_GATE_REASONS["checkpoint"]
            elif cfg.precond != "jacobi":
                gate = gate_reason("precond-df", precond=cfg.precond)
            else:
                import time as _time

                import jax.numpy as _jnp

                t0 = _time.monotonic()
                dinv32 = jacobi_dinv_uniform(t, n, 2.0, _jnp.float32)
                jax.block_until_ready(dinv32)
                bundle = build_jacobi_bundle(
                    dinv32, setup_s=_time.monotonic() - t0)
                pre_df = make_jacobi_df(dinv32)
                if engine:
                    engine = False
                    res.extra["precond_gate_reason"] = (
                        PRECOND_GATE_REASONS["engine"])
            stamp_precond(res.extra, cfg, bundle=bundle, gate_reason=gate)
        compile_opts = scoped_vmem_options(kib) if engine else None
        record_engine(res.extra, engine, ENGINE_FORM_NAMES.get(form, form))

        def _lower(f):
            return jax.jit(f).lower(op, u)

        def _fused(force_chunked=False):
            if cfg.use_cg:
                return lambda A, b: kron_cg_df_solve(
                    A, b, cfg.nreps, force_chunked=force_chunked)
            return lambda A, b: action_ring_df(
                A, b, cfg.nreps, force_chunked=force_chunked)

        def _unfused():
            if cfg.use_cg:
                # pre_df (a small dinv closure) rides the lowered
                # computation as a constant: df runs are CPU-proof
                # scale today (the hardware precond stage runs f32)
                return lambda A, b: cg_solve_df(A, b, cfg.nreps,
                                                capture=conv,
                                                precond=pre_df)
            return lambda A, b: action_df(A, b, cfg.nreps)

        run_ck = ck_store = None
        ck_restored = 0
        ck_saves = {"n": 0}
        if ckpt:
            run_ck, ck_store, ck_restored, ck_saves = (
                _make_checkpointed_cg_df(cfg, res, obs, op, u))
            with obs.phase("transfer"):
                warm = run_ck(save=False)
                _fence_scalar(warm)
                del warm
            fn = None
        else:
            try:
                with obs.phase("compile"):
                    fn = compile_lowered(
                        _lower(_fused() if engine else _unfused()),
                        compile_opts)
            except Exception as exc:
                if not engine:
                    raise
                # Mosaic rejection of the fused df engine: retry the
                # chunked form when the first pick was one-kernel (same
                # policy as the f32 engine), then fall back to the
                # unfused path, recording why. Compile errors only —
                # execution errors propagate.
                fn = None
                with obs.phase("compile"):
                    if form == "one":
                        try:
                            fn = compile_lowered(
                                _lower(_fused(force_chunked=True)))
                            # the one-kernel rejection is kept alongside:
                            # a drifted tier boundary is only diagnosable
                            # from it
                            res.extra["cg_engine_form"] = "chunked"
                            res.extra["cg_engine_one_kernel_error"] = (
                                exc_str(exc))
                        except Exception as exc2:
                            res.extra["cg_engine_retry_error"] = (
                                exc_str(exc2))
                    if fn is None:
                        engine = False
                        # the recorded form never ran — the unfused stamp
                        # must not attribute unfused timings to an engine
                        # form
                        record_engine(res.extra, False, error=exc)
                        fn = compile_lowered(_lower(_unfused()))
            with obs.phase("transfer"):
                warm = fn(op, u)
                _fence_scalar(warm)
                del warm

    y = obs.timed_reps(run_ck if run_ck is not None
                       else (lambda: fn(op, u)))
    res.mat_free_time = obs.elapsed()
    conv_info = None
    if conv and run_ck is None:
        # convergence-captured df solve: fetch the history once, here
        y, conv_info = y

    # Norms on device: L2 via the compensated df dot (f64-class); Linf on
    # the f32-rounded hi+lo (|.|max to ~f32 relative accuracy — casting to
    # f64 on device would need x64, which this mode keeps off). No O(N)
    # host transfer at any problem size.
    from ..la.df64 import df_dot

    import jax.numpy as jnp

    dot_fn = jax.jit(df_dot)  # compiled once, reused for u and y
    linf_fn = jax.jit(lambda a: jnp.max(jnp.abs(a.hi + a.lo)))

    def norms(v):
        l2 = float(np.sqrt(max(float(df_to_f64(dot_fn(v, v))), 0.0)))
        return l2, float(linf_fn(v))

    with Timer("% Norms (device reduce)"):
        res.unorm, res.unorm_linf = norms(u)
        res.ynorm, res.ynorm_linf = norms(y)
    iters_timed = cfg.nreps - (ck_restored if run_ck is not None else 0)
    res.gdof_per_second = ndofs_global * iters_timed / (
        1e9 * res.mat_free_time
    )
    if run_ck is not None:
        stamp_checkpoint(res.extra, cfg, ck_store, ck_restored,
                         ck_saves["n"])
    stamp_breakdown(res.extra, res.ynorm)
    stamp_observability(cfg, res, obs, "df32")
    if conv_info is not None:
        stamp_convergence(res.extra, conv_info,
                          wall_s=res.mat_free_time, iters_run=cfg.nreps)

    if cfg.mat_comp:
        # assembled-CSR oracle in true f64 (host path; oracle runs are
        # small, so the one O(N) host transfer of y here is fine)
        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        e = df_to_f64(y) - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def _finish_batched(cfg: BenchConfig, res: BenchmarkResults, n, op, u,
                    folded: bool, compile_opts, oracle_args=None):
    """Batched multi-RHS completion of the single-chip f32/f64 benchmark:
    nrhs per-lane-scaled copies of the benchmark RHS through ONE batched
    computation. The single-chip uniform kron f32 CG path runs the FUSED
    nrhs-native delay ring (ops.kron_cg.kron_cg_solve_batched,
    `cg_engine_form: "one_kernel_batched"`) where the per-bucket VMEM
    plan admits it; every other combination runs
    `la.cg.cg_solve_batched` over the vmapped UNFUSED apply (CG) or a
    vmapped apply inside the fenced rep loop (action), recording
    BATCHED_UNFUSED_REASON. Reported norms are lane 0's (scale 1.0 —
    the one-shot problem verbatim, so unorm/ynorm stay comparable
    across nrhs); GDoF/s accounts the whole batch
    (ndofs * nreps * nrhs / t)."""
    import jax
    import jax.numpy as jnp

    from ..la.cg import cg_solve_batched
    from ..la.vector import norm, norm_linf

    stamp_nrhs(res.extra, cfg.nrhs, cfg.checkpoint_every)
    apply_one = (lambda A: A.apply_cg) if folded else (lambda A: A.apply)
    scales = jnp.asarray(batch_scales(cfg.nrhs), u.dtype)
    B = scales.reshape((-1,) + (1,) * u.ndim) * u[None]

    # Fused batched engine (ops.kron_cg.kron_cg_solve_batched): the
    # nrhs-native delay ring on the single-chip uniform kron CG path,
    # where the per-bucket VMEM plan admits this lane count. Everything
    # else (action, folded, non-f32, over-budget nrhs) stays on the
    # unfused vmapped apply with the reason recorded.
    engine = False
    planned_form = "unfused"
    engine_run = None
    engine_opts = compile_opts
    if (cfg.use_cg and not folded
            and res.extra.get("backend") == "kron"
            and jax.default_backend() == "tpu"):
        from ..ops.kron_cg import (
            engine_plan_batched,
            kron_cg_solve_batched,
            supports_kron_cg_engine_batched,
        )

        if supports_kron_cg_engine_batched(u.shape, cfg.degree, u.dtype,
                                           cfg.nrhs):
            form, kib = engine_plan_batched(u.shape, cfg.degree, cfg.nrhs)
            engine = True
            planned_form = form
            engine_opts = scoped_vmem_options(kib)
            record_engine(res.extra, True,
                          ENGINE_FORM_NAMES.get(form, form))

            def engine_run(A, Bv):
                return kron_cg_solve_batched(A, Bv, cfg.nreps)

    # convergence capture (ISSUE 10): per-lane history through
    # cg_solve_batched(capture=True); the fused batched ring gates off
    # with the reason recorded (same discipline as the single-RHS gate)
    conv = cfg.convergence and cfg.use_cg
    if cfg.convergence and not cfg.use_cg:
        res.extra["convergence_gate_reason"] = (
            GATE_REASONS["convergence-action"])
    if conv and engine:
        engine = False
        engine_run = None
        planned_form = "unfused"
        res.extra["convergence_gate_reason"] = CONVERGENCE_GATE_REASON

    # Preconditioning (ISSUE 11) on the batched paths: Jacobi only (an
    # elementwise diagonal broadcasts across lanes for free; the
    # apply-based preconditioners have no batched cost model yet —
    # recorded remainder). s-step has no batched form (recorded).
    pdinv = None
    if cfg.s_step > 1:
        from ..la.sstep import SSTEP_GATE_REASON

        res.extra["s_step_gate_reason"] = SSTEP_GATE_REASON
        res.extra["s_step"] = int(cfg.s_step)
    if cfg.precond != "none" and cfg.use_cg:
        from ..la.precond import build_jacobi_bundle, op_jacobi_dinv

        gate = None
        bundle = None
        if cfg.precond != "jacobi":
            gate = gate_reason("precond-batched", precond=cfg.precond)
        else:
            import time as _time

            t0 = _time.monotonic()
            pdinv = op_jacobi_dinv(op)
            if pdinv is None:
                from ..la.precond import PRECOND_GATE_REASONS

                gate = PRECOND_GATE_REASONS["folded"]
            else:
                jax.block_until_ready(pdinv)
                bundle = build_jacobi_bundle(
                    pdinv, setup_s=_time.monotonic() - t0)
                if engine:
                    from ..la.precond import PRECOND_GATE_REASONS

                    engine = False
                    engine_run = None
                    planned_form = "unfused"
                    res.extra["precond_gate_reason"] = (
                        PRECOND_GATE_REASONS["engine"])
        stamp_precond(res.extra, cfg, bundle=bundle, gate_reason=gate)
    elif cfg.precond != "none":
        from ..la.precond import PRECOND_GATE_REASONS

        stamp_precond(res.extra, cfg,
                      gate_reason=PRECOND_GATE_REASONS["action"])

    if not engine:
        record_engine(res.extra, False, error=BATCHED_UNFUSED_REASON)

    if cfg.use_cg and pdinv is not None:
        def run(A, Bv, d):
            return cg_solve_batched(apply_one(A), Bv,
                                    jnp.zeros_like(Bv), cfg.nreps,
                                    capture=conv,
                                    precond=lambda R: d[None] * R)
    elif cfg.use_cg:
        def run(A, Bv):
            return cg_solve_batched(apply_one(A), Bv,
                                    jnp.zeros_like(Bv), cfg.nreps,
                                    capture=conv)
    else:
        def run(A, Bv):
            def _rep(i, Y):
                BB, _ = jax.lax.optimization_barrier((Bv, Y))
                return jax.vmap(apply_one(A))(BB)

            return jax.lax.fori_loop(0, cfg.nreps, _rep,
                                     jnp.zeros_like(Bv))

    # Exec-cache key on the PLANNED form (deterministic per config; a
    # Mosaic-reject fallback executable is stored under the planned key
    # with its true routing stamps replayed from the entry meta).
    obs = BenchObserver(cfg)
    batch_extra = (pdinv,) if pdinv is not None else ()
    batch_kind = ("cg+conv" if conv else "cg") if cfg.use_cg else "action"
    if pdinv is not None:
        batch_kind += "+jacobi"
    key = _exec_cache_key(cfg, n, planned_form, batch_kind)
    tuned = _stamp_tuning(key, res)
    if tuned and engine and tuned.get("window_kib"):
        # tuned scoped-VMEM window beats the plan's static estimate;
        # compile-option only, numerics untouched
        engine_opts = scoped_vmem_options(int(tuned["window_kib"]))
    fn = _exec_cache_get(cfg, key, res)
    from_cache = fn is not None
    with obs.phase("compile"):
        if fn is None and engine:
            # Same hardening as the single-RHS engine compiles: a Mosaic
            # rejection of the batched ring (a drifted per-bucket tier
            # boundary) must not sink the benchmark — fall back to the
            # unfused vmapped path, recording why. Compile errors only.
            try:
                fn = compile_lowered(jax.jit(engine_run).lower(op, B),
                                     engine_opts)
            except Exception as exc:
                record_engine(res.extra, False, error=exc)
        if fn is None:
            fn = compile_lowered(
                jax.jit(run).lower(op, B, *batch_extra), compile_opts)
    if not from_cache:
        _exec_cache_put(cfg, key, fn, res)
    with obs.phase("transfer"):
        warm = fn(op, B, *batch_extra)
        _fence_scalar(warm)
        del warm

    Y = obs.timed_reps(lambda: fn(op, B, *batch_extra))
    elapsed = obs.elapsed()
    conv_info = None
    if conv:
        Y, conv_info = Y

    res.mat_free_time = elapsed
    y0 = Y[0]
    res.unorm = float(norm(u))
    res.ynorm = float(norm(y0))
    res.unorm_linf = float(norm_linf(u))
    res.ynorm_linf = float(norm_linf(y0))
    res.gdof_per_second = (
        res.ndofs_global * cfg.nreps * cfg.nrhs / (1e9 * elapsed))
    stamp_observability(cfg, res, obs)
    if conv_info is not None:
        stamp_convergence(res.extra, conv_info, wall_s=elapsed,
                          iters_run=cfg.nreps, nrhs=cfg.nrhs)

    if cfg.mat_comp and oracle_args is not None:
        t, dm, bc_grid, b_host, G_host = oracle_args
        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        y = np.asarray(y0)
        if folded:
            from ..ops.folded import unfold_vector

            y = unfold_vector(y, op.layout)
        e = np.asarray(y, dtype=np.float64) - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def _finish_batched_df(cfg: BenchConfig, res: BenchmarkResults, n, op, u,
                       oracle_args=None):
    """Batched multi-RHS completion of the single-chip df32 (kron)
    benchmark: the whole per-lane df solve vmapped over the batch axis
    (each lane runs `cg_solve_df`'s exact recurrence, including its
    per-lane residual-floor freeze — lane 0 is bitwise the one-shot df
    solve). Power-of-two lane scales keep the df pairs exact."""
    import jax
    import jax.numpy as jnp

    from ..la.df64 import DF, df_dot, df_to_f64
    from ..ops.kron_df import action_df, cg_solve_df

    stamp_nrhs(res.extra, cfg.nrhs, cfg.checkpoint_every)
    record_engine(res.extra, False, error=BATCHED_UNFUSED_REASON)
    if cfg.convergence:
        # the batched df path vmaps the WHOLE per-lane df solve; its
        # capture form is not wired (recorded, never silent)
        res.extra["convergence_gate_reason"] = (
            GATE_REASONS["convergence-batched-df"])
    if cfg.precond != "none":
        stamp_precond(res.extra, cfg,
                      gate_reason=GATE_REASONS["precond-batched-df"])
    if cfg.s_step > 1:
        res.extra["s_step"] = int(cfg.s_step)
        res.extra["s_step_gate_reason"] = GATE_REASONS["sstep-batched-df"]
    scales = jnp.asarray(batch_scales(cfg.nrhs), jnp.float32)
    sb = scales.reshape((-1,) + (1,) * u.hi.ndim)
    B = DF(sb * u.hi[None], sb * u.lo[None])
    nreps = cfg.nreps
    if cfg.use_cg:
        def run(A, Bh, Bl):
            return jax.vmap(lambda b: cg_solve_df(A, b, nreps))(DF(Bh, Bl))
    else:
        def run(A, Bh, Bl):
            return jax.vmap(lambda b: action_df(A, b, nreps))(DF(Bh, Bl))

    obs = BenchObserver(cfg)
    key = _exec_cache_key(cfg, n, "unfused",
                          "cg" if cfg.use_cg else "action")
    _stamp_tuning(key, res)
    fn = _exec_cache_get(cfg, key, res)
    if fn is None:
        with obs.phase("compile"):
            fn = compile_lowered(jax.jit(run).lower(op, B.hi, B.lo), None)
        _exec_cache_put(cfg, key, fn, res)
    with obs.phase("transfer"):
        warm = fn(op, B.hi, B.lo)
        float(warm.hi[(0,) * warm.hi.ndim])
        del warm

    Y = obs.timed_reps(lambda: fn(op, B.hi, B.lo))
    res.mat_free_time = obs.elapsed()

    dot_fn = jax.jit(df_dot)
    linf_fn = jax.jit(lambda a: jnp.max(jnp.abs(a.hi + a.lo)))

    def norms(v):
        l2 = float(np.sqrt(max(float(df_to_f64(dot_fn(v, v))), 0.0)))
        return l2, float(linf_fn(v))

    y0 = DF(Y.hi[0], Y.lo[0])
    with Timer("% Norms (device reduce)"):
        res.unorm, res.unorm_linf = norms(u)
        res.ynorm, res.ynorm_linf = norms(y0)
    res.gdof_per_second = (
        res.ndofs_global * cfg.nreps * cfg.nrhs
        / (1e9 * res.mat_free_time))
    stamp_observability(cfg, res, obs, "df32")

    if cfg.mat_comp and oracle_args is not None:
        t, dm, bc_grid, b_host, G_host = oracle_args
        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        e = df_to_f64(y0) - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def _run_benchmark_bf16(cfg: BenchConfig) -> BenchmarkResults:
    """--precision bf16[-refine] (ISSUE 17): the mixed-precision speed
    ladder. Every hot-loop operator apply streams the bfloat16-rounded
    operator (ops.bf16.Bf16Operator — half the HBM bytes of the f32
    stream, f32 accumulation) on both geometry paths: the kron
    Kronecker operand structure on uniform meshes, the xla einsum path
    (G streamed at bf16) on perturbed geometry. "bf16" runs the plain
    CG/action protocol at bf16-class accuracy; "bf16-refine" wraps the
    same bf16 hot loop in the iterative-refinement outer correction
    (la.refine — one f64 apply per outer) and hands back f64-class
    answers with the `refine` evidence stamp (inner/outer iteration
    split, rel history, time_to_rtol_s). Backend routing resolves
    through the engines.registry bf16 rows — no capability chain lives
    here — and every unsupported combination records its REGISTERED
    gate reason. All numbers are cpu-measured until the harness `bf16`
    agenda stage re-runs them on hardware."""
    import jax
    import jax.numpy as jnp

    from ..engines.registry import specs
    from ..ops.bf16 import bf16_dinv, to_bf16

    refine = cfg.precision == "bf16-refine"
    if cfg.ndevices > 1:
        # bf16 is single-chip today: the sharded f32 path runs instead
        # and the dist driver stamps the registered bf16-sharded reason
        return _run_benchmark(cfg)
    if cfg.backend == "pallas":
        raise ValueError(gate_reason("bf16-backend", backend="pallas"))

    n, rule, t, mesh = _mesh_setup(cfg)
    geom = "uniform" if mesh.is_uniform else "perturbed"
    if cfg.backend == "kron" and geom != "uniform":
        raise ValueError(GATE_REASONS["kron-perturbed"])
    if cfg.backend in ("kron", "xla"):
        backend = cfg.backend
    else:
        # registry-resolved routing: the bf16 row for this geometry
        # names the operand backend (kron_bf16 / xla_bf16)
        backend = next(s for s in specs(precision="bf16", geometry=geom)
                       if s.backend != "any").backend
    ndofs_global = global_ndofs(n, cfg.degree)
    res = BenchmarkResults(
        ncells_global=mesh.ncells, ndofs_global=ndofs_global,
        nreps=cfg.nreps
    )
    res.extra["backend"] = backend
    res.extra["precision"] = cfg.precision
    # no fused bf16 Mosaic ring exists yet: the unfused bf16-stream
    # composition runs, with the registered reason recorded
    record_engine(res.extra, False, error=GATE_REASONS["bf16-fused"])

    if cfg.use_cg and cfg.checkpoint_every > 0 and cfg.nrhs == 1:
        res.extra["checkpoint_gate_reason"] = (
            GATE_REASONS["checkpoint-bf16"])
    if cfg.sdc_audit:
        # the boundary audit rides the checkpointed loop, which bf16
        # gates off; la.cg's CGAudit covers per-apply bf16 detection
        # against the calibrated bf16 envelope tier (ops.abft)
        res.extra["sdc_gate_reason"] = GATE_REASONS["sdc-no-checkpoint"]
    if cfg.s_step > 1:
        res.extra["s_step"] = int(cfg.s_step)
        res.extra["s_step_gate_reason"] = GATE_REASONS["sstep-unsupported"]
    if refine and not cfg.use_cg:
        refine = False
        res.extra["refine_gate_reason"] = GATE_REASONS["refine-action"]
    if refine and cfg.nrhs > 1:
        refine = False
        res.extra["refine_gate_reason"] = GATE_REASONS["refine-batched"]
    conv = cfg.convergence and cfg.use_cg and not refine
    if cfg.convergence and not cfg.use_cg:
        res.extra["convergence_gate_reason"] = (
            GATE_REASONS["convergence-action"])
    elif cfg.convergence and refine:
        # the refinement stamp carries its own per-outer rel history
        res.extra["convergence_gate_reason"] = (
            GATE_REASONS["convergence-refine"])

    dtype = jnp.float32
    device_setup = backend == "kron" and not cfg.mat_comp
    b_host = bc_grid = dm = G_host = None
    if not device_setup:
        _, _, _, _, _, bc_grid, dm, b_host, G_host = _setup_problem(
            cfg, n, prebuilt=(n, rule, t, mesh)
        )

    obs = BenchObserver(cfg)
    with Timer("% Create matfree operator"):
        op32 = build_laplacian(
            mesh, cfg.degree, cfg.qmode, rule, kappa=2.0, dtype=dtype,
            tables=t, backend=backend,
        )
        if device_setup:
            from ..ops.kron import device_rhs_uniform

            u = jax.jit(lambda: device_rhs_uniform(t, mesh.n, dtype))()
        else:
            u = jnp.asarray(b_host, dtype=dtype)
        # the HBM-resident operator state rounds to bf16 ONCE here —
        # every subsequent hot-loop apply streams half-width operands
        op_lo = to_bf16(op32)

    # Preconditioning: Jacobi only (the f32 diag-inverse, computed from
    # the widened operand state, is outer-loop state — not a streamed
    # hot-loop operand). With refine it arms the flexible-PCG inner
    # solve; plain bf16 runs standard PCG on the bf16 op.
    pdinv = None
    if cfg.precond != "none":
        from ..la.precond import PRECOND_GATE_REASONS, build_jacobi_bundle

        gate = None
        bundle = None
        if not cfg.use_cg:
            gate = PRECOND_GATE_REASONS["action"]
        elif cfg.precond != "jacobi":
            gate = gate_reason("precond-bf16", precond=cfg.precond)
        else:
            import time as _time

            t0 = _time.monotonic()
            pdinv = bf16_dinv(op_lo)
            if pdinv is None:
                gate = PRECOND_GATE_REASONS["folded"]
            else:
                jax.block_until_ready(pdinv)
                bundle = build_jacobi_bundle(
                    pdinv, setup_s=_time.monotonic() - t0)
        stamp_precond(res.extra, cfg, bundle=bundle, gate_reason=gate)

    if cfg.nrhs > 1:
        oracle_args = (None if device_setup
                       else (t, dm, bc_grid, b_host, G_host))
        return _finish_batched_bf16(cfg, res, n, op_lo, u, pdinv, conv,
                                    obs, oracle_args)
    if refine:
        oracle_args = (None if device_setup
                       else (t, dm, bc_grid, b_host, G_host))
        return _finish_refine(cfg, res, n, mesh, t, rule, geom, obs,
                              op_lo, pdinv, device_setup, b_host,
                              oracle_args)

    # Plain bf16: the f32 CG/action protocol verbatim on the bf16-stream
    # operator (bf16-class answers — refinement is the f64-class rung).
    cg_kind = ("cg+conv" if conv else "cg") if cfg.use_cg else "action"
    if pdinv is not None and cfg.use_cg:
        cg_kind += "+jacobi"
    cg_extra = (pdinv,) if (pdinv is not None and cfg.use_cg) else ()
    exec_key = _exec_cache_key(cfg, n, "unfused", cg_kind)
    _stamp_tuning(exec_key, res)
    fn = _exec_cache_get(cfg, exec_key, res)
    from_cache = fn is not None
    if fn is None:
        with obs.phase("compile"):
            if cfg.use_cg and pdinv is not None:
                fn = compile_lowered(jax.jit(
                    lambda A, b, x0, d: cg_solve(
                        A.apply, b, x0, cfg.nreps, capture=conv,
                        precond=lambda z: d * z)
                ).lower(op_lo, u, jnp.zeros_like(u), pdinv), None)
            elif cfg.use_cg:
                fn = compile_lowered(jax.jit(
                    lambda A, b, x0: cg_solve(
                        A.apply, b, x0, cfg.nreps, capture=conv)
                ).lower(op_lo, u, jnp.zeros_like(u)), None)
            else:
                def _action(A, x):
                    def _rep(i, y):
                        xx, _ = jax.lax.optimization_barrier((x, y))
                        return A.apply(xx)

                    return jax.lax.fori_loop(0, cfg.nreps, _rep,
                                             jnp.zeros_like(x))

                fn = compile_lowered(jax.jit(_action).lower(op_lo, u),
                                     None)
        _exec_cache_put(cfg, exec_key, fn, res)
    with obs.phase("transfer"):
        warm = (fn(op_lo, u, jnp.zeros_like(u), *cg_extra) if cfg.use_cg
                else fn(op_lo, u))
        _fence_scalar(warm)
        del warm

    y = obs.timed_reps(lambda: fn(op_lo, u, jnp.zeros_like(u), *cg_extra)
                       if cfg.use_cg else fn(op_lo, u))
    elapsed = obs.elapsed()
    conv_info = None
    if conv:
        y, conv_info = y

    res.mat_free_time = elapsed
    from ..la.vector import norm, norm_linf

    res.unorm = float(norm(u))
    res.ynorm = float(norm(y))
    res.unorm_linf = float(norm_linf(u))
    res.ynorm_linf = float(norm_linf(y))
    res.gdof_per_second = ndofs_global * cfg.nreps / (1e9 * elapsed)
    stamp_breakdown(res.extra, res.ynorm)
    stamp_observability(cfg, res, obs, "bf16")
    if conv_info is not None:
        stamp_convergence(res.extra, conv_info, wall_s=elapsed,
                          iters_run=cfg.nreps)

    if cfg.mat_comp:
        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        e = np.asarray(y, dtype=np.float64) - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def _finish_refine(cfg: BenchConfig, res: BenchmarkResults, n, mesh, t,
                   rule, geom, obs, op_lo, pdinv, device_setup, b_host,
                   oracle_args):
    """bf16-refine completion (ISSUE 17): the f64 outer residual
    correction around the bf16 inner CG (la.refine.refine_solve). The
    outer operator and RHS live in TRUE f64 — x64 is toggled on around
    this scope only (the bf16/f32 inner arrays are unaffected) — so the
    answer class is f64 while every hot-loop apply streams bf16. The
    warm solve pays every jit compile; the timed solve reuses them, and
    its RefineResult stamps the evidence block: inner/outer split, rel
    history, achieved rel, wall and time_to_rtol_s (the end-to-end
    adjudicator a cheaper-but-weaker precision must win), plus the
    combined inner+outer HBM byte model (obs.roofline, labelled
    design-estimate)."""
    import jax
    import jax.numpy as jnp

    from ..engines.registry import DEFAULT_REFINE_INNER_ITERS
    from ..la.refine import refine_solve

    # Tuning consumption (engines.autotune): a swept refine_inner_iters
    # beats the registry default; source/label/reason stamp either way.
    key = _exec_cache_key(cfg, n, "unfused", "cg+refine")
    tuned = _stamp_tuning(key, res)
    inner_iters = (int(tuned["refine_inner_iters"])
                   if tuned and tuned.get("refine_inner_iters")
                   else DEFAULT_REFINE_INNER_ITERS)

    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        with Timer("% Create matfree operator"):
            op_hi = build_laplacian(
                mesh, cfg.degree, cfg.qmode, rule, kappa=2.0,
                dtype=jnp.float64, tables=t,
                backend=res.extra["backend"],
            )
            if device_setup:
                from ..ops.kron import device_rhs_uniform

                b = jax.jit(
                    lambda: device_rhs_uniform(t, mesh.n, jnp.float64)
                )()
            else:
                b = jnp.asarray(b_host, jnp.float64)
        with obs.phase("compile"):
            # warm solve: pays the outer-residual / inner-correction /
            # axpy compiles so the timed solve below measures execution
            refine_solve(op_hi, op_lo, b, inner_iters=inner_iters,
                         dinv=pdinv)
        result = obs.timed_reps(lambda: refine_solve(
            op_hi, op_lo, b, inner_iters=inner_iters, dinv=pdinv))
        elapsed = obs.elapsed()

        res.mat_free_time = elapsed
        stamp = result.stamp()
        res.extra["refine"] = stamp
        if result.time_to_rtol_s is not None:
            res.extra["time_to_rtol_s"] = stamp["time_to_rtol_s"]
        from ..obs.roofline import refine_byte_model

        stamp["byte_model"] = refine_byte_model(
            family="kron" if res.extra["backend"] == "kron" else "xla",
            degree=cfg.degree, qmode=cfg.qmode, geom=geom,
            inner_iters_total=result.inner_iters_total,
            outer_iters=len(result.rel_history))

        from ..la.vector import norm, norm_linf

        res.unorm = float(norm(b))
        res.ynorm = float(norm(result.x))
        res.unorm_linf = float(norm_linf(b))
        res.ynorm_linf = float(norm_linf(result.x))
        # every apply is accounted: inner bf16 iterations + one hi
        # residual apply per outer check (len(rel_history))
        total_iters = result.inner_iters_total + len(result.rel_history)
        res.gdof_per_second = (
            res.ndofs_global * total_iters / (1e9 * elapsed))
        stamp_breakdown(res.extra, res.ynorm)
        stamp_observability(cfg, res, obs, "bf16")

        if cfg.mat_comp and oracle_args is not None:
            t_, dm, bc_grid, bh, G_host = oracle_args
            z = _mat_comp_oracle(cfg, t_, dm, bc_grid, bh, G_host)
            e = np.asarray(result.x, dtype=np.float64) - z
            res.znorm = float(np.linalg.norm(z))
            res.enorm = float(np.linalg.norm(e))
        return res
    finally:
        jax.config.update("jax_enable_x64", prev_x64)


def _finish_batched_bf16(cfg: BenchConfig, res: BenchmarkResults, n,
                         op_lo, u, pdinv, conv, obs, oracle_args):
    """Batched multi-RHS completion of the bf16 benchmark: the unfused
    vmapped bf16-stream apply through la.cg.cg_solve_batched (CG) or a
    vmapped apply inside the fenced rep loop (action). The bf16
    registry rows plan "unfused" always (no fused bf16 ring), recorded
    via BATCHED_UNFUSED_REASON like every other unfused batched branch;
    lane 0 runs the one-shot problem verbatim (scale 1.0)."""
    import jax
    import jax.numpy as jnp

    from ..la.cg import cg_solve_batched
    from ..la.vector import norm, norm_linf

    stamp_nrhs(res.extra, cfg.nrhs, cfg.checkpoint_every)
    record_engine(res.extra, False, error=BATCHED_UNFUSED_REASON)
    scales = jnp.asarray(batch_scales(cfg.nrhs), u.dtype)
    B = scales.reshape((-1,) + (1,) * u.ndim) * u[None]

    if cfg.use_cg and pdinv is not None:
        def run(A, Bv, d):
            return cg_solve_batched(A.apply, Bv, jnp.zeros_like(Bv),
                                    cfg.nreps, capture=conv,
                                    precond=lambda R: d[None] * R)
    elif cfg.use_cg:
        def run(A, Bv):
            return cg_solve_batched(A.apply, Bv, jnp.zeros_like(Bv),
                                    cfg.nreps, capture=conv)
    else:
        def run(A, Bv):
            def _rep(i, Y):
                BB, _ = jax.lax.optimization_barrier((Bv, Y))
                return jax.vmap(A.apply)(BB)

            return jax.lax.fori_loop(0, cfg.nreps, _rep,
                                     jnp.zeros_like(Bv))

    batch_extra = (pdinv,) if (pdinv is not None and cfg.use_cg) else ()
    batch_kind = ("cg+conv" if conv else "cg") if cfg.use_cg else "action"
    if batch_extra:
        batch_kind += "+jacobi"
    key = _exec_cache_key(cfg, n, "unfused", batch_kind)
    _stamp_tuning(key, res)
    fn = _exec_cache_get(cfg, key, res)
    from_cache = fn is not None
    if fn is None:
        with obs.phase("compile"):
            fn = compile_lowered(
                jax.jit(run).lower(op_lo, B, *batch_extra), None)
    if not from_cache:
        _exec_cache_put(cfg, key, fn, res)
    with obs.phase("transfer"):
        warm = fn(op_lo, B, *batch_extra)
        _fence_scalar(warm)
        del warm

    Y = obs.timed_reps(lambda: fn(op_lo, B, *batch_extra))
    elapsed = obs.elapsed()
    conv_info = None
    if conv:
        Y, conv_info = Y

    res.mat_free_time = elapsed
    y0 = Y[0]
    res.unorm = float(norm(u))
    res.ynorm = float(norm(y0))
    res.unorm_linf = float(norm_linf(u))
    res.ynorm_linf = float(norm_linf(y0))
    res.gdof_per_second = (
        res.ndofs_global * cfg.nreps * cfg.nrhs / (1e9 * elapsed))
    stamp_breakdown(res.extra, res.ynorm)
    stamp_observability(cfg, res, obs, "bf16")
    if conv_info is not None:
        stamp_convergence(res.extra, conv_info, wall_s=elapsed,
                          iters_run=cfg.nreps, nrhs=cfg.nrhs)

    if cfg.mat_comp and oracle_args is not None:
        t, dm, bc_grid, b_host, G_host = oracle_args
        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        e = np.asarray(y0, dtype=np.float64) - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def _run_benchmark(cfg: BenchConfig) -> BenchmarkResults:
    import jax
    import jax.numpy as jnp

    dtype = jnp.float64 if cfg.float_bits == 64 else jnp.float32

    if cfg.ndevices > 1:
        from ..dist.driver import run_distributed

        res = BenchmarkResults(nreps=cfg.nreps)
        return run_distributed(cfg, res, dtype)

    n, rule, t, mesh = _mesh_setup(cfg)
    backend = resolve_backend(cfg.backend, cfg.float_bits,
                              uniform=mesh.is_uniform, degree=cfg.degree,
                              qmode=cfg.qmode)
    ndofs_global = global_ndofs(n, cfg.degree)
    res = BenchmarkResults(
        ncells_global=mesh.ncells, ndofs_global=ndofs_global, nreps=cfg.nreps
    )
    res.extra["backend"] = backend
    # default engine record (the kron/folded branches below overwrite):
    # the xla backend has no fused engine form
    record_engine(res.extra, False)

    # Both fast paths build their RHS on device: the kron path from
    # separable 1D factors, the folded path from cell corners
    # (ops.folded_rhs) — no O(ndofs) host arrays in either. The host path
    # remains for the mat_comp oracle and the XLA fallback backend.
    device_setup = backend in ("kron", "pallas") and not cfg.mat_comp
    if not device_setup:
        # Host-side RHS/oracle setup (O(ndofs) host arrays; needed by the
        # mat_comp oracle and the general-geometry backends). Forward the
        # mesh/tables already built above — no duplicate setup.
        _, _, _, _, grid_shape, bc_grid, dm, b_host, G_host = _setup_problem(
            cfg, n, prebuilt=(n, rule, t, mesh)
        )

    folded = backend == "pallas"
    with Timer("% Create matfree operator"):
        if backend == "kron" and device_setup:
            # Uniform-mesh fast path: RHS built on device from separable 1D
            # factors (ops.kron.device_rhs_uniform) — no O(ndofs) host
            # arrays anywhere, so problem size is capped by HBM, not host
            # RAM (the reference's 300M-dofs-per-device configs fit).
            from ..ops.kron import device_rhs_uniform

            op = build_laplacian(
                mesh, cfg.degree, cfg.qmode, rule, kappa=2.0, dtype=dtype,
                tables=t, backend="kron",
            )
            u = jax.jit(
                lambda: device_rhs_uniform(t, mesh.n, dtype)
            )()
        elif folded:
            # The folded vector layout is the TPU fast path for general
            # geometry (see ops.folded): no per-apply gather/fold
            # transposes, ~2x the grid-layout rate. The ndevices>1 branch
            # above routes pallas runs through dist.folded the same way.
            from ..ops.folded import (
                build_folded_laplacian,
                fold_vector,
                ghost_corner_arrays,
            )

            op = build_folded_laplacian(
                mesh, cfg.degree, cfg.qmode, rule, kappa=2.0, dtype=dtype,
                tables=t,
            )
            if device_setup:
                # Device-side RHS from cell corners (ops.folded_rhs): the
                # perturbed-mesh analogue of the kron path's separable RHS.
                from ..ops.folded_rhs import device_rhs_folded

                ccs, mcs = ghost_corner_arrays(op.layout, mesh.cell_corners)
                u = jax.jit(
                    lambda c, m, bc: device_rhs_folded(
                        c, m, bc, op.layout, t, dtype
                    )
                )(jnp.asarray(ccs, dtype), jnp.asarray(mcs, dtype),
                  op.bc_mask)
            else:
                u = jnp.asarray(fold_vector(b_host.astype(dtype), op.layout))
        else:
            op = build_laplacian(
                mesh, cfg.degree, cfg.qmode, rule, kappa=2.0, dtype=dtype,
                tables=t, backend=backend,
            )
            u = jnp.asarray(b_host, dtype=dtype)
        if cfg.nrhs > 1:
            # Batched multi-RHS (the serving-layer shape): unfused
            # vmapped apply, batched dots, one executable for the whole
            # batch. The fused engines stay out of the loop (recorded).
            if folded:
                from ..ops.folded import pallas_plan

                res.extra["geom"] = "corner" if op.G is None else "g"
                batched_opts = scoped_vmem_options(pallas_plan(
                    cfg.degree, t.nq, np.dtype(dtype).itemsize)[2])
            else:
                batched_opts = None
            oracle_args = (None if device_setup
                           else (t, dm, bc_grid, b_host, G_host))
            return _finish_batched(cfg, res, n, op, u, folded,
                                   batched_opts, oracle_args)
        # AOT-compile outside the timed region (see module docstring). The
        # operator is a pytree *argument*, not a closure capture: closed-over
        # arrays become HLO constants, and the geometry tensor G (hundreds of
        # MB at benchmark sizes) must stay an HBM-resident parameter.
        # Folded operators have a fused benchmark engine (ops.folded_cg):
        # delay-ring single-view apply, in-kernel p-update/dots/bc — the
        # measured fast path. Valid because every CG/action vector here
        # descends from the RHS, whose bc rows are zero (homogeneous
        # Dirichlet). Falls back to apply_cg (multi-view fused kernel) when
        # the input ring would not fit VMEM.
        engine = False
        engine_cg = None  # fused (A, b) -> x solve, nreps baked in
        engine_apply = None  # fused (A, x) -> y single apply
        engine_cg_retry = None  # chunked-form retry after a Mosaic reject
        engine_apply_retry = None
        # Per-compile TPU options (utils.compilation): the folded
        # streamed-corner kernels (degrees 5-6) and the kron one-kernel
        # engine at large grids need a raised scoped-VMEM limit; every
        # other path compiles with none (a blanket raise measured a ~12%
        # flagship regression, MEASURE_r04.log A probe).
        compile_opts = None
        if folded:
            from ..ops.folded import pallas_plan
            from ..ops.folded_cg import (
                folded_apply_ring,
                folded_cg_solve,
                supports_cg_engine,
            )

            compile_opts = scoped_vmem_options(
                pallas_plan(cfg.degree, t.nq, np.dtype(dtype).itemsize)[2]
            )
            engine = supports_cg_engine(op)
            res.extra["geom"] = "corner" if op.G is None else "g"
            record_engine(res.extra, engine, "one_kernel")
            if engine:
                engine_cg = lambda A, b: folded_cg_solve(A, b, cfg.nreps)  # noqa: E731
                engine_apply = folded_apply_ring
        elif backend == "kron":
            # The kron path has its own fused engine (ops.kron_cg): one
            # delay-ring kernel per iteration instead of three stage kernels
            # plus unfused vector algebra. Pallas => TPU f32 only (same
            # auto rule as KronLaplacian.apply); VMEM gates the ring.
            from ..ops.kron_cg import (
                engine_plan,
                kron_apply_ring,
                kron_cg_solve,
                supports_kron_cg_engine,
            )

            engine = (
                jax.default_backend() == "tpu"
                and supports_kron_cg_engine(u.shape, cfg.degree, u.dtype)
            )
            form, kib = engine_plan(u.shape, cfg.degree)
            record_engine(res.extra, engine,
                          ENGINE_FORM_NAMES.get(form, form))
            if engine:
                compile_opts = scoped_vmem_options(kib)
                engine_cg = lambda A, b: kron_cg_solve(A, b, cfg.nreps)  # noqa: E731
                engine_apply = kron_apply_ring
                if form == "one":
                    # Near the VMEM budget line the estimate can admit a
                    # one-kernel form Mosaic then rejects; the chunked
                    # form (O(chunk) VMEM) is the right retry before
                    # giving up the engine entirely.
                    engine_cg_retry = lambda A, b: kron_cg_solve(  # noqa: E731
                        A, b, cfg.nreps, force_chunked=True)
                    engine_apply_retry = partial(
                        kron_apply_ring, force_chunked=True)
        unfused_apply = (
            (lambda A: A.apply_cg) if folded else (lambda A: A.apply)
        )
        # kron fallbacks (chunked retry / unfused) fit the default scoped
        # limit — compiling them with the raise would hand them the same
        # ~12% pipeline-headroom handicap the A probe measured; folded
        # fallbacks still run the streamed kernels and keep the request.
        fallback_opts = compile_opts if folded else None

        def _record_engine_failure(exc):
            record_engine(res.extra, False, error=exc)

        apply_fn = unfused_apply
        if engine:
            apply_fn = lambda A: partial(engine_apply, A)  # noqa: E731
        ckpt = cfg.use_cg and cfg.checkpoint_every > 0
        if cfg.sdc_audit and not ckpt:
            # the boundary audit rides the checkpointed loop (its
            # cadence IS the rollback cadence) — asking for it without
            # one records why it did not run, never silently
            res.extra["sdc_gate_reason"] = GATE_REASONS["sdc-no-checkpoint"]
        if ckpt and engine:
            # durable checkpointing needs iteration boundaries; the
            # fused whole-solve engines have none (CHECKPOINT_GATE_REASON)
            engine = False
            apply_fn = unfused_apply
            res.extra["checkpoint_gate_reason"] = CHECKPOINT_GATE_REASON
            record_engine(res.extra, False)
        # Convergence capture (ISSUE 10): the history buffer rides the
        # unfused la.cg loop; fused whole-solve engines gate off with
        # the reason recorded (same discipline as the checkpoint gate).
        conv = cfg.convergence and cfg.use_cg and not ckpt
        if cfg.convergence and cfg.use_cg and ckpt:
            conv = False
            res.extra["convergence_gate_reason"] = (
                GATE_REASONS["convergence-checkpoint"])
        if cfg.convergence and not cfg.use_cg:
            res.extra["convergence_gate_reason"] = (
                GATE_REASONS["convergence-action"])
        if conv and engine:
            engine = False
            apply_fn = unfused_apply
            res.extra["convergence_gate_reason"] = CONVERGENCE_GATE_REASON
            record_engine(res.extra, False)
        # Preconditioning + s-step routing (ISSUE 11). Resolution order:
        # action runs and checkpointed loops gate both features with
        # recorded reasons; precond wins over s-step when both are
        # requested (no communication-avoiding PCG form exists — the
        # combination is a recorded remainder); either feature routes a
        # fused engine to the unfused loop, checkpoint-gate style.
        pbundle = None
        sstep_on = False
        if cfg.precond != "none" or cfg.s_step > 1:
            from ..la.precond import PRECOND_GATE_REASONS
            from ..la.sstep import SSTEP_GATE_REASON

            if not cfg.use_cg:
                stamp_precond(res.extra, cfg,
                              gate_reason=(PRECOND_GATE_REASONS["action"]
                                           if cfg.precond != "none"
                                           else None))
                if cfg.s_step > 1:
                    res.extra["s_step_gate_reason"] = (
                        GATE_REASONS["sstep-action"])
            elif ckpt:
                stamp_precond(
                    res.extra, cfg,
                    gate_reason=(PRECOND_GATE_REASONS["checkpoint"]
                                 if cfg.precond != "none" else None))
                if cfg.s_step > 1:
                    res.extra["s_step_gate_reason"] = (
                        GATE_REASONS["sstep-checkpoint"])
            else:
                gate = None
                if cfg.precond != "none":
                    pbundle, gate = resolve_precond_bundle(cfg, op, u,
                                                           mesh=mesh)
                sstep_on = cfg.s_step > 1 and pbundle is None
                if cfg.s_step > 1 and pbundle is not None:
                    res.extra["s_step_gate_reason"] = (
                        GATE_REASONS["sstep-precond"])
                stamp_precond(res.extra, cfg, bundle=pbundle,
                              gate_reason=gate)
                if (pbundle is not None or sstep_on) and engine:
                    engine = False
                    apply_fn = unfused_apply
                    record_engine(res.extra, False)
                    res.extra.setdefault(
                        "precond_gate_reason" if pbundle is not None
                        else "s_step_gate_reason",
                        PRECOND_GATE_REASONS["engine"] if pbundle
                        is not None else GATE_REASONS["sstep-engine"])
        # Executable-cache key: the PLANNED engine form (what the plan
        # functions deterministically pick for this config), so a repeat
        # of the same config finds the executable its first compile
        # produced — even when that compile fell back (the fallback
        # executable is stored under the planned key, the final routing
        # stamps replay from the entry's meta). A capture-mode solve
        # lowers a DIFFERENT output signature (x, info) — its key must
        # never collide with the plain solve's.
        cg_extra = ()
        pfactory = None
        if pbundle is not None:
            # computed HERE (after all engine gating) so the chebyshev
            # factory closes over the apply that actually runs, and so
            # an exec-cache HIT still has its dinv argument list
            cg_extra, pfactory = precond_compile_form(pbundle, apply_fn)
        cg_kind = ("cg+conv" if conv else "cg") if cfg.use_cg else "action"
        if pbundle is not None:
            # a preconditioned executable's signature (extra dinv args,
            # different recurrence) must never collide with the bare one
            cg_kind += f"+{pbundle.kind}"
        if sstep_on:
            cg_kind += f"+s{cfg.s_step}"
        exec_key = _exec_cache_key(
            cfg, n, res.extra.get("cg_engine_form", "unfused"), cg_kind)
        tuned = _stamp_tuning(exec_key, res)
        if tuned and engine and tuned.get("window_kib"):
            # tuned scoped-VMEM window beats the plan's static estimate;
            # compile-option only, numerics untouched
            compile_opts = scoped_vmem_options(int(tuned["window_kib"]))
        obs = BenchObserver(cfg)
        run_ck = ck_store = ck_saves = ck_sdc = None
        ck_restored = 0
        if ckpt:
            # the iteration-boundary loop (bitwise cg_solve — the body
            # is verbatim) with durable snapshots at each boundary; the
            # warm-up pays compile/transfer without writing snapshots
            run_ck, ck_store, ck_restored, ck_saves, ck_sdc = (
                _make_checkpointed_cg(cfg, res, obs, op, apply_fn, u,
                                      fallback_opts))
            with obs.phase("transfer"):
                warm = run_ck(save=False)
        elif cfg.use_cg:
            fn = _exec_cache_get(cfg, exec_key, res)
            from_cache = fn is not None
            if fn is None and engine:
                # A Mosaic rejection of the fused engine (e.g. a VMEM or
                # lowering limit this config's estimates missed) must not
                # sink the benchmark: retry the chunked form when the
                # first pick was the one-kernel form, then fall back to
                # the unfused path, recording why. Compile errors only —
                # execution errors propagate (a fallback there could mask
                # wrong results).
                def _compile_cg(cg, opts):
                    with obs.phase("compile"):
                        return compile_lowered(jax.jit(
                            lambda A, b, x0: cg(A, b)
                        ).lower(op, u, jnp.zeros_like(u)), opts)

                try:
                    fn = _compile_cg(engine_cg, compile_opts)
                except Exception as exc:
                    if engine_cg_retry is not None:
                        try:
                            fn = _compile_cg(engine_cg_retry, fallback_opts)
                            res.extra["cg_engine_form"] = "chunked"
                            # keep the one-kernel rejection too: the scoped
                            # VMEM tiers are hardware-calibrated estimates,
                            # and a drifted tier boundary is only
                            # diagnosable from the first failure's text
                            res.extra["cg_engine_one_kernel_error"] = (
                                exc_str(exc)
                            )
                        except Exception as exc2:
                            engine = False
                            _record_engine_failure(exc)
                            res.extra["cg_engine_retry_error"] = (
                                exc_str(exc2)
                            )
                    else:
                        engine = False
                        _record_engine_failure(exc)
                    if not engine:
                        apply_fn = unfused_apply
            if fn is None:
                with obs.phase("compile"):
                    if sstep_on:
                        from ..la.sstep import sstep_cg_solve

                        fn = compile_lowered(jax.jit(
                            lambda A, b, x0: sstep_cg_solve(
                                apply_fn(A), b, x0, cfg.nreps,
                                cfg.s_step, capture=conv)
                        ).lower(op, u, jnp.zeros_like(u)), fallback_opts)
                    elif pbundle is not None:
                        fn = compile_lowered(jax.jit(
                            lambda A, b, x0, *ps: cg_solve(
                                apply_fn(A), b, x0, cfg.nreps,
                                capture=conv, precond=pfactory(A, *ps))
                        ).lower(op, u, jnp.zeros_like(u), *cg_extra),
                            fallback_opts)
                    else:
                        fn = compile_lowered(jax.jit(
                            lambda A, b, x0: cg_solve(
                                apply_fn(A), b, x0, cfg.nreps,
                                capture=conv)
                        ).lower(op, u, jnp.zeros_like(u)), fallback_opts)
            if not from_cache:
                _exec_cache_put(cfg, exec_key, fn, res)
            with obs.phase("transfer"):
                warm = fn(op, u, jnp.zeros_like(u), *cg_extra)
        else:
            # All nreps applies in one jitted fori_loop: same semantics as
            # the reference's per-rep launches (y = A u each rep, same input,
            # laplacian_solver.cpp:119-127) but with no host dispatch in the
            # timed region — the reference's launch cost is ~us, while a
            # host round-trip through the axon tunnel is ~60 ms and would
            # measure the tunnel, not the operator. The optimization_barrier
            # ties the apply's input to the loop carry so no present or
            # future XLA pass can hoist the loop-invariant apply out of the
            # timed loop (a zero-cost compiler fence, no data movement).
            def _rep(i, y, A, x, af):
                xx, _ = jax.lax.optimization_barrier((x, y))
                return af(A)(xx)

            def _compile_action(af, opts):
                with obs.phase("compile"):
                    return compile_lowered(jax.jit(
                        lambda A, x: jax.lax.fori_loop(
                            0, cfg.nreps, partial(_rep, A=A, x=x, af=af),
                            jnp.zeros_like(x),
                        )
                    ).lower(op, u), opts)

            fn = _exec_cache_get(cfg, exec_key, res)
            if fn is None:
                try:
                    fn = _compile_action(apply_fn, compile_opts)
                except Exception as exc:
                    if not engine:  # nothing to fall back to
                        raise
                    # engine apply failed to compile: chunked retry, then
                    # unfused fallback (same rationale as the CG branch
                    # above)
                    fn = None
                    if engine_apply_retry is not None:
                        try:
                            fn = _compile_action(
                                lambda A: partial(engine_apply_retry, A),
                                fallback_opts)
                            res.extra["cg_engine_form"] = "chunked"
                            res.extra["cg_engine_one_kernel_error"] = (
                                exc_str(exc)
                            )
                        except Exception as exc2:
                            res.extra["cg_engine_retry_error"] = (
                                exc_str(exc2)
                            )
                    if fn is None:
                        engine = False
                        _record_engine_failure(exc)
                        fn = _compile_action(unfused_apply, fallback_opts)
                _exec_cache_put(cfg, exec_key, fn, res)
            with obs.phase("transfer"):
                warm = fn(op, u)
        # One warm-up execution (fenced, attributed to the "transfer"
        # phase — it pays the one-time transfer/initialisation costs):
        # it runs the full nreps computation because a cheaper 1-rep
        # warm-up would need a second full compile (tens of seconds) to
        # save a few seconds of device time — net slower at every
        # benchmark size we run.
        with obs.phase("transfer"):
            _fence_scalar(warm)
            del warm

    if run_ck is not None:
        y = obs.timed_reps(run_ck)
    else:
        y = obs.timed_reps(lambda: fn(op, u, jnp.zeros_like(u), *cg_extra)
                           if cfg.use_cg else fn(op, u))
    elapsed = obs.elapsed()
    conv_info = None
    if sstep_on:
        # s-step solves always return (x, info); a breakdown (monomial
        # Gram projection went non-SPD) falls back GRACEFULLY to the
        # standard recurrence with the reason recorded — never a silent
        # half-converged answer
        y, ss_info = y
        if bool(np.asarray(ss_info["breakdown"])):
            from ..la.sstep import SSTEP_FALLBACK_REASON

            res.extra["s_step_fallback_reason"] = SSTEP_FALLBACK_REASON
            with obs.phase("compile"):
                fn = compile_lowered(jax.jit(
                    lambda A, b, x0: cg_solve(apply_fn(A), b, x0,
                                              cfg.nreps, capture=conv)
                ).lower(op, u, jnp.zeros_like(u)), fallback_opts)
            with obs.phase("transfer"):
                warm = fn(op, u, jnp.zeros_like(u))
                _fence_scalar(warm)
                del warm
            y = obs.timed_reps(lambda: fn(op, u, jnp.zeros_like(u)))
            elapsed = obs.elapsed()
            if conv:
                y, conv_info = y
        elif conv:
            conv_info = ss_info
    elif conv:
        # convergence-captured solve: (x, info) — the history is
        # fetched HERE, once, outside the timed region (conv implies
        # the unfused capture loop compiled above; ckpt forces conv off)
        y, conv_info = y

    res.mat_free_time = elapsed
    from ..la.vector import norm, norm_linf

    res.unorm = float(norm(u))
    res.ynorm = float(norm(y))
    res.unorm_linf = float(norm_linf(u))
    res.ynorm_linf = float(norm_linf(y))
    # a restored run only executed the REMAINING iterations: its rate
    # must not be credited with the snapshot's pre-crash work
    iters_timed = cfg.nreps - (ck_restored if run_ck is not None else 0)
    res.gdof_per_second = ndofs_global * iters_timed / (1e9 * elapsed)
    stamp_breakdown(res.extra, res.ynorm)
    if run_ck is not None:
        stamp_checkpoint(res.extra, cfg, ck_store, ck_restored,
                         ck_saves["n"])
        stamp_sdc(res.extra, ck_sdc)
    stamp_observability(cfg, res, obs,
                        "f32" if cfg.float_bits == 32 else "f64")
    if conv_info is not None:
        stamp_convergence(res.extra, conv_info, wall_s=elapsed,
                          iters_run=cfg.nreps)

    if cfg.mat_comp:
        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        if folded:
            from ..ops.folded import unfold_vector

            y = unfold_vector(np.asarray(y), op.layout)
        e = np.asarray(y, dtype=np.float64) - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host) -> np.ndarray:
    """Assembled-CSR comparison path (laplacian_solver.cpp:151-227): same
    number of operator applications or CG iterations through scipy CSR."""
    from ..fem import native
    from ..fem.assemble import csr_cg_reference

    use_native = native.available()
    with Timer("% Assemble CSR (oracle)"):
        if use_native:
            A = native.assemble_csr(t, G_host, 2.0, dm, bc_grid.ravel())
        else:
            A = assemble_csr(
                element_stiffness_matrices(t, G_host, 2.0), dm, bc_grid.ravel()
            )
    u = b_host.ravel()
    with Timer("% CSR Matvec"):
        if cfg.use_cg:
            z = native.csr_cg(A, u, cfg.nreps) if use_native else csr_cg_reference(A, u, cfg.nreps)
        else:
            z = native.csr_spmv(A, u) if use_native else A @ u
    return z.reshape(b_host.shape)


def _run_benchmark_form(cfg: BenchConfig) -> BenchmarkResults:
    """Operator-zoo driver (ISSUE 20): run a forms.registry weak form —
    mass (L2 projection), helmholtz (stiffness - k^2 mass, the first
    non-SPD operator in the suite), varkappa (variable-coefficient
    diffusion), heat (mass + dt stiffness) — through the general
    sum-factorised form action (forms.operators) on the single-chip
    unfused XLA path, with the SAME protocol as the flagship driver:
    AOT compile outside the timed region, operator as a pytree
    argument, fenced warm-up, and the assembled-CSR oracle behind
    --mat_comp (fem.assemble.element_form_matrices — full 3D tables,
    never the 1D factorised chain).

    CG runs always carry the breakdown sentinels (la.cg sentinel=True):
    helmholtz is genuinely indefinite at the registry shift, and the
    sentinel counters + failure_class taxonomy are how a breakdown is
    CLASSIFIED instead of crashing or shipping NaN. Unsupported feature
    combinations raise (df32/bf16/sharded/batched/backend) or record
    (checkpoint/s-step/precond) their REGISTERED form-* gate reasons —
    never a silent fallback."""
    import jax
    import jax.numpy as jnp

    from ..fem.assemble import csr_cg_reference, element_form_matrices
    from ..forms.operators import build_form_operator, kappa_at_quadrature
    from ..forms.registry import form_spec

    fspec = form_spec(cfg.form)  # unknown form -> ValueError (vocabulary)
    if cfg.float_bits == 64 and cfg.f64_impl == "df32":
        raise ValueError(gate_reason("form-df", form=cfg.form))
    if cfg.precision != "auto":
        raise ValueError(gate_reason("form-bf16", form=cfg.form))
    if cfg.ndevices > 1:
        raise ValueError(gate_reason("form-sharded", form=cfg.form))
    if cfg.nrhs > 1:
        raise ValueError(gate_reason("form-batched", form=cfg.form))
    if cfg.backend not in ("auto", "xla"):
        raise ValueError(gate_reason("form-backend", form=cfg.form,
                                     backend=cfg.backend))

    dtype = jnp.float64 if cfg.float_bits == 64 else jnp.float32
    n, rule, t, mesh = _mesh_setup(cfg)
    ndofs_global = global_ndofs(n, cfg.degree)
    res = BenchmarkResults(
        ncells_global=mesh.ncells, ndofs_global=ndofs_global,
        nreps=cfg.nreps)
    res.extra["backend"] = "xla"
    res.extra["form"] = cfg.form
    record_engine(res.extra, False)
    if cfg.checkpoint_every > 0 or cfg.sdc_audit:
        res.extra["checkpoint_gate_reason"] = gate_reason(
            "form-checkpoint", form=cfg.form)
    if cfg.s_step > 1:
        res.extra["s_step"] = int(cfg.s_step)
        res.extra["s_step_gate_reason"] = gate_reason("form-sstep",
                                                      form=cfg.form)
    if cfg.precond != "none":
        stamp_precond(res.extra, cfg, gate_reason=(
            gate_reason("helmholtz-precond") if cfg.form == "helmholtz"
            else gate_reason("form-precond", form=cfg.form)))

    # Host setup, kept local instead of _setup_problem: the form oracle
    # needs wdetJ (the mass chain) next to G (the stiffness chain).
    grid_shape = dof_grid_shape(n, cfg.degree)
    bc_grid = boundary_dof_marker(n, cfg.degree)
    with Timer("% Assemble RHS (host)"):
        coords = dof_coordinates(mesh.vertices, cfg.degree, t.nodes1d)
        f = default_source(coords).ravel()
        dm = cell_dofmap(n, cfg.degree)
        corners = mesh.cell_corners.reshape(-1, 2, 2, 2, 3)
        bc_flat = bc_grid.ravel()
        G_host, wdetJ = geometry_factors(
            corners, t.pts1d, t.wts1d,
            compute_G=cfg.mat_comp and fspec.grad_coeff != 0.0)
        b_host = assemble_rhs(t, wdetJ, dm, f, bc_flat).reshape(grid_shape)

    obs = BenchObserver(cfg)
    with Timer("% Create matfree operator"):
        op = build_form_operator(mesh, fspec, cfg.degree, cfg.qmode,
                                 rule, dtype=dtype, tables=t)
        u = jnp.asarray(b_host, dtype=dtype)

    nreps = cfg.nreps
    conv = cfg.convergence and cfg.use_cg
    if cfg.use_cg:
        def run(A, b, x0):
            return cg_solve(A.apply, b, x0, nreps, sentinel=True,
                            capture=conv)

        with obs.phase("compile"):
            fn = compile_lowered(
                jax.jit(run).lower(op, u, jnp.zeros_like(u)), None)
        with obs.phase("transfer"):
            warm = fn(op, u, jnp.zeros_like(u))
            _fence_scalar(warm)
            del warm
        y = obs.timed_reps(lambda: fn(op, u, jnp.zeros_like(u)))
    else:
        def run(A, x):
            def _rep(i, y):
                xx, _ = jax.lax.optimization_barrier((x, y))
                return A.apply(xx)

            return jax.lax.fori_loop(0, nreps, _rep, jnp.zeros_like(x))

        with obs.phase("compile"):
            fn = compile_lowered(jax.jit(run).lower(op, u), None)
        with obs.phase("transfer"):
            warm = fn(op, u)
            _fence_scalar(warm)
            del warm
        y = obs.timed_reps(lambda: fn(op, u))
    elapsed = obs.elapsed()
    if cfg.use_cg:
        y, info = y
        # the sentinel verdicts are the helmholtz taxonomy evidence:
        # restarts counted, a non-finite residual freezing the state is
        # classified `breakdown` below (stamp_breakdown), never NaN out
        res.extra["cg_sentinel"] = {
            "breakdown_restarts": int(np.asarray(
                info["breakdown_restarts"])),
            "nonfinite": bool(np.asarray(info["nonfinite"])),
            "stag_max": int(np.asarray(info["stag_max"]))}
        if conv:
            stamp_convergence(res.extra, info, wall_s=elapsed,
                              iters_run=nreps)

    res.mat_free_time = elapsed
    from ..la.vector import norm, norm_linf

    res.unorm = float(norm(u))
    res.ynorm = float(norm(y))
    res.unorm_linf = float(norm_linf(u))
    res.ynorm_linf = float(norm_linf(y))
    res.gdof_per_second = ndofs_global * nreps / (1e9 * elapsed)
    stamp_breakdown(res.extra, res.ynorm)
    stamp_observability(cfg, res, obs,
                        "f32" if cfg.float_bits == 32 else "f64")

    if cfg.mat_comp:
        kq = (kappa_at_quadrature(corners, t.pts1d)
              if fspec.coefficient == "varkappa" else None)
        with Timer("% Assemble CSR (oracle)"):
            elem = element_form_matrices(t, G_host, wdetJ,
                                         fspec.grad_coeff,
                                         fspec.mass_coeff, kq=kq)
            A = assemble_csr(elem, dm, bc_flat)
        ub = b_host.ravel()
        with Timer("% CSR Matvec"):
            z = (csr_cg_reference(A, ub, cfg.nreps) if cfg.use_cg
                 else A @ ub)
        e = np.asarray(y, dtype=np.float64).ravel() - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res
