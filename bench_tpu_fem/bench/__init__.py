"""Benchmark orchestration (layer L6): build the problem, time the operator
or CG, verify against the CSR oracle, report results.

Replaces `laplace_action_gpu/cpu` (/root/reference/src/laplacian_solver.cpp)
and the JSON assembly in main.cpp:122-132."""

from .driver import BenchConfig, BenchmarkResults, run_benchmark
from .reporting import banner, results_json

__all__ = ["BenchConfig", "BenchmarkResults", "run_benchmark", "banner", "results_json"]
