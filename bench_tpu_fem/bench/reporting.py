"""Banner and JSON output matching the reference contract
(/root/reference/src/main.cpp:242-270, 296-307)."""

from __future__ import annotations

import json

from .driver import BenchConfig, BenchmarkResults


def banner(cfg: BenchConfig, device_info: str) -> str:
    lines = [
        device_info,
        "-----------------------------------",
        f"Platform: {cfg.platform}",
        f"Polynomial degree : {cfg.degree}",
        f"Number of devices : {cfg.ndevices}",
        f"Requested number of global DoFs : {cfg.ndofs_global}",
        f"Number of repetitions : {cfg.nreps}",
        f"Scalar Type: {cfg.float_bits}",
        f"Use Gauss-Jacobi: {int(cfg.use_gauss)}",
        f"Compare to matrix: {int(cfg.mat_comp)}",
        "-----------------------------------",
    ]
    return "\n".join(lines)


def results_json(cfg: BenchConfig, res: BenchmarkResults) -> str:
    """Same two-level {"input": ..., "output": ...} schema as the reference
    (main.cpp:262-270 for input echo, main.cpp:122-132 for output)."""
    root = {
        "input": {
            "p": cfg.degree,
            "ndevices": cfg.ndevices,
            "ndofs_local_requested": cfg.ndofs_global // max(cfg.ndevices, 1),
            "nreps": cfg.nreps,
            "scalar_size": cfg.float_bits,
            "use_gauss": cfg.use_gauss,
            "mat_comp": cfg.mat_comp,
            "qmode": cfg.qmode,
            "cg": cfg.use_cg,
            "nrhs": cfg.nrhs,
        },
        "output": {
            "ncells_global": res.ncells_global,
            "ndofs_global": res.ndofs_global,
            "mat_free_time": res.mat_free_time,
            "u_norm": res.unorm,
            "y_norm": res.ynorm,
            "u_norm_linf": res.unorm_linf,
            "y_norm_linf": res.ynorm_linf,
            "z_norm": res.znorm,
            "gdof_per_second": res.gdof_per_second,
        },
    }
    if cfg.nrhs > 1:
        # batched artifact stamp: GDoF/s above accounts the whole batch
        # (ndofs * nreps * nrhs / t); the bucket is the serve cache's
        # padding class for this batch size
        root["output"]["nrhs"] = res.extra.get("nrhs", cfg.nrhs)
        root["output"]["nrhs_bucket"] = res.extra.get("nrhs_bucket")
    # observability stamps (ISSUE 8): attribution rides on every record
    # — roofline placement, peak device memory, span-attributed phase
    # shares and the per-rep timing distribution (each carries its own
    # evidence label; see obs/)
    for key in ("roofline", "peak_memory_bytes", "memory", "phase_s",
                "phase_share", "timing"):
        if key in res.extra:
            root["output"][key] = res.extra[key]
    # fault-tolerance stamp (ISSUE 9): checkpoint cadence/saves/restore
    # provenance + evidence label, and why checkpointing was gated or a
    # snapshot was not restored — the record must say what recovered
    for key in ("checkpoint", "checkpoint_gate_reason",
                "checkpoint_restore_skipped", "checkpoint_restore_error"):
        if key in res.extra:
            root["output"][key] = res.extra[key]
    # convergence stamp (ISSUE 10): the folded residual-history block +
    # the paired time-to-rtol metric next to gdof_per_second, or the
    # recorded reason capture was gated off
    for key in ("convergence", "time_to_rtol_s",
                "convergence_gate_reason", "convergence_error"):
        if key in res.extra:
            root["output"][key] = res.extra[key]
    # preconditioning + s-step stamps (ISSUE 11): what ran, what it
    # cost to build, and why a request was gated or fell back
    for key in ("precond", "precond_gate_reason", "s_step",
                "s_step_gate_reason", "s_step_fallback_reason"):
        if key in res.extra:
            root["output"][key] = res.extra[key]
    # SDC defense stamp (ISSUE 14): boundary-audit verdicts (checks,
    # worst clean drift vs envelope, injections, detections, rollback
    # adjudication) or the recorded reason the audit was gated off
    for key in ("sdc", "sdc_gate_reason"):
        if key in res.extra:
            root["output"][key] = res.extra[key]
    # tuning stamp (ISSUE 16): which build parameters ran — source=db
    # with the entry's evidence label and round, or source=default with
    # the registered fallback reason (never silent defaults)
    if "tuning" in res.extra:
        root["output"]["tuning"] = res.extra["tuning"]
    # mixed-precision ladder stamps (ISSUE 17): which precision rung
    # ran, the refinement evidence block (inner/outer split, rel
    # history, per-precision byte model) or the registered reason
    # refinement/bf16 was gated or demoted on this config
    for key in ("precision", "refine", "refine_gate_reason",
                "bf16_gate_reason"):
        if key in res.extra:
            root["output"][key] = res.extra[key]
    return json.dumps(root)
