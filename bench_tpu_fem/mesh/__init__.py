"""Structured box hex mesh, tensor-product dofmap and boundary data (layer L1).

Replaces DOLFINx mesh creation/partitioning and dofmap machinery used by the
reference (/root/reference/src/mesh.cpp). Because the domain is a structured
unit-cube box of hexahedra, vertex coordinates, cell connectivity, dofmaps and
boundary-dof markers are all closed-form — there is no graph partitioner; the
distributed layer (bench_tpu_fem.dist) uses a block partition of the cell grid.
"""

from .sizing import compute_mesh_size
from .box import BoxMesh, create_box_mesh
from .dofmap import (
    cell_dofmap,
    dof_grid_shape,
    boundary_dof_marker,
    dof_coordinates,
    global_ncells,
    global_ndofs,
)

__all__ = [
    "compute_mesh_size",
    "BoxMesh",
    "create_box_mesh",
    "cell_dofmap",
    "dof_grid_shape",
    "boundary_dof_marker",
    "dof_coordinates",
    "global_ncells",
    "global_ndofs",
]
