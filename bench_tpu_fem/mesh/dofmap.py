"""Closed-form tensor-product dofmap for degree-P Lagrange on a box mesh.

Replaces the DOLFINx dofmap/IndexMap machinery the reference leans on
(`V.dofmap()->map()` shipped to the device in
/root/reference/src/laplacian.hpp:106-113, built in tensor-product order via
`basix::create_tp_element` / `tp_dof_ordering`, mesh.cpp:90-94).

Dofs live on a grid of shape (nx*P+1, ny*P+1, nz*P+1); the dof at grid point
(gx, gy, gz) has id gx*NY*NZ + gy*NZ + gz (row-major). Cell (cx, cy, cz)
owns the (P+1)^3 dofs at grid points (cx*P + i, cy*P + j, cz*P + k), in
lexicographic local order — the 1D element nodes are the *sorted* GLL points,
so grid position along each axis is also the 1D node index.
"""

from __future__ import annotations

import numpy as np


def dof_grid_shape(n: tuple[int, int, int], degree: int) -> tuple[int, int, int]:
    return tuple(int(ni) * degree + 1 for ni in n)


def global_ndofs(n: tuple[int, int, int], degree: int) -> int:
    """Exact global dof count as a Python int. The weak-scaling sweep
    crosses 2^31 global dofs (billions at pod scale), where a numpy
    product can silently wrap on platforms whose default integer is
    int32 — every driver/artifact dof count routes through this instead
    of `np.prod(dof_grid_shape(...))`."""
    out = 1
    for s in dof_grid_shape(n, degree):
        out *= int(s)
    return out


def global_ncells(n: tuple[int, int, int]) -> int:
    """Exact global cell count as a Python int (same overflow rationale
    as global_ndofs)."""
    out = 1
    for ni in n:
        out *= int(ni)
    return out


def cell_dofmap(n: tuple[int, int, int], degree: int) -> np.ndarray:
    """(ncells, (P+1)^3) int32 dofmap; cells in (cx, cy, cz) row-major order,
    local dofs in (i, j, k) row-major order."""
    nx, ny, nz = n
    NX, NY, NZ = dof_grid_shape(n, degree)
    nd = degree + 1
    # int64 throughout: numpy's default integer is int32 on some
    # platforms, and the per-term products (gy * NZ, gx * NY * NZ) can
    # wrap before the final promotion at > 2^31 global dofs
    ar = lambda k: np.arange(k, dtype=np.int64)  # noqa: E731
    gx = (ar(nx) * degree)[:, None] + ar(nd)[None, :]  # (nx, nd)
    gy = (ar(ny) * degree)[:, None] + ar(nd)[None, :]
    gz = (ar(nz) * degree)[:, None] + ar(nd)[None, :]
    # dof id = gx*NY*NZ + gy*NZ + gz, broadcast to (nx,ny,nz,nd,nd,nd)
    ids = (
        gx[:, None, None, :, None, None] * np.int64(NY * NZ)
        + gy[None, :, None, None, :, None] * np.int64(NZ)
        + gz[None, None, :, None, None, :]
    )
    if ids.max() > np.iinfo(np.int32).max:
        raise ValueError("dof ids exceed int32 range")
    return ids.reshape(nx * ny * nz, nd * nd * nd).astype(np.int32)


def boundary_dof_marker(n: tuple[int, int, int], degree: int) -> np.ndarray:
    """(NX, NY, NZ) bool grid marking dofs on the exterior boundary of the
    cube (homogeneous Dirichlet on all exterior facets, as located in
    /root/reference/src/main.cpp:94-102)."""
    NX, NY, NZ = dof_grid_shape(n, degree)
    marker = np.zeros((NX, NY, NZ), dtype=bool)
    marker[0, :, :] = marker[-1, :, :] = True
    marker[:, 0, :] = marker[:, -1, :] = True
    marker[:, :, 0] = marker[:, :, -1] = True
    return marker


def dof_coordinates(
    vertices: np.ndarray, degree: int, nodes1d: np.ndarray
) -> np.ndarray:
    """(NX, NY, NZ, 3) physical coordinates of every dof grid point, obtained
    by pushing the reference element nodes through each cell's trilinear map.

    Equivalent to DOLFINx's interpolation-point pushforward used by
    `f->interpolate` (/root/reference/src/main.cpp:81-92). Grid points shared
    between neighbouring cells get identical coordinates from either side
    (the trilinear map is continuous across faces), so attributing each grid
    point to the lower-index cell is exact.
    """
    P = degree
    n = tuple(s - 1 for s in vertices.shape[:3])
    t = np.asarray(nodes1d, dtype=np.float64)  # (P+1,) reference nodes

    def axis_split(N_axis: int, ncells_axis: int):
        g = np.arange(N_axis)
        c = np.minimum(g // P, ncells_axis - 1)
        w = t[g - c * P]  # local reference coordinate in [0, 1]
        return c, w

    cx, wx = axis_split(n[0] * P + 1, n[0])
    cy, wy = axis_split(n[1] * P + 1, n[1])
    cz, wz = axis_split(n[2] * P + 1, n[2])

    out = np.zeros((len(cx), len(cy), len(cz), 3), dtype=vertices.dtype)
    for a in (0, 1):
        fx = (wx if a else 1.0 - wx)[:, None, None, None]
        for b in (0, 1):
            fy = (wy if b else 1.0 - wy)[None, :, None, None]
            for c in (0, 1):
                fz = (wz if c else 1.0 - wz)[None, None, :, None]
                corner = vertices[np.ix_(cx + a, cy + b, cz + c)]
                out += fx * fy * fz * corner
    return out
