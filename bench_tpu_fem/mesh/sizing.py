"""Mesh sizing: pick (nx, ny, nz) to hit a requested global dof count.

Behavioural parity with `benchdolfinx::compute_mesh_size`
(/root/reference/src/mesh.cpp:117-152): start from the cube-root estimate and
brute-force search +/-5 cells in each direction for the best fit of
(nx*p+1)(ny*p+1)(nz*p+1) to ndofs_global.

int32-overflow audit (ISSUE 7 — the weak-scaling sweep crosses 2^31
global dofs): every intermediate here is either a Python int (arbitrary
precision) or an explicitly `int64` numpy array — the candidate arrays
pin `dtype=np.int64` rather than trusting numpy's platform-default
integer (int32 on some hosts), so the (ndx*ndy*ndz - ndofs_global)
misfit stays exact at multi-billion-dof targets (regression-tested to
19B dofs in tests/test_overlap_cg.py). Exact dof/cell COUNTS for
drivers/artifacts live in mesh.dofmap.global_ndofs/global_ncells.
"""

from __future__ import annotations

import numpy as np


def compute_mesh_size(
    ndofs_global: int, degree: int, dshape: tuple[int, int, int] = (1, 1, 1)
) -> tuple[int, int, int]:
    """With dshape != (1,1,1), cell counts are constrained to multiples of the
    device-mesh shape so the distributed block partition divides evenly; the
    search is the same +/-5-steps-per-axis scan, in device-mesh strides."""
    nx_approx = (ndofs_global ** (1.0 / 3.0) - 1.0) / degree
    n0 = int(nx_approx + 0.5)

    def candidates(d: int) -> np.ndarray:
        # Sharded axes need >= 2 cell layers per shard: the halo protocols
        # (dist.kron P-plane exchange, dist.folded ghost columns) exchange
        # owned-interior data that a 1-cell-deep shard does not have.
        lo = 2 * d if d > 1 else d
        base = max(lo, round(max(1, n0) / d) * d)
        return np.array(sorted({max(lo, base + k * d) for k in range(-5, 6)}), dtype=np.int64)

    cx, cy, cz = (candidates(d) for d in dshape)
    ndx, ndy, ndz = (c * degree + 1 for c in (cx, cy, cz))
    misfit = np.abs(
        ndx[:, None, None] * ndy[None, :, None] * ndz[None, None, :] - ndofs_global
    )
    if dshape == (1, 1, 1):
        # Match the reference's scan order (first strict improvement over the
        # initial (n0, n0, n0) guess wins; ties keep the guess).
        best0 = abs((n0 * degree + 1) ** 3 - ndofs_global)
        flat = misfit.reshape(-1)
        idx = int(np.argmin(flat))
        if flat[idx] >= best0:
            return (n0, n0, n0)
    else:
        idx = int(np.argmin(misfit.reshape(-1)))
    i, j, k = np.unravel_index(idx, misfit.shape)
    return (int(cx[i]), int(cy[j]), int(cz[k]))
