"""Mesh sizing: pick (nx, ny, nz) to hit a requested global dof count.

Behavioural parity with `benchdolfinx::compute_mesh_size`
(/root/reference/src/mesh.cpp:117-152): start from the cube-root estimate and
brute-force search +/-5 cells in each direction for the best fit of
(nx*p+1)(ny*p+1)(nz*p+1) to ndofs_global.
"""

from __future__ import annotations

import numpy as np


def compute_mesh_size(ndofs_global: int, degree: int) -> tuple[int, int, int]:
    nx_approx = (ndofs_global ** (1.0 / 3.0) - 1.0) / degree
    n0 = int(nx_approx + 0.5)
    lo = max(1, n0 - 5)
    cand = np.arange(lo, n0 + 6, dtype=np.int64)
    ndofs_1d = cand * degree + 1
    misfit = np.abs(
        ndofs_1d[:, None, None] * ndofs_1d[None, :, None] * ndofs_1d[None, None, :]
        - ndofs_global
    )
    best0 = (n0 * degree + 1) ** 3 - ndofs_global
    best = (n0, n0, n0)
    # Match the reference's scan order (first strict improvement wins).
    flat = misfit.reshape(-1)
    idx = int(np.argmin(flat))
    if flat[idx] < abs(best0):
        i, j, k = np.unravel_index(idx, misfit.shape)
        best = (int(cand[i]), int(cand[j]), int(cand[k]))
    return best
