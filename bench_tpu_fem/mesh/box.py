"""Structured hexahedral mesh of the unit cube.

Replaces `dolfinx::mesh::create_box` + the vertex-ghost-layer repartition
(/root/reference/src/mesh.cpp:190-218, 26-114). Vertices live on an
(nx+1, ny+1, nz+1) grid; cell (cx, cy, cz) has its 8 corners at grid points
(cx+a, cy+b, cz+c). The optional geometry perturbation randomly shifts vertex
x-coordinates by up to `fact * (1/nx)` with a fixed seed (mesh.cpp:199-207) —
it exists to harden correctness checks against accidentally-regular geometry.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

import numpy as np


@dataclass(frozen=True)
class BoxMesh:
    n: tuple[int, int, int]  # cells per direction
    vertices: np.ndarray  # (nx+1, ny+1, nz+1, 3) float64 vertex coordinates
    # True iff the mesh is the unperturbed axis-aligned uniform grid; uniform
    # geometry makes the operator an exact Kronecker sum of 1D matrices
    # (see ops.kron), which is the single-chip fast path. Defaults to False
    # so a mesh built from arbitrary vertices must opt in explicitly.
    is_uniform: bool = False

    @property
    def ncells(self) -> int:
        return self.n[0] * self.n[1] * self.n[2]

    @cached_property
    def cell_corners(self) -> np.ndarray:
        """(nx, ny, nz, 2, 2, 2, 3): corner coordinates of every cell,
        indexed by local corner offsets (a, b, c) along (x, y, z)."""
        v = self.vertices
        nx, ny, nz = self.n
        out = np.empty((nx, ny, nz, 2, 2, 2, 3), dtype=v.dtype)
        for a in range(2):
            for b in range(2):
                for c in range(2):
                    out[:, :, :, a, b, c, :] = v[a : nx + a, b : ny + b, c : nz + c, :]
        return out


def create_box_mesh(
    n: tuple[int, int, int], geom_perturb_fact: float = 0.0, seed: int = 42
) -> BoxMesh:
    nx, ny, nz = (int(v) for v in n)
    if min(nx, ny, nz) < 1:
        raise ValueError(f"invalid mesh size {n}")
    xs = np.linspace(0.0, 1.0, nx + 1)
    ys = np.linspace(0.0, 1.0, ny + 1)
    zs = np.linspace(0.0, 1.0, nz + 1)
    verts = np.stack(np.meshgrid(xs, ys, zs, indexing="ij"), axis=-1)
    if geom_perturb_fact != 0.0:
        # Deterministic perturbation of vertex x-coordinates, generated over
        # the *global* vertex set so results are partition-independent.
        perturb = geom_perturb_fact / nx
        rng = np.random.RandomState(seed)
        shift = rng.uniform(-perturb, perturb, size=verts.shape[:3])
        verts = verts.copy()
        verts[..., 0] += shift
    return BoxMesh(
        n=(nx, ny, nz), vertices=verts, is_uniform=(geom_perturb_fact == 0.0)
    )
