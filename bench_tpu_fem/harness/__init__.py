"""Resilient evidence-collection harness (journaled, resumable, fault-
classified).

Rounds 4 and 5 both lost their hardware evidence to a wedged TPU tunnel:
the retry/timeout/backoff machinery existed as three ad-hoc fragments
(bench.py's retry parent, measure_all.py's linear stage loop with an
in-process ``dfacc_ok`` flag, scripts/watch_tunnel.sh). This package
unifies them:

- ``journal``   crash-safe append-only JSONL journal (``MEASURE_rNN.jsonl``)
                — every stage attempt recorded before/after execution, so a
                SIGKILL'd agenda loses at most one record — plus the ONE
                error-line schema (``error_record``) shared by bench.py, the
                watchdog and the harness stages;
- ``classify``  the failure taxonomy (``tunnel_wedge`` / ``oom`` /
                ``mosaic_reject`` / ``accuracy_fail`` / ``timeout`` /
                ``unsupported`` / ``transient``) derived from rc + output
                patterns;
- ``policy``    per-stage retry/timeout/backoff policy + the generalized
                OOM size-halving degradation ladder (lifted from
                bench.py:run_df32_side_metric — any stage can opt in);
- ``runner``    the resumable stage state machine: journal-completed stages
                skip on ``--resume``, persisted gate outcomes (dfacc) keep
                gating across resumes, a tunnel wedge triggers health
                re-probe + bounded backoff instead of burning the remaining
                stages' timeouts;
- ``agenda``    the measurement agendas (round6 = measure_all's stages) +
                the ``python -m bench_tpu_fem.harness run|watch`` CLI
                (watch replaces scripts/watch_tunnel.sh);
- ``faults``    fault injection (hang / crash / OOM / wedge-then-recover /
                gate failure scripts) so the whole state machine is
                CPU-testable in CI with no hardware.

Every module here is stdlib-only: the harness parent process never runs a
JAX computation or initialises a backend (a wedged PJRT client is
unrecoverable in-process — all device work happens in killable child
processes, the round-4/5 lesson). The parent *package* import does pull in
the jax module for its compat shims; that is safe under a wedged tunnel —
backend initialisation, not module import, is what hangs (see
utils/hermetic.py).
"""

from . import classify, journal, policy  # noqa: F401  (stdlib-only, cheap)
from .classify import TAXONOMY, classify_exception, classify_text  # noqa: F401
from .journal import Journal, error_record  # noqa: F401
from .policy import OomLadder, RetryPolicy, StagePolicy  # noqa: F401
