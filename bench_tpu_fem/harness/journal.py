"""Crash-safe append-only JSONL journal + the unified error-line schema.

One record per line, written (flush + fsync) BEFORE and AFTER every stage
attempt, so a SIGKILL'd agenda loses at most the record being written.
``replay`` folds a journal back into the state the runner needs to resume:
which stages completed, every persisted gate outcome (a crash between a
``dfacc`` FAIL and the next df stage must NOT silently un-gate the df
agenda on re-run), and the last degradation-ladder size per stage.

A truncated final line (the crash case) is tolerated on read; anything
else unparseable is surfaced in ``JournalState.corrupt`` rather than
silently dropped — a measurement journal is evidence, and evidence loss
must be visible.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

SCHEMA_VERSION = 1

# The bench JSON contract's metric name (bench.py prints exactly one line
# with this metric; the driver greps for it).
BENCH_METRIC = "cg_gdof_per_s_per_chip_q3_f32"


def error_record(msg: str, failure_class: str, **extra) -> dict:
    """The ONE failure-line schema. bench.py's ``_error_line``, its
    ``_probe_devices`` watchdog and every harness stage emit this shape,
    so failure audits across BENCH/MEASURE artifacts are a single grep on
    ``failure_class`` (same contract as ``cg_engine_form``)."""
    from .classify import TAXONOMY

    if failure_class not in TAXONOMY:
        raise ValueError(f"failure_class {failure_class!r} not in {TAXONOMY}")
    rec = {
        "metric": BENCH_METRIC,
        "value": 0.0,
        "unit": "GDoF/s",
        "vs_baseline": 0.0,
        "error": msg,
        "failure_class": failure_class,
    }
    rec.update(extra)
    return rec


class Journal:
    """Append-only JSONL file. Every ``append`` stamps a monotonic ``seq``,
    a wall-clock ``ts`` and the schema version, then flushes AND fsyncs:
    the journal must survive the process being SIGKILL'd the next
    instant (the whole point of journaling before each attempt)."""

    def __init__(self, path: str):
        self.path = path
        self._seq = _tail_seq(path) + 1

    def _next_seq(self) -> int:
        """Best-effort monotonic seq across the writers sharing one round
        file (the runner, bench.py's parent journaling its attempts, the
        watch daemon): re-read the tail seq so interleaved appends keep
        ascending instead of replaying a stale cached counter."""
        self._seq = max(self._seq, _tail_seq(self.path) + 1)
        return self._seq

    def append(self, record: dict) -> dict:
        rec = {"v": SCHEMA_VERSION, "seq": self._next_seq(),
               "ts": time.time()}
        rec.update(record)
        self._seq += 1
        line = json.dumps(rec, sort_keys=True)
        # O_APPEND open per record: atomic single-write append even when
        # bench.py (journaling its parent attempts) and the harness runner
        # share one journal file. A crash mid-write leaves a newline-less
        # torn tail; gluing the next record onto it would destroy BOTH
        # (read_records drops the merged line), so heal it first. The
        # prepended newline rides in the same single write; if two
        # recovering writers race the heal, the worst case is one empty
        # line, which read_records skips.
        with open(self.path, "a") as fh:
            prefix = "\n" if _torn_tail(self.path) else ""
            fh.write(prefix + line + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        return rec

    def records(self) -> list[dict]:
        recs, _ = read_records(self.path)
        return recs


def _torn_tail(path: str) -> bool:
    """True when the file's last byte is not a newline — the signature a
    SIGKILL between ``write`` and the end of ``append`` leaves behind."""
    try:
        with open(path, "rb") as fh:
            fh.seek(-1, os.SEEK_END)
            return fh.read(1) != b"\n"
    except (OSError, ValueError):
        return False


def _tail_seq(path: str) -> int:
    """Highest seq among the last few records of the file (-1 when none):
    a bounded tail read, so per-append cost stays O(1) as journals grow."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return -1
    with open(path, "rb") as fh:
        fh.seek(max(0, size - 65536))
        chunk = fh.read().decode("utf-8", errors="replace")
    for line in reversed(chunk.splitlines()):
        try:
            obj = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(obj, dict) and isinstance(obj.get("seq"), int):
            return obj["seq"]
    return -1


def read_records(path: str) -> tuple[list[dict], list[str]]:
    """Parse a journal file; returns (records, corrupt_lines). A torn
    FINAL line (crash mid-write) is expected and not counted corrupt."""
    if not os.path.exists(path):
        return [], []
    recs: list[dict] = []
    corrupt: list[str] = []
    with open(path) as fh:
        lines = fh.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            if i == len(lines) - 1:
                continue  # torn tail record: the crash case, by design
            corrupt.append(line)
            continue
        if isinstance(obj, dict):
            recs.append(obj)
        else:
            corrupt.append(line)
    return recs, corrupt


@dataclass
class JournalState:
    """The fold of a journal the resumable runner consumes."""

    completed: dict[str, dict] = field(default_factory=dict)
    failed: dict[str, dict] = field(default_factory=dict)
    gates: dict[str, bool] = field(default_factory=dict)
    attempts: dict[str, int] = field(default_factory=dict)
    last_size: dict[str, int] = field(default_factory=dict)
    corrupt: list[str] = field(default_factory=list)

    def done(self, stage: str) -> bool:
        return stage in self.completed


def replay(path_or_records) -> JournalState:
    """Fold journal records into resumable state. Later records win (a
    re-run stage's fresh outcome replaces its old one; a re-run gate stage
    refreshes the persisted gate)."""
    if isinstance(path_or_records, str):
        records, corrupt = read_records(path_or_records)
    else:
        records, corrupt = list(path_or_records), []
    st = JournalState(corrupt=corrupt)
    for rec in records:
        ev = rec.get("event")
        stage = rec.get("stage")
        if ev == "attempt_start" and stage:
            st.attempts[stage] = st.attempts.get(stage, 0) + 1
            if rec.get("size") is not None:
                st.last_size[stage] = rec["size"]
        elif ev == "attempt_end" and stage:
            if rec.get("outcome") == "ok":
                st.completed[stage] = rec
                st.failed.pop(stage, None)
            else:
                st.failed[stage] = rec
                st.completed.pop(stage, None)
        elif ev == "gate" and rec.get("gate"):
            st.gates[rec["gate"]] = bool(rec.get("ok"))
    return st


def default_journal_path(root: str, round_tag: str) -> str:
    """MEASURE_rNN.jsonl next to MEASURE_rNN.log — the round's evidence
    journal (round-stamped per the evidence-hygiene rule)."""
    return os.path.join(root, f"MEASURE_{round_tag}.jsonl")
