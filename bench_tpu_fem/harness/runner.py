"""The resumable stage runner: journal + classify + policy, one state
machine.

Executes an agenda of stages, each in its own killable process session
(bench.py spawns detached single-attempt children, and a parent-only kill
would orphan one holding the wedged TPU client — the whole group dies on
timeout). Every attempt is journaled before and after; ``--resume`` skips
journal-completed stages, re-runs failed ones per policy, and honors
persisted gate outcomes (a crash between a dfacc FAIL and the next df
stage must not un-gate the df agenda).

Wedge handling is the round-5 fix: a classified tunnel_wedge (or a
timeout whose re-probe fails) does NOT burn the remaining stages'
timeouts — the runner enters a bounded probe×backoff loop and either
resumes the agenda on recovery or aborts it, journaled either way, for
the watch daemon to re-arm.

Clock, sleep, probe and stage execution are all injectable so the entire
state machine runs under fault injection on CPU in CI (harness/faults.py).
"""

from __future__ import annotations

import os
import signal
import subprocess  # noqa: TID251  (the one sanctioned process-control site)
import sys
import time
from dataclasses import dataclass, field

from ..obs.trace import span
from .classify import classify, classify_text
from .journal import Journal, replay
from .policy import DEGRADE, REPROBE, RETRY, StagePolicy, next_action

# Output lines dropped from journaled tails (measure_all's filter): pure
# noise at best, and at worst they push the informative tail lines out.
_NOISE_PREFIXES = ("warning",)          # matched on the lowercased line
_NOISE_SUBSTRINGS = ("Platform 'axon'",)


def clean_tail(out: str, tail: int = 25) -> str:
    keep = [
        ln for ln in (out or "").strip().splitlines()
        if not ln.lower().startswith(_NOISE_PREFIXES)
        and not any(s in ln for s in _NOISE_SUBSTRINGS)
    ]
    return "\n".join(keep[-tail:])


@dataclass
class SubprocessResult:
    rc: int | None          # None = killed at the deadline
    out: str                # captured output — PARTIAL output on timeout
    timed_out: bool
    wall_s: float


def run_subprocess(cmd, timeout_s, env=None, cwd=None) -> SubprocessResult:
    """Shared child runner (lifted from measure_all._run / bench.py main):
    own session, stdout+stderr merged, the WHOLE GROUP SIGKILLed on
    timeout. The captured partial output survives the kill — *where* a
    stage hung is evidence (a wedge at device init reads differently from
    a hang mid-CG), and the old TIMEOUT path that discarded it lost
    exactly the lines that diagnose the wedge."""
    t0 = time.monotonic()
    try:
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=cwd, env=env, start_new_session=True,
        )
    except OSError as exc:
        return SubprocessResult(None, f"spawn failed: {exc}", False,
                                time.monotonic() - t0)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return SubprocessResult(proc.returncode, out or "", False,
                                time.monotonic() - t0)
    except subprocess.TimeoutExpired as exc:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        out, _ = proc.communicate()
        # communicate(timeout=) buffers what the child wrote before the
        # kill either in exc.output (pre-kill reads) or in the post-kill
        # drain — keep whichever carries the evidence.
        partial = out or (exc.output if isinstance(exc.output, str) else "")
        return SubprocessResult(None, partial or "", True,
                                time.monotonic() - t0)


@dataclass
class StageContext:
    """What a stage's command builder sees: the current ladder size (None
    when the stage didn't opt into the OOM ladder) and the attempt
    number."""

    size: int | None = None
    attempt: int = 1
    round_tag: str = ""


@dataclass
class Stage:
    """One agenda entry. ``command`` builds the child argv from the
    context (ladder stages interpolate ``ctx.size``); tests bypass it via
    the runner's injectable executor."""

    name: str
    command: object = None          # callable(StageContext) -> list[str]
    policy: StagePolicy = field(default_factory=StagePolicy)
    requires_gate: str | None = None
    provides_gate: str | None = None
    size: int | None = None         # initial OOM-ladder size
    env: dict | None = None         # stage-specific env overrides
    # > 0: the stage's bench runs write durable CG snapshots every N
    # iterations into a round-stable per-stage directory
    # (BENCH_CHECKPOINT_EVERY/DIR env -> BenchConfig defaults), so a
    # retried or resumed attempt — wedge recovery, preemption retry, a
    # --resume after SIGKILL — restarts the solve from the last snapshot
    # instead of iteration 0. OOM-ladder rungs change the problem size
    # and therefore the snapshot fingerprint: a downsized rung measures
    # fresh by construction (harness.checkpoint skips mismatches).
    ckpt_every: int = 0
    critical: bool = False          # terminal failure aborts the agenda
    check: object = None            # callable(rc, out) -> bool (success)
    parse: object = None            # callable(out) -> dict | None (result)
    tail: int = 25


class Runner:
    """Drives an agenda through the journal/classify/policy machinery.

    ``probe`` is the tunnel health check: callable() -> (ok, detail). The
    default runs a tiny device computation in a killable child (see
    agenda.probe_tunnel). ``exec_stage`` (callable(stage, ctx) ->
    SubprocessResult) defaults to the subprocess runner; fault-injection
    tests swap it for a scripted executor."""

    def __init__(self, stages, journal: Journal, probe=None,
                 sleep=time.sleep, log=None, exec_stage=None,
                 base_env=None, cwd=None, round_tag=""):
        self.stages = list(stages)
        self.journal = journal
        self.probe = probe
        self.sleep = sleep
        self.log = log or (lambda msg: print(msg, flush=True))
        self.exec_stage = exec_stage or self._exec_subprocess
        self.base_env = base_env
        self.cwd = cwd
        self.round_tag = round_tag
        self.gates: dict[str, bool] = {}
        self.aborted: str | None = None  # set by run(); the watch daemon
        # re-arms on "tunnel_wedge" instead of giving up

    # -- execution ---------------------------------------------------------

    def _exec_subprocess(self, stage: Stage, ctx: StageContext):
        env = dict(self.base_env if self.base_env is not None else os.environ)
        if stage.env:
            env.update(stage.env)
        if stage.ckpt_every > 0:
            # durable-checkpoint opt-in (ISSUE 9): a round-stable
            # per-stage snapshot dir, so every retry/resume of THIS
            # stage restores the solve from its last snapshot
            env.setdefault("BENCH_CHECKPOINT_EVERY", str(stage.ckpt_every))
            env.setdefault("BENCH_CHECKPOINT_DIR", os.path.join(
                self.cwd or ".", ".ckpt",
                self.round_tag or "r0", stage.name))
        cmd = stage.command(ctx)
        return run_subprocess(cmd, stage.policy.timeout_s, env=env,
                              cwd=self.cwd)

    def _probe(self) -> bool:
        """One journaled health probe."""
        if self.probe is None:
            return True
        ok, detail = self.probe()
        self.journal.append({"event": "probe", "ok": bool(ok),
                             "detail": str(detail)[:300]})
        self.log(f"probe {'OK' if ok else 'DOWN'}: {detail}")
        return bool(ok)

    def _wedge_recovery(self, stage: Stage, attempt: int) -> bool:
        """Bounded probe×backoff loop after a classified wedge. True =
        tunnel recovered (re-run the stage); False = still wedged (abort
        the agenda — the watch daemon owns longer horizons)."""
        pol = stage.policy
        for round_i in range(1, pol.wedge_max_probes + 1):
            wait = pol.retry.backoff(round_i)
            self.log(f"[{stage.name}] wedge backoff {wait:.0f}s "
                     f"(probe {round_i}/{pol.wedge_max_probes})")
            self.sleep(wait)
            if self._probe():
                return True
        return False

    # -- the state machine -------------------------------------------------

    def run(self, resume: bool = False) -> int:
        state = replay(self.journal.records()) if resume else None
        if state is not None:
            # Persisted gate outcomes survive the crash/kill (satellite:
            # dfacc FAIL keeps gating df stages on re-run until the gate
            # stage itself re-runs and passes).
            self.gates.update(state.gates)
            if state.corrupt:
                self.log(f"journal: {len(state.corrupt)} corrupt line(s) "
                         "retained for audit")
        self.journal.append({
            "event": "agenda_start", "resume": resume,
            "round": self.round_tag,
            "stages": [s.name for s in self.stages],
        })
        aborted: str | None = None
        failed: list[str] = []
        for stage in self.stages:
            if aborted:
                self.journal.append({"event": "stage_skip",
                                     "stage": stage.name,
                                     "reason": f"agenda aborted: {aborted}"})
                continue
            if resume and state is not None and state.done(stage.name):
                self.log(f"=== {stage.name} SKIPPED (journal: completed)")
                self.journal.append({"event": "stage_skip",
                                     "stage": stage.name,
                                     "reason": "already-completed"})
                continue
            gate = stage.requires_gate
            if gate is not None and self.gates.get(gate) is False:
                self.log(f"=== {stage.name} SKIPPED: {gate} gate failed — "
                         "df numbers don't count without the on-hardware "
                         "accuracy check")
                self.journal.append({"event": "stage_skip",
                                     "stage": stage.name,
                                     "reason": "gate-failed", "gate": gate})
                continue
            outcome, why, abort = self._run_stage(stage, state)
            if outcome != "ok":
                failed.append(stage.name)
                if abort or (stage.critical and outcome == "failed"):
                    aborted = why or "critical stage failed"
        self.journal.append({"event": "agenda_end", "aborted": aborted,
                             "failed": failed, "round": self.round_tag})
        self.aborted = aborted
        if aborted:
            self.log(f"agenda ABORTED: {aborted}")
        return 0 if not failed and not aborted else 1

    def _run_stage(self, stage: Stage, state):
        """Run one stage to a terminal outcome. Returns (outcome,
        terminal_failure_class, abort_agenda) — abort only for a
        probe-confirmed tunnel wedge, never for a stage that merely
        *classifies* like one while the tunnel answers."""
        size = stage.size
        if (state is not None and stage.name in state.last_size
                and stage.policy.oom_ladder is not None):
            # resume the ladder where the killed run left it — the rungs
            # above are journal-proven OOM
            size = state.last_size[stage.name]
        attempt = 0
        degrades = 0  # ladder rungs don't consume plain-retry budget
        wedge_rounds = 0
        while True:
            attempt += 1
            ctx = StageContext(size=size, attempt=attempt,
                               round_tag=self.round_tag)
            self.log(f"=== stage {stage.name} (attempt {attempt}"
                     + (f", size {size}" if size is not None else "") + ")")
            self.journal.append({"event": "attempt_start",
                                 "stage": stage.name, "attempt": attempt,
                                 "size": size})
            # stage span (obs.trace): no-op unless the tracer is enabled
            # (harness CLI --trace sinks these into the round journal)
            with span(f"stage:{stage.name}", attempt=attempt, size=size):
                res = self.exec_stage(stage, ctx)
            tail = clean_tail(res.out, stage.tail)
            ok = (res.rc == 0 and not res.timed_out)
            if stage.check is not None:
                ok = bool(stage.check(res.rc, res.out)) and not res.timed_out
            cls = None
            if not ok:
                # a check-rejected rc==0 run still needs a class (every
                # journaled failure carries one): fall through to the
                # text patterns, "transient" at worst
                cls = (classify(res.rc, res.out, timed_out=res.timed_out)
                       or classify_text(res.out))
            if cls == "timeout" and self.probe is not None:
                # a timeout is only a timeout if the tunnel still answers;
                # a failed re-probe reclassifies it as the wedge it is
                if not self._probe():
                    cls = "tunnel_wedge"
            result = None
            if ok and stage.parse is not None:
                result = stage.parse(res.out)
            end = {"event": "attempt_end", "stage": stage.name,
                   "attempt": attempt, "rc": res.rc,
                   "timed_out": res.timed_out,
                   "wall_s": round(res.wall_s, 3), "size": size,
                   "outcome": "ok" if ok else "failed",
                   "failure_class": cls, "output_tail": tail}
            if result is not None:
                end["result"] = result
            self.journal.append(end)
            self.log(f"{stage.name} rc={res.rc}"
                     + (" TIMEOUT" if res.timed_out else "")
                     + (f" [{cls}]" if cls else "") + f": {tail}")
            if ok:
                self._set_gate(stage, True)
                return "ok", None, False
            act = next_action(cls, attempt - degrades, stage.policy,
                              size=size)
            self.journal.append({"event": "action", "stage": stage.name,
                                 "kind": act.kind, "reason": act.reason,
                                 "wait_s": act.wait_s,
                                 "next_size": act.next_size})
            if act.kind == RETRY:
                self.log(f"[{stage.name}] {act.reason}; backoff "
                         f"{act.wait_s:.0f}s")
                self.sleep(act.wait_s)
                continue
            if act.kind == DEGRADE:
                self.log(f"[{stage.name}] {act.reason}")
                size = act.next_size
                degrades += 1
                continue
            if act.kind == REPROBE:
                wedge_rounds += 1
                if wedge_rounds > stage.policy.wedge_max_probes:
                    # the tunnel answered every probe, yet the stage keeps
                    # failing with a wedge signature: a deterministic
                    # failure whose text merely matches the wedge patterns
                    # (e.g. an embedded gRPC UNAVAILABLE). Terminal for
                    # the STAGE — aborting the agenda here would send the
                    # watch daemon into an endless re-arm loop while the
                    # remaining stages never run.
                    self._set_gate(stage, False)
                    self.log(f"[{stage.name}] FAILED terminally: wedge-"
                             "classified but the tunnel answers probes")
                    return "failed", cls, False
                if self._wedge_recovery(stage, attempt):
                    self.log(f"[{stage.name}] tunnel recovered; re-running")
                    continue
                self._set_gate(stage, False)
                return "failed", "tunnel_wedge", True
            # GIVE_UP
            self._set_gate(stage, False)
            self.log(f"[{stage.name}] FAILED terminally: {act.reason}")
            return "failed", cls, False

    def _set_gate(self, stage: Stage, ok: bool) -> None:
        if stage.provides_gate is None:
            return
        self.gates[stage.provides_gate] = ok
        self.journal.append({"event": "gate", "gate": stage.provides_gate,
                             "ok": ok, "stage": stage.name})


def last_json_line(text: str) -> dict | None:
    """The bench JSON contract parser (shared with bench.py's parent):
    last parseable {"metric": ...} line wins."""
    import json

    for line in reversed((text or "").strip().splitlines()):
        try:
            obj = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(obj, dict) and "metric" in obj:
            return obj
    return None
