"""The measurement agendas + the ``python -m bench_tpu_fem.harness`` CLI.

The round-6 agenda is scripts/measure_all.py's stage set, restated as
declarative :class:`~.runner.Stage` entries over the journal/classify/
policy machinery (measure_all itself is now a thin back-compat shim over
this module). Composite measure_all names (``ab12``, ``large``,
``dfeng``, ``dflarge``) expand via ``ALIASES`` so old invocations keep
working, stage-per-subprocess.

``run``   executes an agenda: ``python -m bench_tpu_fem.harness run
          --agenda round6 --resume`` skips journal-completed stages,
          re-runs failed ones per policy, and honors persisted gate
          outcomes (dfacc).
``watch`` replaces scripts/watch_tunnel.sh: probe the tunnel on an
          interval, run the agenda (resumed) the moment it lives, re-arm
          when the agenda aborts on a fresh wedge — all journaled.

Every stage runs in its own killable child process; stage payloads are
the same code strings measure_all ran (the df accuracy gates, A/B
configs, probe delegations are measurement DESIGN, unchanged here — only
the fault handling around them moved into the harness).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from .journal import Journal, default_journal_path
from .policy import OomLadder, RetryPolicy, StagePolicy
from .runner import Runner, Stage, clean_tail, last_json_line, run_subprocess

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
# The round tag rides the MEASURE_ROUND env var into child stages, so a
# script a stage shells out to (probe_scoped_vmem) logs into the SAME
# round's files as the journal that launched it (evidence hygiene).
DEFAULT_ROUND = os.environ.get("MEASURE_ROUND", "r06")

# Ladder-size placeholder in templated stage payloads (str.format would
# choke on the payloads' own braces).
_NDOFS = "__NDOFS__"

# The probe requires the TPU backend unless the caller explicitly pinned
# CPU (tests/dev): a fast-failing TPU client makes jax fall back to CPU
# with a warning, and a successful CPU matmul must read as "tunnel DOWN",
# not up — an agenda run on the fallback would journal bogus "hardware"
# numbers (watch_tunnel.sh's old backend guard, kept).
PROBE_CODE = """
import os, sys
import jax, jax.numpy as jnp
x = jax.device_put(jnp.ones((1024, 1024)))
(x @ x).block_until_ready()
backend = jax.default_backend()
pinned_cpu = os.environ.get('JAX_PLATFORMS', '') == 'cpu'
print(('TPU OK' if backend == 'tpu' else f'{backend} (pinned)' if
       pinned_cpu else f'NOT TPU: fell back to {backend}'), jax.devices())
sys.exit(0 if backend == 'tpu' or pinned_cpu else 1)
"""

PRE = """
import time, numpy as np, jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig, run_benchmark
def timed_res(cfg):
    # monotonic, not time.time(): an NTP step mid-stage must not corrupt
    # the journaled stage wall
    t0 = time.monotonic(); res = run_benchmark(cfg); w = time.monotonic()-t0
    return res, w
"""


def base_env(round_tag: str = DEFAULT_ROUND) -> dict:
    return {**os.environ, "PYTHONPATH": f"{ROOT}:/root/.axon_site",
            "MEASURE_ROUND": round_tag}


def probe_tunnel(timeout_s: float = 180.0):
    """The tunnel health probe, in a killable child (a wedged PJRT client
    hangs holding the GIL — the parent must never touch it in-process).
    Returns (ok, detail)."""
    res = run_subprocess([sys.executable, "-u", "-c", PROBE_CODE],
                         timeout_s, env=base_env(), cwd=ROOT)
    ok = res.rc == 0 and not res.timed_out
    tail = (res.out or "").strip().splitlines()
    detail = tail[-1] if tail else ("TIMEOUT" if res.timed_out else "no output")
    return ok, f"rc={res.rc} {detail}"


def run_py(code, timeout=900, tail=25):
    """Legacy stage helper for scripts/ (probe_scoped_vmem delegates its
    probes here): one ``python -c`` child under the harness subprocess
    runner, (rc, output-tail) out — measure_all._run's contract, with rc
    -9 standing in for a timeout kill. Unlike the old _run, the captured
    PARTIAL output rides along after the TIMEOUT marker: where a stage
    hung is evidence."""
    res = run_subprocess([sys.executable, "-u", "-c", code], timeout,
                         env=base_env(), cwd=ROOT)
    text = clean_tail(res.out, tail)
    if res.timed_out:
        return -9, (f"TIMEOUT after {timeout}s; partial output tail:\n"
                    f"{text}" if text else f"TIMEOUT after {timeout}s "
                    "(no output before the kill)")
    return res.rc, text


def _bench_code(label, cfg_kwargs, setup="", tail_expr=""):
    """The shared single-config benchmark payload (measure_all's
    _bench_stage): one BenchConfig, one run_benchmark, one labelled
    print."""
    kw = ", ".join(f"{k}={v}" if v == _NDOFS else f"{k}={v!r}"
                   for k, v in cfg_kwargs.items())
    return PRE + f"""
{setup}
cfg = BenchConfig({kw})
res, w = timed_res(cfg)
print({label!r}, res.gdof_per_second, res.extra{tail_expr})
"""


def _py(name, code, timeout, *, tries=1, gate=None, provides=None,
        size=None, floor=None, env=None, critical=False, parse=None,
        tail=25, ckpt_every=0):
    """A python -c stage. ``size``/``floor`` opt the stage into the OOM
    degradation ladder: its payload carries the __NDOFS__ placeholder and
    re-runs halved on a classified OOM down to ``floor``.
    ``ckpt_every`` opts the stage's bench runs into durable CG snapshots
    (BENCH_CHECKPOINT_EVERY/DIR env -> BenchConfig): a wedge/preemption
    retry or a --resume after SIGKILL restarts from the last snapshot
    instead of iteration 0."""
    policy = StagePolicy(
        timeout_s=timeout,
        retry=RetryPolicy(max_attempts=max(tries, 1)),
        oom_ladder=OomLadder(floor=floor) if floor is not None else None,
    )

    def command(ctx):
        payload = code
        if ctx.size is not None:
            payload = payload.replace(_NDOFS, str(ctx.size))
        return [sys.executable, "-u", "-c", payload]

    return Stage(name=name, command=command, policy=policy,
                 requires_gate=gate, provides_gate=provides, size=size,
                 env=env, critical=critical, parse=parse, tail=tail,
                 ckpt_every=ckpt_every)


def _script(name, args, timeout, *, tail=15, env=None):
    return Stage(name=name,
                 command=lambda ctx: [sys.executable] + list(args),
                 policy=StagePolicy(timeout_s=timeout), tail=tail,
                 env=env)


# --------------------------------------------------------------------------
# Stage payloads (measure_all's measurement design, verbatim).

AB12_ENGINE = PRE + """
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=32, nreps=1000, use_cg=True)
res, w = timed_res(cfg)
print("ENGINE:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""

AB12_BASELINE = PRE + """
# force the non-engine path by monkeypatching the support gate
import bench_tpu_fem.ops.kron_cg as KC
KC.supports_kron_cg_engine = lambda *a, **k: False
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=32, nreps=1000, use_cg=True)
res, w = timed_res(cfg)
print("BASELINE3STAGE:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""

DF32 = PRE + """
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=64, nreps=50, use_cg=True, f64_impl="df32")
res, w = timed_res(cfg)
print("DF32:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=64, nreps=50, use_cg=True)
res, w = timed_res(cfg)
print("EMULATED:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""

DIST1 = """
import jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig
from bench_tpu_fem.dist.driver import run_distributed
from bench_tpu_fem.bench.driver import BenchmarkResults
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=32, nreps=100, use_cg=True, ndevices=1)
res = BenchmarkResults()
run_distributed(cfg, res, jnp.float32)
print("DIST1:", res.gdof_per_second, res.extra)
"""

DFDIST1 = """
import jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig, BenchmarkResults
from bench_tpu_fem.dist.driver import run_distributed_df64
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=64, nreps=50, use_cg=True,
                  f64_impl="df32", ndevices=1)
res = BenchmarkResults()
run_distributed_df64(cfg, res)
print("DFDIST1:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""

DEG6STREAM = PRE + """
import bench_tpu_fem.ops.folded as FO
import bench_tpu_fem.ops.pallas_laplacian as PL
orig = FO.pallas_geom_constraint
FO.pallas_geom_constraint = lambda d, nq, itemsize=4: (
    (True, "corner") if d == 6 else orig(d, nq, itemsize))
PL.corner_streamed_lanes_ok = lambda nd, nq, itemsize=4: True
cfg = BenchConfig(ndofs_global=12_500_000, degree=6, qmode=1,
                  float_bits=32, nreps=200, use_cg=True,
                  geom_perturb_fact=0.2, backend="pallas")
res, w = timed_res(cfg)
print("DEG6STREAM:", res.gdof_per_second, res.extra)
"""

DFACC = PRE + """
cfg = BenchConfig(ndofs_global=50_000, degree=3, qmode=1, float_bits=64,
                  nreps=30, use_cg=True, mat_comp=True, f64_impl="df32")
res, w = timed_res(cfg)
print("DFACC one:", "enorm/znorm", res.enorm / res.znorm, res.extra)
assert res.extra.get("cg_engine") is True, "engine did not engage"
assert res.enorm / res.znorm < 1e-9, "df one-kernel lost f64 accuracy"
import bench_tpu_fem.ops.kron_cg_df as KCD
KCD.engine_plan_df = lambda *a: ("chunked", None)
res, w = timed_res(cfg)
print("DFACC chunked:", "enorm/znorm", res.enorm / res.znorm, res.extra)
assert res.enorm / res.znorm < 1e-9, "df chunked lost f64 accuracy"
print("DFACC OK")
"""

PERTDF = PRE + """
cfg = BenchConfig(ndofs_global=50_000, degree=3, qmode=1, float_bits=64,
                  nreps=30, use_cg=True, mat_comp=True, f64_impl="df32",
                  geom_perturb_fact=0.2)
res, w = timed_res(cfg)
print("PERTDF acc:", "enorm/znorm", res.enorm / res.znorm, res.extra)
assert res.extra.get("f64_impl") == "df32", res.extra
assert res.enorm / res.znorm < 1e-9, "folded-df lost f64 accuracy"
import bench_tpu_fem.ops.folded_df as FD
import bench_tpu_fem.bench.driver as BD
orig = FD.build_folded_laplacian_df
FD.build_folded_laplacian_df = lambda *a, **k: orig(
    *a, **{**k, "geom": "corner"})
res, w = timed_res(cfg)
print("PERTDF acc corner:", "enorm/znorm", res.enorm / res.znorm,
      res.extra)
assert res.extra.get("f64_impl") == "df32", res.extra
assert res.extra.get("geom") == "corner", res.extra
assert res.enorm / res.znorm < 1e-9, "folded-df corner lost f64 accuracy"
FD.build_folded_laplacian_df = orig
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=64, nreps=100, use_cg=True, f64_impl="df32",
                  geom_perturb_fact=0.2)
res, w = timed_res(cfg)
print("PERTDF12.5M:", res.gdof_per_second, res.extra,
      "vs4.02:", res.gdof_per_second / 4.02)
"""

FOLDENG = """
import dataclasses
import jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig, BenchmarkResults
from bench_tpu_fem.dist.driver import run_distributed
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=32, nreps=500, use_cg=True, ndevices=1,
                  backend="pallas", geom_perturb_fact=0.2)
res = BenchmarkResults(nreps=cfg.nreps)
run_distributed(cfg, res, jnp.float32)
print("FOLDENG:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
# loud on routing drift: the overlap form engages by default on the
# folded engine (ISSUE 7); an unfused fallback here would otherwise make
# the A/B below compare unfused vs unfused (the reason is in the extras)
assert res.extra.get("cg_engine_form") == "halo_overlap", res.extra
res_sync = BenchmarkResults(nreps=cfg.nreps)
run_distributed(dataclasses.replace(cfg, overlap="off"), res_sync,
                jnp.float32)
print("FOLDENG-SYNC:", res_sync.gdof_per_second, res_sync.extra,
      "ynorm", res_sync.ynorm, "overlap_speedup:",
      res.gdof_per_second / max(res_sync.gdof_per_second, 1e-12))
assert res_sync.extra.get("cg_engine_form") == "halo", res_sync.extra
import bench_tpu_fem.dist.folded_cg as DFC
DFC.dist_folded_engine_plan = lambda op: (False, None)
res2 = BenchmarkResults(nreps=cfg.nreps)
run_distributed(cfg, res2, jnp.float32)
print("FOLDENG-UNFUSED:", res2.gdof_per_second, res2.extra,
      "ynorm", res2.ynorm, "speedup:",
      res.gdof_per_second / max(res2.gdof_per_second, 1e-12))
"""

DFEXT2D = """
import jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig, BenchmarkResults
from bench_tpu_fem.dist.driver import run_distributed_df64
nd = len(jax.devices())
if nd >= 8:
    ndev, tag = 8, "(2,2,2)"
else:
    import bench_tpu_fem.dist.kron_cg_df as KCD
    KCD._is_x_only = lambda op: False
    ndev, tag = 1, "forced-ext2d-1dev"
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=64, nreps=50, use_cg=True,
                  f64_impl="df32", ndevices=ndev)
res = BenchmarkResults(nreps=cfg.nreps)
run_distributed_df64(cfg, res)
print("DFEXT2D", tag, ":", res.gdof_per_second, res.extra,
      "ynorm", res.ynorm)
# overlap engages by default on the df engine (ISSUE 7)
assert res.extra.get("cg_engine_form") == "ext2d_overlap", res.extra
"""


AUTOTUNE = """
import json, os
import jax
round_tag = os.environ.get('MEASURE_ROUND', 'r06')
os.environ.setdefault('BTF_TUNING_DB',
                      os.path.join(os.getcwd(), f'TUNING_{round_tag}.db'))
from bench_tpu_fem.engines.autotune import LABELS, default_tuning_db, run_sweep
db = default_tuning_db()
on_tpu = jax.default_backend() == 'tpu'
ndofs = 50_000 if on_tpu else 2000
sweeps = []
for degree, bucket in ((3, 2), (3, 4), (3, 8), (6, 4)):
    out = run_sweep(db, degree=degree, ndofs=ndofs, precision='f32',
                    geom='uniform', nrhs_bucket=bucket, nreps=30,
                    round_stamp=round_tag, time_candidates=on_tpu)
    sweeps.append({'degree': degree, 'bucket': bucket,
                   'label': out['label'], 'winner': out['winner'],
                   'rejected': out['rejected']})
stats = db.stats()
assert stats['labels_ok'], stats
# consumption check: a serve build must read its swept entry back with
# the tuning evidence stamped (source=db + registered label)
from bench_tpu_fem.serve.engine import CompiledSolver, SolveSpec
sol = CompiledSolver(SolveSpec(degree=3, ndofs=ndofs, nreps=30), 4)
assert sol.tuning['source'] == 'db', sol.tuning
assert sol.tuning['label'] in LABELS, sol.tuning
print(json.dumps({'metric': 'autotune',
                  'autotune_db': os.environ['BTF_TUNING_DB'],
                  'sweeps': sweeps, 'stats': stats,
                  'consumed': sol.tuning}))
"""

FUSEDBATCH = PRE + """
# The nrhs-native fused batched kron engine (ISSUE 6) on hardware:
# batched GDoF/s at the serve buckets vs the unfused vmapped fallback,
# with the engine-form stamp asserted — converts the per-bucket VMEM
# tier admissions from design estimates to measurements.
for nrhs in (2, 4, 8):
    cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                      float_bits=32, nreps=200, use_cg=True, nrhs=nrhs)
    res, w = timed_res(cfg)
    print(f"FUSEDBATCH nrhs{nrhs}:", res.gdof_per_second, res.extra)
    assert res.extra.get("cg_engine_form") == "one_kernel_batched", \\
        res.extra
import bench_tpu_fem.ops.kron_cg as KC
KC.engine_plan_batched = lambda *a: ("unfused", None)
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=32, nreps=200, use_cg=True, nrhs=4)
res2, w = timed_res(cfg)
print("FUSEDBATCH unfused4:", res2.gdof_per_second, res2.extra)
"""


BF16 = PRE + """
# bf16 mixed-precision speed ladder (ISSUE 17) on hardware: the plain
# bf16-stream apply A/B against f32 at the same solve (the halved byte
# model becomes a measured GDoF/s ratio), the refinement ladder's
# time_to_rtol_s at f64-class accuracy, and a hardware-labelled bf16
# tuning sweep the builds consume (source=db). CPU runs keep every
# assertion at small sizes with the labels recording the provenance.
import json, os
on_tpu = jax.default_backend() == 'tpu'
ndofs = 12_500_000 if on_tpu else 2000
refine_ndofs = 1_000_000 if on_tpu else 2000
nreps = 200 if on_tpu else 30
round_tag = os.environ.get('MEASURE_ROUND', 'r06')
os.environ.setdefault('BTF_TUNING_DB',
                      os.path.join(os.getcwd(), f'TUNING_{round_tag}.db'))
from bench_tpu_fem.engines.autotune import default_tuning_db, run_sweep
db = default_tuning_db()
sw = run_sweep(db, degree=3, ndofs=ndofs, precision='bf16',
               geom='uniform', nreps=nreps, round_stamp=round_tag,
               time_candidates=on_tpu)
swr = run_sweep(db, degree=3, ndofs=refine_ndofs, precision='bf16',
                geom='uniform', nreps=nreps, round_stamp=round_tag,
                refine=True)
# seed the driver's exec key with the sweep winner so the refine run
# below consumes it (source=db) — same bridge perfgate exercises
from bench_tpu_fem.bench.driver import _exec_cache_key
from bench_tpu_fem.mesh.sizing import compute_mesh_size
out = {'metric': 'bf16', 'sweep_label': sw['label'],
       'refine_winner': swr['winner']}
for prec in ('auto', 'bf16'):
    cfg = BenchConfig(ndofs_global=ndofs, degree=3, qmode=1,
                      float_bits=32, nreps=nreps, use_cg=True,
                      precision=prec)
    res, w = timed_res(cfg)
    out[prec] = {'gdof_s': res.gdof_per_second,
                 'hbm_bytes_per_dof':
                     res.extra['roofline']['hbm_bytes_per_dof'],
                 'wall_s': w}
# the byte-model claim the roofline carries: bf16 streams exactly half
assert out['bf16']['hbm_bytes_per_dof'] * 2 == \\
    out['auto']['hbm_bytes_per_dof'], out
rcfg = BenchConfig(ndofs_global=refine_ndofs, degree=3, qmode=1,
                   float_bits=32, nreps=nreps, use_cg=True,
                   precision='bf16-refine', precond='jacobi')
rkey = _exec_cache_key(rcfg, compute_mesh_size(refine_ndofs, 3),
                       'unfused', 'cg+refine')
db.put(rkey, swr['winner'], score=swr['score'], label=swr['label'],
       round_stamp=round_tag, engine='bf16_refine')
rres, w = timed_res(rcfg)
st = rres.extra['refine']
assert st['converged'] and st['achieved_rel'] <= 1e-10, st
assert rres.extra['tuning']['source'] == 'db', rres.extra['tuning']
out['refine'] = dict(st, time_to_rtol_s=rres.extra.get('time_to_rtol_s'),
                     tuning=rres.extra.get('tuning'), wall_s=w)
print(json.dumps(out))
"""


SERVE_SMOKE = """
import os
if os.environ.get('JAX_PLATFORMS', '') == 'cpu':
    from bench_tpu_fem.utils.hermetic import force_host_cpu_devices
    force_host_cpu_devices(1)
import json, threading, urllib.request
from bench_tpu_fem.serve import (Broker, ExecutableCache, Metrics,
                                 SolveSpec, make_server)
import time
cache = ExecutableCache(); metrics = Metrics()
broker = Broker(cache, metrics, queue_max=256, nrhs_max=8, window_s=0.05)
specs = [SolveSpec(degree=d, ndofs=4000, nreps=40) for d in (1, 2, 3)]
broker.warmup(specs)
compiles0 = cache.stats()['compiles']
srv = make_server(broker); host, port = srv.server_address[:2]
threading.Thread(target=srv.serve_forever, daemon=True).start()
results = []
results_lock = threading.Lock()
def fire(i):
    spec = specs[i % 3]
    body = json.dumps({'degree': spec.degree, 'ndofs': spec.ndofs,
                       'nreps': spec.nreps, 'scale': 1.0}).encode()
    req = urllib.request.Request(f'http://{host}:{port}/solve',
                                 data=body, method='POST')
    with urllib.request.urlopen(req, timeout=120) as r:
        rec = json.loads(r.read())
    with results_lock:
        results.append(rec)
threads = [threading.Thread(target=fire, args=(i,)) for i in range(64)]
# ramp arrivals: the queue must span solve boundaries so continuous
# batching has mid-solve work to admit (ISSUE 6 acceptance)
for t in threads:
    t.start(); time.sleep(0.02)
[t.join() for t in threads]
snap = json.loads(urllib.request.urlopen(
    f'http://{host}:{port}/metrics', timeout=30).read())
srv.shutdown(); broker.shutdown()
assert len(results) == 64 and all(r['ok'] for r in results), snap
assert all(r['cg_engine_form'] == 'one_kernel_batched'
           for r in results), results[0]
assert snap['mean_batch_occupancy'] >= 4.0, snap
assert snap['cache_hit_rate_requests'] > 0.9, snap
assert snap['midsolve_admissions'] >= 1, snap
assert cache.stats()['compiles'] == compiles0, cache.stats()
print('SERVE OK', {k: round(snap[k], 3) for k in (
    'requests_total', 'batches', 'mean_batch_occupancy',
    'cache_hit_rate_requests', 'midsolve_admissions',
    'mean_live_lanes', 'padding_waste')})
"""


FORMS = PRE + """
# Operator zoo + heat workload (ISSUE 20), CPU-pinned like the serve
# stages (the acceptance contract is CPU round-stamped; hardware
# per-form rates ride the bench stages once the zoo lands there): the
# per-form GDoF/s table beside the Poisson reference at one size, the
# Helmholtz CG breakdown taxonomy stamped (classified, not crashed),
# and a serve-side heat smoke — the temporally-correlated scale stream
# through the live broker warm vs the same stream cold, iteration
# savings asserted positive (scripts/perfgate.py's `forms` leg pins
# the number; this stage proves the stack under the round's journal).
import os
if os.environ.get('JAX_PLATFORMS', '') == 'cpu':
    from bench_tpu_fem.utils.hermetic import force_host_cpu_devices
    force_host_cpu_devices(1)
import json
out = {'metric': 'forms', 'forms': {}}
for form in ('poisson', 'mass', 'varkappa', 'helmholtz', 'heat'):
    cfg = BenchConfig(ndofs_global=4096, degree=3, qmode=1,
                      float_bits=64, nreps=30, use_cg=True, form=form)
    res, w = timed_res(cfg)
    entry = {'gdof_s': res.gdof_per_second, 'wall_s': round(w, 3)}
    if form != 'poisson':
        assert res.extra.get('form') == form, res.extra
    if form == 'helmholtz':
        sent = res.extra.get('cg_sentinel')
        assert sent is not None, res.extra
        entry['cg_sentinel'] = sent
    out['forms'][form] = entry
    print(f'FORM {form}:', res.gdof_per_second, res.extra)
jax.config.update('jax_enable_x64', True)
from bench_tpu_fem.serve import Broker, Metrics, SolveSpec
from bench_tpu_fem.workload import heat_scale_stream, warm_pairs
br = Broker(metrics=Metrics(), nrhs_max=2, window_s=0.01)
spec = SolveSpec(degree=3, ndofs=4096, nreps=400, precision='f64',
                 form='heat')
pairs = warm_pairs(heat_scale_stream(10, seed=0, drift=0.01))
def run_stream(warmed):
    iters = []
    for scale, wsc in pairs:
        p = br.submit(spec, scale, warm_scale=wsc if warmed else 0.0)
        r = br.wait(p, timeout_s=300)
        assert r['ok'], r
        iters.append(int(r['iters_run']))
    return iters
warm_iters = run_stream(True)
cold_iters = run_stream(False)
br.shutdown()
saved = sum(cold_iters[1:]) - sum(warm_iters[1:])
assert saved > 0, (warm_iters, cold_iters)
out['heat_serve'] = {'iters_warm': warm_iters, 'iters_cold': cold_iters,
                     'iters_saved': saved}
print(json.dumps(out))
"""


def make_stages(round_tag: str = DEFAULT_ROUND) -> dict[str, Stage]:
    """All known stages by name. Gate topology: ``dfacc`` (the
    on-hardware df accuracy oracle) gates every df perf stage; the gate
    outcome persists in the journal across resumes."""
    journal_path = default_journal_path(ROOT, round_tag)
    stages = [
        _py("health", PROBE_CODE, 180, critical=True),
        _py("ab12", AB12_ENGINE, 1200),
        _py("ab12base", AB12_BASELINE, 1200),
        _py("q6", _bench_code("Q6:", dict(
            ndofs_global=12_500_000, degree=6, qmode=1, float_bits=32,
            nreps=1000, use_cg=True),
            tail_expr=', "vs4.40:", res.gdof_per_second/4.40'), 1200),
        _py("deg4", _bench_code("DEG4PERT:", dict(
            ndofs_global=12_500_000, degree=4, qmode=1, float_bits=32,
            nreps=500, use_cg=True, geom_perturb_fact=0.2)), 1800),
        _py("deg5", _bench_code("DEG5PERT:", dict(
            ndofs_global=12_500_000, degree=5, qmode=1, float_bits=32,
            nreps=500, use_cg=True, geom_perturb_fact=0.2)), 1800),
        _py("df32", DF32, 1800),
        _py("dist1", DIST1, 1200),
        _py("dfdist1", DFDIST1, 1200),
        _py("deg6stream", DEG6STREAM, 1800),
        _py("q6one", _bench_code("Q6ONEKERNEL:", dict(
            ndofs_global=12_500_000, degree=6, qmode=1, float_bits=32,
            nreps=1000, use_cg=True),
            setup="import bench_tpu_fem.ops.kron_cg as KC\n"
                  "KC.VMEM_BUDGET = 14 * 2**20  # probe the one-kernel "
                  "form"), 1800),
        # Serving-layer smoke (CPU-pinned: a software-stack check, not a
        # hardware measurement — and it must never hang on a wedged
        # tunnel): 64 ramped mixed-degree requests through the broker,
        # asserting the fused batched engine form, batch occupancy,
        # mid-solve admissions (continuous batching), warm-cache
        # hit-rate and zero recompiles. See README "Serving".
        _py("serve", SERVE_SMOKE, 300, env={"JAX_PLATFORMS": "cpu"}),
        # Chaos/soak (ISSUE 9): scripted SIGKILL/worker-crash/NaN/
        # preemption schedules against the live serve stack, asserting
        # exactly-once, boundary-checkpoint resume, breakdown sentinels
        # and bitwise preemption recovery. CPU-pinned (a software-
        # recovery proof, not a hardware measurement — and it must never
        # hang on a wedged tunnel).
        _script("chaos", ["scripts/chaos_soak.py", "--quick"], 600,
                env={"JAX_PLATFORMS": "cpu"}),
        # Overload resilience (ISSUE 18): the chaos script's overload
        # leg alone — deadline refusals with retry hints, the held-
        # straggler hedge rescue under the exactly-once ledger, and the
        # brownout ladder step/recover cycle. CPU-pinned like the chaos
        # stage (a control-plane proof; thresholds re-arm on hardware
        # through the same journal evidence labels).
        _script("overload", ["scripts/chaos_soak.py", "--quick",
                             "--legs", "overload"], 600,
                env={"JAX_PLATFORMS": "cpu"}),
        # Operator zoo + heat workload (ISSUE 20): per-form GDoF/s next
        # to the Poisson reference, the Helmholtz breakdown taxonomy
        # stamped, and the warm-vs-cold heat serve smoke. CPU-pinned
        # (the warm-start savings contract is CPU round-stamped).
        _py("forms", FORMS, 900, env={"JAX_PLATFORMS": "cpu"},
            parse=last_json_line),
        # On-chip autotune sweep (ISSUE 16): persist hardware-labelled
        # tuning winners per (degree, bucket) slice into the round's
        # tuning DB BEFORE the bench stages run, so their builds consume
        # measured parameters (CPU runs label design-estimate; the
        # evidence stamp records which). The parse line journals the
        # swept winners + the consumption check's stamp.
        _py("autotune", AUTOTUNE, 900, parse=last_json_line),
        # The fused batched engine on hardware (ISSUE 6): batched
        # GDoF/s at serve buckets 2/4/8 + the unfused A/B — converts
        # the per-bucket VMEM tiers from design estimates to
        # measurements the moment the tunnel lives.
        _py("fusedbatch", FUSEDBATCH, 2400),
        # bf16 speed ladder on hardware (ISSUE 17): plain bf16-stream
        # A/B vs f32 (the halved byte model becomes a measured GDoF/s
        # ratio), refinement time_to_rtol_s at f64-class accuracy, and
        # hardware-labelled bf16 tuning sweeps the builds consume.
        _py("bf16", BF16, 2400, parse=last_json_line),
        _py("dfacc", DFACC, 1800, provides="dfacc"),
        _py("pertdf", PERTDF, 2400, gate="dfacc"),
        _py("foldeng", FOLDENG, 2400),
        _py("dfext2d", DFEXT2D, 2400, gate="dfacc"),
        # Weak scaling with overlap A/B (ISSUE 7): fixed 2M local dofs
        # swept over the available device mesh, journaled GDoF/s +
        # per-iteration collective counts per overlap arm. Armed for
        # hardware; the CPU lane proves parity and the one-psum
        # invariant via `--smoke` in CI (multihost gloo lane).
        _script("scale", ["scripts/weak_scaling.py", "--local-dofs",
                          "2000000", "--nreps", "200"], 2400),
        # Convergence telemetry on hardware (ISSUE 10): the flagship
        # problem with per-iteration residual capture — stamps the
        # `convergence` block + the paired time-to-rtol metric with the
        # `hardware` evidence label (the CPU lanes only ever produce
        # cpu-measured times). Capture rides the unfused loop (the
        # fused engine gates off, reason recorded), so this is a paired
        # A/B point next to `ab12`, not a flagship-rate claim.
        _py("conv", _bench_code("CONV12.5M:", dict(
            ndofs_global=12_500_000, degree=3, qmode=1, float_bits=32,
            nreps=1000, use_cg=True, convergence=True),
            tail_expr=', "time_to_rtol",'
                      ' res.extra.get("time_to_rtol_s")'), 1800),
        # Preconditioning on hardware (ISSUE 11): the flagship problem
        # with Jacobi PCG + convergence capture — the A/B point against
        # `conv` above that flips the CPU-measured time-to-rtol win to
        # a hardware number (PCG rides the unfused loop; the engine
        # gate is recorded, so this is a paired convergence claim, not
        # a flagship-rate claim). The chebyshev arm stamps its
        # power-method setup cost + per-iteration apply multiplier.
        _py("precond", _bench_code("PRECOND12.5M:", dict(
            ndofs_global=12_500_000, degree=3, qmode=1, float_bits=32,
            nreps=1000, use_cg=True, convergence=True,
            precond="jacobi"),
            tail_expr=', "time_to_rtol",'
                      ' res.extra.get("time_to_rtol_s")'), 1800),
        _py("precondcheb", _bench_code("PRECONDCHEB12.5M:", dict(
            ndofs_global=12_500_000, degree=3, qmode=1, float_bits=32,
            nreps=400, use_cg=True, convergence=True,
            precond="chebyshev"),
            tail_expr=', "time_to_rtol",'
                      ' res.extra.get("time_to_rtol_s")'), 1800),
        _py("dfeng", _bench_code("DFENG12.5M:", dict(
            ndofs_global=12_500_000, degree=3, qmode=1, float_bits=64,
            nreps=200, use_cg=True, f64_impl="df32"),
            tail_expr=', "vs4.02:", res.gdof_per_second/4.02'),
            1800, gate="dfacc"),
        _py("dfunf", _bench_code("DFUNFUSED12.5M:", dict(
            ndofs_global=12_500_000, degree=3, qmode=1, float_bits=64,
            nreps=50, use_cg=True, f64_impl="df32"),
            setup="import bench_tpu_fem.ops.kron_cg_df as KCD\n"
                  "KCD.engine_plan_df = lambda *a: ('unfused', None)"),
            1800, gate="dfacc"),
        # The df capacity points opt into the OOM degradation ladder: df32
        # roughly doubles per-dof memory vs f32, and a downsized number
        # (journaled with the size measured) beats no number — the
        # generalized form of bench.py:run_df32_side_metric's loop.
        # The capacity ladders carry durable CG snapshots (ISSUE 9,
        # ckpt_every): these are the stages a preemption/wedge most
        # often kills mid-solve, and a retried/resumed attempt restores
        # from the last boundary instead of re-running the whole solve
        # (a downsized OOM rung changes the fingerprint and measures
        # fresh). The fused engines gate off under checkpointing — the
        # ladder stages run the unfused df path anyway.
        _py("dflarge100", _bench_code("DFLARGE100M:", dict(
            ndofs_global=_NDOFS, degree=3, qmode=1, float_bits=64,
            nreps=50, use_cg=True, f64_impl="df32")),
            2400, gate="dfacc", size=100_000_000, floor=25_000_000,
            ckpt_every=10),
        _py("dflarge150", _bench_code("DFLARGE150M:", dict(
            ndofs_global=_NDOFS, degree=3, qmode=1, float_bits=64,
            nreps=30, use_cg=True, f64_impl="df32")),
            2400, gate="dfacc", size=150_000_000, floor=25_000_000,
            ckpt_every=10),
        # f32 capacity points (fixed sizes; the f32 ceiling climb is the
        # measurement itself, so no ladder — an OOM IS the data point).
        _py("large100", _bench_code("LARGE 100000000:", dict(
            ndofs_global=100_000_000, degree=3, qmode=1, float_bits=32,
            nreps=100, use_cg=True)), 2400),
        _py("large128", _bench_code("LARGE 128000000:", dict(
            ndofs_global=128_000_000, degree=3, qmode=1, float_bits=32,
            nreps=100, use_cg=True)), 2400),
        _py("large200", _bench_code("LARGE 200000000:", dict(
            ndofs_global=200_000_000, degree=3, qmode=1, float_bits=32,
            nreps=50, use_cg=True)), 2400),
        _py("large300", _bench_code("LARGE 300000000:", dict(
            ndofs_global=300_000_000, degree=3, qmode=1, float_bits=32,
            nreps=50, use_cg=True)), 2400),
        # bench.py runs under a SHORT retry window here (the agenda only
        # reaches it when health passed; its 2h default is the driver's
        # end-of-round capture) and journals its parent attempts into the
        # same round journal.
        Stage(name="bench",
              command=lambda ctx: [sys.executable, "bench.py"],
              policy=StagePolicy(timeout_s=2400),
              env={"BENCH_WINDOW_S": "1800",
                   "BENCH_ATTEMPT_TIMEOUT_S": "1500",
                   "BENCH_JOURNAL": journal_path,
                   "BENCH_ROUND": round_tag},
              parse=last_json_line, tail=15),
        _script("matrix", ["scripts/baseline_matrix.py",
                           f"BASELINE_MATRIX_{round_tag}.json"], 10800),
        _script("p300", ["scripts/probe_scoped_vmem.py", "q3_300m"], 1800),
        _script("pert100", ["scripts/probe_scoped_vmem.py", "pert100"],
                2100),
        _script("deg7probe", ["scripts/probe_scoped_vmem.py", "deg7probe"],
                1800),
    ]
    return {s.name: s for s in stages}


# Composite measure_all stage names -> granular harness stages.
ALIASES = {
    "ab12": ["ab12", "ab12base"],
    "precond": ["precond", "precondcheb"],
    "large": ["large100", "large128", "large200", "large300"],
    "dfeng": ["dfeng", "dfunf"],
    "dflarge": ["dflarge100", "dflarge150"],
}

# Round-6 default agenda, ordered by value-per-minute under wedge risk
# (measure_all's ordering, expanded through ALIASES).
AGENDAS = {
    "round6": ["health", "serve", "chaos", "overload", "forms", "autotune",
               "fusedbatch", "bf16",
               "dfacc",
               "pertdf", "foldeng", "dfext2d", "scale", "dfeng", "bench",
               "conv", "precond", "dflarge", "pert100", "deg7probe",
               "matrix"],
}


def resolve_stage_names(wanted, stages) -> list[str]:
    """Expand composite aliases; error on unknown names (measure_all's
    CLI contract)."""
    out: list[str] = []
    unknown: list[str] = []
    for name in wanted:
        if name in ALIASES:
            out.extend(ALIASES[name])
        elif name in stages:
            out.append(name)
        else:
            unknown.append(name)
    if unknown:
        valid = sorted(set(stages) | set(ALIASES))
        raise SystemExit(f"unknown stage(s) {unknown}; valid: {valid}")
    # order-preserving dedupe: "dfeng" is both a composite alias and a
    # granular stage name, so naming both must not run dfunf twice
    return list(dict.fromkeys(out))


def make_log(round_tag: str):
    """measure_all's tee logger: [HH:MM:SS] lines to stdout + the round
    log (human narrative; the machine record is the .jsonl journal)."""
    path = os.path.join(ROOT, f"MEASURE_{round_tag}.log")

    def log(msg):
        line = f"[{time.strftime('%H:%M:%S')}] {msg}"
        print(line, flush=True)
        with open(path, "a") as fh:
            fh.write(line + "\n")

    return log


# The current round's shared logger (probe_scoped_vmem and the two
# agendas write one log, one line convention).
log = make_log(DEFAULT_ROUND)


def build_runner(stage_names=None, round_tag: str = DEFAULT_ROUND,
                 agenda: str = "round6") -> Runner:
    stages = make_stages(round_tag)
    names = resolve_stage_names(stage_names or AGENDAS[agenda], stages)
    journal = Journal(default_journal_path(ROOT, round_tag))
    return Runner([stages[n] for n in names], journal,
                  probe=probe_tunnel, log=make_log(round_tag),
                  base_env=base_env(round_tag), cwd=ROOT,
                  round_tag=round_tag)


def watch(stage_names=None, round_tag: str = DEFAULT_ROUND,
          agenda: str = "round6", interval_s: float = 180.0,
          max_cycles: int = 0, sleep=time.sleep) -> int:
    """The watch daemon (replaces scripts/watch_tunnel.sh): probe the
    tunnel every ``interval_s``; on recovery run the agenda RESUMED (the
    round-4 lesson: wedges last hours and recovery windows are precious —
    fire the moment the tunnel returns, skip what the journal already
    holds); if the agenda aborts on a fresh wedge, re-arm instead of
    exiting. ``max_cycles`` bounds probe attempts (0 = unbounded)."""
    log = make_log(round_tag)
    journal = Journal(default_journal_path(ROOT, round_tag))
    cycles = 0
    ran_once = False
    while True:
        cycles += 1
        ok, detail = probe_tunnel()
        journal.append({"event": "probe", "ok": ok, "detail": detail[:300],
                        "source": "watch"})
        if ok:
            log(f"[watch] tunnel up ({detail}); running agenda")
            runner = build_runner(stage_names, round_tag, agenda)
            # Explicitly NAMED stages measure fresh on the first pass
            # (the measure_all contract: re-collecting by name must not
            # replay the journal); re-arms after a wedge always resume —
            # they continue THIS watch session's partial agenda.
            rc = runner.run(resume=ran_once or not stage_names)
            ran_once = True
            if runner.aborted == "tunnel_wedge":
                log("[watch] agenda aborted on a fresh wedge; re-arming")
            else:
                return rc
        else:
            log(f"[watch] tunnel down ({detail}); "
                f"sleeping {interval_s:.0f}s")
        if max_cycles and cycles >= max_cycles:
            log(f"[watch] giving up after {cycles} cycles")
            return 1
        sleep(interval_s)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.harness",
        description="Resilient measurement harness (journaled, resumable,"
                    " fault-classified)")
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("run", help="run a measurement agenda")
    pw = sub.add_parser("watch", help="probe-and-run daemon "
                                      "(watch_tunnel replacement)")
    for sp in (pr, pw):
        sp.add_argument("stages", nargs="*",
                        help="stage names (default: the agenda's list)")
        sp.add_argument("--agenda", default="round6",
                        choices=sorted(AGENDAS))
        sp.add_argument("--round", default=DEFAULT_ROUND,
                        help="round tag stamped on journal/log artifacts")
        sp.add_argument("--trace", action="store_true",
                        help="enable the obs span tracer: stage spans "
                             "fold into the round journal as 'span' "
                             "records (render with python -m "
                             "bench_tpu_fem.obs --journal ...)")
    pr.add_argument("--resume", action="store_true",
                    help="skip journal-completed stages; honor persisted "
                         "gate outcomes")
    pw.add_argument("--interval", type=float, default=180.0,
                    help="probe interval seconds")
    pw.add_argument("--max-cycles", type=int, default=0,
                    help="probe attempts before giving up (0 = unbounded)")
    args = p.parse_args(argv)
    if args.trace:
        from ..obs.trace import enable

        enable(journal=Journal(default_journal_path(ROOT, args.round)))
    if args.cmd == "run":
        runner = build_runner(args.stages or None, args.round, args.agenda)
        return runner.run(resume=args.resume)
    return watch(args.stages or None, args.round, args.agenda,
                 interval_s=args.interval, max_cycles=args.max_cycles)
