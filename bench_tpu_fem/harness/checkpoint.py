"""Crash-safe host-side solve snapshots (the durable half of ISSUE 9's
checkpointable CG: `la.checkpoint` owns the state algebra, this module
owns the bytes).

Write protocol (the journal's fsync discipline, applied to snapshots):

    ckpt-<iteration>.ck.tmp  <- MAGIC | payload_len | crc32 | npz payload
    flush + fsync            (the bytes are durable)
    os.replace -> ckpt-<iteration>.ck   (atomic: readers see old or new,
                                         never a torn file)
    fsync(directory)         (the rename itself is durable)

so a SIGKILL at ANY instant leaves either the previous snapshot intact
or the new one complete — `latest()` walks snapshots newest-first,
validates magic + length + CRC + the embedded meta, and silently skips
anything torn (a `.tmp` the crash stranded, a truncated payload). A
snapshot whose meta fingerprint does not match the restoring solve is
skipped too: resuming a DIFFERENT problem's state would be worse than
restarting.

Only the newest `keep` snapshots are retained (pruned AFTER the new one
is durable, so there is always at least one valid snapshot on disk once
the first save completes).

Chaos seam: ``CHAOS_CKPT_KILL_AFTER=N`` in the environment SIGKILLs the
process right after the Nth successful save — the scripted
"preemption mid-CG" fault `scripts/chaos_soak.py` drives (the kill
lands after the rename+fsync, so the snapshot it proves recovery from
is exactly the one a real preemption would leave behind).

stdlib + numpy only (no jax): snapshots must be writable/readable from
harness tooling even when the accelerator stack is wedged.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import signal
import struct
import zlib

import numpy as np

MAGIC = b"BTFCKPT1"
_HEADER = struct.Struct(">QI")  # payload length, crc32


def solve_fingerprint(**fields) -> str:
    """Deterministic identity of one solve configuration (degree, grid,
    nreps, precision, ...): snapshots only restore into the exact solve
    that wrote them."""
    blob = json.dumps(fields, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class CheckpointStore:
    """Directory of durable snapshots for ONE solve (keyed by its
    fingerprint — a store root may hold many solves' subdirectories)."""

    def __init__(self, root: str, fingerprint: str, keep: int = 2,
                 kill_after: int | None = None):
        self.dir = os.path.join(root, fingerprint)
        self.fingerprint = fingerprint
        self.keep = max(int(keep), 1)
        os.makedirs(self.dir, exist_ok=True)
        if kill_after is None:
            kill_after = int(os.environ.get("CHAOS_CKPT_KILL_AFTER", "0"))
        self.kill_after = kill_after
        self.saves = 0

    # -- write -------------------------------------------------------------

    def save(self, iteration: int, arrays: dict[str, np.ndarray],
             meta: dict | None = None) -> str:
        """Durably write one snapshot at `iteration`; returns its path.
        `meta` rides inside the payload (fingerprint + iteration are
        always stamped) and is validated on restore."""
        meta = dict(meta or {})
        meta["fingerprint"] = self.fingerprint
        meta["iteration"] = int(iteration)
        buf = io.BytesIO()
        np.savez(buf, __meta__=np.frombuffer(
            json.dumps(meta, sort_keys=True).encode(), np.uint8), **arrays)
        payload = buf.getvalue()
        path = os.path.join(self.dir, f"ckpt-{int(iteration):09d}.ck")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(MAGIC)
            fh.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        self._fsync_dir()
        self.saves += 1
        self._prune()
        if self.kill_after and self.saves >= self.kill_after:
            # chaos seam: die AFTER the snapshot is durable (see module
            # docstring) — the recovery test's scripted preemption
            os.kill(os.getpid(), signal.SIGKILL)
        return path

    def _fsync_dir(self) -> None:
        try:
            fd = os.open(self.dir, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # some filesystems refuse directory fsync; best-effort

    def _prune(self) -> None:
        snaps = self._snapshots()
        for _, path in snaps[self.keep:]:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- read --------------------------------------------------------------

    def _snapshots(self) -> list[tuple[int, str]]:
        """(iteration, path) newest-first."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for name in names:
            if not (name.startswith("ckpt-") and name.endswith(".ck")):
                continue
            try:
                it = int(name[5:-3])
            except ValueError:
                continue
            out.append((it, os.path.join(self.dir, name)))
        out.sort(reverse=True)
        return out

    def _read(self, path: str):
        """One validated snapshot or None (torn/corrupt/mismatched —
        recovery skips, never crashes on bad bytes)."""
        try:
            with open(path, "rb") as fh:
                if fh.read(len(MAGIC)) != MAGIC:
                    return None
                head = fh.read(_HEADER.size)
                if len(head) != _HEADER.size:
                    return None
                length, crc = _HEADER.unpack(head)
                payload = fh.read(length)
            if len(payload) != length or zlib.crc32(payload) != crc:
                return None
            with np.load(io.BytesIO(payload)) as z:
                arrays = {k: z[k] for k in z.files if k != "__meta__"}
                meta = json.loads(bytes(z["__meta__"]).decode())
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return None
        if meta.get("fingerprint") != self.fingerprint:
            return None
        return meta.get("iteration", 0), arrays, meta

    def latest(self):
        """Newest valid snapshot as (iteration, arrays, meta), or None.
        Torn/corrupt snapshots are skipped (the crash case, by design —
        the previous durable snapshot then wins)."""
        for _, path in self._snapshots():
            snap = self._read(path)
            if snap is not None:
                return snap
        return None

    def clear(self) -> None:
        for _, path in self._snapshots():
            try:
                os.remove(path)
            except OSError:
                pass
