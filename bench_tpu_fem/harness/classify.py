"""Failure taxonomy: one classifier for every failure artifact line.

Classes (the shared vocabulary — ``error_record``/``record_engine`` refuse
anything else):

  tunnel_wedge   the TPU tunnel / PJRT client is hung or unreachable
                 (device init exceeds its watchdog, UNAVAILABLE /
                 DEADLINE_EXCEEDED transport errors). Policy: health
                 re-probe + bounded backoff — retrying the stage into a
                 wedged tunnel just burns its timeout.
  oom            device memory exhaustion (XLA RESOURCE_EXHAUSTED /
                 "Out of memory"). Policy: size-halving degradation
                 ladder where the stage opts in, else give up.
  mosaic_reject  the Mosaic/Pallas TPU compiler rejected a kernel
                 (deterministic — retrying cannot help; the drivers fall
                 back to chunked/unfused forms and record why).
  accuracy_fail  a correctness gate failed (mat_comp oracle disagreement,
                 "lost f64 accuracy" assertions). Deterministic; when the
                 stage provides a gate (dfacc) the FAIL is persisted so
                 dependent stages stay gated across resumes.
  timeout        the stage overran its budget with no wedge signature —
                 re-probe decides whether it was really a wedge.
  unsupported    a capability/plan gate declined the configuration
                 (folded_df_plan, engine_plan tiers) — not a fault, but a
                 recorded fallback still carries a class.
  transient      everything else (spawn failures, flaky infrastructure);
                 worth a plain bounded retry.

Derivation is rc + output patterns (the only evidence a killed child
leaves), mirroring what the drivers' except-clauses match in-process
(bench.py's RESOURCE_EXHAUSTED test, the Mosaic fallback chains).
"""

from __future__ import annotations

import re

TAXONOMY = (
    "tunnel_wedge",
    "oom",
    "mosaic_reject",
    "accuracy_fail",
    "timeout",
    "unsupported",
    "transient",
)

# Pattern tables, first hit wins within a class. All matched case-
# sensitively except where the compiled regex says otherwise: the strings
# are exact artifacts of XLA/Mosaic/bench.py, not prose.
_OOM_PAT = re.compile(
    r"RESOURCE_EXHAUSTED|Out of memory|MemoryError|\bOOM\b|\boom\b"
)
_MOSAIC_PAT = re.compile(
    r"Mosaic|mosaic|Pallas TPU lowering|pallas_call|scoped vmem|Scoped Vmem"
)
_ACCURACY_PAT = re.compile(
    r"lost f64 accuracy|accuracy_fail|enorm/znorm exceeded|mat_comp mismatch"
    r"|engine did not engage"
)
_WEDGE_PAT = re.compile(
    r"tunnel (?:unavailable|wedged|down)|TPU tunnel|DEADLINE_EXCEEDED"
    r"|UNAVAILABLE|device init/probe exceeded|[Ww]edged"
)
_UNSUPPORTED_PAT = re.compile(
    r"exceeds the df VMEM model|is not supported|unsupported|requires a "
    r"uniform"
)


def classify_text(text: str, timed_out: bool = False) -> str:
    """Classify a failure's textual evidence (child output tail, exception
    string, recorded fallback reason). ``timed_out`` marks that the parent
    killed the child at its deadline — a wedge signature in the partial
    output upgrades that to tunnel_wedge (the round-5 BENCH_r05.json
    failure mode), otherwise it stays a plain timeout for the re-probe
    step to adjudicate."""
    text = text or ""
    # Deterministic, content-specific classes outrank the kill reason: a
    # child that printed an OOM then hung in teardown is an OOM.
    if _ACCURACY_PAT.search(text):
        return "accuracy_fail"
    if _OOM_PAT.search(text):
        return "oom"
    if _MOSAIC_PAT.search(text):
        return "mosaic_reject"
    if _WEDGE_PAT.search(text):
        return "tunnel_wedge"
    if _UNSUPPORTED_PAT.search(text):
        return "unsupported"
    if timed_out:
        return "timeout"
    return "transient"


def classify(rc: int | None, output: str, timed_out: bool = False) -> str | None:
    """Classify a finished child process: None means success. Only an
    actual deadline kill counts as ``timed_out``; rc None WITHOUT a
    timeout is a spawn failure (the child never ran — transient
    infrastructure, not a deadline, so it gets the plain bounded retry
    rather than a tunnel re-probe). Negative rc is a signal death
    (transient unless the output says otherwise)."""
    if rc == 0 and not timed_out:
        return None
    return classify_text(output, timed_out=timed_out)


def classify_exception(exc: BaseException) -> str:
    """In-process twin of ``classify_text`` for the drivers' fallback
    chains and bench.py's single-attempt loop: same taxonomy from an
    exception's type + message."""
    if isinstance(exc, MemoryError):
        return "oom"
    if isinstance(exc, TimeoutError):
        return "timeout"
    return classify_text(f"{type(exc).__name__}: {exc}")
