"""Failure taxonomy: one classifier for every failure artifact line.

Classes (the shared vocabulary — ``error_record``/``record_engine`` refuse
anything else):

  tunnel_wedge   the TPU tunnel / PJRT client is hung or unreachable
                 (device init exceeds its watchdog, UNAVAILABLE /
                 DEADLINE_EXCEEDED transport errors). Policy: health
                 re-probe + bounded backoff — retrying the stage into a
                 wedged tunnel just burns its timeout.
  oom            device memory exhaustion (XLA RESOURCE_EXHAUSTED /
                 "Out of memory"). Policy: size-halving degradation
                 ladder where the stage opts in, else give up.
  mosaic_reject  the Mosaic/Pallas TPU compiler rejected a kernel
                 (deterministic — retrying cannot help; the drivers fall
                 back to chunked/unfused forms and record why).
  accuracy_fail  a correctness gate failed (mat_comp oracle disagreement,
                 "lost f64 accuracy" assertions). Deterministic; when the
                 stage provides a gate (dfacc) the FAIL is persisted so
                 dependent stages stay gated across resumes.
  timeout        the stage overran its budget with no wedge signature —
                 re-probe decides whether it was really a wedge.
  deadline_exceeded
                 the CLIENT's latency budget ran out before the serve
                 stack burned (or finished) a solve (ISSUE 18): the
                 broker refuses at admission / batch formation when the
                 remaining ``SolveSpec.deadline_s`` budget is gone or
                 the predicted completion time exceeds it. Disjoint from
                 ``timeout`` (the HARNESS killed an overrunning child)
                 and from ``tunnel_wedge``'s uppercase gRPC
                 DEADLINE_EXCEEDED transport artifact — this class is
                 the serve layer's own lowercase refusal text. Policy:
                 retriable with backoff (the work was never attempted;
                 resubmitting with a fresh budget — ideally after the
                 shed's ``retry_after_s`` hint — is always safe).
  preempted      the TPU worker/VM was preempted out from under the run
                 (the maintenance/eviction notices preemptible fleets
                 emit). Retriable by definition — the work was fine, the
                 machine went away; with durable CG checkpoints
                 (la.checkpoint + harness.checkpoint) the retry RESUMES
                 from the last snapshot instead of iteration 0.
  breakdown      the CG recurrence broke down numerically (non-finite
                 residual norm, <p, A p> <= 0) — the la.cg sentinel
                 classes (ISSUE 9). Deterministic for a given input:
                 retrying the same solve reproduces it. The CG loops
                 freeze on an exact-zero residual in-loop (never
                 synthesize NaN out of exact convergence); the serve
                 broker answers a non-finite lane `breakdown`
                 lane-locally at retire; the bench drivers stamp the
                 class on any non-finite solve record. The full in-loop
                 guard set (NaN freeze at the last finite iterate,
                 steepest-descent restart, stagnation counters) is the
                 opt-in `cg_solve(sentinel=True)` carry.
  sdc            silent data corruption: an ABFT / true-residual audit
                 caught a FINITE-but-inconsistent value (ISSUE 14 —
                 the mercurial-core class the breakdown sentinels
                 cannot see). Adjudicated by RE-RUN, not by evidence:
                 a single detection is presumed transient (a cosmic
                 ray, a marginal core under a voltage droop) and the
                 solve rolls back to its last durable checkpoint and
                 re-runs ONCE; detected AGAIN on the re-run =
                 deterministic fault (a bad core, a wrong executable),
                 never retried — the serve fleet quarantines the lane
                 instead (serve.fleet).
  unsupported    a capability/plan gate declined the configuration
                 (folded_df_plan, engine_plan tiers) — not a fault, but a
                 recorded fallback still carries a class.
  transient      everything else (spawn failures, flaky infrastructure);
                 worth a plain bounded retry.

Derivation is rc + output patterns (the only evidence a killed child
leaves), mirroring what the drivers' except-clauses match in-process
(bench.py's RESOURCE_EXHAUSTED test, the Mosaic fallback chains).
"""

from __future__ import annotations

import re

TAXONOMY = (
    "tunnel_wedge",
    "oom",
    "mosaic_reject",
    "accuracy_fail",
    "timeout",
    "deadline_exceeded",
    "preempted",
    "breakdown",
    "sdc",
    "unsupported",
    "transient",
)

# Classes worth retrying (capacity/infrastructure went away, the work was
# fine); everything else in the taxonomy is deterministic. The serve
# broker's internal retry and the chaos invariants read this set;
# StagePolicy.retry_on is deliberately narrower (oom and tunnel_wedge
# have their own ladder/probe handling there, not a plain retry).
# `sdc` is deliberately NOT here: membership means "tell the client to
# resubmit", and an sdc-classified failure surfaces only AFTER its
# rollback re-run adjudicated it deterministic — advertising it
# retriable would relaunder corruption through client retries. The ONE
# adjudication re-run is owned by the layers themselves
# (harness.policy's explicit sdc branch; the serve broker's internal
# retry special-cases it the same way).
RETRIABLE_CLASSES = frozenset(
    {"transient", "timeout", "oom", "tunnel_wedge", "preempted",
     "deadline_exceeded"})

# Pattern tables, first hit wins within a class. All matched case-
# sensitively except where the compiled regex says otherwise: the strings
# are exact artifacts of XLA/Mosaic/bench.py, not prose.
_OOM_PAT = re.compile(
    r"RESOURCE_EXHAUSTED|Out of memory|MemoryError|\bOOM\b|\boom\b"
)
_MOSAIC_PAT = re.compile(
    r"Mosaic|mosaic|Pallas TPU lowering|pallas_call|scoped vmem|Scoped Vmem"
)
_ACCURACY_PAT = re.compile(
    r"lost f64 accuracy|accuracy_fail|enorm/znorm exceeded|mat_comp mismatch"
    r"|engine did not engage"
)
_BREAKDOWN_PAT = re.compile(
    r"CG breakdown|breakdown_restarts|non-?finite residual"
    r"|failure_class.{0,4}breakdown|\bCGBreakdown\b"
)
# SDC audit exceedance reports (ISSUE 14): the audited drivers/serve
# phrase every detection with one of these signatures. Checked BEFORE
# the breakdown patterns — an SDC report mentions the residual audit,
# and the classes are disjoint by construction (sdc = finite but
# inconsistent; breakdown = non-finite).
_SDC_PAT = re.compile(
    r"[Ss]ilent data corruption|\bSDC\b|sdc_detected"
    r"|failure_class.{0,4}sdc|ABFT (?:check|audit)"
    r"|(?:true-)?residual audit (?:drift|exceed|failed)"
)
# Real preemptible-fleet eviction notices: the Cloud TPU maintenance-
# event phrasing, the libtpu/gRPC worker-restart ABORTED text, the GCE
# instance-preempted operation, and the k8s pod-eviction message. These
# must outrank the wedge patterns — the gRPC notice embeds UNAVAILABLE,
# and a preemption is NOT a wedge (the machine is gone, not hung; the
# right policy is resume-from-snapshot, not probe-and-wait).
_PREEMPT_PAT = re.compile(
    r"[Pp]reempt(?:ed|ion)|maintenance event"
    r"|[Tt]he TPU worker .{0,40}(?:restarted|terminated)"
    r"|instance was (?:preempted|terminated)"
    r"|[Ee]victed pod|TerminationByKubernetes"
)
# Serve-layer deadline refusals (ISSUE 18): the broker's own lowercase
# phrasing. Deliberately DISJOINT from the wedge table's uppercase gRPC
# DEADLINE_EXCEEDED transport code (case-sensitive on both sides) and
# from every breakdown/timeout signature — a test pins the disjointness.
_DEADLINE_PAT = re.compile(
    r"deadline_exceeded|deadline budget"
    r"|past its deadline|exceeds .{0,40}remaining deadline"
)
_WEDGE_PAT = re.compile(
    r"tunnel (?:unavailable|wedged|down)|TPU tunnel|DEADLINE_EXCEEDED"
    r"|UNAVAILABLE|device init/probe exceeded|[Ww]edged"
)
_UNSUPPORTED_PAT = re.compile(
    r"exceeds the df VMEM model|is not supported|unsupported|requires a "
    r"uniform"
)


def classify_text(text: str, timed_out: bool = False) -> str:
    """Classify a failure's textual evidence (child output tail, exception
    string, recorded fallback reason). ``timed_out`` marks that the parent
    killed the child at its deadline — a wedge signature in the partial
    output upgrades that to tunnel_wedge (the round-5 BENCH_r05.json
    failure mode), otherwise it stays a plain timeout for the re-probe
    step to adjudicate."""
    text = text or ""
    # Deterministic, content-specific classes outrank the kill reason: a
    # child that printed an OOM then hung in teardown is an OOM.
    if _ACCURACY_PAT.search(text):
        return "accuracy_fail"
    if _SDC_PAT.search(text):
        return "sdc"
    if _BREAKDOWN_PAT.search(text):
        return "breakdown"
    if _OOM_PAT.search(text):
        return "oom"
    if _MOSAIC_PAT.search(text):
        return "mosaic_reject"
    if _DEADLINE_PAT.search(text):
        return "deadline_exceeded"
    if _PREEMPT_PAT.search(text):
        return "preempted"
    if _WEDGE_PAT.search(text):
        return "tunnel_wedge"
    if _UNSUPPORTED_PAT.search(text):
        return "unsupported"
    if timed_out:
        return "timeout"
    return "transient"


def classify(rc: int | None, output: str, timed_out: bool = False) -> str | None:
    """Classify a finished child process: None means success. Only an
    actual deadline kill counts as ``timed_out``; rc None WITHOUT a
    timeout is a spawn failure (the child never ran — transient
    infrastructure, not a deadline, so it gets the plain bounded retry
    rather than a tunnel re-probe). Negative rc is a signal death
    (transient unless the output says otherwise)."""
    if rc == 0 and not timed_out:
        return None
    return classify_text(output, timed_out=timed_out)


def classify_exception(exc: BaseException) -> str:
    """In-process twin of ``classify_text`` for the drivers' fallback
    chains and bench.py's single-attempt loop: same taxonomy from an
    exception's type + message."""
    if isinstance(exc, MemoryError):
        return "oom"
    if isinstance(exc, TimeoutError):
        return "timeout"
    return classify_text(f"{type(exc).__name__}: {exc}")
