"""Chaos schedules for the live serve stack (ISSUE 9): faults.py's
scripted-outcome idea, extended from stage subprocesses to the serving
layer's own fault seams so the recovery machinery is CPU-provable.

Each helper drives ONE seam the recovery work owns, and each maps to a
scripted fault in ``scripts/chaos_soak.py``'s schedules:

  worker-thread crash   ``BoundaryCrashHook`` installed as
                        ``serve.engine.BOUNDARY_HOOK`` raises at scripted
                        iteration boundaries inside the broker's
                        disposable solve thread; the broker's bounded
                        retry must RESUME the batch from its parked
                        boundary checkpoint (``serve_retry`` with
                        resumed=true), not restart it at iteration 0.
  injected NaN          a request submitted with ``scale=nan`` poisons
                        exactly one lane's RHS; the breakdown sentinel
                        must answer that request ``failure_class:
                        "breakdown"`` while its batch-mates retire
                        normally (lane algebra is independent).
  preemption mid-CG     ``CHAOS_CKPT_KILL_AFTER=N`` (read by
                        harness.checkpoint.CheckpointStore) SIGKILLs the
                        process right after the Nth durable snapshot —
                        the resumed solve must match the uninterrupted
                        one bitwise (the la.checkpoint restore proof).
  SIGKILL mid-batch     the soak script's generation driver: the parent
                        SIGKILLs a serving child mid-incident, then the
                        next generation replays the shared journal
                        through ``Broker.recover``.
  torn journal tail     ``tear_journal_tail`` appends a deliberately
                        truncated record (the bytes a crash mid-write
                        strands); recovery must drop exactly that line
                        (``read_records``' torn-tail rule) and replay
                        the request it failed to answer.
  silent corruption     ``harness.faults.SdcInjectionHook`` installed as
                        ``serve.engine.SDC_HOOK`` (ISSUE 14) bit-flips
                        one lane's iterates at scripted continuous-
                        batching boundaries — FINITE and wrong, so the
                        breakdown sentinel never fires; the broker's
                        retire-time audit must detect it, roll the lane
                        back once (the re-run adjudicates), answer
                        ``failure_class: "sdc"`` on a second detection,
                        and the fleet's windowed quarantine must
                        isolate the lane with an exactly-once queue
                        drain and a self-test readmission.

The soak invariant the schedules are judged against is
``serve.recovery.verify_exactly_once`` over the WHOLE journal — all
generations appended to one file: every submitted request answered
exactly once, no losses, no duplicates.

stdlib-only (the serve imports are lazy): the harness package stays
importable with the accelerator stack wedged.
"""

from __future__ import annotations

import json
import os

from .journal import _torn_tail


class BoundaryCrash(RuntimeError):
    """The scripted worker-thread death. The message classifies
    `transient` (harness taxonomy) so the broker's bounded retry — not
    the client — absorbs it."""

    def __init__(self, boundary: int):
        super().__init__(
            f"Traceback: injected worker-thread crash at iteration "
            f"boundary {boundary} (chaos schedule)")
        self.boundary = boundary


class BoundaryCrashHook:
    """Scripted ``serve.engine.BOUNDARY_HOOK``: raises BoundaryCrash at
    each scripted boundary index (indices count BOUNDARY_HOOK calls
    across the broker's solve attempts, so ``crash_at=[2, 5]`` kills the
    worker thread twice; a resumed attempt continues the count). Calls
    are recorded for assertions."""

    def __init__(self, crash_at):
        self.crash_at = set(int(b) for b in crash_at)
        self.calls = 0
        self.crashes: list[int] = []

    def __call__(self, spec, boundary_iter) -> None:
        i = self.calls
        self.calls += 1
        if i in self.crash_at:
            self.crash_at.discard(i)
            self.crashes.append(i)
            raise BoundaryCrash(i)


def tear_journal_tail(path: str,
                      rid: str = "r999999",
                      event: str = "serve_response") -> str:
    """Append a deliberately TORN record (no trailing newline, truncated
    mid-value): byte-for-byte what a crash between ``write`` and the end
    of ``Journal.append``'s line leaves behind. Returns the bytes
    written. ``read_records`` must drop exactly this line, so a torn
    ``serve_response`` must NOT count as answered (the client was never
    released — the fsync never returned) and the request replays."""
    frag = json.dumps({"event": event, "id": rid, "ok": True})[:-8]
    with open(path, "a") as fh:
        fh.write("\n" if _torn_tail(path) else "")
        fh.write(frag)
        fh.flush()
        os.fsync(fh.fileno())
    return frag


def install_boundary_hook(hook):
    """Install/uninstall helper (pairs with a try/finally):
    ``prev = install_boundary_hook(h)`` ... ``install_boundary_hook(prev)``."""
    from ..serve import engine as _engine

    prev = _engine.BOUNDARY_HOOK
    _engine.BOUNDARY_HOOK = hook
    return prev


def install_fault_hook(hook):
    """Install/uninstall helper for the scripted-solve-fault seam
    (``serve.engine.FAULT_HOOK`` — FaultySolveHook, HeldSolveHook) —
    same try/finally pairing as `install_boundary_hook`. The hook runs
    at the top of every compiled-solver execution and may raise a
    classified fault, sleep past a deadline, or block until released
    (the ISSUE 18 deterministic straggler)."""
    from ..serve import engine as _engine

    prev = _engine.FAULT_HOOK
    _engine.FAULT_HOOK = hook
    return prev


def install_sdc_hook(hook):
    """Install/uninstall helper for the silent-corruption seam
    (``serve.engine.SDC_HOOK``, ISSUE 14) — same try/finally pairing as
    `install_boundary_hook`. The hook (harness.faults.SdcInjectionHook)
    is called after every continuous-batching cont_step and may hand a
    bit-flipped state back to the solve."""
    from ..serve import engine as _engine

    prev = _engine.SDC_HOOK
    _engine.SDC_HOOK = hook
    return prev
