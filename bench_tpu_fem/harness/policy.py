"""Per-stage retry/timeout/backoff policy + the OOM degradation ladder.

The policy layer is a pure decision table: given a failure class and the
attempt history, return the next action. All clocks/sleeps live in the
runner (injectable for the fault-injection tests); nothing here blocks.

The OOM ladder generalises bench.py:run_df32_side_metric's one-off
halving loop: any stage that opts in (``StagePolicy.oom_ladder``) walks
requested → requested/2 → ... → floor before giving up, and the size
actually measured is journaled (evidence-hygiene: a downsized number must
say so).
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Action kinds the runner executes.
RETRY = "retry"                    # same stage, same size, after wait_s
DEGRADE = "degrade"                # same stage at next_size (OOM ladder)
REPROBE = "reprobe"                # health-probe loop w/ backoff, then retry
GIVE_UP = "give_up"                # stage failed terminally


@dataclass(frozen=True)
class Action:
    kind: str
    wait_s: float = 0.0
    next_size: int | None = None
    reason: str = ""


@dataclass(frozen=True)
class OomLadder:
    """Size-halving degradation ladder. ``floor`` is the smallest size
    still worth measuring (bench.py's df32 side metric uses 2M dofs: a
    halved size still yields the round's df headline where the flagship
    size OOMs)."""

    floor: int
    factor: float = 0.5

    def next_size(self, size: int) -> int | None:
        nxt = int(size * self.factor)
        return nxt if nxt >= self.floor else None

    def sizes(self, start: int):
        """All ladder rungs from ``start`` down to the floor (the
        in-process consumers — bench.py — iterate this)."""
        size = start
        while size >= min(self.floor, start):
            yield size
            nxt = int(size * self.factor)
            if nxt == size:
                break
            size = nxt
            if size < self.floor:
                break


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff (the round-4 lesson: one
    180 s fail-fast at capture time turned a 2.31x round into an official
    0.0 artifact — but unbounded retries burn the recovery window)."""

    max_attempts: int = 2
    backoff_s: float = 60.0
    backoff_factor: float = 2.0
    max_backoff_s: float = 900.0

    def backoff(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        return min(
            self.backoff_s * self.backoff_factor ** max(attempt - 1, 0),
            self.max_backoff_s,
        )


@dataclass(frozen=True)
class StagePolicy:
    timeout_s: float = 900.0
    retry: RetryPolicy = field(default_factory=RetryPolicy)
    # Classes worth a plain same-size retry. Deterministic failures
    # (mosaic_reject / accuracy_fail / unsupported / breakdown) never
    # are. `preempted` retries by definition — the work was fine, the
    # machine went away; with durable CG checkpoints the retry resumes
    # from the last snapshot instead of iteration 0.
    # `deadline_exceeded` (ISSUE 18) retries WITH BACKOFF: the serve
    # layer refused before burning a solve, so resubmitting is always
    # safe — and the backoff is the point, since the refusal means the
    # fleet was overloaded right now.
    retry_on: tuple[str, ...] = ("transient", "timeout", "preempted",
                                 "deadline_exceeded")
    # Bounded wedge recovery: how many probe×backoff rounds one stage may
    # spend waiting for the tunnel before the agenda aborts (wedges last
    # hours; the watch daemon re-arms at that horizon instead).
    wedge_max_probes: int = 5
    oom_ladder: OomLadder | None = None


def next_action(
    failure_class: str,
    attempt: int,
    policy: StagePolicy,
    size: int | None = None,
) -> Action:
    """The decision table. ``attempt`` is the 1-based attempt that just
    failed; ladder rungs do not consume plain-retry budget (a stage that
    OOMs four times down the ladder has learned something each time)."""
    if failure_class == "oom" and policy.oom_ladder and size is not None:
        nxt = policy.oom_ladder.next_size(size)
        if nxt is not None:
            return Action(DEGRADE, next_size=nxt,
                          reason=f"oom ladder {size} -> {nxt}")
        return Action(GIVE_UP,
                      reason=f"oom ladder exhausted at floor (size {size})")
    if failure_class == "tunnel_wedge":
        if policy.wedge_max_probes > 0:
            return Action(REPROBE, wait_s=policy.retry.backoff(attempt),
                          reason="tunnel wedge: re-probe + bounded backoff")
        return Action(GIVE_UP, reason="tunnel wedge (probing disabled)")
    if failure_class == "sdc":
        # SDC adjudication (ISSUE 14): the re-run IS the verdict. One
        # detection is presumed a transient upset (the checkpointed
        # drivers roll back to the last durable snapshot, so the retry
        # resumes, not restarts); a SECOND detection on the re-run is a
        # deterministic fault — a bad core or a wrong executable — and
        # retrying it again would just launder corruption into the
        # measurement record. The fleet's response to the deterministic
        # verdict is lane quarantine (serve.fleet), not another retry.
        if attempt < 2:
            return Action(RETRY, wait_s=policy.retry.backoff(attempt),
                          reason="sdc: single detection — rollback "
                                 "re-run adjudicates transient vs "
                                 "deterministic")
        return Action(GIVE_UP, reason="sdc detected again on the re-run: "
                                      "deterministic fault, never retried")
    if failure_class in policy.retry_on and attempt < policy.retry.max_attempts:
        return Action(RETRY, wait_s=policy.retry.backoff(attempt),
                      reason=f"{failure_class}: retry "
                             f"{attempt + 1}/{policy.retry.max_attempts}")
    return Action(GIVE_UP, reason=f"{failure_class}: no retry "
                                  f"(attempt {attempt})")
