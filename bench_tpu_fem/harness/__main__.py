"""``python -m bench_tpu_fem.harness`` — run/watch measurement agendas."""

import sys

from .agenda import main

if __name__ == "__main__":
    sys.exit(main())
