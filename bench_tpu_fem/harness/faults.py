"""Fault injection: scripted stage outcomes + probes so the runner state
machine (wedge→backoff→recover→resume, crash-mid-stage replay, OOM
ladder, gate-fail propagation) is CPU-testable in CI with no hardware.

An injected outcome is just a SubprocessResult the executor returns in
place of spawning a child; the canned output texts are real artifacts of
the failure modes they simulate (XLA's RESOURCE_EXHAUSTED phrasing, the
Mosaic lowering rejection, bench.py's watchdog line), so the classifier
is exercised on the same evidence hardware produces.
"""

from __future__ import annotations

from .runner import SubprocessResult

# Canned child-output texts, verbatim from the failure modes they model.
OOM_TEXT = (
    "jaxlib.xla_extension.XlaRuntimeError: RESOURCE_EXHAUSTED: "
    "Out of memory while trying to allocate 12884901888 bytes."
)
MOSAIC_TEXT = (
    "ValueError: The Pallas TPU lowering currently requires that the last "
    "two dimensions of your block shape are divisible by 8 and 128 "
    "respectively. Mosaic lowering failed."
)
ACCURACY_TEXT = (
    "AssertionError: df one-kernel lost f64 accuracy\n"
    "DFACC one: enorm/znorm 3.1e-05"
)
WEDGE_TEXT = (
    '{"metric": "cg_gdof_per_s_per_chip_q3_f32", "value": 0.0, '
    '"unit": "GDoF/s", "vs_baseline": 0.0, "error": "device init/probe '
    'exceeded 180s (TPU tunnel unavailable/wedged)", '
    '"failure_class": "tunnel_wedge"}'
)
HANG_PARTIAL = (
    "% Element tables (quadrature+basis): 0.41s\n"
    "% Build box mesh: 1.73s\n"
    "% Create matfree operator:"  # ...and then nothing, ever
)
# The libtpu/gRPC worker-restart notice a preempted Cloud TPU fleet
# emits (embeds UNAVAILABLE: the preemption patterns must outrank the
# wedge patterns, harness.classify) and the GCE operation text.
PREEMPT_TEXT = (
    "jaxlib.xla_extension.XlaRuntimeError: UNAVAILABLE: "
    "The TPU worker at address 10.0.0.7:8471 restarted unexpectedly "
    "(maintenance event: the instance was preempted)."
)
# The SDC audit's own exceedance report (ISSUE 14): what the audited
# drivers/serve raise when a finite-but-inconsistent solve is detected
# AGAIN after rollback — the deterministic-fault adjudication. The
# phrasing is the classifier's `sdc` signature.
SDC_TEXT = (
    "RuntimeError: silent data corruption detected again after "
    "checkpoint rollback (true-residual audit drift 3.2e-01 > envelope "
    "1.0e-03): deterministic fault, failure_class sdc"
)


def ok(out: str = "STAGE OK", wall_s: float = 1.0) -> SubprocessResult:
    return SubprocessResult(0, out, False, wall_s)


def crash(rc: int = 1, out: str = "Traceback: something transient",
          wall_s: float = 1.0) -> SubprocessResult:
    return SubprocessResult(rc, out, False, wall_s)


def oom(out: str = OOM_TEXT) -> SubprocessResult:
    return SubprocessResult(1, out, False, 5.0)


def mosaic_reject(out: str = MOSAIC_TEXT) -> SubprocessResult:
    return SubprocessResult(1, out, False, 5.0)


def accuracy_fail(out: str = ACCURACY_TEXT) -> SubprocessResult:
    return SubprocessResult(1, out, False, 5.0)


def hang(partial: str = HANG_PARTIAL, wall_s: float = 900.0) -> SubprocessResult:
    """Timed out + killed: rc None, PARTIAL output preserved (the
    evidence of where it hung)."""
    return SubprocessResult(None, partial, True, wall_s)


def preempted(out: str = PREEMPT_TEXT) -> SubprocessResult:
    """The machine went away mid-stage (SIGKILL'd by the fleet: negative
    rc, the eviction notice in the tail)."""
    return SubprocessResult(-9, out, False, 30.0)


class Killed(BaseException):
    """Raised by a scripted outcome to simulate the harness process itself
    dying mid-stage (SIGKILL): the attempt_start record is in the journal,
    the attempt_end never lands."""


def kill_harness():
    def _raise() -> SubprocessResult:
        raise Killed()

    return _raise


class FaultyExecutor:
    """Scripted stage executor: ``script`` maps stage name -> list of
    outcomes (SubprocessResult, or a callable returning one — callables
    let a script raise Killed). Each execution pops the next outcome; a
    stage past its script (or unscripted) succeeds. Every call is
    recorded as (stage_name, attempt, size) for assertions."""

    def __init__(self, script: dict[str, list]):
        self.script = {k: list(v) for k, v in script.items()}
        self.calls: list[tuple[str, int, int | None]] = []

    def __call__(self, stage, ctx) -> SubprocessResult:
        self.calls.append((stage.name, ctx.attempt, ctx.size))
        seq = self.script.get(stage.name)
        outcome = seq.pop(0) if seq else ok()
        if callable(outcome):
            outcome = outcome()
        return outcome


class FlakyProbe:
    """Scripted health probe: yields the scripted booleans, then stays at
    the final value (a recovered tunnel stays up; a dead one stays down)."""

    def __init__(self, results: list[bool]):
        self.results = list(results)
        self.calls = 0

    def __call__(self) -> tuple[bool, str]:
        self.calls += 1
        if self.results:
            up = self.results.pop(0) if len(self.results) > 1 else self.results[0]
        else:
            up = True
        return up, f"scripted probe #{self.calls}: {'up' if up else 'down'}"


class FakeSleep:
    """Records requested sleeps instead of blocking (the backoff
    assertions read ``waits``)."""

    def __init__(self):
        self.waits: list[float] = []

    def __call__(self, seconds: float) -> None:
        self.waits.append(seconds)


class FaultySolveHook:
    """Scripted serve-layer solve faults: install as
    ``serve.engine.FAULT_HOOK`` and every compiled-solver execution pops
    the next outcome — "ok" passes through, "oom"/"mosaic"/"accuracy"
    raise RuntimeErrors carrying the canned hardware texts (so the
    broker's classifier sees the same evidence real failures produce),
    "hang" sleeps past the broker's batch deadline (the
    abandoned-thread path), "crash" raises a transient. Past the end of
    the script everything succeeds — an incident that ENDS, so the test
    can also assert recovery. Calls are recorded for assertions."""

    def __init__(self, script: list[str], hang_s: float = 30.0,
                 sleep=None):
        import time as _time

        self.script = list(script)
        self.hang_s = hang_s
        self.sleep = sleep or _time.sleep
        self.calls: list[tuple[str, int]] = []

    def __call__(self, spec, scales) -> None:
        outcome = self.script.pop(0) if self.script else "ok"
        self.calls.append((outcome, len(scales)))
        if outcome == "ok":
            return
        if outcome == "oom":
            raise RuntimeError(OOM_TEXT)
        if outcome == "mosaic":
            raise RuntimeError(MOSAIC_TEXT)
        if outcome == "accuracy":
            raise RuntimeError(ACCURACY_TEXT)
        if outcome == "preempt":
            raise RuntimeError(PREEMPT_TEXT)
        if outcome == "sdc":
            raise RuntimeError(SDC_TEXT)
        if outcome == "hang":
            self.sleep(self.hang_s)
            return
        raise RuntimeError(f"Traceback: injected {outcome} fault")


class HeldSolveHook:
    """Deterministic straggler (ISSUE 18 hedged dispatch): install as
    ``serve.engine.FAULT_HOOK`` and the first ``hold`` solver executions
    BLOCK on an Event until the test calls ``release()`` — a lane that
    is alive but arbitrarily slow, which is exactly the tail hedging
    rescues. Unlike FaultySolveHook's "hang" (a fixed sleep), the
    straggler's duration is under TEST control: hedge the queued victim,
    assert the hedge wins on the healthy lane, THEN release the
    straggler and assert its late retire loses the claim CAS cleanly.
    Executions past the hold count pass through untouched. ``waited``
    records each held call's (spec degree, lane count) for assertions;
    ``timeout_s`` bounds the block so a test bug cannot wedge the
    suite."""

    def __init__(self, hold: int = 1, timeout_s: float = 60.0):
        import threading as _threading

        self.hold = int(hold)
        self.timeout_s = timeout_s
        self.release_evt = _threading.Event()
        self.held = 0
        self.waited: list[tuple[int, int]] = []

    def release(self) -> None:
        self.release_evt.set()

    def __call__(self, spec, scales) -> None:
        if self.held >= self.hold:
            return
        self.held += 1
        self.waited.append((getattr(spec, "degree", -1), len(scales)))
        self.release_evt.wait(self.timeout_s)


# ---------------------------------------------------------------------------
# Silent-data-corruption injection (ISSUE 14): the CHAOS_SDC seam.
#
# A mercurial core flips a bit and the value stays FINITE — so the
# injector must too: one seeded XOR of a finite-exponent bit in one
# element of live solver state, deterministic, and BITWISE OFF when not
# armed (the off path runs zero extra code). Two seams share the model:
# the audited CG loop takes `la.cg.SdcInject` (jit-safe, in-loop), and
# the host-visible boundaries (the driver's checkpointed loop, the serve
# broker's continuous batches) take the numpy flip below.
# ---------------------------------------------------------------------------


def sdc_env_plan(env: dict | None = None) -> dict | None:
    """Parse the ``CHAOS_SDC`` environment seam into an injection plan,
    or None when unarmed. Format: ``iter=8[,bit=26][,index=-1][,once=0]``
    — flip `bit` of element `index` (−1 = largest magnitude) of the
    solve state once the loop crosses iteration `iter`; ``once=1`` (the
    default) fires a single time ever (the TRANSIENT fault model — a
    rollback re-run comes back clean), ``once=0`` re-fires on every
    crossing (the DETERMINISTIC model — the re-run detects again and
    the adjudication goes terminal)."""
    import os

    raw = (env if env is not None else os.environ).get("CHAOS_SDC", "")
    if not raw:
        return None
    plan = {"iteration": None, "bit": None, "index": -1, "once": True}
    for part in raw.split(","):
        key, _, val = part.strip().partition("=")
        if key in ("iter", "iteration"):
            plan["iteration"] = int(val)
        elif key == "bit":
            plan["bit"] = int(val)
        elif key == "index":
            plan["index"] = int(val)
        elif key == "once":
            plan["once"] = bool(int(val))
    if plan["iteration"] is None:
        raise ValueError(f"CHAOS_SDC={raw!r}: needs iter=<N>")
    return plan


def flip_host_bit(arr, index: int = -1, bit: int | None = None):
    """XOR one bit of one element of a host numpy array (returns a
    copy): the mercurial-core model applied at a host-visible boundary.
    ``index`` < 0 flips the largest-magnitude element (guaranteed above
    any scale-normalised audit envelope); ``bit`` None picks the
    per-dtype finite-exponent default (ops.abft.DEFAULT_FLIP_BIT)."""
    import numpy as np

    out = np.array(arr, copy=True)
    flat = out.reshape(-1)
    if bit is None:
        # the canonical per-dtype default lives with the detector
        # (ops.abft): the injector must corrupt exactly the way the
        # detector is judged against, so there is ONE set of constants
        from ..ops.abft import default_flip_bit

        bit = default_flip_bit(flat.dtype)
    idx = int(np.argmax(np.abs(flat))) if index < 0 else int(index)
    udt = np.uint32 if flat.dtype.itemsize == 4 else np.uint64
    word = flat[idx:idx + 1].view(udt)
    word ^= udt(1) << udt(bit)
    return out


class SdcInjectionHook:
    """Scripted ``serve.engine.SDC_HOOK``: at each scripted boundary
    index (counting SDC_HOOK calls across the broker's continuous
    batches, the BoundaryCrashHook convention) it bit-flips lane
    ``lane``'s solution iterate in the batched CG state and hands the
    corrupted state back to the solve — finite, wrong, and invisible to
    everything except the retire-time audit. Works on both the f32/f64
    `BatchedCGState` and the df `BatchedCGStateDF` (hi channel). Calls
    and firings are recorded for assertions."""

    def __init__(self, corrupt_at, lane: int = 0, index: int = -1,
                 bit: int | None = None):
        self.corrupt_at = set(int(b) for b in corrupt_at)
        self.lane = int(lane)
        self.index = index
        self.bit = bit
        self.calls = 0
        self.fired: list[int] = []

    def __call__(self, spec, boundary_iter, state):
        i = self.calls
        self.calls += 1
        if i not in self.corrupt_at:
            return None
        self.corrupt_at.discard(i)
        self.fired.append(i)
        import jax.numpy as jnp
        import numpy as np

        X = state.X
        if hasattr(X, "hi"):  # df (hi, lo) pair: corrupt the hi channel
            hi = np.asarray(X.hi)
            lane_flat = flip_host_bit(hi[self.lane], self.index, self.bit)
            hi = np.array(hi, copy=True)
            hi[self.lane] = lane_flat
            return state._replace(X=type(X)(jnp.asarray(hi), X.lo))
        host = np.asarray(X)
        lane_flat = flip_host_bit(host[self.lane], self.index, self.bit)
        host = np.array(host, copy=True)
        host[self.lane] = lane_flat
        return state._replace(X=jnp.asarray(host))
