"""Logging setup (replaces spdlog + dolfinx init_logging,
/root/reference/src/main.cpp:229, util.cpp)."""

from __future__ import annotations

import logging


def init_logging(level: str = "info") -> logging.Logger:
    logger = logging.getLogger("bench_tpu_fem")
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("[%(asctime)s] [%(levelname)s] %(message)s", "%H:%M:%S")
        )
        logger.addHandler(handler)
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    return logger
