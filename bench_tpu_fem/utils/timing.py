"""Named wall-clock timer registry.

Replaces `dolfinx::common::Timer` + `list_timings` (MPI_MAX aggregated table,
/root/reference/src/main.cpp:314, laplacian_solver.cpp:90,174-198). Timers
accumulate by name in a process-local registry; `timer_report` renders the
table. Scope note: JAX here is single-controller — one Python process
drives every device — so one registry IS the whole-job view and no
cross-host reduction exists (the reference needs MPI_MAX only because each
rank times independently). A future multi-controller deployment would
max-reduce `timings()` across processes before printing.
"""

from __future__ import annotations

import time
from collections import defaultdict

_registry: dict[str, list[float]] = defaultdict(list)


class Timer:
    """Context manager: `with Timer("% assemble"): ...`"""

    def __init__(self, name: str):
        self.name = name
        self.elapsed = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        _registry[self.name].append(self.elapsed)
        return False


def timings() -> dict[str, dict[str, float]]:
    return {
        name: {
            "count": len(vals),
            "total": sum(vals),
            "max": max(vals),
        }
        for name, vals in _registry.items()
    }


def timer_report() -> str:
    rows = [f"{'Timer':<40s} {'count':>6s} {'total (s)':>12s} {'max (s)':>12s}"]
    for name, t in sorted(timings().items()):
        rows.append(f"{name:<40s} {t['count']:>6d} {t['total']:>12.4f} {t['max']:>12.4f}")
    return "\n".join(rows)


def reset_timers() -> None:
    _registry.clear()
