"""Named wall-clock timer registry.

Replaces `dolfinx::common::Timer` + `list_timings` (MPI_MAX aggregated table,
/root/reference/src/main.cpp:314, laplacian_solver.cpp:90,174-198). Timers
accumulate by name in a process-local registry; `aggregated_timings`
folds the table, max-reducing across controller processes first when running
multi-controller (utils.multihost) — the reference needs MPI_MAX because
each rank times independently, and a multi-controller JAX job is in the
same position. Single-controller (the common case: one Python process
drives every device) the local registry IS the whole-job view and no
communication happens.

Rendering lives in the obs layer: the CLI banner and the obs CLI both
use ``obs.report.render_timer_rows`` over ``aggregated_timings()`` /
span aggregates (the deprecated ``timer_report`` shim flagged in the
observability PR has been removed). New attribution work should use the
obs span tracer (``bench_tpu_fem.obs.trace``) + ``python -m
bench_tpu_fem.obs`` — span tree, Chrome trace export and roofline table
on top of the same count/total/max shape (README "Observability").
"""

from __future__ import annotations

import time
from collections import defaultdict

import numpy as np

_registry: dict[str, list[float]] = defaultdict(list)


class Timer:
    """Context manager: `with Timer("% assemble"): ...`"""

    def __init__(self, name: str):
        self.name = name
        self.elapsed = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        _registry[self.name].append(self.elapsed)
        return False


def timings() -> dict[str, dict[str, float]]:
    return {
        name: {
            "count": len(vals),
            "total": sum(vals),
            "max": max(vals),
        }
        for name, vals in _registry.items()
    }


def _reduce_gathered(names: list[str],
                     gathered: np.ndarray) -> dict[str, dict[str, float]]:
    """MPI_MAX-equivalent fold of per-process timer rows: `gathered` is
    (nproc, len(names), 3) of [count, total, max] rows in `names` order.
    Split out from aggregated_timings so the reduction is unit-testable
    without a multi-process run."""
    return {
        name: {
            "count": int(gathered[:, i, 0].max()),
            "total": float(gathered[:, i, 1].max()),
            "max": float(gathered[:, i, 2].max()),
        }
        for i, name in enumerate(names)
    }


# fixed width so the allgather sees identical shapes on every process.
# The readable head is truncated at 4 KiB; divergence PAST the cap is
# caught by the appended metadata row: len(names) plus a sha256-derived
# 8-byte digest of the FULL joined list, so name lists that agree in the
# first 4 KiB but diverge beyond it can no longer silently max-reduce
# unrelated phases against each other.
_NAMES_CAP = 4096
_NAMES_META = 16  # 8-byte big-endian length + 8-byte sha256 prefix
_NAMES_ROW = _NAMES_CAP + _NAMES_META


def _names_blob(names: list[str]) -> np.ndarray:
    """Fixed-width uint8 encoding of the phase-name list for allgather
    (uint8 is exempt from the x64-off f64→f32 demotion, so the check can
    run outside the x64 save/restore): the truncated readable head plus
    the full-list length/digest metadata."""
    import hashlib

    joined = ("\x1f".join(names)).encode()
    head = joined[:_NAMES_CAP].ljust(_NAMES_CAP, b"\0")
    meta = (len(names).to_bytes(8, "big")
            + hashlib.sha256(joined).digest()[:8])
    return np.frombuffer(head + meta, dtype=np.uint8).copy()


def _check_gathered_names(gathered_names: np.ndarray, names: list[str]) -> None:
    """Raise if any process gathered a different phase-name list: equal
    phase COUNTS with divergent NAMES (an engine fallback firing on one
    host only) would otherwise reshape fine and silently max-reduce
    unrelated phases against each other. The digest row extends the
    check past the 4 KiB readable cap."""
    rows = np.asarray(gathered_names).reshape(-1, _NAMES_ROW)
    if not (rows == rows[0]).all():
        raise RuntimeError(
            "timer phase names diverge across processes; cannot "
            f"max-reduce the timing table (local names: {names})"
        )


def aggregated_timings() -> dict[str, dict[str, float]]:
    """`timings()`, max-reduced across controller processes when the job
    is multi-controller (`jax.process_count() > 1`) — the reference's
    `list_timings` MPI_MAX table (main.cpp:314). Requires every process
    to have timed the same phases (the SPMD drivers do; the reference's
    list_timings carries the same symmetry assumption). Single-process
    returns the local registry untouched, without any device traffic."""
    local = timings()
    if not local:
        # empty registry: nothing to reduce (and a 0-row gather would
        # fail to reshape) — every process sees the same empty table,
        # and the path stays jax-free (no backend init for no table)
        return local
    import jax

    if jax.process_count() == 1:
        return local
    from jax.experimental import multihost_utils

    names = sorted(local)
    rows = np.array(
        [[local[n]["count"], local[n]["total"], local[n]["max"]]
         for n in names],
        dtype=np.float64,
    )
    # keep the f64 rows through the gather: without x64 the collective
    # silently demotes to f32 (the drivers deliberately leave x64 off).
    # Explicit save/restore of the config flag — jax.experimental has no
    # enable_x64 context manager in the installed jax.
    _check_gathered_names(
        multihost_utils.process_allgather(_names_blob(names)), names)
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        gathered = np.asarray(multihost_utils.process_allgather(rows))
    finally:
        jax.config.update("jax_enable_x64", prev)
    return _reduce_gathered(names, gathered.reshape(-1, len(names), 3))


def reset_timers() -> None:
    _registry.clear()
