"""Compatibility shims for older JAX versions.

The framework targets current JAX, where `jax.shard_map` is a top-level
API with a `check_vma` flag and `jax.lax.pcast` adjusts varying-mesh-axes
types. Some deployment containers pin jax 0.4.x, where the same
functionality lives at `jax.experimental.shard_map.shard_map` with the
older `check_rep` flag and no VMA system at all. These shims install the
new names on old installations so the dist layer runs unmodified:

- `jax.shard_map`: forwards to the experimental entry point, translating
  `check_vma=` to `check_rep=` (semantically the corresponding check in
  the pre-VMA representation system);
- `jax.lax.pcast`: identity. pcast exists purely to satisfy the VMA type
  system (marking a replicated value as device-varying so loop-carry
  types match); without that system the value itself is already correct.

On current JAX every `hasattr` gate passes and this module does nothing.
Applied once from the package `__init__` (idempotent).
"""

from __future__ import annotations


def apply_compat_shims() -> None:
    import jax

    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, **kw):
            # check_rep (the pre-VMA replication lint) cannot type the
            # drivers' frozen-state CG carries — on old jax its own error
            # message prescribes check_rep=False as the workaround, and
            # pcast (the new-API fix) does not exist to express the
            # annotation. The check is a lint, never semantics.
            kw.pop("check_vma", None)
            kw["check_rep"] = False
            return _shard_map(f, **kw)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            from jax._src import core as _core

            env = _core.get_axis_env()
            if isinstance(axis_name, (tuple, list)):
                size = 1
                for n in axis_name:
                    size *= env.axis_size(n)
                return size
            return env.axis_size(axis_name)

        jax.lax.axis_size = axis_size

    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axis_name, to=None, **kw):  # noqa: ARG001
            return x

        jax.lax.pcast = pcast

    _ensure_optimization_barrier_batching()


def _ensure_optimization_barrier_batching() -> None:
    """Older jax (0.4.x) ships no vmap batching rule for
    `optimization_barrier`, which breaks `jax.vmap` over anything built
    on la.df64 (every df product launders its operands through a barrier)
    — exactly what the serve layer's batched df32 path does. The barrier
    is semantically an identity with a compiler fence, so the batching
    rule is a pass-through: bind the primitive on the batched operands,
    keep each operand's batch dim. Current jax registers its own rule
    and this is a no-op."""
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:  # pragma: no cover - layout drift in future jax
        return
    if optimization_barrier_p in batching.primitive_batchers:
        return

    def _batcher(args, dims, **params):
        return optimization_barrier_p.bind(*args, **params), dims

    batching.primitive_batchers[optimization_barrier_p] = _batcher
