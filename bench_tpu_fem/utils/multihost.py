"""Multi-host (DCN) initialisation glue.

The reference scales across hosts with one MPI rank per GPU (README.md:94-96,
`mpirun`/SLURM launch, examples/submit.sh). The JAX-native equivalent is
multi-controller SPMD: every host runs the same program, calls
`jax.distributed.initialize()`, and then `jax.devices()` spans the whole
slice/pod — after which the framework's `shard_map` code (dist/) is
UNCHANGED: the device grid simply contains remote devices, XLA routes
`ppermute` neighbours over ICI within a slice and DCN across slices, and
`psum`/`pmax` reductions span everything (the MPI_Allreduce analogue,
vector.hpp:173).

On Cloud TPU pods the coordinator/process-id/process-count arguments are
discovered from the TPU environment automatically; on other clusters they
come from the standard JAX env vars (JAX_COORDINATOR_ADDRESS,
JAX_PROCESS_ID / JAX_NUM_PROCESSES) that launchers such as SLURM scripts
export. Single-process runs (including this repo's CI and the 1-chip
benchmark rig) need no initialisation — `maybe_initialize` is a no-op
unless a multi-process launch is detectable.
"""

from __future__ import annotations

import os

_MULTIHOST_ENV = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def launched_multihost() -> bool:
    """True when the environment indicates a multi-process launch."""
    return any(os.environ.get(k) for k in _MULTIHOST_ENV)


def maybe_initialize() -> bool:
    """Call jax.distributed.initialize() iff launched multi-host; returns
    whether initialisation ran. Must be called before any backend use
    (the CLI does, right after platform selection)."""
    if not launched_multihost():
        return False
    import jax

    jax.distributed.initialize()
    return True
