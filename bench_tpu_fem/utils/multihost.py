"""Multi-host (DCN) initialisation glue.

The reference scales across hosts with one MPI rank per GPU (README.md:94-96,
`mpirun`/SLURM launch, examples/submit.sh). The JAX-native equivalent is
multi-controller SPMD: every host runs the same program, calls
`jax.distributed.initialize()`, and then `jax.devices()` spans the whole
slice/pod — after which the framework's `shard_map` code (dist/) is
UNCHANGED: the device grid simply contains remote devices, XLA routes
`ppermute` neighbours over ICI within a slice and DCN across slices, and
`psum`/`pmax` reductions span everything (the MPI_Allreduce analogue,
vector.hpp:173).

On Cloud TPU pods the coordinator/process-id/process-count arguments are
discovered from the TPU environment automatically; on other clusters they
come from the standard JAX env vars (JAX_COORDINATOR_ADDRESS,
JAX_PROCESS_ID / JAX_NUM_PROCESSES) that launchers such as SLURM scripts
export. Single-process runs (including this repo's CI and the 1-chip
benchmark rig) need no initialisation — `maybe_initialize` is a no-op
unless a multi-process launch is detectable.
"""

from __future__ import annotations

import os

_MULTIHOST_ENV = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def launched_multihost() -> bool:
    """True when the environment indicates a multi-process launch."""
    return any(os.environ.get(k) for k in _MULTIHOST_ENV)


def maybe_initialize() -> bool:
    """Call jax.distributed.initialize() iff launched multi-host; returns
    whether initialisation ran. Must be called before any backend use
    (the CLI does, right after platform selection).

    On launchers that export the coordinator/process env vars explicitly
    (JAX_COORDINATOR_ADDRESS + JAX_PROCESS_ID/JAX_NUM_PROCESSES — the
    repo's own 2-process CI lane, scripts/multihost_smoke.py, and any
    plain-ssh launch) the values are passed to initialize() directly:
    the installed jax 0.4.x only auto-detects SLURM/OpenMPI/TPU cluster
    environments, not these generic vars. Cluster launchers without the
    explicit pair keep the autodetect path."""
    if not launched_multihost():
        return False
    import jax

    # Only JAX_COORDINATOR_ADDRESS names the jax.distributed service
    # itself; the other launch-detection vars (MEGASCALE_*) point at
    # different services and must stay on the autodetect path. All three
    # explicit vars must be non-empty together — empty-string exports
    # (unset launcher substitutions) fall through to autodetect rather
    # than crashing on int("").
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if addr and nproc and pid:
        try:
            nproc_i, pid_i = int(nproc), int(pid)
        except ValueError as exc:
            raise ValueError(
                "malformed multihost env: JAX_NUM_PROCESSES="
                f"{nproc!r} JAX_PROCESS_ID={pid!r} must be integers"
            ) from exc
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=nproc_i,
            process_id=pid_i,
        )
    else:
        jax.distributed.initialize()
    return True
