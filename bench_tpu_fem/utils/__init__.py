"""Logging and timing utilities (reference: spdlog + dolfinx::common::Timer,
see SURVEY.md C17)."""

from .timing import Timer, timings
from .logging import init_logging

__all__ = ["Timer", "timings", "init_logging"]
