"""Logging and timing utilities (reference: spdlog + dolfinx::common::Timer,
see SURVEY.md C17)."""

from .timing import Timer, timer_report, timings
from .logging import init_logging

__all__ = ["Timer", "timer_report", "timings", "init_logging"]
