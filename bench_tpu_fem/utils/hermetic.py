"""Force JAX onto the host-CPU platform with N virtual devices.

This is the TPU analogue of the reference CI's oversubscribed ``mpirun -n 2``
(reference .github/workflows/ci.yml:100-106): multi-chip logic is exercised on
one host by splitting the CPU into N XLA devices via
``--xla_force_host_platform_device_count``.

Complication: the axon TPU-tunnel PJRT plugin registers itself in every Python
process via sitecustomize (which runs before any of our code) and monkeypatches
JAX's backend selection so the axon backend is consulted even under
``JAX_PLATFORMS=cpu``; if the tunnel is wedged, any JAX computation then hangs.
Hermetic CPU runs (tests, the driver's multi-chip dry-run) must surgically undo
the hook — the original function is held in the wrapper's closure — drop the
axon backend factory, and pin the config to CPU before any backend initialises.
"""

from __future__ import annotations

import os


def _jaxlib_at_least(major: int, minor: int) -> bool:
    """True when the installed jaxlib is at least `major.minor` (flag
    availability gate; unparseable versions count as too old)."""
    try:
        import jaxlib

        ver = tuple(int(p) for p in jaxlib.__version__.split(".")[:2])
    except Exception:
        return False
    return ver >= (major, minor)


def force_host_cpu_devices(n_devices: int) -> None:
    """Pin this process to the CPU platform with ``n_devices`` XLA devices.

    Must be called before any JAX backend initialises (i.e. before the first
    ``jax.devices()`` / jit execution). Safe to call when JAX is already
    imported, as long as no backend client exists yet.
    """
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"  # also inherited by subprocesses
    flags = os.environ.get("XLA_FLAGS", "")
    # Never lower an existing count (a stale exported flag must not shrink the
    # requested device mesh); raise it when the caller needs more devices.
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    if m is None:
        flags = (flags + f" --xla_force_host_platform_device_count={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        flags = (
            flags[: m.start()]
            + f"--xla_force_host_platform_device_count={n_devices}"
            + flags[m.end():]
        )
    # XLA:CPU's fusion emitters send LLVM into an effectively unbounded
    # (>28 min) opt blowup on the df64 distributed apply whenever the
    # mesh is sharded in x only — the unrolled edge-row df chains fuse
    # into one giant concatenate/slice kernel with no collective to
    # split the region (root-caused 2026-07-31, MEASURE_r04.log; the
    # same graph compiles in ~18 s with the emitters off, and in ~37 s
    # untouched when y/z halos break the fusion). Disabling them here
    # only changes the CPU compile strategy, never numerics; TPU
    # compiles are unaffected (this entry point pins the CPU platform).
    # VERSION-GATED: the flag only exists from jaxlib 0.5; older
    # bundled-XLA flag parsers ABORT the whole process on any unknown
    # XLA_FLAGS entry (parse_flags_from_env.cc), which would turn every
    # hermetic CPU run — the entire test suite — into a hard crash.
    if ("--xla_cpu_use_fusion_emitters" not in flags
            and _jaxlib_at_least(0, 5)):
        flags = (flags + " --xla_cpu_use_fusion_emitters=false").strip()
    os.environ["XLA_FLAGS"] = flags

    import jax
    from jax._src import xla_bridge as _xb

    hook = _xb._get_backend_uncached
    if getattr(hook, "__name__", "") == "_axon_get_backend_uncached" and hook.__closure__:
        for cell in hook.__closure__:
            try:
                v = cell.cell_contents
            except ValueError:
                continue
            if callable(v) and getattr(v, "__name__", "") == "_get_backend_uncached":
                _xb._get_backend_uncached = v
                break
    _xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")
