"""Per-compile TPU compiler options.

PJRT forwards `compiler_options` inside each compile request, so they
reach the TPU compiler even where client-side env XLA_FLAGS cannot (the
axon remote-compile terminal snapshots its own env and rejects unknown
flags in the local CPU jaxlib's parser — verified 2026-07-31).

The one option used so far: `xla_tpu_scoped_vmem_limit_kib` raises
Mosaic's scoped-VMEM stack limit (default ~16 MB on v5e, whose physical
VMEM is 128 MiB/core). That limit is what gates the largest fused
kernels: the degree-5/6 plane-streamed corner-geometry folded kernels
(19.3/23.2 MB measured) and the kron one-kernel CG engine at large
grids (~30 MiB estimated at 100M dofs). Raising it trades pipeline-
buffer headroom for stack space, so callers request it per-path (see
scoped_vmem_options) rather than globally.
"""

from __future__ import annotations

# Mutable hook: the drivers merge this into every TPU .compile() call,
# and it wins over per-path options (probes use it to pin a limit).
# Mutate IN PLACE (.update()/.clear()): rebinding the name in an
# importing module leaves compile_lowered reading this original dict.
TPU_COMPILER_OPTIONS: dict[str, str] = {}


def scoped_vmem_options(kib: int | None) -> dict[str, str] | None:
    """The per-path compiler-options dict for a raised scoped-VMEM
    limit (None when the path fits the default limit) — the single
    spelling of the option key."""
    if kib is None:
        return None
    return {"xla_tpu_scoped_vmem_limit_kib": str(kib)}


def compile_lowered(lowered, extra: dict[str, str] | None = None):
    """`.compile()` with the TPU compiler options (the global hook wins
    over `extra`). On CPU (tests, interpret mode) options are dropped:
    the CPU backend rejects TPU flags."""
    import jax

    opts = {**extra, **TPU_COMPILER_OPTIONS} if extra else dict(
        TPU_COMPILER_OPTIONS)
    if opts and jax.default_backend() == "tpu":
        return lowered.compile(compiler_options=opts)
    return lowered.compile()
