"""Per-compile TPU compiler options.

PJRT forwards `compiler_options` inside each compile request, so they
reach the TPU compiler even where client-side env XLA_FLAGS cannot (the
axon remote-compile terminal snapshots its own env and rejects unknown
flags in the local CPU jaxlib's parser — verified 2026-07-31).

The one option used so far: `xla_tpu_scoped_vmem_limit_kib` raises
Mosaic's scoped-VMEM stack limit (default ~16 MB on v5e, whose physical
VMEM is 128 MiB/core). That limit is what gates the largest fused
kernels: the degree-5/6 plane-streamed corner-geometry folded kernels
(19.3/23.2 MB measured) and the kron one-kernel CG engine at large
grids (~30 MiB estimated at 100M dofs). Raising it trades pipeline-
buffer headroom for stack space, so callers request it per-path (see
scoped_vmem_options) rather than globally.
"""

from __future__ import annotations

# Mutable hook: the drivers merge this into every TPU .compile() call,
# and it wins over per-path options (probes use it to pin a limit).
# Mutate IN PLACE (.update()/.clear()): rebinding the name in an
# importing module leaves compile_lowered reading this original dict.
TPU_COMPILER_OPTIONS: dict[str, str] = {}


def scoped_vmem_options(kib: int | None) -> dict[str, str] | None:
    """The per-path compiler-options dict for a raised scoped-VMEM
    limit (None when the path fits the default limit) — the single
    spelling of the option key."""
    if kib is None:
        return None
    return {"xla_tpu_scoped_vmem_limit_kib": str(kib)}


# XLA:CPU's fusion emitters blow up LLVM compile time (>28 min,
# effectively unbounded) on the df64 distributed apply when the mesh is
# sharded in x only — see utils.hermetic (which sets the equivalent env
# flag for every entry that pins the CPU platform: tests, dryrun, and
# CLI runs with platform=cpu) for the root cause. This per-compile form
# covers the one driver path hermetic never sees: platform='auto' with
# no JAX_PLATFORMS set, on a host whose default backend resolves to CPU.
CPU_DF_DIST_OPTIONS: dict[str, bool] = {"xla_cpu_use_fusion_emitters": False}


def exc_str(exc: BaseException) -> str:
    """Truncated `Type: message` form the drivers record in result
    extras when a compile fails and a fallback path takes over."""
    return f"{type(exc).__name__}: {exc}"[:300]


def compile_lowered(lowered, extra: dict[str, str] | None = None,
                    cpu_extra: dict | None = None):
    """`.compile()` with per-platform compiler options: on TPU, `extra`
    merged under the global hook (the hook wins); on CPU, `cpu_extra`
    (TPU flags are dropped there — the CPU backend rejects them)."""
    import jax

    backend = jax.default_backend()
    if backend == "tpu":
        opts = {**extra, **TPU_COMPILER_OPTIONS} if extra else dict(
            TPU_COMPILER_OPTIONS)
        if opts:
            return lowered.compile(compiler_options=opts)
    elif backend == "cpu" and cpu_extra:
        # cpu_extra is CPU-only (xla_cpu_*); any other backend (e.g. a
        # GPU host under platform='auto') must fall through to a plain
        # compile rather than receive a flag its compiler rejects.
        # Older jaxlibs don't know the option NAMES either (e.g.
        # xla_cpu_use_fusion_emitters predates jaxlib 0.5) and raise on
        # them — the options only steer compile strategy, never
        # numerics, so fall back to a plain compile there.
        try:
            return lowered.compile(compiler_options=dict(cpu_extra))
        except Exception:
            return lowered.compile()
    return lowered.compile()
