"""Command line interface (layer L7).

Flag-for-flag reproduction of the reference binary's options
(/root/reference/src/main.cpp:144-183), with TPU-relevant additions
(--ndevices for multi-chip sharding). `--ndofs` is per device, `--ndofs_global`
total; specifying both non-default values is an error (main.cpp:192-196).
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bench-tpu-fem",
        description=(
            "TPU FEM benchmark\n-----------------\n"
            "Finite Element Operator Action Benchmark which computes\n"
            "the Laplacian operator on a cube mesh of hexahedral elements."
        ),
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--platform", default="auto", help="Compute platform (auto, tpu or cpu)")
    p.add_argument("--float", dest="float_bits", type=int, default=64,
                   help="Float size (bits). 32 or 64.")
    p.add_argument("--ndofs", type=int, default=None,
                   help="Number of degrees-of-freedom per device (default 1000)")
    p.add_argument("--ndofs_global", type=int, default=None,
                   help="Number of global degrees-of-freedom")
    p.add_argument("--qmode", type=int, default=1,
                   help="Quadrature mode (0 or 1): qmode=0 has P+1 points in each "
                        "direction, qmode=1 has P+2 points in each direction.")
    p.add_argument("--cg", action="store_true",
                   help="Do CG iterations, rather than simple operator action")
    p.add_argument("--nrhs", type=int, default=1,
                   help="Batched multi-RHS: solve this many right-hand "
                        "sides (distinct per-lane scales of the benchmark "
                        "RHS) in ONE batched computation — the serving-"
                        "layer shape (bench_tpu_fem.serve). GDoF/s "
                        "accounts the batch: ndofs x nreps x nrhs / t.")
    p.add_argument("--nreps", type=int, default=1000, help="Number of repetitions")
    p.add_argument("--degree", type=int, default=3, help='Polynomial degree "P" (1-7)')
    p.add_argument("--mat_comp", action="store_true",
                   help="Compare result to matrix operator (slow with large ndofs)")
    p.add_argument("--geom_perturb_fact", type=float, default=0.0,
                   help="Randomly perturb the geometry (useful to check correctness)")
    p.add_argument("--use_gauss", action="store_true",
                   help="Use Gauss quadrature rather than GLL quadrature")
    p.add_argument("--json", default="", help="Filename for JSON output")
    p.add_argument("--ndevices", type=int, default=0,
                   help="Devices to shard over (0 = all visible devices)")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "xla", "pallas", "kron"],
                   help="Operator kernel backend (auto: kron fast path on "
                        "uniform meshes, Pallas on TPU f32 otherwise)")
    p.add_argument("--f64_impl", default="emulated",
                   choices=["emulated", "df32"],
                   help="--float 64 strategy on TPUs (no f64 units): "
                        "'emulated' = XLA software f64 (exact, ~100x "
                        "slower); 'df32' = double-float f32 pairs "
                        "(~1e-12 CG residual floors at ~20x flops; "
                        "uniform single-chip meshes)")
    p.add_argument("--overlap", default="auto",
                   choices=["auto", "on", "off"],
                   help="Communication/compute overlap for the sharded "
                        "fused CG engines (double-buffered halo exchange "
                        "+ single-psum iterations, engine forms "
                        "halo_overlap/ext2d_overlap). 'auto' engages "
                        "where supported; unsupported configs record the "
                        "gate reason and run synchronously. Single-chip "
                        "runs ignore this.")
    p.add_argument("--precond", default=None,
                   choices=["none", "jacobi", "chebyshev", "pmg"],
                   help="CG preconditioner (ISSUE 11): matrix-free "
                        "jacobi diagonal, chebyshev polynomial in "
                        "D^-1 A, or a p-multigrid V-cycle across the "
                        "degree family. 'none' (default) is bitwise "
                        "the unpreconditioned solve; unsupported paths "
                        "record precond_gate_reason. Every "
                        "preconditioned record stamps setup cost + "
                        "per-iteration applies; time_to_rtol_s "
                        "adjudicates (run with --convergence).")
    p.add_argument("--precision", default=None,
                   choices=["auto", "bf16", "bf16-refine"],
                   help="Mixed-precision speed ladder (ISSUE 17): "
                        "'bf16' streams every hot-loop operator apply "
                        "at bfloat16 (half the f32 HBM bytes, f32 "
                        "accumulate, bf16-class answers); 'bf16-refine' "
                        "wraps the same bf16 hot loop in the iterative-"
                        "refinement outer correction (la.refine) and "
                        "returns f64-class answers, stamping the "
                        "`refine` evidence block. Requires --float 32. "
                        "'auto' (default) keeps the --float/--f64_impl "
                        "precision. Env default: BENCH_PRECISION.")
    p.add_argument("--s-step", type=int, default=None, dest="s_step",
                   help="s-step (communication-avoiding) CG: batch the "
                        "reductions of N iterations into one stacked "
                        "reduction (sharded: ONE psum per N "
                        "iterations). 1 = standard recurrence; "
                        "breakdown falls back with "
                        "s_step_fallback_reason recorded.")
    p.add_argument("--log-level", default="info")
    p.add_argument("--profile", default="",
                   help="Write a jax.profiler trace of the timed region to "
                        "this directory (view with TensorBoard / xprof)")
    p.add_argument("--trace", default="",
                   help="Enable the obs span tracer and write a Chrome "
                        "trace-event JSON (Perfetto-loadable) to this "
                        "path; span + bench records also land in a "
                        "sibling .jsonl journal. Render both with "
                        "python -m bench_tpu_fem.obs")
    p.add_argument("--timing-reps", type=int, default=1,
                   help="Execute the timed region this many times and "
                        "report the per-rep wall distribution "
                        "(min/median/max) — exposes warmup and jitter; "
                        "the reported time is the median")
    p.add_argument("--checkpoint-every", type=int, default=None,
                   help="Durable CG checkpoints: snapshot the solve state "
                        "every N iterations (la.checkpoint + crash-safe "
                        "harness.checkpoint store); a restarted run "
                        "restores from the newest snapshot instead of "
                        "iteration 0. Gates the fused whole-solve engines "
                        "off (reason recorded). 0 (default) leaves the "
                        "hot path untouched. Env default: "
                        "BENCH_CHECKPOINT_EVERY.")
    p.add_argument("--checkpoint-dir", default=None,
                   help="Snapshot directory for --checkpoint-every "
                        "(unset: the chunked loop runs but writes "
                        "nothing — the measured-overhead A/B arm). Env "
                        "default: BENCH_CHECKPOINT_DIR.")
    p.add_argument("--sdc-audit", action="store_true", default=None,
                   help="SDC defense (ISSUE 14): true-residual-audit "
                        "every checkpoint boundary (rides "
                        "--checkpoint-every > 0); an exceedance rolls "
                        "back to the last durable snapshot and re-runs "
                        "— a second detection is the deterministic "
                        "`sdc` verdict. CHAOS_SDC=iter=N[,bit=B,"
                        "index=I,once=0|1] arms the seeded injector. "
                        "Env default: BENCH_SDC_AUDIT.")
    p.add_argument("--convergence", action="store_true", default=None,
                   help="Convergence telemetry (ISSUE 10): capture the "
                        "per-iteration CG residual history on device "
                        "(no host sync in the loop) and stamp the "
                        "`convergence` block — iterations/time-to-rtol "
                        "at the 1e-2..1e-8 ladder, stagnation/restart "
                        "counts — plus the paired time_to_rtol_s metric "
                        "next to GDoF/s. Routes fused whole-solve "
                        "engines to the capture-able unfused loop "
                        "(reason recorded). Env default: "
                        "BENCH_CONVERGENCE=1.")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    if args.float_bits not in (32, 64):
        raise SystemExit("Invalid float size. Must be 32 or 64.")
    if args.qmode not in (0, 1):
        raise SystemExit("Invalid qmode.")

    # Reject any run where both options are explicitly specified, matching
    # the reference (main.cpp:192-196) — even if a value equals its default.
    if args.ndofs is not None and args.ndofs_global is not None:
        raise SystemExit("Conflicting options 'ndofs' and 'ndofs_global'")
    if (args.precision in ("bf16", "bf16-refine")
            and args.float_bits != 32):
        # the registered bf16-float-bits gate, surfaced at parse time
        raise SystemExit(
            f"--precision {args.precision} requires --float 32 (bf16 "
            f"streams the f32-assembled operator at bfloat16)")
    if args.nrhs < 1:
        raise SystemExit("Invalid nrhs. Must be >= 1.")
    # Early serve-bucket audit (satellite, ISSUE 6): the benchmark
    # compiles the EXACT nrhs width, but a serving deployment pads the
    # batch to its executable-cache bucket — warn up front when those
    # differ (dead padded lanes burn bucket capacity), instead of the
    # user discovering it deep in the driver's artifact stamps. The
    # padded width is stamped on the artifact either way (stamp_nrhs).
    from .serve.cache import NRHS_BUCKETS, nrhs_bucket

    padded_nrhs = nrhs_bucket(args.nrhs)
    if args.nrhs > NRHS_BUCKETS[-1]:
        import warnings

        warnings.warn(
            f"--nrhs {args.nrhs} exceeds the largest serve bucket "
            f"({NRHS_BUCKETS[-1]}): a serving deployment would split "
            f"this batch across buckets; the benchmark itself runs the "
            f"exact width. Artifact stamps nrhs_bucket={padded_nrhs}.")
    elif args.nrhs > 1 and padded_nrhs != args.nrhs:
        import warnings

        warnings.warn(
            f"--nrhs {args.nrhs} is not a serve bucket "
            f"{NRHS_BUCKETS}: a serving deployment pads this batch to "
            f"{padded_nrhs} lanes ({padded_nrhs - args.nrhs} dead); the "
            f"benchmark itself runs the exact width. Artifact stamps "
            f"nrhs_bucket={padded_nrhs}.")

    from .utils.logging import init_logging

    init_logging(args.log_level)

    # Hermetic CPU runs (multi-device sharding on one host, CI) must pin the
    # host platform before any backend initialises — see utils.hermetic for
    # why plain JAX_PLATFORMS=cpu is not enough under the axon TPU tunnel.
    import os

    if args.platform == "cpu" or (
        args.platform == "auto" and os.environ.get("JAX_PLATFORMS", "") == "cpu"
    ):
        from .utils.hermetic import force_host_cpu_devices

        force_host_cpu_devices(max(args.ndevices, 1))

    # Multi-host (DCN) launches: every host runs this same program; join
    # the cluster before any backend use so jax.devices() spans the pod
    # (no-op on single-process runs — see utils.multihost).
    from .utils.multihost import maybe_initialize

    maybe_initialize()

    # x64 must be configured before device arrays exist.
    import jax

    jax.config.update(
        "jax_enable_x64",
        args.float_bits == 64 and args.f64_impl == "emulated",
    )
    if args.platform in ("cpu", "tpu"):
        try:
            jax.config.update("jax_platforms", args.platform)
        except Exception as exc:
            import warnings

            warnings.warn(
                f"could not select platform '{args.platform}' ({exc}); "
                f"continuing on the default JAX backend"
            )

    devices = jax.devices()
    ndevices = args.ndevices or len(devices)

    if args.ndofs_global is not None:
        ndofs_global = args.ndofs_global
    else:
        ndofs_global = (args.ndofs if args.ndofs is not None else 1000) * ndevices

    from .bench.driver import BenchConfig, run_benchmark
    from .bench.reporting import banner, results_json

    cfg = BenchConfig(
        ndofs_global=ndofs_global,
        degree=args.degree,
        qmode=args.qmode,
        float_bits=args.float_bits,
        nreps=args.nreps,
        use_cg=args.cg,
        mat_comp=args.mat_comp,
        use_gauss=args.use_gauss,
        geom_perturb_fact=args.geom_perturb_fact,
        platform=args.platform,
        ndevices=ndevices,
        backend=args.backend,
        f64_impl=args.f64_impl,
        profile_dir=args.profile,
        nrhs=args.nrhs,
        overlap=args.overlap,
        timing_reps=max(args.timing_reps, 1),
        # None = fall back to the BENCH_CHECKPOINT_* env defaults the
        # dataclass reads (harness stages opt in through those)
        **({} if args.checkpoint_every is None
           else {"checkpoint_every": args.checkpoint_every}),
        **({} if args.checkpoint_dir is None
           else {"checkpoint_dir": args.checkpoint_dir}),
        # None = fall back to the BENCH_SDC_AUDIT env default
        **({} if args.sdc_audit is None else {"sdc_audit": True}),
        # None = fall back to the BENCH_CONVERGENCE env default
        **({} if args.convergence is None
           else {"convergence": True}),
        # None = fall back to the BENCH_PRECOND / BENCH_S_STEP env
        # defaults (harness stages opt in without payload changes)
        **({} if args.precond is None else {"precond": args.precond}),
        **({} if args.s_step is None else {"s_step": max(args.s_step, 1)}),
        # None = fall back to the BENCH_PRECISION env default
        **({} if args.precision is None else {"precision": args.precision}),
    )

    obs_journal = None
    if args.trace:
        # span tracer on for the whole run: spans stream into the
        # sibling .jsonl journal as they close (crash-safe), the Chrome
        # trace exports after the run
        from .harness.journal import Journal
        from .obs.trace import enable

        base = (args.trace[:-5] if args.trace.endswith(".json")
                else args.trace)
        obs_journal = Journal(base + ".jsonl")
        enable(journal=obs_journal, fresh=True)

    dev = devices[0]
    info = f"Device: {dev.platform}:{dev.device_kind} x{len(devices)}"
    print(banner(cfg, info))

    res = run_benchmark(cfg)

    if args.trace:
        from .obs.trace import export_chrome_trace

        export_chrome_trace(args.trace)
        # the journal also carries the obs-stamped bench record, so
        # `python -m bench_tpu_fem.obs --journal` renders the roofline
        # table next to the span tree from one file
        obs_journal.append({
            "event": "bench_record",
            "gdof_per_second": res.gdof_per_second,
            "ndofs_global": res.ndofs_global,
            "roofline": res.extra.get("roofline"),
            "peak_memory_bytes": res.extra.get("peak_memory_bytes"),
            "memory": res.extra.get("memory"),
            "phase_s": res.extra.get("phase_s"),
            "phase_share": res.extra.get("phase_share"),
            "timing": res.extra.get("timing"),
            "cg_engine_form": res.extra.get("cg_engine_form"),
            # convergence telemetry (ISSUE 10): the paired metric +
            # the folded block ride the journal record too, so
            # `python -m bench_tpu_fem.obs trend` can render the
            # convergence curve from the journal alone. Presence-gated
            # like results_json: a non-capture run's record must not
            # carry dead null fields.
            **{k: res.extra[k] for k in
               ("convergence", "time_to_rtol_s", "collectives_per_iter")
               if k in res.extra},
        })
        print(f"*** Writing Chrome trace to: {args.trace} "
              f"(journal: {obs_journal.path})")

    comp_type = "CG" if cfg.use_cg else "Action"
    print(f"Computation time ({comp_type}): {res.mat_free_time}s")
    print(f"Computation rate (Gdofs/s): {res.gdof_per_second}")
    print(f"Norm of u = {res.unorm}")
    print(f"Norm of y = {res.ynorm}")
    if cfg.mat_comp:
        print(f"Norm of z = {res.znorm}")
        print(f"Norm of error = {res.enorm}")
        print(f"Relative norm of error = {res.enorm / res.znorm if res.znorm else float('nan')}")

    out = results_json(cfg, res)
    if args.json:
        with open(args.json, "w") as fh:
            fh.write(out + "\n")
        print(f"*** Writing output to: {args.json}")
    else:
        print(out)

    # the reference-parity timing banner, rendered by the obs table
    # renderer (the deprecated utils.timing.timer_report shim is gone —
    # spans and the legacy `%`-phase registry share one renderer)
    from .obs.report import render_timer_rows
    from .utils.timing import aggregated_timings

    print(render_timer_rows(aggregated_timings()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
