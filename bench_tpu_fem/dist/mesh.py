"""Device mesh construction and cell-grid block partitioning.

Replaces ParMETIS partitioning + the custom vertex-ghost repartitioner
(/root/reference/src/mesh.cpp:26-114): on a structured box the partition is a
closed-form block decomposition, so "partitioning" a 19B-dof mesh is free
(the reference spends minutes in ParMETIS at that scale, examples/slurm.out).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

AXIS_NAMES = ("dx", "dy", "dz")


def factor_devices(n: int) -> tuple[int, int, int]:
    """Factor a device count into a near-cubic 3D mesh shape (descending)."""
    if n < 1:
        raise ValueError("need at least one device")
    best = (n, 1, 1)
    best_cost = None
    for a in range(1, n + 1):
        if n % a:
            continue
        m = n // a
        for b in range(1, m + 1):
            if m % b:
                continue
            c = m // b
            dims = tuple(sorted((a, b, c), reverse=True))
            cost = max(dims) / min(dims)
            if best_cost is None or cost < best_cost:
                best, best_cost = dims, cost
    return best


@dataclass(frozen=True)
class DeviceGrid:
    """A 3D jax.sharding.Mesh over the devices plus partition bookkeeping."""

    mesh: object  # jax.sharding.Mesh with axes ("dx","dy","dz")
    dshape: tuple[int, int, int]

    @property
    def ndevices(self) -> int:
        return int(np.prod(self.dshape))


def make_device_grid(
    n_devices: int | None = None,
    dshape: tuple[int, int, int] | None = None,
    devices=None,
) -> DeviceGrid:
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if dshape is None:
        dshape = factor_devices(n_devices or len(devices))
    nd = int(np.prod(dshape))
    if nd > len(devices):
        raise ValueError(f"device mesh {dshape} needs {nd} devices, have {len(devices)}")
    dev_array = np.array(devices[:nd]).reshape(dshape)
    return DeviceGrid(mesh=Mesh(dev_array, AXIS_NAMES), dshape=tuple(dshape))


def shard_cells(n: tuple[int, int, int], dshape: tuple[int, int, int]) -> tuple[int, int, int]:
    """Cells per shard along each axis; requires exact divisibility (the
    distributed mesh-sizing search guarantees it)."""
    out = []
    for ni, di in zip(n, dshape):
        if ni % di:
            raise ValueError(f"mesh size {n} not divisible by device mesh {dshape}")
        out.append(ni // di)
    return tuple(out)


def compute_mesh_size_sharded(
    ndofs_global: int, degree: int, dshape: tuple[int, int, int]
) -> tuple[int, int, int]:
    """Mesh sizing constrained to cell counts divisible by the device-mesh
    shape (delegates to the shared search in mesh.sizing)."""
    from ..mesh.sizing import compute_mesh_size

    return compute_mesh_size(ndofs_global, degree, dshape)
