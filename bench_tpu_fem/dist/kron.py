"""Distributed Kronecker (uniform-mesh) fast path: the flagship operator
sharded over the device grid.

The banded Kronecker apply (ops.kron) is a pure stencil on the (NX, NY, NZ)
dof grid — row i of each 1D factor touches only rows i-P..i+P — so unlike
the general cell-based operator it needs *no* reverse scatter-add at shard
seams: each shard can compute every one of its rows exactly, given P planes
of neighbour input per side per axis. That is the whole distribution
protocol: 2 `lax.ppermute`s per axis per stage, nothing else (the analogue
of the reference's ghost scatter, /root/reference/src/vector.hpp:31-149,
with the scatter-back leg structurally eliminated).

Comm/compute overlap (the reference's lcell/bcell split,
/root/reference/src/laplacian.hpp:286-347, mapped to XLA): each banded
stage computes the *zero-padded local* apply — the single-chip kernel,
unchanged, covering all rows but missing halo contributions in the first
and last P planes — while the ppermutes are in flight; the received planes
enter only the recomputation of those 2P boundary planes (an O(P * face)
epilogue). Because the main kernel has no data dependency on the
collective, XLA's scheduler is free to run the P-plane exchange behind the
full-volume compute; only the boundary epilogue waits on it.

Shard layout follows dist.operator: local dof blocks of shape
(c_a P + 1) per axis where plane 0 duplicates the left neighbour's last
plane (ghost everywhere but on shard 0). Ghost planes stay *consistent*
through CG without any exchange: both owners of a seam plane recompute it
as a full banded row in canonical ascending-diagonal order (_edge_rows)
from bitwise-identical inputs, and CG's axpys use globally psum-reduced
scalars — so the duplicate entries remain bit-identical by induction
(asserted in tests). Reductions count owned planes once via
dist.halo.owned_mask.

Per-shard 1D coefficients are dynamic-sliced from the replicated global
banded diagonals ((2P+1, N_a) — kilobytes) by `lax.axis_index`; there is no
O(global-dofs) host or device setup anywhere in this path (the RHS is built
per shard from the same separable 1D factors as ops.kron.device_rhs_uniform).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..elements.tables import OperatorTables, build_operator_tables
from ..ops.kron import axis_matrices_1d, banded_apply, banded_diags
from .halo import (
    _shift_from_left,
    _shift_from_right,
    masked_dot,
    masked_linf,
    owned_mask,
)
from .mesh import AXIS_NAMES, shard_cells


def halo_slabs(x: jnp.ndarray, axis: int, name: str, P: int):
    """Receive P planes of neighbour input from each side along `axis`
    (zeros at domain edges). With the shared-plane block layout the left
    neighbour's rows [L-1-P, L-1) are this shard's global rows g0-P..g0-1,
    and the right neighbour's rows [1, P+1) are rows g0+L..g0+L+P-1."""
    L = x.shape[axis]
    to_left = lax.slice_in_dim(x, 1, P + 1, axis=axis)
    halo_r = _shift_from_right(to_left, name)
    to_right = lax.slice_in_dim(x, L - 1 - P, L - 1, axis=axis)
    halo_l = _shift_from_left(to_right, name)
    return halo_l, halo_r


def _edge_rows(x, halo_l, halo_r, dloc, axis: int, P: int):
    """Recompute the P boundary output planes on each side as full banded
    rows over the halo-extended window, summing strictly in ascending
    diagonal order. Both owners of a duplicated seam plane execute this
    identical term sequence on bitwise-identical inputs, so the duplicates
    stay *bit-identical* through CG with no ghost refresh — the invariant
    tests/test_dist_kron.py asserts. Zero halos at global domain edges meet
    the zero boundary rows of the banded storage, so edge shards need no
    special casing."""
    L = dloc.shape[1]

    def plane(h, j):
        return lax.index_in_dim(h, j, axis=axis, keepdims=True)

    # Extended windows: ext_l[j] = global row g0 - P + j  (extent 3P),
    # ext_r[j] = global row g0 + L - 2P + j (extent 3P).
    ext_l = jnp.concatenate(
        [halo_l, lax.slice_in_dim(x, 0, 2 * P, axis=axis)], axis=axis
    )
    ext_r = jnp.concatenate(
        [lax.slice_in_dim(x, L - 2 * P, L, axis=axis), halo_r], axis=axis
    )
    left = []
    for i in range(P):
        acc = None
        for di in range(2 * P + 1):
            term = dloc[di, i] * plane(ext_l, i + di)
            acc = term if acc is None else acc + term
        left.append(acc)
    right = []
    for j in range(P):
        i = L - P + j
        acc = None
        for di in range(2 * P + 1):
            term = dloc[di, i] * plane(ext_r, j + di)
            acc = term if acc is None else acc + term
        right.append(acc)
    return (
        jnp.concatenate(left, axis=axis),
        jnp.concatenate(right, axis=axis),
    )


def _replace_edges(y, rows_l, rows_r, axis: int, P: int):
    L = y.shape[axis]
    mid = lax.slice_in_dim(y, P, L - P, axis=axis)
    return jnp.concatenate([rows_l, mid, rows_r], axis=axis)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["Kd", "Md", "notbc1d", "kappa"],
    meta_fields=["n", "L", "dshape", "degree", "impl"],
)
@dataclass(frozen=True)
class DistKronLaplacian:
    """Sharded uniform-mesh Kronecker operator. All array state is the
    *global* 1D coefficient set (replicated — kilobytes); per-shard slices
    are cut inside shard_map by device position."""

    Kd: tuple  # 3x (2P+1, N_a) global banded diagonals of K_a diag(m_a)
    Md: tuple  # 3x (2P+1, N_a)
    notbc1d: tuple  # 3x (N_a,)
    kappa: jnp.ndarray
    n: tuple[int, int, int]  # global cells per axis
    L: tuple[int, int, int]  # local dof block shape (c_a * P + 1)
    dshape: tuple[int, int, int]
    degree: int
    impl: str = "auto"

    def local_coeffs(self):
        """Per-shard coefficient slices (call inside shard_map, once per
        jitted computation — hoisted out of the CG loop)."""
        P = self.degree
        Kloc, Mloc, nbloc = [], [], []
        for ax, name in enumerate(AXIS_NAMES):
            La = self.L[ax]
            g0 = lax.axis_index(name) * (La - 1)
            z0 = jnp.zeros((), dtype=g0.dtype)
            Kloc.append(lax.dynamic_slice(self.Kd[ax], (z0, g0), (2 * P + 1, La)))
            Mloc.append(lax.dynamic_slice(self.Md[ax], (z0, g0), (2 * P + 1, La)))
            nbloc.append(lax.dynamic_slice(self.notbc1d[ax], (g0,), (La,)))
        return Kloc, Mloc, nbloc

    def resolve_impl(self) -> str:
        if self.impl != "auto":
            return self.impl
        return (
            "pallas"
            if (
                jax.default_backend() == "tpu"
                and self.kappa.dtype == jnp.float32
            )
            else "xla"
        )

    def apply_local(self, x, coeffs=None):
        """y = A x for one shard's (Lx, Ly, Lz) dof block (inside shard_map)."""
        P = self.degree
        impl = self.resolve_impl()
        Kloc, Mloc, nbloc = coeffs if coeffs is not None else self.local_coeffs()
        if impl == "pallas":
            from ..ops.kron_pallas import (
                _use_interpret,
                x_stage_pallas,
                y_stage_pallas,
                z_stage_pallas,
            )

            interp = _use_interpret()

        # Unsharded axes need no halo/edge pass at all: halos would be zeros
        # and the zero-padded local apply is already globally exact there
        # (the banded storage's zero boundary rows). Skipping also keeps
        # 1-cell-deep unsharded axes legal (L = P + 1 < 2P).
        sx, sy, sz = (d > 1 for d in self.dshape)

        # --- Z stage (axis 2): halo in flight, local zero-padded main apply
        # (no data dependency on the collective — XLA overlaps), then the
        # 2P boundary planes are recomputed canonically from the halos.
        if sz:
            hl, hr = halo_slabs(x, 2, AXIS_NAMES[2], P)
        if impl == "pallas":
            aK, aM = z_stage_pallas(x, Kloc[2], Mloc[2], P, interp)
        else:
            aK = banded_apply(x, Kloc[2], 2)
            aM = banded_apply(x, Mloc[2], 2)
        if sz:
            rl, rr = _edge_rows(x, hl, hr, Kloc[2], 2, P)
            aK = _replace_edges(aK, rl, rr, 2, P)
            rl, rr = _edge_rows(x, hl, hr, Mloc[2], 2, P)
            aM = _replace_edges(aM, rl, rr, 2, P)

        # --- Y stage (axis 1): both operands ride one ppermute payload.
        if sy:
            s = jnp.stack([aK, aM])  # y axis is 2 in the stacked view
            hl, hr = halo_slabs(s, 2, AXIS_NAMES[1], P)
            hlK, hlM, hrK, hrM = hl[0], hl[1], hr[0], hr[1]
        if impl == "pallas":
            t12, tyz = y_stage_pallas(aK, aM, Kloc[1], Mloc[1], P, interp)
        else:
            t12 = banded_apply(aK, Mloc[1], 1) + banded_apply(aM, Kloc[1], 1)
            tyz = banded_apply(aM, Mloc[1], 1)
        if sy:
            al, ar = _edge_rows(aK, hlK, hrK, Mloc[1], 1, P)
            bl, br = _edge_rows(aM, hlM, hrM, Kloc[1], 1, P)
            t12 = _replace_edges(t12, al + bl, ar + br, 1, P)
            rl, rr = _edge_rows(aM, hlM, hrM, Mloc[1], 1, P)
            tyz = _replace_edges(tyz, rl, rr, 1, P)

        # --- X stage (axis 0): kappa folds into the coefficients; the
        # Dirichlet blend is re-applied on the recomputed boundary planes.
        cMx = self.kappa * Mloc[0]
        cKx = self.kappa * Kloc[0]
        if sx:
            s = jnp.stack([t12, tyz])  # x axis is 1 in the stacked view
            hl, hr = halo_slabs(s, 1, AXIS_NAMES[0], P)
            hlT, hlZ, hrT, hrZ = hl[0], hl[1], hr[0], hr[1]
        nbx, nby, nbz = nbloc
        if impl == "pallas":
            nbc_yz = (nby[:, None] * nbz[None, :]).reshape(1, -1)
            y = x_stage_pallas(
                t12, tyz, x, cMx, cKx, nbx, nbc_yz, P, interp
            )
        else:
            acc = banded_apply(t12, cMx, 0) + banded_apply(tyz, cKx, 0)
            nb3 = nbx[:, None, None] * nby[None, :, None] * nbz[None, None, :]
            y = nb3 * acc + (1.0 - nb3) * x
        if not sx:
            return y
        tl, tr = _edge_rows(t12, hlT, hrT, cMx, 0, P)
        zl, zr = _edge_rows(tyz, hlZ, hrZ, cKx, 0, P)
        nb_yz = nby[None, :, None] * nbz[None, None, :]
        Lx = x.shape[0]
        nb_l = nbx[:P, None, None] * nb_yz
        nb_r = nbx[Lx - P :, None, None] * nb_yz
        rows_l = nb_l * (tl + zl) + (1.0 - nb_l) * lax.slice_in_dim(x, 0, P, axis=0)
        rows_r = nb_r * (tr + zr) + (1.0 - nb_r) * lax.slice_in_dim(x, Lx - P, Lx, axis=0)
        return _replace_edges(y, rows_l, rows_r, 0, P)


def build_dist_kron(
    n: tuple[int, int, int],
    dgrid,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    dtype=jnp.float32,
    tables: OperatorTables | None = None,
    impl: str = "auto",
) -> DistKronLaplacian:
    """Build the sharded Kronecker operator for the uniform n-cell box over
    the device grid. Host work is O(N^(1/3)) — three 1D assemblies."""
    t = tables or build_operator_tables(degree, qmode, rule)
    dshape = dgrid.dshape
    ncl = shard_cells(n, dshape)
    for c, d in zip(ncl, dshape):
        if d > 1 and c < 2:
            raise ValueError(
                "distributed kron needs >= 2 cells per shard on sharded axes "
                f"(got {ncl} cells/shard over device mesh {dshape})"
            )
    P = degree
    Ks, Ms, masks = axis_matrices_1d(t, n)
    Kd = tuple(jnp.asarray(banded_diags(K1, P), dtype=dtype) for K1 in Ks)
    Md = tuple(jnp.asarray(banded_diags(M1, P), dtype=dtype) for M1 in Ms)
    nb = tuple(jnp.asarray(m, dtype=dtype) for m in masks)
    return DistKronLaplacian(
        Kd=Kd,
        Md=Md,
        notbc1d=nb,
        kappa=jnp.asarray(kappa, dtype=dtype),
        n=tuple(n),
        L=tuple(c * P + 1 for c in ncl),
        dshape=tuple(dshape),
        degree=degree,
        impl=impl,
    )


def resolve_kron_engine(op: DistKronLaplacian) -> bool:
    """The engine auto rule, shared by make_kron_sharded_fns and the dist
    driver's metadata/fallback logic so the recorded cg_engine flag can
    never diverge from what actually runs."""
    from .kron_cg import supports_dist_kron_engine

    return op.resolve_impl() == "pallas" and supports_dist_kron_engine(op)


def resolve_kron_overlap(op: DistKronLaplacian) -> tuple[bool, str | None]:
    """(supported, gate_reason) for the communication-overlapped engine
    form (dist.kron_cg.dist_kron_cg_solve_local_overlap) — shared by the
    driver so the recorded `cg_engine_form` and any gate reason cannot
    diverge from the routing."""
    from .kron_cg import supports_dist_kron_overlap

    from ..engines.registry import GATE_REASONS

    if not resolve_kron_engine(op):
        return False, GATE_REASONS["overlap-engine-kron"]
    if not supports_dist_kron_overlap(op):
        return False, GATE_REASONS["overlap-fusion-wall-kron"]
    return True, None


def make_kron_sharded_fns(op: DistKronLaplacian, dgrid, nreps: int,
                          engine: bool | None = None,
                          overlap: bool = False,
                          capture: bool = False):
    """Jittable sharded callables (apply, CG, norm) over (Dx,Dy,Dz,Lx,Ly,Lz)
    grid blocks — same contract as dist.folded.make_folded_sharded_fns.
    The operator rides along as a replicated pytree argument.

    `engine=None` (auto) routes CG through the distributed fused delay-ring
    engine (dist.kron_cg) when the Pallas impl is active and the ring fits
    VMEM — the ~2x-fewer-streams iteration measured on the single-chip
    engine. x-only meshes use the plane-halo kernel form; 3D meshes the
    ext2d form (cross-sections halo-extended too). The unfused 3-stage
    path (with its collective-independent main kernel) serves everything
    else.

    `overlap=True` routes CG through the communication-overlapped engine
    form (dist.kron_cg.dist_kron_cg_solve_local_overlap: carried halo
    state, one y-boundary ppermute off the critical path, ONE stacked
    psum per iteration) — requires the engine; callers gate via
    resolve_kron_overlap and record the form as `halo_overlap` /
    `ext2d_overlap`.

    `capture=True` (ISSUE 10) runs the UNFUSED CG with the
    per-iteration residual-history buffer (la.cg capture=True; the
    psum'd dots make the history replicated) — `cg_fn` then returns
    ``(x, hist)`` with the `(nreps + 1,)` history replicated. Requires
    engine=False (the fused rings have no per-iteration residual to
    buffer; the drivers gate and record the reason)."""
    from jax.sharding import PartitionSpec as P

    from ..la.cg import cg_solve
    from .halo import owned_dot
    from .kron_cg import (
        dist_kron_apply_ring_local,
        dist_kron_cg_solve_local,
        dist_kron_cg_solve_local_overlap,
    )

    spec = P(*AXIS_NAMES)
    rep = P()
    # pallas_call's out_shape carries no varying-mesh-axes annotation, which
    # the default shard_map VMA check rejects; scope the opt-out to the
    # impl that needs it.
    vma = op.resolve_impl() != "pallas"
    if engine is None:
        engine = resolve_kron_engine(op)
    if overlap and not engine:
        raise ValueError("the overlapped kron CG form rides the fused "
                         "engine; pass engine=True (or let it resolve)")
    if capture and engine:
        raise ValueError("convergence capture rides the unfused CG "
                         "loop; pass engine=False (the drivers gate "
                         "the fused forms and record the reason)")

    def _local(a):
        return a[0, 0, 0]

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, rep),
             out_specs=spec, check_vma=False if engine else vma)
    def apply_fn(x, A):
        if engine:
            return dist_kron_apply_ring_local(A, _local(x))[None, None, None]
        return A.apply_local(_local(x))[None, None, None]

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, rep),
             out_specs=(spec, rep) if capture else spec,
             check_vma=False if (engine or capture) else vma)
    def cg_fn(b, A):
        bl = _local(b)
        if engine:
            solve = (dist_kron_cg_solve_local_overlap if overlap
                     else dist_kron_cg_solve_local)
            return solve(A, bl, nreps)[None, None, None]
        coeffs = A.local_coeffs()  # hoisted: sliced once, reused every iter
        out = cg_solve(
            lambda v: A.apply_local(v, coeffs),
            bl,
            jnp.zeros_like(bl),
            nreps,
            dot=owned_dot(owned_mask(bl.shape).astype(bl.dtype)),
            capture=capture,
        )
        if capture:
            # history derives from the psum'd dots — replicated; the
            # VMA checker cannot infer that (check_vma off above)
            x, info = out
            return x[None, None, None], info["rnorm_history"]
        return out[None, None, None]

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=spec, out_specs=rep)
    def norm_fn(x):
        """Global (L2, Linf) over owned dofs — psum / pmax reductions
        (reference MPI_Allreduce SUM / MAX, vector.hpp:196-218)."""
        xl = _local(x)
        m = owned_mask(xl.shape)
        return jnp.stack(
            [jnp.sqrt(masked_dot(xl, xl, m)), masked_linf(xl, m)]
        )

    return apply_fn, cg_fn, norm_fn


def make_kron_pcg_fn(op: DistKronLaplacian, dgrid, nreps: int,
                     kind: str, cheb: tuple | None = None,
                     capture: bool = False):
    """Sharded PRECONDITIONED CG (ISSUE 11) for the kron operator: the
    la.cg._pcg_solve <r, z> recurrence inside shard_map, with the
    owned-dof psum dot for <p, A p> and the fused owned_pair_dot for
    the (<r, z>, <r, r>) pair — TWO psums per iteration, the
    synchronous bare loop's count. The inverse diagonal rides as a
    sharded grid-blocks argument (same layout/sharding as b, shared
    planes identical by construction); `kind` is "jacobi" or
    "chebyshev" (`cheb = (lmax, lmin, steps)` — the interval is
    estimated at the driver level through the sharded apply, so the
    polynomial is identical on every shard). Runs the UNFUSED local
    apply: the fused rings bake the unpreconditioned recurrence (the
    drivers gate them with the recorded reason)."""
    from jax.sharding import PartitionSpec as P

    from ..la.cg import cg_solve
    from ..la.precond import make_chebyshev
    from .halo import owned_dot, owned_pair_dot

    spec = P(*AXIS_NAMES)
    rep = P()

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, rep, spec),
             out_specs=(spec, rep) if capture else spec, check_vma=False)
    def pcg_fn(b, A, dinv):
        bl, dl = b[0, 0, 0], dinv[0, 0, 0]
        coeffs = A.local_coeffs()
        apply_l = lambda v: A.apply_local(v, coeffs)  # noqa: E731
        mask = owned_mask(bl.shape).astype(bl.dtype)
        if kind == "chebyshev":
            lmax, lmin, steps = cheb
            precond = make_chebyshev(apply_l, dl, lmax, lmin, steps)
        else:
            precond = lambda rr: dl * rr  # noqa: E731
        out = cg_solve(
            apply_l, bl, jnp.zeros_like(bl), nreps,
            dot=owned_dot(mask), precond=precond,
            dotpair=owned_pair_dot(mask), capture=capture,
        )
        if capture:
            x, info = out
            return x[None, None, None], info["rnorm_history"]
        return out[None, None, None]

    return pcg_fn


def make_kron_sstep_cg_fn(op: DistKronLaplacian, dgrid, nreps: int,
                          s: int, capture: bool = False):
    """Sharded s-step CG (ISSUE 11): la.sstep's outer iteration inside
    shard_map with the owned-dof Gram reduction — ONE stacked psum per
    s iterations (`reductions` = 1 in the loop-body trace, i.e. 1/s per
    CG iteration: the below-one-collective contract the tests and the
    perfgate counter pin). Always returns ``(x, info)`` (+ history when
    capturing) — the breakdown flag is replicated, so the driver's
    post-solve fallback check is one scalar fetch."""
    from jax.sharding import PartitionSpec as P

    from ..la.sstep import sstep_cg_solve
    from .halo import owned_dot, owned_gram

    spec = P(*AXIS_NAMES)
    rep = P()
    info_spec = {"breakdown": rep, "iters": rep}
    if capture:
        info_spec = dict(info_spec, rnorm_history=rep)

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, rep),
             out_specs=(spec, info_spec), check_vma=False)
    def sstep_fn(b, A):
        bl = b[0, 0, 0]
        coeffs = A.local_coeffs()
        mask = owned_mask(bl.shape).astype(bl.dtype)
        x, info = sstep_cg_solve(
            lambda v: A.apply_local(v, coeffs), bl,
            jnp.zeros_like(bl), nreps, s,
            gram=owned_gram(mask), dot=owned_dot(mask), capture=capture,
        )
        return x[None, None, None], info

    return sstep_fn


def make_kron_batched_cg_fn(op: DistKronLaplacian, dgrid, nreps: int):
    """Batched multi-RHS sharded CG (the serving-layer shape): a
    (nrhs, Dx, Dy, Dz, Lx, Ly, Lz) stack solved in ONE shard_map
    computation — vmapped UNFUSED local apply (the halo ppermutes batch
    cleanly under vmap; the fused delay-ring engine has no batched form
    and the caller records that), with the fused owned-dof dot TRIO
    (dist.halo.owned_batched_dot3): ONE stacked (3, nrhs) psum per
    iteration carries every lane's reductions — the single-reduction
    recurrence (la.cg.onered_scalars per lane), closing the PR 7/PR 10
    batched-dist remainder. The scalar `dot` stays the owned batched
    dot (rnorm0 init); parity vs the two-reduction oracle sits inside
    the standing fused-engine envelope."""
    from jax.sharding import PartitionSpec as P

    from ..la.cg import cg_solve_batched
    from .halo import owned_batched_dot, owned_batched_dot3

    bspec = P(None, *AXIS_NAMES)
    rep = P()

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(bspec, rep),
             out_specs=bspec, check_vma=False)
    def cg_fn(Bv, A):
        Bl = Bv[:, 0, 0, 0]
        coeffs = A.local_coeffs()  # hoisted: sliced once, shared by lanes
        mask = owned_mask(Bl.shape[1:]).astype(Bl.dtype)

        X = cg_solve_batched(
            lambda v: A.apply_local(v, coeffs), Bl,
            jnp.zeros_like(Bl), nreps, dot=owned_batched_dot(mask),
            dot3=owned_batched_dot3(mask),
        )
        return X[:, None, None, None]

    return cg_fn


def make_kron_rhs_fn(op: DistKronLaplacian, dgrid, tables: OperatorTables):
    """Jittable sharded RHS builder: b = M3d f_h per shard, from the global
    separable 1D factors (ops.kron.rhs_factors_1d — O(N^(1/3)) host work,
    replicated kilobytes on device, one outer product per shard). The
    distributed analogue of ops.kron.device_rhs_uniform; no O(global-dofs)
    array exists anywhere."""
    from jax.sharding import PartitionSpec as P

    from ..ops.kron import rhs_factors_1d

    factors = rhs_factors_1d(tables, op.n)
    dtype = op.kappa.dtype
    fs = tuple(jnp.asarray(f, dtype=dtype) for f in factors)

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(P(), P(), P()),
             out_specs=P(*AXIS_NAMES))
    def rhs_fn(fx, fy, fz):
        loc = []
        for ax, (name, f) in enumerate(zip(AXIS_NAMES, (fx, fy, fz))):
            La = op.L[ax]
            g0 = lax.axis_index(name) * (La - 1)
            loc.append(lax.dynamic_slice(f, (g0,), (La,)))
        b = loc[0][:, None, None] * loc[1][None, :, None] * loc[2][None, None, :]
        return b[None, None, None]

    return lambda: rhs_fn(*fs)
