"""Distributed fused folded CG engine: the one-kernel-per-iteration
delay-ring design of ops.folded_cg on sharded perturbed meshes — the
general-geometry twin of dist.kron_cg, closing the last sharded
configuration that still ran the unfused `cg_solve(apply_local)`
composition (and re-paid the ~3x glue HBM cost every iteration on every
shard, README's single-chip engine measurement).

The folded layout makes this carry-over structurally simpler than the
kron one: each shard's folded vector ALREADY contains its halo (the ghost
cell columns, dist.folded), so there is no slab extension — the engine is

- STACKED HALO REFRESH: one `ppermute` payload per sharded axis per
  iteration carries BOTH (r, p_prev) ghost cross-sections (the
  dist.folded `_halo_refresh_view` machinery with a leading channel
  axis, exactly the dist.kron_cg_df stacked-channel pattern). The
  in-kernel p-update then computes p = beta*p_prev + r at ghost slots
  from the owner's refreshed copies with the same elementwise
  instruction the owner executes — ghost p stays owner-consistent by
  replay (the f32 invariant dist.kron pins).
- THE SAME DELAY-RING KERNEL, HALO FORM: `ops.folded_cg._cg_apply_call`
  with `masks=(bc, w)` — the per-shard Dirichlet mask streams as a block
  operand (the single-chip closed form assumes global coordinates), and
  the in-kernel <p, A p> partials are weighted by the streamed
  owned-dof mask (dist.folded.owned_folded_mask as dtype) so ghost
  columns and duplicated seam slots count ZERO before the psum — every
  dof exactly once globally. Ghost cells keep their zero geometry rows,
  so they self-mask exactly as on one chip.
- SEAM OVERLAP-ADD IN TWO TIERS: intra-shard seams resolve inside the
  kernel's VMEM seam rings (ops.folded._seam_accumulate, unchanged);
  inter-shard seams are the partials the kernel leaves in the ghost
  columns, resolved by the reverse-scatter tail (ghost -> owner ppermute
  + add, dist.folded.folded_reverse_scatter). The <p, A p> partial the
  kernel emits therefore misses exactly the incoming inter-shard seam
  contributions; `folded_reverse_scatter_dot` accumulates that O(surface)
  correction — sum of p * received-partials over owned destination slots
  — alongside the scatter, so the psum'd dot is exact without re-reading
  the two O(volume) vectors (the stream the engine exists to save).

Trade-off vs the unfused dist path (same as dist.kron_cg, documented
deliberately): the kernel input depends on the halo refresh, so the
collective is on the critical path — the unfused path's main-kernel/
collective independence is given up for one fused pass instead of
main kernel + three epilogues + CG glue. The exchange moves O(surface)
bytes against O(volume) compute; the unfused path remains available via
`make_folded_sharded_fns(..., engine=False)` and is the driver's
recorded compile-failure fallback.

VMEM: identical rings to the single-chip engine on the PER-SHARD layout
(the input ring shrinks with the shard cross-section), plus two streamed
mask blocks that ride the existing pipeline — `dist_folded_engine_plan`
reuses the single-chip `MAX_RING_BLOCKS` gate and the folded
`pallas_plan` scoped-VMEM request. Both are DESIGN ESTIMATES for this
form until the `foldeng` stage measures it on hardware.

float32 only (Mosaic has no f64; the sharded df path is dist.folded's
unfused df section). Benchmark semantics (rtol = 0, exactly nreps
iterations).
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..la.cg import fused_cg_solve, onered_scalars
from ..ops.folded import pallas_plan
from ..ops.folded_cg import MAX_RING_BLOCKS, _cg_apply_call, ring_depth
from ..ops.kron_cg import PALLAS_UPDATE_MIN_DOFS, cg_update_pallas
from .folded import (
    DistFoldedLaplacian,
    _cview,
    _from_cview,
    _halo_refresh_view,
    folded_halo_refresh,
    folded_reverse_scatter,
)
from .halo import _shift_from_left, owned_dot, psum_all, psum_stack
from .mesh import AXIS_NAMES


def dist_folded_engine_plan(
    op: DistFoldedLaplacian,
) -> tuple[bool, int | None]:
    """(supported, scoped_vmem_kib): f32 only (Mosaic has no f64) and the
    per-shard input ring within the single-chip engine's MAX_RING_BLOCKS
    VMEM gate (the ring depth is a per-shard layout property — sharding
    the y/z axes shrinks it). The kib request forwards the folded
    pallas_plan's raised scoped limit (the degree 5-6 streamed-corner
    kernels), exactly what the unfused dist folded compile already
    requests — the halo form adds only pipeline-buffered mask streams, no
    new scratch, so the same ladder applies (DESIGN ESTIMATE until the
    foldeng stage measures it)."""
    if op.bc_mask.dtype != jnp.float32:
        return False, None
    if ring_depth(op.layout) > MAX_RING_BLOCKS:
        return False, None
    nq = int(np.asarray(op.phi0_c).shape[0])
    return True, pallas_plan(op.degree, nq, 4)[2]


def supports_dist_folded_engine(op: DistFoldedLaplacian) -> bool:
    """Supported component of dist_folded_engine_plan."""
    return dist_folded_engine_plan(op)[0]


def _refresh_rp(r, p_prev, layout):
    """Stacked halo refresh of (r, p_prev): ONE ppermute payload per
    sharded axis carries both channels' ghost cross-sections (the
    dist.folded view machinery with a leading channel axis)."""
    vs = jnp.stack([_cview(r, layout), _cview(p_prev, layout)])
    vs = _halo_refresh_view(vs, 1)
    return (_from_cview(vs[0], r, layout),
            _from_cview(vs[1], p_prev, layout))


def folded_reverse_scatter_dot(y, p, w, layout):
    """Inter-shard seam tail WITH the dot correction: the reverse scatter
    of dist.folded (ghost partials -> owner, sequentially x, y, z so
    edge/corner partials forward transitively), accumulating
    dcorr = sum over owned destination slots of p * received-partial.

    The kernel's <p, A p> partials already count p * (own contributions)
    at every owned slot; the incoming seam partials are exactly what they
    miss. Weighting each stage's receive by the owned mask counts a
    forwarded partial only at its final owned destination (intermediate
    shards see it on slots their mask zeroes), and p is owner-consistent
    at duplicated slots, so the psum of (kernel partials + dcorr) is the
    exact global dot — no O(volume) re-read. Returns (y_scattered,
    dcorr)."""
    v = _cview(y, layout)
    pv = _cview(p, layout)
    wv = _cview(w, layout)
    dcorr = jnp.zeros((), y.dtype)
    for ax, name in zip(range(3), AXIS_NAMES):
        n = lax.axis_size(name)
        if n == 1:
            continue
        cax = 3 + ax
        idx = lax.axis_index(name)
        last = v.shape[cax] - 1

        def islab_of(a, ax=ax):
            return lax.index_in_dim(a, 0, axis=ax, keepdims=True)

        islab = islab_of(v)
        ghost = lax.index_in_dim(islab, last, axis=cax, keepdims=True)
        contrib = jnp.where(idx == n - 1, jnp.zeros_like(ghost), ghost)
        recv = _shift_from_left(contrib, name)  # zeros on shard 0
        first = lax.index_in_dim(islab, 0, axis=cax, keepdims=True)
        p_first = lax.index_in_dim(islab_of(pv), 0, axis=cax,
                                   keepdims=True)
        w_first = lax.index_in_dim(islab_of(wv), 0, axis=cax,
                                   keepdims=True)
        dcorr = dcorr + jnp.sum(recv * p_first * w_first)
        new_first = first + recv
        new_ghost = jnp.where(idx == n - 1, ghost, jnp.zeros_like(ghost))
        islab = jnp.concatenate(
            [new_first, lax.slice_in_dim(islab, 1, last, axis=cax),
             new_ghost], axis=cax,
        )
        rest = lax.slice_in_dim(v, 1, v.shape[ax], axis=ax)
        v = jnp.concatenate([islab, rest], axis=ax)
    return _from_cview(v, y, layout), dcorr


def dist_folded_cg_solve_local(op: DistFoldedLaplacian, b, state, nreps,
                               interpret: bool | None = None):
    """Per-shard fused-engine CG (inside shard_map): returns the local
    folded solution block. Matches the unfused dist path
    (dist.folded.make_folded_sharded_fns cg_fn) to f32 reassociation
    accuracy at one kernel pass per iteration. Shares the exact
    `sharded_state` tuple of the unfused path: geom rides to the kernel,
    bc streams as the in-kernel Dirichlet mask, and the owned/"not a true
    ghost" mask doubles as the dot-ownership weight (they are the same
    array under dist.folded's ownership partition)."""
    layout = op.layout
    geom, bc, w, _epi = state
    phi0 = np.asarray(op.phi0_c, np.float64)
    dphi1 = np.asarray(op.dphi1_c, np.float64)
    apply_cg = partial(
        _cg_apply_call, layout, geom, op.kappa, phi0, dphi1,
        op.is_identity, op.geom_tables,
    )

    def engine(r, p_prev, beta):
        r_h, p_h = _refresh_rp(r, p_prev, layout)
        p, y, pdot = apply_cg(True, interpret, r_h, p_h, beta,
                              masks=(bc, w))
        y, dcorr = folded_reverse_scatter_dot(y, p, w, layout)
        return p, y, psum_all(jnp.sum(pdot) + dcorr)

    # owned-dof psum dot; w is hoisted state (no per-iteration cast)
    inner = owned_dot(w)

    update = None
    if b.size >= PALLAS_UPDATE_MIN_DOFS:
        # Chunked pallas x/r update above the shared size policy
        # (ops.kron_cg.PALLAS_UPDATE_MIN_DOFS: XLA TPU fails whole-vector
        # fusions ~130M dofs; the folded (nb, P^3, B) layout rides the
        # pass as a 3D grid). Its <r1, r1> counts every local slot; the
        # non-owned contribution (ghost columns — structural pads are
        # zero in every vector) is subtracted before the psum.
        def update(x, pv, r, y, alpha):
            x1, r1, rr = cg_update_pallas(x, pv, r, y, alpha, interpret)
            seam = jnp.sum(r1 * r1 * (1.0 - w))
            return x1, r1, psum_all(rr - seam)

    return fused_cg_solve(engine, b, nreps, update=update, inner=inner)


# ---------------------------------------------------------------------------
# Communication-overlapped folded engine form. The folded layout keeps
# ghosts structural (slots inside the vector), so "double buffering"
# here means carrying the REFRESHED (r, p_prev) vectors across
# iterations instead of refreshing them on the kernel's critical path:
#
#  - the per-iteration forward refresh moves from the kernel INPUT
#    (r, p_prev — 2 channels, blocking the kernel) to the kernel OUTPUT
#    y (1 channel, issued right after the reverse scatter); its only
#    consumers are the r-update's ghost slots at the very end of the
#    body, so the refresh overlaps the dot partials, the fused psum and
#    the x update, and the NEXT kernel starts with its halo already
#    resident;
#  - the two psum'd dots fuse into ONE stacked psum of (<p, A p> kernel
#    partials + the reverse-scatter dot correction, <r, y>, <y, y>) —
#    the la.cg.onered_scalars recurrence supplies <r1, r1>.
#
# Ghost slots of r and p stay owner-consistent by f32 elementwise replay
# (the in-kernel p-update and the elementwise r update apply identical
# instructions at ghost and owner slots — the invariant the synchronous
# form already pins); the refreshed y supplies the owner's seam-complete
# value where the local partial would be wrong. Gated as engine form
# `halo_overlap`; parity vs the synchronous folded engine <= 1e-7 rel
# f32 (the reassociated residual-norm recurrence).
# ---------------------------------------------------------------------------


def supports_dist_folded_overlap(op: DistFoldedLaplacian) -> bool:
    """Same plan as the synchronous folded engine: the overlap form runs
    the identical kernel (halo form, update_p) plus one extra O(volume)
    elementwise read pass for the fused dot trio."""
    return supports_dist_folded_engine(op)


def dist_folded_cg_solve_local_overlap(op: DistFoldedLaplacian, b, state,
                                       nreps,
                                       interpret: bool | None = None):
    """Per-shard communication-overlapped fused folded CG (inside
    shard_map): matches the synchronous engine
    (dist_folded_cg_solve_local) to the single-reduction reassociation
    envelope (<= 1e-7 rel f32) at one kernel pass, one reverse scatter,
    one forward refresh (of y, off the next kernel's critical path) and
    ONE stacked psum per iteration."""
    layout = op.layout
    geom, bc, w, _epi = state
    phi0 = np.asarray(op.phi0_c, np.float64)
    dphi1 = np.asarray(op.dphi1_c, np.float64)
    apply_cg = partial(
        _cg_apply_call, layout, geom, op.kappa, phi0, dphi1,
        op.is_identity, op.geom_tables,
    )
    inner = owned_dot(w)
    rnorm0 = inner(b, b)  # one psum, outside the loop
    # the rhs is already owner-complete at owned slots; refresh once so
    # the carried r starts ghost-consistent (the synchronous engine
    # refreshes on every iteration's critical path instead)
    r0 = folded_halo_refresh(b, layout)
    big = b.size >= PALLAS_UPDATE_MIN_DOFS

    def body(_, st):
        x, r_h, p_prev_h, beta, rnorm = st
        # kernel consumes the CARRIED refreshed state: no collective on
        # its critical path; in-kernel p-update covers ghost slots by
        # elementwise replay
        p, y, pdk = apply_cg(True, interpret, r_h, p_prev_h, beta,
                             masks=(bc, w))
        y, dcorr = folded_reverse_scatter_dot(y, p, w, layout)
        # the forward refresh moved here, onto y: issued before the
        # psum, consumed only by the r update's ghost slots
        y_r = folded_halo_refresh(y, layout)
        # fused dot trio: owned slots only (w zeroes ghosts), so the
        # pre-refresh y is correct and the dots do NOT wait on the
        # refresh collective
        yw = y * w
        g = psum_stack(jnp.sum(pdk) + dcorr, jnp.sum(r_h * yw),
                       jnp.sum(y * yw))
        alpha, rnorm1, beta1 = onered_scalars(rnorm, g[0], g[1], g[2])
        if big:
            # chunked pallas x/r update (the XLA whole-vector fusion
            # wall); its fused <r1,r1> is discarded — the overlap
            # recurrence never reads it
            x1, r1_h, _ = cg_update_pallas(x, p, r_h, y_r, alpha,
                                           interpret)
        else:
            x1 = x + alpha * p
            r1_h = r_h - alpha * y_r  # ghost slots replay the owner
        return (x1, r1_h, p, beta1, rnorm1)

    state0 = (jnp.zeros_like(b), r0, jnp.zeros_like(b),
              jnp.zeros((), b.dtype), rnorm0)
    x, *_ = lax.fori_loop(0, nreps, body, state0)
    return x


def dist_folded_apply_ring_local(op: DistFoldedLaplacian, x, state,
                                 interpret: bool | None = None):
    """Per-shard single delay-ring apply y = A x (inside shard_map) with
    FULL general-x operator semantics (unlike the CG engine's invariant
    form): halo refresh, pre-mask bc rows out of the interior windows,
    one halo-form kernel pass, reverse-scatter tail, Dirichlet rows
    restored from the refreshed input — the action-benchmark analogue of
    dist.kron_cg.dist_kron_apply_ring_local, value-matching
    DistFoldedLaplacian.apply_local."""
    layout = op.layout
    geom, bc, w, _epi = state
    apply_cg = partial(
        _cg_apply_call, layout, geom, op.kappa,
        np.asarray(op.phi0_c, np.float64),
        np.asarray(op.dphi1_c, np.float64),
        op.is_identity, op.geom_tables,
    )
    xr = folded_halo_refresh(x, layout)
    xm = xr * (1 - bc)
    y, _ = apply_cg(False, interpret, xm, masks=(bc, w))
    y = folded_reverse_scatter(y, layout)
    return y + bc * (xr - y)
