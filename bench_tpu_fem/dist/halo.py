"""Halo exchange primitives — run *inside* `jax.shard_map`.

The ICI-native replacement for the reference's ghost scatter
(`scatter_fwd_begin/end` with device pack/unpack kernels feeding MPI
neighbourhood all-to-all, /root/reference/src/vector.hpp:31-149,
laplacian.hpp:286-320): each sharded axis needs exactly one neighbour
`lax.ppermute` per direction, and XLA schedules these collectives
asynchronously against local compute (the comm/compute overlap the
reference implements by hand with its lcell/bcell split).

Local block layout along each sharded axis: planes [0, L) where plane 0 is a
ghost copy of the left neighbour's last plane (except on the first shard,
where it is the owned global-boundary plane).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .mesh import AXIS_NAMES


def _shift_from_left(x, axis_name):
    """ppermute i -> i+1: every shard receives its left neighbour's payload
    (zeros on shard 0)."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return jnp.zeros_like(x)
    perm = [(i, i + 1) for i in range(n - 1)]
    return lax.ppermute(x, axis_name, perm)


def _shift_from_right(x, axis_name):
    """ppermute i -> i-1: every shard receives its right neighbour's payload
    (zeros on the last shard)."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return jnp.zeros_like(x)
    perm = [(i, i - 1) for i in range(1, n)]
    return lax.ppermute(x, axis_name, perm)


def halo_refresh(x_local: jnp.ndarray, grid_axes=(0, 1, 2)) -> jnp.ndarray:
    """Forward scatter (owner -> ghost): refresh ghost plane 0 along each
    sharded axis from the left neighbour's owned last plane."""
    for ax, name in zip(grid_axes, AXIS_NAMES):
        n = lax.axis_size(name)
        if n == 1:
            continue
        last = lax.index_in_dim(x_local, x_local.shape[ax] - 1, axis=ax, keepdims=True)
        recv = _shift_from_left(last, name)
        idx = lax.axis_index(name)
        first = lax.index_in_dim(x_local, 0, axis=ax, keepdims=True)
        new_first = jnp.where(idx == 0, first, recv)
        rest = lax.slice_in_dim(x_local, 1, x_local.shape[ax], axis=ax)
        x_local = jnp.concatenate([new_first, rest], axis=ax)
    return x_local


def reverse_scatter_add(y_local: jnp.ndarray, grid_axes=(0, 1, 2)) -> jnp.ndarray:
    """Reverse scatter (ghost -> owner, accumulate): send the partial sums
    accumulated on ghost plane 0 back to the owning left neighbour and add
    them into its last plane. The ghost plane is then zeroed (its value is
    not owned and must not enter masked reductions)."""
    for ax, name in zip(grid_axes, AXIS_NAMES):
        n = lax.axis_size(name)
        if n == 1:
            continue
        idx = lax.axis_index(name)
        first = lax.index_in_dim(y_local, 0, axis=ax, keepdims=True)
        # Shard 0's first plane is owned, not a partial to forward.
        contrib = jnp.where(idx == 0, jnp.zeros_like(first), first)
        recv = _shift_from_right(contrib, name)  # zeros on the last shard
        last = lax.index_in_dim(y_local, y_local.shape[ax] - 1, axis=ax, keepdims=True)
        new_first = jnp.where(idx == 0, first, jnp.zeros_like(first))
        mid = lax.slice_in_dim(y_local, 1, y_local.shape[ax] - 1, axis=ax)
        y_local = jnp.concatenate([new_first, mid, last + recv], axis=ax)
    return y_local


def owned_mask(local_shape: tuple[int, ...], grid_axes=(0, 1, 2)) -> jnp.ndarray:
    """Multiplicative mask (1 on owned dofs, 0 on ghost planes) for the local
    block — used by inner products / norms so every dof counts exactly once
    globally (the reference counts only `size_local` owned entries,
    vector.hpp:163-165)."""
    mask = jnp.ones(local_shape, dtype=bool)
    for ax, name in zip(grid_axes, AXIS_NAMES):
        idx = lax.axis_index(name)
        sel = jnp.arange(local_shape[ax]) > 0
        sel = jnp.logical_or(sel, idx == 0)
        shape = [1] * len(local_shape)
        shape[ax] = local_shape[ax]
        mask = jnp.logical_and(mask, sel.reshape(shape))
    return mask


def psum_all(x):
    """Sum over the whole device grid (MPI_Allreduce -> psum over all axes)."""
    return lax.psum(x, AXIS_NAMES)


def pmax_all(x):
    """Max over the whole device grid (the reference's MPI_MAX for Linf,
    vector.hpp:211)."""
    return lax.pmax(x, AXIS_NAMES)


def masked_dot(a: jnp.ndarray, b: jnp.ndarray, mask: jnp.ndarray):
    local = jnp.sum(a * b * mask.astype(a.dtype))
    return psum_all(local)


# ---------------------------------------------------------------------------
# Shared owned-dof psum dot factories. Every sharded family (kron, df,
# folded) and every batched path used to hand-copy these closures; they
# live here once so the masked-reduction convention (weight as a
# multiplicative 0/1 array, ONE psum over all mesh axes) cannot drift
# between families.
# ---------------------------------------------------------------------------


def owned_dot(weight: jnp.ndarray):
    """Scalar owned-dof psum inner product over local blocks: `weight` is
    the 0/1 ownership array (ghost planes / duplicated seams zero), cast
    and closed over ONCE so no per-iteration cast rides the CG loop."""
    def dot(u, v):
        return psum_all(jnp.sum(u * v * weight))

    return dot


def owned_batched_dot(weight: jnp.ndarray):
    """Batched twin of owned_dot over (nrhs, ...) lane stacks: per-lane
    local reductions, then ONE psum carries the whole (nrhs,) vector —
    per lane exactly the reference's MPI_Allreduce dot, amortised across
    the batch."""
    def dot(U, V):
        return psum_all(jnp.sum(U * V * weight[None],
                                axis=tuple(range(1, U.ndim))))

    return dot


def owned_dot3(weight: jnp.ndarray):
    """Fused single-reduction dot trio (la.cg.stacked_dot3's distributed
    twin): [<p,y>, <r,y>, <y,y>] over owned dofs in ONE stacked psum.
    The fused ENGINES build their trio from the kernel's in-kernel
    <p,Ap> partial via psum_stack instead; this closure is the
    `cg_solve(dot3=)` / `cg_solve_batched(dot3=)` hook for the unfused
    and batched sharded paths — property-tested today, wired into
    production routing when the batched overlap form lands (ROADMAP
    item 5 remainder)."""
    def dot3(p, y, r):
        yw = y * weight
        return psum_all(jnp.stack([
            jnp.sum(p * yw), jnp.sum(r * yw), jnp.sum(y * yw)
        ]))

    return dot3


def owned_pair_dot(weight: jnp.ndarray):
    """Fused (<r, z>, <r, r>) pair over owned dofs in ONE stacked psum —
    the `cg_solve(precond=, dotpair=)` hook (ISSUE 11): the
    preconditioned recurrence needs both post-update reductions, and
    stacking them keeps the sharded PCG at TWO psums per iteration
    (<p,Ap> + this pair), the synchronous bare loop's count."""
    def pair(r, z):
        rw = r * weight
        st = psum_all(jnp.stack([jnp.sum(rw * z), jnp.sum(rw * r)]))
        return st[0], st[1]

    return pair


def owned_batched_dot3(weight: jnp.ndarray):
    """Batched fused dot trio (la.cg.batched_dot3's distributed twin):
    ONE stacked (3, nrhs) psum carries every lane's [<p,y>, <r,y>,
    <y,y>] — closing the PR 7/PR 10 remainder where the batched sharded
    paths still psum'd two separate (nrhs,) dots per iteration. Same
    reassociated recurrence (la.cg.onered_scalars per lane), same
    standing parity envelope as the single-RHS overlap forms."""
    def dot3(P, Y, R):
        axes = tuple(range(1, P.ndim))
        Yw = Y * weight[None]
        return psum_all(jnp.stack([
            jnp.sum(P * Yw, axis=axes),
            jnp.sum(R * Yw, axis=axes),
            jnp.sum(Y * Yw, axis=axes),
        ]))

    return dot3


def owned_gram(weight: jnp.ndarray):
    """Gram matrix of a basis stack over owned dofs in ONE stacked psum
    (la.sstep.local_gram's distributed twin): the s-step outer
    iteration's ONLY reduction — (2s+1)^2 scalars for s CG iterations,
    i.e. 1/s reductions per iteration, the below-one-psum contract."""
    def gram(V):
        Vw = V * weight[None]
        axes = tuple(range(1, V.ndim))
        return psum_all(jnp.tensordot(Vw, V, axes=(axes, axes)))

    return gram


def psum_stack(*partials):
    """ONE psum carrying several already-reduced local scalar partials
    (the overlap engines stack the kernel's in-kernel <p, A p> partial
    next to the locally-computed <r, y> / <y, y> partials)."""
    return psum_all(jnp.stack([jnp.asarray(p) for p in partials]))


def masked_linf(a: jnp.ndarray, mask: jnp.ndarray):
    """Global Linf over owned dofs (ghost planes excluded)."""
    local = jnp.max(jnp.abs(a) * mask.astype(a.dtype))
    return pmax_all(local)
