"""Distributed double-float (df64) Kronecker path: f64-class CG on
sharded uniform meshes without XLA's ~100x software-f64 emulation.

Composition of two existing designs, changing neither:

- the banded-stencil distribution protocol of dist.kron — zero-padded
  local apply per stage (no data dependency on the collective) + P-plane
  ppermute halos + canonical ascending-diagonal edge-row recomputation;
- the df64 arithmetic of la.df64/ops.kron_df — error-free f32-pair
  transforms (~48-bit mantissas, CG residual floors ~1e-12 rel).

A DF value's (hi, lo) components ride ONE stacked ppermute payload per
side per axis, exactly like dist.kron stacks aK/aM.

One deliberate deviation from the f32 protocol: the f32 path keeps
duplicated seam planes bit-identical with NO ghost refresh (bitwise
replay of identical instruction sequences). df compilation breaks that
guarantee — XLA's fused df chains can round the lo component differently
at different lane positions (see _df_seam_refresh) — so the df apply
ends with an explicit owner -> ghost seam-plane refresh per sharded axis:
O(face) traffic, consistency by construction instead of by replay.

Cross-shard reductions: a plain `psum` of df partials would re-round in
f32 at every tree-combine and silently discard the compensation. Instead
`df_psum_all` all-gathers the per-shard DF partials (ndevices tiny
scalars) and folds them in a fixed order with df_add on every shard —
deterministic, identical on all shards (the SPMD invariant CG needs), and
compensated end to end. The reference's MPI_Allreduce on f64 scalars
(vector.hpp:173) has the same role; this is its precision-preserving
TPU analogue.

Single-chip df32 (`ops.kron_df`) remains the ndevices=1 path; the driver
dispatches here for f64_impl='df32' with ndevices > 1.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..elements.tables import OperatorTables, build_operator_tables
from ..la.df64 import (
    DF,
    _prod_terms,
    _renorm,
    df_add,
    df_axpy,
    df_div,
    df_from_f64,
    df_scale,
    df_sub,
    df_sum,
    df_zeros_like,
)
from ..ops.kron import axis_matrices_1d, banded_diags
from ..ops.kron_df import banded_apply_df
from .halo import owned_mask
from .kron import halo_slabs
from .mesh import AXIS_NAMES, shard_cells


def _df_stack(*dfs):
    """Pack DF operands into one array for a single ppermute payload."""
    parts = []
    for d in dfs:
        parts += [d.hi, d.lo]
    return jnp.stack(parts)


def _df_unstack(arr, n):
    return tuple(DF(arr[2 * i], arr[2 * i + 1]) for i in range(n))


def _df_halo(dfs, axis: int, name: str, P: int):
    """Halo slabs for DF operands: one stacked exchange, returning
    (halo_l, halo_r) tuples of DF."""
    s = _df_stack(*dfs)
    hl, hr = halo_slabs(s, axis + 1, name, P)
    return _df_unstack(hl, len(dfs)), _df_unstack(hr, len(dfs))


def _plane(a, j, axis):
    return lax.index_in_dim(a, j, axis=axis, keepdims=True)


def _df_seam_refresh(y: DF, dshape) -> DF:
    """Owner -> ghost seam-plane refresh (dist.halo.halo_refresh on the
    stacked hi/lo pair): one tiny ppermute per sharded axis.

    The f32 dist path keeps duplicated seam planes consistent with NO
    refresh, by bitwise replay: both owners execute the identical
    instruction sequence on identical inputs. That guarantee does not
    survive df compilation: XLA fuses the df chains and the backend may
    contract mul+add pairs (FMA) differently across vectorization paths,
    so the same df math at different lane positions can round its lo
    component differently (observed on XLA:CPU as ~1e-16 lo drift on a
    seam plane whose inputs were verified bitwise identical). Rather than
    pin compiler codegen, the df path makes consistency structural: after
    each apply the owner's seam plane overwrites the neighbour's ghost
    copy — the reference's forward scatter (vector.hpp:95-149), O(face)
    traffic against the O(volume) apply."""
    from .halo import _shift_from_left

    if all(d == 1 for d in dshape):
        return y
    s = jnp.stack([y.hi, y.lo])  # grid axes shift by one in the stack
    for ax, name in zip((0, 1, 2), AXIS_NAMES):
        if dshape[ax] == 1:
            continue
        sax = ax + 1
        last = lax.index_in_dim(s, s.shape[sax] - 1, axis=sax,
                                keepdims=True)
        recv = _shift_from_left(last, name)
        idx = lax.axis_index(name)
        first = lax.index_in_dim(s, 0, axis=sax, keepdims=True)
        new_first = jnp.where(idx == 0, first, recv)
        rest = lax.slice_in_dim(s, 1, s.shape[sax], axis=sax)
        s = jnp.concatenate([new_first, rest], axis=sax)
    return DF(s[0], s[1])


def _edge_rows_df(x: DF, halo_l: DF, halo_r: DF, dloc: DF, axis: int,
                  P: int):
    """df twin of dist.kron._edge_rows: recompute the P boundary output
    planes per side as full banded rows over the halo-extended window,
    summing strictly in ascending diagonal order (in df arithmetic) so
    both owners of a duplicated seam plane replay the identical term
    sequence — hi AND lo stay bit-identical.

    Plane selection is PYTHON-STATIC (j, di are unrolled ints): each term
    indexes the halo or the interior directly instead of slicing a
    concatenated [halo | interior] window. Value-identical to the
    windowed form (only input selection changes, never the arithmetic
    sequence), but the concat-of-slices graph the windowed form built
    sent XLA:CPU's fusion emitter into an LLVM-opt blowup — >28 min,
    effectively unbounded, whenever no earlier collective had split the
    fusion region (meshes sharded in x only: the dryrun(4) hang,
    MEASURE_r04.log 2026-07-31; --xla_cpu_use_fusion_emitters=false
    confirmed the diagnosis at 17.8 s)."""
    L = dloc.hi.shape[1]

    def ext_plane(side, idx):
        """Plane `idx` of the virtual [halo | interior-slice] window."""
        if side == "l":  # [halo_l (P) | x[0:2P]]
            src, k = (halo_l, idx) if idx < P else (x, idx - P)
        else:  # [x[L-2P:L] (2P) | halo_r]
            src, k = (x, L - 2 * P + idx) if idx < 2 * P else (
                halo_r, idx - 2 * P)
        return DF(_plane(src.hi, k, axis), _plane(src.lo, k, axis))

    def rows(side, row_of):
        out = []
        for j in range(P):
            i = row_of(j)
            acc = None
            for di in range(2 * P + 1):
                c = DF(dloc.hi[di, i], dloc.lo[di, i])
                pl_ = ext_plane(side, j + di)
                term = _renorm(*_prod_terms(c, pl_))
                acc = term if acc is None else df_add(acc, term)
            out.append(acc)
        return DF(
            jnp.concatenate([o.hi for o in out], axis=axis),
            jnp.concatenate([o.lo for o in out], axis=axis),
        )

    left = rows("l", lambda j: j)
    right = rows("r", lambda j: L - P + j)
    return left, right


def _replace_edges_df(y: DF, rl: DF, rr: DF, axis: int, P: int):
    L = y.hi.shape[axis]

    def rep(a, l_, r_):
        mid = lax.slice_in_dim(a, P, L - P, axis=axis)
        return jnp.concatenate([l_, mid, r_], axis=axis)

    return DF(rep(y.hi, rl.hi, rr.hi), rep(y.lo, rl.lo, rr.lo))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["Kd", "Md", "notbc1d"],
    meta_fields=["n", "L", "dshape", "degree"],
)
@dataclass(frozen=True)
class DistKronLaplacianDF:
    """Sharded df64 uniform-mesh Kronecker operator: global DF banded 1D
    coefficient sets (replicated kilobytes; kappa folded into the x
    factors host-side in f64, as in ops.kron_df), per-shard slices cut
    inside shard_map by device position."""

    Kd: tuple  # 3x DF (2P+1, N_a)
    Md: tuple  # 3x DF
    notbc1d: tuple  # 3x f32 (N_a,) — exact 0/1, hi-only
    n: tuple[int, int, int]
    L: tuple[int, int, int]
    dshape: tuple[int, int, int]
    degree: int

    def local_coeffs(self):
        P = self.degree
        Kloc, Mloc, nbloc = [], [], []
        for ax, name in enumerate(AXIS_NAMES):
            La = self.L[ax]
            g0 = lax.axis_index(name) * (La - 1)
            z0 = jnp.zeros((), dtype=g0.dtype)

            def cut(df):
                return DF(
                    lax.dynamic_slice(df.hi, (z0, g0), (2 * P + 1, La)),
                    lax.dynamic_slice(df.lo, (z0, g0), (2 * P + 1, La)),
                )

            Kloc.append(cut(self.Kd[ax]))
            Mloc.append(cut(self.Md[ax]))
            nbloc.append(lax.dynamic_slice(self.notbc1d[ax], (g0,), (La,)))
        return Kloc, Mloc, nbloc

    def apply_local(self, x: DF, coeffs=None) -> DF:
        """y = A x for one shard's DF dof block (inside shard_map) —
        dist.kron.apply_local's stage/halo/edge structure in df
        arithmetic. The zero-padded local banded apply per stage has no
        data dependency on the collective; only the 2P edge planes
        consume the halos."""
        P = self.degree
        Kloc, Mloc, nbloc = coeffs if coeffs is not None else self.local_coeffs()
        sx, sy, sz = (d > 1 for d in self.dshape)

        if sz:
            hl, hr = _df_halo((x,), 2, AXIS_NAMES[2], P)
        aK = banded_apply_df(x, Kloc[2], 2)
        aM = banded_apply_df(x, Mloc[2], 2)
        if sz:
            rl, rr = _edge_rows_df(x, hl[0], hr[0], Kloc[2], 2, P)
            aK = _replace_edges_df(aK, rl, rr, 2, P)
            rl, rr = _edge_rows_df(x, hl[0], hr[0], Mloc[2], 2, P)
            aM = _replace_edges_df(aM, rl, rr, 2, P)

        if sy:
            hl, hr = _df_halo((aK, aM), 1, AXIS_NAMES[1], P)
        t12 = df_add(
            banded_apply_df(aK, Mloc[1], 1), banded_apply_df(aM, Kloc[1], 1)
        )
        tyz = banded_apply_df(aM, Mloc[1], 1)
        if sy:
            al, ar = _edge_rows_df(aK, hl[0], hr[0], Mloc[1], 1, P)
            bl, br = _edge_rows_df(aM, hl[1], hr[1], Kloc[1], 1, P)
            t12 = _replace_edges_df(
                t12, df_add(al, bl), df_add(ar, br), 1, P
            )
            rl, rr = _edge_rows_df(aM, hl[1], hr[1], Mloc[1], 1, P)
            tyz = _replace_edges_df(tyz, rl, rr, 1, P)

        if sx:
            hl, hr = _df_halo((t12, tyz), 0, AXIS_NAMES[0], P)
        acc = df_add(
            banded_apply_df(t12, Mloc[0], 0), banded_apply_df(tyz, Kloc[0], 0)
        )
        nbx, nby, nbz = nbloc
        nb3 = (nbx[:, None, None] * nby[None, :, None]
               * nbz[None, None, :])
        y = df_add(
            DF(nb3 * acc.hi, nb3 * acc.lo),
            DF((1.0 - nb3) * x.hi, (1.0 - nb3) * x.lo),
        )
        if not sx:
            return _df_seam_refresh(y, self.dshape)
        tl, tr = _edge_rows_df(t12, hl[0], hr[0], Mloc[0], 0, P)
        zl, zr = _edge_rows_df(tyz, hl[1], hr[1], Kloc[0], 0, P)
        Lx = x.hi.shape[0]
        nb_yz = nby[None, :, None] * nbz[None, None, :]
        nb_l = nbx[:P, None, None] * nb_yz
        nb_r = nbx[Lx - P:, None, None] * nb_yz

        def blend(rows, nb_m, xs):
            s = df_add(*rows)
            return DF(nb_m * s.hi + (1.0 - nb_m) * xs.hi,
                      nb_m * s.lo + (1.0 - nb_m) * xs.lo)

        x_l = DF(lax.slice_in_dim(x.hi, 0, P, axis=0),
                 lax.slice_in_dim(x.lo, 0, P, axis=0))
        x_r = DF(lax.slice_in_dim(x.hi, Lx - P, Lx, axis=0),
                 lax.slice_in_dim(x.lo, Lx - P, Lx, axis=0))
        rows_l = blend((tl, zl), nb_l, x_l)
        rows_r = blend((tr, zr), nb_r, x_r)
        return _df_seam_refresh(
            _replace_edges_df(y, rows_l, rows_r, 0, P), self.dshape
        )


def build_dist_kron_df(
    n: tuple[int, int, int],
    dgrid,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    tables: OperatorTables | None = None,
) -> DistKronLaplacianDF:
    t = tables or build_operator_tables(degree, qmode, rule)
    dshape = dgrid.dshape
    ncl = shard_cells(n, dshape)
    for c, d in zip(ncl, dshape):
        if d > 1 and c < 2:
            raise ValueError(
                "distributed kron needs >= 2 cells per shard on sharded "
                f"axes (got {ncl} cells/shard over device mesh {dshape})"
            )
    P = degree
    Ks, Ms, masks = axis_matrices_1d(t, n)
    Kd, Md = [], []
    for a, (K1, M1) in enumerate(zip(Ks, Ms)):
        scale = kappa if a == 0 else 1.0
        Kd.append(df_from_f64(banded_diags(K1 * scale, P)))
        Md.append(df_from_f64(banded_diags(M1 * scale, P)))
    return DistKronLaplacianDF(
        Kd=tuple(Kd),
        Md=tuple(Md),
        notbc1d=tuple(jnp.asarray(m, jnp.float32) for m in masks),
        n=tuple(n),
        L=tuple(c * P + 1 for c in ncl),
        dshape=tuple(dshape),
        degree=degree,
    )


def df_psum_all(s: DF, dshape) -> DF:
    """Compensated cross-shard sum of a scalar DF: all-gather the
    per-shard partials over every mesh axis, then fold them in a fixed
    order with df_add on each shard. A raw psum would re-round in f32 at
    every combine; this keeps the ~48-bit accumulation and is bitwise
    identical on all shards."""
    flat = DF(s.hi.reshape(1), s.lo.reshape(1))
    for name, d in zip(AXIS_NAMES, dshape):
        if d == 1:
            continue
        flat = DF(
            lax.all_gather(flat.hi, name, axis=0, tiled=True),
            lax.all_gather(flat.lo, name, axis=0, tiled=True),
        )
    n = flat.hi.shape[0]
    acc = DF(flat.hi[0], flat.lo[0])
    for i in range(1, n):
        acc = df_add(acc, DF(flat.hi[i], flat.lo[i]))
    return acc


def df_psum_all_stacked(parts, dshape):
    """Compensated cross-shard fold of SEVERAL scalar DF partials in ONE
    collective per sharded axis — the df analogue of the overlap form's
    single stacked psum. All partials' (hi, lo) channels ride a single
    stacked all-gather payload (a separate df_psum_all per dot would run
    one gather chain each), then each partial folds in the same fixed
    order as df_psum_all: deterministic, identical on all shards, and
    compensated end to end. Returns a tuple of DF scalars."""
    k = len(parts)
    flat = jnp.stack(
        [c.reshape(()) for p in parts for c in (p.hi, p.lo)]
    ).reshape(1, 2 * k)
    for name, d in zip(AXIS_NAMES, dshape):
        if d == 1:
            continue
        flat = lax.all_gather(flat, name, axis=0, tiled=True)
    n = flat.shape[0]
    out = []
    for i in range(k):
        acc = DF(flat[0, 2 * i], flat[0, 2 * i + 1])
        for j in range(1, n):
            acc = df_add(acc, DF(flat[j, 2 * i], flat[j, 2 * i + 1]))
        out.append(acc)
    return tuple(out)


def df_dot_dist(a: DF, b: DF, mask, dshape) -> DF:
    """Owned-dof-masked df inner product with the compensated cross-shard
    reduction (the df analogue of dist.halo.masked_dot)."""
    m = mask.astype(a.hi.dtype)
    local = df_sum(DF(*_prod_terms(DF(a.hi * m, a.lo * m), b)))
    return df_psum_all(local, dshape)


def dist_cg_solve_df_local(op: DistKronLaplacianDF, b: DF,
                           nreps: int, capture: bool = False):
    """Per-shard fixed-iteration df CG (inside shard_map): the
    ops.kron_df.cg_solve_df recurrence with distributed compensated dots
    and the same past-the-floor freeze guard.

    ``capture=True`` (ISSUE 10) carries the `(nreps + 1,)` f32 buffer of
    the carried squared residual norms' hi channels (the
    ops.kron_df.cg_solve_df capture contract; the gathered compensated
    dots make every entry identical on all shards) and returns
    ``(x, hist)``."""
    mask = owned_mask(b.hi.shape)
    coeffs = op.local_coeffs()  # hoisted out of the loop
    floor = jnp.float32(1e-24)

    def dot(u, v):
        return df_dot_dist(u, v, mask, op.dshape)

    rnorm0 = dot(b, b)
    rnorm0_hi = rnorm0.hi

    def body(i, state):
        if capture:
            x, r, p, rnorm, done, hist = state
        else:
            x, r, p, rnorm, done = state
        y = op.apply_local(p, coeffs)
        alpha = df_div(rnorm, dot(p, y))
        x1 = df_axpy(x, alpha, p)
        r1 = df_sub(r, df_scale(y, alpha))
        rnorm1 = dot(r1, r1)
        beta = df_div(rnorm1, rnorm)
        p1 = df_add(df_scale(p, beta), r1)
        done1 = jnp.logical_or(done, rnorm1.hi <= floor * rnorm0_hi)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda nw, o: jnp.where(done, o, nw), new, old
            )

        rnorm_keep = keep(rnorm1, rnorm)
        out = (keep(x1, x), keep(r1, r), keep(p1, p), rnorm_keep, done1)
        if capture:
            out = out + (hist.at[i + 1].set(rnorm_keep.hi),)
        return out

    # `done` is derived from the gathered dot, which shard_map's VMA
    # system marks device-varying (the values are in fact identical on
    # every shard — the reduction is deterministic); the initial carry
    # must carry the same varying annotation for the loop types to match.
    done0 = jax.lax.pcast(jnp.asarray(False), AXIS_NAMES, to="varying")
    state = (df_zeros_like(b), b, b, rnorm0, done0)
    if capture:
        state = state + (
            jnp.zeros((nreps + 1,), jnp.float32).at[0].set(rnorm0.hi),)
        x, _, _, _, _, hist = jax.lax.fori_loop(0, nreps, body, state)
        return x, hist
    x, *_ = jax.lax.fori_loop(0, nreps, body, state)
    return x


def resolve_df_engine(op: DistKronLaplacianDF) -> bool:
    """The fused dist df engine auto rule (mirrors
    dist.kron.resolve_kron_engine): Mosaic kernels on TPU only, any
    device mesh (x-only meshes take the plane-halo form, 3D meshes the
    ext2d form), ring within a scoped-VMEM tier."""
    import jax as _jax

    from .kron_cg_df import supports_dist_df_engine

    return (_jax.default_backend() == "tpu"
            and supports_dist_df_engine(op))


def resolve_df_overlap(op: DistKronLaplacianDF) -> tuple[bool, str | None]:
    """(supported, gate_reason) for the overlapped df engine form —
    shared with the driver so the recorded form cannot diverge from the
    routing."""
    from .kron_cg_df import supports_dist_df_overlap

    from ..engines.registry import GATE_REASONS

    if not resolve_df_engine(op):
        return False, GATE_REASONS["overlap-engine-df"]
    if not supports_dist_df_overlap(op):
        return False, GATE_REASONS["overlap-fusion-wall-df"]
    return True, None


def make_kron_df_sharded_fns(op: DistKronLaplacianDF, dgrid, nreps: int,
                             engine: bool | None = None,
                             overlap: bool = False,
                             capture: bool = False):
    """Jittable sharded callables over DF grid blocks (hi/lo each
    (Dx,Dy,Dz,Lx,Ly,Lz)): (apply, CG, l2norm) — the df twin of
    dist.kron.make_kron_sharded_fns.

    `engine=None` (auto) routes CG and the apply through the fused
    distributed df delay-ring engine (dist.kron_cg_df) on TPU where the
    ring fits a scoped-VMEM tier — any dshape (x-only meshes take the
    plane-halo kernel form, 3D meshes the ext2d form); the unfused df
    stage/halo path serves everything else and remains the
    compile-failure fallback.

    `overlap=True` routes CG through the communication-overlapped df
    engine form (dist.kron_cg_df.dist_kron_df_cg_solve_local_overlap:
    carried halo state, one y exchange off the critical path, ONE
    stacked compensated fold per iteration) — requires the engine;
    callers gate via resolve_df_overlap and record the form as
    `halo_overlap` / `ext2d_overlap`."""
    from jax.sharding import PartitionSpec as P

    spec = P(*AXIS_NAMES)
    rep = P()
    if engine is None:
        engine = resolve_df_engine(op)
    elif engine:
        from .kron_cg_df import supports_dist_df_engine

        if not supports_dist_df_engine(op):
            # the one remaining unsupported region: rings past every
            # scoped-VMEM tier (the chunked df form has no halo
            # variant) — an explicit override there would Mosaic-fail
            # anyway, so refuse with the reason
            raise ValueError(
                "the fused dist df engine needs a VMEM-tier-fitting "
                f"ring (dshape {op.dshape}, local {op.L})"
            )
    if overlap and not engine:
        raise ValueError("the overlapped df CG form rides the fused "
                         "engine; pass engine=True (or let it resolve)")
    if capture and engine:
        raise ValueError("convergence capture rides the unfused df CG "
                         "loop; pass engine=False (the drivers gate "
                         "the fused forms and record the reason)")

    def _local(a):
        return DF(a.hi[0, 0, 0], a.lo[0, 0, 0])

    def _wrap(a):
        return DF(a.hi[None, None, None], a.lo[None, None, None])

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, rep),
             out_specs=spec, check_vma=not engine)
    def apply_fn(x, A):
        if engine:
            from .kron_cg_df import dist_kron_df_apply_ring_local

            return _wrap(dist_kron_df_apply_ring_local(A, _local(x)))
        return _wrap(A.apply_local(_local(x)))

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, rep),
             out_specs=(spec, rep) if capture else spec,
             check_vma=False if capture else not engine)
    def cg_fn(b, A):
        if engine:
            from .kron_cg_df import (
                dist_kron_df_cg_solve_local,
                dist_kron_df_cg_solve_local_overlap,
            )

            solve = (dist_kron_df_cg_solve_local_overlap if overlap
                     else dist_kron_df_cg_solve_local)
            return _wrap(solve(A, _local(b), nreps))
        if capture:
            # the history derives from the gathered compensated dots —
            # replicated, but the VMA system cannot infer it (the same
            # reason norm_fn below runs check_vma=False)
            x, hist = dist_cg_solve_df_local(A, _local(b), nreps,
                                             capture=True)
            return _wrap(x), hist
        return _wrap(dist_cg_solve_df_local(A, _local(b), nreps))

    # check_vma off: the gathered compensated fold is genuinely replicated
    # (same order on every shard) but the VMA system cannot infer that.
    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, rep),
             out_specs=rep, check_vma=False)
    def norm_fn(x, A):
        """[<x,x>.hi, <x,x>.lo, Linf] over owned dofs. The df32 mode runs
        with x64 disabled, so the hi+lo recombination and sqrt happen in
        the CALLER's Python f64 (an on-device astype(float64) would
        silently stay f32) — see norms_from. Linf is on the f32-rounded
        hi+lo, as in the single-chip df path."""
        xl = _local(x)
        m = owned_mask(xl.hi.shape)
        d = df_dot_dist(xl, xl, m, A.dshape)
        linf = lax.pmax(
            jnp.max(jnp.abs(xl.hi + xl.lo) * m.astype(jnp.float32)),
            AXIS_NAMES,
        )
        return jnp.stack([d.hi, d.lo, linf])

    def norms_from(triple) -> tuple[float, float]:
        """(L2, Linf) in full precision from norm_fn's output."""
        hi, lo, linf = (float(v) for v in np.asarray(triple))
        return float(np.sqrt(hi + lo)), linf

    return apply_fn, cg_fn, norm_fn, norms_from


def make_kron_df_batched_cg_fn(op: DistKronLaplacianDF, dgrid, nreps: int):
    """Batched multi-RHS sharded df CG: the whole per-lane UNFUSED local
    df solve (`dist_cg_solve_df_local` — df halo exchange, compensated
    psum dots, per-lane residual-floor freeze) vmapped over the batch
    axis inside one shard_map. The df collectives and the
    optimization_barrier laundering batch under vmap (utils.jax_compat
    registers the barrier's pass-through batching rule on older jax);
    the fused dist df engine has no batched form — the caller records
    the unfused fallback."""
    from jax.sharding import PartitionSpec as P

    bspec = P(None, *AXIS_NAMES)
    rep = P()

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(bspec, rep),
             out_specs=bspec, check_vma=False)
    def cg_fn(b, A):
        lb = DF(b.hi[:, 0, 0, 0], b.lo[:, 0, 0, 0])
        X = jax.vmap(lambda v: dist_cg_solve_df_local(A, v, nreps))(lb)
        return DF(X.hi[:, None, None, None], X.lo[:, None, None, None])

    return cg_fn


def make_kron_df_rhs_fn(op: DistKronLaplacianDF, dgrid,
                        tables: OperatorTables):
    """Per-shard separable df RHS (the df twin of
    dist.kron.make_kron_rhs_fn): 1D DF factor slices by shard position,
    outer-multiplied on device in df arithmetic — no O(global) array."""
    from jax.sharding import PartitionSpec as P

    from ..ops.kron import rhs_factors_1d

    fs = tuple(df_from_f64(f) for f in rhs_factors_1d(tables, op.n))
    rep = P()

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(rep,) * 6,
             out_specs=P(*AXIS_NAMES))
    def rhs_fn(fxh, fxl, fyh, fyl, fzh, fzl):
        loc = []
        for ax, (name, fh, fl) in enumerate(
            zip(AXIS_NAMES, (fxh, fyh, fzh), (fxl, fyl, fzl))
        ):
            La = op.L[ax]
            g0 = lax.axis_index(name) * (La - 1)
            loc.append(DF(lax.dynamic_slice(fh, (g0,), (La,)),
                          lax.dynamic_slice(fl, (g0,), (La,))))
        Lx, Ly, Lz = op.L

        def bc3(a, shape_pos):
            sh = [1, 1, 1]
            sh[shape_pos] = -1
            return DF(
                jnp.broadcast_to(a.hi.reshape(sh), (Lx, Ly, Lz)),
                jnp.broadcast_to(a.lo.reshape(sh), (Lx, Ly, Lz)),
            )

        xy = _renorm(*_prod_terms(bc3(loc[0], 0), bc3(loc[1], 1)))
        b = _renorm(*_prod_terms(xy, bc3(loc[2], 2)))
        return DF(b.hi[None, None, None], b.lo[None, None, None])

    return lambda: rhs_fn(fs[0].hi, fs[0].lo, fs[1].hi, fs[1].lo,
                          fs[2].hi, fs[2].lo)
