"""Distributed benchmark driver: the multi-chip `laplace_action`
(/root/reference/src/laplacian_solver.cpp:65-230 under `mpirun`, one rank per
GPU). Owns its setup because the mesh size must be divisible by the device
grid (weak scaling: `--ndofs` is per device, main.cpp:237-240)."""

from __future__ import annotations

import time
from functools import partial

import numpy as np

from ..bench.driver import _fence_scalar, record_engine
from ..engines.registry import GATE_REASONS
from ..la.cg import cg_solve
from ..obs import trace as obs_trace
from ..obs.trace import BenchObserver
from ..mesh.dofmap import global_ncells, global_ndofs
from ..utils.compilation import (
    CPU_DF_DIST_OPTIONS,
    compile_lowered,
    exc_str,
    scoped_vmem_options,
)
from ..utils.timing import Timer
from .halo import masked_dot, masked_linf, owned_dot, owned_mask
from .mesh import AXIS_NAMES, compute_mesh_size_sharded, make_device_grid
from .operator import (
    build_dist_laplacian,
    shard_grid_blocks,
    unshard_grid_blocks,
)


def _stamp_collectives(extra: dict, nreps: int, elapsed: float,
                       cg_fn, *args) -> None:
    """Per-iteration collective-vs-compute attribution for the sharded
    drivers (the overlap A/B's evidence): ``per_iter_s`` always (cheap
    arithmetic), plus the TRACE-level per-iteration collective counts
    (analysis.capture.loop_collective_counts — nothing executes) when
    the obs tracer is enabled and the original engine actually ran (a
    fallback's fn differs from the traced one, so counts would lie)."""
    extra["per_iter_s"] = round(elapsed / max(nreps, 1), 9)
    if not obs_trace.enabled() or "cg_engine_error" in extra:
        return
    try:
        from ..analysis.capture import loop_collective_counts

        counts = loop_collective_counts(cg_fn, *args)
        extra["collectives_per_iter"] = {
            k: int(v) for k, v in counts.items()}
    except Exception:
        pass  # attribution must never sink the benchmark


def _resolve_overlap_mode(cfg, extra: dict, supported: bool,
                          gate_reason: str | None) -> bool:
    """cfg.overlap ('auto' | 'on' | 'off') -> whether the communication-
    overlapped CG form engages, recording a reasoned gate when 'auto'/
    'on' stays synchronous (the ISSUE-7 contract: every overlap branch
    stamps its form and records why it did not engage)."""
    mode = getattr(cfg, "overlap", "auto")
    if mode == "off":
        return False
    if supported:
        return True
    if gate_reason:
        extra["overlap_gate_reason"] = gate_reason
    return False


def make_sharded_fns(op, dgrid, nreps: int, capture: bool = False):
    """Build jittable sharded callables: one operator apply, one full CG
    solve, and a masked global norm — each a single shard_map computation.

    ``capture=True`` (ISSUE 10) runs the CG with the per-iteration
    residual-history buffer (la.cg capture=True): the history derives
    from the psum'd owned-dof dots, so it is replicated across shards
    and returned alongside the solution as a replicated `(nreps + 1,)`
    array — `cg_fn` then returns ``(x, hist)``. The VMA checker cannot
    infer that the gathered scalars are replicated (the
    dist_cg_solve_df_local precedent), so the capture form runs with
    check_vma off."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    spec = P(*AXIS_NAMES)
    rep = P()

    def _local(a):
        return a[0, 0, 0]

    @partial(
        jax.shard_map,
        mesh=dgrid.mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def apply_fn(x, G, bc):
        y = op.apply_local(_local(x), _local(G), _local(bc))
        return y[None, None, None]

    @partial(
        jax.shard_map,
        mesh=dgrid.mesh,
        in_specs=(spec, spec, spec),
        out_specs=(spec, rep) if capture else spec,
        **({"check_vma": False} if capture else {}),
    )
    def cg_fn(b, G, bc):
        bl, Gl, bcl = _local(b), _local(G), _local(bc)
        out = cg_solve(
            lambda v: op.apply_local(v, Gl, bcl),
            bl,
            jnp.zeros_like(bl),
            nreps,
            dot=owned_dot(owned_mask(bl.shape).astype(bl.dtype)),
            capture=capture,
        )
        if capture:
            x, info = out
            return x[None, None, None], info["rnorm_history"]
        return out[None, None, None]

    @partial(
        jax.shard_map,
        mesh=dgrid.mesh,
        in_specs=spec,
        out_specs=rep,
    )
    def norm_fn(x):
        """Global (L2, Linf) over owned dofs (psum / pmax)."""
        xl = _local(x)
        mask = owned_mask(xl.shape)
        return jnp.stack(
            [jnp.sqrt(masked_dot(xl, xl, mask)), masked_linf(xl, mask)]
        )

    return apply_fn, cg_fn, norm_fn


def make_sharded_batched_cg(op, dgrid, nreps: int):
    """Batched multi-RHS sharded CG for the general-geometry (xla)
    operator: vmapped local apply + the fused owned-dof dot trio — ONE
    stacked (3, nrhs) psum per iteration (see
    dist.kron.make_kron_batched_cg_fn for the kron twin and the
    PR 7/PR 10 batched-remainder note)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..la.cg import cg_solve_batched
    from .halo import owned_batched_dot, owned_batched_dot3

    bspec = P(None, *AXIS_NAMES)
    spec = P(*AXIS_NAMES)

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(bspec, spec, spec), out_specs=bspec,
             check_vma=False)
    def cg_fn(Bv, G, bc):
        Bl, Gl, bcl = Bv[:, 0, 0, 0], G[0, 0, 0], bc[0, 0, 0]
        mask = owned_mask(Bl.shape[1:]).astype(Bl.dtype)

        X = cg_solve_batched(
            lambda v: op.apply_local(v, Gl, bcl), Bl,
            jnp.zeros_like(Bl), nreps, dot=owned_batched_dot(mask),
            dot3=owned_batched_dot3(mask),
        )
        return X[:, None, None, None]

    return cg_fn


def make_sharded_dinv_fn(op, dgrid):
    """Sharded matrix-free Jacobi inverse diagonal for the
    general-geometry (xla) operator: one shard_map pass — local
    basis-squared contraction + fold, seams completed by the ghost-plane
    collectives (la.precond.jacobi_dinv_dist_local). Returns a callable
    of (G, bc) producing the (Dx,Dy,Dz,Lx,Ly,Lz) dinv blocks, sharded
    exactly like the solve vectors."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ..la.precond import jacobi_dinv_dist_local

    spec = P(*AXIS_NAMES)

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, spec),
             out_specs=spec, check_vma=False)
    def dinv_fn(G, bc):
        Gl, bcl = G[0, 0, 0], bc[0, 0, 0]
        d = jacobi_dinv_dist_local(Gl, op.phi0, op.dphi1, bcl, op.kappa,
                                   op.n_local, op.degree)
        return d[None, None, None]

    return dinv_fn


def make_sharded_pcg_fn(op, dgrid, nreps: int, kind: str,
                        cheb: tuple | None = None, capture: bool = False):
    """Sharded preconditioned CG for the general-geometry (xla)
    operator — dist.kron.make_kron_pcg_fn's twin: the <r, z> recurrence
    with the owned-dof <p, A p> psum and ONE stacked psum for the
    (<r, z>, <r, r>) pair (two psums per iteration, the synchronous
    count). `dinv` rides as a sharded argument (make_sharded_dinv_fn's
    output)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..la.cg import cg_solve
    from ..la.precond import make_chebyshev
    from .halo import owned_pair_dot

    spec = P(*AXIS_NAMES)
    rep = P()

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec, spec, spec, spec),
             out_specs=(spec, rep) if capture else spec, check_vma=False)
    def pcg_fn(b, G, bc, dinv):
        bl, Gl, bcl, dl = (b[0, 0, 0], G[0, 0, 0], bc[0, 0, 0],
                           dinv[0, 0, 0])
        apply_l = lambda v: op.apply_local(v, Gl, bcl)  # noqa: E731
        mask = owned_mask(bl.shape).astype(bl.dtype)
        if kind == "chebyshev":
            lmax, lmin, steps = cheb
            precond = make_chebyshev(apply_l, dl, lmax, lmin, steps)
        else:
            precond = lambda rr: dl * rr  # noqa: E731
        out = cg_solve(
            apply_l, bl, jnp.zeros_like(bl), nreps,
            dot=owned_dot(owned_mask(bl.shape).astype(bl.dtype)),
            precond=precond, dotpair=owned_pair_dot(mask),
            capture=capture,
        )
        if capture:
            x, info = out
            return x[None, None, None], info["rnorm_history"]
        return out[None, None, None]

    return pcg_fn


def make_sharded_sstep_cg(op, dgrid, nreps: int, s: int,
                          capture: bool = False):
    """Sharded s-step CG for the general-geometry (xla) operator —
    dist.kron.make_kron_sstep_cg_fn's twin: ONE stacked Gram psum per s
    iterations (the below-one-reduction contract); `(x, info)` with the
    replicated breakdown flag for the driver's recorded fallback."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..la.sstep import sstep_cg_solve
    from .halo import owned_gram

    spec = P(*AXIS_NAMES)
    rep = P()
    info_spec = {"breakdown": rep, "iters": rep}
    if capture:
        info_spec = dict(info_spec, rnorm_history=rep)

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, spec, spec),
             out_specs=(spec, info_spec), check_vma=False)
    def sstep_fn(b, G, bc):
        bl, Gl, bcl = b[0, 0, 0], G[0, 0, 0], bc[0, 0, 0]
        mask = owned_mask(bl.shape).astype(bl.dtype)
        x, info = sstep_cg_solve(
            lambda v: op.apply_local(v, Gl, bcl), bl,
            jnp.zeros_like(bl), nreps, s,
            gram=owned_gram(mask), dot=owned_dot(mask), capture=capture,
        )
        return x[None, None, None], info

    return sstep_fn


def batch_sharded_rhs(u, nrhs: int, dgrid):
    """(nrhs, Dx, Dy, Dz, ...) batched RHS stack from the sharded u:
    per-lane power-of-two scales (bench.driver.batch_scales — lane 0 is
    the one-shot problem verbatim), resharded so the batch axis is
    replicated and the grid axes keep their shards."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..bench.driver import batch_scales

    scales = jnp.asarray(batch_scales(nrhs), u.dtype)
    sharding = NamedSharding(dgrid.mesh, P(None, *AXIS_NAMES))
    return jax.jit(
        lambda v: scales.reshape((-1,) + (1,) * v.ndim) * v[None],
        out_shardings=sharding,
    )(u)


def _make_dist_checkpointed_cg(cfg, res, obs, op, dgrid, u, kron: bool):
    """Iteration-boundary sharded CG (ISSUE 9) for the kron-unfused and
    xla backends: la.checkpoint's step (cg_solve's body verbatim) runs
    ``checkpoint_every`` iterations per shard_map call with the same
    owned-dof psum dot as the one-executable sharded solve — so the
    chunked loop is bitwise that solve — and the carry is fetched to the
    host and snapshotted crash-safely at every boundary
    (harness.checkpoint.CheckpointStore). A restarted process restores
    the newest valid snapshot and continues mid-solve instead of at
    iteration 0. Returns ``(run, store, restored_iteration, saves)`` —
    the ``_make_checkpointed_cg`` contract (bench.driver)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..bench.driver import checkpointed_loop, open_checkpoint
    from ..la.checkpoint import (
        CGCkptState,
        cg_ckpt_init,
        cg_ckpt_run,
        make_cg_ckpt_step,
    )

    every = int(cfg.checkpoint_every)
    nreps = cfg.nreps
    spec = P(*AXIS_NAMES)
    rep = P()
    # grid leaves stay shard-blocked; the psum'd scalars are replicated
    state_specs = CGCkptState(x=spec, r=spec, p=spec, rnorm=rep,
                              rnorm0=rep, done=rep, iters=rep)

    if kron:
        args = (op,)
        arg_specs = (rep,)

        def local_apply(A):
            coeffs = A.local_coeffs()  # hoisted per chunk call
            return lambda v: A.apply_local(v, coeffs)
    else:
        args = (op.G, op.bc_mask)
        arg_specs = (spec, spec)

        def local_apply(G, bc):
            Gl, bcl = G[0, 0, 0], bc[0, 0, 0]
            return lambda v: op.apply_local(v, Gl, bcl)

    def _block(st):
        e = lambda a: a[None, None, None]  # noqa: E731
        return CGCkptState(x=e(st.x), r=e(st.r), p=e(st.p),
                           rnorm=st.rnorm, rnorm0=st.rnorm0,
                           done=st.done, iters=st.iters)

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec,) + arg_specs, out_specs=state_specs,
             check_vma=False)
    def init_fn(b, *a):
        bl = b[0, 0, 0]
        dot = owned_dot(owned_mask(bl.shape).astype(bl.dtype))
        return _block(cg_ckpt_init(local_apply(*a), bl,
                                   jnp.zeros_like(bl), dot=dot))

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(state_specs,) + arg_specs, out_specs=state_specs,
             check_vma=False)
    def run_fn(state, *a):
        st = CGCkptState(x=state.x[0, 0, 0], r=state.r[0, 0, 0],
                         p=state.p[0, 0, 0], rnorm=state.rnorm,
                         rnorm0=state.rnorm0, done=state.done,
                         iters=state.iters)
        dot = owned_dot(owned_mask(st.x.shape).astype(st.x.dtype))
        step = make_cg_ckpt_step(local_apply(*a), nreps, dot=dot)
        return _block(cg_ckpt_run(st, step, every))

    with obs.phase("compile"):
        init_j = jax.jit(init_fn)
        run_j = jax.jit(run_fn)
        state_s = jax.eval_shape(init_fn, u, *args)
        # trigger the real XLA compiles HERE so the phase attribution is
        # honest (tracing the jit wrappers compiles nothing — without
        # this the sharded CG compile would land in the first warm call's
        # "transfer" phase): one init + one discarded chunk on the real
        # sharded inputs, and the jit cache serves every later call
        run_j(init_j(u, *args), *args)

    store = None
    start_state = None
    restored_it = 0
    if cfg.checkpoint_dir:
        kind = (f"dist_cg_{'kron' if kron else 'xla'}_"
                f"{'x'.join(str(d) for d in dgrid.dshape)}")
        store, host, restored_it = open_checkpoint(
            cfg, res, state_s, kind, nreps)
        if host is not None:
            sh = NamedSharding(dgrid.mesh, spec)
            start_state = CGCkptState(
                x=jax.device_put(host.x, sh),
                r=jax.device_put(host.r, sh),
                p=jax.device_put(host.p, sh),
                rnorm=host.rnorm, rnorm0=host.rnorm0,
                done=host.done, iters=host.iters)
    saves = {"n": 0}

    def run(save: bool = True):
        st = start_state if start_state is not None else init_j(u, *args)
        st = checkpointed_loop(
            st, lambda s: run_j(s, *args), store=store,
            restored_it=restored_it, nreps=nreps, k=every,
            kind="dist_cg", saves=saves, save=save)
        jax.block_until_ready(st.x)
        return st.x

    return run, store, restored_it, saves


def run_distributed(cfg, res, dtype):
    """Multi-device benchmark. Fills and returns `res` (BenchmarkResults)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..bench.driver import _setup_problem

    dgrid = make_device_grid(cfg.ndevices)
    n = compute_mesh_size_sharded(cfg.ndofs_global, cfg.degree, dgrid.dshape)

    from ..bench.driver import resolve_backend

    backend = resolve_backend(
        cfg.backend, cfg.float_bits,
        uniform=cfg.geom_perturb_fact == 0.0, degree=cfg.degree,
        qmode=cfg.qmode,
    )
    res.extra["backend"] = backend
    if getattr(cfg, "precision", "auto").startswith("bf16"):
        # bf16 streaming is single-chip today (ISSUE 17): the sharded
        # f32 path runs, with the registered reason recorded
        res.extra["bf16_gate_reason"] = GATE_REASONS["bf16-sharded"]
    kron = backend == "kron"
    if kron and cfg.geom_perturb_fact != 0.0:
        # Mirror build_kron_laplacian's single-chip guard: an explicit
        # backend='kron' must not silently time the wrong (uniform) operator
        # on a perturbed mesh.
        raise ValueError(
            "kron backend requires an unperturbed (uniform) box mesh; "
            "use the xla/pallas backends for perturbed geometry"
        )
    folded = backend == "pallas"
    # per-path raised scoped-VMEM request (utils.compilation), set by the
    # kron-engine / folded-plan branches below
    compile_opts = None
    # communication-overlap routing state, set by the kron/folded
    # branches (the xla path has no engine and therefore no overlap form)
    overlap_on = False
    base_form = None
    # convergence capture routing (ISSUE 10), resolved in the CG branch
    conv_on = False
    # s-step routing (ISSUE 11), resolved in the CG branch
    sstep_dist = False
    res.ncells_global = global_ncells(n)
    res.ndofs_global = global_ndofs(n, cfg.degree)
    obs = BenchObserver(cfg, run="dist")

    # Neither fast path needs O(global-dofs) host arrays: the kron flagship's
    # operator state is three 1D assemblies with a per-shard separable device
    # RHS, and the folded path builds per-shard closed-form masks with a
    # per-shard corner-based device RHS (the reference's per-rank setup,
    # mesh.cpp:190-218 + laplacian_solver.cpp:100-114, with 'per-rank' made
    # closed-form by the structured box). The host path remains for the XLA
    # fallback backend and for the mat_comp oracle.
    if (kron or folded) and not cfg.mat_comp:
        from ..elements.tables import build_operator_tables
        from ..mesh.box import create_box_mesh

        rule = "gauss" if cfg.use_gauss else "gll"
        t = build_operator_tables(cfg.degree, cfg.qmode, rule)
        b_host = G_host = dm = bc_grid = None
        mesh = (None if kron
                else create_box_mesh(n, cfg.geom_perturb_fact))
    else:
        n, rule, t, mesh, grid_shape, bc_grid, dm, b_host, G_host = (
            _setup_problem(cfg, n)
        )

    with Timer("% Create matfree operator"):
        sharding = NamedSharding(dgrid.mesh, P(*AXIS_NAMES))
        if kron:
            from .kron import (
                build_dist_kron,
                make_kron_rhs_fn,
                make_kron_sharded_fns,
            )

            op = build_dist_kron(
                n, dgrid, cfg.degree, cfg.qmode, rule, kappa=2.0,
                dtype=dtype, tables=t,
            )
            from .kron import resolve_kron_engine, resolve_kron_overlap
            from .kron_cg import _is_x_only, dist_kron_engine_plan

            base_form = "halo" if _is_x_only(op) else "ext2d"
            ovl_ok, ovl_gate = resolve_kron_overlap(op)
            overlap_on = cfg.use_cg and cfg.nrhs == 1 and (
                _resolve_overlap_mode(cfg, res.extra, ovl_ok, ovl_gate))
            apply_fn, cg_fn, norm_fn = make_kron_sharded_fns(
                op, dgrid, cfg.nreps, overlap=overlap_on
            )
            # same predicate the kernel routing uses, so the recorded
            # form cannot diverge from the form that runs
            record_engine(res.extra, resolve_kron_engine(op),
                          base_form + ("_overlap" if overlap_on else ""))
            if res.extra["cg_engine"]:
                # raised-tier one-kernel rings need the per-compile
                # scoped-VMEM request, same plan as the single-chip driver
                compile_opts = scoped_vmem_options(
                    dist_kron_engine_plan(op)[1])
            if b_host is not None:
                # mat_comp: feed the oracle-precision host RHS to both paths.
                u_blocks = shard_grid_blocks(b_host, n, cfg.degree, dgrid.dshape)
                u = jax.device_put(jnp.asarray(u_blocks, dtype=dtype), sharding)
            else:
                u = jax.jit(make_kron_rhs_fn(op, dgrid, t))()
            cg_args = (op,)
            apply_args = (op,)
            norm_args = ()
        elif folded:
            # Folded shards (ghost cell columns = halo; see dist.folded:
            # overlap-by-construction apply, per-shard closed-form setup).
            from ..ops.folded import pallas_plan
            from .folded import (
                build_dist_folded,
                make_folded_rhs_fn,
                make_folded_sharded_fns,
                resolve_folded_engine,
                shard_corner_cs,
                shard_folded_vectors,
            )

            # the streamed-corner kernels (degrees 5-6) compile only with
            # the raised scoped-VMEM limit, exactly like the single-chip
            # folded path (dist_folded_engine_plan forwards the same kib)
            compile_opts = scoped_vmem_options(
                pallas_plan(cfg.degree, t.nq, np.dtype(dtype).itemsize)[2])
            op = build_dist_folded(
                mesh, dgrid, cfg.degree, t, kappa=2.0, dtype=dtype
            )
            # fused dist folded engine (dist.folded_cg) when the
            # per-shard ring fits — the auto rule inside
            # make_folded_sharded_fns is the same resolver, so the
            # recorded flag cannot diverge from what runs
            from .folded import resolve_folded_overlap

            base_form = "halo"
            ovl_ok, ovl_gate = resolve_folded_overlap(op)
            overlap_on = cfg.use_cg and cfg.nrhs == 1 and (
                _resolve_overlap_mode(cfg, res.extra, ovl_ok, ovl_gate))
            record_engine(res.extra, resolve_folded_engine(op),
                          "halo_overlap" if overlap_on else "halo")
            apply_fn, cg_fn, norm_fn, sharded_state = (
                make_folded_sharded_fns(op, dgrid, cfg.nreps,
                                        overlap=overlap_on)
            )
            state = sharded_state(op)
            if b_host is not None:
                # mat_comp: feed the oracle-precision host RHS to both paths.
                u_blocks = shard_folded_vectors(
                    b_host.astype(dtype), n, cfg.degree, dgrid.dshape,
                    op.layout,
                )
                u = jax.device_put(jnp.asarray(u_blocks), sharding)
            else:
                # Per-shard device RHS (no O(global-dof) host arrays).
                ccs, mcs = shard_corner_cs(mesh, dgrid.dshape, op.layout)
                rhs_fn = make_folded_rhs_fn(op, dgrid, t, dtype)
                # device_put numpy directly with the sharding: never stage
                # the global corner array on a single device
                np_dt = np.float32 if dtype == jnp.float32 else np.float64
                u = jax.jit(rhs_fn)(
                    jax.device_put(np.asarray(ccs, np_dt), sharding),
                    jax.device_put(np.asarray(mcs, np_dt), sharding),
                    op.bc_mask,
                )
            cg_args = (state, op.owned)
            apply_args = (state,)
            norm_args = (op.owned,)
        else:
            record_engine(res.extra, False)  # xla path: no engine form
            op = build_dist_laplacian(
                mesh, dgrid, cfg.degree, t, kappa=2.0, dtype=dtype,
                backend=backend,
            )
            u_blocks = shard_grid_blocks(b_host, n, cfg.degree, dgrid.dshape)
            u = jax.device_put(jnp.asarray(u_blocks, dtype=dtype), sharding)
            apply_fn, cg_fn, norm_fn = make_sharded_fns(op, dgrid, cfg.nreps)
            cg_args = (op.G, op.bc_mask)
            apply_args = (op.G, op.bc_mask)
            norm_args = ()

        run_input = u
        run_ck = ck_store = None
        ck_restored = 0
        ck_saves = {"n": 0}
        if cfg.nrhs > 1:
            # Batched multi-RHS sharded solve (the serving-layer shape):
            # one executable, psum'd batched dots, unfused vmapped local
            # apply — the fused engines have no batched form (recorded).
            from ..bench.driver import BATCHED_UNFUSED_REASON, stamp_nrhs

            if not cfg.use_cg:
                raise ValueError(GATE_REASONS["batched-sharded-action"])
            if folded:
                raise ValueError(GATE_REASONS["batched-sharded-folded"])
            record_engine(res.extra, False, error=BATCHED_UNFUSED_REASON)
            stamp_nrhs(res.extra, cfg.nrhs, cfg.checkpoint_every)
            if cfg.convergence:
                res.extra["convergence_gate_reason"] = (
                    GATE_REASONS["convergence-batched-sharded"])
            if cfg.precond != "none":
                from ..bench.driver import stamp_precond

                stamp_precond(res.extra, cfg, gate_reason=(
                    GATE_REASONS["precond-batched-sharded"]))
            if cfg.s_step > 1:
                res.extra["s_step"] = int(cfg.s_step)
                res.extra["s_step_gate_reason"] = (
                    GATE_REASONS["sstep-batched-sharded"])
            if kron:
                from .kron import make_kron_batched_cg_fn

                cg_fn = make_kron_batched_cg_fn(op, dgrid, cfg.nreps)
            else:
                cg_fn = make_sharded_batched_cg(op, dgrid, cfg.nreps)
            B = batch_sharded_rhs(u, cfg.nrhs, dgrid)
            run_input = B
            # unfused path: the default scoped limit suffices (kron/xla)
            with obs.phase("compile"):
                fn = compile_lowered(jax.jit(cg_fn).lower(B, *cg_args))
            run_args = cg_args
        elif cfg.use_cg and cfg.checkpoint_every > 0 and not folded:
            # durable checkpoints (ISSUE 9): iteration-boundary sharded
            # loop + host snapshots. The fused sharded engines are one
            # whole-solve executable — gated off, reason recorded.
            if res.extra.get("cg_engine"):
                from ..bench.driver import CHECKPOINT_GATE_REASON

                record_engine(res.extra, False)
                res.extra["checkpoint_gate_reason"] = (
                    CHECKPOINT_GATE_REASON)
                overlap_on = False
            if cfg.convergence:
                res.extra["convergence_gate_reason"] = (
                    GATE_REASONS["convergence-checkpoint"])
            if cfg.precond != "none":
                from ..bench.driver import stamp_precond
                from ..la.precond import PRECOND_GATE_REASONS

                stamp_precond(
                    res.extra, cfg,
                    gate_reason=PRECOND_GATE_REASONS["checkpoint"])
            if cfg.s_step > 1:
                res.extra["s_step"] = int(cfg.s_step)
                res.extra["s_step_gate_reason"] = (
                    GATE_REASONS["sstep-checkpoint"])
            run_ck, ck_store, ck_restored, ck_saves = (
                _make_dist_checkpointed_cg(cfg, res, obs, op, dgrid, u,
                                           kron))
            fn = None
            run_args = ()
        elif cfg.use_cg:
            if cfg.checkpoint_every > 0:
                # sharded folded (pallas): the per-shard seam state rides
                # the kernel and there is no checkpointable unfused local
                # apply yet — recorded, runs the standard whole-solve
                # executable with snapshots disabled
                res.extra["checkpoint_gate_reason"] = (
                    GATE_REASONS["checkpoint-folded-sharded"])
            # convergence capture (ISSUE 10): the history buffer rides
            # the unfused sharded CG (la.cg capture through the psum'd
            # owned-dof dots); the fused/overlap engine forms gate off
            # with the reason recorded — the checkpoint-gate discipline
            if cfg.convergence:
                if folded:
                    res.extra["convergence_gate_reason"] = (
                        GATE_REASONS["convergence-folded-sharded"])
                else:
                    from ..bench.driver import CONVERGENCE_GATE_REASON

                    conv_on = True
                    if res.extra.get("cg_engine"):
                        record_engine(res.extra, False)
                        res.extra["convergence_gate_reason"] = (
                            CONVERGENCE_GATE_REASON)
                        overlap_on = False
                    if kron:
                        from .kron import make_kron_sharded_fns

                        _, cg_fn, _ = make_kron_sharded_fns(
                            op, dgrid, cfg.nreps, engine=False,
                            capture=True)
                        # the unfused kron loop fits the default scoped
                        # limit (the raised request was the ring's)
                        compile_opts = None
                    else:
                        _, cg_fn, _ = make_sharded_fns(
                            op, dgrid, cfg.nreps, capture=True)

            # Preconditioning + s-step (ISSUE 11) on the sharded kron /
            # xla paths: the PCG twin runs the unfused local apply with
            # the owned-dof psum dot and ONE stacked psum for the
            # (<r,z>, <r,r>) pair; s-step batches s iterations'
            # reductions into ONE Gram psum (< 1 reduction/iteration,
            # trace-gated). Folded backend, pmg, and precond+s-step
            # combinations gate with recorded reasons.
            if cfg.precond != "none" or cfg.s_step > 1:
                from ..bench.driver import stamp_precond
                from ..la.precond import PRECOND_GATE_REASONS

                pre_kind = cfg.precond if cfg.precond != "none" else None
                want_sstep = cfg.s_step > 1
                pre_gate = None
                if folded:
                    if pre_kind:
                        pre_gate = PRECOND_GATE_REASONS["folded"]
                        pre_kind = None
                    if want_sstep:
                        want_sstep = False
                        res.extra["s_step"] = int(cfg.s_step)
                        res.extra["s_step_gate_reason"] = (
                            GATE_REASONS["sstep-folded-sharded"])
                elif pre_kind == "pmg":
                    pre_gate = GATE_REASONS["precond-pmg-sharded"]
                    pre_kind = None
                if cfg.precond != "none" and pre_kind is None:
                    stamp_precond(res.extra, cfg, gate_reason=pre_gate)
                if pre_kind and want_sstep:
                    want_sstep = False
                    res.extra["s_step"] = int(cfg.s_step)
                    res.extra["s_step_gate_reason"] = (
                        GATE_REASONS["sstep-precond"])
                if (pre_kind or want_sstep) and res.extra.get("cg_engine"):
                    record_engine(res.extra, False)
                    overlap_on = False
                    if pre_kind:
                        res.extra.setdefault(
                            "precond_gate_reason",
                            PRECOND_GATE_REASONS["engine"])
                    else:
                        res.extra.setdefault(
                            "s_step_gate_reason",
                            GATE_REASONS["sstep-engine-sharded"])
                    if kron:
                        compile_opts = None
                if pre_kind:
                    import time as _time

                    from ..la.precond import (
                        CHEB_LMIN_FRACTION,
                        CHEB_STEPS,
                        POWER_ITERS,
                        PrecondBundle,
                    )

                    t0 = _time.monotonic()
                    if kron:
                        from ..la.precond import jacobi_dinv_uniform_host

                        np_dt = (np.float32 if dtype == jnp.float32
                                 else np.float64)
                        dinv_host = jacobi_dinv_uniform_host(
                            t, n, 2.0, np_dt)
                        dinv = jax.device_put(jnp.asarray(
                            shard_grid_blocks(dinv_host, n, cfg.degree,
                                              dgrid.dshape)), sharding)
                    else:
                        dinv = jax.jit(make_sharded_dinv_fn(op, dgrid))(
                            op.G, op.bc_mask)
                    jax.block_until_ready(dinv)
                    cheb = None
                    setup_applies = 0
                    if pre_kind == "chebyshev":
                        # the SAME estimator as the single-chip driver
                        # (la.precond.estimate_lmax: fixed-seed start,
                        # deterministic), driven through the SHARDED
                        # apply and the masked psum norm so the interval
                        # — and therefore the polynomial — is identical
                        # on every shard
                        from ..la.precond import estimate_lmax

                        lmax = estimate_lmax(
                            lambda v: apply_fn(v, *apply_args), dinv,
                            u.shape, u.dtype,
                            norm_fn=lambda v: norm_fn(v, *norm_args)[0])
                        cheb = (lmax, lmax / CHEB_LMIN_FRACTION,
                                CHEB_STEPS)
                        setup_applies = POWER_ITERS
                    if kron:
                        from .kron import make_kron_pcg_fn

                        cg_fn = make_kron_pcg_fn(
                            op, dgrid, cfg.nreps, pre_kind, cheb=cheb,
                            capture=conv_on)
                        cg_args = (op, dinv)
                    else:
                        cg_fn = make_sharded_pcg_fn(
                            op, dgrid, cfg.nreps, pre_kind, cheb=cheb,
                            capture=conv_on)
                        cg_args = (op.G, op.bc_mask, dinv)
                    bundle = PrecondBundle(
                        kind=pre_kind, apply=None,
                        setup_s=_time.monotonic() - t0,
                        setup_applies=setup_applies,
                        applies_per_iter=(CHEB_STEPS - 1
                                          if cheb is not None else 0),
                        params=({"steps": cheb[2],
                                 "lmax": round(cheb[0], 6),
                                 "lmin": round(cheb[1], 8)}
                                if cheb is not None else {}))
                    stamp_precond(res.extra, cfg, bundle=bundle)
                    pcg_on = True
                elif want_sstep:
                    if kron:
                        from .kron import make_kron_sstep_cg_fn

                        cg_fn = make_kron_sstep_cg_fn(
                            op, dgrid, cfg.nreps, cfg.s_step,
                            capture=conv_on)
                    else:
                        cg_fn = make_sharded_sstep_cg(
                            op, dgrid, cfg.nreps, cfg.s_step,
                            capture=conv_on)
                    res.extra["s_step"] = int(cfg.s_step)
                    sstep_dist = True

            def _rebuild_cg(eng, ovl):
                if kron:
                    _, c, _ = make_kron_sharded_fns(
                        op, dgrid, cfg.nreps, engine=eng, overlap=ovl
                    )
                    # unfused kron fallback fits the default scoped limit
                    opts = compile_opts if eng else None
                else:
                    _, c, _, _ = make_folded_sharded_fns(
                        op, dgrid, cfg.nreps, engine=eng, overlap=ovl
                    )
                    # unfused folded fallback still runs the streamed
                    # corner kernels — keep the raised scoped request
                    opts = compile_opts
                with obs.phase("compile"):
                    return compile_lowered(jax.jit(c).lower(u, *cg_args),
                                           opts)

            try:
                with obs.phase("compile"):
                    fn = compile_lowered(
                        jax.jit(cg_fn).lower(u, *cg_args), compile_opts)
            except Exception as exc:
                # Same hardening as the single-chip driver: a Mosaic/XLA
                # rejection of the fused dist engine must not sink the
                # benchmark — fall back to the unfused sharded CG (whose
                # main kernel is also collective-independent) and record
                # why. Only a failure of the *engine* path warrants the
                # fallback recompile; anything else re-raises unchanged.
                if not ((kron or folded) and res.extra.get("cg_engine")):
                    raise
                if overlap_on:
                    # an overlap-form rejection first retries the
                    # SYNCHRONOUS engine (the recorded fallback the
                    # overlap contract requires), then the unfused path
                    record_engine(res.extra, True, base_form, error=exc)
                    try:
                        fn = _rebuild_cg(True, False)
                    except Exception as exc2:
                        record_engine(res.extra, False, error=exc2)
                        fn = _rebuild_cg(False, False)
                else:
                    record_engine(res.extra, False, error=exc)
                    fn = _rebuild_cg(False, False)
            run_args = cg_args
        else:
            # One jitted fori_loop over all reps (same rationale as the
            # single-chip driver: reference per-rep semantics, no host
            # dispatch in the timed region; the optimization_barrier ties
            # the input to the loop carry so the invariant apply can never
            # be hoisted out of the timed loop).
            if cfg.convergence:
                # same recorded gate as the single-chip driver: capture
                # was requested but action runs carry no residual
                res.extra["convergence_gate_reason"] = (
                    GATE_REASONS["convergence-action"])
            if cfg.precond != "none":
                from ..bench.driver import stamp_precond
                from ..la.precond import PRECOND_GATE_REASONS

                stamp_precond(res.extra, cfg,
                              gate_reason=PRECOND_GATE_REASONS["action"])
            if cfg.s_step > 1:
                res.extra["s_step"] = int(cfg.s_step)
                res.extra["s_step_gate_reason"] = (
                    GATE_REASONS["sstep-action"])

            def _compile_action(ap, opts):
                def _rep(i, y, x, a):
                    xx, _ = jax.lax.optimization_barrier((x, y))
                    return ap(xx, *a)

                with obs.phase("compile"):
                    return compile_lowered(jax.jit(
                        lambda x, *a: jax.lax.fori_loop(
                            0, cfg.nreps, partial(_rep, x=x, a=a),
                            jnp.zeros_like(x),
                        )
                    ).lower(u, *apply_args), opts)

            try:
                fn = _compile_action(apply_fn, compile_opts)
            except Exception as exc:
                # Engine-apply compile failure: unfused fallback, same
                # rationale as the CG branch above.
                if not ((kron or folded) and res.extra.get("cg_engine")):
                    raise
                record_engine(res.extra, False, error=exc)
                if kron:
                    apply_fn, _, _ = make_kron_sharded_fns(
                        op, dgrid, cfg.nreps, engine=False
                    )
                    fn = _compile_action(apply_fn, None)
                else:
                    apply_fn, _, _, _ = make_folded_sharded_fns(
                        op, dgrid, cfg.nreps, engine=False
                    )
                    fn = _compile_action(apply_fn, compile_opts)
            run_args = apply_args
        with obs.phase("compile"):
            norm_c = compile_lowered(jax.jit(norm_fn).lower(u, *norm_args))
        # Warm-up executes the full compiled computation once: the first
        # execution pays program-load/buffer-init costs that are not
        # operator throughput. A cheaper 1-rep warm-up would need a SECOND
        # full compile of the CG loop (tens of seconds) to save a few
        # seconds of device time — net slower at every size we run.
        with obs.phase("transfer"):
            warm = (run_ck(save=False) if run_ck is not None
                    else fn(run_input, *run_args))
            _fence_scalar(warm)
            del warm

    y = obs.timed_reps(run_ck if run_ck is not None
                       else (lambda: fn(run_input, *run_args)))
    elapsed = obs.elapsed()
    conv_hist = None
    if sstep_dist:
        # s-step solves return (x, info) with a replicated breakdown
        # flag; a breakdown re-runs the standard sharded recurrence
        # with the reason recorded (the graceful-fallback contract)
        y, ss_info = y
        if bool(np.asarray(ss_info["breakdown"])):
            from ..la.sstep import SSTEP_FALLBACK_REASON

            res.extra["s_step_fallback_reason"] = SSTEP_FALLBACK_REASON
            if kron:
                from .kron import make_kron_sharded_fns as _mk

                _, cg_fn, _ = _mk(op, dgrid, cfg.nreps, engine=False,
                                  capture=conv_on)
            else:
                _, cg_fn, _ = make_sharded_fns(op, dgrid, cfg.nreps,
                                               capture=conv_on)
            cg_args = ((op,) if kron else (op.G, op.bc_mask))
            with obs.phase("compile"):
                fn = compile_lowered(jax.jit(cg_fn).lower(u, *cg_args))
            with obs.phase("transfer"):
                warm = fn(u, *cg_args)
                _fence_scalar(warm)
                del warm
            run_args = cg_args
            y = obs.timed_reps(lambda: fn(run_input, *run_args))
            elapsed = obs.elapsed()
            if conv_on:
                y, conv_hist = y
        elif conv_on:
            conv_hist = ss_info["rnorm_history"]
    elif conv_on:
        # capture cg_fn returns (x, replicated history); the history is
        # fetched once, here, outside the timed region
        y, conv_hist = y

    if cfg.nrhs > 1:
        # lane 0 (scale 1.0) is the one-shot problem verbatim: norms and
        # the mat_comp oracle below read it, GDoF/s accounts the batch
        y = y[0]
    res.mat_free_time = elapsed
    un = np.asarray(norm_c(u, *norm_args))
    yn = np.asarray(norm_c(y, *norm_args))
    res.unorm, res.unorm_linf = float(un[0]), float(un[1])
    res.ynorm, res.ynorm_linf = float(yn[0]), float(yn[1])
    # a restored run only executed the remaining iterations (same
    # accounting as the single-chip checkpointed driver)
    iters_timed = cfg.nreps - (ck_restored if run_ck is not None else 0)
    res.gdof_per_second = (
        res.ndofs_global * iters_timed * cfg.nrhs / (1e9 * elapsed))
    from ..bench.driver import (
        stamp_breakdown,
        stamp_checkpoint,
        stamp_convergence,
        stamp_observability,
    )

    stamp_breakdown(res.extra, res.ynorm)
    if run_ck is not None:
        stamp_checkpoint(res.extra, cfg, ck_store, ck_restored,
                         ck_saves["n"])
    stamp_observability(cfg, res, obs,
                        "f32" if cfg.float_bits == 32 else "f64")
    if conv_hist is not None:
        stamp_convergence(res.extra, {"rnorm_history": conv_hist},
                          wall_s=elapsed, iters_run=cfg.nreps)
    if cfg.use_cg and cfg.nrhs == 1 and run_ck is None:
        _stamp_collectives(res.extra, cfg.nreps, elapsed, cg_fn, u,
                           *cg_args)

    if cfg.mat_comp:
        from ..bench.driver import _mat_comp_oracle

        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        if folded:
            from .folded import unshard_folded_vectors

            y_global = unshard_folded_vectors(
                np.asarray(y, dtype=np.float64), n, cfg.degree, dgrid.dshape,
                op.layout,
            )
        else:
            y_global = unshard_grid_blocks(
                np.asarray(y, dtype=np.float64), n, cfg.degree, dgrid.dshape
            )
        e = y_global - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def _run_distributed_folded_df(cfg, res):
    """Sharded perturbed df32: per-shard folded df pipeline (dist.folded
    df section — stacked-channel ppermute halos, compensated psum dots).
    The sharded XLA-emulation fallback only engages with a recorded
    reason (plan-unsupported config or compile rejection), mirroring the
    single-chip folded-df driver."""
    import jax
    import jax.numpy as jnp

    from ..bench.driver import _mat_comp_oracle, _setup_problem
    from ..elements.tables import build_operator_tables
    from ..la.df64 import DF
    from ..mesh.box import create_box_mesh
    from ..ops.folded_df import folded_df_plan
    from .folded import (
        build_dist_folded_df,
        make_folded_df_sharded_fns,
        shard_folded_vectors_df,
        unshard_folded_vectors,
    )

    if cfg.backend not in ("auto", "pallas"):
        raise ValueError(
            "perturbed f64_impl='df32' runs the folded pallas-df path; "
            f"--backend {cfg.backend} is not supported with it")

    def fallback(reason):
        # fresh results object (the failed folded attempt may already have
        # stamped f64_df32_path/geom — those must not survive onto a
        # number the emulated path produced) and backend reset to 'auto'
        # (an explicit pallas request cannot run f64 under Mosaic)
        import dataclasses

        from ..bench.driver import BenchmarkResults

        fcfg = dataclasses.replace(cfg, backend="auto")
        out = BenchmarkResults(nreps=cfg.nreps)
        prev = jax.config.jax_enable_x64
        jax.config.update("jax_enable_x64", True)
        try:
            out = run_distributed(fcfg, out, jnp.float64)
        finally:
            jax.config.update("jax_enable_x64", prev)
        out.extra["f64_impl"] = "emulated-fallback"
        out.extra["f64_df32_fallback_reason"] = reason
        from ..harness.classify import classify_text

        out.extra["failure_class"] = classify_text(reason)
        return out

    dgrid = make_device_grid(cfg.ndevices)
    n = compute_mesh_size_sharded(cfg.ndofs_global, cfg.degree, dgrid.dshape)
    rule = "gauss" if cfg.use_gauss else "gll"
    t = build_operator_tables(cfg.degree, cfg.qmode, rule)
    supported, _, kib = folded_df_plan(cfg.degree, t.nq)
    if not supported:
        from ..engines.registry import gate_reason

        return fallback(gate_reason("df-plan-unsupported",
                                    degree=cfg.degree, qmode=cfg.qmode))
    mesh = create_box_mesh(n, cfg.geom_perturb_fact)
    res.ncells_global = global_ncells(n)
    res.ndofs_global = global_ndofs(n, cfg.degree)
    res.extra["backend"] = "pallas"
    res.extra["f64_impl"] = "df32"
    res.extra["f64_df32_path"] = "folded"
    # the sharded folded df pipeline is deliberately unfused (dist.folded
    # df section) — no fused engine form exists for it yet
    record_engine(res.extra, False)
    if cfg.convergence:
        # the folded df CG's residual rides the kernel chain — no
        # per-iteration buffer to capture into (recorded, never silent)
        res.extra["convergence_gate_reason"] = (
            GATE_REASONS["convergence-folded-df-sharded"])

    # Host-assembled f64 RHS split into df channels and sharded per
    # channel. O(global-dof) host arrays — accepted on this path (the
    # accuracy/capacity pipeline; the geometry state, the actual HBM
    # driver at scale, stays per-shard).
    _, _, _, _, _, bc_grid, dm, b_host, G_host = _setup_problem(
        cfg, n, prebuilt=(n, rule, t, mesh)
    )

    obs = BenchObserver(cfg, run="dist")
    with Timer("% Create matfree operator"):
        from jax.sharding import NamedSharding, PartitionSpec as P

        sharding = NamedSharding(dgrid.mesh, P(*AXIS_NAMES))
        op = build_dist_folded_df(mesh, dgrid, cfg.degree, t, kappa=2.0)
        res.extra["geom"] = "corner" if op.Gh is None else "g"
        apply_fn, cg_fn, norm_fn, norms_from, sharded_state = (
            make_folded_df_sharded_fns(op, dgrid, cfg.nreps)
        )
        state = sharded_state(op)
        u = shard_folded_vectors_df(
            np.asarray(b_host, np.float64), n, cfg.degree, dgrid.dshape,
            op.layout,
        )
        u = DF(jax.device_put(u.hi, sharding), jax.device_put(u.lo, sharding))
        compile_opts = (scoped_vmem_options(kib)
                        if jax.default_backend() == "tpu" else None)
        from ..la.df64 import df_zeros_like

        if cfg.use_cg:
            low = jax.jit(cg_fn).lower(u, state, op.owned)
            run_args = (state, op.owned)
        else:
            def _rep(i, y, x, st):
                xx, _ = jax.lax.optimization_barrier((x, y))
                return apply_fn(xx, st)

            low = jax.jit(
                lambda x, st: jax.lax.fori_loop(
                    0, cfg.nreps, partial(_rep, x=x, st=st),
                    df_zeros_like(x),
                )
            ).lower(u, state)
            run_args = (state,)
        try:
            with obs.phase("compile"):
                fn = compile_lowered(low, compile_opts,
                                     cpu_extra=CPU_DF_DIST_OPTIONS)
        except Exception as exc:
            from ..engines.registry import gate_reason

            return fallback(gate_reason("df-compile-failed",
                                        error=exc_str(exc)))
        with obs.phase("transfer"):
            warm = fn(u, *run_args)
            float(warm.hi[(0,) * warm.hi.ndim])
            del warm

    y = obs.timed_reps(lambda: fn(u, *run_args))
    res.mat_free_time = obs.elapsed()

    norm_c = compile_lowered(jax.jit(norm_fn).lower(u, op.owned),
                             cpu_extra=CPU_DF_DIST_OPTIONS)
    res.unorm, res.unorm_linf = norms_from(norm_c(u, op.owned))
    res.ynorm, res.ynorm_linf = norms_from(norm_c(y, op.owned))
    res.gdof_per_second = (
        res.ndofs_global * cfg.nreps / (1e9 * res.mat_free_time)
    )
    from ..bench.driver import stamp_observability

    stamp_observability(cfg, res, obs, "df32")
    if cfg.use_cg:
        _stamp_collectives(res.extra, cfg.nreps, res.mat_free_time,
                           cg_fn, u, state, op.owned)

    if cfg.mat_comp:
        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        y64 = (
            unshard_folded_vectors(np.asarray(y.hi, np.float64), n,
                                   cfg.degree, dgrid.dshape, op.layout)
            + unshard_folded_vectors(np.asarray(y.lo, np.float64), n,
                                     cfg.degree, dgrid.dshape, op.layout)
        )
        e = y64 - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res


def run_distributed_df64(cfg, res):
    """Multi-device df64 (double-float) benchmark: the dist.kron_df path.
    Uniform meshes only (the kron decomposition); same protocol as
    run_distributed — AOT compile, full warm-up, fenced timing — with DF
    state and the compensated distributed reductions."""
    import jax
    import jax.numpy as jnp

    from ..bench.driver import _setup_problem
    from ..elements.tables import build_operator_tables
    from .kron_df import (
        DF,
        build_dist_kron_df,
        make_kron_df_rhs_fn,
        make_kron_df_sharded_fns,
    )

    if cfg.geom_perturb_fact != 0.0:
        return _run_distributed_folded_df(cfg, res)
    if cfg.backend not in ("auto", "kron"):
        from ..engines.registry import gate_reason

        raise ValueError(gate_reason("df-backend-kron",
                                     backend=cfg.backend))
    dgrid = make_device_grid(cfg.ndevices)
    n = compute_mesh_size_sharded(cfg.ndofs_global, cfg.degree, dgrid.dshape)
    rule = "gauss" if cfg.use_gauss else "gll"
    t = build_operator_tables(cfg.degree, cfg.qmode, rule)
    res.ncells_global = global_ncells(n)
    res.ndofs_global = global_ndofs(n, cfg.degree)
    res.extra["backend"] = "kron"
    res.extra["f64_impl"] = "df32"

    b_host = bc_grid = dm = G_host = None
    if cfg.mat_comp:
        # oracle runs solve the oracle's own host-assembled RHS (see
        # _run_benchmark_df64): enorm then measures solver error only
        from ..mesh.box import create_box_mesh

        _, _, _, _, _, bc_grid, dm, b_host, G_host = _setup_problem(
            cfg, n, prebuilt=(n, rule, t, create_box_mesh(n))
        )

    obs = BenchObserver(cfg, run="dist")
    with Timer("% Create matfree operator"):
        from ..la.df64 import df_from_f64
        from jax.sharding import NamedSharding, PartitionSpec as P

        op = build_dist_kron_df(n, dgrid, cfg.degree, cfg.qmode, rule,
                                kappa=2.0, tables=t)
        if cfg.mat_comp:
            bdf = df_from_f64(np.asarray(b_host, np.float64))
            sharding = NamedSharding(dgrid.mesh, P(*AXIS_NAMES))
            u = DF(*(
                jax.device_put(
                    jnp.asarray(shard_grid_blocks(
                        np.asarray(c), n, cfg.degree, dgrid.dshape)),
                    sharding)
                for c in (bdf.hi, bdf.lo)
            ))
        else:
            u = jax.jit(make_kron_df_rhs_fn(op, dgrid, t))()
        from .kron_cg_df import _is_x_only, dist_df_engine_plan
        from .kron_df import resolve_df_engine, resolve_df_overlap

        u_run = u
        conv_on = False
        if cfg.nrhs > 1:
            # batched multi-RHS sharded df: vmapped unfused local df
            # solve + compensated psum dots (dist.kron_df); the fused
            # dist df engine has no batched form — recorded fallback
            from ..bench.driver import (
                BATCHED_UNFUSED_REASON,
                batch_scales,
                stamp_nrhs,
            )
            from .kron_df import make_kron_df_batched_cg_fn

            if not cfg.use_cg:
                raise ValueError(GATE_REASONS["batched-sharded-df-action"])
            record_engine(res.extra, False, error=BATCHED_UNFUSED_REASON)
            stamp_nrhs(res.extra, cfg.nrhs, cfg.checkpoint_every)
            if cfg.convergence:
                res.extra["convergence_gate_reason"] = (
                    GATE_REASONS["convergence-batched-df-sharded"])
            _, _, norm_fn, norms_from = make_kron_df_sharded_fns(
                op, dgrid, cfg.nreps, engine=False)
            sc = jnp.asarray(batch_scales(cfg.nrhs), jnp.float32)
            bsh = NamedSharding(dgrid.mesh, P(None, *AXIS_NAMES))

            def _mk(c):
                return jax.device_put(
                    sc.reshape((-1,) + (1,) * c.ndim) * c[None], bsh)

            u_run = DF(_mk(u.hi), _mk(u.lo))
            cg_bat = make_kron_df_batched_cg_fn(op, dgrid, cfg.nreps)
            with obs.phase("compile"):
                fn = compile_lowered(
                    jax.jit(cg_bat).lower(u_run, op),
                    cpu_extra=CPU_DF_DIST_OPTIONS)
            engine = False
        else:
            engine = resolve_df_engine(op)
            base_form = "halo" if _is_x_only(op) else "ext2d"
            ovl_ok, ovl_gate = resolve_df_overlap(op)
            overlap_on = cfg.use_cg and (
                _resolve_overlap_mode(cfg, res.extra, ovl_ok, ovl_gate))
            record_engine(res.extra, engine,
                          base_form + ("_overlap" if overlap_on else ""))
            # convergence capture (ISSUE 10): rides the unfused sharded
            # df loop; the fused df ring gates off, reason recorded
            conv_on = cfg.convergence and cfg.use_cg
            if cfg.convergence and not cfg.use_cg:
                res.extra["convergence_gate_reason"] = (
                    GATE_REASONS["convergence-action"])
            if conv_on and engine:
                from ..bench.driver import CONVERGENCE_GATE_REASON

                engine = False
                overlap_on = False
                record_engine(res.extra, False)
                res.extra["convergence_gate_reason"] = (
                    CONVERGENCE_GATE_REASON)
        opts = (scoped_vmem_options(dist_df_engine_plan(op)[1])
                if engine else None)
        from ..la.df64 import df_zeros_like

        built = {}  # the python cg fn that ran (collective attribution)

        def _build(eng, ovl=False):
            a_fn, c_fn, n_fn, n_from = make_kron_df_sharded_fns(
                op, dgrid, cfg.nreps, engine=eng, overlap=ovl,
                capture=conv_on and not eng,
            )
            if cfg.use_cg:
                built["cg_fn"] = c_fn
                low = jax.jit(c_fn).lower(u, op)
            else:
                def _rep(i, y, x, A):
                    xx, _ = jax.lax.optimization_barrier((x, y))
                    return a_fn(xx, A)

                low = jax.jit(
                    lambda x, A: jax.lax.fori_loop(
                        0, cfg.nreps, partial(_rep, x=x, A=A),
                        df_zeros_like(x),
                    )
                ).lower(u, op)
            with obs.phase("compile"):
                return n_fn, n_from, compile_lowered(
                    low, extra=opts if eng else None,
                    cpu_extra=CPU_DF_DIST_OPTIONS)

        if cfg.nrhs == 1:
            try:
                norm_fn, norms_from, fn = _build(engine, overlap_on)
            except Exception as exc:
                # a Mosaic rejection of the fused dist df engine must not
                # sink the benchmark: record and complete on the unfused
                # path (an overlap-form rejection first retries the
                # synchronous engine, the recorded fallback the overlap
                # contract requires)
                if not engine:
                    raise
                if overlap_on:
                    record_engine(res.extra, True, base_form, error=exc)
                    try:
                        norm_fn, norms_from, fn = _build(True, False)
                    except Exception as exc2:
                        engine = False
                        record_engine(res.extra, False, error=exc2)
                        norm_fn, norms_from, fn = _build(False)
                else:
                    engine = False
                    record_engine(res.extra, False, error=exc)
                    norm_fn, norms_from, fn = _build(False)
        with obs.phase("transfer"):
            warm = fn(u_run, op)
            _fence_scalar(warm)
            del warm

    y = obs.timed_reps(lambda: fn(u_run, op))
    res.mat_free_time = obs.elapsed()
    conv_hist = None
    if conv_on:
        # capture cg_fn returns ((hi, lo), replicated history)
        y, conv_hist = y

    if cfg.nrhs > 1:
        # lane 0 (scale 1.0) is the one-shot problem verbatim; GDoF/s
        # accounts the whole batch
        y = DF(y.hi[0], y.lo[0])
    norm_c = compile_lowered(jax.jit(norm_fn).lower(u, op),
                             cpu_extra=CPU_DF_DIST_OPTIONS)
    res.unorm, res.unorm_linf = norms_from(norm_c(u, op))
    res.ynorm, res.ynorm_linf = norms_from(norm_c(y, op))
    res.gdof_per_second = (
        res.ndofs_global * cfg.nreps * cfg.nrhs
        / (1e9 * res.mat_free_time)
    )
    from ..bench.driver import stamp_convergence, stamp_observability

    stamp_observability(cfg, res, obs, "df32")
    if conv_hist is not None:
        stamp_convergence(res.extra, {"rnorm_history": conv_hist},
                          wall_s=res.mat_free_time, iters_run=cfg.nreps)
    if cfg.use_cg and cfg.nrhs == 1 and built.get("cg_fn") is not None:
        _stamp_collectives(res.extra, cfg.nreps, res.mat_free_time,
                           built["cg_fn"], u, op)

    if cfg.mat_comp:
        from ..bench.driver import _mat_comp_oracle

        z = _mat_comp_oracle(cfg, t, dm, bc_grid, b_host, G_host)
        y64 = (
            unshard_grid_blocks(np.asarray(y.hi, np.float64), n,
                                cfg.degree, dgrid.dshape)
            + unshard_grid_blocks(np.asarray(y.lo, np.float64), n,
                                  cfg.degree, dgrid.dshape)
        )
        e = y64 - z
        res.znorm = float(np.linalg.norm(z))
        res.enorm = float(np.linalg.norm(e))
    return res
