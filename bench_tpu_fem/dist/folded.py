"""Distributed folded-layout operator: folded shards over the device grid.

The folded layout (ops.folded) makes the halo structural: each shard's ghost
cell columns are exactly the data it needs from its +x/+y/+z neighbours, so

- forward halo  = one `lax.ppermute` per axis carrying the neighbour's
  (c*=0, i=0) slab into the local ghost column (right -> left), and
- reverse scatter = the same slab of accumulated seam partials sent left ->
  right and added into the owner (the distributed tail of the overlap-add
  that replaces the reference's atomicAdd + MPI ghost scatter,
  /root/reference/src/vector.hpp:31-149, laplacian.hpp:286-347).

Exchanges run in axis order x, y, z; each payload spans the full local
c-cross-section *including* previously refreshed ghost columns, which fills
edge/corner ghosts transitively (all shards move in SPMD lockstep, so the
x-refreshed data is present before the y exchange reads it). Ownership: the
plane shared by two shards belongs to the *right* shard (it is that shard's
(c*=0, i=0) slots); the global last plane per axis belongs to the last
shard's ghost column.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..elements.tables import OperatorTables
from ..mesh.box import BoxMesh
from ..mesh.dofmap import boundary_dof_marker
from ..ops.folded import (
    FoldedLayout,
    fold_vector,
    folded_cell_apply,
    make_layout,
    unfold_vector,
)
from ..ops.laplacian import freeze_table
from .halo import _shift_from_left, _shift_from_right, masked_linf, psum_all
from .mesh import AXIS_NAMES, shard_cells


def _cview(x: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    """Folded (nb, P^3, B) vector -> 6D cell view (P, P, P, npx, npy, npz)
    (drops the block-padding tail, which stays untouched by halo traffic)."""
    P = layout.degree
    flat = jnp.transpose(x, (1, 0, 2)).reshape(P * P * P, layout.lv)
    return flat[:, : layout.cg].reshape(P, P, P, *layout.np3)


def _from_cview(v: jnp.ndarray, x: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    P = layout.degree
    xflat = jnp.transpose(x, (1, 0, 2)).reshape(P * P * P, layout.lv)
    flat = jnp.concatenate(
        [v.reshape(P * P * P, layout.cg), xflat[:, layout.cg:]], axis=-1
    )
    return jnp.transpose(
        flat.reshape(P * P * P, layout.nblocks, layout.block), (1, 0, 2)
    )


def folded_halo_refresh(x: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    """Fill ghost-column (i=0) slots from the right neighbour along each
    axis (the forward scatter, owner -> ghost). The last shard keeps its own
    ghost column: those slots are the owned global boundary plane."""
    v = _cview(x, layout)
    for ax, name in zip(range(3), AXIS_NAMES):
        n = lax.axis_size(name)
        if n == 1:
            continue
        cax = 3 + ax  # cell axis in the 6D view
        iax = ax  # local dof index axis
        # payload: the (c_ax = 0, i_ax = 0) slab, all other dims full
        payload = lax.index_in_dim(
            lax.index_in_dim(v, 0, axis=iax, keepdims=True), 0, axis=cax,
            keepdims=True,
        )
        recv = _shift_from_right(payload, name)
        idx = lax.axis_index(name)
        last = v.shape[cax] - 1
        ghost = lax.index_in_dim(
            lax.index_in_dim(v, 0, axis=iax, keepdims=True), last, axis=cax,
            keepdims=True,
        )
        new_ghost = jnp.where(idx == n - 1, ghost, recv)
        # reassemble along the i axis x cell axis
        islab = lax.index_in_dim(v, 0, axis=iax, keepdims=True)
        islab = jnp.concatenate(
            [lax.slice_in_dim(islab, 0, last, axis=cax), new_ghost], axis=cax
        )
        rest = lax.slice_in_dim(v, 1, v.shape[iax], axis=iax)
        v = jnp.concatenate([islab, rest], axis=iax)
    return _from_cview(v, x, layout)


def folded_reverse_scatter(y: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    """Send ghost-column seam partials to the owning right neighbour and
    accumulate (ghost -> owner). Non-last shards' ghost columns are zeroed;
    the last shard's ghost column holds owned boundary dofs and is kept."""
    v = _cview(y, layout)
    for ax, name in zip(range(3), AXIS_NAMES):
        n = lax.axis_size(name)
        if n == 1:
            continue
        cax = 3 + ax
        iax = ax
        idx = lax.axis_index(name)
        last = v.shape[cax] - 1
        islab = lax.index_in_dim(v, 0, axis=iax, keepdims=True)
        ghost = lax.index_in_dim(islab, last, axis=cax, keepdims=True)
        contrib = jnp.where(idx == n - 1, jnp.zeros_like(ghost), ghost)
        recv = _shift_from_left(contrib, name)  # zeros on shard 0
        first = lax.index_in_dim(islab, 0, axis=cax, keepdims=True)
        new_first = first + recv
        new_ghost = jnp.where(idx == n - 1, ghost, jnp.zeros_like(ghost))
        islab = jnp.concatenate(
            [new_first, lax.slice_in_dim(islab, 1, last, axis=cax), new_ghost],
            axis=cax,
        )
        rest = lax.slice_in_dim(v, 1, v.shape[iax], axis=iax)
        v = jnp.concatenate([islab, rest], axis=iax)
    return _from_cview(v, y, layout)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["G", "bc_mask", "owned", "kappa"],
    meta_fields=["n_local", "degree", "nl", "is_identity", "phi0_c", "dphi1_c"],
)
@dataclass(frozen=True)
class DistFoldedLaplacian:
    """Stacked per-shard folded operator state (leading (Dx, Dy, Dz) axes
    sharded over the device grid)."""

    G: jnp.ndarray  # (Dx,Dy,Dz, nblocks, 6, nq,nq,nq, 8, nl)
    bc_mask: jnp.ndarray  # (Dx,Dy,Dz, nb, P^3, B) bool
    owned: jnp.ndarray  # (Dx,Dy,Dz, nb, P^3, B) bool: dof counted here
    kappa: jnp.ndarray
    n_local: tuple[int, int, int]
    degree: int
    nl: int
    is_identity: bool
    phi0_c: tuple = ()
    dphi1_c: tuple = ()

    @property
    def layout(self) -> FoldedLayout:
        return FoldedLayout(n=self.n_local, degree=self.degree, nl=self.nl)

    def apply_local(self, x, G_local, bc_local):
        """y = A x for one shard (inside shard_map): halo refresh -> local
        folded apply -> reverse seam scatter -> Dirichlet pass-through."""
        layout = self.layout
        x = folded_halo_refresh(x, layout)
        xm = jnp.where(bc_local, 0, x)
        y = folded_cell_apply(
            xm, G_local, self.kappa, layout,
            np.asarray(self.phi0_c, np.float64),
            np.asarray(self.dphi1_c, np.float64),
            self.is_identity,
        )
        y = folded_reverse_scatter(y, layout)
        return jnp.where(bc_local, x, y)


def shard_folded_vectors(
    grid: np.ndarray,
    n: tuple[int, int, int],
    degree: int,
    dshape: tuple[int, int, int],
    layout: FoldedLayout,
) -> np.ndarray:
    """Global dof grid -> stacked per-shard folded vectors
    (Dx, Dy, Dz, nb, P^3, B). Each shard folds its inclusive local block
    (owned planes + the right-neighbour-owned closing plane, which lands in
    ghost slots: harmless placeholders, refreshed before use)."""
    P = degree
    ncl = shard_cells(n, dshape)
    out = np.zeros((*dshape, *layout.vec_shape), dtype=grid.dtype)
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                x0, y0, z0 = i * ncl[0] * P, j * ncl[1] * P, k * ncl[2] * P
                blk = grid[
                    x0: x0 + ncl[0] * P + 1,
                    y0: y0 + ncl[1] * P + 1,
                    z0: z0 + ncl[2] * P + 1,
                ]
                out[i, j, k] = fold_vector(blk, layout)
    return out


def unshard_folded_vectors(
    blocks: np.ndarray,
    n: tuple[int, int, int],
    degree: int,
    dshape: tuple[int, int, int],
    layout: FoldedLayout,
) -> np.ndarray:
    """Inverse of shard_folded_vectors, trusting only owned planes (interior
    shards' ghost-held closing planes are taken from the owning right
    neighbour's (c*=0, i=0) slots)."""
    P = degree
    ncl = shard_cells(n, dshape)
    N = tuple(nc * ds * P + 1 for nc, ds in zip(ncl, dshape))
    out = np.empty(N, dtype=blocks.dtype)
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                blk = unfold_vector(blocks[i, j, k], layout)
                x0, y0, z0 = i * ncl[0] * P, j * ncl[1] * P, k * ncl[2] * P
                out[
                    x0: x0 + ncl[0] * P + 1,
                    y0: y0 + ncl[1] * P + 1,
                    z0: z0 + ncl[2] * P + 1,
                ] = blk
    return out


def owned_folded_mask(layout: FoldedLayout, shard_pos, dshape) -> np.ndarray:
    """Host-side: bool mask of slots counted by this shard in global
    reductions (every dof exactly once). Structural slots and interior
    shards' ghost columns are excluded."""
    P3 = layout.degree ** 3
    marks = fold_vector(
        np.ones(tuple(c * layout.degree + 1 for c in layout.n)), layout
    ) > 0
    mflat = marks.transpose(1, 0, 2).reshape(P3, layout.lv)
    v = mflat[:, : layout.cg].reshape(
        layout.degree, layout.degree, layout.degree, *layout.np3
    ).copy()
    for ax in range(3):
        if shard_pos[ax] != dshape[ax] - 1:
            sl = [slice(None)] * 6
            sl[3 + ax] = layout.np3[ax] - 1
            v[tuple(sl)] = False
    flat = np.zeros((P3, layout.lv), dtype=bool)
    flat[:, : layout.cg] = v.reshape(P3, layout.cg)
    return np.ascontiguousarray(
        flat.reshape(P3, layout.nblocks, layout.block).transpose(1, 0, 2)
    )


def build_dist_folded(
    mesh: BoxMesh,
    dgrid,
    degree: int,
    tables: OperatorTables,
    kappa: float = 2.0,
    dtype=jnp.float32,
    nl: int | None = None,
) -> DistFoldedLaplacian:
    """Build stacked folded shards; per-shard geometry computed on device
    inside shard_map (ghost/pad cells: unit corners + zero mask, as in
    ops.folded.build_folded_laplacian)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ops.folded import blocked_G_traced, ghost_corner_arrays

    t = tables
    dshape = dgrid.dshape
    ncl = shard_cells(mesh.n, dshape)
    layout = make_layout(ncl, degree, t.nq, np.dtype(dtype).itemsize, nl=nl)

    # Host-side per-shard corner/mask/bc/owned prep (ghost-cell convention
    # shared with the single-device builder via ghost_corner_arrays).
    corners_all = mesh.cell_corners  # (nx, ny, nz, 2,2,2,3)
    bc_global = boundary_dof_marker(mesh.n, degree)

    corners_cs = np.empty((*dshape, layout.lv, 2, 2, 2, 3), dtype=np.float64)
    mask_cs = np.zeros((*dshape, layout.lv))
    bc_blocks = np.zeros((*dshape, *layout.vec_shape), dtype=bool)
    owned_blocks = np.zeros((*dshape, *layout.vec_shape), dtype=bool)
    Pd = degree
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                blk = corners_all[
                    i * ncl[0]: (i + 1) * ncl[0],
                    j * ncl[1]: (j + 1) * ncl[1],
                    k * ncl[2]: (k + 1) * ncl[2],
                ]
                corners_cs[i, j, k], mask_cs[i, j, k] = ghost_corner_arrays(
                    layout, blk
                )
                x0, y0, z0 = i * ncl[0] * Pd, j * ncl[1] * Pd, k * ncl[2] * Pd
                bc_blk = bc_global[
                    x0: x0 + ncl[0] * Pd + 1,
                    y0: y0 + ncl[1] * Pd + 1,
                    z0: z0 + ncl[2] * Pd + 1,
                ]
                bc_blocks[i, j, k] = fold_vector(bc_blk, layout)
                owned_blocks[i, j, k] = owned_folded_mask(layout, (i, j, k), dshape)

    spec = P(*AXIS_NAMES)
    sharding = NamedSharding(dgrid.mesh, spec)
    corners_d = jax.device_put(jnp.asarray(corners_cs, dtype=dtype), sharding)
    mask_d = jax.device_put(jnp.asarray(mask_cs, dtype=dtype), sharding)

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, spec), out_specs=spec)
    def shard_geometry(c, m):
        # Chunked (see ops.folded.blocked_G_traced): the per-shard G build
        # must not peak at ~3x final-G — that was the capacity limit.
        return blocked_G_traced(c[0, 0, 0], m[0, 0, 0], layout, t)[None, None, None]

    G = shard_geometry(corners_d, mask_d)

    return DistFoldedLaplacian(
        G=G,
        bc_mask=jax.device_put(jnp.asarray(bc_blocks), sharding),
        owned=jax.device_put(jnp.asarray(owned_blocks), sharding),
        kappa=jnp.asarray(kappa, dtype=dtype),
        n_local=tuple(ncl),
        degree=degree,
        nl=layout.nl,
        is_identity=t.is_identity,
        phi0_c=freeze_table(t.phi0),
        dphi1_c=freeze_table(t.dphi1),
    )


def make_folded_sharded_fns(op: DistFoldedLaplacian, dgrid, nreps: int):
    """Jittable sharded callables (apply, CG, norm) over folded shards —
    mirrors dist.driver.make_sharded_fns."""
    from jax.sharding import PartitionSpec as P

    from ..la.cg import cg_solve

    spec = P(*AXIS_NAMES)
    rep = P()

    def _local(a):
        return a[0, 0, 0]

    def _dot(mask):
        def dot(u, v):
            return psum_all(jnp.sum(u * v * mask.astype(u.dtype)))

        return dot

    # check_vma=False is *required* here, not a blanket waiver: every folded
    # sharded computation runs the Pallas kernel (folded_cell_apply), whose
    # pallas_call output carries no varying-mesh-axes annotation, and the
    # default shard_map VMA check rejects exactly that. This mirrors
    # dist/kron.py's scoped `check_vma = impl != "pallas"` — the folded path
    # simply has no non-pallas impl to scope back to.
    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    def apply_fn(x, G, bc):
        return op.apply_local(_local(x), _local(G), _local(bc))[None, None, None]

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec, spec, spec, spec), out_specs=spec, check_vma=False)
    def cg_fn(b, G, bc, owned):
        bl = _local(b)
        x = cg_solve(
            lambda v: op.apply_local(v, _local(G), _local(bc)),
            bl,
            jnp.zeros_like(bl),
            nreps,
            dot=_dot(_local(owned)),
        )
        return x[None, None, None]

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, spec), out_specs=rep)
    def norm_fn(x, owned):
        """Global (L2, Linf) over owned dofs (psum / pmax)."""
        xl, ol = _local(x), _local(owned)
        return jnp.stack(
            [jnp.sqrt(_dot(ol)(xl, xl)), masked_linf(xl, ol)]
        )

    return apply_fn, cg_fn, norm_fn
