"""Distributed folded-layout operator: folded shards over the device grid.

The folded layout (ops.folded) makes the halo structural: each shard's ghost
cell columns are exactly the data it needs from its +x/+y/+z neighbours.
This module gives the general-geometry distributed path the same two
properties the kron flagship path has (dist/kron.py):

COMM/COMPUTE OVERLAP BY CONSTRUCTION (the reference's lcell/bcell split,
/root/reference/src/laplacian.hpp:286-347). The apply is decomposed by
LINEARITY of the operator in its input:

    y = A(x_interior) + A(g_x) + A(g_y) + A(g_z)

where x_interior is the local vector with true-ghost slots zeroed and g_a
is the (disjoint) class of ghost slots refreshed along axis a. The MAIN
kernel — the full-volume fused Pallas apply — consumes only x_interior and
therefore has NO data dependency on any collective: XLA is free to run the
ppermute chain behind it. The ghost contributions are added by three THIN
EPILOGUES, each a fused apply on a 2-cell-column sub-layout (the only cells
whose windows touch that ghost class) — O(surface) compute that alone waits
on the halo. The final reverse seam scatter (ghost partials -> owner,
the distributed tail of the overlap-add replacing the reference's
atomicAdd + MPI scatter, vector.hpp:31-149) runs after the adds.

Ghost-class partition (exact, no double counting): g_x = all slots in the
+x ghost column; g_y = +y ghost column minus g_x's corner slots (only when
x is actually sharded — otherwise those slots belong to g_y); g_z = +z
ghost column minus both. Transitive corner filling follows from the
exchange order x, y, z with payloads spanning the full refreshed
cross-section (all shards move in SPMD lockstep).

PER-SHARD CLOSED-FORM SETUP. No O(global-dof) host arrays anywhere:
Dirichlet/ghost/owned masks are computed per shard from the shard position
(the box structure makes them closed-form), geometry ships as per-shard
cell corners (24 floats/cell; G is computed in-kernel — ops.folded corner
mode — or precomputed per shard on device), and the RHS is assembled on
device per shard (ops.folded_rhs) and seam-reduced. Host work is O(local)
per shard plus one corner-array slice.

Ownership: the plane shared by two shards belongs to the *right* shard (it
is that shard's (c*=0, i=0) slots); the global last plane per axis belongs
to the last shard's ghost column (an owned, real column there).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..elements.tables import OperatorTables
from ..mesh.box import BoxMesh
from ..ops.folded import (
    FoldedLayout,
    auto_geom,
    blocked_corners,
    check_tpu_lane_support,
    fold_vector,
    folded_cell_apply_fused,
    ghost_corner_arrays,
    make_layout,
    unfold_vector,
)
from ..ops.laplacian import freeze_table
from .halo import _shift_from_left, _shift_from_right, masked_linf
from .mesh import AXIS_NAMES, shard_cells


def _cview(x: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    """Folded (nb, P^3, B) vector -> 6D cell view (P, P, P, npx, npy, npz)
    (drops the block-padding tail, which stays untouched by halo traffic)."""
    P = layout.degree
    flat = jnp.transpose(x, (1, 0, 2)).reshape(P * P * P, layout.lv)
    return flat[:, : layout.cg].reshape(P, P, P, *layout.np3)


def _from_cview(v: jnp.ndarray, x: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    P = layout.degree
    xflat = jnp.transpose(x, (1, 0, 2)).reshape(P * P * P, layout.lv)
    flat = jnp.concatenate(
        [v.reshape(P * P * P, layout.cg), xflat[:, layout.cg:]], axis=-1
    )
    return jnp.transpose(
        flat.reshape(P * P * P, layout.nblocks, layout.block), (1, 0, 2)
    )


def _cview_to_folded(v: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    """6D cell view -> folded (nb, P^3, B), block-padding tail zero."""
    P = layout.degree
    flat = v.reshape(P * P * P, layout.cg)
    flat = jnp.pad(flat, ((0, 0), (0, layout.lv - layout.cg)))
    return jnp.transpose(
        flat.reshape(P * P * P, layout.nblocks, layout.block), (1, 0, 2)
    )


def _halo_refresh_view(v: jnp.ndarray, lead: int) -> jnp.ndarray:
    """Owner -> ghost refresh on a 6D cell view with `lead` extra leading
    (channel) axes — one ppermute per sharded axis carrying ALL leading
    channels in a single stacked payload (the dist.kron_cg_df
    stacked-channel pattern). Shared by the f32 (lead=0) and df (lead=1,
    stacked hi/lo) forms."""
    for ax, name in zip(range(3), AXIS_NAMES):
        n = lax.axis_size(name)
        if n == 1:
            continue
        cax = lead + 3 + ax  # cell axis in the view
        iax = lead + ax  # local dof index axis
        # payload: the (c_ax = 0, i_ax = 0) slab, all other dims full
        payload = lax.index_in_dim(
            lax.index_in_dim(v, 0, axis=iax, keepdims=True), 0, axis=cax,
            keepdims=True,
        )
        recv = _shift_from_right(payload, name)
        idx = lax.axis_index(name)
        last = v.shape[cax] - 1
        ghost = lax.index_in_dim(
            lax.index_in_dim(v, 0, axis=iax, keepdims=True), last, axis=cax,
            keepdims=True,
        )
        new_ghost = jnp.where(idx == n - 1, ghost, recv)
        # reassemble along the i axis x cell axis
        islab = lax.index_in_dim(v, 0, axis=iax, keepdims=True)
        islab = jnp.concatenate(
            [lax.slice_in_dim(islab, 0, last, axis=cax), new_ghost], axis=cax
        )
        rest = lax.slice_in_dim(v, 1, v.shape[iax], axis=iax)
        v = jnp.concatenate([islab, rest], axis=iax)
    return v


def folded_halo_refresh(x: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    """Fill ghost-column (i=0) slots from the right neighbour along each
    axis (the forward scatter, owner -> ghost). The last shard keeps its own
    ghost column: those slots are the owned global boundary plane. Payloads
    span the full refreshed cross-section, so later axes carry earlier
    axes' ghost data into edge/corner slots transitively. Depends only on
    the input — never on operator output — so the whole chain can run
    behind the main kernel."""
    return _from_cview(_halo_refresh_view(_cview(x, layout), 0), x, layout)


def _reverse_scatter_view(v: jnp.ndarray, lead: int, add) -> jnp.ndarray:
    """Ghost -> owner seam accumulation on a 6D cell view with `lead`
    leading channel axes; `add` combines the owner's first-column slab
    with the received partials (plain + for f32, a channel-split df_add
    for the stacked df form — channel-wise adds would drop carries)."""
    for ax, name in zip(range(3), AXIS_NAMES):
        n = lax.axis_size(name)
        if n == 1:
            continue
        cax = lead + 3 + ax
        iax = lead + ax
        idx = lax.axis_index(name)
        last = v.shape[cax] - 1
        islab = lax.index_in_dim(v, 0, axis=iax, keepdims=True)
        ghost = lax.index_in_dim(islab, last, axis=cax, keepdims=True)
        contrib = jnp.where(idx == n - 1, jnp.zeros_like(ghost), ghost)
        recv = _shift_from_left(contrib, name)  # zeros on shard 0
        first = lax.index_in_dim(islab, 0, axis=cax, keepdims=True)
        new_first = add(first, recv)
        new_ghost = jnp.where(idx == n - 1, ghost, jnp.zeros_like(ghost))
        islab = jnp.concatenate(
            [new_first, lax.slice_in_dim(islab, 1, last, axis=cax), new_ghost],
            axis=cax,
        )
        rest = lax.slice_in_dim(v, 1, v.shape[iax], axis=iax)
        v = jnp.concatenate([islab, rest], axis=iax)
    return v


def folded_reverse_scatter(y: jnp.ndarray, layout: FoldedLayout) -> jnp.ndarray:
    """Send ghost-column seam partials to the owning right neighbour and
    accumulate (ghost -> owner). Non-last shards' ghost columns are zeroed;
    the last shard's ghost column holds owned boundary dofs and is kept."""
    v = _reverse_scatter_view(_cview(y, layout), 0, lambda a, b: a + b)
    return _from_cview(v, y, layout)


def _epi_layout(layout: FoldedLayout, axis: int) -> FoldedLayout:
    """Sub-layout of the axis-a epilogue: the 2 cell columns adjacent to the
    +a ghost plane (n_a -> 1, other axes unchanged). For axis 0 this is the
    parent's trailing contiguous flat-c range; shifts along the other axes
    are inherited exactly."""
    n = list(layout.n)
    n[axis] = 1
    return FoldedLayout(n=tuple(n), degree=layout.degree, nl=layout.nl)


def _extract_epi_input(xe6, layout: FoldedLayout, axis: int,
                       excl: tuple[bool, bool, bool]):
    """Build the axis-a epilogue sub-vector from the 6D view of the
    ghost-only input xe (refreshed, bc-masked, true-ghost slots only):
    columns [np_a - 2, np_a) with the adjacent real column zeroed (its data
    is the main kernel's) and, per `excl`, the ghost slots already claimed
    by an earlier sharded axis zeroed (the g_x > g_y > g_z partition)."""
    np3 = layout.np3
    cax = 3 + axis
    ghost = lax.index_in_dim(xe6, np3[axis] - 1, axis=cax, keepdims=True)
    for a2 in range(3):
        if a2 == axis or not excl[a2]:
            continue
        # zero the a2 ghost plane inside this ghost column (claimed by g_a2)
        c2 = 3 + a2
        keep = lax.slice_in_dim(ghost, 0, np3[a2] - 1, axis=c2)
        zero = jnp.zeros_like(
            lax.index_in_dim(ghost, np3[a2] - 1, axis=c2, keepdims=True)
        )
        ghost = jnp.concatenate([keep, zero], axis=c2)
    sub6 = jnp.concatenate([jnp.zeros_like(ghost), ghost], axis=cax)
    return _cview_to_folded(sub6, _epi_layout(layout, axis))


def _addback_epi(y6, ye, layout: FoldedLayout, axis: int):
    """Add the axis-a epilogue output (sub-folded) into the parent 6D view
    at columns [np_a - 2, np_a)."""
    sl = _epi_layout(layout, axis)
    P = layout.degree
    ye6 = jnp.transpose(ye, (1, 0, 2)).reshape(P * P * P, sl.lv)[
        :, : sl.cg
    ].reshape(P, P, P, *sl.np3)
    cax = 3 + axis
    np_a = layout.np3[axis]
    head = lax.slice_in_dim(y6, 0, np_a - 2, axis=cax)
    tail = lax.slice_in_dim(y6, np_a - 2, np_a, axis=cax)
    return jnp.concatenate([head, tail + ye6], axis=cax)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["G", "corners", "cmask", "bc_mask", "owned",
                 "epi_geom", "kappa"],
    meta_fields=["n_local", "degree", "nl", "is_identity", "dshape",
                 "phi0_c", "dphi1_c", "pts_c", "wts_c"],
)
@dataclass(frozen=True)
class DistFoldedLaplacian:
    """Stacked per-shard folded operator state (leading (Dx, Dy, Dz) axes
    sharded over the device grid). Geometry is corner mode (G None) or
    precomputed per shard (corners/cmask None), as in ops.folded."""

    G: jnp.ndarray | None  # (Dx,Dy,Dz, nb, 6, nq,nq,nq, 8, nl) or None
    corners: jnp.ndarray | None  # (Dx,Dy,Dz, nb, 3, 2,2,2, 8, nl) or None
    cmask: jnp.ndarray | None  # (Dx,Dy,Dz, nb, 8, nl) or None
    bc_mask: jnp.ndarray  # (Dx,Dy,Dz, nb, P^3, B) 0/1, vector dtype
    owned: jnp.ndarray  # (Dx,Dy,Dz, nb, P^3, B) bool: dof counted here
    # NOTE: the "not a true ghost" mask the main kernel needs is exactly
    # `owned` (every real non-ghost slot is owned under this partition);
    # sharded_state derives it as owned.astype(dtype) instead of storing a
    # byte-identical copy.
    # per sharded axis: (geomlike..., bc_sub) for the 2-column epilogue
    # sub-layout, stacked per shard; None for unsharded axes
    epi_geom: tuple
    kappa: jnp.ndarray
    n_local: tuple[int, int, int]
    degree: int
    nl: int
    is_identity: bool
    dshape: tuple[int, int, int] = (1, 1, 1)
    phi0_c: tuple = ()
    dphi1_c: tuple = ()
    pts_c: tuple = ()
    wts_c: tuple = ()

    @property
    def layout(self) -> FoldedLayout:
        return FoldedLayout(n=self.n_local, degree=self.degree, nl=self.nl)

    @property
    def geom_tables(self):
        if self.G is not None:
            return None
        return (np.asarray(self.pts_c), np.asarray(self.wts_c))

    def _tables(self):
        return (np.asarray(self.phi0_c, np.float64),
                np.asarray(self.dphi1_c, np.float64))

    def _fused(self, xb, bcf, geom, layout):
        phi0, dphi1 = self._tables()
        return folded_cell_apply_fused(
            xb, bcf, geom, self.kappa, layout, phi0, dphi1,
            self.is_identity, geom_tables=self.geom_tables,
        )

    def apply_local(self, x, state):
        """y = A x for one shard (inside shard_map), with the main kernel
        structurally independent of the halo collectives (see module
        docstring). `state` holds this shard's slices (geom, bc, nghost,
        epilogue state)."""
        layout = self.layout
        geom, bc, ngh, epi = state
        # halo chain: depends only on x — overlaps the main kernel
        xr = folded_halo_refresh(x, layout)
        # main kernel: interior + locally-complete contributions only
        xb = x * ngh * (1 - bc)
        y = self._fused(xb, bc, geom, layout)
        # thin epilogues: the ghost-slot contributions, per sharded axis
        xe = xr * (1 - bc) * (1 - ngh)  # true-ghost slots only
        xe6 = _cview(xe, layout)
        y6 = _cview(y, layout)
        excl = tuple(d > 1 for d in self.dshape)
        for ax in range(3):
            if self.dshape[ax] == 1:
                continue
            sub = _extract_epi_input(
                xe6, layout, ax,
                tuple(excl[a] and a < ax for a in range(3)),
            )
            geom_e, bc_e = epi[ax]
            ye = self._fused(sub, bc_e, geom_e, _epi_layout(layout, ax))
            y6 = _addback_epi(y6, ye, layout, ax)
        y = _from_cview(y6, y, layout)
        # distributed tail of the overlap-add, then Dirichlet pass-through
        y = folded_reverse_scatter(y, layout)
        return y + bc * (xr - y)


# ---------------------------------------------------------------------------
# Host-side shard helpers (test/oracle transport)
# ---------------------------------------------------------------------------

def shard_folded_vectors(
    grid: np.ndarray,
    n: tuple[int, int, int],
    degree: int,
    dshape: tuple[int, int, int],
    layout: FoldedLayout,
) -> np.ndarray:
    """Global dof grid -> stacked per-shard folded vectors
    (Dx, Dy, Dz, nb, P^3, B). Each shard folds its inclusive local block
    (owned planes + the right-neighbour-owned closing plane, which lands in
    ghost slots: harmless placeholders, refreshed before use)."""
    P = degree
    ncl = shard_cells(n, dshape)
    out = np.zeros((*dshape, *layout.vec_shape), dtype=grid.dtype)
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                x0, y0, z0 = i * ncl[0] * P, j * ncl[1] * P, k * ncl[2] * P
                blk = grid[
                    x0: x0 + ncl[0] * P + 1,
                    y0: y0 + ncl[1] * P + 1,
                    z0: z0 + ncl[2] * P + 1,
                ]
                out[i, j, k] = fold_vector(blk, layout)
    return out


def unshard_folded_vectors(
    blocks: np.ndarray,
    n: tuple[int, int, int],
    degree: int,
    dshape: tuple[int, int, int],
    layout: FoldedLayout,
) -> np.ndarray:
    """Inverse of shard_folded_vectors, trusting only owned planes (interior
    shards' ghost-held closing planes are taken from the owning right
    neighbour's (c*=0, i=0) slots)."""
    P = degree
    ncl = shard_cells(n, dshape)
    N = tuple(nc * ds * P + 1 for nc, ds in zip(ncl, dshape))
    out = np.empty(N, dtype=blocks.dtype)
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                blk = unfold_vector(blocks[i, j, k], layout)
                x0, y0, z0 = i * ncl[0] * P, j * ncl[1] * P, k * ncl[2] * P
                out[
                    x0: x0 + ncl[0] * P + 1,
                    y0: y0 + ncl[1] * P + 1,
                    z0: z0 + ncl[2] * P + 1,
                ] = blk
    return out


# ---------------------------------------------------------------------------
# Per-shard closed-form setup
# ---------------------------------------------------------------------------

def _local_grid_marker(layout: FoldedLayout, shard_pos, dshape,
                       n_global) -> np.ndarray:
    """Local inclusive dof-grid bool: global Dirichlet boundary. Closed
    form from the shard position — no global array is ever built
    (C-equivalent of main.cpp:94-102 restricted to the shard)."""
    P = layout.degree
    marks = []
    for ax in range(3):
        L = layout.n[ax] * P + 1
        g0 = shard_pos[ax] * layout.n[ax] * P
        g = g0 + np.arange(L)
        marks.append((g == 0) | (g == n_global[ax] * P))
    return (marks[0][:, None, None] | marks[1][None, :, None]
            | marks[2][None, None, :])


def owned_folded_mask(layout: FoldedLayout, shard_pos, dshape) -> np.ndarray:
    """Host-side: bool mask of slots counted by this shard in global
    reductions (every dof exactly once). Structural slots and interior
    shards' ghost columns are excluded. O(local) closed form."""
    P3 = layout.degree ** 3
    marks = fold_vector(
        np.ones(tuple(c * layout.degree + 1 for c in layout.n)), layout
    ) > 0
    mflat = marks.transpose(1, 0, 2).reshape(P3, layout.lv)
    v = mflat[:, : layout.cg].reshape(
        layout.degree, layout.degree, layout.degree, *layout.np3
    ).copy()
    for ax in range(3):
        if shard_pos[ax] != dshape[ax] - 1:
            sl = [slice(None)] * 6
            sl[3 + ax] = layout.np3[ax] - 1
            v[tuple(sl)] = False
    flat = np.zeros((P3, layout.lv), dtype=bool)
    flat[:, : layout.cg] = v.reshape(P3, layout.cg)
    return np.ascontiguousarray(
        flat.reshape(P3, layout.nblocks, layout.block).transpose(1, 0, 2)
    )


def build_dist_folded(
    mesh: BoxMesh,
    dgrid,
    degree: int,
    tables: OperatorTables,
    kappa: float = 2.0,
    dtype=jnp.float32,
    nl: int | None = None,
    geom: str = "auto",
) -> DistFoldedLaplacian:
    """Build stacked per-shard folded state. All masks are O(local) closed
    form from the shard position; geometry is precomputed per shard on
    device (geom='g' — the faster apply, chosen by 'auto' (default) while
    the per-shard tensor fits HBM) or ships as per-shard corner slices
    with G computed in-kernel (geom='corner' — the capacity mode). The only O(global) host touch is slicing
    the mesh's corner array (O(ncells), same order as the reference's mesh
    build, mesh.cpp:190-218)."""
    t = tables
    dshape = dgrid.dshape
    ncl = shard_cells(mesh.n, dshape)
    itemsize = np.dtype(dtype).itemsize
    if geom not in ("auto", "corner", "g"):
        raise ValueError(f"unknown geom mode {geom!r}")
    from ..ops.folded import resolve_pallas_geom

    geom, nl = resolve_pallas_geom(degree, t.nq, itemsize, geom, nl)
    layout = make_layout(ncl, degree, t.nq, itemsize, nl=nl)
    if geom == "auto":
        # Shared policy with the single-chip builder, applied to the
        # PER-SHARD layout: G while it fits, corner mode for capacity.
        geom = auto_geom(layout, t.nq, dtype)
    check_tpu_lane_support(layout, degree, t.qmode)

    corners_all = mesh.cell_corners  # (nx, ny, nz, 2,2,2,3)

    def shard_corner_block(pos, sub_axis=None):
        """This shard's cell-corner slice; for an epilogue sub-layout,
        only the last real cell column along sub_axis (the ghost column
        gets unit-cube placeholders from ghost_corner_arrays)."""
        sl = []
        for ax in range(3):
            c0 = pos[ax] * ncl[ax]
            c1 = c0 + ncl[ax]
            if sub_axis == ax:
                c0 = c1 - 1
            sl.append(slice(c0, c1))
        return corners_all[tuple(sl)]

    shp = dshape
    # stacked per-shard state
    stack = lambda builder, shape: np.stack([  # noqa: E731
        np.stack([
            np.stack([builder((i, j, k)) for k in range(shp[2])])
            for j in range(shp[1])
        ]) for i in range(shp[0])
    ]).reshape(*shp, *shape)

    np_dt = np.float32 if dtype == jnp.float32 else np.float64
    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    sharding = NamedSharding(dgrid.mesh, Pspec(*AXIS_NAMES))

    def put(a):
        """Shard a stacked host array straight onto the device grid (never
        staged whole on one device — the stacked state is Dx*Dy*Dz times
        one chip's share)."""
        return jax.device_put(a, sharding)

    def corner_arrays(lay, corner_block):
        """Blocked corner-mode geometry operands (host numpy, O(local))."""
        ccs, mcs = ghost_corner_arrays(lay, corner_block)
        cb, mb = blocked_corners(ccs, mcs, lay)
        return cb.astype(np_dt), mb.astype(np_dt)

    def build_G_sharded(lay, sub_axis=None):
        """geom='g': per-shard G computed ON EACH SHARD'S OWN DEVICE inside
        shard_map (chunked, ops.folded.blocked_G_traced) — neither the
        host nor any single device ever holds the global G."""
        from ..ops.folded import blocked_G_traced

        ccs = np.empty((*shp, lay.lv, 2, 2, 2, 3))
        mcs = np.empty((*shp, lay.lv))
        for i in range(shp[0]):
            for j in range(shp[1]):
                for k in range(shp[2]):
                    ccs[i, j, k], mcs[i, j, k] = ghost_corner_arrays(
                        lay, shard_corner_block((i, j, k), sub_axis)
                    )
        ccs_d = put(np.asarray(ccs, np_dt))
        mcs_d = put(np.asarray(mcs, np_dt))

        @partial(jax.shard_map, mesh=dgrid.mesh,
                 in_specs=(Pspec(*AXIS_NAMES), Pspec(*AXIS_NAMES)),
                 out_specs=Pspec(*AXIS_NAMES))
        def shard_geometry(c, m):
            return blocked_G_traced(
                c[0, 0, 0], m[0, 0, 0], lay, t
            )[None, None, None]

        return shard_geometry(ccs_d, mcs_d)

    # main geometry
    if geom == "corner":
        parts = [corner_arrays(layout, shard_corner_block((i, j, k)))
                 for i in range(shp[0]) for j in range(shp[1])
                 for k in range(shp[2])]
        corners_b = put(np.stack([p[0] for p in parts]).reshape(
            *shp, *parts[0][0].shape))
        cmask_b = put(np.stack([p[1] for p in parts]).reshape(
            *shp, *parts[0][1].shape))
        G_b = None
    else:
        G_b = build_G_sharded(layout)
        corners_b = cmask_b = None

    bcf = put(stack(
        lambda pos: np.asarray(fold_vector(
            _local_grid_marker(layout, pos, dshape, mesh.n).astype(
                np.float64), layout)),
        layout.vec_shape,
    ).astype(np_dt))
    owned = put(stack(
        lambda pos: owned_folded_mask(layout, pos, dshape),
        layout.vec_shape,
    ))

    # epilogue state per sharded axis: geometry + bc for the 2-column
    # sub-layout (same for every shard along unsharded axes; stacked per
    # shard so shard_map slices it uniformly)
    epi = []
    for ax in range(3):
        if dshape[ax] == 1:
            epi.append(None)
            continue
        slay = _epi_layout(layout, ax)

        if geom == "corner":
            parts = [corner_arrays(slay, shard_corner_block((i, j, k), ax))
                     for i in range(shp[0]) for j in range(shp[1])
                     for k in range(shp[2])]
            ge = (put(np.stack([p[0] for p in parts]).reshape(
                      *shp, *parts[0][0].shape)),
                  put(np.stack([p[1] for p in parts]).reshape(
                      *shp, *parts[0][1].shape)))
        else:
            ge = build_G_sharded(slay, sub_axis=ax)

        def epi_bc(pos, ax=ax):
            m = _local_grid_marker(layout, pos, dshape, mesh.n)
            P = degree
            lo = (layout.n[ax] - 1) * P
            sl = [slice(None)] * 3
            sl[ax] = slice(lo, lo + P + 1)
            return np.asarray(fold_vector(m[tuple(sl)].astype(np.float64),
                                          slay))

        bce = put(stack(epi_bc, slay.vec_shape).astype(np_dt))
        epi.append((ge, bce))

    return DistFoldedLaplacian(
        G=G_b,
        corners=corners_b,
        cmask=cmask_b,
        bc_mask=bcf,
        owned=owned,
        epi_geom=tuple(epi),
        kappa=jnp.asarray(kappa, dtype=dtype),
        n_local=tuple(ncl),
        degree=degree,
        nl=layout.nl,
        is_identity=t.is_identity,
        dshape=tuple(dshape),
        phi0_c=freeze_table(t.phi0),
        dphi1_c=freeze_table(t.dphi1),
        pts_c=tuple(float(v) for v in t.pts1d),
        wts_c=tuple(float(v) for v in t.wts1d),
    )


def shard_corner_cs(mesh: BoxMesh, dshape, layout: FoldedLayout):
    """Stacked per-shard c-space corner/mask arrays for the device RHS:
    ((Dx,Dy,Dz, Lv, 2,2,2,3), (Dx,Dy,Dz, Lv))."""
    ncl = layout.n
    ccs = np.empty((*dshape, layout.lv, 2, 2, 2, 3))
    mcs = np.empty((*dshape, layout.lv))
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                blk = mesh.cell_corners[
                    i * ncl[0]:(i + 1) * ncl[0],
                    j * ncl[1]:(j + 1) * ncl[1],
                    k * ncl[2]:(k + 1) * ncl[2],
                ]
                ccs[i, j, k], mcs[i, j, k] = ghost_corner_arrays(layout, blk)
    return ccs, mcs


def resolve_folded_engine(op: DistFoldedLaplacian) -> bool:
    """The dist folded engine auto rule, shared by make_folded_sharded_fns
    and the dist driver's metadata/fallback logic so the recorded
    cg_engine flag can never diverge from what actually runs. No backend
    gate: like the single-chip folded engine, CPU runs take the same
    kernels through interpret mode (the folded path is pallas-only)."""
    from .folded_cg import supports_dist_folded_engine

    return supports_dist_folded_engine(op)


def resolve_folded_overlap(op: DistFoldedLaplacian) -> tuple[bool, str | None]:
    """(supported, gate_reason) for the communication-overlapped folded
    engine form — shared with the driver so the recorded form cannot
    diverge from the routing."""
    from .folded_cg import supports_dist_folded_overlap

    from ..engines.registry import GATE_REASONS

    if not resolve_folded_engine(op):
        return False, GATE_REASONS["overlap-engine-folded"]
    if not supports_dist_folded_overlap(op):
        return False, GATE_REASONS["overlap-plan-folded"]
    return True, None


def make_folded_sharded_fns(op: DistFoldedLaplacian, dgrid, nreps: int,
                            engine: bool | None = None,
                            overlap: bool = False):
    """Jittable sharded callables (apply, CG, norm) over folded shards —
    mirrors dist.driver.make_sharded_fns. The sharded per-shard arrays ride
    as one pytree argument; the operator's replicated metadata rides via
    closure.

    `engine=None` (auto) routes CG and the apply through the distributed
    fused delay-ring engine (dist.folded_cg) when the per-shard input
    ring fits VMEM — one kernel pass per iteration instead of main
    kernel + epilogues + unfused CG glue. The unfused composition (with
    its collective-independent main kernel) serves everything else and
    remains the driver's recorded compile-failure fallback. Both paths
    consume the same `sharded_state` tuple; per-iteration-invariant state
    (the geometry tuple, the owned-dof dot weight) is hoisted out of the
    CG loop in both.

    `overlap=True` routes CG through the communication-overlapped engine
    form (dist.folded_cg.dist_folded_cg_solve_local_overlap: carried
    refreshed state, the forward refresh moved onto y off the next
    kernel's critical path, ONE stacked psum per iteration) — requires
    the engine; callers gate via resolve_folded_overlap and record the
    form as `halo_overlap`."""
    from jax.sharding import PartitionSpec as P

    from ..la.cg import cg_solve
    from .folded_cg import (
        dist_folded_apply_ring_local,
        dist_folded_cg_solve_local,
        dist_folded_cg_solve_local_overlap,
    )
    from .halo import owned_dot

    spec = P(*AXIS_NAMES)
    rep = P()
    if engine is None:
        engine = resolve_folded_engine(op)
    if overlap and not engine:
        raise ValueError("the overlapped folded CG form rides the fused "
                         "engine; pass engine=True (or let it resolve)")

    def _local(a):
        return jax.tree_util.tree_map(lambda x: x[0, 0, 0], a)

    def _dot(mask):
        # hoisted: cast once, not per dot
        return owned_dot(mask.astype(op.bc_mask.dtype))

    def sharded_state(A):
        geom = A.G if A.G is not None else (A.corners, A.cmask)
        # "not a true ghost" == owned under this ownership partition (pad
        # slots are zero in every vector, so their mask value is moot);
        # the engine path reuses the same array as its dot-ownership
        # weight and streamed kernel mask.
        nghost = A.owned.astype(A.bc_mask.dtype)
        return (geom, A.bc_mask, nghost, A.epi_geom)

    # check_vma=False is *required* on these two shard_maps, not a blanket
    # waiver, and for a pallas-only reason: every folded sharded
    # computation (unfused AND engine form) runs a Pallas kernel
    # (folded_cell_apply_fused / the halo-form delay ring), whose
    # pallas_call outputs carry no varying-mesh-axes annotation, and the
    # default shard_map VMA check rejects exactly that. This mirrors
    # dist/kron.py's scoped `check_vma = impl != "pallas"` — the folded
    # path simply has no non-pallas impl to scope back to.
    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec, spec), out_specs=spec, check_vma=False)
    def apply_fn(x, state):
        if engine:
            y = dist_folded_apply_ring_local(op, _local(x), _local(state))
            return y[None, None, None]
        return op.apply_local(_local(x), _local(state))[None, None, None]

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    def cg_fn(b, state, owned):
        bl = _local(b)
        sl = _local(state)  # hoisted: sliced once, reused every iteration
        if engine:
            solve = (dist_folded_cg_solve_local_overlap if overlap
                     else dist_folded_cg_solve_local)
            x = solve(op, bl, sl, nreps)
            return x[None, None, None]
        x = cg_solve(
            lambda v: op.apply_local(v, sl),
            bl,
            jnp.zeros_like(bl),
            nreps,
            dot=_dot(_local(owned)),
        )
        return x[None, None, None]

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, spec),
             out_specs=rep)
    def norm_fn(x, owned):
        """Global (L2, Linf) over owned dofs (psum / pmax)."""
        xl, ol = _local(x), _local(owned)
        return jnp.stack(
            [jnp.sqrt(_dot(ol)(xl, xl)), masked_linf(xl, ol)]
        )

    return apply_fn, cg_fn, norm_fn, sharded_state


# ---------------------------------------------------------------------------
# Double-float (df64) sharded variant: f64-class CG on perturbed sharded
# meshes (the distributed tail of ops.folded_df). Deliberately UNFUSED and
# halo-first: the df pass refreshes ghosts, runs ONE full-volume unfused df
# kernel pass on the refreshed vector (by linearity identical to the f32
# path's interior + ghost-epilogue split, without df epilogue state), then
# reverse-scatters seam partials with compensated adds. The f32 path's
# comm/compute overlap decomposition is traded away: this is the
# accuracy/capacity path, and the halo is O(surface) against an
# arithmetic-heavy O(volume) df apply. Channels ride the halo as ONE
# stacked ppermute payload per axis (the dist.kron_cg_df 4-channel
# pattern, here 2 channels per vector), which also makes ghost copies
# owner-consistent by construction — the df-specific requirement
# dist.kron_df derived (compiled df chains round lo bits
# position-dependently, so df seams cannot rely on bitwise replay).
# ---------------------------------------------------------------------------


def folded_halo_refresh_df(x, layout: FoldedLayout):
    """df halo refresh: both channels in one stacked ppermute payload per
    sharded axis; ghost slots become owner copies by construction."""
    from ..la.df64 import DF

    vs = jnp.stack([_cview(x.hi, layout), _cview(x.lo, layout)])
    vs = _halo_refresh_view(vs, 1)
    return DF(_from_cview(vs[0], x.hi, layout),
              _from_cview(vs[1], x.lo, layout))


def folded_reverse_scatter_df(y, layout: FoldedLayout):
    """df seam reverse scatter: ghost partials accumulate into the owner
    with a df_add (channel-wise adds would drop the two_sum carries)."""
    from ..la.df64 import DF, df_add

    def dfadd(a, b):
        s = df_add(DF(a[0], a[1]), DF(b[0], b[1]))
        return jnp.stack([s.hi, s.lo])

    vs = jnp.stack([_cview(y.hi, layout), _cview(y.lo, layout)])
    vs = _reverse_scatter_view(vs, 1, dfadd)
    return DF(_from_cview(vs[0], y.hi, layout),
              _from_cview(vs[1], y.lo, layout))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["Gh", "Gl", "ch", "cl", "cmask", "bc_mask", "owned"],
    meta_fields=["n_local", "degree", "nl", "is_identity", "kappa",
                 "dshape", "phi0_c", "dphi1_c", "pts_c", "wts_c"],
)
@dataclass(frozen=True)
class DistFoldedLaplacianDF:
    """Stacked per-shard folded df operator state (leading (Dx, Dy, Dz)
    axes sharded). Geometry is a per-shard blocked df G pair or df corner
    pairs + mask, as in ops.folded_df; masks are the f32 builder's
    closed-form per-shard arrays."""

    Gh: jnp.ndarray | None
    Gl: jnp.ndarray | None
    ch: jnp.ndarray | None
    cl: jnp.ndarray | None
    cmask: jnp.ndarray | None
    bc_mask: jnp.ndarray  # (Dx,Dy,Dz, nb, P^3, B) 0/1, f32
    owned: jnp.ndarray  # (Dx,Dy,Dz, nb, P^3, B) bool
    n_local: tuple[int, int, int]
    degree: int
    nl: int
    is_identity: bool
    kappa: float
    dshape: tuple[int, int, int] = (1, 1, 1)
    phi0_c: tuple = ()
    dphi1_c: tuple = ()
    pts_c: tuple = ()
    wts_c: tuple = ()

    @property
    def layout(self) -> FoldedLayout:
        return FoldedLayout(n=self.n_local, degree=self.degree, nl=self.nl)

    @property
    def geom_tables(self):
        if self.Gh is not None:
            return None
        return (np.asarray(self.pts_c), np.asarray(self.wts_c))

    def apply_local(self, x, state):
        """y = A x for one shard's df pair (inside shard_map): halo-first
        (see section comment), one full-volume unfused df pass, seam
        reverse scatter, Dirichlet blend via exact 0/1-mask selects."""
        from ..la.df64 import DF
        from ..ops.folded_df import folded_cell_apply_df

        geom, bc = state
        layout = self.layout
        xr = folded_halo_refresh_df(x, layout)
        nbm = 1.0 - bc
        xm = DF(xr.hi * nbm, xr.lo * nbm)
        y = folded_cell_apply_df(
            xm, geom, layout,
            np.asarray(self.phi0_c, np.float64),
            np.asarray(self.dphi1_c, np.float64),
            self.is_identity, self.kappa,
            geom_tables=self.geom_tables,
        )
        y = folded_reverse_scatter_df(y, layout)
        return DF(y.hi * nbm + bc * xr.hi, y.lo * nbm + bc * xr.lo)


def build_dist_folded_df(
    mesh: BoxMesh,
    dgrid,
    degree: int,
    tables: OperatorTables,
    kappa: float = 2.0,
    nl: int | None = None,
    geom: str = "auto",
) -> DistFoldedLaplacianDF:
    """Build stacked per-shard folded df state: per-shard f64 host
    geometry split into (hi, lo) channels (ops.folded_df helpers), the
    f32 builder's closed-form per-shard bc/owned masks. O(local) host
    work per shard plus the corner-array slices."""
    from ..ops.folded_df import (
        auto_geom_df,
        folded_df_plan,
        host_blocked_G_df,
        split_corner_arrays_df,
    )

    t = tables
    dshape = dgrid.dshape
    ncl = shard_cells(mesh.n, dshape)
    if geom not in ("auto", "corner", "g"):
        raise ValueError(f"unknown geom mode {geom!r}")
    if nl is None and geom != "g":
        forced = folded_df_plan(degree, t.nq)[1]
        if forced is not None:
            geom = forced
    layout = make_layout(ncl, degree, t.nq, 4, nl=nl)
    check_tpu_lane_support(layout, degree, t.qmode)
    if geom == "auto":
        geom = auto_geom_df(layout, t.nq)

    corners_all = mesh.cell_corners
    shp = dshape

    def shard_corner_block(pos):
        return corners_all[tuple(
            slice(pos[ax] * ncl[ax], (pos[ax] + 1) * ncl[ax])
            for ax in range(3)
        )]

    stack = lambda builder, shape: np.stack([  # noqa: E731
        np.stack([
            np.stack([builder((i, j, k)) for k in range(shp[2])])
            for j in range(shp[1])
        ]) for i in range(shp[0])
    ]).reshape(*shp, *shape)

    from jax.sharding import NamedSharding, PartitionSpec as Pspec

    sharding = NamedSharding(dgrid.mesh, Pspec(*AXIS_NAMES))

    def put(a):
        return jax.device_put(a, sharding)

    Gh = Gl = ch = cl = cm = None
    parts = []
    for i in range(shp[0]):
        for j in range(shp[1]):
            for k in range(shp[2]):
                ccs, mcs = ghost_corner_arrays(
                    layout, shard_corner_block((i, j, k))
                )
                if geom == "corner":
                    parts.append(split_corner_arrays_df(ccs, mcs, layout))
                else:
                    parts.append(host_blocked_G_df(ccs, mcs, layout, t,
                                                   kappa))
    if geom == "corner":
        ch = put(np.stack([p[0] for p in parts]).reshape(
            *shp, *parts[0][0].shape))
        cl = put(np.stack([p[1] for p in parts]).reshape(
            *shp, *parts[0][1].shape))
        cm = put(np.stack([p[2] for p in parts]).reshape(
            *shp, *parts[0][2].shape))
    else:
        Gh = put(np.stack([p[0] for p in parts]).reshape(
            *shp, *parts[0][0].shape))
        Gl = put(np.stack([p[1] for p in parts]).reshape(
            *shp, *parts[0][1].shape))

    bcf = put(stack(
        lambda pos: np.asarray(fold_vector(
            _local_grid_marker(layout, pos, dshape, mesh.n).astype(
                np.float64), layout)),
        layout.vec_shape,
    ).astype(np.float32))
    owned = put(stack(
        lambda pos: owned_folded_mask(layout, pos, dshape),
        layout.vec_shape,
    ))
    return DistFoldedLaplacianDF(
        Gh=Gh, Gl=Gl, ch=ch, cl=cl, cmask=cm,
        bc_mask=bcf,
        owned=owned,
        n_local=tuple(ncl),
        degree=degree,
        nl=layout.nl,
        is_identity=t.is_identity,
        kappa=float(kappa),
        dshape=tuple(dshape),
        phi0_c=freeze_table(t.phi0),
        dphi1_c=freeze_table(t.dphi1),
        pts_c=tuple(float(v) for v in t.pts1d),
        wts_c=tuple(float(v) for v in t.wts1d),
    )


def shard_folded_vectors_df(grid: np.ndarray, n, degree: int, dshape,
                            layout: FoldedLayout):
    """f64 global dof grid -> stacked per-shard folded DF pairs (host
    split, then the f32 shard transport per channel)."""
    from ..la.df64 import DF

    hi = np.asarray(grid, np.float32)
    lo = np.asarray(grid - np.asarray(hi, np.float64), np.float32)
    return DF(
        jnp.asarray(shard_folded_vectors(hi, n, degree, dshape, layout)),
        jnp.asarray(shard_folded_vectors(lo, n, degree, dshape, layout)),
    )


def make_folded_df_sharded_fns(op: DistFoldedLaplacianDF, dgrid,
                               nreps: int):
    """Jittable sharded df callables (apply, CG, norm, norms_from,
    sharded_state) over folded df shards — the df twin of
    make_folded_sharded_fns, with owned-masked compensated dots folded
    cross-shard via dist.kron_df.df_psum_all."""
    from jax.sharding import PartitionSpec as P

    from ..la.df64 import (
        DF,
        _prod_terms,
        df_add,
        df_axpy,
        df_div,
        df_scale,
        df_sub,
        df_sum,
        df_zeros_like,
    )
    from .kron_df import df_psum_all

    spec = P(*AXIS_NAMES)
    rep = P()

    def _local(a):
        return jax.tree_util.tree_map(lambda x: x[0, 0, 0], a)

    def sharded_state(A):
        geom = ((A.Gh, A.Gl) if A.Gh is not None
                else (A.ch, A.cl, A.cmask))
        return (geom, A.bc_mask)

    def _dot(owned):
        m = owned.astype(jnp.float32)

        def dot(u, v):
            uw = DF(u.hi * m, u.lo * m)
            return df_psum_all(df_sum(DF(*_prod_terms(uw, v))), op.dshape)

        return dot

    # check_vma=False for the same reason as the f32 folded fns: every
    # computation runs the Pallas kernel, whose outputs carry no
    # varying-mesh-axes annotation.
    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec, spec), out_specs=spec, check_vma=False)
    def apply_fn(x, state):
        y = op.apply_local(_local(x), _local(state))
        return DF(y.hi[None, None, None], y.lo[None, None, None])

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
    def cg_fn(b, state, owned):
        bl = _local(b)
        sl = _local(state)
        dot = _dot(_local(owned))
        floor = jnp.float32(1e-24)
        rnorm0 = dot(bl, bl)
        rnorm0_hi = rnorm0.hi

        def body(_, st):
            x, r, p, rnorm, done = st
            y = op.apply_local(p, sl)
            alpha = df_div(rnorm, dot(p, y))
            x1 = df_axpy(x, alpha, p)
            r1 = df_sub(r, df_scale(y, alpha))
            rnorm1 = dot(r1, r1)
            beta = df_div(rnorm1, rnorm)
            p1 = df_add(df_scale(p, beta), r1)
            done1 = jnp.logical_or(done, rnorm1.hi <= floor * rnorm0_hi)

            def keep(new, old):
                return jax.tree_util.tree_map(
                    lambda nw, o: jnp.where(done, o, nw), new, old
                )

            return (keep(x1, x), keep(r1, r), keep(p1, p),
                    keep(rnorm1, rnorm), done1)

        # `done` derives from the gathered dots (device-varying under the
        # VMA system); the initial carry must match — the dist.kron_df
        # pcast idiom.
        done0 = lax.pcast(jnp.asarray(False), AXIS_NAMES, to="varying")
        st = (df_zeros_like(bl), bl, bl, rnorm0, done0)
        x, *_ = jax.lax.fori_loop(0, nreps, body, st)
        return DF(x.hi[None, None, None], x.lo[None, None, None])

    @partial(jax.shard_map, mesh=dgrid.mesh, in_specs=(spec, spec),
             out_specs=rep, check_vma=False)
    def norm_fn(x, owned):
        """[<x,x>.hi, <x,x>.lo, Linf] over owned dofs; the hi+lo
        recombination and sqrt happen in the caller's Python f64
        (norms_from) because df mode keeps x64 off."""
        xl, ol = _local(x), _local(owned)
        d = _dot(ol)(xl, xl)
        linf = lax.pmax(
            jnp.max(jnp.abs(xl.hi + xl.lo) * ol.astype(jnp.float32)),
            AXIS_NAMES,
        )
        return jnp.stack([d.hi, d.lo, linf])

    def norms_from(triple):
        hi, lo, linf = (float(v) for v in np.asarray(triple))
        return float(np.sqrt(max(hi + lo, 0.0))), linf

    return apply_fn, cg_fn, norm_fn, norms_from, sharded_state


def make_folded_rhs_fn(op: DistFoldedLaplacian, dgrid,
                       t: OperatorTables, dtype):
    """Jittable sharded RHS: per-shard device assembly (ops.folded_rhs)
    from the shard's own corners, then the seam reverse-scatter so shared
    planes hold the full sum and non-owned ghost slots are zero (the
    distributed analogue of assemble + scatter_rev + bc.set,
    laplacian_solver.cpp:100-105)."""
    from jax.sharding import PartitionSpec as P

    from ..ops.folded_rhs import device_rhs_folded

    spec = P(*AXIS_NAMES)

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(spec, spec, spec), out_specs=spec)
    def rhs_fn(ccs, mcs, bcf):
        b = device_rhs_folded(
            ccs[0, 0, 0], mcs[0, 0, 0], bcf[0, 0, 0], op.layout, t,
            dtype=dtype,
        )
        b = folded_reverse_scatter(b, op.layout)
        # bc rows again (seam sums may have re-populated shared bc rows)
        return (b * (1 - bcf[0, 0, 0]))[None, None, None]

    return rhs_fn
