"""Distributed fused df32 CG engine: the f64-class delay-ring iteration
on x-sharded device meshes.

Composes the two existing designs without changing either:

- the distributed halo protocol of dist.kron_cg — halo-extended input
  slabs, one stacked ppermute pair per iteration, the SAME kernel in its
  halo form emitting exactly the local planes, in-kernel dot ownership;
- the df arithmetic of ops.kron_cg_df — (hi, lo) plane pairs with
  error-free products and compensated accumulation.

The DF halo payload stacks all four channels (r.hi, r.lo, p.hi, p.lo)
into ONE ppermute pair per side, and the left-neighbour payload carries
ONE EXTRA plane — the owner's copy of the shared seam plane — which
overwrites this shard's ghost plane 0 before the kernel. That folded
seam refresh is the df-specific requirement dist.kron_df derived: f32
seams stay bit-identical by replay of identical instruction sequences,
but compiled df chains may round the lo channel position-dependently
(XLA fusion; interpret mode runs the kernel through XLA too), so df
ghost copies are made consistent by construction — owner wins — at zero
extra collectives (the refresh plane rides the halo exchange).

Cross-shard reductions reuse dist.kron_df's compensated fold
(df_psum_all: all-gather the per-shard DF partials, fixed-order df_add
— a raw psum would re-round away the compensation); the kernel's
<p, A p> partial already excludes duplicated seam planes via the aux
dot weights. x/r updates + <r, r> run through the chunked pallas df
pass (ops.kron_cg_df.cg_update_df_pallas) above the same size policy as
f32, with the duplicated seam plane's <r1, r1> contribution subtracted
before the fold.

Device-mesh coverage: x-only meshes (dshape = (D, 1, 1)) use the plane-
halo kernel form above; any other dshape uses the `ext2d` kernel form
(ops.kron_cg_df, the df twin of the f32 ext2d form in ops.kron_cg) —
cross-sections halo-extended by P per sharded side with the owner's seam
plane folded into every left payload (the same owner-wins refresh as the
x exchange, per axis), per-shard global-indexed 4-channel coefficient
slices, and streamed (NY, NZ) interior/dot-ownership mask planes. The
unfused dist df path (dist.kron_df) serves rings past the VMEM tiers and
remains the compile-failure fallback. Reference parity: ghost scatter
vector.hpp:31-149, CG recurrence cg.hpp:89-169, f64 dispatch
main.cpp:277-288.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..la.cg import onered_scalars_df
from ..la.df64 import (
    DF,
    _prod_terms,
    df_add,
    df_axpy,
    df_scale,
    df_sub,
    df_sum,
    df_zeros_like,
)
from ..ops.kron_cg import PALLAS_UPDATE_MIN_DOFS
from ..ops.kron_cg_df import (
    _coeff_stack4,
    _cx_rows_df,
    _kron_cg_df_call,
    cg_update_df_pallas,
    engine_plan_df,
    fused_cg_solve_df,
)
from .kron_df import DistKronLaplacianDF, df_psum_all, df_psum_all_stacked
from .mesh import AXIS_NAMES


def _is_x_only(op: DistKronLaplacianDF) -> bool:
    return op.dshape[1] == 1 and op.dshape[2] == 1


def dist_df_engine_plan(op: DistKronLaplacianDF) -> tuple[bool, int | None]:
    """(supported, scoped_vmem_kib): any device mesh with the df
    one-kernel ring inside a scoped-VMEM tier — x-only meshes by the
    global cross-section, 3D meshes by the halo-extended LOCAL
    cross-section consumed by the ext2d form's ephemeral contraction
    operands (the same accounting as the f32 dist_kron_engine_plan).
    The chunked df form has no halo variant — past the tiers the
    unfused dist df path serves."""
    P = op.degree
    Lx = op.L[0]
    if _is_x_only(op):
        cross = (op.notbc1d[1].shape[0], op.notbc1d[2].shape[0])
    else:
        cross = (op.L[1] + 2 * P, op.L[2] + 2 * P)
    form, kib = engine_plan_df((Lx, *cross), P)
    return form == "one", kib


def supports_dist_df_engine(op: DistKronLaplacianDF) -> bool:
    return dist_df_engine_plan(op)[0]


def _shard_tables_df(op: DistKronLaplacianDF, dtype=jnp.float32):
    """Per-shard tables (inside shard_map, hoisted out of the CG loop):
    the local 8nb-channel x-coefficient rows, the [interior-in-x,
    dot-weight] aux rows, and the (global, replicated) z/y coefficient
    stacks."""
    P = op.degree
    Lx = op.L[0]
    NXg = op.notbc1d[0].shape[0]
    x0 = lax.axis_index(AXIS_NAMES[0]) * (Lx - 1)
    cx_global = _cx_rows_df(op, NXg)  # (NXg, 1, 8nb)
    z0 = jnp.zeros((), dtype=x0.dtype)
    cx_local = lax.dynamic_slice(
        cx_global, (x0, z0, z0), (Lx, 1, 8 * (2 * P + 1))
    )
    gx = x0 + jnp.arange(Lx)
    mi = jnp.logical_and(gx > 0, gx < NXg - 1).astype(dtype)
    w = jnp.where(jnp.logical_and(jnp.arange(Lx) == 0, x0 > 0),
                  jnp.zeros((), dtype), jnp.ones((), dtype))
    aux_local = jnp.stack([mi, w], axis=-1)[:, None, :]  # (Lx, 1, 2)
    coeffs = (
        _coeff_stack4(op.Kd[2]),
        _coeff_stack4(op.Md[2]),
        _coeff_stack4(op.Kd[1]),
        _coeff_stack4(op.Md[1]),
        cx_global,  # placeholder slot; the call takes cx=cx_local
    )
    return cx_local, aux_local, coeffs


def _ext_coeff4(df_banded, axis_i: int, L: int, P: int, nb: int):
    """Halo-extended per-shard 4-channel coefficient slice: the global
    (4, nb, N_a) stack zero-padded by P columns each side (all four
    channels — splits of 0 are 0, so the banded zero-boundary behaviour
    is preserved exactly), then dynamic-sliced to (4, nb, L + 2P) at the
    shard position. Padded index a0 == global index a0 - P: the extended
    slice starts P rows/cols before the local block (the f32 ext_coeff
    of dist.kron_cg, per channel)."""
    stack = _coeff_stack4(df_banded)
    a0 = lax.axis_index(AXIS_NAMES[axis_i]) * (L - 1)
    padded = jnp.pad(stack, ((0, 0), (0, 0), (P, P)))
    z0 = jnp.zeros((), dtype=a0.dtype)
    return lax.dynamic_slice(padded, (z0, z0, a0), (4, nb, L + 2 * P))


def _shard_tables_df_3d(op: DistKronLaplacianDF, dtype=jnp.float32):
    """Per-shard tables for the ext2d df kernel form (3D-sharded
    meshes): the x-coefficient/aux rows of _shard_tables_df, plus the
    halo-extended y/z 4-channel banded slices (global-indexed, zero
    outside the domain), the cross-section Dirichlet-interior mask, and
    the cross-section dot-ownership weights (0 on duplicated seam
    rows/cols so reductions count every dof once globally) — the df
    twin of dist.kron_cg._shard_tables_3d."""
    P = op.degree
    nb = 2 * P + 1
    cx_local, aux_local, _ = _shard_tables_df(op, dtype)
    coeffs = (
        _ext_coeff4(op.Kd[2], 2, op.L[2], P, nb),
        _ext_coeff4(op.Md[2], 2, op.L[2], P, nb),
        _ext_coeff4(op.Kd[1], 1, op.L[1], P, nb),
        _ext_coeff4(op.Md[1], 1, op.L[1], P, nb),
        cx_local,  # placeholder slot; the call takes cx=cx_local
    )

    def local_1d(vec, axis_i):
        La = op.L[axis_i]
        a0 = lax.axis_index(AXIS_NAMES[axis_i]) * (La - 1)
        return lax.dynamic_slice(vec.astype(dtype), (a0,), (La,)), a0

    nby, y0 = local_1d(op.notbc1d[1], 1)
    nbz, z0 = local_1d(op.notbc1d[2], 2)
    mask2d = nby[:, None] * nbz[None, :]
    wy = jnp.where(jnp.logical_and(jnp.arange(op.L[1]) == 0, y0 > 0),
                   jnp.zeros((), dtype), jnp.ones((), dtype))
    wz = jnp.where(jnp.logical_and(jnp.arange(op.L[2]) == 0, z0 > 0),
                   jnp.zeros((), dtype), jnp.ones((), dtype))
    w2d = wy[:, None] * wz[None, :]
    return cx_local, aux_local, coeffs, mask2d, w2d


def _extend_all_axes_df(dfs, P: int, dshape):
    """Halo-extend every channel of the DF operands by P planes per side
    along each sharded axis, sequentially z -> y -> x so later exchanges
    carry the earlier extensions (corner/edge halo data arrives already
    extended — the f32 _extend_all_axes construction), with the
    owner's seam plane folded into each left payload overwriting ghost
    plane 0 (the per-axis generalisation of _extend_df's df
    owner-consistency refresh — compiled df chains can round lo bits
    position-dependently, so every duplicated seam plane is made
    owner-consistent by construction). Unsharded axes zero-extend
    locally: the zero fringe meets the zero-padded coefficient slices
    exactly like a global domain edge."""
    from .halo import _shift_from_left, _shift_from_right

    chans = []
    for d in dfs:
        chans += [d.hi, d.lo]
    s = jnp.stack(chans)  # grid axes shift by 1 in the stacked view
    for ax in (2, 1, 0):
        sax = ax + 1
        L = s.shape[sax]
        if dshape[ax] > 1:
            name = AXIS_NAMES[ax]
            to_left = lax.slice_in_dim(s, 1, P + 1, axis=sax)
            halo_r = _shift_from_right(to_left, name)
            # P halo planes + the seam owner's plane L-1 (= this shard's
            # ghost plane 0) in one payload
            to_right = lax.slice_in_dim(s, L - 1 - P, L, axis=sax)
            recv_l = _shift_from_left(to_right, name)
            halo_l = lax.slice_in_dim(recv_l, 0, P, axis=sax)
            seam = lax.slice_in_dim(recv_l, P, P + 1, axis=sax)
            idx = lax.axis_index(name)
            first = lax.slice_in_dim(s, 0, 1, axis=sax)
            new_first = jnp.where(idx == 0, first, seam)
            s = jnp.concatenate(
                [halo_l, new_first, lax.slice_in_dim(s, 1, L, axis=sax),
                 halo_r], axis=sax,
            )
        else:
            shp = list(s.shape)
            shp[sax] = P
            zero = jnp.zeros(shp, s.dtype)
            s = jnp.concatenate([zero, s, zero], axis=sax)
    return tuple(DF(s[2 * i], s[2 * i + 1]) for i in range(len(dfs)))


def _extend_df(dfs, P: int):
    """One stacked ppermute pair exchanges the P halo planes of every
    channel of the given DF operands, with the seam-refresh plane folded
    into the left-neighbour payload: planes [L-1-P, L) (P halos + the
    owner's seam copy). Returns the halo-extended slabs with ghost plane
    0 overwritten by the owner's value (except on shard 0, which owns
    it)."""
    from .halo import _shift_from_left, _shift_from_right

    name = AXIS_NAMES[0]
    chans = []
    for d in dfs:
        chans += [d.hi, d.lo]
    s = jnp.stack(chans)  # x axis -> 1
    L = s.shape[1]
    to_left = lax.slice_in_dim(s, 1, P + 1, axis=1)
    halo_r = _shift_from_right(to_left, name)
    # P halo planes + the seam owner's plane L-1 (= this shard's ghost
    # plane 0) in one payload
    to_right = lax.slice_in_dim(s, L - 1 - P, L, axis=1)
    recv_l = _shift_from_left(to_right, name)
    halo_l = lax.slice_in_dim(recv_l, 0, P, axis=1)
    seam = lax.slice_in_dim(recv_l, P, P + 1, axis=1)
    idx = lax.axis_index(name)
    first = lax.slice_in_dim(s, 0, 1, axis=1)
    new_first = jnp.where(idx == 0, first, seam)
    body = jnp.concatenate(
        [new_first, lax.slice_in_dim(s, 1, L, axis=1)], axis=1
    )
    ext = jnp.concatenate([halo_l, body, halo_r], axis=1)
    return tuple(DF(ext[2 * i], ext[2 * i + 1])
                 for i in range(len(dfs)))


def dist_kron_df_cg_solve_local(op: DistKronLaplacianDF, b: DF,
                                nreps: int,
                                interpret: bool | None = None) -> DF:
    """Per-shard fused-engine df CG (inside shard_map): returns the
    local DF solution block. Matches the unfused dist df path
    (dist.kron_df.dist_cg_solve_df_local) to df reassociation accuracy.
    x-only device meshes use the plane-halo kernel form; any other
    dshape the ext2d form (cross-sections halo-extended too, seam dedup
    via the streamed w2d weight plane)."""
    P = op.degree
    x_only = _is_x_only(op)
    if x_only:
        cx_local, aux_local, coeffs = _shard_tables_df(op)
        wplane = aux_local[:, 0, 1][:, None, None]

        def engine(r, p_prev, beta4):
            r_ext, p_ext = _extend_df((r, p_prev), P)
            p, y, pdot = _kron_cg_df_call(
                op, coeffs, True, interpret, r_ext, p_ext, beta4,
                cx=cx_local, aux=aux_local,
            )
            return p, y, df_psum_all(pdot, op.dshape)
    else:
        cx_local, aux_local, coeffs, mask2d, w2d = _shard_tables_df_3d(op)
        wplane = aux_local[:, 0, 1][:, None, None] * w2d[None]

        def engine(r, p_prev, beta4):
            r_ext, p_ext = _extend_all_axes_df((r, p_prev), P, op.dshape)
            p, y, pdot = _kron_cg_df_call(
                op, coeffs, True, interpret, r_ext, p_ext, beta4,
                cx=cx_local, aux=aux_local, mask2d=mask2d, w2d=w2d,
            )
            return p, y, df_psum_all(pdot, op.dshape)

    def inner(u: DF, v: DF) -> DF:
        uw = DF(u.hi * wplane, u.lo * wplane)
        local = df_sum(DF(*_prod_terms(uw, v)))
        return df_psum_all(local, op.dshape)

    update = None
    if b.hi.size >= PALLAS_UPDATE_MIN_DOFS:
        # chunked pallas df update (the XLA whole-vector df fusion
        # compile wall, ops.kron_cg_df); the duplicated seam planes'
        # <r1, r1> is subtracted before the compensated fold — one
        # O(cross-section) plane on x-only meshes, the full weighted
        # correction on ext2d meshes (seam rows/cols along every axis)
        def update(x, pv, r, y, alpha):
            x1, r1, rr = cg_update_df_pallas(x, pv, r, y, alpha,
                                             interpret)
            if x_only:
                w0 = 1.0 - wplane[0, 0, 0]
                r1s = DF(r1.hi[0], r1.lo[0])
                seam = df_sum(DF(*_prod_terms(
                    DF(r1s.hi * w0, r1s.lo * w0), r1s
                )))
            else:
                w0 = 1.0 - wplane
                seam = df_sum(DF(*_prod_terms(
                    DF(r1.hi * w0, r1.lo * w0), r1
                )))
            rr_own = df_sub(rr, seam)
            return x1, r1, df_psum_all(rr_own, op.dshape)

    # `done` derives from the gathered dots, which shard_map's VMA
    # system marks device-varying (the fold is deterministic and
    # identical on all shards); the initial carry must match — the same
    # pcast the unfused dist df loop uses (dist.kron_df).
    import jax

    done0 = jax.lax.pcast(jnp.asarray(False), AXIS_NAMES, to="varying")
    return fused_cg_solve_df(engine, b, nreps, update=update,
                             inner=inner, done0=done0)


# ---------------------------------------------------------------------------
# Communication-overlapped df engine form: the dist.kron_cg overlap
# design (carried halo-extended state, one y-boundary exchange off the
# critical path, ONE fused cross-shard reduction per iteration) in df
# arithmetic. The fused reduction is a single stacked compensated fold
# (df_psum_all_stacked) instead of one gather chain per dot; the
# p-update moves outside the kernel as a df elementwise pass.
#
# One DELIBERATE relaxation vs the synchronous df engine: the carried
# slab's duplicated seam/fringe values are maintained by local
# elementwise df replay, not by the owner-wins structural refresh (the
# y exchange still folds the owner's seam plane into each payload, the
# _extend_df convention). Compiled df chains can round lo bits
# position-dependently, so replayed copies may drift at the lo level
# (~1e-16 rel) instead of staying structurally identical — bounded by
# the overlap form's tested parity envelope (<= 1e-13 df-class vs the
# synchronous oracle over benchmark budgets), and gated as its own
# engine form (`halo_overlap` / `ext2d_overlap`) so the strict form
# remains the default oracle.
# ---------------------------------------------------------------------------


def supports_dist_df_overlap(op: DistKronLaplacianDF) -> bool:
    """Overlap rides the df engine plan and keeps its whole-slab df r
    update as one XLA elementwise pass (no chunked-update route on the
    carried slab) — past the whole-vector fusion wall the synchronous
    engine serves with the reason recorded by the driver."""
    return (supports_dist_df_engine(op)
            and int(np.prod(op.L)) < PALLAS_UPDATE_MIN_DOFS)


def dist_kron_df_cg_solve_local_overlap(op: DistKronLaplacianDF, b: DF,
                                        nreps: int,
                                        interpret: bool | None = None
                                        ) -> DF:
    """Per-shard communication-overlapped fused df CG (inside
    shard_map): matches the synchronous df engine
    (dist_kron_df_cg_solve_local) to the df single-reduction envelope
    (<= 1e-13 rel). x-only meshes use the plane-halo kernel form; any
    other dshape the ext2d form."""
    P = op.degree
    x_only = _is_x_only(op)
    if x_only:
        cx_local, aux_local, coeffs = _shard_tables_df(op)
        wplane = aux_local[:, 0, 1][:, None, None]
        kw = dict(cx=cx_local, aux=aux_local)

        def extend(dfs):
            return _extend_df(dfs, P)
    else:
        cx_local, aux_local, coeffs, mask2d, w2d = _shard_tables_df_3d(op)
        wplane = aux_local[:, 0, 1][:, None, None] * w2d[None]
        kw = dict(cx=cx_local, aux=aux_local, mask2d=mask2d, w2d=w2d)

        def extend(dfs):
            return _extend_all_axes_df(dfs, P, op.dshape)

    def interior(v: DF) -> DF:
        def cut(a):
            if x_only:
                return lax.slice_in_dim(a, P, P + op.L[0], axis=0)
            for ax in range(3):
                a = lax.slice_in_dim(a, P, P + op.L[ax], axis=ax)
            return a

        return DF(cut(v.hi), cut(v.lo))

    def wdot_local(u: DF, v: DF) -> DF:
        uw = DF(u.hi * wplane, u.lo * wplane)
        return df_sum(DF(*_prod_terms(uw, v)))

    rnorm0 = df_psum_all(wdot_local(b, b), op.dshape)  # outside the loop
    rnorm0_hi = rnorm0.hi
    (r_ext0,) = extend((b,))
    floor = jnp.float32(1e-24)
    import jax

    # `done` derives from the gathered dots (device-varying under the
    # VMA system); the initial carry must match — the dist.kron_df
    # pcast idiom.
    done0 = jax.lax.pcast(jnp.asarray(False), AXIS_NAMES, to="varying")

    def body(_, state):
        x, r_ext, p_prev_ext, beta, rnorm, done = state
        # externalised df p-update over the carried slab (fringe/seam by
        # elementwise replay — see the section comment)
        p_ext = df_add(df_scale(p_prev_ext, beta), r_ext)
        y, pd = _kron_cg_df_call(op, coeffs, False, interpret, p_ext,
                                 **kw)
        # the iteration's ONLY big exchange: y's boundary planes (owner
        # seam folded in), consumed solely by the r-update tail
        (y_ext,) = extend((y,))
        r_loc = interior(r_ext)
        p_loc = interior(p_ext)
        g = df_psum_all_stacked(
            (pd, wdot_local(r_loc, y), wdot_local(y, y)), op.dshape)
        alpha, rnorm1, beta1 = onered_scalars_df(rnorm, g[0], g[1], g[2])
        x1 = df_axpy(x, alpha, p_loc)
        r1_ext = df_sub(r_ext, df_scale(y_ext, alpha))
        done1 = jnp.logical_or(done, rnorm1.hi <= floor * rnorm0_hi)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(done, o, n), new, old
            )

        return (keep(x1, x), keep(r1_ext, r_ext),
                keep(p_ext, p_prev_ext), keep(beta1, beta),
                keep(rnorm1, rnorm), done1)

    zero = DF(jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
    state = (df_zeros_like(b), r_ext0, df_zeros_like(r_ext0), zero,
             rnorm0, done0)
    x, *_ = lax.fori_loop(0, nreps, body, state)
    return x


def dist_kron_df_apply_ring_local(op: DistKronLaplacianDF, x: DF,
                                  interpret: bool | None = None) -> DF:
    """Per-shard single fused df apply y = A x (inside shard_map),
    discarding the fused dot — the df action-benchmark analogue of
    dist.kron_cg.dist_kron_apply_ring_local. x-only meshes use the
    plane-halo form; any other dshape the ext2d form."""
    P = op.degree
    if _is_x_only(op):
        cx_local, aux_local, coeffs = _shard_tables_df(op)
        (x_ext,) = _extend_df((x,), P)  # 2 channels only: no p payload
        y, _ = _kron_cg_df_call(
            op, coeffs, False, interpret, x_ext,
            cx=cx_local, aux=aux_local,
        )
        return y
    cx_local, aux_local, coeffs, mask2d, w2d = _shard_tables_df_3d(op)
    (x_ext,) = _extend_all_axes_df((x,), P, op.dshape)
    y, _ = _kron_cg_df_call(
        op, coeffs, False, interpret, x_ext,
        cx=cx_local, aux=aux_local, mask2d=mask2d, w2d=w2d,
    )
    return y
