"""Distributed domain decomposition over a TPU device mesh (SPMD).

TPU-native replacement for the reference's MPI machinery: mesh partitioning +
vertex-ghost layer (/root/reference/src/mesh.cpp:26-114), the DOLFINx
Scatterer ghost exchange with device pack/unpack kernels (vector.hpp:31-149),
and MPI_Allreduce dot products (vector.hpp:159-176). Design:

- The cell grid is block-partitioned over a 3D device mesh ("dx","dy","dz");
  every shard stores its full local dof-grid block *including* the shared
  interface planes. Plane ownership convention: the lower-index shard owns
  the shared plane, so the first plane along each sharded axis is a ghost
  copy on every shard except the first.
- Operator apply does, per sharded axis: one `ppermute` shift-right to
  refresh the ghost plane (forward scatter, owner -> ghost) and one
  `ppermute` shift-left to return boundary partial sums to their owner
  (reverse scatter-add). Unlike the reference — which ghosts a full layer of
  *cells* and redundantly recomputes them on both ranks to avoid a reverse
  scatter — ICI neighbour hops are cheap enough that sending one dof plane
  back is both simpler and does no duplicate FLOPs.
- Dot products mask ghost planes and `psum` over all mesh axes
  (MPI_Allreduce -> lax.psum). The whole CG loop, collectives included,
  compiles to a single XLA computation under `jax.shard_map`.
"""

from .mesh import DeviceGrid, factor_devices, make_device_grid, shard_cells
from .halo import halo_refresh, reverse_scatter_add, owned_mask
from .operator import DistLaplacian, build_dist_laplacian

__all__ = [
    "DeviceGrid",
    "factor_devices",
    "make_device_grid",
    "shard_cells",
    "halo_refresh",
    "reverse_scatter_add",
    "owned_mask",
    "DistLaplacian",
    "build_dist_laplacian",
]
