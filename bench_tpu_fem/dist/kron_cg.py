"""Distributed fused CG engine for the Kronecker (uniform-mesh) fast path.

The single-chip fused engine (ops.kron_cg) is worth ~1.4x over the unfused
3-stage composition on a v5e chip (9.14 vs 6.35 GDoF/s at the 12.5M-dof
flagship config) because the CG iteration is HBM-stream-bound. This module
carries that engine to x-axis-sharded device meshes (`dshape = (D, 1, 1)`,
the natural decomposition for the plane-sequential delay ring):

- HALO-EXTENDED INPUT, NO EDGE EPILOGUE: each shard owns dof planes
  [x0, x0 + Lx) (seam planes shared with neighbours, dist.kron layout).
  Before the kernel, ONE stacked `lax.ppermute` pair exchanges P planes of
  (r, p_prev) per side along x (the ICI analogue of the reference's ghost
  scatter, /root/reference/src/vector.hpp:31-149). The kernel — the
  *same* `ops.kron_cg` kernel, in its `halo = P` form — then runs the
  delay-ring recurrence over the extended slab [halo_l | local | halo_r]
  and emits exactly the local planes: every output row is globally exact
  by construction, so the 2P-plane boundary recomputation of dist.kron's
  unfused path disappears entirely.
- SEAM BIT-CONSISTENCY BY REPLAY: a seam plane is computed by both owners
  from bitwise-identical inputs through the identical kernel instruction
  sequence (same plane-local z/y contractions, same ascending-diagonal x
  sum), and the CG updates use globally psum-reduced scalars — so the
  duplicated planes stay bit-identical through CG with no refresh, the
  same invariant tests/test_dist_kron.py pins for the unfused path
  (tests/test_dist_kron_cg.py asserts the distributed apply is BITWISE
  equal to the single-chip engine apply).
- OWNERSHIP IN-KERNEL: the per-plane [interior-in-x, dot-weight] pair
  streams through SMEM next to the x-coefficient rows; duplicated seam
  planes get dot-weight 0 so <p, A p> partials count every dof once
  globally before the psum.

Trade-off vs the unfused distributed path (documented deliberately): the
kernel input depends on the halo exchange, so the collective is on the
critical path — the unfused path's main-kernel/collective independence
(overlap by construction) is given up for ~2x fewer HBM streams per
iteration. The exchange moves O(P * cross-section) bytes against
O(volume) compute; on ICI this is microseconds against milliseconds, so
the stream saving wins at any realistic size (the unfused path remains
available via `make_kron_sharded_fns(..., engine=False)`, and the dist
driver falls back to it if this engine fails to compile).

VMEM: the ring holds KI = 2P + 2 full (NY, NZ) cross-section planes; with
x-only sharding the cross-section does not shrink with the device count,
so `dist_kron_engine_plan` follows the single-chip engine_plan tiers
(including its raised scoped-VMEM requests, threaded through the dist
driver's compile) and callers fall back to the unfused dist path beyond
them (a y-chunked dist form is the natural extension if that ceiling
ever matters). Very
large per-shard blocks route the x/r update through the chunked pallas
pass exactly like the single-chip solve (PALLAS_UPDATE_MIN_DOFS — the
XLA TPU backend fails whole-vector fusions around ~130M dofs).

float32 only (Mosaic has no f64); benchmark semantics (rtol = 0, exactly
nreps iterations, reference cg.hpp:88-91).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..la.cg import fused_cg_solve
from ..ops.kron_cg import (
    PALLAS_UPDATE_MIN_DOFS,
    _cx_rows,
    _kron_cg_call,
    cg_update_pallas,
    engine_plan,
)
from .halo import psum_all
from .kron import DistKronLaplacian, halo_slabs
from .mesh import AXIS_NAMES


def dist_kron_engine_plan(
    op: DistKronLaplacian,
) -> tuple[bool, int | None]:
    """(supported, scoped_vmem_kib): x-only device meshes, f32, and the
    one-kernel ring within any one-kernel tier of the single-chip
    engine_plan (including the raised-limit tiers) —
    the ring's VMEM is set by the unsharded (NY, NZ) cross-section, so
    the same plan applies per shard; the kib request forwards through
    the dist driver's compile exactly like the single-chip one."""
    Lx, NY, NZ = op.L[0], op.notbc1d[1].shape[0], op.notbc1d[2].shape[0]
    if not (op.dshape[1] == 1 and op.dshape[2] == 1
            and op.kappa.dtype == jnp.float32):
        return False, None
    form, kib = engine_plan((Lx, NY, NZ), op.degree)
    return form == "one", kib


def supports_dist_kron_engine(op: DistKronLaplacian) -> bool:
    """Supported component of dist_kron_engine_plan (see module
    docstring)."""
    return dist_kron_engine_plan(op)[0]


def _shard_tables(op: DistKronLaplacian, dtype):
    """Per-shard SMEM row streams, cut once per jitted computation (inside
    shard_map, hoisted out of the CG loop): the local x-coefficient rows
    and the [interior-in-x, dot-weight] aux rows."""
    P = op.degree
    Lx = op.L[0]
    NXg = op.notbc1d[0].shape[0]
    x0 = lax.axis_index(AXIS_NAMES[0]) * (Lx - 1)
    cx_global = _cx_rows(op, dtype)  # (NXg, 1, 2(2P+1))
    z0 = jnp.zeros((), dtype=x0.dtype)
    cx_local = lax.dynamic_slice(
        cx_global, (x0, z0, z0), (Lx, 1, 2 * (2 * P + 1))
    )
    gx = x0 + jnp.arange(Lx)
    mi = jnp.logical_and(gx > 0, gx < NXg - 1).astype(dtype)
    w = jnp.where(jnp.logical_and(jnp.arange(Lx) == 0, x0 > 0),
                  jnp.zeros((), dtype), jnp.ones((), dtype))
    aux_local = jnp.stack([mi, w], axis=-1)[:, None, :]  # (Lx, 1, 2)
    return cx_local, aux_local


def _extend_rp(r, p_prev, P: int):
    """One stacked ppermute pair exchanges the P halo planes of r and
    p_prev together; returns the halo-extended slabs."""
    s = jnp.stack([r, p_prev])  # x axis is 1 in the stacked view
    hl, hr = halo_slabs(s, 1, AXIS_NAMES[0], P)
    r_ext = jnp.concatenate([hl[0], r, hr[0]], axis=0)
    p_ext = jnp.concatenate([hl[1], p_prev, hr[1]], axis=0)
    return r_ext, p_ext


def _dist_kron_cg_call(op, cx_local, aux_local, update_p: bool, interpret,
                       *vectors):
    """Per-shard engine call: the shared ops.kron_cg kernel in halo form."""
    return _kron_cg_call(op, update_p, interpret, *vectors,
                         cx=cx_local, aux=aux_local)


def dist_kron_cg_solve_local(op: DistKronLaplacian, b, nreps: int,
                             interpret: bool | None = None):
    """Per-shard fused-engine CG (call inside shard_map over an x-only
    device mesh): returns the local solution block. Matches the unfused
    dist path (dist.kron.make_kron_sharded_fns cg_fn) to f32 reassociation
    accuracy, at ~half the HBM streams per iteration."""
    dtype = b.dtype
    cx_local, aux_local = _shard_tables(op, dtype)
    P = op.degree
    # owned-dof weight per plane for the masked psum inner products (the
    # same ownership the kernel's aux column 1 applies to <p, A p>)
    wplane = aux_local[:, 0, 1][:, None, None]

    def inner(u, v):
        return psum_all(jnp.sum(u * v * wplane))

    def engine(r, p_prev, beta):
        r_ext, p_ext = _extend_rp(r, p_prev, P)
        p, y, pdot = _dist_kron_cg_call(
            op, cx_local, aux_local, True, interpret, r_ext, p_ext, beta
        )
        return p, y, psum_all(pdot)

    update = None
    if b.size >= PALLAS_UPDATE_MIN_DOFS:
        # Chunked pallas x/r update (single-chip rationale at
        # ops.kron_cg.PALLAS_UPDATE_MIN_DOFS: XLA TPU fails whole-vector
        # fusions ~130M dofs). Its <r1,r1> counts every local plane; the
        # duplicated seam plane is subtracted before the psum.
        def update(x, pv, r, y, alpha):
            x1, r1, rr = cg_update_pallas(x, pv, r, y, alpha, interpret)
            seam0 = jnp.sum(r1[0] * r1[0]) * (1.0 - wplane[0, 0, 0])
            return x1, r1, psum_all(rr - seam0)

    return fused_cg_solve(engine, b, nreps, update=update, inner=inner)


def dist_kron_apply_ring_local(op: DistKronLaplacian, x,
                               interpret: bool | None = None):
    """Per-shard single delay-ring apply y = A x (inside shard_map),
    discarding the fused dot partial — the distributed action-benchmark
    analogue of ops.kron_cg.kron_apply_ring."""
    cx_local, aux_local = _shard_tables(op, x.dtype)
    hl, hr = halo_slabs(x, 0, AXIS_NAMES[0], op.degree)
    x_ext = jnp.concatenate([hl, x, hr], axis=0)
    y, _ = _dist_kron_cg_call(
        op, cx_local, aux_local, False, interpret, x_ext
    )
    return y
