"""Distributed fused CG engine for the Kronecker (uniform-mesh) fast path.

The single-chip fused engine (ops.kron_cg) is worth ~1.4x over the unfused
3-stage composition on a v5e chip (9.14 vs 6.35 GDoF/s at the 12.5M-dof
flagship config — ROUND-4 measurement of the f32 engine,
BASELINE_MATRIX_r04.json; the distributed form below and every df
variant are design-stage and unmeasured on hardware) because the CG
iteration is HBM-stream-bound. This module
carries that engine to x-axis-sharded device meshes (`dshape = (D, 1, 1)`,
the natural decomposition for the plane-sequential delay ring):

- HALO-EXTENDED INPUT, NO EDGE EPILOGUE: each shard owns dof planes
  [x0, x0 + Lx) (seam planes shared with neighbours, dist.kron layout).
  Before the kernel, ONE stacked `lax.ppermute` pair exchanges P planes of
  (r, p_prev) per side along x (the ICI analogue of the reference's ghost
  scatter, /root/reference/src/vector.hpp:31-149). The kernel — the
  *same* `ops.kron_cg` kernel, in its `halo = P` form — then runs the
  delay-ring recurrence over the extended slab [halo_l | local | halo_r]
  and emits exactly the local planes: every output row is globally exact
  by construction, so the 2P-plane boundary recomputation of dist.kron's
  unfused path disappears entirely.
- SEAM BIT-CONSISTENCY BY REPLAY: a seam plane is computed by both owners
  from bitwise-identical inputs through the identical kernel instruction
  sequence (same plane-local z/y contractions, same ascending-diagonal x
  sum), and the CG updates use globally psum-reduced scalars — so the
  duplicated planes stay bit-identical through CG with no refresh, the
  same invariant tests/test_dist_kron.py pins for the unfused path
  (tests/test_dist_kron_cg.py asserts the distributed apply is BITWISE
  equal to the single-chip engine apply).
- OWNERSHIP IN-KERNEL: the per-plane [interior-in-x, dot-weight] pair
  streams through SMEM next to the x-coefficient rows; duplicated seam
  planes get dot-weight 0 so <p, A p> partials count every dof once
  globally before the psum.

Trade-off vs the unfused distributed path (documented deliberately): the
kernel input depends on the halo exchange, so the collective is on the
critical path — the unfused path's main-kernel/collective independence
(overlap by construction) is given up for ~2x fewer HBM streams per
iteration. The exchange moves O(P * cross-section) bytes against
O(volume) compute; on ICI this is microseconds against milliseconds, so
the stream saving wins at any realistic size (the unfused path remains
available via `make_kron_sharded_fns(..., engine=False)`, and the dist
driver falls back to it if this engine fails to compile).

VMEM: the ring holds KI = 2P + 2 full (NY, NZ) cross-section planes; with
x-only sharding the cross-section does not shrink with the device count,
so `dist_kron_engine_plan` follows the single-chip engine_plan tiers
(including its raised scoped-VMEM requests, threaded through the dist
driver's compile) and callers fall back to the unfused dist path beyond
them (a y-chunked dist form is the natural extension if that ceiling
ever matters). Very
large per-shard blocks route the x/r update through the chunked pallas
pass exactly like the single-chip solve (PALLAS_UPDATE_MIN_DOFS — the
XLA TPU backend fails whole-vector fusions around ~130M dofs).

float32 only (Mosaic has no f64); benchmark semantics (rtol = 0, exactly
nreps iterations, reference cg.hpp:88-91).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

import numpy as np

from ..la.cg import fused_cg_solve, onered_scalars
from ..ops.kron_cg import (
    PALLAS_UPDATE_MIN_DOFS,
    _cx_rows,
    _kron_cg_call,
    cg_update_pallas,
    engine_plan,
)
from .halo import owned_dot, psum_all, psum_stack
from .kron import DistKronLaplacian, halo_slabs
from .mesh import AXIS_NAMES


def dist_kron_engine_plan(
    op: DistKronLaplacian,
) -> tuple[bool, int | None]:
    """(supported, scoped_vmem_kib): f32, and the one-kernel ring within
    any one-kernel tier of the single-chip engine_plan (including the
    raised-limit tiers). On x-only meshes the ring's VMEM is set by the
    unsharded (NY, NZ) cross-section; on 3D meshes by the halo-extended
    local cross-section (the ext2d kernel's ephemeral contraction
    operands are (Ly+2P, Lz+2P)) — the same tier plan applies per shard,
    and the kib request forwards through the dist driver's compile
    exactly like the single-chip one."""
    if op.kappa.dtype != jnp.float32:
        return False, None
    P = op.degree
    Lx = op.L[0]
    if op.dshape[1] == 1 and op.dshape[2] == 1:
        cross = (op.notbc1d[1].shape[0], op.notbc1d[2].shape[0])
    else:
        cross = (op.L[1] + 2 * P, op.L[2] + 2 * P)
    form, kib = engine_plan((Lx, *cross), P)
    return form == "one", kib


def supports_dist_kron_engine(op: DistKronLaplacian) -> bool:
    """Supported component of dist_kron_engine_plan (see module
    docstring)."""
    return dist_kron_engine_plan(op)[0]


def _shard_tables(op: DistKronLaplacian, dtype):
    """Per-shard SMEM row streams, cut once per jitted computation (inside
    shard_map, hoisted out of the CG loop): the local x-coefficient rows
    and the [interior-in-x, dot-weight] aux rows."""
    P = op.degree
    Lx = op.L[0]
    NXg = op.notbc1d[0].shape[0]
    x0 = lax.axis_index(AXIS_NAMES[0]) * (Lx - 1)
    cx_global = _cx_rows(op, dtype)  # (NXg, 1, 2(2P+1))
    z0 = jnp.zeros((), dtype=x0.dtype)
    cx_local = lax.dynamic_slice(
        cx_global, (x0, z0, z0), (Lx, 1, 2 * (2 * P + 1))
    )
    gx = x0 + jnp.arange(Lx)
    mi = jnp.logical_and(gx > 0, gx < NXg - 1).astype(dtype)
    w = jnp.where(jnp.logical_and(jnp.arange(Lx) == 0, x0 > 0),
                  jnp.zeros((), dtype), jnp.ones((), dtype))
    aux_local = jnp.stack([mi, w], axis=-1)[:, None, :]  # (Lx, 1, 2)
    return cx_local, aux_local


def _extend_rp(r, p_prev, P: int):
    """One stacked ppermute pair exchanges the P halo planes of r and
    p_prev together; returns the halo-extended slabs."""
    s = jnp.stack([r, p_prev])  # x axis is 1 in the stacked view
    hl, hr = halo_slabs(s, 1, AXIS_NAMES[0], P)
    r_ext = jnp.concatenate([hl[0], r, hr[0]], axis=0)
    p_ext = jnp.concatenate([hl[1], p_prev, hr[1]], axis=0)
    return r_ext, p_ext


def _extend_all_axes(arrs, P: int, dshape):
    """Halo-extend the stacked arrays by P planes per side along every
    sharded axis, sequentially z -> y -> x so later exchanges carry the
    earlier extensions (corner/edge halo data arrives already extended —
    the standard sequential-corner construction). Unsharded axes are
    zero-extended locally (no collective): the zero fringe meets the
    zero-padded coefficient slices exactly like a global domain edge."""
    s = jnp.stack(arrs)  # grid axes shift by 1 in the stacked view
    for ax in (2, 1, 0):
        sax = ax + 1
        if dshape[ax] > 1:
            hl, hr = halo_slabs(s, sax, AXIS_NAMES[ax], P)
        else:
            shp = list(s.shape)
            shp[sax] = P
            hl = hr = jnp.zeros(shp, s.dtype)
        s = jnp.concatenate([hl, s, hr], axis=sax)
    return tuple(s[i] for i in range(len(arrs)))


def _shard_tables_3d(op: DistKronLaplacian, dtype):
    """Per-shard tables for the ext2d kernel form (3D-sharded meshes):
    the x-coefficient/aux rows of _shard_tables, plus the halo-extended
    y/z banded coefficient slices (global-indexed, zero outside the
    domain), the cross-section Dirichlet-interior mask, and the
    cross-section dot-ownership weights (0 on duplicated seam rows/cols
    so reductions count every dof once globally)."""
    P = op.degree
    nb = 2 * P + 1
    cx_local, aux_local = _shard_tables(op, dtype)

    def ext_coeff(global_diags, axis_i):
        La = op.L[axis_i]
        a0 = lax.axis_index(AXIS_NAMES[axis_i]) * (La - 1)
        padded = jnp.pad(global_diags.astype(dtype), ((0, 0), (P, P)))
        z0 = jnp.zeros((), dtype=a0.dtype)
        # padded index a0 == global index a0 - P: the extended slice
        # starts P rows/cols before the local block
        return lax.dynamic_slice(padded, (z0, a0), (nb, La + 2 * P))

    ckz = ext_coeff(op.Kd[2], 2)
    cmz = ext_coeff(op.Md[2], 2)
    cky = ext_coeff(op.Kd[1], 1)
    cmy = ext_coeff(op.Md[1], 1)

    def local_1d(vec, axis_i):
        La = op.L[axis_i]
        a0 = lax.axis_index(AXIS_NAMES[axis_i]) * (La - 1)
        return lax.dynamic_slice(vec.astype(dtype), (a0,), (La,)), a0

    nby, y0 = local_1d(op.notbc1d[1], 1)
    nbz, z0 = local_1d(op.notbc1d[2], 2)
    mask2d = nby[:, None] * nbz[None, :]
    wy = jnp.where(jnp.logical_and(jnp.arange(op.L[1]) == 0, y0 > 0),
                   jnp.zeros((), dtype), jnp.ones((), dtype))
    wz = jnp.where(jnp.logical_and(jnp.arange(op.L[2]) == 0, z0 > 0),
                   jnp.zeros((), dtype), jnp.ones((), dtype))
    w2d = wy[:, None] * wz[None, :]
    return cx_local, aux_local, (ckz, cmz, cky, cmy), mask2d, w2d


def _dist_kron_cg_call(op, cx_local, aux_local, update_p: bool, interpret,
                       *vectors):
    """Per-shard engine call: the shared ops.kron_cg kernel in halo form."""
    return _kron_cg_call(op, update_p, interpret, *vectors,
                         cx=cx_local, aux=aux_local)


def _is_x_only(op: DistKronLaplacian) -> bool:
    return op.dshape[1] == 1 and op.dshape[2] == 1


def dist_kron_cg_solve_local(op: DistKronLaplacian, b, nreps: int,
                             interpret: bool | None = None):
    """Per-shard fused-engine CG (call inside shard_map): returns the
    local solution block. Matches the unfused dist path
    (dist.kron.make_kron_sharded_fns cg_fn) to f32 reassociation
    accuracy, at ~half the HBM streams per iteration. x-only meshes use
    the plane-halo kernel form; 3D meshes the ext2d form (cross-sections
    halo-extended too, seam dedup via in-kernel weight planes)."""
    dtype = b.dtype
    P = op.degree
    if _is_x_only(op):
        cx_local, aux_local = _shard_tables(op, dtype)
        coeffs = mask2d = w2d = None
        w3 = aux_local[:, 0, 1][:, None, None]

        def engine(r, p_prev, beta):
            r_ext, p_ext = _extend_rp(r, p_prev, P)
            p, y, pdot = _dist_kron_cg_call(
                op, cx_local, aux_local, True, interpret, r_ext, p_ext,
                beta
            )
            return p, y, psum_all(pdot)
    else:
        cx_local, aux_local, coeffs, mask2d, w2d = _shard_tables_3d(
            op, dtype)
        w3 = aux_local[:, 0, 1][:, None, None] * w2d[None]

        def engine(r, p_prev, beta):
            r_ext, p_ext = _extend_all_axes((r, p_prev), P, op.dshape)
            p, y, pdot = _kron_cg_call(
                op, True, interpret, r_ext, p_ext, beta,
                cx=cx_local, aux=aux_local, coeffs=coeffs,
                mask2d=mask2d, w2d=w2d,
            )
            return p, y, psum_all(pdot)

    # owned-dof weight for the masked psum inner products (the same
    # ownership the kernel's dot weighting applies to <p, A p>)
    inner = owned_dot(w3)

    update = None
    if b.size >= PALLAS_UPDATE_MIN_DOFS:
        # Chunked pallas x/r update (single-chip rationale at
        # ops.kron_cg.PALLAS_UPDATE_MIN_DOFS: XLA TPU fails whole-vector
        # fusions ~130M dofs). Its <r1,r1> counts every local dof; the
        # duplicated seam contribution is subtracted before the psum —
        # one O(cross-section) plane read on x-only meshes (a full-array
        # re-read would add a whole HBM stream per iteration on exactly
        # the path built to minimise streams); ext2d seams need the
        # full weighted correction.
        x_only = _is_x_only(op)

        def update(x, pv, r, y, alpha):
            x1, r1, rr = cg_update_pallas(x, pv, r, y, alpha, interpret)
            if x_only:
                seam = jnp.sum(r1[0] * r1[0]) * (1.0 - w3[0, 0, 0])
            else:
                seam = jnp.sum(r1 * r1 * (1.0 - w3))
            return x1, r1, psum_all(rr - seam)

    return fused_cg_solve(engine, b, nreps, update=update, inner=inner)


# ---------------------------------------------------------------------------
# Communication-overlapped (double-buffered halo) engine form.
#
# The synchronous engine above puts BOTH collectives on the iteration's
# critical path: the (r, p_prev) halo exchange feeds the kernel, and two
# psum'd dots serialize against the updates. The overlap form
# restructures the loop around a carried halo-extended state:
#
#  - DOUBLE-BUFFERED HALO: the loop carries (r_ext, p_prev_ext) —
#    already halo-extended slabs. The iteration's ONLY ppermute is the
#    exchange of the fresh operator output y's boundary planes, issued
#    immediately after the kernel; its sole consumer is the O(fringe)
#    tail of the r update (r1_ext = r_ext - alpha * y_ext), so XLA can
#    run the exchange behind the dot partials, the psum, and the whole
#    x update — and the NEXT iteration's kernel input needs no exchange
#    at all (the halo for apply k+1 is in flight while iteration k's
#    interior compute runs).
#  - SINGLE-PSUM ITERATIONS: the two reductions fuse into one stacked
#    psum of (<p, A p>, <r, y>, <y, y>) — <r1, r1> follows from the
#    la.cg.onered_scalars recurrence. The kernel's in-kernel owned-
#    weighted <p, A p> partial rides the same stack.
#
# The p-update moves OUT of the kernel (p_ext = beta * p_prev_ext +
# r_ext, one fused elementwise pass over the extended slab) so the ghost
# fringe replays the owner's arithmetic elementwise — XLA applies the
# identical instruction to every element of one fused op, so fringe and
# seam values stay bitwise consistent across shards, exactly the replay
# invariant the synchronous form pins. Cost accounting (the deliberate
# trade): one extra O(volume) elementwise stream (the externalised
# p-update) and one extra fused read pass for <r, y>/<y, y>, against one
# fewer psum per iteration and every halo exchange moved off the
# critical path. At pod scale and fixed local size the collective
# latency dominates those streams; the weak-scaling harness
# (scripts/weak_scaling.py) measures exactly this A/B and the CPU lane
# proves parity + the collective-count invariant today. Gated as engine
# forms `halo_overlap` / `ext2d_overlap`; parity vs the synchronous
# oracle <= 1e-7 rel f32 (the reassociated residual-norm recurrence).
# ---------------------------------------------------------------------------


def supports_dist_kron_overlap(op: DistKronLaplacian) -> bool:
    """The overlap form shares the synchronous engine's ring plan; the
    ext2d variant additionally keeps its whole-slab r update as one XLA
    elementwise pass (no chunked-update route on the 3D fringe yet), so
    shards at the XLA whole-vector fusion wall fall back to the
    synchronous engine with the reason recorded by the driver."""
    if not supports_dist_kron_engine(op):
        return False
    if _is_x_only(op):
        return True
    return int(np.prod(op.L)) < PALLAS_UPDATE_MIN_DOFS


def _extend_arrs(arrs, op: DistKronLaplacian):
    """Halo-extend arrays for the kernel-input slab of the active form:
    x-only meshes extend along x only (one stacked ppermute pair); 3D
    meshes extend every axis (the sequential-corner construction)."""
    P = op.degree
    if _is_x_only(op):
        s = jnp.stack(arrs)  # x axis is 1 in the stacked view
        hl, hr = halo_slabs(s, 1, AXIS_NAMES[0], P)
        s = jnp.concatenate([hl, s, hr], axis=1)
        return tuple(s[i] for i in range(len(arrs)))
    return _extend_all_axes(arrs, P, op.dshape)


def _interior(v, op: DistKronLaplacian):
    """Local (Lx, Ly, Lz) block of a halo-extended slab."""
    P = op.degree
    if _is_x_only(op):
        return lax.slice_in_dim(v, P, P + op.L[0], axis=0)
    for ax in range(3):
        v = lax.slice_in_dim(v, P, P + op.L[ax], axis=ax)
    return v


def dist_kron_cg_solve_local_overlap(op: DistKronLaplacian, b, nreps: int,
                                     interpret: bool | None = None):
    """Per-shard communication-overlapped fused-engine CG (inside
    shard_map): carried halo-extended (r, p_prev) state, one y-boundary
    ppermute per iteration off the critical path, ONE stacked psum per
    iteration. Matches the synchronous engine
    (dist_kron_cg_solve_local) to the single-reduction reassociation
    envelope (<= 1e-7 rel f32). x-only meshes use the plane-halo kernel
    form; 3D meshes the ext2d form."""
    dtype = b.dtype
    P = op.degree
    x_only = _is_x_only(op)
    if x_only:
        cx_local, aux_local = _shard_tables(op, dtype)
        w3 = aux_local[:, 0, 1][:, None, None]
        kw = dict(cx=cx_local, aux=aux_local)
    else:
        cx_local, aux_local, coeffs, mask2d, w2d = _shard_tables_3d(
            op, dtype)
        w3 = aux_local[:, 0, 1][:, None, None] * w2d[None]
        kw = dict(cx=cx_local, aux=aux_local, coeffs=coeffs,
                  mask2d=mask2d, w2d=w2d)

    rnorm0 = owned_dot(w3)(b, b)  # one psum, outside the loop
    (r_ext0,) = _extend_arrs((b,), op)
    # chunked pallas x/r update above the shared size policy (x-only
    # meshes: the fringe planes update elementwise and the local block
    # rides the pallas pass, its fused <r1,r1> discarded — the overlap
    # recurrence never reads it)
    big = x_only and b.size >= PALLAS_UPDATE_MIN_DOFS

    def body(_, state):
        x, r_ext, p_prev_ext, beta, rnorm = state
        # externalised p-update: one fused elementwise pass over the
        # extended slab (fringe replays the owner's arithmetic)
        p_ext = beta * p_prev_ext + r_ext
        y, pd = _kron_cg_call(op, False, interpret, p_ext, **kw)
        # the ONLY exchange of the iteration: y's boundary planes for
        # the NEXT apply's halo — consumed solely by the r-update tail,
        # so it overlaps the dots, the psum and the x update
        (y_ext,) = _extend_arrs((y,), op)
        r_loc = _interior(r_ext, op)
        p_loc = _interior(p_ext, op)
        yw = y * w3
        g = psum_stack(pd, jnp.sum(r_loc * yw), jnp.sum(y * yw))
        alpha, rnorm1, beta1 = onered_scalars(rnorm, g[0], g[1], g[2])
        if big:
            x1, r1_loc, _ = cg_update_pallas(x, p_loc, r_loc, y, alpha,
                                             interpret)
            Le = r_ext.shape[0]
            r1_ext = jnp.concatenate([
                lax.slice_in_dim(r_ext, 0, P, axis=0)
                - alpha * lax.slice_in_dim(y_ext, 0, P, axis=0),
                r1_loc,
                lax.slice_in_dim(r_ext, Le - P, Le, axis=0)
                - alpha * lax.slice_in_dim(y_ext, Le - P, Le, axis=0),
            ], axis=0)
        else:
            x1 = x + alpha * p_loc
            r1_ext = r_ext - alpha * y_ext
        return (x1, r1_ext, p_ext, beta1, rnorm1)

    state = (jnp.zeros_like(b), r_ext0, jnp.zeros_like(r_ext0),
             jnp.zeros((), dtype), rnorm0)
    x, *_ = lax.fori_loop(0, nreps, body, state)
    return x


def dist_kron_apply_ring_local(op: DistKronLaplacian, x,
                               interpret: bool | None = None):
    """Per-shard single delay-ring apply y = A x (inside shard_map),
    discarding the fused dot partial — the distributed action-benchmark
    analogue of ops.kron_cg.kron_apply_ring."""
    P = op.degree
    if _is_x_only(op):
        cx_local, aux_local = _shard_tables(op, x.dtype)
        hl, hr = halo_slabs(x, 0, AXIS_NAMES[0], P)
        x_ext = jnp.concatenate([hl, x, hr], axis=0)
        y, _ = _dist_kron_cg_call(
            op, cx_local, aux_local, False, interpret, x_ext
        )
        return y
    cx_local, aux_local, coeffs, mask2d, w2d = _shard_tables_3d(op, x.dtype)
    (x_ext,) = _extend_all_axes((x,), P, op.dshape)
    y, _ = _kron_cg_call(
        op, False, interpret, x_ext,
        cx=cx_local, aux=aux_local, coeffs=coeffs, mask2d=mask2d, w2d=w2d,
    )
    return y
