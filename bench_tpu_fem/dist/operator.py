"""Distributed matrix-free Laplacian: per-shard state + shard-level apply.

The distributed analogue of `MatFreeLaplacianGPU` (/root/reference/src/
laplacian.hpp:89-447). Each shard holds the geometry tensor for its own cell
block and the local slice of the Dirichlet marker; `apply_local` runs inside
`jax.shard_map` and performs

    halo_refresh -> gather -> sum-factorised kernel -> fold -> reverse_scatter

which is the reference's scatter_fwd / lcell+bcell compute / atomicAdd
pipeline collapsed into per-axis ICI neighbour collectives (see dist/halo.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..elements.tables import OperatorTables
from ..mesh.box import BoxMesh
from ..mesh.dofmap import boundary_dof_marker, dof_grid_shape
from ..ops.laplacian import (
    cell_apply,
    fold_cells,
    freeze_table,
    gather_cells,
)
from .halo import halo_refresh, reverse_scatter_add
from .mesh import shard_cells


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["G", "phi0", "dphi1", "bc_mask", "kappa"],
    meta_fields=["n_local", "degree", "is_identity", "backend", "phi0_c", "dphi1_c"],
)
@dataclass(frozen=True)
class DistLaplacian:
    """Stacked per-shard operator state. Array leading axes (Dx, Dy, Dz) are
    sharded over the device grid; `apply_local` sees one shard's block."""

    G: jnp.ndarray  # (Dx,Dy,Dz, ncells_local, 6, nq,nq,nq); block-major for pallas
    phi0: jnp.ndarray  # (nq, nd) replicated
    dphi1: jnp.ndarray  # (nq, nq) replicated
    bc_mask: jnp.ndarray  # (Dx,Dy,Dz, Lx,Ly,Lz) bool
    kappa: jnp.ndarray  # scalar
    n_local: tuple[int, int, int]  # cells per shard
    degree: int
    is_identity: bool
    backend: str = "xla"
    phi0_c: tuple | None = None
    dphi1_c: tuple | None = None

    def apply_local(self, x_local: jnp.ndarray, G_local, bc_local) -> jnp.ndarray:
        """y = A x for one shard's block (call inside shard_map).

        Grid-layout shards support the XLA einsum kernel only: the Pallas
        hot path is the folded layout (dist.folded / ops.folded_cg), which
        replaced the earlier grid-layout pallas branch here — that branch
        was unreachable from the driver and would not trace under the
        default shard_map VMA check."""
        x = halo_refresh(x_local)
        xm = jnp.where(bc_local, 0, x)
        u = gather_cells(xm, self.n_local, self.degree)
        y = cell_apply(
            u, G_local, self.phi0, self.dphi1, self.kappa, self.is_identity,
            backend=self.backend,
        )
        y_grid = fold_cells(y, self.n_local, self.degree)
        y_grid = reverse_scatter_add(y_grid)
        return jnp.where(bc_local, x, y_grid)


def local_grid_shape(n_local: tuple[int, int, int], degree: int) -> tuple[int, int, int]:
    """Local dof block shape: owned planes plus the leading ghost plane
    (numerically the same formula as the global dof_grid_shape)."""
    return dof_grid_shape(n_local, degree)


def shard_grid_blocks(
    grid: np.ndarray, n: tuple[int, int, int], degree: int, dshape: tuple[int, int, int]
) -> np.ndarray:
    """Slice a global dof grid (NX, NY, NZ[, ...]) into overlapping local
    blocks, stacked as (Dx, Dy, Dz, Lx, Ly, Lz[, ...])."""
    P = degree
    ncl = shard_cells(n, dshape)
    L = local_grid_shape(ncl, degree)
    out = np.empty((*dshape, *L, *grid.shape[3:]), dtype=grid.dtype)
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                x0, y0, z0 = i * ncl[0] * P, j * ncl[1] * P, k * ncl[2] * P
                out[i, j, k] = grid[
                    x0 : x0 + L[0], y0 : y0 + L[1], z0 : z0 + L[2]
                ]
    return out


def unshard_grid_blocks(
    blocks: np.ndarray, n: tuple[int, int, int], degree: int, dshape: tuple[int, int, int]
) -> np.ndarray:
    """Inverse of shard_grid_blocks: reassemble the global grid from owned
    planes (ghost plane 0 of non-first shards is dropped)."""
    P = degree
    ncl = shard_cells(n, dshape)
    N = dof_grid_shape(n, degree)
    out = np.empty(N, dtype=blocks.dtype)
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                blk = blocks[i, j, k]
                sx = 0 if i == 0 else 1
                sy = 0 if j == 0 else 1
                sz = 0 if k == 0 else 1
                x0, y0, z0 = i * ncl[0] * P, j * ncl[1] * P, k * ncl[2] * P
                out[
                    x0 + sx : x0 + blk.shape[0],
                    y0 + sy : y0 + blk.shape[1],
                    z0 + sz : z0 + blk.shape[2],
                ] = blk[sx:, sy:, sz:]
    return out


def shard_cell_corners(
    mesh: BoxMesh, dshape: tuple[int, int, int]
) -> np.ndarray:
    """(Dx, Dy, Dz, ncells_local, 2, 2, 2, 3) per-shard cell corners."""
    ncl = shard_cells(mesh.n, dshape)
    corners = mesh.cell_corners  # (nx, ny, nz, 2,2,2,3)
    out = np.empty((*dshape, int(np.prod(ncl)), 2, 2, 2, 3), dtype=corners.dtype)
    for i in range(dshape[0]):
        for j in range(dshape[1]):
            for k in range(dshape[2]):
                blk = corners[
                    i * ncl[0] : (i + 1) * ncl[0],
                    j * ncl[1] : (j + 1) * ncl[1],
                    k * ncl[2] : (k + 1) * ncl[2],
                ]
                out[i, j, k] = blk.reshape(-1, 2, 2, 2, 3)
    return out


def build_dist_laplacian(
    mesh: BoxMesh,
    dgrid,
    degree: int,
    tables: OperatorTables,
    kappa: float = 2.0,
    dtype=jnp.float64,
    backend: str = "xla",
) -> DistLaplacian:
    """Build stacked per-shard operator state. The geometry tensor is computed
    *on device, per shard* inside shard_map (each shard einsums only its own
    cells — the distributed analogue of `compute_geometry`,
    laplacian.hpp:238-272). Grid-layout distribution is XLA-only; the Pallas
    distributed path is dist.folded."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..ops.geometry import geometry_factors_jax
    from .mesh import AXIS_NAMES

    if backend not in ("xla",):
        raise ValueError(
            f"grid-layout distributed operator supports backend='xla' only "
            f"(got {backend!r}); the Pallas distributed path is the folded "
            f"layout (dist.folded)"
        )
    t = tables
    dshape = dgrid.dshape
    corners_host = shard_cell_corners(mesh, dshape).astype(
        np.float64 if dtype == jnp.float64 else np.float32
    )
    spec = P(*AXIS_NAMES)
    sharding = NamedSharding(dgrid.mesh, spec)
    corners = jax.device_put(jnp.asarray(corners_host), sharding)

    @partial(
        jax.shard_map,
        mesh=dgrid.mesh,
        in_specs=spec,
        out_specs=spec,
    )
    def shard_geometry(c):
        G, _ = geometry_factors_jax(c[0, 0, 0], t.pts1d, t.wts1d)
        return G[None, None, None]

    G = shard_geometry(corners)

    ncl = shard_cells(mesh.n, dshape)
    bc_global = boundary_dof_marker(mesh.n, degree)
    bc_blocks = shard_grid_blocks(bc_global, mesh.n, degree, dshape)
    bc = jax.device_put(jnp.asarray(bc_blocks), sharding)

    return DistLaplacian(
        G=G,
        phi0=jnp.asarray(t.phi0, dtype=dtype),
        dphi1=jnp.asarray(t.dphi1, dtype=dtype),
        bc_mask=bc,
        kappa=jnp.asarray(kappa, dtype=dtype),
        n_local=ncl,
        degree=degree,
        is_identity=t.is_identity,
        backend=backend,
        phi0_c=freeze_table(t.phi0),
        dphi1_c=freeze_table(t.dphi1),
    )
