"""Hardware verification of the per-path scoped-VMEM policy
(utils.compilation + ops.kron_cg.engine_plan + ops.folded.pallas_plan).

History (MEASURE_r04.log): the raw probes that set the policy ran with a
global TPU_COMPILER_OPTIONS hook — A_FLAG64 8.13 (blanket raise costs
the flagship ~12%), B_25M_ONE 6.92, C_100M_ONE 7.66, D_DEG6PERT 0.199
(old routing: xla), E_DEG5PERT 3.82. These stages verify the shipped
per-path plan reproduces the wins with no global hook and no flagship
regression.

Usage: python scripts/probe_scoped_vmem.py [stage...]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# shared logging/runner: one copy of the output filter + the
# MEASURE_rNN.log line format (the harness agenda delegates its p300
# stage back here, so the two agendas share one log convention — and the
# harness runner's timeout path keeps the partial output tail)
from bench_tpu_fem.harness.agenda import log, run_py  # noqa: E402

BENCH = """
from bench_tpu_fem.bench.driver import BenchConfig, run_benchmark
cfg = BenchConfig({cfg})
r = run_benchmark(cfg)
print('{tag}:', r.gdof_per_second, r.extra.get('backend'),
      r.extra.get('geom'), r.extra.get('cg_engine'),
      r.extra.get('cg_engine_form'),
      str(r.extra.get('cg_engine_error'))[:120])
"""


def probe(tag, cfg, timeout=900):
    rc, out = run_py(BENCH.format(tag=tag, cfg=cfg), timeout)
    tail = [ln for ln in out.splitlines() if ln.startswith(tag)]
    log(f"{tag} rc={rc}: " + (tail[-1] if tail else out))


STAGES = {
    # flagship must stay ~9.1+ (no raised limit on its path)
    "flag": lambda: probe(
        "P_FLAG", "ndofs_global=12_500_000, degree=3, qmode=1, "
        "float_bits=32, nreps=1000, use_cg=True"),
    # one-kernel via plan at the sizes the chunked form used to take
    "q3_25m": lambda: probe(
        "P_25M", "ndofs_global=25_000_000, degree=3, qmode=1, "
        "float_bits=32, nreps=500, use_cg=True"),
    "q3_100m": lambda: probe(
        "P_100M", "ndofs_global=100_000_000, degree=3, qmode=1, "
        "float_bits=32, nreps=100, use_cg=True", 1200),
    "q3_128m": lambda: probe(
        "P_128M", "ndofs_global=128_000_000, degree=3, qmode=1, "
        "float_bits=32, nreps=100, use_cg=True", 1200),
    # tier 3 (96 MiB request): a regression here (e.g. a Mosaic stack-
    # allocator change) silently degrades 200-300M and Q6@64M to the
    # chunked retry — this stage makes that visible
    "q3_300m": lambda: probe(
        "P_300M", "ndofs_global=300_000_000, degree=3, qmode=1, "
        "float_bits=32, nreps=50, use_cg=True", 1200),
    # streamed-corner perturbed paths at matrix configs
    "deg5pert": lambda: probe(
        "P_DEG5PERT", "ndofs_global=12_500_000, degree=5, qmode=1, "
        "float_bits=32, nreps=500, use_cg=True, geom_perturb_fact=0.2",
        1200),
    "deg6pert": lambda: probe(
        "P_DEG6PERT", "ndofs_global=12_500_000, degree=6, qmode=1, "
        "float_bits=32, nreps=300, use_cg=True, geom_perturb_fact=0.2",
        1200),
    "q6": lambda: probe(
        "P_Q6", "ndofs_global=12_500_000, degree=6, qmode=1, "
        "float_bits=32, nreps=1000, use_cg=True", 1200),
    # perturbed capacity: corner mode at the reference-scale sizes (the
    # matrix measures perturbed only at 12.5M; auto-geom switches to
    # corner above ~6 GB of G). The folded engine auto-falls-back with
    # a recorded reason if its ring misses VMEM at this cross-section.
    "pert100": lambda: probe(
        "P_PERT100", "ndofs_global=100_000_000, degree=3, qmode=1, "
        "float_bits=32, nreps=100, use_cg=True, geom_perturb_fact=0.2",
        1800),
}


def _deg7_probe():
    """Raw compile probe: degree-7 qmode-1 plane-streamed corner kernel
    under a 48 MiB scoped limit (model ~24 MB x ~1.4 Mosaic ratio ~34 MB
    — plausibly fits, but pallas_plan keeps degree 7 on the XLA fallback
    until this compiles on hardware; a pass here is the evidence needed
    to widen the plan next round)."""
    code = """
import jax, jax.numpy as jnp, numpy as np
import bench_tpu_fem.ops.pallas_laplacian as PL
PL._STREAMED_SCOPED_BUDGET_BYTES = 64 * 2**20  # admit degree 7 for the probe
from bench_tpu_fem.mesh.box import create_box_mesh
from bench_tpu_fem.mesh.sizing import compute_mesh_size
from bench_tpu_fem.ops.folded import build_folded_laplacian, fold_vector
from bench_tpu_fem.utils.compilation import compile_lowered
n = compute_mesh_size(2_000_000, 7)
mesh = create_box_mesh(n, geom_perturb_fact=0.2)
op = build_folded_laplacian(mesh, 7, 1, dtype=jnp.float32, geom='corner')
g = np.random.RandomState(0).rand(*[d*7+1 for d in n]).astype(np.float32)
b = jnp.asarray(fold_vector(g, op.layout))
# the raised limit must ride the compile request: plain jax.jit never
# consults TPU_COMPILER_OPTIONS (only compile_lowered merges it)
fn = compile_lowered(jax.jit(op.apply_cg).lower(b),
                     {'xla_tpu_scoped_vmem_limit_kib': '49152'})
y = fn(b)
jax.block_until_ready(y)
print('DEG7PROBE:', float(jnp.linalg.norm(y)))
"""
    rc, out = run_py(code, 1500)
    tail = [ln for ln in out.splitlines() if ln.startswith("DEG7PROBE")]
    # on failure keep the full tail: the Mosaic diagnostic IS the result
    log(f"P_DEG7 rc={rc}: " + (tail[-1] if tail else out))


STAGES["deg7probe"] = _deg7_probe

if __name__ == "__main__":
    wanted = sys.argv[1:] or list(STAGES)
    unknown = [s for s in wanted if s not in STAGES]
    if unknown:
        print(f"unknown stage(s) {unknown}; valid: {list(STAGES)}",
              file=sys.stderr)
        sys.exit(2)
    for name in wanted:
        log(f"=== stage {name}")
        STAGES[name]()
