#!/usr/bin/env python
"""One process of the 2-process multi-controller CI lane — the true
analogue of the reference CI's `mpirun -n 2` job (.github/workflows/
ci.yml), which virtual-device tests cannot exercise: every virtual-device
suite runs ONE controller, so jax.distributed.initialize, the gloo CPU
collectives, cross-PROCESS ppermute/psum, and the cross-host timer
allgather (utils.timing.aggregated_timings) never execute there.

Launched once per process by tests/test_multihost.py (or by hand, see
below) with the coordinator env vars set; each process contributes ONE
CPU device, joins via utils.multihost.maybe_initialize, runs the golden
sharded config (2197 dofs at degree 3 — the config where serial and
sharded mesh sizings provably coincide, scripts/check_output.py) through
the distributed kron CG driver over the 2-device grid, max-reduces the
timer table across the processes, and prints one RESULT line. The parent
asserts both processes print the SAME y_norm and that it matches a
serial single-process reference to f64 reduction tolerance.

Manual launch (two shells or one with &):

    JAX_PLATFORMS=cpu JAX_COORDINATOR_ADDRESS=127.0.0.1:29511 \
    JAX_NUM_PROCESSES=2 JAX_PROCESS_ID=0 python scripts/multihost_smoke.py &
    JAX_PLATFORMS=cpu JAX_COORDINATOR_ADDRESS=127.0.0.1:29511 \
    JAX_NUM_PROCESSES=2 JAX_PROCESS_ID=1 python scripts/multihost_smoke.py
"""
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# Pin the CPU platform WITHOUT the virtual-device multiplication the
# test conftest exports: each controller must contribute exactly one
# device, or the 2-device grid would land entirely on process 0.
os.environ["JAX_PLATFORMS"] = "cpu"

from bench_tpu_fem.utils.hermetic import force_host_cpu_devices  # noqa: E402

force_host_cpu_devices(1)

import jax  # noqa: E402

# gloo is the jaxlib-bundled cross-process CPU collectives backend (the
# MPI analogue); must be selected before the backend initialises
jax.config.update("jax_cpu_collectives_implementation", "gloo")

from bench_tpu_fem.utils.multihost import maybe_initialize  # noqa: E402


def main() -> int:
    assert maybe_initialize(), (
        "multihost env vars not set — launch via tests/test_multihost.py "
        "or the manual command in the module docstring"
    )
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from bench_tpu_fem.bench.driver import BenchConfig, BenchmarkResults
    from bench_tpu_fem.dist.driver import run_distributed
    from bench_tpu_fem.utils.timing import aggregated_timings

    cfg = BenchConfig(ndofs_global=2197, degree=3, qmode=0, float_bits=64,
                      nreps=10, use_cg=True, ndevices=2)
    res = BenchmarkResults(nreps=cfg.nreps)
    run_distributed(cfg, res, jnp.float64)

    # the cross-host timer allgather: max-reduces the per-process timer
    # registries (the reference's MPI_MAX list_timings table) — raises if
    # the phase-name digests diverge across the two processes
    agg = aggregated_timings()
    assert agg, "timer registry empty — the driver stopped timing phases"

    print(f"RESULT pid={jax.process_index()} ynorm={res.ynorm!r} "
          f"unorm={res.unorm!r} ncells={res.ncells_global} "
          f"ntimers={len(agg)} extra={res.extra}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
