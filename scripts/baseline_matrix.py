#!/usr/bin/env python
"""Measure the full BASELINE.md configuration matrix on the available chip
and write a JSON artifact (BASELINE_MATRIX_r*.json):

- Q3 CG at the flagship size and at max-HBM size (the reference's Q3-300M
  config: degree 3, qmode 1, CG x1000; published 4.02 GDoF/s/GPU on GH200,
  examples/Q3-300M.json)
- Q6 CG at max fitting size (reference Q6-500M: degree 6, qmode 1;
  published 4.40 GDoF/s/GPU, examples/Q6-500M.json)
- operator-action degree sweep Q1..Q7 (reference README.md:176-179)
- perturbed-geometry Q3 CG (the general-geometry kernel class)

All f32 (TPU-native width; the reference numbers are f64 on GPUs with
native f64 — see README 'Precision policy'). Usage:

    python scripts/baseline_matrix.py [out.json]
"""

import json
import os
import sys
import time

# runnable as `python scripts/baseline_matrix.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = {3: 4.02, 6: 4.40}  # published per-GPU GDoF/s (Q3-300M / Q6-500M)


def run_cfg(**kw):
    from bench_tpu_fem.bench.driver import BenchConfig, run_benchmark

    cfg = BenchConfig(**kw)
    t0 = time.time()
    res = run_benchmark(cfg)
    out = {
        "config": {k: getattr(cfg, k) for k in (
            "ndofs_global", "degree", "qmode", "float_bits", "nreps",
            "use_cg", "geom_perturb_fact", "backend", "f64_impl",
        )},
        "ndofs_global": res.ndofs_global,
        "gdof_per_second": round(res.gdof_per_second, 4),
        "mat_free_time_s": round(res.mat_free_time, 3),
        "unorm": res.unorm,
        "ynorm": res.ynorm,
        "backend": res.extra.get("backend"),
        "cg_engine": res.extra.get("cg_engine"),
        "geom": res.extra.get("geom"),
        "wall_s": round(time.time() - t0, 1),
    }
    base = BASE.get(cfg.degree)
    if base and cfg.use_cg:
        out["vs_baseline_per_gpu"] = round(res.gdof_per_second / base, 4)
    return out


def try_cfg(results, name, **kw):
    try:
        results[name] = run_cfg(**kw)
        print(name, "->", json.dumps(results[name]), flush=True)
    except Exception as e:
        results[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
        print(name, "FAILED:", results[name]["error"], flush=True)


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "BASELINE_MATRIX.json"
    results = {}

    # Q3 flagship size (same as bench.py)
    try_cfg(results, "q3_cg_12.5M", ndofs_global=12_500_000, degree=3,
            qmode=1, float_bits=32, nreps=1000, use_cg=True)
    # Q3 at large sizes, up to the reference's Q3-300M per-device count.
    # Round 3 hit an XLA VMEM stack-allocation compile failure above ~130M
    # ("allocating on stack for ... f32[667,670,670]"); the fused kron CG
    # engine replaces those whole-vector fusions with pallas kernels plus
    # one elementwise+reduce pass — each size below records success or the
    # verbatim failure.
    try_cfg(results, "q3_cg_100M", ndofs_global=100_000_000, degree=3,
            qmode=1, float_bits=32, nreps=100, use_cg=True)
    try_cfg(results, "q3_cg_128M", ndofs_global=128_000_000, degree=3,
            qmode=1, float_bits=32, nreps=100, use_cg=True)
    try_cfg(results, "q3_cg_200M", ndofs_global=200_000_000, degree=3,
            qmode=1, float_bits=32, nreps=50, use_cg=True)
    try_cfg(results, "q3_cg_300M", ndofs_global=300_000_000, degree=3,
            qmode=1, float_bits=32, nreps=50, use_cg=True)
    # Q6 at reference scale (Q6-500M is 500M/GPU on 120 GB GH200): push
    # degree 6 to the largest size this chip's HBM fits — the kron path
    # needs no geometry tensor, ~6 vectors + setup, so ~128-500M is the
    # candidate range on 16 GB; each size records success or the
    # verbatim HBM/compile ceiling (VERDICT r4 item 2's done-criterion)
    try_cfg(results, "q6_cg_64M", ndofs_global=64_000_000, degree=6,
            qmode=1, float_bits=32, nreps=200, use_cg=True)
    try_cfg(results, "q6_cg_128M", ndofs_global=128_000_000, degree=6,
            qmode=1, float_bits=32, nreps=100, use_cg=True)
    try_cfg(results, "q6_cg_200M", ndofs_global=200_000_000, degree=6,
            qmode=1, float_bits=32, nreps=50, use_cg=True)
    try_cfg(results, "q6_cg_300M", ndofs_global=300_000_000, degree=6,
            qmode=1, float_bits=32, nreps=30, use_cg=True)
    try_cfg(results, "q6_cg_400M", ndofs_global=400_000_000, degree=6,
            qmode=1, float_bits=32, nreps=30, use_cg=True)
    try_cfg(results, "q6_cg_500M", ndofs_global=500_000_000, degree=6,
            qmode=1, float_bits=32, nreps=20, use_cg=True)
    try_cfg(results, "q6_cg_12.5M", ndofs_global=12_500_000, degree=6,
            qmode=1, float_bits=32, nreps=1000, use_cg=True)
    # Operator action sweep Q1..Q7 (uniform mesh, qmode 1 except degree 1)
    for p in range(1, 8):
        try_cfg(results, f"action_q{p}_12.5M", ndofs_global=12_500_000,
                degree=p, qmode=(1 if p >= 2 else 0), float_bits=32,
                nreps=400, use_cg=False)
    # Perturbed-geometry CG (general-geometry kernel class); degree 4 runs
    # the forced-corner folded path (full 128-lane blocks fit only with
    # in-kernel geometry — ops.folded.resolve_pallas_geom)
    try_cfg(results, "q3_cg_perturbed_12.5M", ndofs_global=12_500_000,
            degree=3, qmode=1, float_bits=32, nreps=1000, use_cg=True,
            geom_perturb_fact=0.2)
    try_cfg(results, "q4_cg_perturbed_12.5M", ndofs_global=12_500_000,
            degree=4, qmode=1, float_bits=32, nreps=500, use_cg=True,
            geom_perturb_fact=0.2)
    # degrees 5-6 join the Pallas path via the plane-streamed corner
    # form under the raised per-compile scoped-VMEM limit
    # (ops.folded.pallas_plan) — coverage of the general-geometry path
    # at the reference's second headline degree
    try_cfg(results, "q5_cg_perturbed_12.5M", ndofs_global=12_500_000,
            degree=5, qmode=1, float_bits=32, nreps=500, use_cg=True,
            geom_perturb_fact=0.2)
    try_cfg(results, "q6_cg_perturbed_12.5M", ndofs_global=12_500_000,
            degree=6, qmode=1, float_bits=32, nreps=300, use_cg=True,
            geom_perturb_fact=0.2)
    # f64-class strategies side by side (TPUs have no f64 units):
    # XLA software emulation vs double-float f32 pairs, now through the
    # fused df delay-ring engine (ops.kron_cg_df) at benchmark sizes —
    # the r5 headline axis (vs_baseline_per_gpu is against the SAME
    # published f64 numbers, so these rows are the apples-to-apples
    # comparison)
    try_cfg(results, "q3_cg_f64_emulated_2M", ndofs_global=2_000_000,
            degree=3, qmode=1, float_bits=64, nreps=50, use_cg=True)
    try_cfg(results, "q3_cg_f64_df32_2M", ndofs_global=2_000_000,
            degree=3, qmode=1, float_bits=64, nreps=50, use_cg=True,
            f64_impl="df32")
    try_cfg(results, "q3_cg_f64_df32_12.5M", ndofs_global=12_500_000,
            degree=3, qmode=1, float_bits=64, nreps=200, use_cg=True,
            f64_impl="df32")
    try_cfg(results, "q3_cg_f64_df32_100M", ndofs_global=100_000_000,
            degree=3, qmode=1, float_bits=64, nreps=50, use_cg=True,
            f64_impl="df32")
    try_cfg(results, "q3_cg_f64_df32_300M", ndofs_global=300_000_000,
            degree=3, qmode=1, float_bits=64, nreps=30, use_cg=True,
            f64_impl="df32")
    try_cfg(results, "q6_cg_f64_df32_12.5M", ndofs_global=12_500_000,
            degree=6, qmode=1, float_bits=64, nreps=100, use_cg=True,
            f64_impl="df32")

    import jax

    doc = {
        "note": ("single-chip f32 measurements vs the reference's published "
                 "f64 per-GPU numbers (64x GH200): Q3 4.02, Q6 4.40 GDoF/s"),
        "device": str(jax.devices()[0].device_kind),
        "results": results,
    }
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=1)
    print("wrote", out_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
