#!/usr/bin/env python
"""Weak-scaling harness: sweep the device mesh at FIXED local DoFs and
measure the communication-overlapped sharded CG engines A/B against the
synchronous forms (ISSUE 7, ROADMAP item 5 — the paper's own scaling
axis: one rank per device, ghost exchange + allreduce per iteration,
GDoF/s at billions of global DoFs).

Per sweep point (device count d, dshape = factor_devices(d), global
dofs = local_dofs * d) the script builds the sharded f32 kron operator,
runs CG with `overlap` off and on (engine forms `halo`/`ext2d` vs
`halo_overlap`/`ext2d_overlap`), and journals one `weak_scaling` record
each:

    {"event": "weak_scaling", "round": ..., "devices": d,
     "dshape": [...], "ndofs_global": ..., "local_dofs": ...,
     "degree": ..., "nreps": ..., "overlap": bool, "engine_form": ...,
     "gdof_s": ..., "elapsed_s": ..., "ynorm": ...,
     "collectives_per_iter": {"psum": ..., "ppermute": ..., ...},
     "backend": "cpu"|"tpu", "measured": "cpu-interpret"|"hardware"}

The per-iteration collective counts come from a TRACE-level walk of the
CG loop body (analysis.capture.loop_collective_counts) — the overlapped
form must show exactly ONE psum per iteration, the synchronous form two.
That invariant plus overlap-vs-sync solution parity is what the CPU lane
(--smoke, also launched 2-process over gloo by tests/test_multihost.py)
proves today; the same script on a TPU pod is the armed `scale` agenda
stage (GDoF/s columns become hardware evidence the moment the tunnel
lives — until then every CPU number is labelled `cpu-interpret`, never a
throughput claim).

Multihost: launch one process per host with the standard coordinator env
vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) and
the sweep runs over the global device set; every process prints the same
ynorm (asserted by the gloo CI lane).
"""
import argparse
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

MULTIHOST = bool(os.environ.get("JAX_COORDINATOR_ADDRESS"))
if MULTIHOST:
    # one device per controller (mirrors scripts/multihost_smoke.py)
    os.environ["JAX_PLATFORMS"] = "cpu"
    from bench_tpu_fem.utils.hermetic import force_host_cpu_devices

    force_host_cpu_devices(1)

import jax  # noqa: E402

if MULTIHOST:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

from bench_tpu_fem.utils.multihost import maybe_initialize  # noqa: E402

maybe_initialize()

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from bench_tpu_fem.analysis.capture import loop_collective_counts  # noqa: E402
from bench_tpu_fem.dist.kron import (  # noqa: E402
    build_dist_kron,
    make_kron_rhs_fn,
    make_kron_sharded_fns,
)
from bench_tpu_fem.dist.kron_cg import (  # noqa: E402
    _is_x_only,
    supports_dist_kron_overlap,
)
from bench_tpu_fem.dist.mesh import (  # noqa: E402
    compute_mesh_size_sharded,
    factor_devices,
    make_device_grid,
)
from bench_tpu_fem.elements.tables import build_operator_tables  # noqa: E402
from bench_tpu_fem.harness.journal import (  # noqa: E402
    Journal,
    default_journal_path,
)
from bench_tpu_fem.mesh.dofmap import global_ndofs  # noqa: E402


def device_sweep(max_devices: int | None) -> list[int]:
    """Power-of-two device counts up to the available (or capped) mesh."""
    avail = len(jax.devices())
    cap = min(avail, max_devices) if max_devices else avail
    out, d = [], 1
    while d <= cap:
        out.append(d)
        d *= 2
    if out[-1] != cap and cap not in out:
        out.append(cap)
    return out


def run_point(degree: int, local_dofs: int, d: int, nreps: int,
              overlap: bool, journal, round_tag: str, measured: str):
    dshape = factor_devices(d)
    dgrid = make_device_grid(d, dshape=dshape)
    ndofs_req = local_dofs * d
    n = compute_mesh_size_sharded(ndofs_req, degree, dshape)
    op = build_dist_kron(n, dgrid, degree, 1, dtype=jnp.float32)
    t = build_operator_tables(degree, 1, "gll")
    b = jax.jit(make_kron_rhs_fn(op, dgrid, t))()
    # A/B the FUSED engine forms (the interesting comparison); on CPU the
    # kernels run interpret mode — parity/collective evidence, not speed.
    if overlap and not supports_dist_kron_overlap(op):
        # ISSUE-7 contract: a gated overlap arm records WHY (otherwise a
        # hardware sweep's missing A/B points are undiagnosable). The
        # plan-level predicate fails on exactly two grounds:
        from bench_tpu_fem.dist.kron_cg import supports_dist_kron_engine

        reason = ("engine ring past every scoped-VMEM tier (or non-f32)"
                  if not supports_dist_kron_engine(op) else
                  "ext2d shard past the whole-vector fusion wall "
                  "(PALLAS_UPDATE_MIN_DOFS); sync engine serves")
        gate = {"event": "weak_scaling_gate", "round": round_tag,
                "devices": d, "dshape": list(dshape),
                "overlap_gate_reason": reason}
        if journal is not None and jax.process_index() == 0:
            journal.append(gate)
        print("WEAK-GATED", json.dumps(gate, sort_keys=True), flush=True)
        return None
    _, cg_fn, norm_fn = make_kron_sharded_fns(op, dgrid, nreps,
                                              engine=True, overlap=overlap)
    counts = loop_collective_counts(cg_fn, b, op)
    if jax.default_backend() == "tpu":
        # raised-tier one-kernel rings need the per-compile scoped-VMEM
        # request, exactly like the dist driver's compile
        from bench_tpu_fem.dist.kron_cg import dist_kron_engine_plan
        from bench_tpu_fem.utils.compilation import (
            compile_lowered,
            scoped_vmem_options,
        )

        fn = compile_lowered(
            jax.jit(cg_fn).lower(b, op),
            scoped_vmem_options(dist_kron_engine_plan(op)[1]))
    else:
        fn = jax.jit(cg_fn)
    x = fn(b, op)  # warm-up: compile + first run
    jax.block_until_ready(x)
    t0 = time.perf_counter()
    x = fn(b, op)
    jax.block_until_ready(x)
    elapsed = time.perf_counter() - t0
    ynorm = float(np.asarray(jax.jit(norm_fn)(x))[0])
    ndofs = global_ndofs(n, degree)
    form = ("halo" if _is_x_only(op) else "ext2d") + (
        "_overlap" if overlap else "")
    gdof_s = ndofs * nreps / (1e9 * elapsed)
    rec = {
        "event": "weak_scaling", "round": round_tag, "devices": d,
        "dshape": list(dshape), "ndofs_global": ndofs,
        "local_dofs": ndofs // d, "degree": degree, "nreps": nreps,
        "overlap": overlap, "engine_form": form,
        "gdof_s": gdof_s,
        "elapsed_s": elapsed, "per_iter_s": elapsed / max(nreps, 1),
        "ynorm": ynorm,
        "collectives_per_iter": {k: int(v) for k, v in counts.items()},
        "backend": jax.default_backend(),
        "measured": measured,
    }
    from bench_tpu_fem.obs.roofline import roofline_stamp

    # roofline placement for the sweep point (evidence-labelled: a CPU
    # sweep's fraction is a design aid, not a hardware claim)
    roofline_stamp(rec, degree=degree, qmode=1, precision="f32",
                   backend="kron", geom="uniform", use_cg=True,
                   gdof_s=gdof_s, platform=jax.default_backend())
    if journal is not None and jax.process_index() == 0:
        journal.append(rec)
    print("WEAK", json.dumps(rec, sort_keys=True), flush=True)
    return rec, x


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--local-dofs", type=int, default=2_000_000,
                   help="dofs per device (held fixed across the sweep)")
    p.add_argument("--degree", type=int, default=3)
    p.add_argument("--nreps", type=int, default=100)
    p.add_argument("--max-devices", type=int, default=0,
                   help="cap the sweep (0 = all available devices)")
    p.add_argument("--overlap", default="both",
                   choices=["both", "on", "off"])
    p.add_argument("--round", default=os.environ.get("MEASURE_ROUND",
                                                     "r06"))
    p.add_argument("--no-journal", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="CPU proving lane: tiny config, overlap A/B "
                        "parity + exactly-one-psum-per-iteration "
                        "assertions (what CI runs; also 2-process gloo)")
    args = p.parse_args()

    if args.smoke:
        args.local_dofs = min(args.local_dofs, 1500)
        args.nreps = min(args.nreps, 4)

    on_tpu = jax.default_backend() == "tpu"
    measured = "hardware" if on_tpu else "cpu-interpret"
    journal = None
    if not args.no_journal and jax.process_index() == 0:
        journal = Journal(default_journal_path(ROOT, args.round))

    sweep = device_sweep(args.max_devices or None)
    if args.smoke:
        sweep = sweep[-1:]  # one point: the full available mesh
    rc = 0
    for d in sweep:
        recs = {}
        for overlap in (False, True):
            if args.overlap == "on" and not overlap:
                continue
            if args.overlap == "off" and overlap:
                continue
            out = run_point(args.degree, args.local_dofs, d, args.nreps,
                            overlap, journal, args.round, measured)
            if out is not None:
                recs[overlap] = out
        if recs.get(False) and recs.get(True):
            # Per-iteration collective-vs-compute share attribution for
            # the overlap A/B (ISSUE 8): the overlap form hides the
            # collective behind the kernel, so the sync-minus-overlap
            # per-iteration delta is an A/B-derived estimate of the
            # collective share of a synchronous iteration. On CPU the
            # kernels run interpret mode — the share is labelled with
            # the sweep's `measured` tag and is never a hardware claim.
            sync_r, ovl_r = recs[False][0], recs[True][0]
            ps, po = sync_r["per_iter_s"], ovl_r["per_iter_s"]
            attr = {
                "event": "weak_scaling_attribution", "round": args.round,
                "devices": d, "dshape": sync_r["dshape"],
                "sync_per_iter_s": ps, "overlap_per_iter_s": po,
                "collective_share_of_sync_iter": (
                    max(ps - po, 0.0) / ps if ps > 0 else 0.0),
                "sync_collectives_per_iter":
                    sync_r["collectives_per_iter"],
                "overlap_collectives_per_iter":
                    ovl_r["collectives_per_iter"],
                "measured": measured + "-ab-derived",
            }
            if journal is not None and jax.process_index() == 0:
                journal.append(attr)
            print("WEAK-ATTR", json.dumps(attr, sort_keys=True),
                  flush=True)
        if args.smoke and recs.get(False) and recs.get(True):
            (sync_r, xs), (ovl_r, xo) = recs[False], recs[True]
            ps = sync_r["collectives_per_iter"].get("psum", 0) + \
                sync_r["collectives_per_iter"].get("psum2", 0)
            po = ovl_r["collectives_per_iter"].get("psum", 0) + \
                ovl_r["collectives_per_iter"].get("psum2", 0)
            # full-solution parity (not just norms): the overlap
            # recurrence's f32 envelope at smoke budgets
            rel = float(jnp.linalg.norm((xo - xs).ravel())
                        / jnp.linalg.norm(xs.ravel()))
            ok = po == 1 and ps == 2 and rel < 5e-6
            print(f"SMOKE devices={d} psum_sync={ps} psum_overlap={po} "
                  f"solution_rel={rel:.3e} -> {'OK' if ok else 'FAIL'}",
                  flush=True)
            if not ok:
                rc = 1
        if MULTIHOST and recs:
            # per-process RESULT line: the gloo lane asserts every
            # controller computed identical global norms
            any_rec = next(r for r, _ in recs.values() if r)
            print(f"RESULT pid={jax.process_index()} "
                  f"ynorm={any_rec['ynorm']!r} devices={d}", flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
