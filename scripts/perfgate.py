#!/usr/bin/env python
"""Perfgate snapshot collector (ISSUE 10): one CPU-pinned pass over the
stack's deterministic observability surface, folded into a JSON snapshot
`python -m bench_tpu_fem.obs gate` compares against the pinned baseline.

    JAX_PLATFORMS=cpu python scripts/perfgate.py --out /tmp/cur.json
    python -m bench_tpu_fem.obs gate --current /tmp/cur.json \
        --baseline PERFGATE_BASELINE.json

Three in-process measurements (no subprocesses, no network):

  * **bench**: a small traced single-chip CG run with convergence
    capture on and ``--timing-reps`` > 1 — contributes the record
    contract (roofline/phase/timing/memory/convergence stamps), the
    per-rep wall distribution (advisory Mann-Whitney input) and the
    convergence block.
  * **dist**: the same problem on 2 virtual CPU devices with the span
    tracer enabled — contributes the trace-level
    ``collectives_per_iter`` counts (the overlapped-CG one-psum
    contract's counter: noise-free, gates hard) and a second timing
    distribution.
  * **serve**: an in-process broker round (warmup + ramped requests,
    request tracing armed) — contributes compile counts,
    request-weighted cache hit-rate, shed/failed counts, the SLO
    burn-rate state from the journaled request lifecycles, and the
    ISSUE-15 reqtrace counters (trace-complete rate pinned 1.0,
    anomaly count pinned 0, queue-share-of-p99 presence-gated with an
    advisory value) with the live /metrics block asserted EQUAL to the
    journal's fold_reqtrace replay.
  * **fleet** (ISSUE 13): a 2-lane fleet with a shared artifact store
    on a PINNED hang-and-rebalance schedule — contributes the
    deterministic steal count, routing-weighted affinity hit-rate,
    warm-load counts, the standby replica's recompile count (== 0, the
    shared-artifact acceptance) and the journal's exactly-once ledger
    (lost/duplicates == 0).
  * **sdc** (ISSUE 14): detection counters on a deterministic injected
    bit-flip schedule — the serve retire-time audit at f32/f64/df32
    (clean lanes audited for false positives, a flipped lane for
    detection) plus the driver's boundary-audited checkpointed loop
    (clean run zero detections; injected run detects, rolls back to
    the durable snapshot and finishes BITWISE equal to the clean run).
    detected == injected, missed == 0, false_positives == 0 pin in the
    baseline, and `sdc_detected` sits in the HIGHER table so a
    suppressed detector gates rc 1.
  * **overload** (ISSUE 18): deadline/hedge/brownout counters on a
    pinned 2-lane schedule — predictive admission refuses impossible
    budgets EARLY (deadline_exceeded_early gates HIGHER; a suppressed
    admission controller is a regression), late misses and hedge
    duplicates across the speculative pair pin at 0 (LOWER), exactly
    one hedge fires and wins through the claim CAS, and the brownout
    ladder steps once under sustained burn then recovers on hysteresis
    (brownout_steps gates HIGHER — the CI probe zeroes it).
  * **forms** (ISSUE 20): the operator zoo's device-action-vs-CSR
    parity flags (mass/helmholtz/varkappa/heat on the fixed-seed
    perturbed problem, pinned True) and a 200-step temporally-
    correlated heat stream served warm-vs-cold through a 2-lane fleet
    — ``heat_warm_start_iters_saved`` gates HIGHER (the CI
    ``BENCH_SUPPRESS_WARMSTART=1`` probe zeroes it; the collector
    itself refuses zero savings) over a closed exactly-once ledger.

The counters land in ``snapshot["counters"]`` (the hard gate);
wall-clock distributions stay inside the per-section ``timing`` blocks
(advisory). Deterministic on CPU for this pinned workload: the same
code must produce the same counters — a drift IS the regression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", required=True, help="snapshot JSON path")
    p.add_argument("--ndofs", type=int, default=4096)
    p.add_argument("--nreps", type=int, default=20)
    p.add_argument("--timing-reps", type=int, default=5)
    p.add_argument("--requests", type=int, default=16,
                   help="serve requests fired through the broker")
    p.add_argument("--pcg-nreps", type=int, default=150,
                   help="iteration budget of the precond legs (must "
                        "cross rtol 1e-6 on the fixed-seed perturbed "
                        "problem for both arms)")
    p.add_argument("--slo-objective", type=float, default=5.0,
                   help="latency objective for the serve SLO fold "
                        "(generous: CPU solves are slow; the gate is "
                        "on counters, the SLO state is evidence)")
    args = p.parse_args(argv)

    # hermetic CPU with 2 virtual devices BEFORE any backend init: the
    # dist leg needs a device grid, and a wedged TPU tunnel must never
    # hang the gate
    from bench_tpu_fem.utils.hermetic import force_host_cpu_devices

    force_host_cpu_devices(2)
    import jax

    # x64 on (the test suite's configuration): the sdc leg audits an
    # f64 serve solver; f32/df32 paths pin their dtypes explicitly and
    # are unaffected
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from bench_tpu_fem.bench.driver import (
        BenchConfig,
        BenchmarkResults,
        run_benchmark,
    )
    from bench_tpu_fem.dist.driver import run_distributed
    from bench_tpu_fem.obs import trace as obs_trace
    from bench_tpu_fem.obs.regress import check_record_contract

    tracer = obs_trace.enable(fresh=True)

    # -- bench leg: record contract + timing distribution + convergence
    cfg = BenchConfig(ndofs_global=args.ndofs, degree=3, qmode=1,
                      float_bits=32, nreps=args.nreps, use_cg=True,
                      timing_reps=args.timing_reps, convergence=True)
    res = run_benchmark(cfg)
    bench = {k: res.extra.get(k) for k in (
        "roofline", "phase_share", "phase_s", "timing",
        "peak_memory_bytes", "convergence", "time_to_rtol_s",
        "cg_engine_form")}
    bench["gdof_per_second"] = res.gdof_per_second

    # -- dist leg: trace-level collective counts (the hard counter)
    dcfg = BenchConfig(ndofs_global=args.ndofs, degree=3, qmode=1,
                       float_bits=32, nreps=args.nreps, use_cg=True,
                       ndevices=2, timing_reps=args.timing_reps)
    dres = BenchmarkResults(nreps=dcfg.nreps)
    run_distributed(dcfg, dres, jnp.float32)
    dist = {k: dres.extra.get(k) for k in (
        "timing", "collectives_per_iter", "cg_engine_form",
        "per_iter_s")}

    # -- precond legs (ISSUE 11): the fixed-seed perturbed-geometry
    # degree-3 problem, bare vs Jacobi-PCG, convergence capture on —
    # iterations-to-1e-6 are DETERMINISTIC counters on CPU (properties
    # of the arithmetic, not the clock) and gate hard: an iteration
    # increase on either arm is a solver regression, and Jacobi must
    # stay strictly below bare (the ISSUE-11 acceptance, collected
    # fresh every gate run). time_to_rtol_s rides as evidence
    # (advisory: wall-clock never gates on shared CI).
    pc_iters = {}
    pcg = {}
    for kind in ("none", "jacobi"):
        pcfg = BenchConfig(ndofs_global=args.ndofs, degree=3, qmode=1,
                           float_bits=32, nreps=args.pcg_nreps,
                           use_cg=True, geom_perturb_fact=0.2,
                           convergence=True, precond=kind)
        pres = run_benchmark(pcfg)
        conv = pres.extra.get("convergence") or {}
        pc_iters[kind] = (conv.get("iters_to_rtol") or {}).get("1e-06")
        pcg[kind] = {
            "iters_to_rtol": conv.get("iters_to_rtol"),
            "time_to_rtol_s": pres.extra.get("time_to_rtol_s"),
            "precond": pres.extra.get("precond"),
            "precond_cost": (pres.extra.get("roofline") or {}).get(
                "precond_cost"),
        }

    # -- s-step leg: 2 virtual devices, s=2 — the below-one-reduction
    # contract as a hard counter (reductions per loop body / s)
    scfg = BenchConfig(ndofs_global=args.ndofs, degree=3, qmode=1,
                       float_bits=32, nreps=args.nreps, use_cg=True,
                       ndevices=2, s_step=2)
    sres = BenchmarkResults(nreps=scfg.nreps)
    run_distributed(scfg, sres, jnp.float32)
    ss_counts = sres.extra.get("collectives_per_iter") or {}
    sstep = {"collectives_per_iter": ss_counts,
             "s_step": sres.extra.get("s_step"),
             "fallback": sres.extra.get("s_step_fallback_reason")}

    # -- serve leg: broker round with journaled lifecycles + SLO
    from bench_tpu_fem.obs.regress import fold_slo
    from bench_tpu_fem.serve.broker import Broker
    from bench_tpu_fem.serve.cache import ExecutableCache
    from bench_tpu_fem.serve.engine import SolveSpec
    from bench_tpu_fem.serve.metrics import Metrics

    journal_path = args.out + ".serve.jsonl"
    try:
        os.unlink(journal_path)
    except OSError:
        pass
    cache = ExecutableCache()
    metrics = Metrics(journal_path, slo_objective_s=args.slo_objective)
    # reqtrace armed (ISSUE 15): the pinned schedule's trace-complete
    # rate, anomaly count and queue-share-of-p99 join the gated
    # counters, and the journal fold must reproduce the live block
    broker = Broker(cache, metrics, queue_max=64, nrhs_max=4,
                    window_s=0.05, reqtrace=True)
    spec = SolveSpec(degree=3, ndofs=4000, nreps=30)
    broker.warmup([spec])
    compiles_after_warmup = cache.stats()["compiles"]
    pendings = []
    import time as _time

    for i in range(args.requests):
        pendings.append(broker.submit(spec, scale=float(2 ** (i % 3))))
        _time.sleep(0.01)  # ramped arrivals: spans solve boundaries
    results = [broker.wait(pr, 120.0) for pr in pendings]
    # the continuous batch's serve_batch record (which carries the
    # hit/miss accounting) lands AFTER the last retire answers the
    # final wait — settle before snapshotting or the hit-rate counter
    # reads racy
    deadline = _time.monotonic() + 10.0
    while (metrics.cache_hit_requests + metrics.cache_miss_requests
           < args.requests and _time.monotonic() < deadline):
        _time.sleep(0.05)
    snap = metrics.snapshot(cache_stats=cache.stats())
    broker.shutdown()
    from bench_tpu_fem.harness.journal import read_records

    records, corrupt = read_records(journal_path)
    from bench_tpu_fem.obs.reqtrace import fold_reqtrace

    rq_live = snap.get("reqtrace") or {}
    rq_fold = fold_reqtrace(records)
    serve = {
        "ok_responses": sum(1 for r in results if r.get("ok")),
        "metrics": snap,
        "slo": fold_slo(records, objective_s=args.slo_objective),
        "reqtrace_fold": {k: v for k, v in rq_fold.items()
                          if k != "exemplars"},
        "corrupt_lines": len(corrupt),
    }

    # -- fleet leg (ISSUE 13): deterministic routing/steal/warm counters.
    # A 2-lane fleet with a shared artifact store and the balancer on
    # MANUAL (balance_interval_s=0): lane0 warms + publishes one spec;
    # its first solve is scripted to hang (FaultySolveHook) while 6 more
    # requests queue behind it, so ONE manual rebalance pass moves
    # EXACTLY (6-0)//2 = 3 requests to lane1, which warm-loads the
    # executable from the store (zero compiles). Then a STANDBY fleet on
    # the same store serves its first request — the warm-replica
    # recompiles == 0 acceptance counter. All counts are deterministic
    # functions of this pinned schedule, so they gate hard.
    import shutil

    import bench_tpu_fem.serve.engine as serve_engine
    from bench_tpu_fem.harness.faults import FaultySolveHook
    from bench_tpu_fem.serve.artifacts import ArtifactStore
    from bench_tpu_fem.serve.fleet import FleetDispatcher
    from bench_tpu_fem.serve.recovery import verify_exactly_once

    fleet_journal = args.out + ".fleet.jsonl"
    artdir = args.out + ".artifacts"
    for path in (fleet_journal,):
        try:
            os.unlink(path)
        except OSError:
            pass
    shutil.rmtree(artdir, ignore_errors=True)
    fspec = SolveSpec(degree=3, ndofs=4000, nreps=30)
    store = ArtifactStore(artdir)
    primary = FleetDispatcher(2, journal_path=fleet_journal,
                              artifacts=store, queue_max=64, nrhs_max=4,
                              window_s=0.01, balance_interval_s=0)
    primary.warmup([fspec])
    serve_engine.FAULT_HOOK = FaultySolveHook(["hang"], hang_s=1.5)
    try:
        fpend = [primary.submit(fspec, scale=1.0)]
        _time.sleep(0.4)  # lane0's worker is inside the hung solve
        fpend += [primary.submit(fspec, scale=float(2 ** (i % 3)))
                  for i in range(6)]
        moved = primary.rebalance_once()
        fouts = [primary.wait(p, 120.0) for p in fpend]
    finally:
        serve_engine.FAULT_HOOK = None
    fsnap = primary.metrics_snapshot()
    primary.shutdown()
    # standby replica: same store, fresh caches — its first matching
    # request must be served from a warm artifact load, zero compiles.
    # Adoption BEFORE traffic is the standby protocol even with nothing
    # outstanding: it hands off the id space, so fresh ids never
    # collide with the dead generation's in the shared journal (the
    # exactly-once ledger's duplicate check would catch exactly that)
    standby = FleetDispatcher(2, journal_path=fleet_journal,
                              artifacts=store, queue_max=64, nrhs_max=4,
                              window_s=0.01, balance_interval_s=0)
    standby.adopt_journal(fleet_journal)
    sout = standby.wait(standby.submit(fspec, scale=2.0), 120.0)
    ssnap = standby.metrics_snapshot()
    standby.shutdown()
    fleet_ledger = verify_exactly_once(fleet_journal)
    fleet_leg = {
        "ok_responses": sum(1 for o in fouts if o.get("ok")),
        "moved": moved,
        "primary": {"fleet": fsnap["fleet"], "cache": fsnap["cache"]},
        "standby": {"ok": bool(sout.get("ok")),
                    "fleet": ssnap["fleet"], "cache": ssnap["cache"]},
        "exactly_once": fleet_ledger,
    }

    # -- sdc leg (ISSUE 14): detection counters on a DETERMINISTIC
    # injected schedule. Two halves: (1) the serve retire-time audit at
    # all three servable precisions — solve a clean 2-lane batch, audit
    # every lane (false positives), then bit-flip lane 0's iterate (the
    # mercurial-core model, harness.faults) and audit again (detection;
    # the untouched batch-mate must stay clean); (2) the driver's
    # boundary-audited checkpointed loop — one clean run (zero
    # detections over every boundary check) and one CHAOS_SDC-injected
    # run whose single detection must roll back to the durable snapshot
    # and finish BITWISE EQUAL to the clean run. detected == injected
    # and false_positives == 0 gate hard; a suppressed detector is the
    # worst regression this subsystem can have (the CI lane probes
    # exactly that).
    from bench_tpu_fem.harness.faults import SdcInjectionHook
    from bench_tpu_fem.serve.engine import build_solver

    sdc_injected = sdc_detected = sdc_falsep = 0
    sdc_serve = {}
    for precision in ("f32", "f64", "df32"):
        pspec = SolveSpec(degree=1, ndofs=2000, nreps=12,
                          precision=precision)
        solver = build_solver(pspec, bucket=2)
        st = solver.cont_init([1.0, 2.0])
        for _ in range(-(-pspec.nreps // solver.iter_chunk)):
            st = solver.cont_step(st)
        clean = [solver.audit_lane(st, lane, sc)
                 for lane, sc in ((0, 1.0), (1, 2.0))]
        sdc_falsep += sum(1 for v in clean if not v["ok"])
        hook = SdcInjectionHook(corrupt_at=[0], lane=0)
        st_bad = hook(pspec, 0, st)
        sdc_injected += 1
        bad = solver.audit_lane(st_bad, 0, 1.0)
        if not bad["ok"]:
            sdc_detected += 1
        mate = solver.audit_lane(st_bad, 1, 2.0)
        sdc_falsep += 0 if mate["ok"] else 1
        sdc_serve[precision] = {
            "clean_drift": [v["drift"] for v in clean],
            "injected_drift": bad["drift"], "envelope": bad["envelope"],
            "detected": not bad["ok"], "mate_clean": mate["ok"]}

    ck_kw = dict(ndofs_global=args.ndofs, degree=2, qmode=1,
                 float_bits=32, nreps=args.nreps, use_cg=True,
                 checkpoint_every=5, sdc_audit=True)
    clean_ck = run_benchmark(BenchConfig(
        **ck_kw, checkpoint_dir=args.out + ".ck.clean"))
    os.environ["CHAOS_SDC"] = f"iter={args.nreps // 2},once=1"
    try:
        inj_ck = run_benchmark(BenchConfig(
            **ck_kw, checkpoint_dir=args.out + ".ck.inj"))
    finally:
        del os.environ["CHAOS_SDC"]
    clean_stamp = clean_ck.extra["sdc"]
    inj_stamp = inj_ck.extra["sdc"]
    sdc_falsep += clean_stamp["detections"]
    sdc_injected += inj_stamp["injected"]
    sdc_detected += inj_stamp["detections"]
    sdc_rollback_bitwise = inj_ck.ynorm == clean_ck.ynorm
    sdc_leg = {"serve": sdc_serve, "driver_clean": clean_stamp,
               "driver_injected": inj_stamp,
               "rollback_bitwise": sdc_rollback_bitwise}

    # -- autotune leg (ISSUE 16): a deterministic CPU sweep persists a
    # tuning DB, then a driver build AND a serve build consume the
    # swept entries — the tuning stamps must read source=db with a
    # registered provenance label, and the hit/fallback deltas pin in
    # the counters (an injected probe that zeroes the hits gates rc 1).
    from bench_tpu_fem.engines.autotune import (
        DB_ENV,
        default_tuning_db,
        reset_default_db,
        run_sweep,
    )
    from bench_tpu_fem.serve.engine import CompiledSolver, SolveSpec

    at_ndofs, at_nreps, at_bucket = 2000, 8, 2
    os.environ[DB_ENV] = args.out + ".tuningdb"
    reset_default_db()
    tdb = default_tuning_db()
    at_spec = SolveSpec(degree=3, ndofs=at_ndofs, nreps=at_nreps)
    sweep = run_sweep(tdb, degree=3, ndofs=at_ndofs, precision="f32",
                      geom="uniform", nrhs_bucket=at_bucket,
                      nreps=at_nreps, round_stamp="r06")
    # driver slice: run once untuned to learn the planned engine form
    # (its stamp records the registered entry-missing reason), seed the
    # sweep winner under the driver's exact executable key, rerun —
    # the second build must consume the entry (source=db)
    from bench_tpu_fem.bench.driver import _exec_cache_key
    from bench_tpu_fem.mesh.sizing import compute_mesh_size

    at_cfg = BenchConfig(ndofs_global=at_ndofs, degree=3, qmode=1,
                         float_bits=32, nreps=at_nreps, use_cg=True)
    res_pre = run_benchmark(at_cfg)
    pre_stamp = res_pre.extra.get("tuning")
    at_key = _exec_cache_key(
        at_cfg, compute_mesh_size(at_ndofs, 3),
        res_pre.extra.get("cg_engine_form", "unfused"), "cg")
    tdb.put(at_key, sweep["winner"], score=sweep["score"],
            label=sweep["label"], engine="kron_fused",
            round_stamp="r06")
    s0 = tdb.stats()
    res_tuned = run_benchmark(at_cfg)
    driver_stamp = res_tuned.extra.get("tuning")
    solver_tuned = CompiledSolver(at_spec, at_bucket)
    serve_stamp = solver_tuned.tuning
    s1 = tdb.stats()
    # the persisted file round-trips: a FRESH process-equivalent load
    # (reset + re-read) must serve the same entries
    reset_default_db()
    tdb2 = default_tuning_db()
    roundtrip_ok = len(tdb2.entries()) == len(tdb.entries()) >= 2
    autotune_leg = {
        "sweep": sweep, "pre_stamp": pre_stamp,
        "driver_stamp": driver_stamp, "serve_stamp": serve_stamp,
        "db_stats": s1, "roundtrip_ok": roundtrip_ok,
    }
    tuning_db_hits = s1["hits"] - s0["hits"]
    tuning_fallbacks = s1["fallbacks"] - s0["fallbacks"]
    # -- bf16 leg (ISSUE 17): the fixed-seed mixed-precision refinement
    # solve must reach f64-class rtol (<= 1e-10) with EVERY hot-loop
    # apply on the bf16-stream operator — the speed ladder's acceptance,
    # pinned as counters: the deterministic outer/inner iteration split
    # (LOWER tables — an increase means the bf16 inner solve got
    # weaker), bf16_parity_ok (HIGHER — the ladder must keep delivering
    # f64-class answers) and the calibrated bf16 envelope's measured
    # clean-drift headroom on a serve audit (HIGHER — a shrink drifts
    # toward false positives). The driver AND the serve build must also
    # consume swept TuningDB entries under bf16 keys (source=db), same
    # contract as the f32 autotune leg above.
    import numpy as _np

    from bench_tpu_fem.serve.engine import spec_cache_key

    bf_db = default_tuning_db()
    bf_cfg = BenchConfig(ndofs_global=at_ndofs, degree=3, qmode=1,
                         float_bits=32, nreps=args.nreps, use_cg=True,
                         precision="bf16-refine", precond="jacobi")
    bf_key = _exec_cache_key(bf_cfg, compute_mesh_size(at_ndofs, 3),
                             "unfused", "cg+refine")
    bf_sweep = run_sweep(bf_db, degree=3, ndofs=at_ndofs,
                         precision="bf16", geom="uniform",
                         nreps=args.nreps, round_stamp="r06",
                         refine=True)
    bf_db.put(bf_key, bf_sweep["winner"], score=bf_sweep["score"],
              label=bf_sweep["label"], engine="bf16_refine",
              round_stamp="r06")
    bf_spec = SolveSpec(degree=3, ndofs=at_ndofs, nreps=40,
                        precision="bf16")
    bf_skey = spec_cache_key(bf_spec, 1)
    bf_db.put(bf_skey, bf_sweep["winner"], score=bf_sweep["score"],
              label=bf_sweep["label"], engine="kron_bf16",
              round_stamp="r06")
    bf_res = run_benchmark(bf_cfg)
    bf_stamp = bf_res.extra["refine"]
    bf_tuning = bf_res.extra.get("tuning")
    bf16_parity_ok = int(bool(bf_stamp["converged"])
                         and bf_stamp["achieved_rel"] <= 1e-10)
    # serve bf16: build consumes its swept key, then a clean lane's
    # retire-time audit measures the calibrated envelope's headroom
    bf_solver = CompiledSolver(bf_spec, 1)
    bf_serve_tuning = bf_solver.tuning
    bf_state = bf_solver.cont_init(_np.ones(bf_solver.bucket))
    for _ in range(10):
        bf_state = bf_solver.cont_step(bf_state)
    bf_audit = bf_solver.audit_lane(bf_state, 0, 1.0)
    bf16_envelope_headroom = round(
        bf_audit["envelope"] / max(bf_audit["drift"], 1e-30), 2)
    bf16_leg = {
        "refine": bf_stamp, "driver_tuning": bf_tuning,
        "serve_tuning": bf_serve_tuning, "sweep": bf_sweep,
        "audit": bf_audit, "parity_ok": bf16_parity_ok,
        "envelope_headroom": bf16_envelope_headroom,
        "time_to_rtol_s": bf_res.extra.get("time_to_rtol_s"),
    }
    del os.environ[DB_ENV]
    reset_default_db()

    # -- overload leg (ISSUE 18): deterministic deadline/hedge/brownout
    # counters on a pinned 2-lane schedule. The predictor is warmed with
    # 4 real solves, then: two impossible-budget submissions MUST shed
    # early at admission (prediction present, p95 >> budget); one
    # straggler-held lane forces exactly one hedge whose speculative
    # copy wins on the healthy lane (the claim CAS makes the ledger
    # duplicate count a hard 0); one queued request expires behind a
    # second held solve and is answered at batch formation without a
    # solve; sustained burn against a tiny objective steps the brownout
    # ladder once, and an aged clock steps it back. Every count is a
    # deterministic function of this schedule.
    from bench_tpu_fem.harness.chaos import install_fault_hook
    from bench_tpu_fem.harness.faults import HeldSolveHook
    from bench_tpu_fem.serve.broker import QueueFull

    ov_journal = args.out + ".overload.jsonl"
    try:
        os.unlink(ov_journal)
    except OSError:
        pass
    ov = FleetDispatcher(
        2, journal_path=ov_journal, queue_max=64, nrhs_max=2,
        window_s=0.02, balance_interval_s=0,
        slo_objective_s=0.01, spill_burn=1e9,
        hedge=True, hedge_budget=1.0, hedge_delay_s=0.05,
        brownout=True, brownout_burn=0.5, brownout_clear_burn=0.25,
        brownout_windows=((30.0, "fast"), (60.0, "slow")))
    ov_spec = SolveSpec(degree=1, ndofs=2000, nreps=12)
    import dataclasses as _dc

    ov_sheds = []
    try:
        ov.warmup([ov_spec])
        for i in range(4):  # predictor evidence: 4 real completions
            ov.wait(ov.submit(ov_spec, float(1 + i)), 120.0)
        doomed = _dc.replace(ov_spec, deadline_s=1e-4)
        for _ in range(2):
            try:
                ov.submit(doomed, 1.0)
            except QueueFull as exc:
                ov_sheds.append({"failure_class": exc.failure_class,
                                 "retry_after_s": exc.retry_after_s})
        # expired-in-queue: answered at batch formation, no solve
        # burned. This phase runs BEFORE the straggler latencies join
        # the per-spec window — admission must predict UNDER the 0.5s
        # budget here (clean warm samples only), then the wall clock
        # expires it while queued behind the held solve.
        hook2 = HeldSolveHook(hold=1, timeout_s=120.0)
        prev_fh = install_fault_hook(hook2)
        try:
            ova2 = ov.submit(ov_spec, 1.0)
            _time.sleep(0.3)
            ovc = ov.submit(_dc.replace(ov_spec, deadline_s=0.5), 1.0)
            _time.sleep(0.7)  # the budget expires while queued
            hook2.release()
            ova2_out = ov.wait(ova2, 120.0)
            ovc_out = ov.wait(ovc, 120.0)
        finally:
            install_fault_hook(prev_fh)
            hook2.release()
        # straggler + hedge: lane 0 held, the queued copy wins on lane 1
        # (the fixed hedge-delay override keeps this phase insensitive
        # to the latency-window pollution the phases above caused)
        hook = HeldSolveHook(hold=1, timeout_s=120.0)
        prev_fh = install_fault_hook(hook)
        try:
            ova = ov.submit(ov_spec, 1.0)
            _time.sleep(0.3)
            ovb = ov.submit(ov_spec, 2.0)
            _time.sleep(0.3)  # past the 0.05s hedge delay
            ov_hedges = ov.hedge_scan()
            ovb_out = ov.wait(ovb, 120.0)
            hook.release()
            ova_out = ov.wait(ova, 120.0)
        finally:
            install_fault_hook(prev_fh)
            hook.release()
        # brownout: every sample violates the tiny objective -> step;
        # the degraded response carries ladder provenance; an aged
        # clock drains the burn windows -> hysteresis recovery
        ov_step = ov.brownout_scan()
        ovd_out = ov.wait(ov.submit(ov_spec, 1.0), 300.0)
        ov_rec = ov.brownout_scan(now=_time.time() + 3600.0)
        ovsnap = ov.metrics_snapshot()
    finally:
        ov.shutdown()
    ov_ledger = verify_exactly_once(ov_journal)
    ov_fleet = ovsnap["fleet"]
    overload_leg = {
        "predictive_sheds": ov_sheds,
        "hedge": {"fired": ov_hedges, "win": ovb_out.get("ok"),
                  "straggler_ok": ova_out.get("ok")},
        "expired_in_queue": {
            "failure_class": ovc_out.get("failure_class"),
            "straggler_ok": ova2_out.get("ok")},
        "brownout": {"step": ov_step, "recover": ov_rec,
                     "degraded": ovd_out.get("degraded"),
                     "state": ov_fleet.get("brownout")},
        "exactly_once": ov_ledger,
    }

    # -- forms leg (ISSUE 20): the operator zoo's parity contract + the
    # heat workload's warm-start savings, end to end through the fleet.
    # Parity is deterministic arithmetic (each form's device action vs
    # the CSR oracle assembled from the SAME tables/geometry on the
    # fixed-seed perturbed problem — contract flags, pinned True); the
    # savings counter is a deterministic function of the pinned
    # 200-step temporally-correlated scale stream (HIGHER table — the
    # CI suppression probe, BENCH_SUPPRESS_WARMSTART=1, zeroes it, and
    # the collector itself refuses to snapshot zero savings).
    import numpy as _np

    from bench_tpu_fem.elements import build_operator_tables
    from bench_tpu_fem.fem.assemble import (
        assemble_csr,
        element_form_matrices,
    )
    from bench_tpu_fem.fem.geometry import geometry_factors
    from bench_tpu_fem.forms.operators import (
        build_form_operator,
        kappa_at_quadrature,
    )
    from bench_tpu_fem.forms.registry import form_spec
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.dofmap import (
        boundary_dof_marker,
        cell_dofmap,
        dof_grid_shape,
    )
    from bench_tpu_fem.workload import heat_scale_stream, warm_pairs

    form_parity = {}
    fm_n, fm_degree, fm_perturb = (3, 2, 2), 3, 0.15
    fm_mesh = create_box_mesh(fm_n, geom_perturb_fact=fm_perturb)
    fm_t = build_operator_tables(fm_degree, 1, "gll")
    fm_corners = fm_mesh.cell_corners.reshape(-1, 2, 2, 2, 3)
    fm_G, fm_wdetJ = geometry_factors(fm_corners, fm_t.pts1d, fm_t.wts1d)
    fm_dm = cell_dofmap(fm_n, fm_degree)
    fm_bc = boundary_dof_marker(fm_n, fm_degree).ravel()
    fm_rng = _np.random.default_rng(20)
    fm_shape = dof_grid_shape(fm_n, fm_degree)
    for fname in ("mass", "helmholtz", "varkappa", "heat"):
        fs = form_spec(fname)
        fop = build_form_operator(fm_mesh, fs, fm_degree, 1, "gll",
                                  dtype=jnp.float64, tables=fm_t)
        kq = (kappa_at_quadrature(fm_corners, fm_t.pts1d)
              if fs.coefficient == "varkappa" else None)
        fA = assemble_csr(
            element_form_matrices(fm_t, fm_G, fm_wdetJ, fs.grad_coeff,
                                  fs.mass_coeff, kq=kq), fm_dm, fm_bc)
        fx = fm_rng.standard_normal(fA.shape[0])
        fy = _np.asarray(fop.apply(jnp.asarray(
            fx.reshape(fm_shape)))).ravel()
        fref = fA @ fx
        frel = float(_np.linalg.norm(fy - fref)
                     / _np.linalg.norm(fref))
        form_parity[fname] = {"rel": frel, "ok": frel < 1e-12}

    forms_journal = args.out + ".forms.jsonl"
    try:
        os.unlink(forms_journal)
    except OSError:
        pass
    heat_fleet = FleetDispatcher(2, journal_path=forms_journal,
                                 queue_max=64, nrhs_max=2,
                                 window_s=0.01, balance_interval_s=0)
    heat_spec = SolveSpec(degree=3, ndofs=2000, nreps=400,
                          precision="f64", form="heat")
    heat_pairs = warm_pairs(heat_scale_stream(200, seed=0, drift=0.01))
    try:
        heat_iters = {}
        for warmed in (True, False):
            iters = []
            for scale, wsc in heat_pairs:
                hp = heat_fleet.submit(heat_spec, scale=scale,
                                       warm_scale=wsc if warmed else 0.0)
                hout = heat_fleet.wait(hp, 120.0)
                if not hout.get("ok"):
                    print(f"forms leg heat request failed: {hout}")
                    return 1
                iters.append(int(hout["iters_run"]))
            heat_iters["warm" if warmed else "cold"] = iters
    finally:
        heat_fleet.shutdown()
    heat_saved = (sum(heat_iters["cold"][1:])
                  - sum(heat_iters["warm"][1:]))
    heat_ledger = verify_exactly_once(forms_journal)
    forms_leg = {
        "parity": form_parity,
        "heat": {"nsteps": len(heat_pairs),
                 "iters_warm_total": sum(heat_iters["warm"]),
                 "iters_cold_total": sum(heat_iters["cold"]),
                 "iters_saved": heat_saved},
        "exactly_once": heat_ledger,
    }

    # -- trace validity + record contract (contract booleans gate)
    from bench_tpu_fem.obs.trace import validate_chrome_trace

    trace_violations = validate_chrome_trace(tracer.chrome_trace())
    record_errs = check_record_contract(bench, require_convergence=True)

    sstep_reductions_per_iter = (
        float(ss_counts["reductions"]) / float(scfg.s_step)
        if isinstance(ss_counts.get("reductions"), int) else None)
    counters = {
        "collectives_per_iter": dist.get("collectives_per_iter"),
        # ISSUE 11: deterministic convergence counters on the fixed-seed
        # perturbed problem + the s-step communication contract; the
        # labels make a future precond-config change a LABELLED
        # apples-to-oranges gap instead of a phantom regression
        "iters_to_1e-06_none": pc_iters.get("none"),
        "iters_to_1e-06_jacobi": pc_iters.get("jacobi"),
        "precond_label": "none+jacobi",
        "s_step_label": f"s{scfg.s_step}",
        "sstep_reductions_per_iter": sstep_reductions_per_iter,
        "compiles": snap["cache"]["compiles"],
        "recompiles": snap["cache"]["compiles"] - compiles_after_warmup,
        "cache_hit_rate_requests": snap["cache_hit_rate_requests"],
        "shed_total": snap["shed_total"],
        "responses_failed": snap["failed"],
        "completed": snap["completed"],
        "corrupt_lines": len(corrupt),
        # ISSUE 15 request-trace counters on the pinned serve schedule:
        # completeness and the anomaly count are DETERMINISTIC (every OK
        # response must stamp all four required phases; the clean
        # schedule injects nothing, breaches nothing) and gate hard.
        # queue_share_p99 is timing-derived: its VALUE stays advisory
        # (never gated), its PRESENCE is the contract (tracing on) —
        # obs.regress.MEASURED_ONLY_COUNTERS.
        "reqtrace_complete_rate": rq_live.get("trace_complete_rate"),
        "reqtrace_incomplete": rq_live.get("trace_incomplete"),
        # slo_violation is EXCLUDED from the gated sum: it is the one
        # timing-derived tag (latency vs the objective on a shared CI
        # host), and timing never gates. The deterministic tags (retry,
        # sdc, breakdown, steal_moved, quarantine_drained, failed) pin
        # at 0 on this clean uninjected schedule.
        "reqtrace_anomalous": sum(
            n for tag, n in (rq_live.get("anomalies") or {}).items()
            if tag != "slo_violation"),
        "reqtrace_queue_share_p99": rq_live.get("queue_share_p99"),
        "record_contract_ok": not record_errs,
        "trace_valid": not trace_violations,
        # ISSUE 13 fleet counters: deterministic functions of the
        # pinned hang-and-rebalance schedule above. steals pins the
        # balancer's half-the-gap move; affinity is routing-decision-
        # weighted (every request routed to the lane already holding
        # the executable); warm-replica recompiles == 0 is THE shared-
        # artifact acceptance; lost/duplicates come from the journal's
        # exactly-once ledger over both fleets.
        "fleet_steals": fsnap["fleet"]["steals"],
        "fleet_affinity_hit_rate": fsnap["fleet"]["affinity_hit_rate"],
        "fleet_warm_loads": (fsnap["cache"]["warm_loads"]
                             + ssnap["cache"]["warm_loads"]),
        "fleet_warm_replica_recompiles": ssnap["cache"]["compiles"],
        "fleet_lost": len(fleet_ledger["lost"]),
        "fleet_duplicates": len(fleet_ledger["duplicates"]),
        # ISSUE 14 SDC counters: deterministic functions of the pinned
        # injected schedule (3 serve-audit flips + 1 driver boundary
        # flip). detected must track injected exactly; missed and
        # false_positives pin at 0 (LOWER tables), detected in the
        # HIGHER table so a SUPPRESSED detector gates rc 1.
        "sdc_injected": sdc_injected,
        "sdc_detected": sdc_detected,
        "sdc_missed": sdc_injected - sdc_detected,
        "sdc_false_positives": sdc_falsep,
        # ISSUE 16 autotuner counters on the pinned sweep-then-consume
        # schedule: both consumers (driver rerun + serve build) must
        # find their swept entry (hits in the HIGHER table — the
        # injected probe zeroes them), zero fallbacks after tuning
        # (LOWER table), and every DB entry must carry a registered
        # provenance label (contract flag).
        "tuning_db_hits": tuning_db_hits,
        "tuning_fallbacks": tuning_fallbacks,
        "tuning_labels_ok": s1["labels_ok"],
        # ISSUE 17 bf16 speed-ladder counters on the fixed-seed
        # refinement solve: the outer/inner split is deterministic on
        # CPU (LOWER tables — an increase is the bf16 inner solve
        # regressing, the exact drift the CI refinement probe injects);
        # parity_ok pins the f64-class-answer acceptance and the
        # envelope headroom pins the calibrated bf16 audit margin
        # (HIGHER tables — a drop gates rc 1).
        "refine_outer_iters": bf_stamp["outer_iters"],
        "refine_inner_iters_total": bf_stamp["inner_iters_total"],
        "bf16_parity_ok": bf16_parity_ok,
        "bf16_envelope_headroom": bf16_envelope_headroom,
        # ISSUE 18 overload counters on the pinned schedule above:
        # early sheds pin the predictive-refusal count (HIGHER — a
        # suppressed admission controller gates rc 1), late misses and
        # ledger duplicates across the hedge pair pin at 0 (LOWER —
        # either going nonzero is the worst overload regression), the
        # hedge win pins the speculative-copy rescue, and the brownout
        # step pins the ladder engaging under burn (HIGHER — the
        # suppressed-brownout probe zeroes it).
        "deadline_exceeded_early": ovsnap["deadline_exceeded_early"],
        "deadline_exceeded_late": ovsnap["deadline_exceeded_late"],
        "hedge_wins": ovsnap["hedge_wins"],
        "hedge_duplicates": len(ov_ledger["duplicates"]),
        "brownout_steps": ov_fleet["brownout_steps"],
        "brownout_recoveries": ov_fleet["brownout_recoveries"],
        # ISSUE 20 operator-zoo counters: per-form parity vs the CSR
        # oracle pins True (contract flags — arithmetic, not timing),
        # and the 200-step heat stream's warm-start savings pin in the
        # HIGHER table (a shrink is the warm path regressing; the CI
        # probe suppresses warm hints and must gate rc 1). The label
        # makes a future stream-config change a LABELLED gap instead of
        # a phantom regression.
        "form_parity_ok_mass": form_parity["mass"]["ok"],
        "form_parity_ok_helmholtz": form_parity["helmholtz"]["ok"],
        "form_parity_ok_varkappa": form_parity["varkappa"]["ok"],
        "form_parity_ok_heat": form_parity["heat"]["ok"],
        "heat_warm_start_iters_saved": heat_saved,
        "heat_warm_start_label": (
            f"heat{len(heat_pairs)}:d{heat_spec.degree}"
            f":n{heat_spec.ndofs}:seed0:drift0.01"),
    }
    snapshot = {
        "workload": {"ndofs": args.ndofs, "nreps": args.nreps,
                     "timing_reps": args.timing_reps,
                     "requests": args.requests,
                     "pcg_nreps": args.pcg_nreps,
                     "platform": jax.default_backend()},
        "bench": bench,
        "dist": dist,
        "pcg": pcg,
        "sstep": sstep,
        "serve": serve,
        "fleet": fleet_leg,
        "sdc": sdc_leg,
        "autotune": autotune_leg,
        "bf16": bf16_leg,
        "overload": overload_leg,
        "forms": forms_leg,
        "counters": counters,
        "record_contract_errors": record_errs,
        "trace_violations": trace_violations[:5],
    }
    with open(args.out, "w") as fh:
        json.dump(snapshot, fh, indent=1, sort_keys=True)
    print(f"perfgate snapshot -> {args.out}")
    print(json.dumps(counters, sort_keys=True))
    # the collector itself fails loud when the contracts are broken
    # (the gate would catch it against any sane baseline, but a broken
    # contract should not need a baseline to be visible)
    if record_errs or trace_violations:
        print("CONTRACT VIOLATIONS:", record_errs + trace_violations[:5])
        return 1
    if serve["ok_responses"] != args.requests:
        print(f"serve leg lost requests: {serve['ok_responses']}"
              f"/{args.requests}")
        return 1
    # ISSUE-15 acceptance, asserted by the collector itself: every
    # response carries a complete decomposition, and the journal fold
    # reproduces the live /metrics reqtrace block EXACTLY (live-vs-
    # replay parity — both sides run obs.reqtrace.summarize_phases)
    if rq_live.get("trace_complete_rate") != 1.0:
        print(f"reqtrace leg incomplete traces: {rq_live}")
        return 1
    for key in ("phases", "trace_complete", "trace_incomplete",
                "trace_complete_rate", "queue_share_p99", "anomalies"):
        if rq_fold.get(key) != rq_live.get(key):
            print(f"reqtrace live-vs-replay parity broken on {key!r}: "
                  f"live {rq_live.get(key)} vs fold {rq_fold.get(key)}")
            return 1
    # ISSUE-11 acceptance, asserted by the collector itself: both
    # precond arms must CROSS 1e-6, Jacobi strictly below bare, and the
    # sharded s-step loop strictly below one reduction per iteration
    if pc_iters.get("none") is None or pc_iters.get("jacobi") is None:
        print(f"precond legs did not cross rtol 1e-6: {pc_iters} "
              f"(raise --pcg-nreps)")
        return 1
    if not pc_iters["jacobi"] < pc_iters["none"]:
        print("jacobi PCG did not reduce iterations-to-1e-6: "
              f"{pc_iters}")
        return 1
    if not (sstep_reductions_per_iter is not None
            and sstep_reductions_per_iter < 1.0):
        print("s-step leg did not go below one reduction per "
              f"iteration: {sstep}")
        return 1
    # ISSUE-13 acceptance, asserted by the collector itself: the
    # imbalanced schedule must steal, the warm replica must not compile,
    # and the fleet journal's exactly-once ledger must close
    if fleet_leg["ok_responses"] != len(fouts) or not sout.get("ok"):
        print(f"fleet leg lost requests: {fleet_leg}")
        return 1
    if counters["fleet_steals"] < 1:
        print(f"fleet leg never stole under imbalance: {fleet_leg}")
        return 1
    if counters["fleet_warm_replica_recompiles"] != 0:
        print("standby replica COMPILED instead of warming from the "
              f"artifact store: {fleet_leg['standby']}")
        return 1
    if not fleet_ledger["ok"]:
        print(f"fleet exactly-once ledger violated: {fleet_ledger}")
        return 1
    # ISSUE-14 acceptance, asserted by the collector itself: every
    # injected SDC detected, zero false positives on the clean
    # fixed-seed solves (all three precisions), and the rollback run's
    # answer BITWISE equal to the uninjected one
    if sdc_detected != sdc_injected:
        print(f"sdc leg MISSED injections: detected {sdc_detected} of "
              f"{sdc_injected}: {sdc_leg}")
        return 1
    if sdc_falsep != 0:
        print(f"sdc leg false positives on clean solves: {sdc_falsep}: "
              f"{sdc_leg}")
        return 1
    if not sdc_rollback_bitwise:
        print("sdc rollback run diverged from the clean run "
              f"(ynorm {inj_ck.ynorm!r} vs {clean_ck.ynorm!r})")
        return 1
    # ISSUE-16 acceptance, asserted by the collector itself: the
    # pre-tune stamp records the registered entry-missing reason, both
    # consumers read source=db with a registered label, zero fallbacks
    # after tuning, and the persisted file round-trips a fresh load
    from bench_tpu_fem.engines.autotune import LABELS

    if (pre_stamp or {}).get("source") != "default":
        print(f"autotune leg pre-tune stamp not default: {pre_stamp}")
        return 1
    for who, stamp in (("driver", driver_stamp), ("serve", serve_stamp)):
        if (stamp or {}).get("source") != "db" \
                or (stamp or {}).get("label") not in LABELS:
            print(f"autotune leg {who} build did not consume the swept "
                  f"entry: {stamp}")
            return 1
    if tuning_db_hits < 2 or tuning_fallbacks != 0:
        print(f"autotune leg hit/fallback drift: hits {tuning_db_hits} "
              f"fallbacks {tuning_fallbacks}: {autotune_leg}")
        return 1
    if not s1["labels_ok"] or not roundtrip_ok:
        print(f"autotune leg DB label/round-trip contract broken: "
              f"{autotune_leg}")
        return 1
    # ISSUE-17 acceptance, asserted by the collector itself: the
    # refinement solve reaches f64-class rtol with bf16 hot-loop
    # applies, stamps time_to_rtol_s, both bf16 consumers read their
    # swept TuningDB entries, and the calibrated bf16 envelope keeps
    # real measured headroom over the clean-solve drift
    if not bf16_parity_ok:
        print(f"bf16 refinement missed 1e-10 rel: {bf16_leg['refine']}")
        return 1
    if bf16_leg["time_to_rtol_s"] is None:
        print(f"bf16 refinement did not stamp time_to_rtol_s: {bf16_leg}")
        return 1
    for who, stamp in (("driver", bf_tuning), ("serve", bf_serve_tuning)):
        if (stamp or {}).get("source") != "db" \
                or (stamp or {}).get("label") not in LABELS:
            print(f"bf16 leg {who} build did not consume the swept "
                  f"entry: {stamp}")
            return 1
    if not bf_audit["ok"] or bf16_envelope_headroom < 10:
        print(f"bf16 envelope headroom collapsed: {bf16_leg['audit']}")
        return 1
    # ISSUE-18 acceptance, asserted by the collector itself: both
    # impossible budgets refused early with a computed retry hint, the
    # expired-in-queue request answered deadline_exceeded without a
    # solve, zero LATE misses, exactly one hedge fired and won with the
    # exactly-once ledger closed over the hedge pair, and the brownout
    # ladder stepped once (degraded provenance stamped) then recovered
    if len(ov_sheds) != 2 or any(
            s["failure_class"] != "deadline_exceeded"
            or not s["retry_after_s"] for s in ov_sheds):
        print(f"overload leg predictive sheds wrong: {ov_sheds}")
        return 1
    if counters["deadline_exceeded_early"] != 3 \
            or counters["deadline_exceeded_late"] != 0:
        print(f"overload leg deadline split wrong: "
              f"early={counters['deadline_exceeded_early']} "
              f"late={counters['deadline_exceeded_late']}")
        return 1
    if ovc_out.get("failure_class") != "deadline_exceeded" \
            or not (ova_out.get("ok") and ova2_out.get("ok")
                    and ovb_out.get("ok")):
        print(f"overload leg expired/straggler outcomes wrong: "
              f"{overload_leg}")
        return 1
    if ov_hedges != 1 or counters["hedge_wins"] != 1 \
            or counters["hedge_duplicates"] != 0:
        print(f"overload leg hedge counters wrong: fired={ov_hedges} "
              f"wins={counters['hedge_wins']} "
              f"duplicates={counters['hedge_duplicates']}")
        return 1
    if not ov_ledger["ok"]:
        print(f"overload exactly-once ledger violated: {ov_ledger}")
        return 1
    if ov_step != "step" or ov_rec != "recover" \
            or counters["brownout_steps"] != 1 \
            or counters["brownout_recoveries"] != 1:
        print(f"overload leg brownout state machine wrong: "
              f"{overload_leg['brownout']}")
        return 1
    if (ovd_out.get("degraded") or {}).get("to") != "bf16":
        print(f"overload leg degraded provenance missing: "
              f"{ovd_out.get('degraded')}")
        return 1
    # ISSUE-20 acceptance, asserted by the collector itself: every form
    # matches the CSR oracle at f64, the 200-step heat stream's warm
    # starts SAVE iterations (zero savings means the hints were
    # suppressed or the warm path regressed — the exact state the CI
    # BENCH_SUPPRESS_WARMSTART probe injects), and the stream's
    # exactly-once ledger closes
    bad_parity = {f: v for f, v in form_parity.items() if not v["ok"]}
    if bad_parity:
        print(f"forms leg parity broken vs the CSR oracle: {bad_parity}")
        return 1
    if heat_saved <= 0:
        print(f"heat warm starts saved no iterations (saved="
              f"{heat_saved}): warm hints suppressed or warm-start "
              f"path regressed: {forms_leg['heat']}")
        return 1
    if not heat_ledger["ok"]:
        print(f"forms exactly-once ledger violated: {heat_ledger}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
