#!/usr/bin/env python
"""Chaos/soak harness for the fault-tolerant serving stack (ISSUE 9).

Drives scripted fault schedules (harness.chaos) against the LIVE serve
stack and asserts the recovery invariants:

  1. generations — a serving child (gen 1) is SIGKILL'd mid-incident;
     the parent then TEARS the journal tail (the crash-mid-write bytes)
     and gen 2 recovers the shared journal (``Broker.recover``): every
     admitted-but-unresponded request replays, fresh traffic still
     serves, and ``serve.recovery.verify_exactly_once`` must hold over
     the WHOLE journal — no losses, no duplicates, no deadlock. The
     recovery is span-traced (``serve:recover`` in the journal) and
     counted in /metrics (JSON snapshot + Prometheus exposition).
  2. worker-thread crash — ``BoundaryCrashHook`` raises mid-batch inside
     the broker's disposable solve thread; the bounded retry resumes the
     batch from its parked boundary checkpoint (``serve_retry`` with
     resumed=true) and every request is still answered ok, exactly once.
  3. injected NaN — a poisoned lane (scale=nan) answers
     ``failure_class: "breakdown"``; its batch-mates are unaffected.
  4. preemption mid-CG — a durably-checkpointed bench solve is SIGKILL'd
     right after a snapshot (``CHAOS_CKPT_KILL_AFTER``); the resumed run
     must match the uninterrupted solve BITWISE (the la.checkpoint
     restore proof, end-to-end through a real process death).
  5. standby adoption (ISSUE 13) — a PRIMARY FLEET (2 device lanes +
     shared artifact store) is SIGKILL'd mid-incident; the parent tears
     the journal tail, then a STANDBY fleet adopts the journal
     (``FleetDispatcher.adopt_journal``: the PR 9 fold + id-space
     handoff), warms its executables from the artifact store with ZERO
     compiles, answers every outstanding request, and
     ``verify_exactly_once`` must hold over BOTH generations.

  6. silent data corruption (ISSUE 14) — a scripted bit flip corrupts
     one lane's iterates mid-serve (``SDC_HOOK``: finite, wrong,
     invisible to the breakdown sentinel); the retire-time
     true-residual audit must detect it, the corruption-aware rollback
     must re-run the lane and answer OK, two windowed detections must
     QUARANTINE the lane with its queue drained exactly-once to the
     healthy peer, and a known-answer self-test must readmit it
     (``serve_sdc``/``fleet_quarantine``/``fleet_readmit`` journaled).

  7. overload (ISSUE 18) — a 2-lane fleet under deliberate overload
     with deadline propagation, predictive admission, hedged dispatch
     and the brownout ladder armed: an impossible budget is refused
     EARLY with a predicted-queue-time retry hint, an expired-in-queue
     request is answered without burning a solve (zero LATE misses), a
     straggler-stuck request is hedge-rescued by the healthy lane with
     the exactly-once ledger holding across the hedge pair, and
     sustained SLO burn steps the fleet down the registry precision
     ladder (``degraded`` provenance) then back up on hysteresis.

``--legs`` selects a subset
(generations,crash,nan,preempt,standby,sdc,overload) — the CI fleet
lane runs ``--legs standby`` next to the loadgen smoke.

All CPU (``JAX_PLATFORMS=cpu`` is pinned — this is a software-recovery
proof, not a hardware measurement; snapshot/restore on real HBM stays
hardware-armed per the evidence-hygiene rule). ``--quick`` bounds the
whole run to roughly a minute — the CI ``chaos`` lane's contract.

rc 0 = every invariant held; rc 1 names the first violation.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

CHILD_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": f"{ROOT}:{os.environ.get('PYTHONPATH', '')}"}

# the generation workload: small enough to compile in seconds on CPU,
# slow enough (nreps) that a SIGKILL reliably lands mid-incident
SPEC_KW = dict(degree=2, ndofs=2500, nreps=400)


def _pin_cpu():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from bench_tpu_fem.utils.hermetic import force_host_cpu_devices

    force_host_cpu_devices(1)


def log(msg: str) -> None:
    print(f"[chaos {time.strftime('%H:%M:%S')}] {msg}", flush=True)


def fail(msg: str) -> int:
    print(f"CHAOS FAIL: {msg}", flush=True)
    return 1


# ---------------------------------------------------------------------------
# generation children (leg 1)
# ---------------------------------------------------------------------------


def serve_child(journal: str, generation: int, nreq: int) -> int:
    """One broker generation against the shared journal. Gen 1 submits a
    burst and prints INFLIGHT (the parent's kill cue) while batches are
    mid-solve; gen >= 2 first replays the journal (Broker.recover), then
    serves fresh traffic, and reports its metrics for the parent's
    /metrics assertions."""
    _pin_cpu()
    import threading

    from bench_tpu_fem.harness.journal import Journal
    from bench_tpu_fem.obs.trace import enable
    from bench_tpu_fem.serve import (
        Broker,
        ExecutableCache,
        Metrics,
        SolveSpec,
        prometheus_text,
    )

    # recovery/retry spans stream into the SAME journal as the serve
    # records — the span-traced-recovery acceptance rides this file
    enable(journal=Journal(journal))
    metrics = Metrics(journal)
    broker = Broker(ExecutableCache(), metrics, queue_max=256, nrhs_max=4,
                    window_s=0.02, solve_timeout_s=120.0)
    spec = SolveSpec(**SPEC_KW)
    broker.warmup([spec])
    pending = []
    if generation >= 2:
        rec = broker.recover(journal)
        log(f"gen{generation}: recovered {rec['replayed']} outstanding "
            f"({rec['skipped']} skipped, {rec['plan'].corrupt} corrupt)")
        pending.extend(rec["pending"])
    log(f"gen{generation}: submitting {nreq} requests")
    for i in range(nreq):
        pending.append(broker.submit(spec, scale=2.0 ** (i % 3)))
    print("INFLIGHT", len(pending), flush=True)
    waits = []
    for p in pending:
        t = threading.Thread(target=lambda p=p: waits.append(
            broker.wait(p, 120)), daemon=True)
        t.start()
        t.join(180)
    broker.shutdown()
    snap = metrics.snapshot()
    print("SNAPSHOT", json.dumps(snap), flush=True)
    prom = prometheus_text(snap)
    ok_prom = "benchfem_serve_recovery_runs" in prom
    print("PROM_OK" if ok_prom else "PROM_MISSING", flush=True)
    bad = [w for w in waits if not w.get("ok")]
    print("SERVED", len(waits) - len(bad), "FAILED", len(bad), flush=True)
    return 0


def run_generations(quick: bool) -> int:
    """Leg 1: SIGKILL mid-incident + torn tail + journal-replay recovery
    + whole-journal exactly-once."""
    from bench_tpu_fem.harness.chaos import tear_journal_tail
    from bench_tpu_fem.serve.recovery import (
        fold_outstanding,
        verify_exactly_once,
    )
    from bench_tpu_fem.harness.journal import read_records

    tmp = tempfile.mkdtemp(prefix="chaos_soak_")
    journal = os.path.join(tmp, "SERVE_chaos.jsonl")
    nreq = 6 if quick else 16

    # gen 1: killed mid-incident
    child = subprocess.Popen(
        [sys.executable, "-u", __file__, "--serve-child", "1",
         "--journal", journal, "--nreq", str(nreq)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=CHILD_ENV, cwd=ROOT, start_new_session=True)
    killed = False
    hung = threading.Event()

    def _watchdog():
        # the stdout for-loop below blocks until a LINE arrives: a child
        # wedged before its first print (jax import/compile hang — the
        # failure class this repo designs around) would otherwise pin
        # the soak until CI's outer timeout. Kill the group so the pipe
        # closes and the loop exits with the script's own diagnosis.
        hung.set()
        try:
            os.killpg(child.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass

    wd = threading.Timer(300, _watchdog)
    wd.start()
    try:
        for line in child.stdout:  # type: ignore[union-attr]
            print("  gen1|", line.rstrip(), flush=True)
            if line.startswith("INFLIGHT"):
                time.sleep(0.2)  # let batches reach mid-solve
                os.killpg(child.pid, signal.SIGKILL)
                killed = True
                break
            if hung.is_set():
                break
    finally:
        wd.cancel()
    child.wait(30)
    if hung.is_set() and not killed:
        return fail("gen 1 hung without output for 300 s "
                    "(watchdog killed it)")
    if not killed:
        return fail("gen 1 never reported INFLIGHT (kill cue missed)")
    log(f"gen1 SIGKILL'd (rc {child.returncode})")

    outstanding = fold_outstanding(journal).outstanding
    log(f"journal holds {len(outstanding)} admitted-unresponded requests")
    if not outstanding:
        return fail("SIGKILL left no outstanding requests — the kill "
                    "landed after the incident; nothing recovered")

    # the crash-mid-write bytes: a torn response for one outstanding id
    # must NOT count as answered (the client was never released)
    tear_journal_tail(journal, rid=outstanding[0]["id"])
    still = fold_outstanding(journal).outstanding
    if outstanding[0]["id"] not in [r["id"] for r in still]:
        return fail("torn serve_response tail counted as answered")

    # gen 2: recover + serve fresh traffic
    out = subprocess.run(
        [sys.executable, "-u", __file__, "--serve-child", "2",
         "--journal", journal, "--nreq", "2"],
        capture_output=True, text=True, timeout=600, env=CHILD_ENV,
        cwd=ROOT)
    print("  gen2|", out.stdout.strip().replace("\n", "\n  gen2| "),
          flush=True)
    if out.returncode != 0:
        return fail(f"gen 2 exited rc {out.returncode}")
    snap = None
    for line in out.stdout.splitlines():
        if line.startswith("SNAPSHOT "):
            snap = json.loads(line[len("SNAPSHOT "):])
    if snap is None:
        return fail("gen 2 reported no metrics snapshot")
    if snap["recovery_runs"] < 1 or snap["recovered_requests"] < 1:
        return fail(f"recovery not counted in /metrics: {snap}")
    if "PROM_OK" not in out.stdout:
        return fail("recovery counters missing from Prometheus exposition")

    verdict = verify_exactly_once(journal)
    log(f"exactly-once verdict: {verdict}")
    if not verdict["ok"]:
        return fail(f"exactly-once violated: lost={verdict['lost']} "
                    f"duplicates={verdict['duplicates']}")
    records, _ = read_records(journal)
    spans = [r for r in records if r.get("event") == "span"]
    if not any(r.get("name") == "serve:recover" for r in spans):
        return fail("no serve:recover span in the journal trace")
    log("leg 1 (generations + torn tail) OK")
    return 0


# ---------------------------------------------------------------------------
# standby adoption (leg 5, ISSUE 13)
# ---------------------------------------------------------------------------


def fleet_child(journal: str, artdir: str, generation: int,
                nreq: int) -> int:
    """One FLEET generation against the shared journal + artifact
    store. Gen 1 (the primary) warms, publishes artifacts, submits a
    burst and prints INFLIGHT (the kill cue). Gen >= 2 (the standby)
    ADOPTS the journal first — answering the dead primary's outstanding
    requests under their original ids, executables warmed from the
    artifact store — then serves fresh traffic and reports cache
    counters for the parent's zero-recompile assertion."""
    _pin_cpu()
    import threading

    from bench_tpu_fem.serve import (
        ArtifactStore,
        FleetDispatcher,
        SolveSpec,
    )

    store = ArtifactStore(artdir)
    fleet = FleetDispatcher(2, journal_path=journal, artifacts=store,
                            queue_max=256, nrhs_max=4, window_s=0.02,
                            solve_timeout_s=120.0, steal_threshold=4,
                            balance_interval_s=0.02)
    spec = SolveSpec(**SPEC_KW)
    pending = []
    if generation >= 2:
        rec = fleet.adopt_journal(journal)
        log(f"standby gen{generation}: adopted {rec['routed']} "
            f"outstanding ({rec['skipped']} skipped, "
            f"{rec['plan'].corrupt} corrupt)")
        pending.extend(rec["pending"])
    else:
        fleet.warmup([spec])
    log(f"fleet gen{generation}: submitting {nreq} requests")
    for i in range(nreq):
        pending.append(fleet.submit(spec, scale=2.0 ** (i % 3)))
    print("INFLIGHT", len(pending), flush=True)
    waits = []
    for p in pending:
        t = threading.Thread(target=lambda p=p: waits.append(
            fleet.wait(p, 180)), daemon=True)
        t.start()
        t.join(240)
    snap = fleet.metrics_snapshot()
    fleet.shutdown()
    print("SNAPSHOT", json.dumps(snap), flush=True)
    bad = [w for w in waits if not w.get("ok")]
    print("SERVED", len(waits) - len(bad), "FAILED", len(bad), flush=True)
    return 0


def run_standby(quick: bool) -> int:
    """Leg 5: kill-the-primary mid-incident; the standby fleet must
    adopt the journal, warm from the artifact store with zero compiles,
    and answer every outstanding request exactly once."""
    from bench_tpu_fem.harness.chaos import tear_journal_tail
    from bench_tpu_fem.serve.recovery import (
        fold_outstanding,
        verify_exactly_once,
    )
    from bench_tpu_fem.harness.journal import read_records

    tmp = tempfile.mkdtemp(prefix="chaos_standby_")
    journal = os.path.join(tmp, "FLEET_chaos.jsonl")
    artdir = os.path.join(tmp, "artifacts")
    nreq = 6 if quick else 16

    # the primary: killed mid-incident
    child = subprocess.Popen(
        [sys.executable, "-u", __file__, "--fleet-child", "1",
         "--journal", journal, "--artifacts", artdir,
         "--nreq", str(nreq)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=CHILD_ENV, cwd=ROOT, start_new_session=True)
    killed = False
    hung = threading.Event()

    def _watchdog():
        hung.set()
        try:
            os.killpg(child.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass

    wd = threading.Timer(300, _watchdog)
    wd.start()
    try:
        for line in child.stdout:  # type: ignore[union-attr]
            print("  primary|", line.rstrip(), flush=True)
            if line.startswith("INFLIGHT"):
                time.sleep(0.2)  # let batches reach mid-solve
                os.killpg(child.pid, signal.SIGKILL)
                killed = True
                break
            if hung.is_set():
                break
    finally:
        wd.cancel()
    child.wait(30)
    if hung.is_set() and not killed:
        return fail("primary hung without output for 300 s")
    if not killed:
        return fail("primary never reported INFLIGHT (kill cue missed)")
    log(f"primary SIGKILL'd (rc {child.returncode})")

    outstanding = fold_outstanding(journal).outstanding
    log(f"journal holds {len(outstanding)} admitted-unresponded requests")
    if not outstanding:
        return fail("SIGKILL left no outstanding requests — nothing "
                    "for the standby to adopt")
    # the crash-mid-write bytes: a torn response must not count answered
    tear_journal_tail(journal, rid=outstanding[0]["id"])

    # the standby: adopt + serve fresh traffic
    out = subprocess.run(
        [sys.executable, "-u", __file__, "--fleet-child", "2",
         "--journal", journal, "--artifacts", artdir, "--nreq", "2"],
        capture_output=True, text=True, timeout=600, env=CHILD_ENV,
        cwd=ROOT)
    print("  standby|", out.stdout.strip().replace("\n", "\n  standby| "),
          flush=True)
    if out.returncode != 0:
        return fail(f"standby exited rc {out.returncode}")
    snap = None
    for line in out.stdout.splitlines():
        if line.startswith("SNAPSHOT "):
            snap = json.loads(line[len("SNAPSHOT "):])
    if snap is None:
        return fail("standby reported no metrics snapshot")
    fleet = snap.get("fleet") or {}
    if fleet.get("adoptions", 0) < 1 or fleet.get(
            "adopted_requests", 0) < 1:
        return fail(f"standby adoption not counted: {fleet}")
    cache = snap.get("cache") or {}
    if cache.get("compiles", 0) != 0:
        return fail("standby COMPILED instead of warming from the "
                    f"artifact store: {cache}")
    if cache.get("warm_loads", 0) < 1:
        return fail(f"standby never warm-loaded an artifact: {cache}")

    verdict = verify_exactly_once(journal)
    log(f"exactly-once verdict (both generations): {verdict}")
    if not verdict["ok"]:
        return fail(f"exactly-once violated across generations: "
                    f"lost={verdict['lost']} "
                    f"duplicates={verdict['duplicates']}")
    records, _ = read_records(journal)
    if not any(r.get("event") == "fleet_adopt" for r in records):
        return fail("no fleet_adopt record in the journal")
    log("leg 5 (kill-primary -> standby adoption, zero recompiles) OK")
    return 0


# ---------------------------------------------------------------------------
# in-process legs
# ---------------------------------------------------------------------------


def run_worker_crash(quick: bool) -> int:
    """Leg 2: worker-thread crash mid-batch -> boundary-checkpoint
    resume (serve_retry resumed=true), everyone answered exactly once."""
    _pin_cpu()
    from bench_tpu_fem.harness.chaos import (
        BoundaryCrashHook,
        install_boundary_hook,
    )
    from bench_tpu_fem.serve import (
        Broker,
        ExecutableCache,
        Metrics,
        SolveSpec,
    )

    tmp = tempfile.mkdtemp(prefix="chaos_crash_")
    journal = os.path.join(tmp, "SERVE_crash.jsonl")
    metrics = Metrics(journal)
    broker = Broker(ExecutableCache(), metrics, queue_max=64, nrhs_max=4,
                    window_s=0.02, solve_timeout_s=120.0, retry_max=2,
                    retry_backoff_s=0.01)
    spec = SolveSpec(**SPEC_KW)
    broker.warmup([spec])
    hook = BoundaryCrashHook(crash_at=[5])
    prev = install_boundary_hook(hook)
    try:
        pending = [broker.submit(spec, scale=float(1 + i))
                   for i in range(3)]
        outs = [broker.wait(p, 180) for p in pending]
    finally:
        install_boundary_hook(prev)
        broker.shutdown()
    if not all(o.get("ok") for o in outs):
        return fail(f"worker-crash leg: not all answered ok: {outs}")
    if not hook.crashes:
        return fail("worker-crash leg: the scripted crash never fired")
    if metrics.batch_resumes < 1:
        return fail("worker-crash leg: retry did not resume the boundary "
                    f"checkpoint (batch_resumes={metrics.batch_resumes})")
    log(f"leg 2 (worker-thread crash -> boundary resume) OK "
        f"(crashed at boundary {hook.crashes[0]}, "
        f"resumes={metrics.batch_resumes})")
    return 0


def run_nan_injection(quick: bool) -> int:
    """Leg 3: injected NaN -> breakdown sentinel, batch-mates clean."""
    _pin_cpu()
    from bench_tpu_fem.serve import (
        Broker,
        ExecutableCache,
        Metrics,
        SolveSpec,
    )

    broker = Broker(ExecutableCache(), Metrics(), queue_max=64,
                    nrhs_max=4, window_s=0.05, solve_timeout_s=120.0)
    # pre-convergence budget: past the f32 residual floor, underflow
    # breaks exact power-of-two lane scaling (post-floor noise — the
    # standing serve-parity caveat), which would fog the lane-isolation
    # check this leg exists for
    spec = SolveSpec(**{**SPEC_KW, "nreps": 60})
    broker.warmup([spec])
    try:
        pending = [broker.submit(spec, scale=s)
                   for s in (1.0, float("nan"), 2.0)]
        outs = [broker.wait(p, 180) for p in pending]
    finally:
        broker.shutdown()
    poisoned = outs[1]
    if poisoned.get("ok") or poisoned.get("failure_class") != "breakdown":
        return fail(f"NaN lane not classified breakdown: {poisoned}")
    mates = [outs[0], outs[2]]
    if not all(o.get("ok") and math.isfinite(o["xnorm"]) for o in mates):
        return fail(f"NaN lane perturbed its batch-mates: {mates}")
    if abs(mates[1]["xnorm"] - 2.0 * mates[0]["xnorm"]) > 1e-5 * abs(
            mates[1]["xnorm"]):
        return fail(f"batch-mate linearity broken next to the NaN lane: "
                    f"{mates}")
    log("leg 3 (injected NaN -> breakdown, lane-local) OK")
    return 0


def run_sdc(quick: bool) -> int:
    """Leg 6 (ISSUE 14): injected silent corruption mid-serve ->
    retire-time true-residual audit detection -> corruption-aware lane
    rollback (the re-run answers ok) -> windowed lane quarantine with
    an exactly-once queue drain -> known-answer self-test readmission.
    The injected values are FINITE — nothing here trips the breakdown
    sentinel; only the audit sees it."""
    _pin_cpu()
    import bench_tpu_fem.serve.engine as engine_mod
    from bench_tpu_fem.harness.chaos import install_sdc_hook
    from bench_tpu_fem.harness.faults import FaultySolveHook, SdcInjectionHook
    from bench_tpu_fem.harness.journal import read_records
    from bench_tpu_fem.serve import FleetDispatcher, SolveSpec
    from bench_tpu_fem.serve.recovery import verify_exactly_once

    tmp = tempfile.mkdtemp(prefix="chaos_sdc_")
    journal = os.path.join(tmp, "SDC_chaos.jsonl")
    fleet = FleetDispatcher(2, journal_path=journal, queue_max=64,
                            nrhs_max=2, window_s=0.02,
                            solve_timeout_s=120.0, balance_interval_s=0,
                            audit=True, quarantine_threshold=2,
                            quarantine_window_s=300.0)
    spec = SolveSpec(degree=1, ndofs=2000, nreps=12)
    try:
        fleet.warmup([spec])  # affinity home: lane 0 (round-robin)
        # two corruptions on lane 0's device, one per request: each is
        # detected at retire, rolled back (the lane re-runs from its
        # write-ahead record) and the re-run answers OK — detection
        # without rollback would fail these waits
        hook = SdcInjectionHook(corrupt_at=[2, 8], lane=0)
        prev = install_sdc_hook(hook)
        try:
            o1 = fleet.wait(fleet.submit(spec, 1.0), 180)
            o2 = fleet.wait(fleet.submit(spec, 2.0), 180)
        finally:
            install_sdc_hook(prev)
        if not (o1.get("ok") and o2.get("ok")):
            return fail(f"sdc leg: rollback did not recover: {o1} {o2}")
        if len(hook.fired) != 2:
            return fail(f"sdc leg: injector fired {hook.fired}, wanted 2")
        m0 = fleet.lanes[0].metrics
        if m0.sdc_detected != 2 or m0.sdc_rollbacks != 2:
            return fail(f"sdc leg: detections {m0.sdc_detected} "
                        f"rollbacks {m0.sdc_rollbacks}, wanted 2/2")
        if abs(o2["xnorm"] - 2.0 * o1["xnorm"]) > 1e-5 * abs(o2["xnorm"]):
            return fail(f"sdc leg: recovered answers broke linearity: "
                        f"{o1['xnorm']} {o2['xnorm']}")
        # queue work behind a held lane 0, then trip the quarantine:
        # the drain must move the queued requests to lane 1 through the
        # steal/adopt machinery and every one must still answer exactly
        # once
        engine_mod.FAULT_HOOK = FaultySolveHook(["hang"], hang_s=1.5)
        try:
            pend = [fleet.submit(spec, 1.0)]
            time.sleep(0.4)  # lane 0's worker entered the held solve
            pend += [fleet.submit(spec, float(2 ** (i % 3)))
                     for i in range(4)]
            tripped = fleet.quarantine_scan()
            if tripped != 1 or not fleet.lanes[0].quarantined:
                return fail(f"sdc leg: quarantine did not trip "
                            f"(tripped={tripped})")
            outs = [fleet.wait(p, 180) for p in pend]
        finally:
            engine_mod.FAULT_HOOK = None
        if not all(o.get("ok") for o in outs):
            return fail(f"sdc leg: drained requests lost: {outs}")
        # fresh traffic routes around the quarantined lane
        o3 = fleet.wait(fleet.submit(spec, 4.0), 180)
        if not o3.get("ok"):
            return fail(f"sdc leg: routing around quarantine failed: {o3}")
        # known-answer self-test (the injector is exhausted — the lane
        # is genuinely healthy again) readmits the lane
        st = fleet.run_selftest(0, spec, expect_xnorm=o1["xnorm"])
        if not st["ok"] or fleet.lanes[0].quarantined:
            return fail(f"sdc leg: self-test readmission failed: {st}")
        snap = fleet.metrics_snapshot()
    finally:
        fleet.shutdown()
    f = snap["fleet"]
    if f["quarantines"] != 1 or f["readmits"] != 1:
        return fail(f"sdc leg: quarantine counters wrong: {f}")
    verdict = verify_exactly_once(journal)
    if not verdict["ok"]:
        return fail(f"sdc leg: exactly-once violated across the drain: "
                    f"lost={verdict['lost']} "
                    f"duplicates={verdict['duplicates']}")
    records, _ = read_records(journal)
    evs = [r.get("event") for r in records]
    for needed in ("serve_sdc", "fleet_quarantine", "fleet_selftest",
                   "fleet_readmit"):
        if needed not in evs:
            return fail(f"sdc leg: no {needed} record in the journal")
    drained = [r for r in records if r.get("event") == "fleet_quarantine"]
    log(f"leg 6 (injected SDC -> detect -> rollback -> quarantine "
        f"[drained {drained[0].get('drained')}] -> self-test readmit) OK")
    return 0


def run_overload(quick: bool) -> int:
    """Leg 7 (ISSUE 18): deliberate overload against a 2-lane fleet with
    deadline propagation, predictive admission, hedged dispatch and the
    brownout ladder all armed. Invariants: a request whose predicted
    completion exceeds its budget is REFUSED at admission (early, with a
    predicted-queue-time retry hint); a request that expires in queue is
    answered without burning a solve; zero LATE deadline misses; a
    straggler-stuck request is hedge-rescued by the healthy lane (first
    retire wins the claim CAS, the loser cancels — the exactly-once
    ledger holds across the hedge pair); sustained SLO burn steps the
    fleet down the registry precision ladder (responses stamped
    `degraded`) and hysteresis steps it back up once the burn clears."""
    _pin_cpu()
    from bench_tpu_fem.harness.chaos import install_fault_hook
    from bench_tpu_fem.harness.faults import HeldSolveHook
    from bench_tpu_fem.harness.journal import read_records
    from bench_tpu_fem.serve import FleetDispatcher, SolveSpec
    from bench_tpu_fem.serve.broker import QueueFull
    from bench_tpu_fem.serve.recovery import verify_exactly_once

    tmp = tempfile.mkdtemp(prefix="chaos_overload_")
    journal = os.path.join(tmp, "OVERLOAD_chaos.jsonl")
    # tiny objective: every real solve violates it, so the burn fold
    # reads sustained overload — the brownout trigger under test.
    # spill_burn is parked out of the way (spill would re-route the
    # affinity lane this leg deliberately backs up); custom short burn
    # windows let the recovery phase age the samples out with an
    # injected clock instead of a wall-clock wait.
    fleet = FleetDispatcher(
        2, journal_path=journal, queue_max=64, nrhs_max=2,
        window_s=0.02, solve_timeout_s=120.0, balance_interval_s=0,
        slo_objective_s=0.01, spill_burn=1e9,
        hedge=True, hedge_budget=1.0, hedge_delay_s=0.05,
        brownout=True, brownout_burn=0.5, brownout_clear_burn=0.25,
        brownout_windows=((30.0, "fast"), (60.0, "slow")))
    spec = SolveSpec(degree=1, ndofs=2000, nreps=12)
    try:
        fleet.warmup([spec])
        # seed the per-spec latency windows: the predictor refuses to
        # guess below its minimum sample count, so admission control is
        # inert until real completions exist (no evidence, no shed)
        for i in range(4):
            o = fleet.wait(fleet.submit(spec, float(1 + i)), 180)
            if not o.get("ok"):
                return fail(f"overload leg: warm solve failed: {o}")

        # -- predictive admission: an impossible budget is refused
        # EARLY, before any solve burns, with a computed retry hint
        import dataclasses

        doomed = dataclasses.replace(spec, deadline_s=1e-4)
        try:
            fleet.submit(doomed, 1.0)
            return fail("overload leg: impossible deadline was admitted")
        except QueueFull as exc:
            if exc.failure_class != "deadline_exceeded":
                return fail(f"overload leg: predictive shed classified "
                            f"{exc.failure_class!r}, wanted "
                            f"deadline_exceeded")
            if not exc.retry_after_s:
                return fail("overload leg: predictive shed carried no "
                            "retry_after_s hint")
            log(f"predictive shed OK (retry_after_s={exc.retry_after_s})")

        # -- straggler + hedge rescue + expired-in-queue: lane 0's
        # worker blocks inside a held solve; the queue behind it builds
        hook = HeldSolveHook(hold=1, timeout_s=120.0)
        prev = install_fault_hook(hook)
        try:
            a = fleet.submit(spec, 1.0)     # enters the held solve
            time.sleep(0.3)
            b = fleet.submit(spec, 2.0)     # queues behind the straggler
            c = fleet.submit(
                dataclasses.replace(spec, deadline_s=0.5), 1.0)
            time.sleep(0.6)                 # c expires; b over the delay
            nh = fleet.hedge_scan()
            if nh < 1:
                return fail(f"overload leg: hedge_scan fired {nh} "
                            "hedges, wanted >= 1")
            ob = fleet.wait(b, 180)         # rescued on the healthy lane
            oc = fleet.wait(c, 180)         # expired: answered, no solve
            hook.release()
            oa = fleet.wait(a, 180)         # the straggler retires late
        finally:
            install_fault_hook(prev)
            hook.release()
        if not (oa.get("ok") and ob.get("ok")):
            return fail(f"overload leg: hedge rescue failed: {oa} {ob}")
        if oc.get("ok") or oc.get("failure_class") != "deadline_exceeded":
            return fail(f"overload leg: expired-in-queue request not "
                        f"answered deadline_exceeded: {oc}")
        if len(hook.waited) != 1:
            return fail(f"overload leg: straggler hook held "
                        f"{len(hook.waited)} solves, wanted 1")

        # -- brownout: sustained burn steps the fleet down the registry
        # precision ladder; responses carry degraded provenance
        step = fleet.brownout_scan()
        if step != "step":
            return fail(f"overload leg: brownout did not engage ({step})")
        od = fleet.wait(fleet.submit(spec, 1.0), 300)
        if not od.get("ok"):
            return fail(f"overload leg: brownout-degraded solve failed: "
                        f"{od}")
        deg = od.get("degraded")
        if not deg or deg.get("to") != "bf16" or deg.get("from") != "f32":
            return fail(f"overload leg: degraded response missing its "
                        f"provenance stamp: {deg}")
        # hysteresis recovery: age the burn windows out (injected clock)
        rec = fleet.brownout_scan(now=time.time() + 3600.0)
        if rec != "recover":
            return fail(f"overload leg: brownout did not recover ({rec})")
        oe = fleet.wait(fleet.submit(spec, 1.0), 180)
        if not oe.get("ok") or oe.get("degraded"):
            return fail(f"overload leg: post-recovery response still "
                        f"degraded: {oe}")
        snap = fleet.metrics_snapshot()
    finally:
        fleet.shutdown()

    if snap.get("deadline_exceeded_late", 0) != 0:
        return fail(f"overload leg: LATE deadline misses: "
                    f"{snap['deadline_exceeded_late']}")
    if snap.get("deadline_exceeded_early", 0) < 2:
        return fail(f"overload leg: early sheds "
                    f"{snap.get('deadline_exceeded_early')}, wanted >= 2")
    if snap.get("hedge_wins", 0) < 1:
        return fail(f"overload leg: no hedge win recorded: "
                    f"{snap.get('hedge_wins')}")
    f = snap["fleet"]
    if f.get("hedges_fired", 0) < 1:
        return fail(f"overload leg: hedges_fired {f.get('hedges_fired')}")
    if f.get("brownout_steps") != 1 or f.get("brownout_recoveries") != 1:
        return fail(f"overload leg: brownout counters wrong: {f}")
    brown = f.get("brownout") or {}
    if brown.get("level") != 0 or brown.get("residency_s", 0) <= 0:
        return fail(f"overload leg: brownout state after recovery: "
                    f"{brown}")
    verdict = verify_exactly_once(journal)
    if not verdict["ok"]:
        return fail(f"overload leg: exactly-once violated across the "
                    f"hedge pair: lost={verdict['lost']} "
                    f"duplicates={verdict['duplicates']}")
    records, _ = read_records(journal)
    evs = [r.get("event") for r in records]
    for needed in ("serve_hedge_fired", "serve_hedge_won",
                   "fleet_brownout"):
        if needed not in evs:
            return fail(f"overload leg: no {needed} record in the "
                        "journal")
    sheds = [r for r in records if r.get("event") == "serve_shed"
             and r.get("failure_class") == "deadline_exceeded"]
    if not sheds or not sheds[0].get("controller"):
        return fail("overload leg: deadline shed journaled without its "
                    "controller inputs (not replayable)")
    log("leg 7 (overload: predictive shed -> hedge rescue -> "
        "brownout step/recover, exactly-once incl. hedge pair) OK")
    return 0


def run_preemption(quick: bool) -> int:
    """Leg 4: preemption mid-CG — SIGKILL right after a durable
    snapshot, resume, compare BITWISE with the uninterrupted solve."""
    BENCH = """
import os
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
from bench_tpu_fem.bench.driver import BenchConfig, run_benchmark
import sys
res = run_benchmark(BenchConfig(
    ndofs_global=4000, degree=2, qmode=1, float_bits=32, nreps=24,
    use_cg=True, checkpoint_every={every}, checkpoint_dir={ckdir!r}))
print('YNORM', repr(res.ynorm), res.extra.get('checkpoint'))
"""
    tmp = tempfile.mkdtemp(prefix="chaos_preempt_")
    ckdir = os.path.join(tmp, "snaps")

    def run_bench(extra_env=None, every=6, ckdir_=None):
        env = dict(CHILD_ENV)
        env.update(extra_env or {})
        return subprocess.run(
            [sys.executable, "-u", "-c",
             BENCH.format(every=every, ckdir=ckdir_ or "")],
            capture_output=True, text=True, timeout=600, env=env,
            cwd=ROOT)

    # uninterrupted reference (chunked loop, no snapshots)
    ref = run_bench()
    if ref.returncode != 0:
        return fail(f"preemption leg reference run failed:\n{ref.stdout}"
                    f"\n{ref.stderr}")
    ref_norm = [ln for ln in ref.stdout.splitlines()
                if ln.startswith("YNORM")][0].split()[1]

    # preempted run: SIGKILL'd by the store right after snapshot #2
    pre = run_bench(extra_env={"CHAOS_CKPT_KILL_AFTER": "2"},
                    ckdir_=ckdir)
    if pre.returncode == 0:
        return fail("preemption leg: the scripted SIGKILL never fired")
    log(f"preempted run died rc {pre.returncode} (scripted) — resuming")

    # resumed run restores the snapshot and finishes
    res = run_bench(ckdir_=ckdir)
    if res.returncode != 0:
        return fail(f"preemption leg resume failed:\n{res.stdout}"
                    f"\n{res.stderr}")
    line = [ln for ln in res.stdout.splitlines()
            if ln.startswith("YNORM")][0]
    res_norm = line.split()[1]
    if "'restored_iteration': 0" in line:
        return fail(f"resume did not restore a snapshot: {line}")
    if res_norm != ref_norm:
        return fail(f"recovery parity broken: resumed {res_norm} != "
                    f"uninterrupted {ref_norm} (bitwise contract)")
    log(f"leg 4 (preemption mid-CG -> bitwise resume) OK ({line})")
    return 0


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--quick", action="store_true",
                   help="bound the soak to ~60 s (the CI chaos lane)")
    p.add_argument("--legs", default="",
                   help="comma-separated subset of "
                        "generations,crash,nan,preempt,standby,sdc,"
                        "overload (default: all)")
    p.add_argument("--serve-child", type=int, default=0,
                   help=argparse.SUPPRESS)  # internal: generation driver
    p.add_argument("--fleet-child", type=int, default=0,
                   help=argparse.SUPPRESS)  # internal: standby driver
    p.add_argument("--journal", default="", help=argparse.SUPPRESS)
    p.add_argument("--artifacts", default="", help=argparse.SUPPRESS)
    p.add_argument("--nreq", type=int, default=8, help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.serve_child:
        return serve_child(args.journal, args.serve_child, args.nreq)
    if args.fleet_child:
        return fleet_child(args.journal, args.artifacts,
                           args.fleet_child, args.nreq)
    legs = {"generations": run_generations, "crash": run_worker_crash,
            "nan": run_nan_injection, "preempt": run_preemption,
            "standby": run_standby, "sdc": run_sdc,
            "overload": run_overload}
    selected = ([s.strip() for s in args.legs.split(",") if s.strip()]
                or list(legs))
    unknown = [s for s in selected if s not in legs]
    if unknown:
        return fail(f"unknown legs {unknown} (choose from {list(legs)})")
    t0 = time.monotonic()
    for name in selected:
        rc = legs[name](args.quick)
        if rc:
            return rc
    log(f"CHAOS SOAK OK ({time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
