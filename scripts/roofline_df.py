"""df32 roofline: measure the chip's VPU f32 throughput and HBM
bandwidth, derive the df engine's compute/bandwidth ceilings, and
compare the measured df32 CG rate against them.

VERDICT r4 item 1's done-criterion allows "a committed roofline analysis
proving the df32 ceiling and the best achievable number" where >=1.0x
vs the reference's 4.02 GDoF/s/GPU f64 is not reachable: double-float
arithmetic multiplies VPU work ~15-20x while the f32 engine already ran
near the chip's HBM/VPU balance point, so the df ceiling is set by
whichever of (VPU_flops / df_flops_per_dof, HBM_bytes / df_bytes_per_dof)
is smaller. This script measures both machine numbers ON the chip (no
datasheet guesses), prints the ceilings, runs the df engine, and reports
achieved/ceiling.

Run on hardware: python scripts/roofline_df.py [ndofs]
Writes ROOFLINE_DF_r05.json at the repo root.
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402


def measure_hbm_gbps(nbytes: int = 2 << 30) -> float:
    """Streaming read+write bandwidth: y = x * c on an HBM-resident f32
    array (2 streams)."""
    n = nbytes // 8  # f32 in + f32 out per element
    x = jnp.ones((n,), jnp.float32)
    f = jax.jit(lambda a: a * jnp.float32(1.0000001))
    f(x).block_until_ready()  # compile + warm
    reps = 10
    t0 = time.perf_counter()
    y = x
    for _ in range(reps):
        y = f(y)
    y.block_until_ready()
    dt = time.perf_counter() - t0
    return reps * n * 8 / dt / 1e9


def _vpu_kernel(R: int, NY: int, NZ: int):
    def kernel(x_ref, o_ref):
        a = x_ref[...]
        c = jnp.float32(1.0000001)
        d = jnp.float32(1e-9)
        # 4 independent chains for ILP; R iterations x 4 chains x 2 flops
        b1 = a
        b2 = a * jnp.float32(1.0001)
        b3 = a * jnp.float32(0.9999)
        b4 = a * jnp.float32(1.0002)
        for _ in range(R):
            b1 = b1 * c + d
            b2 = b2 * c + d
            b3 = b3 * c + d
            b4 = b4 * c + d
        o_ref[...] = (b1 + b2) + (b3 + b4)

    return kernel


def measure_vpu_gflops(NY: int = 256, NZ: int = 512) -> float:
    """Sustained f32 VPU rate from a VMEM-resident multiply-add kernel:
    two R values difference out the fixed overhead (launch, load/store)."""
    x = jnp.ones((NY, NZ), jnp.float32)

    def run(R):
        f = pl.pallas_call(
            _vpu_kernel(R, NY, NZ),
            out_shape=jax.ShapeDtypeStruct((NY, NZ), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )
        g = jax.jit(f)
        g(x).block_until_ready()
        reps = 50
        t0 = time.perf_counter()
        for _ in range(reps):
            y = g(x)
        y.block_until_ready()
        return (time.perf_counter() - t0) / reps

    r_lo, r_hi = 64, 512
    t_lo, t_hi = run(r_lo), run(r_hi)
    flops = (r_hi - r_lo) * 4 * 2 * NY * NZ
    return flops / (t_hi - t_lo) / 1e9


def df_flops_per_dof(P: int) -> int:
    """Analytic VPU flop count per dof of one fused df CG iteration
    (ops.kron_cg_df kernel + the XLA update pass), from the kernel
    structure: per banded term ~28 flops (_eft_term 13 + renorm 6 +
    accumulation 9); z stage 2 contractions, y stage 3, x stage 2, each
    (2P+1) terms; + per-stage splits/renorms, p-update, Dirichlet/dot,
    and the XLA-side x/r update + <r,r> (df axpy ~30 + dot ~35)."""
    nb = 2 * P + 1
    per_term = 28
    contractions = (2 + 3 + 2) * nb * per_term
    stage_overhead = 3 * 10 + 2 * 12  # splits + renorms per stage
    p_update = 40
    emit = 6 + 4 + 30  # renorm + blend + compensated dot
    xla_update = 30 + 30 + 35  # x-axpy, r-axpy, <r,r> df_dot tree
    return contractions + stage_overhead + p_update + emit + xla_update


DF_BYTES_PER_DOF = (
    # kernel: r,p_prev in + p,y out, hi+lo each = 8 streams
    8 * 4
    # XLA update: read x,p,r,y + write x,r (hi+lo) = 12 streams; <r,r>
    # tree re-reads ~2 more effective
    + 14 * 4
)


def main() -> int:
    ndofs = int(sys.argv[1]) if len(sys.argv) > 1 else 12_500_000
    out = {"ndofs": ndofs, "degree": 3}
    out["hbm_gbps"] = round(measure_hbm_gbps(), 1)
    out["vpu_f32_gflops"] = round(measure_vpu_gflops(), 1)
    fpd = df_flops_per_dof(3)
    out["df_flops_per_dof"] = fpd
    out["df_bytes_per_dof"] = DF_BYTES_PER_DOF
    out["ceiling_compute_gdofs"] = round(out["vpu_f32_gflops"] / fpd, 3)
    out["ceiling_bandwidth_gdofs"] = round(
        out["hbm_gbps"] / DF_BYTES_PER_DOF, 3)
    out["ceiling_gdofs"] = min(out["ceiling_compute_gdofs"],
                               out["ceiling_bandwidth_gdofs"])

    from bench_tpu_fem.bench.driver import BenchConfig, run_benchmark

    res = run_benchmark(BenchConfig(
        ndofs_global=ndofs, degree=3, qmode=1, float_bits=64,
        nreps=100, use_cg=True, f64_impl="df32",
    ))
    out["measured_df32_gdofs"] = round(res.gdof_per_second, 3)
    out["engine"] = res.extra.get("cg_engine")
    out["fraction_of_ceiling"] = round(
        res.gdof_per_second / out["ceiling_gdofs"], 3)
    out["vs_f64_baseline_4.02"] = round(res.gdof_per_second / 4.02, 3)
    # f32 engine comparison point (same size) for the balance argument
    res32 = run_benchmark(BenchConfig(
        ndofs_global=ndofs, degree=3, qmode=1, float_bits=32,
        nreps=200, use_cg=True,
    ))
    out["f32_engine_gdofs"] = round(res32.gdof_per_second, 3)

    path = os.path.join(ROOT, "ROOFLINE_DF_r05.json")
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
