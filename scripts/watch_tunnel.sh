#!/bin/bash
# Tunnel watcher — thin shim over the harness watch daemon: probe the TPU
# every 3 minutes; on recovery run the measurement agenda RESUMED from its
# journal; if the agenda aborts on a fresh wedge, re-arm instead of
# exiting. Round-4 lesson: wedges last hours and recovery windows are
# precious — the agenda must fire the moment the tunnel returns, not when
# a human notices. All probes/attempts are journaled in MEASURE_rNN.jsonl.
cd "$(dirname "$0")/.."
exec python -m bench_tpu_fem.harness watch --interval 180 "$@"
