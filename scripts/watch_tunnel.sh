#!/bin/bash
# Tunnel watcher: probe the TPU every 3 minutes; on recovery run the
# measurement agenda (scripts/measure_all.py default stages) once and
# exit. Round-4 lesson: wedges last hours and recovery windows are
# precious — the agenda must fire the moment the tunnel returns, not
# when a human notices.
cd "$(dirname "$0")/.."
while true; do
  if timeout 180 python -c "
import jax, jax.numpy as jnp, sys
x = jax.device_put(jnp.ones((1024, 1024)))
(x @ x).block_until_ready()
sys.exit(0 if jax.default_backend() == 'tpu' else 1)
" 2>/dev/null; then
    echo "[watch_tunnel] tunnel up at $(date -u +%H:%M:%S); running agenda"
    python scripts/measure_all.py "$@"
    exit $?
  fi
  sleep 180
done
