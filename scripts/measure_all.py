"""Run the outstanding TPU measurement agenda (round 6), logging each
step as it lands (a mid-run tunnel wedge preserves completed steps).

Earlier rounds' stages remain callable by name. The round-6 default
agenda adds the perturbed-geometry df32 gate for the folded df pipeline
(ops.folded_df) to the still-uncollected round-5 items:

  health    - tunnel probe (aborts the rest when down)
  dfacc     - df32 engine ACCURACY on hardware (mat_comp oracle): the
              Mosaic compile path may behave differently from the
              CPU-validated interpret path (FP rewrites, op support) —
              this gate must pass before any df perf number is believed
  pertdf    - perturbed df32 ACCURACY + throughput: the folded df
              pipeline's first-ever Mosaic compile (its VMEM plan is a
              design estimate until this runs), mat_comp gate first,
              then the 12.5M perf point vs the 4.02 f64 baseline
  dfeng     - fused df32 engine A/B vs unfused at 12.5M dofs
  dflarge   - df32 engine at 100M (tier-3 scoped limit), plus the
              recorded one-kernel ceiling behaviour toward 300M
  pert100   - perturbed capacity at 100M dofs, corner mode
  deg7probe - degree-7 streamed-corner compile probe at 48 MiB
  bench     - the official bench.py line (now includes the df32
              headline side metric at flagship size)

Usage: python scripts/measure_all.py [stage...]
"""
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "MEASURE_r06.log")
ENV = {**os.environ, "PYTHONPATH": f"{ROOT}:/root/.axon_site"}


def log(msg):
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    with open(LOG, "a") as fh:
        fh.write(line + "\n")


def _run(cmd, timeout, tail=25):
    """Shared runner: same env/cwd/timeout handling for every stage. A
    hang (wedged tunnel) is reported as rc=-9 with a TIMEOUT tail instead
    of propagating — the agenda must keep logging whatever it can. The
    stage runs in its own session and the WHOLE GROUP is killed on
    timeout: bench.py spawns detached single-attempt children, and a
    parent-only kill would orphan one holding the wedged TPU client."""
    import signal

    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            cwd=ROOT, env=ENV, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        out, _ = proc.communicate()
        return -9, f"TIMEOUT after {timeout}s"
    keep = [ln for ln in (out or "").strip().splitlines()
            if not ln.lower().startswith("warning")
            and "Platform 'axon'" not in ln]
    return rc, "\n".join(keep[-tail:])


def run_py(code, timeout=900):
    return _run([sys.executable, "-u", "-c", code], timeout)


def run_script(args, timeout):
    return _run([sys.executable] + args, timeout, tail=15)


PRE = """
import time, numpy as np, jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig, run_benchmark
def timed_res(cfg):
    t0 = time.time(); res = run_benchmark(cfg); w = time.time()-t0
    return res, w
"""


def stage_health():
    rc, out = run_py(
        "import jax, jax.numpy as jnp\n"
        "x = jax.device_put(jnp.ones((1024,1024)))\n"
        "(x@x).block_until_ready(); print('TPU OK', jax.devices())",
        timeout=180,
    )
    log(f"health rc={rc}: {out}")
    return rc == 0


def stage_ab12():
    # engine vs non-engine at the flagship config
    code = PRE + """
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=32, nreps=1000, use_cg=True)
res, w = timed_res(cfg)
print("ENGINE:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""
    rc, out = run_py(code, timeout=1200)
    log(f"ab12 engine rc={rc}: {out}")
    code2 = PRE + """
# force the non-engine path by monkeypatching the support gate
import bench_tpu_fem.ops.kron_cg as KC
KC.supports_kron_cg_engine = lambda *a, **k: False
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=32, nreps=1000, use_cg=True)
res, w = timed_res(cfg)
print("BASELINE3STAGE:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""
    rc, out = run_py(code2, timeout=1200)
    log(f"ab12 baseline rc={rc}: {out}")


def stage_q6():
    _bench_stage("q6", "Q6:", dict(
        ndofs_global=12_500_000, degree=6, qmode=1, float_bits=32,
        nreps=1000, use_cg=True),
        tail_expr=', "vs4.40:", res.gdof_per_second/4.40')


def stage_large():
    for nd, reps in ((100_000_000, 100), (128_000_000, 100),
                     (200_000_000, 50), (300_000_000, 50)):
        code = PRE + f"""
cfg = BenchConfig(ndofs_global={nd}, degree=3, qmode=1,
                  float_bits=32, nreps={reps}, use_cg=True)
res, w = timed_res(cfg)
print("LARGE {nd}:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""
        rc, out = run_py(code, timeout=2400)
        log(f"large {nd} rc={rc}: {out}")


def _bench_stage(name, label, cfg_kwargs, setup="", timeout=1800,
                 tail_expr=""):
    """Shared single-config benchmark stage: one BenchConfig, one
    run_benchmark, one labelled print (the four degree/engine stages
    differ only in these parameters)."""
    kw = ", ".join(f"{k}={v!r}" for k, v in cfg_kwargs.items())
    code = PRE + f"""
{setup}
cfg = BenchConfig({kw})
res, w = timed_res(cfg)
print({label!r}, res.gdof_per_second, res.extra{tail_expr})
"""
    rc, out = run_py(code, timeout=timeout)
    log(f"{name} rc={rc}: {out}")


def stage_deg4():
    _bench_stage("deg4", "DEG4PERT:", dict(
        ndofs_global=12_500_000, degree=4, qmode=1, float_bits=32,
        nreps=500, use_cg=True, geom_perturb_fact=0.2))


def stage_df32():
    code = PRE + """
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=64, nreps=50, use_cg=True, f64_impl="df32")
res, w = timed_res(cfg)
print("DF32:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=64, nreps=50, use_cg=True)
res, w = timed_res(cfg)
print("EMULATED:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""
    rc, out = run_py(code, timeout=1800)
    log(f"df32 rc={rc}: {out}")


def stage_matrix():
    rc, out = run_script(
        ["scripts/baseline_matrix.py", "BASELINE_MATRIX_r05.json"],
        timeout=10800,
    )
    log(f"baseline_matrix rc={rc}: {out}")


def stage_bench():
    # The agenda only reaches this stage when health passed, so bench.py
    # gets a SHORT retry window (its 2h default is for the driver's
    # end-of-round capture against a possibly-wedged tunnel) and the
    # stage timeout comfortably covers window + one attempt overrun.
    ENV["BENCH_WINDOW_S"] = "1800"
    ENV["BENCH_ATTEMPT_TIMEOUT_S"] = "1500"
    try:
        rc, out = run_script(["bench.py"], timeout=2400)
    finally:
        ENV.pop("BENCH_WINDOW_S", None)
        ENV.pop("BENCH_ATTEMPT_TIMEOUT_S", None)
    log(f"bench.py rc={rc}: {out}")


def stage_deg5():
    _bench_stage("deg5", "DEG5PERT:", dict(
        ndofs_global=12_500_000, degree=5, qmode=1, float_bits=32,
        nreps=500, use_cg=True, geom_perturb_fact=0.2))


def stage_dist1():
    code = """
import jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig
from bench_tpu_fem.dist.driver import run_distributed
from bench_tpu_fem.bench.driver import BenchmarkResults
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=32, nreps=100, use_cg=True, ndevices=1)
res = BenchmarkResults()
run_distributed(cfg, res, jnp.float32)
print("DIST1:", res.gdof_per_second, res.extra)
"""
    rc, out = run_py(code, timeout=1200)
    log(f"dist1 rc={rc}: {out}")


def stage_dfdist1():
    # distributed df32 path compile+run on a 1-device mesh (the sharded
    # graph end to end; multi-chip perf needs real hardware). With the
    # fused dist df engine landed, run_distributed_df64 auto-routes
    # through it on TPU — the Mosaic compile check the CPU suite cannot
    # give; extras record cg_engine / any recorded fallback reason.
    code = """
import jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig, BenchmarkResults
from bench_tpu_fem.dist.driver import run_distributed_df64
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=64, nreps=50, use_cg=True,
                  f64_impl="df32", ndevices=1)
res = BenchmarkResults()
run_distributed_df64(cfg, res)
print("DFDIST1:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
"""
    rc, out = run_py(code, timeout=1200)
    log(f"dfdist1 rc={rc}: {out}")


def stage_deg6stream():
    # Degree-6 qmode-1 perturbed on the plane-streamed corner path:
    # the VMEM estimate says ~15 MB vs the 14 MiB corner budget vs the
    # ~16.5 MB hardware limit — genuinely borderline, so probe Mosaic
    # directly (policy override; flip pallas_geom_constraint only with
    # a successful compile + sane perf here).
    code = PRE + """
import bench_tpu_fem.ops.folded as FO
import bench_tpu_fem.ops.pallas_laplacian as PL
orig = FO.pallas_geom_constraint
FO.pallas_geom_constraint = lambda d, nq, itemsize=4: (
    (True, "corner") if d == 6 else orig(d, nq, itemsize))
PL.corner_streamed_lanes_ok = lambda nd, nq, itemsize=4: True
cfg = BenchConfig(ndofs_global=12_500_000, degree=6, qmode=1,
                  float_bits=32, nreps=200, use_cg=True,
                  geom_perturb_fact=0.2, backend="pallas")
res, w = timed_res(cfg)
print("DEG6STREAM:", res.gdof_per_second, res.extra)
"""
    rc, out = run_py(code, timeout=1800)
    log(f"deg6stream rc={rc}: {out}")


def stage_q6one():
    _bench_stage("q6one", "Q6ONEKERNEL:", dict(
        ndofs_global=12_500_000, degree=6, qmode=1, float_bits=32,
        nreps=1000, use_cg=True),
        setup="import bench_tpu_fem.ops.kron_cg as KC\n"
              "KC.VMEM_BUDGET = 14 * 2**20  # probe the one-kernel form")


def _probe_stage(name, timeout):
    # delegate to the per-path-policy probe script so the two agendas
    # cannot diverge (it logs its own result lines to the shared log)
    rc, out = run_script(["scripts/probe_scoped_vmem.py", name], timeout)
    log(f"{name} rc={rc}: {out.splitlines()[-1] if out else ''}")


def stage_p300():
    # tier-3 (96 MiB scoped limit) regression probe
    _probe_stage("q3_300m", 1800)


def stage_pert100():
    # perturbed capacity at 100M (corner mode; matrix covers 12.5M only)
    _probe_stage("pert100", 2100)


def stage_deg7probe():
    # raw deg-7 streamed-corner compile probe at 48 MiB (plan-widening
    # evidence; see probe_scoped_vmem._deg7_probe)
    _probe_stage("deg7probe", 1800)


def stage_dfacc():
    # df32 engine accuracy ON HARDWARE (both forms): the CPU suite
    # validates the interpret path; Mosaic's compiled arithmetic
    # (scheduling, any FP rewrites, scratch semantics) is only provable
    # here. The oracle (assembled CSR, true f64) must agree to ~1e-9
    # like the unfused path; a failure here invalidates every df perf
    # number after it.
    code = PRE + """
cfg = BenchConfig(ndofs_global=50_000, degree=3, qmode=1, float_bits=64,
                  nreps=30, use_cg=True, mat_comp=True, f64_impl="df32")
res, w = timed_res(cfg)
print("DFACC one:", "enorm/znorm", res.enorm / res.znorm, res.extra)
assert res.extra.get("cg_engine") is True, "engine did not engage"
assert res.enorm / res.znorm < 1e-9, "df one-kernel lost f64 accuracy"
import bench_tpu_fem.ops.kron_cg_df as KCD
KCD.engine_plan_df = lambda *a: ("chunked", None)
res, w = timed_res(cfg)
print("DFACC chunked:", "enorm/znorm", res.enorm / res.znorm, res.extra)
assert res.enorm / res.znorm < 1e-9, "df chunked lost f64 accuracy"
print("DFACC OK")
"""
    rc, out = run_py(code, timeout=1800)
    log(f"dfacc rc={rc}: {out}")
    return rc == 0


def stage_pertdf():
    # Perturbed f64-class gate for the folded df pipeline (ops.folded_df):
    # accuracy FIRST (the mat_comp oracle must agree to ~1e-9 like every
    # other df path, and the run must NOT have taken the recorded
    # emulation fallback), then the flagship-size perf point. Both
    # geometry modes: auto (G-pair streaming at this size) and forced
    # corner (the capacity mode whose in-kernel df Jacobian chain is the
    # Mosaic-riskiest new code).
    code = PRE + """
cfg = BenchConfig(ndofs_global=50_000, degree=3, qmode=1, float_bits=64,
                  nreps=30, use_cg=True, mat_comp=True, f64_impl="df32",
                  geom_perturb_fact=0.2)
res, w = timed_res(cfg)
print("PERTDF acc:", "enorm/znorm", res.enorm / res.znorm, res.extra)
assert res.extra.get("f64_impl") == "df32", res.extra
assert res.enorm / res.znorm < 1e-9, "folded-df lost f64 accuracy"
import bench_tpu_fem.ops.folded_df as FD
import bench_tpu_fem.bench.driver as BD
orig = FD.build_folded_laplacian_df
FD.build_folded_laplacian_df = lambda *a, **k: orig(
    *a, **{**k, "geom": "corner"})
res, w = timed_res(cfg)
print("PERTDF acc corner:", "enorm/znorm", res.enorm / res.znorm,
      res.extra)
assert res.extra.get("f64_impl") == "df32", res.extra
assert res.extra.get("geom") == "corner", res.extra
assert res.enorm / res.znorm < 1e-9, "folded-df corner lost f64 accuracy"
FD.build_folded_laplacian_df = orig
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=64, nreps=100, use_cg=True, f64_impl="df32",
                  geom_perturb_fact=0.2)
res, w = timed_res(cfg)
print("PERTDF12.5M:", res.gdof_per_second, res.extra,
      "vs4.02:", res.gdof_per_second / 4.02)
"""
    rc, out = run_py(code, timeout=2400)
    log(f"pertdf rc={rc}: {out}")


def stage_dfeng():
    # fused engine vs unfused df at flagship size
    _bench_stage("dfeng", "DFENG12.5M:", dict(
        ndofs_global=12_500_000, degree=3, qmode=1, float_bits=64,
        nreps=200, use_cg=True, f64_impl="df32"),
        tail_expr=', "vs4.02:", res.gdof_per_second/4.02')
    _bench_stage("dfunf", "DFUNFUSED12.5M:", dict(
        ndofs_global=12_500_000, degree=3, qmode=1, float_bits=64,
        nreps=50, use_cg=True, f64_impl="df32"),
        setup="import bench_tpu_fem.ops.kron_cg_df as KCD\n"
              "KCD.engine_plan_df = lambda *a: ('unfused', None)")


def stage_dflarge():
    for nd, reps in ((100_000_000, 50), (150_000_000, 30)):
        _bench_stage(f"dflarge{nd}", f"DFLARGE {nd}:", dict(
            ndofs_global=nd, degree=3, qmode=1, float_bits=64,
            nreps=reps, use_cg=True, f64_impl="df32"), timeout=2400)


def stage_foldeng():
    # Dist folded fused engine vs unfused A/B at the flagship perturbed
    # config (the sharded graph end to end on a 1-device mesh: halo
    # refresh, halo-form delay-ring Mosaic compile, reverse-scatter dot
    # tail — the collectives degenerate to identity there; multi-chip
    # scaling needs real multi-chip hardware). Engine routing and any
    # recorded fallback ride res.extra (cg_engine_form: halo/unfused).
    code = """
import jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig, BenchmarkResults
from bench_tpu_fem.dist.driver import run_distributed
cfg = BenchConfig(ndofs_global=12_500_000, degree=3, qmode=1,
                  float_bits=32, nreps=500, use_cg=True, ndevices=1,
                  backend="pallas", geom_perturb_fact=0.2)
res = BenchmarkResults(nreps=cfg.nreps)
run_distributed(cfg, res, jnp.float32)
print("FOLDENG:", res.gdof_per_second, res.extra, "ynorm", res.ynorm)
# loud on routing drift: an unfused fallback here would otherwise make
# the A/B below compare unfused vs unfused (the reason is in the extras)
assert res.extra.get("cg_engine_form") == "halo", res.extra
import bench_tpu_fem.dist.folded_cg as DFC
DFC.dist_folded_engine_plan = lambda op: (False, None)
res2 = BenchmarkResults(nreps=cfg.nreps)
run_distributed(cfg, res2, jnp.float32)
print("FOLDENG-UNFUSED:", res2.gdof_per_second, res2.extra,
      "ynorm", res2.ynorm, "speedup:",
      res.gdof_per_second / max(res2.gdof_per_second, 1e-12))
"""
    rc, out = run_py(code, timeout=2400)
    log(f"foldeng rc={rc}: {out}")


def stage_dfext2d():
    # ext2d df engine form ((2,2,2)-dshape coverage). On an 8-device rig
    # this is the real (2,2,2) run; on the 1-chip rig the ext2d branch
    # is forced onto the 1-device mesh — the kernel form's FIRST Mosaic
    # compile is the gate that matters (round-4 lesson: interpret mode
    # accepts kernels Mosaic rejects), and with degenerate collectives
    # the halo fringes are zero so the numbers stay exact. Gated behind
    # dfacc in the default agenda like every df number. (The force
    # patches the private _is_x_only predicate, which the solve path
    # reads at call time — the cg_engine_form assert below turns any
    # routing drift into a loud rc!=0, never a silent wrong-form
    # measurement.)
    code = """
import jax, jax.numpy as jnp
from bench_tpu_fem.bench.driver import BenchConfig, BenchmarkResults
from bench_tpu_fem.dist.driver import run_distributed_df64
nd = len(jax.devices())
if nd >= 8:
    ndev, tag = 8, "(2,2,2)"
else:
    import bench_tpu_fem.dist.kron_cg_df as KCD
    KCD._is_x_only = lambda op: False
    ndev, tag = 1, "forced-ext2d-1dev"
cfg = BenchConfig(ndofs_global=2_000_000, degree=3, qmode=1,
                  float_bits=64, nreps=50, use_cg=True,
                  f64_impl="df32", ndevices=ndev)
res = BenchmarkResults(nreps=cfg.nreps)
run_distributed_df64(cfg, res)
print("DFEXT2D", tag, ":", res.gdof_per_second, res.extra,
      "ynorm", res.ynorm)
assert res.extra.get("cg_engine_form") == "ext2d", res.extra
"""
    rc, out = run_py(code, timeout=2400)
    log(f"dfext2d rc={rc}: {out}")


STAGES = {
    "health": stage_health, "ab12": stage_ab12, "q6": stage_q6,
    "large": stage_large, "deg4": stage_deg4, "df32": stage_df32,
    "matrix": stage_matrix, "bench": stage_bench,
    "deg5": stage_deg5, "dist1": stage_dist1, "q6one": stage_q6one,
    "dfdist1": stage_dfdist1, "deg6stream": stage_deg6stream,
    "p300": stage_p300, "pert100": stage_pert100,
    "deg7probe": stage_deg7probe, "dfacc": stage_dfacc,
    "dfeng": stage_dfeng, "dflarge": stage_dflarge,
    "pertdf": stage_pertdf, "foldeng": stage_foldeng,
    "dfext2d": stage_dfext2d,
}

# df stages whose numbers only count after the on-hardware df accuracy
# gate (dfacc) passes — when dfacc runs in the same agenda and FAILS,
# these are skipped with a log line instead of producing numbers that
# round-5's evidence-hygiene rule would have to discard.
DF_GATED = {"pertdf", "dfeng", "dflarge", "dfext2d"}

if __name__ == "__main__":
    # Round-6 default agenda, ordered by value-per-minute under wedge
    # risk: the df accuracy gates first (nothing df counts without
    # them — pertdf is the folded df pipeline's first Mosaic compile),
    # then the new fused-coverage forms (foldeng is f32 — ungated;
    # dfext2d is df — gated), the official bench line, df perf, the
    # leftovers, and the full matrix (longest) last.
    wanted = sys.argv[1:] or ["health", "dfacc", "pertdf", "foldeng",
                              "dfext2d", "dfeng", "bench", "dflarge",
                              "pert100", "deg7probe", "matrix"]
    unknown = [s for s in wanted if s not in STAGES]
    if unknown:
        print(f"unknown stage(s) {unknown}; valid: {list(STAGES)}",
              file=sys.stderr)
        sys.exit(2)
    if "health" in wanted and not stage_health():
        log("tunnel down; aborting")
        sys.exit(1)
    dfacc_ok = None  # unknown until (and unless) the gate stage runs
    for s in wanted:
        if s == "health":
            continue
        if s in DF_GATED and dfacc_ok is False:
            log(f"=== stage {s} SKIPPED: dfacc gate failed — df numbers "
                "don't count without the on-hardware accuracy check")
            continue
        log(f"=== stage {s}")
        try:
            result = STAGES[s]()
        except Exception as e:
            log(f"stage {s} EXC: {e}")
            result = None
        if s == "dfacc":
            dfacc_ok = bool(result)
