"""Back-compat shim over the resilient measurement harness.

The TPU measurement agenda now lives in ``bench_tpu_fem.harness`` —
journaled (MEASURE_rNN.jsonl), resumable, fault-classified. This script
keeps the historical entry point working:

    python scripts/measure_all.py [stage...]

is exactly

    python -m bench_tpu_fem.harness run --resume [stage...]

Stage names are unchanged (health, dfacc, pertdf, foldeng, dfext2d,
dfeng, bench, dflarge, pert100, deg7probe, matrix, and the earlier
rounds' ab12/q6/large/...); composite names expand to their granular
harness stages (see harness.agenda.ALIASES). The legacy contract is kept
exactly: explicitly NAMED stages always run (no --resume — re-collecting
a number by name must measure, not replay the journal), while the
no-argument default agenda runs ``--resume`` because that is strictly
better under wedge risk: completed stages are skipped via the journal,
failed ones re-run per policy, and a previously-FAILED dfacc gate keeps
gating df stages instead of resetting to unknown (the old in-process
``dfacc_ok`` flag died with the process).

The failure taxonomy, retry/backoff policy, OOM degradation ladder and
journal format are documented in README "Measurement harness".
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_tpu_fem.harness.agenda import main  # noqa: E402

if __name__ == "__main__":
    args = sys.argv[1:]
    sys.exit(main(["run", *([] if args else ["--resume"]), *args]))
