#!/usr/bin/env python
"""f32-vs-f64 CG accuracy evidence at benchmark scale (SURVEY §7 hard part
1): the reference's headline configs are f64; TPUs only emulate f64, so the
flagship benchmark numbers here are f32. This artifact quantifies what that
costs in solution quality: run the SAME fixed-iteration CG (rtol = 0,
cg.hpp:88-91 semantics) in f32 and in emulated f64 on the same problem and
report final residual and solution-norm deltas.

The problem size is chosen so the f64 run is tractable (~80x slower than
f32); the iteration count matches the benchmark's 1000. Writes JSON:

    python scripts/f32_accuracy.py [out.json] [ndofs] [nreps]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run(float_bits: int, ndofs: int, nreps: int):
    # Hermetic CPU runs must undo the axon tunnel hook (see utils.hermetic)
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from bench_tpu_fem.utils.hermetic import force_host_cpu_devices

        force_host_cpu_devices(1)
    import jax

    if float_bits == 64:
        jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    from bench_tpu_fem.elements import build_operator_tables
    from bench_tpu_fem.la.cg import cg_solve
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.kron import build_kron_laplacian, device_rhs_uniform

    dtype = jnp.float64 if float_bits == 64 else jnp.float32
    degree, qmode = 3, 1
    n = compute_mesh_size(ndofs, degree)
    mesh = create_box_mesh(n)
    t = build_operator_tables(degree, qmode)
    op = build_kron_laplacian(mesh, degree, qmode, dtype=dtype, tables=t)
    b = jax.jit(lambda: device_rhs_uniform(t, mesh.n, dtype))()

    x = jax.jit(
        lambda A, b: cg_solve(A.apply, b, jnp.zeros_like(b), nreps)
    )(op, b)
    x.block_until_ready()
    r = b - jax.jit(op.apply)(x)
    return {
        "x": np.asarray(x, np.float64),
        "xnorm": float(jnp.linalg.norm(x)),
        "rnorm": float(jnp.linalg.norm(r)),
        "bnorm": float(jnp.linalg.norm(b)),
    }


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "F32_ACCURACY.json"
    ndofs = int(sys.argv[2]) if len(sys.argv) > 2 else 2_000_000
    nreps = int(sys.argv[3]) if len(sys.argv) > 3 else 1000

    import numpy as np

    r32 = run(32, ndofs, nreps)
    r64 = run(64, ndofs, nreps)
    dx = np.linalg.norm(r32["x"] - r64["x"]) / np.linalg.norm(r64["x"])
    doc = {
        "config": {"degree": 3, "qmode": 1, "cg_nreps": nreps,
                   "ndofs": ndofs, "backend": "kron (uniform flagship)"},
        "f32": {k: v for k, v in r32.items() if k != "x"},
        "f64": {k: v for k, v in r64.items() if k != "x"},
        "solution_rel_l2_diff_f32_vs_f64": float(dx),
        "solution_norm_rel_diff": float(
            abs(r32["xnorm"] - r64["xnorm"]) / r64["xnorm"]
        ),
        "final_rel_residual_f32": r32["rnorm"] / r32["bnorm"],
        "final_rel_residual_f64": r64["rnorm"] / r64["bnorm"],
    }
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=1)
    print(json.dumps(doc, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
