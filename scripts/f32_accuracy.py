#!/usr/bin/env python
"""Precision accuracy evidence at benchmark scale (SURVEY §7 hard part 1):
the reference's headline configs are f64; TPUs only emulate f64, so the
flagship benchmark numbers here are f32 (with --f64_impl df32 as the
double-float middle ground). This artifact runs the SAME fixed-iteration
CG (rtol = 0, cg.hpp:88-91 semantics) in f32, emulated f64 and df32 on the
same problem and reports, for each, the residual evaluated through the
TRUE f64 operator plus solution deltas vs the f64 run.

The problem size is chosen so the f64 run is tractable (~80x slower than
f32); the iteration count matches the benchmark's 1000. Writes JSON:

    python scripts/f32_accuracy.py [out.json] [ndofs] [nreps]
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEGREE, QMODE = 3, 1


def _hermetic():
    # Hermetic CPU runs must undo the axon tunnel hook (see utils.hermetic)
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from bench_tpu_fem.utils.hermetic import force_host_cpu_devices

        force_host_cpu_devices(1)


def _setup(ndofs: int):
    from bench_tpu_fem.elements import build_operator_tables
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size

    n = compute_mesh_size(ndofs, DEGREE)
    return create_box_mesh(n), build_operator_tables(DEGREE, QMODE)


def _with_x64(value: bool):
    """Set jax_enable_x64, returning the previous value (callers restore:
    leaking the flag between runs is exactly the bug class the driver's
    save/restore fixed — each precision must trace in its own regime)."""
    import jax

    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", value)
    return prev


def run(float_bits: int, ndofs: int, nreps: int):
    _hermetic()
    import jax

    prev = _with_x64(float_bits == 64)
    try:
        import jax.numpy as jnp
        import numpy as np

        from bench_tpu_fem.la.cg import cg_solve
        from bench_tpu_fem.ops.kron import (
            build_kron_laplacian,
            device_rhs_uniform,
        )

        dtype = jnp.float64 if float_bits == 64 else jnp.float32
        mesh, t = _setup(ndofs)
        op = build_kron_laplacian(mesh, DEGREE, QMODE, dtype=dtype, tables=t)
        b = jax.jit(lambda: device_rhs_uniform(t, mesh.n, dtype))()
        x = jax.jit(
            lambda A, bb: cg_solve(A.apply, bb, jnp.zeros_like(bb), nreps)
        )(op, b)
        x.block_until_ready()
        # recursion self-residual: through this run's own operator in its
        # own precision — the metric the precision policy cites (an f32
        # run's visible stagnation floor)
        r = b - jax.jit(op.apply)(x)
        self_res = float(jnp.linalg.norm(r) / jnp.linalg.norm(b))
        return np.asarray(x, np.float64), self_res
    finally:
        _with_x64(prev)


def run_df32(ndofs: int, nreps: int):
    """--f64_impl df32 on the same problem, traced with x64 OFF exactly as
    the shipped configuration runs (driver forces it off for df32)."""
    _hermetic()
    import jax

    prev = _with_x64(False)
    try:
        import numpy as np

        from bench_tpu_fem.la.df64 import df_to_f64
        from bench_tpu_fem.ops.kron_df import (
            build_kron_laplacian_df,
            cg_solve_df,
            device_rhs_uniform_df,
        )

        mesh, t = _setup(ndofs)
        op = build_kron_laplacian_df(mesh, DEGREE, QMODE, tables=t)
        b = device_rhs_uniform_df(t, mesh.n)
        x = jax.jit(lambda A, bb: cg_solve_df(A, bb, nreps))(op, b)
        jax.block_until_ready(x)
        y = jax.jit(op.apply)(x)
        b64 = df_to_f64(b)
        r = b64 - df_to_f64(y)
        self_res = float(np.linalg.norm(r) / np.linalg.norm(b64))
        return np.asarray(df_to_f64(x), np.float64), self_res
    finally:
        _with_x64(prev)


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "F32_ACCURACY.json"
    ndofs = int(sys.argv[2]) if len(sys.argv) > 2 else 2_000_000
    nreps = int(sys.argv[3]) if len(sys.argv) > 3 else 1000

    import numpy as np

    x32, self32 = run(32, ndofs, nreps)
    x64, self64 = run(64, ndofs, nreps)
    xdf, selfdf = run_df32(ndofs, nreps)

    # Evaluate every solution's residual through the TRUE f64 operator —
    # a self-residual through each run's own operator could not expose
    # operator-level precision error.
    _hermetic()
    prev = _with_x64(True)
    try:
        import jax
        import jax.numpy as jnp

        from bench_tpu_fem.ops.kron import (
            build_kron_laplacian,
            device_rhs_uniform,
        )

        mesh, t = _setup(ndofs)
        op64 = build_kron_laplacian(mesh, DEGREE, QMODE, dtype=jnp.float64,
                                    tables=t)
        b64 = jax.jit(
            lambda: device_rhs_uniform(t, mesh.n, jnp.float64))()
        bnorm = float(jnp.linalg.norm(b64))
        apply64 = jax.jit(op64.apply)

        def true_rel_res(x):
            return float(jnp.linalg.norm(b64 - apply64(jnp.asarray(x)))
                         ) / bnorm

        res = {k: true_rel_res(v) for k, v in
               (("f32", x32), ("f64", x64), ("df32", xdf))}
    finally:
        _with_x64(prev)

    x64n = np.linalg.norm(x64)
    doc = {
        "config": {"degree": DEGREE, "qmode": QMODE, "cg_nreps": nreps,
                   "ndofs": ndofs, "backend": "kron (uniform flagship)"},
        "xnorm": {"f32": float(np.linalg.norm(x32)), "f64": float(x64n),
                  "df32": float(np.linalg.norm(xdf))},
        "solution_rel_l2_diff_f32_vs_f64": float(
            np.linalg.norm(x32 - x64) / x64n),
        "solution_rel_l2_diff_df32_vs_f64": float(
            np.linalg.norm(xdf - x64) / x64n),
        "true_rel_residual_f32": res["f32"],
        "true_rel_residual_f64": res["f64"],
        "true_rel_residual_df32": res["df32"],
        # self-residuals (each run's own operator/precision): the f32
        # value is the visible ~1e-3 stagnation floor the README cites
        "final_rel_residual_f32": self32,
        "final_rel_residual_f64": self64,
        "final_rel_residual_df32": selfdf,
    }
    with open(out_path, "w") as fh:
        json.dump(doc, fh, indent=1)
    print(json.dumps(doc, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
