#!/usr/bin/env python
"""Golden-JSON assertions for the end-to-end CLI runs (CI).

Behavioural analogue of the reference's output check (src/test_output.py).

One file — serial golden config
    --ndofs_global 1000 --degree 3 --qmode 0 --nreps 1 --mat_comp --float 64
asserts the echoed size, matrix-free vs assembled-CSR agreement, and the
golden norm  y_norm = 9.912865833415553  (reference test_output.py:19 —
the same operator on the same mesh must reproduce it to f64 tolerance).

Two files — serial vs sharded equality (the `mpirun -n 2` analogue of the
reference CI): both runs must use a config where the serial and sharded
mesh sizings provably coincide (2197 dofs -> a 4x4x4-cell box, 2 cells per
shard along x); asserts each run's matfree-vs-CSR agreement and that the
two y_norms match to f64 reduction tolerance.

Usage: python scripts/check_output.py out.json
       python scripts/check_output.py out-serial.json out-n2.json
"""

import json
import sys

GOLDEN_Y_NORM = 9.912865833415553


def _load(path: str) -> dict:
    with open(path) as fh:
        out = json.load(fh)["output"]
    # matfree vs assembled-CSR oracle (requires --mat_comp)
    assert abs(out["y_norm"] - out["z_norm"]) < 1e-9 * abs(out["z_norm"]), (
        out["y_norm"], out["z_norm"],
    )
    return out


def main(argv: list[str]) -> int:
    if len(argv) not in (1, 2):
        print("usage: check_output.py OUT.json [SHARDED_OUT.json]",
              file=sys.stderr)
        return 2
    if len(argv) == 1:
        out = _load(argv[0])
        assert out["ndofs_global"] == 1000, out["ndofs_global"]
        assert abs(out["y_norm"] - GOLDEN_Y_NORM) < 1e-9, out["y_norm"]
        print(f"OK: y_norm={out['y_norm']} matches golden {GOLDEN_Y_NORM}")
        return 0
    a, b = (_load(p) for p in argv)
    assert a["ndofs_global"] == b["ndofs_global"], (
        a["ndofs_global"], b["ndofs_global"],
    )
    assert a["ncells_global"] == b["ncells_global"], (
        "serial and sharded sizings disagree — pick a config where they "
        "coincide (e.g. 2197 dofs at degree 3)",
        a["ncells_global"], b["ncells_global"],
    )
    rel = abs(a["y_norm"] - b["y_norm"]) / abs(a["y_norm"])
    assert rel < 1e-12, (a["y_norm"], b["y_norm"], rel)
    print(f"OK: serial and sharded y_norm agree: {a['y_norm']} "
          f"(rel diff {rel:.2e})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
