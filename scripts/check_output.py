#!/usr/bin/env python
"""Golden-JSON assertions for the end-to-end CLI run (CI).

Behavioural analogue of the reference's output check (src/test_output.py):
for the canonical config

    --ndofs_global 1000 --degree 3 --qmode 0 --nreps 1 --mat_comp --float 64

assert the echoed size, matrix-free vs assembled-CSR agreement, and the
golden norm  y_norm = 9.912865833415553  (reference test_output.py:19 —
the same operator on the same mesh must reproduce it to f64 tolerance).

Usage: python scripts/check_output.py out.json
"""

import json
import sys

GOLDEN_Y_NORM = 9.912865833415553


def main(path: str) -> int:
    with open(path) as fh:
        doc = json.load(fh)
    out = doc["output"]
    assert out["ndofs_global"] == 1000, out["ndofs_global"]
    assert abs(out["y_norm"] - out["z_norm"]) < 1e-9, (
        out["y_norm"], out["z_norm"],
    )
    assert abs(out["y_norm"] - GOLDEN_Y_NORM) < 1e-9, out["y_norm"]
    print(f"OK: y_norm={out['y_norm']} matches golden {GOLDEN_Y_NORM}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
